"""Engine state: N virtual membership endpoints as struct-of-arrays.

This is the TPU-native replacement for the reference's object-per-node
architecture: one ``EngineState`` pytree holds every virtual node's protocol
state in padded device arrays (static shapes; membership changes flip bits in
``alive``), so a whole cluster's protocol round is a single fused XLA program.

Cohorts: receivers with identical delivery experience share cut-detector
state. In a reliably-delivered co-located deployment all healthy nodes see
the same alert stream, so their detectors are bit-identical — cohort 0.
Divergence comes from two injectable sources: per-cohort rx-block masks
(asymmetric/one-way links) and per-(cohort, edge) delivery delay jitter
(``EngineConfig.delivery_spread`` — broadcast arrival skew, the paper's
Fig. 11 divergence regime). Delivery masks pack bitwise over cohorts
(uint32 words), so C scales to hundreds of independently-diverging receiver
states at N=100K+ (the reference's N independent ``MultiNodeCutDetector``
instances, ``MultiNodeCutDetector.java:31-37``, sampled at C of them).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

from rapid_tpu.ops.hashing import masked_set_hash
from rapid_tpu.ops.rings import ring_perms, ring_topology_from_perm

# Sentinel for "this edge's alert has not fired": far enough in the future
# that (round_idx - FIRE_NEVER) stays hugely negative in int32. The compact
# int16 storage uses FIRE_NEVER_NARROW instead; the invariant (an unfired
# edge's age stays negative for every in-envelope round index, under the
# NARROWEST round dtype the policy can pick) is pinned by
# tests/test_state_compaction.py::test_fire_never_sentinel_invariant —
# a test, not just this comment.
FIRE_NEVER = 1 << 30
#: The int16-storage sentinel: fire rounds are real (< ROUND_ENVELOPE)
#: or this. Kept a power of two with headroom so (round_idx - sentinel)
#: is not merely negative but ~-2^14 at the envelope edge.
FIRE_NEVER_NARROW = 1 << 14
#: Operating envelope of the compact round counter: a single configuration
#: may run at most this many rounds before fire_round narrowing (int16,
#: FIRE_NEVER_NARROW sentinel) loses the unfired/fired distinction. Every
#: view change resets round_idx to 0; tier-1 dispatch budgets are <= 255
#: rounds, so the envelope holds ~64 maximal dispatches per configuration.
ROUND_ENVELOPE = FIRE_NEVER_NARROW - 1


class EngineConfig(NamedTuple):
    """Static (compile-time) engine parameters."""

    n: int  # padded virtual-node slots
    k: int  # rings
    h: int  # high watermark
    l: int  # low watermark
    c: int = 2  # receiver cohorts
    fd_threshold: int = 3  # consecutive failed probe windows before alerting
    # Run the engine's Pallas TPU kernel (rapid_tpu.ops.pallas_kernels) —
    # the fused alert-delivery kernel, measured 2.25x over XLA's fusion. Off
    # for sharded/CPU runs.
    use_pallas: bool = False
    # Rounds an announced proposal may sit undecided before the classic-Paxos
    # fallback fires (models FastPaxos.java:106-107's jittered recovery; the
    # coordinator rule then forces the plurality value, Paxos.java:271-328).
    fallback_rounds: int = 8
    # Max extra rounds of per-(cohort, edge) alert delivery delay, drawn
    # deterministically from a hash of (cohort, edge, configuration). 0 =
    # same-round delivery for every cohort (no timing divergence). This is
    # the engine's model of broadcast arrival skew — the reason real
    # receivers' cut detectors diverge (paper Fig. 11).
    delivery_spread: int = 0
    # Coordinators racing per classic-fallback attempt. The reference lets
    # any number of nodes start recovery concurrently, ordered by rank
    # (Paxos.java:93-97, 333-339); modeling R > 1 exercises that contention:
    # acceptors promise to every heard rank in order, so a lower-ranked
    # coordinator can win phase 1 yet have its phase 2a rejected wherever a
    # higher rank's phase 1a also arrived.
    concurrent_coordinators: int = 1
    # Failure-detection policy (NEW FIELDS APPEND HERE: EngineConfig loads
    # positionally from checkpoints). 0 = the reference code's
    # cumulative-failure counter (fd_count >= fd_threshold). W in [1, 32] =
    # the PAPER's windowed policy: an edge fires when >= fd_threshold of its
    # last W probe windows failed — kept per edge as a uint32 bit-history
    # (shift + popcount per round; rapid_tpu/monitoring/windowed.py is the
    # host twin). Intermittent blips age out instead of accumulating forever.
    fd_window: int = 0
    # Sub-round delivery-skew granularity. Values 0..999: probability (in
    # permille, per (cohort, edge)) that a delivery draws a NONZERO delay,
    # uniform in [1, delivery_spread] — P(delayed) is exactly permille/1000,
    # interpolating between "no timing divergence" (0) and "every delivery
    # skewed" (→1000). The default 1000 is a distinct LEGACY mode, not the
    # continuum endpoint: the original uniform draw over [0,
    # delivery_spread], whose delayed fraction is spread/(spread+1) (e.g.
    # 0.5 at spread=1 ≙ permille 500 on the dial). The paper's
    # continuous-latency simulation (Fig. 11) sits below one full round of
    # skew; see EVALUATION.md §2 for the calibration.
    delivery_prob_permille: int = 1000
    # (A pallas_watermark field once sat here: a Mosaic watermark kernel
    # measured SLOWER than XLA's own fusion — 2.52 ms vs 3.67 ms at [8, 1M],
    # evidence/round2/microbench_slope.json — and was deleted. Checkpoint
    # loads drop the stale value; see utils/checkpoint.py.)
    # Lane-tile width for the Pallas delivery kernel (multiple of 128).
    # Wider tiles amortize per-grid-step overhead at large N; outputs are
    # bit-identical across widths. Tune per shape with
    # examples/delivery_autotune.py on hardware.
    pallas_lanes: int = 128
    # State-compaction level (an int, not a string: EngineConfig persists
    # as an int64 vector in checkpoints). 0 = the historical wide
    # int32/uint32 layout (the differential oracle); 1 = config-derived
    # dtype narrowing per :func:`compaction_policy` — every lane stored at
    # the minimal legal dtype for this config's K/C/N/fd_window, arithmetic
    # accumulated at >= int32 and bit-identical to wide within the
    # documented envelopes (ROUND_ENVELOPE rounds and < 2^15 - 1 classic
    # attempts / fd events per configuration).
    compact: int = 0
    # Device-resident telemetry plane (an int knob, like ``compact``): 0 =
    # off — the round bodies trace NO telemetry code and compile
    # byte-identical programs (the hlo.lock.json gate freezes that); 1 = a
    # :class:`TelemetryLanes` pytree rides beside the state through the
    # jitted round bodies, accumulating per-round activity/tally/conflict
    # counters on-device. Telemetry never changes engine results: the lanes
    # are write-only inside a round (nothing reads them back into protocol
    # state), pinned bit-identical on-vs-off by tests/test_telemetry_plane.py.
    telemetry: int = 0
    # Device round-trace ring capacity R (an int knob holding the SIZE, not
    # a boolean): 0 = off — the round bodies trace NO ring code and compile
    # byte-identical programs (frozen by the hlo.lock.json gate, like
    # ``telemetry``); R > 0 = a :class:`TraceRing` of the last R per-round
    # records rides beside the state through the jitted round bodies. The
    # ring is a REFINEMENT of the telemetry plane (its active-subject count
    # reuses the telemetry block's cut-mask reduction), so trace > 0
    # requires telemetry == 1 — drivers enforce this at construction. Like
    # every EngineConfig field this appends at the END: checkpoints persist
    # the config positionally as an int64 vector.
    trace: int = 0


class CompactionPolicy(NamedTuple):
    """Per-lane storage dtypes, a pure function of :class:`EngineConfig`
    (:func:`compaction_policy`). Dtype fields are numpy dtype NAMES (strings
    keep the policy hashable and trivially serializable); ``fire_never`` is
    the "edge never fired" sentinel legal at the ``round`` dtype.

    Lane kinds:

    - ``idx``     — ring/topology index tables and cp rank indices, values
                    in [-1, n-1] plus the count n itself (jax index
                    normalization): int8 below 128 slots, int16 below
                    32768.
    - ``cohort``  — receiver-cohort indices, values in [-1, c-1] plus c:
                    int8 below 128 cohorts (c is capped at 1024 -> never
                    wider than int16).
    - ``counter`` — fd_count / classic-Paxos rank rounds / classic_epoch /
                    rounds_undecided: int16 (envelope: < 2^15 - 1 events
                    per configuration; every view change resets them).
    - ``hist``    — fd_hist bit-history: the minimal unsigned dtype holding
                    ``fd_window`` bits (uint8 for the counter mode's unused
                    lane and windows <= 8).
    - ``report``  — report_bits ring bitmasks: the minimal unsigned dtype
                    holding K bits. Held at uint32 under ``use_pallas``
                    (the Mosaic delivery kernel emits uint32 words).
    - ``round``   — fire_round: int16 with the FIRE_NEVER_NARROW sentinel
                    (envelope: ROUND_ENVELOPE rounds per configuration).
    """

    idx: str
    cohort: str
    counter: str
    hist: str
    report: str
    round: str
    fire_never: int


#: The historical layout — and the differential oracle the compact path is
#: pinned bit-identical against.
WIDE_POLICY = CompactionPolicy(
    idx="int32", cohort="int32", counter="int32", hist="uint32",
    report="uint32", round="int32", fire_never=FIRE_NEVER,
)

#: EngineState/FaultInputs lanes the derived policy may store below 32 bits
#: — the ``dtype-widening`` lint (tools/analysis/sharding.py) watches
#: arithmetic on exactly these names; the two sets are pinned equal by
#: tests/test_state_compaction.py.
NARROWABLE_LANES = frozenset({
    "ring_perm", "obs_idx", "subj_idx", "inval_obs", "cohort_of",
    "fd_count", "fd_hist", "fire_round", "report_bits",
    "cp_rnd_r", "cp_rnd_i", "cp_vrnd_r", "cp_vrnd_i", "cp_vval_src",
    "classic_epoch", "rounds_undecided",
})


def min_index_dtype(n: int) -> str:
    """Smallest signed dtype holding indices in [-1, n-1] AND the count
    ``n`` itself: jax's advanced indexing materializes the axis size in
    the index dtype when normalizing negative indices, so a dtype whose
    max is exactly ``n - 1`` overflows at trace time (n=128 under int8
    was the scaling-ladder-found boundary bug)."""
    if n < 1 << 7:
        return "int8"
    if n < 1 << 15:
        return "int16"
    return "int32"


def _min_bits_dtype(bits: int) -> str:
    """Smallest unsigned dtype holding a ``bits``-wide bitmask."""
    if bits <= 8:
        return "uint8"
    if bits <= 16:
        return "uint16"
    return "uint32"


def compaction_policy(cfg: "EngineConfig") -> CompactionPolicy:
    """THE config->dtype derivation (pure; the compiled program's layout is
    a function of the static config, so a policy change is a recompile,
    never a silent reinterpretation). ``cfg.compact == 0`` returns the wide
    oracle layout unchanged."""
    if not cfg.compact:
        return WIDE_POLICY
    return CompactionPolicy(
        idx=min_index_dtype(cfg.n),
        cohort=min_index_dtype(cfg.c),
        counter="int16",
        # fd_window == 0 (counter mode) leaves fd_hist unused — store the
        # all-zeros lane at the minimal width rather than special-casing.
        hist=_min_bits_dtype(max(cfg.fd_window, 1)),
        report="uint32" if cfg.use_pallas else _min_bits_dtype(cfg.k),
        round="int16",
        fire_never=FIRE_NEVER_NARROW,
    )


#: field -> (shape symbols over (n, k, c), policy-kind). One table for BOTH
#: pytrees (the namespaces share no field name); the policy kinds "uint32"
#: / "int32" / "bool" are fixed-width (hash lanes, scalars the drivers
#: fetch, membership masks).
LANE_SPECS: Dict[str, Tuple[Tuple[str, ...], str]] = {
    # EngineState
    "key_hi": (("k", "n"), "uint32"),
    "key_lo": (("k", "n"), "uint32"),
    "ring_perm": (("k", "n"), "idx"),
    "id_hi": (("n",), "uint32"),
    "id_lo": (("n",), "uint32"),
    "alive": (("n",), "bool"),
    "obs_idx": (("k", "n"), "idx"),
    "subj_idx": (("k", "n"), "idx"),
    "inval_obs": (("k", "n"), "idx"),
    "config_epoch": ((), "int32"),
    "config_hi": ((), "uint32"),
    "config_lo": ((), "uint32"),
    "n_members": ((), "int32"),
    "fd_count": (("n", "k"), "counter"),
    "fd_hist": (("n", "k"), "hist"),
    "fd_fired": (("n", "k"), "bool"),
    "fire_round": (("n", "k"), "round"),
    "join_pending": (("n",), "bool"),
    "cohort_of": (("n",), "cohort"),
    "report_bits": (("c", "n"), "report"),
    "seen_down": (("c",), "bool"),
    "released": (("c", "n"), "bool"),
    "announced": (("c",), "bool"),
    "prop_mask": (("c", "n"), "bool"),
    "prop_hi": (("c",), "uint32"),
    "prop_lo": (("c",), "uint32"),
    "vote_hi": (("n",), "uint32"),
    "vote_lo": (("n",), "uint32"),
    "vote_valid": (("n",), "bool"),
    "rounds_undecided": ((), "counter"),
    "cp_rnd_r": (("n",), "counter"),
    "cp_rnd_i": (("n",), "idx"),
    "cp_vrnd_r": (("n",), "counter"),
    "cp_vrnd_i": (("n",), "idx"),
    "cp_vval_src": (("n",), "cohort"),
    "classic_epoch": ((), "counter"),
    "round_idx": ((), "int32"),
    "retired": (("n",), "bool"),
    # FaultInputs
    "crashed": (("n",), "bool"),
    "probe_fail": (("n", "k"), "bool"),
    "rx_block": (("c", "n"), "bool"),
}


def lane_dtypes(cfg: "EngineConfig") -> Dict[str, str]:
    """field -> numpy dtype name under this config's policy, for every
    EngineState/FaultInputs lane."""
    pol = compaction_policy(cfg)
    kinds = {
        "idx": pol.idx, "cohort": pol.cohort, "counter": pol.counter,
        "hist": pol.hist, "report": pol.report, "round": pol.round,
        "uint32": "uint32", "int32": "int32", "bool": "bool",
    }
    return {field: kinds[kind] for field, (_shape, kind) in LANE_SPECS.items()}


class EngineState(NamedTuple):
    """Device state for one virtual cluster (all arrays padded to n slots).

    Dtype comments below are the WIDE (``compact=0``) layout; under
    ``compact=1`` every lane named in :data:`NARROWABLE_LANES` is stored at
    :func:`compaction_policy`'s minimal dtype instead (same shapes, same
    values, bit-identical protocol behavior within the documented
    envelopes)."""

    # Identity & topology (key lanes static per slot; topology re-derived on
    # view change).
    key_hi: jnp.ndarray  # [k, n] uint32
    key_lo: jnp.ndarray  # [k, n] uint32
    ring_perm: jnp.ndarray  # [k, n] int32 — static key-order permutation per ring
    id_hi: jnp.ndarray  # [n] uint32 — node-identity lanes for set hashes
    id_lo: jnp.ndarray  # [n] uint32
    alive: jnp.ndarray  # [n] bool — current membership
    obs_idx: jnp.ndarray  # [k, n] int32 — ring successor (observer) per slot
    subj_idx: jnp.ndarray  # [k, n] int32 — ring predecessor (subject) per slot
    inval_obs: jnp.ndarray  # [k, n] int32 — invalidation-observer table
    config_epoch: jnp.ndarray  # int32 — counts view changes
    config_hi: jnp.ndarray  # uint32 — commutative config-id lanes
    config_lo: jnp.ndarray  # uint32
    n_members: jnp.ndarray  # int32 — membership size of this configuration

    # Failure-detector state per monitoring edge (subject, ring).
    fd_count: jnp.ndarray  # [n, k] int32 cumulative failed windows
    fd_hist: jnp.ndarray  # [n, k] uint32 bit-history of outcomes (windowed mode)
    fd_fired: jnp.ndarray  # [n, k] bool alert already emitted
    fire_round: jnp.ndarray  # [n, k] int32 round the alert fired (FIRE_NEVER if not)

    # Joiner bookkeeping.
    join_pending: jnp.ndarray  # [n] bool — slots waiting to be admitted

    # Cut-detector state per cohort: reports are uint32 ring bitmasks per
    # subject (bit k = ring k reported; OR is the dedup).
    cohort_of: jnp.ndarray  # [n] int32 — receiver cohort of each node
    report_bits: jnp.ndarray  # [c, n] uint32
    seen_down: jnp.ndarray  # [c] bool
    released: jnp.ndarray  # [c, n] bool
    announced: jnp.ndarray  # [c] bool — cohort already proposed this config
    prop_mask: jnp.ndarray  # [c, n] bool — cohort's announced proposal
    prop_hi: jnp.ndarray  # [c] uint32
    prop_lo: jnp.ndarray  # [c] uint32

    # Fast-round votes.
    vote_hi: jnp.ndarray  # [n] uint32
    vote_lo: jnp.ndarray  # [n] uint32
    vote_valid: jnp.ndarray  # [n] bool

    # Rounds spent with an announced-but-undecided proposal (fallback timer).
    rounds_undecided: jnp.ndarray  # int32

    # Classic-Paxos acceptor state, message-level (Paxos.java:64-74): the
    # promised rank rnd and accepted (vrnd, vval) per node. Ranks are
    # (round, node-index) pairs; values are cohort indices into prop_mask
    # (every value in play is some cohort's announced cut); -1 = none.
    cp_rnd_r: jnp.ndarray  # [n] int32
    cp_rnd_i: jnp.ndarray  # [n] int32
    cp_vrnd_r: jnp.ndarray  # [n] int32
    cp_vrnd_i: jnp.ndarray  # [n] int32
    cp_vval_src: jnp.ndarray  # [n] int32 — cohort index of accepted value
    classic_epoch: jnp.ndarray  # int32 — classic attempts this configuration

    # Rounds elapsed in this configuration (drives delivery-delay maturity).
    round_idx: jnp.ndarray  # int32

    # Slots removed by some past view change: their identity lanes are spent
    # (the engine's UUIDAlreadySeenError — re-admitting one would replay an
    # old configuration id). Rejoiners must use fresh slots.
    retired: jnp.ndarray  # [n] bool


def initial_state(cfg: EngineConfig, key_hi, key_lo, id_hi, id_lo, alive) -> EngineState:
    """Build a configuration-consistent state from identity arrays."""
    if not 1 <= cfg.k <= 32:
        raise ValueError(
            f"K must be in [1, 32]: ring reports are uint32 bitmasks (got K={cfg.k})"
        )
    if cfg.c > 1024:
        raise ValueError(
            f"at most 1024 receiver cohorts (per-cohort state is [c, n]; "
            f"sample divergence, don't materialize every receiver), got {cfg.c}"
        )
    if cfg.delivery_spread < 0:
        raise ValueError(f"delivery_spread must be >= 0, got {cfg.delivery_spread}")
    if not 0 <= cfg.fd_window <= 32:
        raise ValueError(
            f"fd_window must be 0 (counter mode) or 1..32 (uint32 bit-history), "
            f"got {cfg.fd_window}"
        )
    if cfg.fd_window and cfg.fd_threshold > cfg.fd_window:
        raise ValueError(
            f"fd_threshold ({cfg.fd_threshold}) cannot exceed fd_window "
            f"({cfg.fd_window}): the edge could never fire"
        )
    alive = jnp.asarray(alive, dtype=bool)
    pol = compaction_policy(cfg)
    idt, cdt = jnp.dtype(pol.idx), jnp.dtype(pol.cohort)
    ndt, rdt = jnp.dtype(pol.counter), jnp.dtype(pol.round)
    # The one sort: ring keys are static per slot, so every topology after
    # this (including every view change) is O(N) scans over these perms.
    perm = ring_perms(jnp.asarray(key_hi), jnp.asarray(key_lo)).astype(idt)
    topo = ring_topology_from_perm(perm, alive)
    config_hi, config_lo = masked_set_hash(jnp.asarray(id_hi), jnp.asarray(id_lo), alive)
    n, k, c = cfg.n, cfg.k, cfg.c
    return EngineState(
        key_hi=jnp.asarray(key_hi, dtype=jnp.uint32),
        key_lo=jnp.asarray(key_lo, dtype=jnp.uint32),
        ring_perm=perm,
        id_hi=jnp.asarray(id_hi, dtype=jnp.uint32),
        id_lo=jnp.asarray(id_lo, dtype=jnp.uint32),
        alive=alive,
        obs_idx=topo.obs_idx.astype(idt),
        subj_idx=topo.subj_idx.astype(idt),
        # A copy, not an alias: engine_step donates its input state, and the
        # runtime rejects the same buffer donated twice.
        inval_obs=jnp.copy(topo.obs_idx.astype(idt)),
        config_epoch=jnp.int32(0),
        config_hi=config_hi,
        config_lo=config_lo,
        n_members=jnp.sum(alive, dtype=jnp.int32),
        fd_count=jnp.zeros((n, k), dtype=ndt),
        fd_hist=jnp.zeros((n, k), dtype=jnp.dtype(pol.hist)),
        fd_fired=jnp.zeros((n, k), dtype=bool),
        fire_round=jnp.full((n, k), pol.fire_never, dtype=rdt),
        join_pending=jnp.zeros((n,), dtype=bool),
        cohort_of=jnp.zeros((n,), dtype=cdt),
        report_bits=jnp.zeros((c, n), dtype=jnp.dtype(pol.report)),
        seen_down=jnp.zeros((c,), dtype=bool),
        released=jnp.zeros((c, n), dtype=bool),
        announced=jnp.zeros((c,), dtype=bool),
        prop_mask=jnp.zeros((c, n), dtype=bool),
        prop_hi=jnp.zeros((c,), dtype=jnp.uint32),
        prop_lo=jnp.zeros((c,), dtype=jnp.uint32),
        vote_hi=jnp.zeros((n,), dtype=jnp.uint32),
        vote_lo=jnp.zeros((n,), dtype=jnp.uint32),
        vote_valid=jnp.zeros((n,), dtype=bool),
        rounds_undecided=jnp.zeros((), dtype=ndt),
        cp_rnd_r=jnp.zeros((n,), dtype=ndt),
        cp_rnd_i=jnp.zeros((n,), dtype=idt),
        cp_vrnd_r=jnp.zeros((n,), dtype=ndt),
        cp_vrnd_i=jnp.zeros((n,), dtype=idt),
        cp_vval_src=jnp.full((n,), -1, dtype=cdt),
        classic_epoch=jnp.zeros((), dtype=ndt),
        round_idx=jnp.int32(0),
        retired=jnp.zeros((n,), dtype=bool),
    )


class FaultInputs(NamedTuple):
    """Per-step fault-injection masks (the device analog of the reference's
    StaticFailureDetector blacklist + MessageDropInterceptor fixtures)."""

    crashed: jnp.ndarray  # [n] bool — unresponsive; never votes or alerts
    probe_fail: jnp.ndarray  # [n, k] bool — extra per-edge probe failures
    rx_block: jnp.ndarray  # [c, n] bool — cohort c cannot hear from slot i

    @staticmethod
    def none(cfg: EngineConfig) -> "FaultInputs":
        return FaultInputs(
            crashed=jnp.zeros((cfg.n,), dtype=bool),
            probe_fail=jnp.zeros((cfg.n, cfg.k), dtype=bool),
            rx_block=jnp.zeros((cfg.c, cfg.n), dtype=bool),
        )


class StepEvents(NamedTuple):
    """Observable outcomes of one engine step (host-side driver reads these)."""

    decided: jnp.ndarray  # scalar bool — consensus reached this step
    # Which path decided: True = one-step fast round; False = the classic
    # fallback's coordinator rule (only meaningful when decided). The engine
    # twin of the host event VIEW_CHANGE_ONE_STEP_FAILED.
    fast_decided: jnp.ndarray  # scalar bool
    winner_mask: jnp.ndarray  # [n] bool — the decided cut (flip set)
    proposals_announced: jnp.ndarray  # [c] bool — cohorts that proposed this step
    alerts_emitted: jnp.ndarray  # int32 — new edge alerts this step
    total_votes: jnp.ndarray  # int32
    max_votes: jnp.ndarray  # int32
    # Per-cohort announced-proposal hash lanes as of THIS round, captured
    # before any view-change reset (reading state.prop_* after a deciding
    # step sees post-reset zeros — observers must use these instead).
    prop_hi: jnp.ndarray  # [c] uint32
    prop_lo: jnp.ndarray  # [c] uint32


# ---------------------------------------------------------------------------
# Device-resident telemetry plane (EngineConfig.telemetry == 1)
# ---------------------------------------------------------------------------

#: Log2 bucket count of the rounds-undecided histogram: bucket b counts
#: decisions that sat undecided for r rounds with floor(log2(max(r, 1)))
#: == b (clamped into the last bucket), so bucket 0 is the one-round fast
#: path and bucket 7 holds every >= 128-round stall.
TELEMETRY_BUCKETS = 8

#: field -> shape symbols over (n, k, c, b) — the LANE_SPECS convention with
#: ``b`` = :data:`TELEMETRY_BUCKETS`. Every telemetry lane is int32: these
#: are accumulators, not protocol state, and the compaction policy never
#: narrows them (a saturating counter would silently lie). The ``telemetry``
#: analyzer family mirrors this exact field set (tools/analysis/telemetry.py)
#: so a new lane cannot skip the partition rules or the exposition surface.
TELEMETRY_LANE_SPECS: Dict[str, Tuple[str, ...]] = {
    "tl_rounds": (),
    "tl_alerts": (),
    "tl_active": ("c", "n"),
    "tl_invalidated": ("c", "n"),
    "tl_proposals": ("c",),
    "tl_tally_sum": (),
    "tl_fast_decisions": (),
    "tl_classic_decisions": (),
    "tl_conflict_rounds": (),
    "tl_undecided_hist": ("b",),
}


class TelemetryLanes(NamedTuple):
    """On-device activity/tally/conflict accumulators, carried alongside
    :class:`EngineState` through the jitted round bodies when
    ``EngineConfig.telemetry == 1`` and fetched ONLY at the existing
    host-sync boundaries (``sync`` / ``stream_fetch`` / ``health_scan``).

    Two grains, one discipline — zero new hot-loop collectives:

    - Scalar counters reuse reductions the round body already computes
      (``alerts_emitted``, the tally scalars, the decision flags), so
      accumulating them adds elementwise int adds only.
    - Per-slot lanes stay at their native [c, n] / [c] grain (sharded by
      the same :data:`rapid_tpu.parallel.mesh.PARTITION_RULES` table);
      cross-shard reductions over them happen in the separate
      ``telemetry_digest`` jit dispatched at fetch boundaries, never
      inside the convergence loop.

    Under the tenancy vmap every lane grows a leading ``[t]`` axis, so
    every metric is per-tenant for free."""

    tl_rounds: jnp.ndarray  # [] int32 — rounds stepped
    tl_alerts: jnp.ndarray  # [] int32 — edge alerts applied (sum of alerts_emitted)
    # Rounds each (cohort, subject) slot was ACTIVE: nonzero report bits or
    # a watermark tally in the [L, H) flux band. The quantity ROADMAP item
    # 3's sparse O(activity) rounds will skip work by.
    tl_active: jnp.ndarray  # [c, n] int32
    tl_invalidated: jnp.ndarray  # [c, n] int32 — implicit-invalidation events
    tl_proposals: jnp.ndarray  # [c] int32 — proposals released per cohort
    tl_tally_sum: jnp.ndarray  # [] int32 — winning-tally sizes, summed at decisions
    tl_fast_decisions: jnp.ndarray  # [] int32 — one-step fast-path decisions
    tl_classic_decisions: jnp.ndarray  # [] int32 — classic-fallback decisions
    # Rounds where some cohort had announced but the fast path did NOT
    # decide — the per-tenant conflict-rate numerator ("The Performance of
    # Paxos and Fast Paxos": the fast path's win hinges on collision rate).
    tl_conflict_rounds: jnp.ndarray  # [] int32
    tl_undecided_hist: jnp.ndarray  # [TELEMETRY_BUCKETS] int32 — log2(rounds-undecided) at decision


def initial_telemetry(cfg: EngineConfig) -> TelemetryLanes:
    """All-zero telemetry lanes for this config's geometry."""
    dims = {"n": cfg.n, "k": cfg.k, "c": cfg.c, "b": TELEMETRY_BUCKETS}
    return TelemetryLanes(**{
        field: jnp.zeros(tuple(dims[s] for s in shape), dtype=jnp.int32)
        for field, shape in TELEMETRY_LANE_SPECS.items()
    })


def telemetry_bytes_total(cfg: EngineConfig) -> int:
    """At-rest bytes of one cluster's telemetry lanes (all int32) — the
    figure the hlo.lock.json ``telemetry`` block freezes per device."""
    dims = {"n": cfg.n, "k": cfg.k, "c": cfg.c, "b": TELEMETRY_BUCKETS}
    total = 0
    for shape in TELEMETRY_LANE_SPECS.values():
        elems = 1
        for sym in shape:
            elems *= dims[sym]
        total += elems * 4
    return total


# ---------------------------------------------------------------------------
# Device round-trace ring (EngineConfig.trace == R > 0)
# ---------------------------------------------------------------------------

#: field -> shape symbols over (r,) with ``r`` = ``EngineConfig.trace`` (the
#: ring capacity R) — the LANE_SPECS convention, mirrored by the ``telemetry``
#: analyzer family (tools/analysis/telemetry.py) exactly like
#: :data:`TELEMETRY_LANE_SPECS`, so a new ring lane cannot skip the partition
#: rules, the decode vocabulary, or the exposition surface. Every lane is
#: int32 (records, not protocol state; compaction never narrows them).
TRACE_LANE_SPECS: Dict[str, Tuple[str, ...]] = {
    "tr_round": ("r",),
    "tr_epoch": ("r",),
    "tr_active": ("r",),
    "tr_alerts": ("r",),
    "tr_proposals": ("r",),
    "tr_tally": ("r",),
    "tr_path": ("r",),
    "tr_conflict": ("r",),
    "tr_undecided": ("r",),
    "tr_cursor": (),
    "tr_wraps": (),
}


class TraceRing(NamedTuple):
    """A bounded device-resident flight recorder of per-round records: the
    last ``EngineConfig.trace`` rounds, one slot per round, written inside
    the jitted round body and fetched ONLY at the existing host-sync
    boundaries (the telemetry plane's discipline — the ring is its
    round-resolution refinement, so ``trace > 0`` requires ``telemetry``).

    Cursor semantics (the wraparound contract the property tests pin):

    - ``tr_cursor`` counts records EVER written (monotone); the slot a
      round lands in is ``tr_cursor % R``, so the ring always holds the
      last ``min(R, tr_cursor)`` rounds.
    - ``tr_wraps`` increments each time the write fills slot ``R - 1`` —
      it reconciles with the cursor as ``tr_wraps == tr_cursor // R``, and
      with the telemetry plane as ``tr_cursor == tl_rounds``.
    - Decode order: rotate from ``tr_cursor % R`` when wrapped; the
      ``(tr_epoch, tr_round)`` pairs of the decoded records are strictly
      lexicographically increasing (``round_idx`` resets at each view
      change, ``config_epoch`` only grows) — monotone across a wrap.

    Under the tenancy vmap every lane grows a leading ``[t]`` axis; frozen
    or quarantined tenants coast with a GATED cursor (the wave's tree-level
    ``where`` holds cursor and slots alike), so a coasting tenant's ring
    never records phantom rounds."""

    tr_round: jnp.ndarray  # [R] int32 — round stamp (round_idx within the epoch)
    tr_epoch: jnp.ndarray  # [R] int32 — config_epoch the round executed in
    tr_active: jnp.ndarray  # [R] int32 — active (cohort, subject) slots this round
    tr_alerts: jnp.ndarray  # [R] int32 — edge alerts applied this round
    tr_proposals: jnp.ndarray  # [R] int32 — proposals released this round
    tr_tally: jnp.ndarray  # [R] int32 — winning-tally size (0 unless decided)
    tr_path: jnp.ndarray  # [R] int32 — decision path: 0 none, 1 fast, 2 classic
    tr_conflict: jnp.ndarray  # [R] int32 — announced-but-no-fast-decision flag
    tr_undecided: jnp.ndarray  # [R] int32 — rounds_undecided entering the round
    tr_cursor: jnp.ndarray  # [] int32 — records ever written (slot = cursor % R)
    tr_wraps: jnp.ndarray  # [] int32 — times the write filled slot R - 1


def initial_trace(cfg: EngineConfig) -> TraceRing:
    """All-zero trace ring for this config's capacity."""
    dims = {"r": cfg.trace}
    return TraceRing(**{
        field: jnp.zeros(tuple(dims[s] for s in shape), dtype=jnp.int32)
        for field, shape in TRACE_LANE_SPECS.items()
    })


def trace_bytes_total(cfg: EngineConfig) -> int:
    """At-rest bytes of one cluster's trace ring (all int32) — the frozen
    per-device figure the hlo.lock.json ``trace`` block carries: R rounds of
    history at a byte cost fixed by config, not by event rate."""
    dims = {"r": cfg.trace}
    total = 0
    for shape in TRACE_LANE_SPECS.values():
        elems = 1
        for sym in shape:
            elems *= dims[sym]
        total += elems * 4
    return total


# ---------------------------------------------------------------------------
# Wide <-> compact converters (the differential seam)
# ---------------------------------------------------------------------------


def _cast_lanes(tree, dtypes: Dict[str, str], fire_never_src: int, fire_never_out: int):
    """Cast every lane of an EngineState/FaultInputs pytree to ``dtypes``,
    remapping the source layout's fire_round sentinel to
    ``fire_never_out``. Elementwise converts only — jit-safe."""
    out = {}
    for field, value in tree._asdict().items():
        dt = jnp.dtype(dtypes[field])
        if field == "fire_round":
            value = jnp.where(
                value == jnp.asarray(fire_never_src, value.dtype),
                jnp.asarray(fire_never_out, dt),
                value.astype(dt),
            )
        out[field] = value.astype(dt)
    return type(tree)(**out)


def widen_state(cfg: EngineConfig, state: EngineState) -> EngineState:
    """A compact state as the wide int32/uint32 layout (sentinel remapped to
    :data:`FIRE_NEVER`). The identity on an already-wide state — which is
    what lets every wide-vs-compact differential compare
    ``widen_state(compact_cfg, compact_state)`` against the oracle's state
    leaf-for-leaf, bit-for-bit."""
    return _cast_lanes(
        state, lane_dtypes(cfg._replace(compact=0)),
        compaction_policy(cfg).fire_never, FIRE_NEVER,
    )


def narrow_state(cfg: EngineConfig, state: EngineState) -> EngineState:
    """A WIDE state at ``cfg``'s compact policy dtypes (inverse of
    :func:`widen_state` within the envelopes). Host callers migrating
    checkpoints should validate ranges first (:func:`validate_envelope`) —
    the cast itself wraps silently, as device casts do."""
    return _cast_lanes(
        state, lane_dtypes(cfg), FIRE_NEVER, compaction_policy(cfg).fire_never
    )


def validate_envelope(cfg: EngineConfig, state: EngineState) -> None:
    """Host-side (fetching) range check that a WIDE state fits ``cfg``'s
    compact policy: counters within int16, round_idx within
    ROUND_ENVELOPE, fire rounds real-or-sentinel. Raises ValueError naming
    the first offending lane — the loud alternative to a wrapping cast."""
    pol = compaction_policy(cfg)
    if pol == WIDE_POLICY:
        return
    limits = {
        "fd_count": (-(1 << 15), (1 << 15) - 1),
        "cp_rnd_r": (0, (1 << 15) - 1),
        "cp_vrnd_r": (0, (1 << 15) - 1),
        "classic_epoch": (0, (1 << 15) - 1),
        "rounds_undecided": (0, (1 << 15) - 1),
        "round_idx": (0, ROUND_ENVELOPE),
    }
    for field, (lo, hi) in limits.items():
        arr = np.asarray(getattr(state, field))
        if arr.size and (int(arr.min()) < lo or int(arr.max()) > hi):
            raise ValueError(
                f"state lane {field!r} range [{arr.min()}, {arr.max()}] "
                f"exceeds the compact envelope [{lo}, {hi}]"
            )
    fire = np.asarray(state.fire_round)
    real = fire[fire != FIRE_NEVER]
    if real.size and (int(real.min()) < 0 or int(real.max()) > ROUND_ENVELOPE):
        raise ValueError(
            f"fire_round carries a non-sentinel value outside "
            f"[0, {ROUND_ENVELOPE}]: [{real.min()}, {real.max()}]"
        )


# ---------------------------------------------------------------------------
# Opt-in bit-packed bool masks (pack/unpack ops + whole-pytree converters)
# ---------------------------------------------------------------------------

#: bool lane -> the SLOT axis it packs 8-to-a-byte along (the n dimension:
#: the only axis guaranteed large; [c]-only lanes stay bool — a cohort
#: count need not divide 8 and saves c/8 bytes total).
PACKED_MASK_AXES: Dict[str, int] = {
    "alive": 0, "join_pending": 0, "vote_valid": 0, "retired": 0,
    "fd_fired": 0, "released": 1, "prop_mask": 1,
    "crashed": 0, "probe_fail": 0, "rx_block": 1,
}


def pack_bool(mask: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Pack a bool array 8-to-a-byte along ``axis`` (little-endian within
    the byte: element i rides bit i%8 of word i//8). The axis length must
    divide 8 — pad the mask (``parallel.mesh.pad_to_multiple``) first."""
    mask = jnp.asarray(mask, dtype=bool)
    size = mask.shape[axis]
    if size % 8:
        raise ValueError(
            f"pack_bool axis {axis} has length {size}, not a multiple of 8"
        )
    moved = jnp.moveaxis(mask, axis, -1)
    grouped = moved.reshape(*moved.shape[:-1], size // 8, 8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    words = jnp.sum(grouped.astype(jnp.uint8) * weights, axis=-1, dtype=jnp.uint8)
    return jnp.moveaxis(words, -1, axis)


def unpack_bool(words: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Inverse of :func:`pack_bool`: uint8 words -> the bool mask (length
    8x along ``axis``)."""
    moved = jnp.moveaxis(jnp.asarray(words, dtype=jnp.uint8), axis, -1)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (moved[..., None] >> shifts) & jnp.uint8(1)
    flat = bits.reshape(*moved.shape[:-1], moved.shape[-1] * 8)
    return jnp.moveaxis(flat, -1, axis).astype(bool)


def pack_masks(tree):
    """The opt-in bit-packed representation of an EngineState/FaultInputs
    pytree: every bool lane in :data:`PACKED_MASK_AXES` packed along its
    slot axis (shape [n] -> [n/8], [c, n] -> [c, n/8], [n, k] -> [n/8, k]).
    Same field names — the :data:`parallel.mesh.PARTITION_RULES` table and
    :func:`parallel.mesh.shard_pytree`'s divisibility validation cover the
    packed shapes unchanged. Requires n % 8 == 0."""
    return type(tree)(**{
        field: (
            pack_bool(value, axis=PACKED_MASK_AXES[field])
            if field in PACKED_MASK_AXES
            else value
        )
        for field, value in tree._asdict().items()
    })


def unpack_masks(tree):
    """Inverse of :func:`pack_masks` (exact: pack/unpack is a bijection on
    whole bytes)."""
    return type(tree)(**{
        field: (
            unpack_bool(value, axis=PACKED_MASK_AXES[field])
            if field in PACKED_MASK_AXES
            else value
        )
        for field, value in tree._asdict().items()
    })


# ---------------------------------------------------------------------------
# Sizing: bytes/member as a pure function of the config (the bench's
# 10M/100M deployment-sizing table reads exactly this)
# ---------------------------------------------------------------------------


def _lane_elems(shape_syms: Tuple[str, ...], n: int, k: int, c: int) -> int:
    dims = {"n": n, "k": k, "c": c}
    total = 1
    for sym in shape_syms:
        total *= dims[sym]
    return total


def state_bytes_total(cfg: EngineConfig, packed: bool = False) -> int:
    """Total at-rest bytes of one cluster's EngineState + FaultInputs under
    ``cfg``'s policy (``packed=True`` additionally prices the opt-in
    bit-packed bool masks). Exact: LANE_SPECS mirrors the constructors
    field-for-field (pinned by tests/test_state_compaction.py against a
    real state pytree's leaf nbytes)."""
    dtypes = lane_dtypes(cfg)
    total = 0
    for field, (shape_syms, _kind) in LANE_SPECS.items():
        elems = _lane_elems(shape_syms, cfg.n, cfg.k, cfg.c)
        if packed and field in PACKED_MASK_AXES:
            # Packs along an n-sized axis: 1 bit per element.
            total += (elems + 7) // 8
        else:
            total += elems * np.dtype(dtypes[field]).itemsize
    return total


def state_bytes_per_member(cfg: EngineConfig, packed: bool = False) -> float:
    """Per-slot state footprint — the scale metric ROADMAP item 5's 100M
    sizing is computed from."""
    return state_bytes_total(cfg, packed=packed) / cfg.n


def pytree_nbytes(tree) -> int:
    """Logical bytes of a pytree's array leaves (works on ShapeDtypeStructs
    and concrete arrays alike — no fetch)."""
    import jax

    return sum(
        int(np.prod(leaf.shape, dtype=np.int64)) * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
    )
