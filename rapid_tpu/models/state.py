"""Engine state: N virtual membership endpoints as struct-of-arrays.

This is the TPU-native replacement for the reference's object-per-node
architecture: one ``EngineState`` pytree holds every virtual node's protocol
state in padded device arrays (static shapes; membership changes flip bits in
``alive``), so a whole cluster's protocol round is a single fused XLA program.

Cohorts: receivers with identical delivery experience share cut-detector
state. In a reliably-delivered co-located deployment all healthy nodes see
the same alert stream, so their detectors are bit-identical — cohort 0.
Divergence comes from two injectable sources: per-cohort rx-block masks
(asymmetric/one-way links) and per-(cohort, edge) delivery delay jitter
(``EngineConfig.delivery_spread`` — broadcast arrival skew, the paper's
Fig. 11 divergence regime). Delivery masks pack bitwise over cohorts
(uint32 words), so C scales to hundreds of independently-diverging receiver
states at N=100K+ (the reference's N independent ``MultiNodeCutDetector``
instances, ``MultiNodeCutDetector.java:31-37``, sampled at C of them).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from rapid_tpu.ops.hashing import masked_set_hash
from rapid_tpu.ops.rings import ring_perms, ring_topology_from_perm

# Sentinel for "this edge's alert has not fired": far enough in the future
# that (round_idx - FIRE_NEVER) stays hugely negative in int32.
FIRE_NEVER = 1 << 30


class EngineConfig(NamedTuple):
    """Static (compile-time) engine parameters."""

    n: int  # padded virtual-node slots
    k: int  # rings
    h: int  # high watermark
    l: int  # low watermark
    c: int = 2  # receiver cohorts
    fd_threshold: int = 3  # consecutive failed probe windows before alerting
    # Run the engine's Pallas TPU kernel (rapid_tpu.ops.pallas_kernels) —
    # the fused alert-delivery kernel, measured 2.25x over XLA's fusion. Off
    # for sharded/CPU runs.
    use_pallas: bool = False
    # Rounds an announced proposal may sit undecided before the classic-Paxos
    # fallback fires (models FastPaxos.java:106-107's jittered recovery; the
    # coordinator rule then forces the plurality value, Paxos.java:271-328).
    fallback_rounds: int = 8
    # Max extra rounds of per-(cohort, edge) alert delivery delay, drawn
    # deterministically from a hash of (cohort, edge, configuration). 0 =
    # same-round delivery for every cohort (no timing divergence). This is
    # the engine's model of broadcast arrival skew — the reason real
    # receivers' cut detectors diverge (paper Fig. 11).
    delivery_spread: int = 0
    # Coordinators racing per classic-fallback attempt. The reference lets
    # any number of nodes start recovery concurrently, ordered by rank
    # (Paxos.java:93-97, 333-339); modeling R > 1 exercises that contention:
    # acceptors promise to every heard rank in order, so a lower-ranked
    # coordinator can win phase 1 yet have its phase 2a rejected wherever a
    # higher rank's phase 1a also arrived.
    concurrent_coordinators: int = 1
    # Failure-detection policy (NEW FIELDS APPEND HERE: EngineConfig loads
    # positionally from checkpoints). 0 = the reference code's
    # cumulative-failure counter (fd_count >= fd_threshold). W in [1, 32] =
    # the PAPER's windowed policy: an edge fires when >= fd_threshold of its
    # last W probe windows failed — kept per edge as a uint32 bit-history
    # (shift + popcount per round; rapid_tpu/monitoring/windowed.py is the
    # host twin). Intermittent blips age out instead of accumulating forever.
    fd_window: int = 0
    # Sub-round delivery-skew granularity. Values 0..999: probability (in
    # permille, per (cohort, edge)) that a delivery draws a NONZERO delay,
    # uniform in [1, delivery_spread] — P(delayed) is exactly permille/1000,
    # interpolating between "no timing divergence" (0) and "every delivery
    # skewed" (→1000). The default 1000 is a distinct LEGACY mode, not the
    # continuum endpoint: the original uniform draw over [0,
    # delivery_spread], whose delayed fraction is spread/(spread+1) (e.g.
    # 0.5 at spread=1 ≙ permille 500 on the dial). The paper's
    # continuous-latency simulation (Fig. 11) sits below one full round of
    # skew; see EVALUATION.md §2 for the calibration.
    delivery_prob_permille: int = 1000
    # (A pallas_watermark field once sat here: a Mosaic watermark kernel
    # measured SLOWER than XLA's own fusion — 2.52 ms vs 3.67 ms at [8, 1M],
    # evidence/round2/microbench_slope.json — and was deleted. Checkpoint
    # loads drop the stale value; see utils/checkpoint.py.)
    # Lane-tile width for the Pallas delivery kernel (multiple of 128).
    # Wider tiles amortize per-grid-step overhead at large N; outputs are
    # bit-identical across widths. Tune per shape with
    # examples/delivery_autotune.py on hardware.
    pallas_lanes: int = 128


class EngineState(NamedTuple):
    """Device state for one virtual cluster (all arrays padded to n slots)."""

    # Identity & topology (key lanes static per slot; topology re-derived on
    # view change).
    key_hi: jnp.ndarray  # [k, n] uint32
    key_lo: jnp.ndarray  # [k, n] uint32
    ring_perm: jnp.ndarray  # [k, n] int32 — static key-order permutation per ring
    id_hi: jnp.ndarray  # [n] uint32 — node-identity lanes for set hashes
    id_lo: jnp.ndarray  # [n] uint32
    alive: jnp.ndarray  # [n] bool — current membership
    obs_idx: jnp.ndarray  # [k, n] int32 — ring successor (observer) per slot
    subj_idx: jnp.ndarray  # [k, n] int32 — ring predecessor (subject) per slot
    inval_obs: jnp.ndarray  # [k, n] int32 — invalidation-observer table
    config_epoch: jnp.ndarray  # int32 — counts view changes
    config_hi: jnp.ndarray  # uint32 — commutative config-id lanes
    config_lo: jnp.ndarray  # uint32
    n_members: jnp.ndarray  # int32 — membership size of this configuration

    # Failure-detector state per monitoring edge (subject, ring).
    fd_count: jnp.ndarray  # [n, k] int32 cumulative failed windows
    fd_hist: jnp.ndarray  # [n, k] uint32 bit-history of outcomes (windowed mode)
    fd_fired: jnp.ndarray  # [n, k] bool alert already emitted
    fire_round: jnp.ndarray  # [n, k] int32 round the alert fired (FIRE_NEVER if not)

    # Joiner bookkeeping.
    join_pending: jnp.ndarray  # [n] bool — slots waiting to be admitted

    # Cut-detector state per cohort: reports are uint32 ring bitmasks per
    # subject (bit k = ring k reported; OR is the dedup).
    cohort_of: jnp.ndarray  # [n] int32 — receiver cohort of each node
    report_bits: jnp.ndarray  # [c, n] uint32
    seen_down: jnp.ndarray  # [c] bool
    released: jnp.ndarray  # [c, n] bool
    announced: jnp.ndarray  # [c] bool — cohort already proposed this config
    prop_mask: jnp.ndarray  # [c, n] bool — cohort's announced proposal
    prop_hi: jnp.ndarray  # [c] uint32
    prop_lo: jnp.ndarray  # [c] uint32

    # Fast-round votes.
    vote_hi: jnp.ndarray  # [n] uint32
    vote_lo: jnp.ndarray  # [n] uint32
    vote_valid: jnp.ndarray  # [n] bool

    # Rounds spent with an announced-but-undecided proposal (fallback timer).
    rounds_undecided: jnp.ndarray  # int32

    # Classic-Paxos acceptor state, message-level (Paxos.java:64-74): the
    # promised rank rnd and accepted (vrnd, vval) per node. Ranks are
    # (round, node-index) pairs; values are cohort indices into prop_mask
    # (every value in play is some cohort's announced cut); -1 = none.
    cp_rnd_r: jnp.ndarray  # [n] int32
    cp_rnd_i: jnp.ndarray  # [n] int32
    cp_vrnd_r: jnp.ndarray  # [n] int32
    cp_vrnd_i: jnp.ndarray  # [n] int32
    cp_vval_src: jnp.ndarray  # [n] int32 — cohort index of accepted value
    classic_epoch: jnp.ndarray  # int32 — classic attempts this configuration

    # Rounds elapsed in this configuration (drives delivery-delay maturity).
    round_idx: jnp.ndarray  # int32

    # Slots removed by some past view change: their identity lanes are spent
    # (the engine's UUIDAlreadySeenError — re-admitting one would replay an
    # old configuration id). Rejoiners must use fresh slots.
    retired: jnp.ndarray  # [n] bool


def initial_state(cfg: EngineConfig, key_hi, key_lo, id_hi, id_lo, alive) -> EngineState:
    """Build a configuration-consistent state from identity arrays."""
    if not 1 <= cfg.k <= 32:
        raise ValueError(
            f"K must be in [1, 32]: ring reports are uint32 bitmasks (got K={cfg.k})"
        )
    if cfg.c > 1024:
        raise ValueError(
            f"at most 1024 receiver cohorts (per-cohort state is [c, n]; "
            f"sample divergence, don't materialize every receiver), got {cfg.c}"
        )
    if cfg.delivery_spread < 0:
        raise ValueError(f"delivery_spread must be >= 0, got {cfg.delivery_spread}")
    if not 0 <= cfg.fd_window <= 32:
        raise ValueError(
            f"fd_window must be 0 (counter mode) or 1..32 (uint32 bit-history), "
            f"got {cfg.fd_window}"
        )
    if cfg.fd_window and cfg.fd_threshold > cfg.fd_window:
        raise ValueError(
            f"fd_threshold ({cfg.fd_threshold}) cannot exceed fd_window "
            f"({cfg.fd_window}): the edge could never fire"
        )
    alive = jnp.asarray(alive, dtype=bool)
    # The one sort: ring keys are static per slot, so every topology after
    # this (including every view change) is O(N) scans over these perms.
    perm = ring_perms(jnp.asarray(key_hi), jnp.asarray(key_lo))
    topo = ring_topology_from_perm(perm, alive)
    config_hi, config_lo = masked_set_hash(jnp.asarray(id_hi), jnp.asarray(id_lo), alive)
    n, k, c = cfg.n, cfg.k, cfg.c
    return EngineState(
        key_hi=jnp.asarray(key_hi, dtype=jnp.uint32),
        key_lo=jnp.asarray(key_lo, dtype=jnp.uint32),
        ring_perm=perm,
        id_hi=jnp.asarray(id_hi, dtype=jnp.uint32),
        id_lo=jnp.asarray(id_lo, dtype=jnp.uint32),
        alive=alive,
        obs_idx=topo.obs_idx,
        subj_idx=topo.subj_idx,
        # A copy, not an alias: engine_step donates its input state, and the
        # runtime rejects the same buffer donated twice.
        inval_obs=topo.obs_idx + 0,
        config_epoch=jnp.int32(0),
        config_hi=config_hi,
        config_lo=config_lo,
        n_members=jnp.sum(alive, dtype=jnp.int32),
        fd_count=jnp.zeros((n, k), dtype=jnp.int32),
        fd_hist=jnp.zeros((n, k), dtype=jnp.uint32),
        fd_fired=jnp.zeros((n, k), dtype=bool),
        fire_round=jnp.full((n, k), FIRE_NEVER, dtype=jnp.int32),
        join_pending=jnp.zeros((n,), dtype=bool),
        cohort_of=jnp.zeros((n,), dtype=jnp.int32),
        report_bits=jnp.zeros((c, n), dtype=jnp.uint32),
        seen_down=jnp.zeros((c,), dtype=bool),
        released=jnp.zeros((c, n), dtype=bool),
        announced=jnp.zeros((c,), dtype=bool),
        prop_mask=jnp.zeros((c, n), dtype=bool),
        prop_hi=jnp.zeros((c,), dtype=jnp.uint32),
        prop_lo=jnp.zeros((c,), dtype=jnp.uint32),
        vote_hi=jnp.zeros((n,), dtype=jnp.uint32),
        vote_lo=jnp.zeros((n,), dtype=jnp.uint32),
        vote_valid=jnp.zeros((n,), dtype=bool),
        rounds_undecided=jnp.int32(0),
        cp_rnd_r=jnp.zeros((n,), dtype=jnp.int32),
        cp_rnd_i=jnp.zeros((n,), dtype=jnp.int32),
        cp_vrnd_r=jnp.zeros((n,), dtype=jnp.int32),
        cp_vrnd_i=jnp.zeros((n,), dtype=jnp.int32),
        cp_vval_src=jnp.full((n,), -1, dtype=jnp.int32),
        classic_epoch=jnp.int32(0),
        round_idx=jnp.int32(0),
        retired=jnp.zeros((n,), dtype=bool),
    )


class FaultInputs(NamedTuple):
    """Per-step fault-injection masks (the device analog of the reference's
    StaticFailureDetector blacklist + MessageDropInterceptor fixtures)."""

    crashed: jnp.ndarray  # [n] bool — unresponsive; never votes or alerts
    probe_fail: jnp.ndarray  # [n, k] bool — extra per-edge probe failures
    rx_block: jnp.ndarray  # [c, n] bool — cohort c cannot hear from slot i

    @staticmethod
    def none(cfg: EngineConfig) -> "FaultInputs":
        return FaultInputs(
            crashed=jnp.zeros((cfg.n,), dtype=bool),
            probe_fail=jnp.zeros((cfg.n, cfg.k), dtype=bool),
            rx_block=jnp.zeros((cfg.c, cfg.n), dtype=bool),
        )


class StepEvents(NamedTuple):
    """Observable outcomes of one engine step (host-side driver reads these)."""

    decided: jnp.ndarray  # scalar bool — consensus reached this step
    # Which path decided: True = one-step fast round; False = the classic
    # fallback's coordinator rule (only meaningful when decided). The engine
    # twin of the host event VIEW_CHANGE_ONE_STEP_FAILED.
    fast_decided: jnp.ndarray  # scalar bool
    winner_mask: jnp.ndarray  # [n] bool — the decided cut (flip set)
    proposals_announced: jnp.ndarray  # [c] bool — cohorts that proposed this step
    alerts_emitted: jnp.ndarray  # int32 — new edge alerts this step
    total_votes: jnp.ndarray  # int32
    max_votes: jnp.ndarray  # int32
    # Per-cohort announced-proposal hash lanes as of THIS round, captured
    # before any view-change reset (reading state.prop_* after a deciding
    # step sees post-reset zeros — observers must use these instead).
    prop_hi: jnp.ndarray  # [c] uint32
    prop_lo: jnp.ndarray  # [c] uint32
