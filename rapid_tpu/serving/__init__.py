"""Streaming serving mode: overlap host I/O with device compute and measure
sustained throughput, not one-shot convergence (ROADMAP item 4).

``stream`` holds the pipeline — :class:`~rapid_tpu.serving.stream.StreamDriver`
double-buffers per-wave ``FaultInputs`` deltas against the in-flight engine
dispatches and synchronizes only at explicit fetch boundaries;
:class:`~rapid_tpu.serving.stream.PoissonChurn` turns a seeded arrival-rate
spec into per-wave churn deltas in the sim families' fault vocabulary, so
chaos schedules stream through the same pipe.
"""

from rapid_tpu.serving.stream import (  # noqa: F401
    STREAMABLE_KINDS,
    FleetPoissonChurn,
    FleetWave,
    PoissonChurn,
    StreamDriver,
    StreamResult,
    StreamWave,
    waves_from_schedule,
)

__all__ = [
    "FleetPoissonChurn",
    "FleetWave",
    "PoissonChurn",
    "StreamDriver",
    "StreamResult",
    "StreamWave",
    "STREAMABLE_KINDS",
    "waves_from_schedule",
]
