"""Streaming serving mode: overlap host I/O with device compute and measure
sustained throughput, not one-shot convergence (ROADMAP item 4).

``stream`` holds the pipeline — :class:`~rapid_tpu.serving.stream.StreamDriver`
double-buffers per-wave ``FaultInputs`` deltas against the in-flight engine
dispatches and synchronizes only at explicit fetch boundaries;
:class:`~rapid_tpu.serving.stream.PoissonChurn` turns a seeded arrival-rate
spec into per-wave churn deltas in the sim families' fault vocabulary, so
chaos schedules stream through the same pipe.

``supervisor`` + ``recovery`` hold the self-healing tier over that pipeline:
deadline-bounded dispatch with seeded-backoff retries
(:class:`~rapid_tpu.serving.supervisor.Supervisor`), crash-consistent
checkpoint/resume with bit-identical deterministic replay, per-tenant
quarantine of poisoned fleet tenants, and the seeded
:class:`~rapid_tpu.serving.supervisor.SupervisorFaultPlan` that injects
every failure class the tier must survive.
"""

from rapid_tpu.serving import recovery  # noqa: F401
from rapid_tpu.serving.stream import (  # noqa: F401
    STREAMABLE_KINDS,
    FleetPoissonChurn,
    FleetWave,
    PoissonChurn,
    StreamDriver,
    StreamResult,
    StreamWave,
    waves_from_schedule,
)
from rapid_tpu.serving.supervisor import (  # noqa: F401
    BackoffPolicy,
    DispatchWedgedError,
    SimulatedProcessKill,
    Supervisor,
    SupervisorBudgets,
    SupervisorFaultPlan,
    TransientDispatchError,
)

__all__ = [
    "BackoffPolicy",
    "DispatchWedgedError",
    "FleetPoissonChurn",
    "FleetWave",
    "PoissonChurn",
    "SimulatedProcessKill",
    "StreamDriver",
    "StreamResult",
    "StreamWave",
    "STREAMABLE_KINDS",
    "Supervisor",
    "SupervisorBudgets",
    "SupervisorFaultPlan",
    "TransientDispatchError",
    "recovery",
    "waves_from_schedule",
]
