"""The streaming dispatch pipeline: double-buffered uploads, pipelined
dispatches, synchronization only at explicit fetch boundaries.

The batch drivers (``VirtualCluster`` / ``TenantFleet``) run build ->
upload -> converge -> fetch: the host idles while the device computes and
the device idles during every ``FaultInputs`` upload. Production traffic is
a continuous alert stream, and the numbers a serving system publishes are
sustained view-changes/sec and p99 alert->commit latency — not one-shot
convergence time. :class:`StreamDriver` restructures the dispatch loop for
that workload:

- **Pipelined dispatches.** Each submitted wave enqueues its churn delta
  (device-side scatters — only slot indices cross the boundary) plus
  ``rounds_per_wave`` engine rounds through the fetch-free ``stream_step``
  seam. JAX async dispatch queues everything in program order; the host
  returns immediately and starts building the NEXT wave while the device
  chews through this one.
- **Double-buffered inputs.** Every engine entrypoint donates its state
  pytree (38/38 leaves aliased, frozen in ``hlo.lock.json``), so the state
  buffers ping-pong in place; the per-wave fault deltas land in fresh
  buffers the host writes while the previous wave's buffers are still
  feeding in-flight dispatches. Donation is what makes this safe: the
  driver never hands the device a buffer the host might still mutate.
- **Explicit fetch boundaries.** The only host syncs are the completion
  ticket waits (the last round's device-resident ``StepEvents.decided``)
  and the drain-time epoch fetch, both accounted under the
  ``stream_fetch`` dispatch phase. Overlap efficiency falls straight out
  of the phase histograms: the fraction of stream wall time the host was
  NOT blocked in ``stream_fetch`` is the fraction during which host work
  (building + uploading the next waves) overlapped device compute.

:class:`PoissonChurn` supplies the traffic: a seeded arrival-rate spec
drawn wave by wave (``numpy`` Poisson, one ``default_rng(seed)`` — a whole
schedule is a pure function of its seed), speaking the sim families' fault
vocabulary (``crash``/``join`` :class:`~rapid_tpu.sim.faults.FaultEvent`
kinds), so chaos schedules stream through the same pipe
(:func:`waves_from_schedule`).

Bit-identity bar: a schedule driven wave-by-wave through the stream driver
yields exactly the cuts, config ids, and final state pytree of the same
schedule driven through the batch seams — same compiled programs, same
inputs, same order; only the synchronization structure differs. Pinned by
``tests/test_stream.py`` for both the single-cluster and fleet paths.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rapid_tpu.sim.faults import FaultEvent
from rapid_tpu.utils.histogram import LogHistogram

#: The subset of the sim fault vocabulary the streaming pipeline carries:
#: membership churn. Environment faults (loss, delay, partitions) ride the
#: engine's delivery knobs instead (sim.faults.loss_as_engine_delivery) —
#: they are configuration, not per-wave traffic.
STREAMABLE_KINDS = frozenset({"crash", "join"})


@dataclass(frozen=True)
class StreamWave:
    """One wave of single-cluster churn: slots to crash and fresh slots to
    admit, applied together before the wave's engine rounds."""

    crash: Tuple[int, ...] = ()
    join: Tuple[int, ...] = ()

    def fault_events(self) -> List[FaultEvent]:
        """This wave in the sim families' fault vocabulary — the exact
        inverse of :func:`waves_from_schedule` (round trip pinned in
        tests/test_stream.py), so stream schedules serialize/replay through
        the same `FaultSchedule` tooling as chaos runs. A wave carrying
        both deltas emits them OVERLAPPED (``settle=False`` on all but the
        last event): one wave applies its whole delta before any engine
        round, which is precisely the schedule's no-convergence-between
        shape.

        An EMPTY wave — pure pacing, ``rounds_per_wave`` engine rounds with
        no churn (Poisson emits one whenever a draw lands on k=0) — is
        rejected loudly: the schedule grammar forbids membership events
        without slots, so the wave has no spelling, and silently dropping
        it would replay FEWER engine rounds than the stream ran — a
        different scenario (failure-detector counters advance per round).
        Filter pacing waves out explicitly if round counts do not matter to
        the replay."""
        if not (self.crash or self.join):
            raise ValueError(
                "an empty wave has no sim-vocabulary spelling (the schedule "
                "grammar forbids membership events without slots), and "
                "dropping it would replay fewer engine rounds than the "
                "stream ran; filter pacing waves explicitly if round counts "
                "do not matter to the replay"
            )
        events = []
        if self.crash:
            events.append(FaultEvent(
                kind="crash", slots=tuple(self.crash),
                settle=not self.join,
            ))
        if self.join:
            events.append(FaultEvent(kind="join", slots=tuple(self.join)))
        return events


@dataclass(frozen=True)
class FleetWave:
    """One wave of fleet churn: ``(tenant, slot)`` crash pairs (fleet
    streaming carries crash churn; joins need per-tenant gatekeeper
    derivation, a pre-stacking ``VirtualCluster`` operation)."""

    crash: Tuple[Tuple[int, int], ...] = ()


def waves_from_schedule(schedule) -> List[StreamWave]:
    """Convert a sim ``FaultSchedule`` (or an iterable of ``FaultEvent``)
    into stream waves, one wave per SETTLED membership event in schedule
    order: an event marked ``settle=False`` overlaps with its successor, so
    it folds into the successor's wave (the wave's whole delta applies
    before any engine round — the schedule's no-convergence-between shape,
    preserved rather than serialized away). Everything the stream cannot
    represent is rejected loudly — kinds outside :data:`STREAMABLE_KINDS`
    and nonzero ``dwell_ms`` (waves advance in engine rounds, not simulated
    milliseconds): silently dropping either would stream a DIFFERENT
    scenario than the schedule describes."""
    events = getattr(schedule, "events", schedule)
    waves: List[StreamWave] = []
    crash: List[int] = []
    join: List[int] = []
    for event in events:
        if event.kind not in STREAMABLE_KINDS:
            raise ValueError(
                f"fault kind {event.kind!r} is not streamable (only "
                f"{sorted(STREAMABLE_KINDS)} carry per-wave deltas); "
                f"environment faults compile onto engine delivery knobs "
                f"(rapid_tpu.sim.faults.loss_as_engine_delivery)"
            )
        if getattr(event, "dwell_ms", 0.0):
            raise ValueError(
                f"dwell_ms={event.dwell_ms!r} is not streamable: the "
                f"pipeline advances in engine rounds (rounds_per_wave), "
                f"not simulated milliseconds — zero the dwell or replay "
                f"the schedule through the sim harness instead"
            )
        if event.kind == "crash":
            crash.extend(event.slots)
        else:
            join.extend(event.slots)
        if getattr(event, "settle", True):
            waves.append(StreamWave(crash=tuple(crash), join=tuple(join)))
            crash, join = [], []
    if crash or join:
        # A trailing settle=False event has nothing to overlap with; it
        # still needs its engine rounds, so it closes the final wave.
        waves.append(StreamWave(crash=tuple(crash), join=tuple(join)))
    return waves


class PoissonChurn:
    """Seeded Poisson arrival process over the engine's slot table.

    Each wave draws ``k ~ Poisson(rate)`` churn events; each event is a
    join of a fresh slot with probability ``join_fraction`` (while fresh
    slots remain — the generator never reuses a slot, which is what lets
    the stream driver skip the admissibility fetch) or a crash of a live
    member. The whole schedule is a pure function of ``seed``.
    """

    def __init__(
        self,
        n_members: int,
        n_slots: int,
        rate: float,
        seed: int = 0,
        join_fraction: float = 0.5,
    ) -> None:
        if rate <= 0:
            raise ValueError(f"arrival rate must be positive, got {rate}")
        if not 0.0 <= join_fraction <= 1.0:
            raise ValueError(f"join_fraction must be in [0, 1], got {join_fraction}")
        if not 0 < n_members <= n_slots:
            raise ValueError(
                f"need 0 < n_members <= n_slots, got {n_members}/{n_slots}"
            )
        self.rate = float(rate)
        self._rng = np.random.default_rng(seed)
        # Host-side slot bookkeeping mirrors the engine's lifecycle rules:
        # crash candidates are the original members still standing (a
        # joiner may still be pending admission — crashing it would model a
        # different scenario than "churn on members"); joins pop fresh
        # slots and never reuse one (the engine's UUIDAlreadySeenError).
        self._live: List[int] = list(range(n_members))
        self._fresh: Deque[int] = deque(range(n_members, n_slots))
        self.join_fraction = float(join_fraction)

    def wave(self) -> StreamWave:
        crash: List[int] = []
        join: List[int] = []
        for _ in range(int(self._rng.poisson(self.rate))):
            wants_join = self._fresh and (
                float(self._rng.random()) < self.join_fraction
            )
            if wants_join:
                join.append(self._fresh.popleft())
            elif self._live:
                victim = int(self._rng.integers(len(self._live)))
                crash.append(self._live.pop(victim))
        return StreamWave(crash=tuple(crash), join=tuple(join))

    def waves(self, count: int) -> List[StreamWave]:
        return [self.wave() for _ in range(count)]

    @classmethod
    def fleet(
        cls,
        tenants: int,
        n_members: int,
        rate: float,
        seed: int = 0,
    ) -> "FleetPoissonChurn":
        """The fleet-shaped generator: independent per-tenant Poisson crash
        streams folded into per-wave ``(tenant, slot)`` pair sets."""
        return FleetPoissonChurn(tenants, n_members, rate, seed)


class FleetPoissonChurn:
    """B independent per-tenant Poisson crash streams (one seeded rng,
    tenant-ordered draws — deterministic per seed), emitting
    :class:`FleetWave` pair sets."""

    def __init__(self, tenants: int, n_members: int, rate: float, seed: int = 0):
        if tenants <= 0:
            raise ValueError(f"need at least one tenant, got {tenants}")
        if rate <= 0:
            raise ValueError(f"arrival rate must be positive, got {rate}")
        self.rate = float(rate)
        self._rng = np.random.default_rng(seed)
        self._live: List[List[int]] = [
            list(range(n_members)) for _ in range(tenants)
        ]

    def wave(self) -> FleetWave:
        pairs: List[Tuple[int, int]] = []
        for tenant, live in enumerate(self._live):
            for _ in range(int(self._rng.poisson(self.rate))):
                if not live:
                    break
                victim = int(self._rng.integers(len(live)))
                pairs.append((tenant, live.pop(victim)))
        return FleetWave(crash=tuple(pairs))

    def waves(self, count: int) -> List[FleetWave]:
        return [self.wave() for _ in range(count)]


class StreamResult(NamedTuple):
    """Drain-time stream report (cumulative since driver construction)."""

    waves: int  # waves submitted
    rounds: int  # engine rounds enqueued (waves * rounds_per_wave)
    cuts: int  # view changes committed (config-epoch delta, fetched once)
    wall_ms: float  # first submit -> drain completion
    view_changes_per_sec: float  # cuts over wall (0.0 on zero-wave/zero-elapsed drains)
    p99_alert_to_commit_ms: Optional[float]  # submit -> observed-complete p99
    overlap_efficiency: Optional[float]  # 1 - fetch-blocked/wall, in [0, 1]
    fetch_blocked_ms: float  # host time in stream_fetch (the un-overlapped part)
    h2d_bytes: int  # bytes uploaded during the stream (delta deltas + indices)


def _stream_fetch_ms(metrics) -> float:
    """Total host-blocked milliseconds in the ``stream_fetch`` phase, read
    from the shared ``engine_dispatch_ms`` histogram family — the overlap
    ratio's denominator input comes from the SAME instrument dashboards
    render, so the published number is checkable from any scrape."""
    family = metrics.phase_timings.get("engine_dispatch", {})
    hist = family.get("stream_fetch")
    if hist is None or not hist.count:
        return 0.0
    return float(hist.summary()["sum"])


def _ticket_ready(ticket) -> bool:
    """Non-blocking completion probe (``jax.Array.is_ready``); a backend
    without the probe reports not-ready and completion is observed at the
    next blocking boundary instead — correctness never depends on it."""
    probe = getattr(ticket, "is_ready", None)
    if not callable(probe):
        return False
    return bool(probe())


class StreamDriver:
    """Pipelined streaming front-end over a ``VirtualCluster`` or
    ``TenantFleet`` (module docstring: the pipeline, the buffers, the fetch
    boundaries).

    ``rounds_per_wave`` engine rounds are enqueued per submitted wave;
    ``depth`` bounds the waves in flight — at the bound, :meth:`submit`
    first blocks on the OLDEST wave's ticket (a ``stream_fetch`` boundary),
    which is the pipeline's backpressure. :meth:`drain` completes every
    outstanding wave, fetches the committed-cut count (one scalar), and
    returns the :class:`StreamResult` with the sustained metrics.
    """

    def __init__(
        self,
        target,
        rounds_per_wave: int = 8,
        depth: int = 2,
        clock=None,
        ticket_wait=None,
        ticket_ready=None,
    ) -> None:
        if rounds_per_wave < 1:
            raise ValueError(f"rounds_per_wave must be >= 1, got {rounds_per_wave}")
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.target = target
        self.rounds_per_wave = int(rounds_per_wave)
        self.depth = int(depth)
        #: Injected timing source (seconds; monotonic). Every latency/wall
        #: decision below reads THIS, so a supervisor (or a test) owns time;
        #: the default is the process clock.
        self._clock = clock if clock is not None else time.perf_counter  # wall-clock-ok: default timing source when no supervisor injects one
        #: Injected blocking-wait seam: ``(budget_phase, wave_index, ticket)
        #: -> None``. The supervision tier (rapid_tpu/serving/supervisor.py)
        #: installs its deadline-bounded waiter here; the default waits
        #: unboundedly (the pre-supervision behavior). ``budget_phase`` is
        #: the budget-table key ("submit" for backpressure waits, "drain",
        #: "stream_fetch"), distinct from the telemetry phase label (always
        #: ``stream_fetch`` — the histogram measures host-blocked time
        #: regardless of WHY the host blocked).
        self._ticket_wait = ticket_wait
        #: Injected non-blocking readiness probe: ``(wave_index, ticket) ->
        #: bool``, consulted by the opportunistic reaper. The supervisor
        #: installs one that reports its fault plan's wedged/lost tickets
        #: as never-ready — without it, a depth>1 pipeline would reap a
        #: plan-wedged wave through the REAL probe before any bounded wait
        #: ever saw it, silently bypassing the injected fault.
        self._ticket_ready = ticket_ready
        self._is_fleet = hasattr(target, "knobs")
        # Host-side admissibility mirror (single-cluster path): ONE
        # pre-stream fetch of the slot-lifecycle lanes, then pure host
        # bookkeeping on every wave — the stream enforces the batch path's
        # reused-slot discipline (the engine's UUIDAlreadySeenError) for
        # ALL wave sources, not just PoissonChurn's fresh-slots-only
        # contract, without putting the per-wave [j]-bool fetch back on
        # the pipeline. Fleet waves carry only crashes — no admissibility.
        if self._is_fleet:
            self._inadmissible = None
        else:
            with target._dispatch("stream_fetch"):
                state = target.state
                # np.array, not asarray: the mirror is mutated per wave and
                # a jax export can surface as a read-only view.
                self._inadmissible = np.array(  # host-sync-ok: one pre-stream lifecycle snapshot
                    state.alive | state.join_pending | state.retired
                )
            target._account_d2h(int(self._inadmissible.nbytes))
        #: (wave index, submit perf_counter, device-resident ticket).
        self._pending: Deque[Tuple[int, float, object]] = deque()
        self.waves_submitted = 0
        self.waves_completed = 0
        self._cuts_reported = 0  # already inc'd into engine_stream_cuts
        self._latency = LogHistogram()
        self._t0_stream: Optional[float] = None
        self._last_result: Optional[StreamResult] = None
        # Baselines for the drain-time deltas (epoch fetch is the one
        # pre-stream sync; its cost is excluded from the overlap ratio by
        # snapshotting the fetch-phase sum AFTER it).
        self._epoch0 = self._fetch_epoch_total()
        self._fetch_ms0 = _stream_fetch_ms(target.metrics)
        self._h2d0 = int(target.metrics.counters.get("engine_h2d_bytes", 0))
        # Round-trace attribution (trace>0 targets): every wave enqueues
        # exactly rounds_per_wave rounds through stream_step, so wave i
        # spans ring sequence [base + i*rpw, base + (i+1)*rpw) per lane —
        # pure host arithmetic from a submit-time cursor snapshot, ZERO
        # added fetches on the pipelined path. The base cursor comes from
        # the decoded cache refreshed here (construction is already a
        # fetch boundary — the epoch/admissibility fetches above).
        self._has_trace = getattr(target, "trace_ring", None) is not None
        self._wave_queue_depth: List[int] = []
        #: Drain-time queue-wait vs rounds-to-decision decomposition
        #: (:meth:`_round_trajectory`), or None before the first drain.
        self.last_trajectory: Optional[dict] = None
        if self._has_trace:
            target._refresh_activity()
            self._trace_base = [
                s["rounds_recorded"] for s in self._trace_summaries()
            ]
        # Surface the stream stats through the target's telemetry snapshot
        # (engine.stream section; golden gauge names pinned in
        # tests/test_engine_telemetry.py).
        target.stream = self

    # -- pipeline -------------------------------------------------------

    def submit(self, wave) -> None:
        """Enqueue one wave: apply its churn delta, enqueue
        ``rounds_per_wave`` engine rounds, remember the completion ticket.
        Returns as soon as everything is QUEUED — the only blocking path is
        backpressure at ``depth`` waves in flight."""
        if self._t0_stream is None:
            self._t0_stream = self._clock()
        while len(self._pending) >= self.depth:
            self._complete_wave("submit")
        self._reap_ready()
        if self._has_trace:
            # Submit-time cursor snapshot, spelled as queue depth: the
            # waves still in flight ahead of this one each own rpw ring
            # records this wave must wait behind.
            self._wave_queue_depth.append(len(self._pending))
        t_submit = self._clock()
        self._apply(wave)
        events = None
        for _ in range(self.rounds_per_wave):
            events = self.target.stream_step()
        # The last round's decided flag is the wave's ticket: a fresh
        # output buffer (never donated away by later rounds), ready exactly
        # when every dispatch of this wave has executed.
        self._pending.append((self.waves_submitted, t_submit, events.decided))
        self.waves_submitted += 1
        self.target.metrics.inc("engine_stream_waves")

    def drain(self) -> StreamResult:
        """Complete every outstanding wave, fetch the committed-cut count,
        and report the sustained metrics (cumulative since construction).

        Degenerate streams are well-defined, never NaN/inf: a zero-wave
        drain (nothing ever submitted) and a zero-elapsed drain (a clock
        too coarse to observe the stream's wall time) both report rate 0.0
        — dividing by a ~0 wall would publish an absurd rate into bench
        JSON, and ``None`` would erase the difference between "not yet
        drained" and "drained, nothing to rate". Pinned in
        tests/test_stream.py."""
        while self._pending:
            self._complete_wave("drain")
        epoch_total = self._fetch_epoch_total()
        # Drain is a stream_fetch boundary, so the device telemetry plane
        # refreshes here too (the lanes' digest fetch carries its own
        # telemetry-fetch-ok marker inside _refresh_activity) — never per
        # submitted wave, which would put a sync on the pipelined path.
        self.target._refresh_activity()
        if self._has_trace:
            self.last_trajectory = self._round_trajectory()
        cuts = epoch_total - self._epoch0
        wall_ms = (
            (self._clock() - self._t0_stream) * 1000.0
            if self._t0_stream is not None
            else 0.0
        )
        fetch_blocked_ms = _stream_fetch_ms(self.target.metrics) - self._fetch_ms0
        overlap = (
            max(0.0, min(1.0, 1.0 - fetch_blocked_ms / wall_ms))
            if wall_ms > 0
            else None
        )
        self.target.metrics.inc("engine_stream_cuts", cuts - self._cuts_reported)
        self._cuts_reported = cuts
        counters = self.target.metrics.counters
        self._last_result = StreamResult(
            waves=self.waves_submitted,
            rounds=self.waves_submitted * self.rounds_per_wave,
            cuts=cuts,
            wall_ms=wall_ms,
            view_changes_per_sec=(
                cuts / (wall_ms / 1000.0) if wall_ms > 0 else 0.0
            ),
            p99_alert_to_commit_ms=(
                float(self._latency.quantile(0.99)) if self._latency.count else None
            ),
            overlap_efficiency=overlap,
            fetch_blocked_ms=fetch_blocked_ms,
            h2d_bytes=int(counters.get("engine_h2d_bytes", 0)) - self._h2d0,
        )
        return self._last_result

    # -- internals ------------------------------------------------------

    def _apply(self, wave) -> None:
        """Enqueue one wave's churn delta through the target's injection
        seams (device-side scatters; only indices upload)."""
        if isinstance(wave, FleetWave):
            if not self._is_fleet:
                raise TypeError(
                    "FleetWave submitted to a single-cluster stream "
                    "(build the driver over a TenantFleet)"
                )
            if wave.crash:
                self.target.stream_crash(wave.crash)
            return
        if self._is_fleet:
            raise TypeError(
                "StreamWave submitted to a fleet stream (use FleetWave — "
                "PoissonChurn.fleet generates them)"
            )
        if wave.crash:
            self.target.crash(list(wave.crash))
            # Crashed slots retire once their cut commits — inadmissible
            # for rejoin either way (members already were).
            self._inadmissible[list(wave.crash)] = True
        if wave.join:
            # The admissibility check runs against the HOST mirror — same
            # rule as the batch path's device fetch, zero pipeline syncs.
            # Out-of-range slots fall through to inject_join_wave's own
            # bounds check (the canonical IndexError).
            bad = [
                s for s in wave.join
                if 0 <= s < self._inadmissible.size and self._inadmissible[s]
            ]
            if bad:
                raise ValueError(
                    f"slots not admissible as joiners (member/pending/"
                    f"retired): {bad}"
                )
            self.target.inject_join_wave(list(wave.join), check_admissible=False)
            self._inadmissible[list(wave.join)] = True

    def _complete_wave(self, budget_phase: str = "stream_fetch") -> None:
        """Block on the OLDEST wave's ticket — an explicit ``stream_fetch``
        boundary — and record its alert->commit latency. ``budget_phase``
        names WHY the host is blocking (backpressure inside ``submit``, the
        ``drain`` sweep) for the injected deadline waiter; the telemetry
        phase stays ``stream_fetch`` either way."""
        idx, t_submit, ticket = self._pending.popleft()
        with self.target._dispatch("stream_fetch"):
            if self._ticket_wait is not None:
                self._ticket_wait(budget_phase, idx, ticket)
            else:
                jax.block_until_ready(ticket)  # host-sync-ok: the explicit fetch boundary
        self._record_completion(t_submit)

    def _reap_ready(self) -> None:
        """Retire already-completed waves without blocking (is_ready probe,
        or the injected fault-aware probe) so alert->commit latencies are
        observed close to actual completion instead of at the next forced
        boundary."""
        while self._pending and (
            self._ticket_ready(self._pending[0][0], self._pending[0][2])
            if self._ticket_ready is not None
            else _ticket_ready(self._pending[0][2])
        ):
            _idx, t_submit, _ticket = self._pending.popleft()
            self._record_completion(t_submit)

    def _record_completion(self, t_submit: float) -> None:
        latency_ms = (self._clock() - t_submit) * 1000.0
        self._latency.observe(latency_ms)
        self.target.metrics.record_ms("engine_stream_alert_to_commit", latency_ms)
        self.waves_completed += 1

    def _trace_summaries(self) -> List[dict]:
        """The target's cached decoded ring summaries, one per lane (the
        single cluster is one lane; a fleet is one per tenant). Reads the
        host cache only — never the device."""
        if self._is_fleet:
            return self.target._trace or []
        return [self.target._trace] if self.target._trace is not None else []

    def _round_trajectory(self) -> dict:
        """Decompose the streamed latency story into queue-wait vs
        rounds-to-decision, from the decoded rings at a drain boundary.

        Wave ``i`` owns ring sequence ``[base + i*rpw, base + (i+1)*rpw)``
        in every lane (each submit enqueues exactly ``rounds_per_wave``
        rounds; the cursor is write-per-round). A wave's rounds-to-decision
        is the 1-based offset of the first decided record in its span,
        maxed across lanes (a fleet wave completes when its slowest tenant
        decides); a wave whose span slid out of the bounded ring is counted
        EVICTED, never silently attributed — the ring holds the last R
        rounds only. Queue-wait rides the submit-time snapshot: each wave
        in flight ahead at submit owns ``rpw`` records this wave queued
        behind."""
        rpw = self.rounds_per_wave
        summaries = self._trace_summaries()
        decisions: List[int] = []
        undecided = evicted = 0
        for w in range(self.waves_submitted):
            lane_hits: List[int] = []
            known = True
            for lane, s in enumerate(summaries):
                lo = self._trace_base[lane] + w * rpw
                oldest = s["rounds_recorded"] - s["rounds_held"]
                if lo < oldest:
                    known = False
                    break
                # Records are oldest-first with contiguous seq, so the
                # span is a direct slice.
                span = s["records"][lo - oldest : lo - oldest + rpw]
                hit = next(
                    (r["seq"] - lo + 1 for r in span if r["path"]), None
                )
                if hit is not None:
                    lane_hits.append(hit)
            if not known:
                evicted += 1
            elif lane_hits:
                decisions.append(max(lane_hits))
            else:
                undecided += 1
        queue_waits = [d * rpw for d in self._wave_queue_depth]
        actives = [
            r["active"] for s in summaries for r in s["records"]
        ]

        def q(vals, p):
            return float(np.percentile(vals, p)) if vals else None

        return {
            "rounds_per_wave": rpw,
            "waves_attributed": len(decisions) + undecided,
            "waves_evicted": evicted,
            "decided_waves": len(decisions),
            "undecided_waves": undecided,
            "rounds_to_decision_p50": q(decisions, 50),
            "rounds_to_decision_p99": q(decisions, 99),
            "rounds_to_decision_max": max(decisions) if decisions else None,
            "queue_wait_rounds_p99": q(queue_waits, 99),
            "active_p99": q(actives, 99),
        }

    def _fetch_epoch_total(self) -> int:
        """Total committed view changes across the SERVING tenants (sum of
        config_epoch — scalar for a cluster, [t] lanes for a fleet), one
        4-byte fetch under the ``stream_fetch`` phase. Quarantined fleet
        tenants are masked out: the batched step program keeps executing
        their rounds (vmap lockstep — freezing them there would need a new
        program input, i.e. a recompile), so a poisoned tenant's garbage
        epoch increments must not pollute the published cut counts and
        rates. With a deadline waiter installed, the wait for the enqueued
        work is bounded BEFORE the scalar fetch, so a wedged pipeline
        surfaces as the waiter's named error, never an unbounded block
        inside the fetch."""
        with self.target._dispatch("stream_fetch"):
            epoch = self.target.state.config_epoch
            if self._ticket_wait is not None:
                self._ticket_wait("stream_fetch", self.waves_submitted, epoch)
            quarantined = getattr(self.target, "quarantined", ())
            if quarantined:
                serving = np.ones(epoch.shape, dtype=bool)
                serving[list(quarantined)] = False
                self.target._account_h2d(serving)
                epoch = jnp.where(jnp.asarray(serving), epoch, 0)
            total = int(jnp.sum(epoch))  # host-sync-ok: fetch boundary
        self.target._account_d2h(4)
        return total

    # -- observability --------------------------------------------------

    def snapshot(self) -> dict:
        """The ``engine.stream`` telemetry section (JSON-serializable;
        gauges render as ``rapid_engine_stream_*``). Pre-drain snapshots
        carry None for the drain-derived rates — the exposition renders
        them NaN so the series set is stable from the first scrape."""
        last = self._last_result
        tj = self.last_trajectory or {}
        return {
            "waves_submitted": self.waves_submitted,
            "waves_completed": self.waves_completed,
            "waves_in_flight": len(self._pending),
            "rounds_per_wave": self.rounds_per_wave,
            "depth": self.depth,
            "view_changes_per_sec": (
                # Always a float after a drain (0.0 on degenerate streams);
                # None means "not yet drained", nothing else.
                round(last.view_changes_per_sec, 3)
                if last is not None
                else None
            ),
            "overlap_efficiency": (
                round(last.overlap_efficiency, 4)
                if last is not None and last.overlap_efficiency is not None
                else None
            ),
            "p99_alert_to_commit_ms": (
                round(float(self._latency.quantile(0.99)), 3)
                if self._latency.count
                else None
            ),
            # Ring-derived decomposition, present only on trace>0 targets
            # (the stable-series rule: a trace=0 stream's scrape vocabulary
            # is unchanged). None before the first drain — the exposition
            # renders NaN, never a missing series.
            **(
                {
                    "rounds_to_decision_p99": tj.get("rounds_to_decision_p99"),
                    "queue_wait_rounds_p99": tj.get("queue_wait_rounds_p99"),
                    "waves_evicted": tj.get("waves_evicted"),
                }
                if self._has_trace
                else {}
            ),
        }


# Referenced by type, not just name, so tree-wide liveness tooling and
# readers alike see the public generator pair together.
__all__ = [
    "FleetPoissonChurn",
    "FleetWave",
    "PoissonChurn",
    "StreamDriver",
    "StreamResult",
    "StreamWave",
    "STREAMABLE_KINDS",
    "waves_from_schedule",
]
