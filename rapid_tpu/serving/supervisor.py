"""The self-healing supervision tier over the streaming serving pipeline.

PR 12 made the *protocol* adversary-proof; this module makes the serving
*runtime* failure-proof — the "Reconfigurable Atomic Transaction Commit"
(arXiv:1906.01365) reconfiguration-under-failure shape applied to the
serving tier itself. Three disciplines, composed over
:class:`~rapid_tpu.serving.stream.StreamDriver`:

- **Deadline-bounded dispatch.** Every ticket wait — ``submit``
  backpressure, the ``drain`` sweep, the ``stream_fetch`` epoch fetch —
  gets a per-phase deadline from the declared :class:`SupervisorBudgets`
  table. The waiter polls the device-resident ticket's ``is_ready`` probe
  between injected-clock sleeps, so a wedged dispatch surfaces as a LOUD
  :class:`DispatchWedgedError` naming the phase and wave index (the exact
  240 s-idle wedge class that froze the perf story at r03, ROADMAP item 1)
  instead of an unbounded host block. All timing decisions read the
  INJECTED clock — no wall-clock reads in the decision path (the
  ``clock-injection`` lint now sweeps ``rapid_tpu/serving/``).

- **Retry with seeded-jitter exponential backoff.** Transient dispatch
  failures (:class:`TransientDispatchError` — what a momentarily
  unavailable backend or an injected fault raises) retry on the
  :class:`BackoffPolicy` schedule, a pure function of its seed (the
  determinism lint's discipline: a supervised run replays bit-identically,
  jitter included). Exhausted retries escalate to the same loud
  :class:`DispatchWedgedError`.

- **Crash-consistent checkpoints + quarantine.** Every ``checkpoint_every``
  waves the supervisor writes an xxh64-sealed, atomically-published fleet
  checkpoint (utils/checkpoint.py) carrying the wave cursor;
  ``rapid_tpu/serving/recovery.py`` resumes from the newest VALID one —
  corrupt files are skipped loudly, and resume replays the seeded churn
  schedule to bit-identical final state. For fleets,
  :meth:`Supervisor.scan_and_quarantine` runs the cheap device-side health
  reduction (``TenantFleet.health_scan``), quarantines poisoned tenants
  inside the running compiled program (the existing per-tenant freeze
  lanes — data, not a recompile), exports a replayable repro dir, and
  keeps the other B-1 tenants serving.

Everything is observable: ledger ``RECOVERY_*`` events (when a ledger is
attached), ``engine_recovery_*`` counters/gauges in the exposition, and
the drained stream metrics unchanged.

:class:`SupervisorFaultPlan` is the fault-injection surface that proves all
of it — fail the Nth dispatch, wedge or lose a wave's ticket, kill the
process between waves, corrupt or truncate a checkpoint — in the sim/chaos
determinism discipline (a plan plus a seed is a whole reproducible
failure drill). Pinned end-to-end in tests/test_supervisor.py.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import numpy as np

from rapid_tpu.serving.stream import FleetWave, StreamDriver, StreamResult
from rapid_tpu.utils.ledger import LedgerEvent


class SupervisorBudgets(NamedTuple):
    """The declared per-phase deadline table (milliseconds): how long each
    ticket-wait class may block before the supervisor declares the dispatch
    wedged. Defaults are far above any healthy CPU/TPU dispatch and far
    below the historical 240 s watchdog idle — a wedge is named in seconds,
    not discovered by the session timeout."""

    submit_ms: float = 60_000.0  # backpressure wait on the oldest ticket
    drain_ms: float = 120_000.0  # the drain sweep's per-ticket waits
    stream_fetch_ms: float = 60_000.0  # the epoch-fetch readiness wait
    checkpoint_ms: float = 120_000.0  # state settle before a checkpoint write

    def for_phase(self, phase: str) -> float:
        try:
            return float(getattr(self, f"{phase}_ms"))
        except AttributeError:
            raise ValueError(
                f"no deadline budget declared for phase {phase!r}; add a "
                f"<phase>_ms field to SupervisorBudgets"
            ) from None


class BackoffPolicy(NamedTuple):
    """Seeded-jitter exponential backoff: the whole retry-delay schedule is
    a pure function of ``seed`` (:meth:`delays_ms`), so a supervised run —
    retries included — replays bit-identically (the sim determinism
    discipline; the ``unseeded-random`` lint sweeps this package)."""

    max_attempts: int = 4
    base_ms: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25  # fraction of the step added as seeded jitter
    seed: int = 0

    def delays_ms(self) -> Tuple[float, ...]:
        """The ``max_attempts - 1`` inter-attempt delays, in order."""
        rng = np.random.default_rng(self.seed)
        return tuple(
            self.base_ms
            * self.multiplier**attempt
            * (1.0 + self.jitter * float(rng.random()))
            for attempt in range(max(0, self.max_attempts - 1))
        )


class TransientDispatchError(RuntimeError):
    """A retryable PRE-DISPATCH admission failure: the supervisor retries
    it on the backoff schedule. Raised by the fault plan (and the class a
    real transient admission check — backend readiness, quota — should be
    translated to). Deliberately NOT caught around the wave application
    itself: once ``driver.submit`` starts, the churn delta may be
    half-applied, and re-running it would double-crash/double-join slots —
    a mid-application failure escalates instead of retrying."""


class DispatchWedgedError(RuntimeError):
    """A dispatch exceeded its phase deadline (or exhausted its retries):
    the supervision tier's loud terminal error, naming the phase and wave
    index so a wedge reads as "wave 7 wedged in submit backpressure", never
    a silent 240 s idle."""

    def __init__(self, phase: str, wave_index: int, reason: str) -> None:
        self.phase = phase
        self.wave_index = wave_index
        super().__init__(
            f"dispatch wedged: phase {phase!r}, wave {wave_index}: {reason}"
        )


class SimulatedProcessKill(RuntimeError):
    """The fault plan's between-waves process kill: raised AFTER the wave
    (and any due checkpoint) completed, exactly where SIGKILL would land in
    a real preemption. The recovery drill catches it and resumes from the
    checkpoint directory (rapid_tpu/serving/recovery.py)."""

    def __init__(self, wave_index: int) -> None:
        self.wave_index = wave_index
        super().__init__(f"simulated process kill after wave {wave_index}")


@dataclass(frozen=True)
class SupervisorFaultPlan:
    """Declarative, seed-free fault injection for the supervision seams
    (determinism rides the supervisor's own seeded backoff — the plan is a
    pure description). Wave indices are ABSOLUTE (they survive a resume's
    ``wave_offset``), matching the checkpoint meta cursor.

    - ``transient_submit``: ``(wave_index, failures)`` pairs — the wave's
      first ``failures`` submit attempts raise
      :class:`TransientDispatchError` (retry/backoff proof);
    - ``wedge_wave`` / ``lose_ticket_wave``: the wave's ticket never
      reports ready (a wedged dispatch / a dropped completion ticket) —
      the phase deadline fires (:class:`DispatchWedgedError` proof);
    - ``kill_after_wave``: :class:`SimulatedProcessKill` after the wave is
      fully submitted and any due checkpoint is written (resume proof);
    - ``corrupt_checkpoint_at`` / ``truncate_checkpoint_at``: the
      checkpoint whose CURSOR (waves submitted when written — the cadence
      multiples) equals the value is bit-flipped / truncated after the
      atomic publish (CheckpointCorruptError fallback proof: resume must
      skip it loudly and fall back to the previous valid one).
    """

    transient_submit: Tuple[Tuple[int, int], ...] = ()
    wedge_wave: Optional[int] = None
    lose_ticket_wave: Optional[int] = None
    kill_after_wave: Optional[int] = None
    corrupt_checkpoint_at: Optional[int] = None
    truncate_checkpoint_at: Optional[int] = None

    def submit_failures(self, wave_index: int) -> int:
        for wave, failures in self.transient_submit:
            if wave == wave_index:
                return failures
        return 0


def _ticket_probe(ticket):
    """The non-blocking completion probe, or None on backends without one
    (there, deadline enforcement degrades to an unbounded wait — documented
    on :meth:`Supervisor._bounded_wait`)."""
    probe = getattr(ticket, "is_ready", None)
    return probe if callable(probe) else None


class Supervisor:
    """Deadline-bounded, retrying, checkpointing front-end over a
    ``VirtualCluster`` or ``TenantFleet`` (module docstring). Owns a
    :class:`StreamDriver` with the bounded waiter installed; callers submit
    waves and drain exactly as they would the bare driver.

    ``wave_offset`` makes wave indices absolute across resumes: a resumed
    supervisor continues the killed run's numbering, so checkpoint cadence,
    fault plans, and ledger events all speak one timeline.
    """

    def __init__(
        self,
        target,
        *,
        rounds_per_wave: int = 8,
        depth: int = 2,
        budgets: Optional[SupervisorBudgets] = None,
        backoff: Optional[BackoffPolicy] = None,
        poll_ms: float = 2.0,
        checkpoint_dir=None,
        checkpoint_every: int = 0,
        checkpoint_keep: int = 2,
        wave_offset: int = 0,
        fault_plan: Optional[SupervisorFaultPlan] = None,
        ledger=None,
        ledger_stage: Optional[str] = None,
        clock=None,
        sleep=None,
    ) -> None:
        if checkpoint_every and checkpoint_dir is None:
            raise ValueError(
                "checkpoint_every needs a checkpoint_dir to write into"
            )
        if checkpoint_keep < 1:
            raise ValueError(f"checkpoint_keep must be >= 1, got {checkpoint_keep}")
        self.target = target
        self.budgets = budgets or SupervisorBudgets()
        self.backoff = backoff or BackoffPolicy()
        self._delays_ms = self.backoff.delays_ms()
        self.poll_ms = float(poll_ms)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = int(checkpoint_every)
        self.checkpoint_keep = int(checkpoint_keep)
        self.wave_offset = int(wave_offset)
        self.fault_plan = fault_plan
        self._ledger = ledger
        self._ledger_stage = ledger_stage
        #: Injected decision clock (seconds, monotonic) and sleep — the
        #: supervision tier's ONLY time sources; tests drive fake ones.
        self._clock = clock if clock is not None else time.monotonic  # wall-clock-ok: default decision clock when none injected
        self._sleep = sleep if sleep is not None else time.sleep
        self.driver = StreamDriver(
            target, rounds_per_wave=rounds_per_wave, depth=depth,
            clock=self._clock, ticket_wait=self._bounded_wait,
            ticket_ready=self._fault_aware_ready,
        )
        self.checkpoints_written = 0
        self.last_checkpoint_wave: Optional[int] = None
        self.last_resume_ms: Optional[float] = None
        # Surface the recovery stats through the target's telemetry
        # snapshot (engine.recovery section, rapid_engine_recovery_*).
        target.recovery = self

    # -- the supervised pipeline ----------------------------------------

    @property
    def waves_submitted(self) -> int:
        """Absolute wave count (offset + this supervisor's submissions)."""
        return self.wave_offset + self.driver.waves_submitted

    def submit(self, wave) -> None:
        """Submit one wave with retry/backoff for transient failures and
        deadline-bounded backpressure; write the cadence checkpoint; then
        honor any fault-plan kill (SimulatedProcessKill lands exactly where
        a real preemption would — after the durable state is published)."""
        w = self.waves_submitted
        wave = self._filter_quarantined(wave)
        # Retry/backoff wraps ONLY the pre-application admission gate: the
        # wave's churn delta has not touched device state yet, so a retry
        # is a pure re-attempt. driver.submit itself runs exactly once —
        # retrying a half-applied wave would double-apply its delta (see
        # TransientDispatchError).
        for attempt in range(self.backoff.max_attempts):
            try:
                self._admission_gate(w, attempt)
                break
            except TransientDispatchError as exc:
                self.target.metrics.inc("engine_recovery_retries")
                self._emit(
                    LedgerEvent.RECOVERY_RETRY, phase="submit", wave=w,
                    attempt=attempt, error=str(exc),
                )
                if attempt + 1 >= self.backoff.max_attempts:
                    self.target.metrics.inc("engine_recovery_wedges")
                    self._emit(
                        LedgerEvent.RECOVERY_WEDGED, phase="submit", wave=w,
                        reason="retries-exhausted",
                    )
                    raise DispatchWedgedError(
                        "submit", w,
                        f"retries exhausted after {attempt + 1} attempts: {exc}",
                    ) from exc
                self._sleep(self._delays_ms[attempt] / 1000.0)
        self.driver.submit(wave)
        if (
            self.checkpoint_every
            and (w + 1) % self.checkpoint_every == 0
        ):
            self.checkpoint()
        if self.fault_plan is not None and self.fault_plan.kill_after_wave == w:
            raise SimulatedProcessKill(w)

    def drain(self) -> StreamResult:
        """Drain the pipeline (every ticket wait deadline-bounded under the
        ``drain`` budget) and return the stream report."""
        return self.driver.drain()

    # -- deadline-bounded waiting ---------------------------------------

    def _presumed_lost(self, wave_index: int) -> bool:
        """True when the fault plan declares this (absolute) wave's
        completion ticket wedged or lost."""
        plan = self.fault_plan
        absolute = self.wave_offset + wave_index
        return plan is not None and (
            plan.wedge_wave == absolute or plan.lose_ticket_wave == absolute
        )

    def _fault_aware_ready(self, wave_index: int, ticket) -> bool:
        """The reaper's readiness probe: a plan-wedged/lost ticket is
        never ready — it must survive opportunistic reaping at any
        pipeline depth and reach the bounded wait, where the deadline
        fires loudly (without this, depth>1 would reap the wave through
        the REAL probe and silently bypass the injected fault)."""
        if self._presumed_lost(wave_index):
            return False
        probe = _ticket_probe(ticket)
        return bool(probe()) if probe is not None else False

    def _bounded_wait(self, phase: str, wave_index: int, ticket) -> None:
        """The waiter installed into the stream driver: poll the ticket's
        ``is_ready`` probe between injected-clock sleeps; past the phase's
        declared budget, raise :class:`DispatchWedgedError` naming phase +
        wave. On a backend without the probe the wait degrades to the
        unbounded block (deadline enforcement needs a non-blocking probe;
        every jax.Array backend in this tree has one). Wave indices in the
        error are ABSOLUTE (driver-relative index + wave_offset)."""
        absolute = self.wave_offset + wave_index
        plan = self.fault_plan
        # The injected wedge/lost-ticket targets COMPLETION-ticket waits
        # (backpressure and the drain sweep — the waits that carry a real
        # per-wave ticket); epoch fetches reuse the wave counter as a label
        # and must not trip a fault aimed at a wave's ticket.
        presumed_lost = (
            phase in ("submit", "drain") and self._presumed_lost(wave_index)
        )
        probe = _ticket_probe(ticket)
        if probe is None and not presumed_lost:
            jax.block_until_ready(ticket)  # host-sync-ok: no readiness probe on this backend — unbounded fetch boundary
            return
        budget_ms = self.budgets.for_phase(phase)
        t0 = self._clock()
        while True:
            if not presumed_lost and probe():
                jax.block_until_ready(ticket)  # host-sync-ok: ready-observed ticket settle, a non-blocking fetch boundary
                return
            waited_ms = (self._clock() - t0) * 1000.0
            if waited_ms >= budget_ms:
                reason = (
                    "completion ticket lost"
                    if plan is not None and plan.lose_ticket_wave == absolute
                    else f"no completion after {waited_ms:.0f} ms "
                         f"(budget {budget_ms:.0f} ms)"
                )
                self.target.metrics.inc("engine_recovery_wedges")
                self._emit(
                    LedgerEvent.RECOVERY_WEDGED, phase=phase, wave=absolute,
                    waited_ms=round(waited_ms, 3), budget_ms=budget_ms,
                )
                raise DispatchWedgedError(phase, absolute, reason)
            self._sleep(
                min(self.poll_ms, max(0.0, budget_ms - waited_ms)) / 1000.0
            )

    # -- checkpoints -----------------------------------------------------

    def checkpoint(self):
        """Write one crash-consistent checkpoint at the current wave
        boundary (a deliberate sync point: materializing the state waits
        for every enqueued dispatch — bounded under the ``checkpoint``
        budget first, so a wedged pipeline cannot masquerade as a slow
        write). Prunes to ``checkpoint_keep`` newest files; returns the
        published path."""
        from rapid_tpu.serving import recovery

        if self.checkpoint_dir is None:
            raise ValueError(
                "checkpoint() needs a checkpoint_dir (pass one at "
                "construction, with or without a cadence)"
            )
        wave_index = self.waves_submitted
        self._bounded_wait(
            "checkpoint", wave_index - self.wave_offset,
            self.target.state.config_epoch,
        )
        path = recovery.write_checkpoint(
            self.checkpoint_dir, self.target, wave_index,
            rounds_per_wave=self.driver.rounds_per_wave,
            depth=self.driver.depth, keep=self.checkpoint_keep,
        )
        self.checkpoints_written += 1
        self.last_checkpoint_wave = wave_index
        self.target.metrics.inc("engine_recovery_checkpoints")
        self._emit(
            LedgerEvent.RECOVERY_CHECKPOINT, wave=wave_index, path=str(path),
        )
        plan = self.fault_plan
        if plan is not None and plan.corrupt_checkpoint_at == wave_index:
            _damage_file(path, truncate=False)
        if plan is not None and plan.truncate_checkpoint_at == wave_index:
            _damage_file(path, truncate=True)
        return path

    # -- quarantine (fleet targets) --------------------------------------

    def scan_and_quarantine(self, repro_dir=None):
        """Run the device-side health reduction over a fleet target and
        quarantine every newly-poisoned tenant inside the running compiled
        program (TenantFleet.quarantine — the existing per-tenant freeze
        lanes; data, not a recompile). The full bit-freeze applies on the
        WAVE path (run_until_membership); the batched step path keeps
        executing the quarantined tenant's rounds (vmap lockstep — see
        quarantine()'s docstring), so the supervisor additionally stops
        feeding it churn and the stream's cut accounting masks its epochs
        out — its garbage never reaches the published rates, and the
        other B-1 tenants are untouched either way (vmap independence).
        With ``repro_dir`` set, each quarantined tenant is exported as a
        replayable repro directory capturing its state AT DETECTION
        (rapid_tpu/serving/recovery.py; ``chaosrun replay`` recognizes
        it). Returns the newly-quarantined tenant indices; single-cluster
        targets have no tenant axis and scan as an empty list."""
        scan = getattr(self.target, "health_scan", None)
        if scan is None:
            return []
        poisoned = scan()
        already = set(self.target.quarantined)
        fresh = [
            int(t) for t in np.nonzero(poisoned)[0].tolist()
            if int(t) not in already
        ]
        if not fresh:
            return []
        self.target.quarantine(fresh)
        for t in fresh:
            violations = self.target.tenant_health_report(t)
            self.target.metrics.inc("engine_recovery_quarantines")
            self._emit(
                LedgerEvent.RECOVERY_QUARANTINE, tenant=t,
                violations=violations,
            )
            if repro_dir is not None:
                from rapid_tpu.serving import recovery

                recovery.write_quarantine_repro(
                    repro_dir, self.target, t, violations
                )
        return fresh

    # -- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        """The ``engine.recovery`` telemetry section (gauges render as
        ``rapid_engine_recovery_*``; None values render NaN so the series
        set is stable from attach)."""
        counters = self.target.metrics.counters
        return {
            "waves_submitted": self.waves_submitted,
            "checkpoint_every": self.checkpoint_every,
            "checkpoints_written": self.checkpoints_written,
            "last_checkpoint_wave": self.last_checkpoint_wave,
            "retries": int(counters.get("engine_recovery_retries", 0)),
            "wedges": int(counters.get("engine_recovery_wedges", 0)),
            "resumes": int(counters.get("engine_recovery_resumes", 0)),
            "quarantined": len(getattr(self.target, "quarantined", ())),
            "mttr_ms": (
                round(self.last_resume_ms, 3)
                if self.last_resume_ms is not None else None
            ),
        }

    # -- internals --------------------------------------------------------

    def _admission_gate(self, wave_index: int, attempt: int) -> None:
        """The retryable pre-dispatch seam: raises TransientDispatchError
        while the wave may not proceed. Today the fault plan's injection
        point; a real deployment's transient admission checks (backend
        readiness, quota) belong here — BEFORE any state mutates."""
        if (
            self.fault_plan is not None
            and attempt < self.fault_plan.submit_failures(wave_index)
        ):
            raise TransientDispatchError(
                f"injected transient failure (wave {wave_index}, "
                f"attempt {attempt})"
            )

    def _filter_quarantined(self, wave):
        """Stop feeding churn to quarantined tenants: their freeze is the
        wave-path done lane, and new fault deltas for a frozen tenant would
        sit unresolved forever (and muddy the repro). Other tenants' pairs
        pass through untouched."""
        quarantined = set(getattr(self.target, "quarantined", ()))
        if not quarantined or not isinstance(wave, FleetWave):
            return wave
        kept = tuple(p for p in wave.crash if p[0] not in quarantined)
        if len(kept) != len(wave.crash):
            self.target.metrics.inc(
                "engine_recovery_quarantine_dropped_events",
                len(wave.crash) - len(kept),
            )
        return FleetWave(crash=kept)

    def _emit(self, event: LedgerEvent, **fields) -> None:
        if self._ledger is not None:
            self._ledger.emit(event, stage=self._ledger_stage, **fields)


def _damage_file(path, truncate: bool) -> None:
    """The fault plan's checkpoint damage: truncate to half, or flip one
    payload byte (both must surface as CheckpointCorruptError on load)."""
    data = bytearray(path.read_bytes())
    if truncate:
        path.write_bytes(bytes(data[: len(data) // 2]))
    else:
        data[len(data) // 2] ^= 0xFF
        path.write_bytes(bytes(data))


__all__ = [
    "BackoffPolicy",
    "DispatchWedgedError",
    "SimulatedProcessKill",
    "Supervisor",
    "SupervisorBudgets",
    "SupervisorFaultPlan",
    "TransientDispatchError",
]
