"""Crash-consistent checkpoint/resume and quarantine export for the
supervised serving pipeline.

The reference JVM survives a process death by REJOINING: the restarted node
pulls the configuration from its peers and re-syncs (Cluster.java's join
path). The engine twin can do strictly better — the whole serving target is
one pytree and the churn source is a pure function of its seed, so resume
is deterministic REPLAY: load the newest valid checkpoint (corrupt files
skipped loudly, never trusted), rebuild the driver, fast-forward the seeded
churn schedule to the checkpointed wave cursor, and replay the remaining
waves. Final state, cuts, and config-id chains come out bit-identical to a
run that was never killed — pinned by tests/test_supervisor.py for both the
``VirtualCluster`` and ``TenantFleet`` serving shapes (PARITY.md's
exceed-the-reference row for this tier).

Checkpoint files are ``ckpt_w<cursor>.npz`` under one directory, written by
:func:`write_checkpoint` (xxh64-sealed, atomic tmp+rename —
utils/checkpoint.py) and pruned to the newest few; the meta block carries
the wave cursor and pipeline shape so :func:`resume` can rebuild the
supervisor without out-of-band state.

Quarantine export: :func:`write_quarantine_repro` collapses a poisoned
tenant to a single-tenant repro directory — the captured state slice plus
the health-report violations — that :func:`replay_quarantine_repro` (and
``chaosrun replay``, which recognizes the ``fleet.json`` marker) re-runs
deterministically: the scan must reproduce the recorded violations.
"""

from __future__ import annotations

import json
import logging
import re
import time
from pathlib import Path
from typing import List, Optional, Tuple

import jax

from rapid_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    load_serving_state,
    save_serving_state,
)
from rapid_tpu.utils.ledger import LedgerEvent

LOG = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"ckpt_w(\d+)\.npz$")


def _checkpoint_path(directory, wave_index: int) -> Path:
    return Path(directory) / f"ckpt_w{wave_index:08d}.npz"


def write_checkpoint(
    directory,
    target,
    wave_index: int,
    *,
    rounds_per_wave: int,
    depth: int,
    keep: int = 2,
) -> Path:
    """Publish one serving checkpoint at the given ABSOLUTE wave cursor and
    prune older files down to ``keep`` (the newest survivors are the
    corruption-fallback chain — a damaged newest checkpoint must leave a
    valid predecessor to resume from)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    knobs = getattr(target, "knobs", None)
    meta = {
        "wave_index": int(wave_index),
        "rounds_per_wave": int(rounds_per_wave),
        "depth": int(depth),
        "kind": "fleet" if knobs is not None else "cluster",
    }
    path = _checkpoint_path(directory, wave_index)
    save_serving_state(
        path, target.cfg, target.state, target.faults, knobs=knobs, meta=meta
    )
    for stale in sorted(
        (p for p in directory.iterdir() if _CKPT_RE.search(p.name)),
        key=lambda p: int(_CKPT_RE.search(p.name).group(1)),
    )[:-keep]:
        stale.unlink()
    return path


def latest_valid_checkpoint(directory) -> Tuple[Optional[Path], Optional[tuple], List[Path]]:
    """``(path, loaded, corrupt)``: the newest checkpoint that passes its
    integrity checks — with its ALREADY-LOADED ``load_serving_state``
    tuple, so :func:`resume` never pays the deserialize+device-settle cost
    twice (at the TPU drill shape the state load dominates the published
    MTTR) — plus the corrupt files skipped on the way down (newest first).
    Corruption is a LOGGED fallback, never a crash — a torn tail must not
    strand the valid predecessor beneath it."""
    directory = Path(directory)
    if not directory.is_dir():
        return None, None, []
    candidates = sorted(
        (p for p in directory.iterdir() if _CKPT_RE.search(p.name)),
        key=lambda p: int(_CKPT_RE.search(p.name).group(1)),
        reverse=True,
    )
    corrupt: List[Path] = []
    for path in candidates:
        try:
            loaded = load_serving_state(path)
        except CheckpointCorruptError as exc:
            LOG.error("checkpoint %s is corrupt, falling back: %s", path, exc)
            corrupt.append(path)
            continue
        return path, loaded, corrupt
    return None, None, corrupt


def resume(
    checkpoint_dir,
    *,
    budgets=None,
    backoff=None,
    poll_ms: float = 2.0,
    checkpoint_every: Optional[int] = None,
    checkpoint_keep: int = 2,
    fault_plan=None,
    ledger=None,
    ledger_stage: Optional[str] = None,
    clock=None,
    sleep=None,
):
    """Resume a killed supervised run from its checkpoint directory:
    rebuild the serving target (cluster or fleet — the checkpoint knows),
    re-attach a :class:`~rapid_tpu.serving.supervisor.Supervisor` with the
    checkpointed pipeline shape and the ABSOLUTE wave offset, and return
    ``(supervisor, wave_index)`` — the caller fast-forwards its seeded
    churn source by ``wave_index`` waves (:func:`fast_forward`) and
    replays the rest; the result is bit-identical to the uninterrupted run.

    The resume duration (checkpoint load through supervisor attach,
    measured on the injected clock) lands on ``supervisor.last_resume_ms``
    — the MTTR the bench ``recovery`` stage publishes — and in the
    ``RECOVERY_RESUME`` ledger event. Corrupt newest checkpoints are
    skipped with ``RECOVERY_CHECKPOINT_CORRUPT`` events; no valid
    checkpoint at all raises FileNotFoundError (resume cannot invent a
    state — restart from scratch instead)."""
    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.serving.supervisor import Supervisor

    read_clock = clock if clock is not None else time.monotonic  # wall-clock-ok: default MTTR clock when none injected
    t0 = read_clock()
    path, loaded, corrupt = latest_valid_checkpoint(checkpoint_dir)
    if ledger is not None:
        for bad in corrupt:
            ledger.emit(
                LedgerEvent.RECOVERY_CHECKPOINT_CORRUPT,
                stage=ledger_stage, path=str(bad),
            )
    if path is None:
        raise FileNotFoundError(
            f"no valid checkpoint under {checkpoint_dir!s} "
            f"({len(corrupt)} corrupt file(s) skipped) — nothing to resume "
            f"from; restart the stream from scratch"
        )
    cfg, state, faults, knobs, meta = loaded
    if knobs is not None:
        from rapid_tpu.tenancy.fleet import TenantFleet

        target = TenantFleet(cfg, state, faults, knobs)
    else:
        target = VirtualCluster(cfg, state)
        target.faults = faults
    wave_index = int(meta["wave_index"])
    supervisor = Supervisor(
        target,
        rounds_per_wave=int(meta["rounds_per_wave"]),
        depth=int(meta["depth"]),
        budgets=budgets,
        backoff=backoff,
        poll_ms=poll_ms,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=(
            int(checkpoint_every) if checkpoint_every is not None else 0
        ),
        checkpoint_keep=checkpoint_keep,
        wave_offset=wave_index,
        fault_plan=fault_plan,
        ledger=ledger,
        ledger_stage=ledger_stage,
        clock=clock,
        sleep=sleep,
    )
    supervisor.last_resume_ms = (read_clock() - t0) * 1000.0
    target.metrics.inc("engine_recovery_resumes")
    if ledger is not None:
        ledger.emit(
            LedgerEvent.RECOVERY_RESUME, stage=ledger_stage,
            wave=wave_index, checkpoint=str(path),
            mttr_ms=round(supervisor.last_resume_ms, 3),
            corrupt_skipped=len(corrupt),
        )
    return supervisor, wave_index


def fast_forward(churn, waves: int):
    """Advance a seeded churn generator past the checkpointed waves: the
    schedule is a pure function of its seed, so discarding ``waves`` draws
    reproduces exactly the per-wave deltas the killed run already applied
    (what makes resume REPLAY rather than approximation). Returns the
    generator for chaining."""
    for _ in range(int(waves)):
        churn.wave()
    return churn


# ---------------------------------------------------------------------------
# Quarantine repro export / replay
# ---------------------------------------------------------------------------


def write_quarantine_repro(directory, fleet, tenant: int, violations) -> Path:
    """Export one quarantined tenant as a replayable single-tenant repro
    dir: the captured state+faults slice (a [1]-stacked fleet checkpoint —
    the poison travels WITH the repro, unlike a schedule-only repro that
    could not reproduce externally-corrupted state), the knob lanes, and
    the health-report violations. ``fleet.json`` carries the
    ``kind: "quarantine"`` marker ``chaosrun replay`` routes on."""
    directory = Path(directory) / f"tenant{tenant}"
    directory.mkdir(parents=True, exist_ok=True)

    def slice_tree(tree):
        return jax.tree_util.tree_map(lambda x: x[tenant : tenant + 1], tree)

    save_serving_state(
        directory / "state.npz",
        fleet.cfg,
        slice_tree(fleet.state),
        slice_tree(fleet.faults),
        knobs=slice_tree(fleet.knobs),
        meta={"kind": "quarantine", "tenant_index": int(tenant)},
    )
    (directory / "fleet.json").write_text(json.dumps({
        "version": 1,
        "kind": "quarantine",
        "tenant_index": int(tenant),
        "fleet_size": int(fleet.b),
        "violations": list(violations),
    }, indent=1) + "\n")
    # violations.txt carries what a REPLAY will see (the write_fleet_repro
    # convention): the slice is a single-tenant fleet, so the re-verified
    # report names tenant 0 — fleet.json keeps the original index and
    # wording for provenance.
    verified = replay_quarantine_repro(directory)
    (directory / "violations.txt").write_text(
        "".join(f"{v}\n" for v in verified) or "(none)\n"
    )
    return directory


def replay_quarantine_repro(directory) -> List[str]:
    """Re-run a quarantine repro: load the captured single-tenant fleet
    slice and re-run the deterministic health scan + report — the recorded
    violations must reproduce (a repro that stops failing is itself news,
    which is why ``chaosrun replay`` diffs against violations.txt)."""
    from rapid_tpu.tenancy.fleet import TenantFleet

    directory = Path(directory)
    cfg, state, faults, knobs, _meta = load_serving_state(
        directory / "state.npz"
    )
    if knobs is None:
        raise CheckpointCorruptError(
            f"{directory}: quarantine repro lacks the knob lanes (not a "
            f"fleet slice)"
        )
    fleet = TenantFleet(cfg, state, faults, knobs)
    poisoned = fleet.health_scan()
    if not bool(poisoned[0]):
        return []
    return fleet.tenant_health_report(0)


__all__ = [
    "fast_forward",
    "latest_valid_checkpoint",
    "replay_quarantine_repro",
    "resume",
    "write_checkpoint",
    "write_quarantine_repro",
]
