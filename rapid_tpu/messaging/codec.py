"""Binary wire codec for the protocol messages.

The framework's wire schema (the equivalent of ``rapid.proto``): one request
envelope carrying exactly one tagged protocol message, one response envelope.
Explicit fixed-layout encoding — no pickling (untrusted peers), no schema
compiler dependency. Layout: little-endian, u8 type tags, u32 lengths/counts,
u64 identifiers.
"""

from __future__ import annotations

import functools
import struct
from typing import Dict, List, Tuple, Type

from rapid_tpu.utils.xxhash import to_signed64 as _signed64
from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    CohortCutMessage,
    ConsensusResponse,
    DelegateDecisionMessage,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    GlobalTierMessage,
    GossipMessage,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    NodeId,
    NodeStatus,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    Rank,
    RapidRequest,
    RapidResponse,
    Response,
)


class CodecError(ValueError):
    pass


# Public field-codec surface for other modules that persist in this layout
# (rapid_tpu.utils.checkpoint); the underscore classes remain as aliases.


class _Writer:
    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def u8(self, v: int) -> None:
        self._parts.append(struct.pack("<B", v))

    def u32(self, v: int) -> None:
        self._parts.append(struct.pack("<I", v))

    def i64(self, v: int) -> None:
        self._parts.append(struct.pack("<q", _signed64(v)))

    def u64(self, v: int) -> None:
        self._parts.append(struct.pack("<Q", v & ((1 << 64) - 1)))

    def blob(self, b: bytes) -> None:
        self.u32(len(b))
        self._parts.append(b)

    def string(self, s: str) -> None:
        self.blob(s.encode("utf-8"))

    def raw(self, b: bytes) -> None:
        """Append bytes verbatim (headers/magic for codec-layout consumers)."""
        self._parts.append(b)

    def getvalue(self) -> bytes:
        return b"".join(self._parts)


class _Reader:
    def __init__(self, data: bytes) -> None:
        self._data = data
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise CodecError("truncated message")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def blob(self) -> bytes:
        return self._take(self.u32())

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def done(self) -> bool:
        return self._pos == len(self._data)


# -- field codecs ----------------------------------------------------------


def _w_endpoint(w: _Writer, ep: Endpoint) -> None:
    w.string(ep.hostname)
    w.u32(ep.port)


def _r_endpoint(r: _Reader) -> Endpoint:
    return Endpoint(r.string(), r.u32())


def _w_endpoints(w: _Writer, eps) -> None:
    w.u32(len(eps))
    for ep in eps:
        _w_endpoint(w, ep)


def _r_endpoints(r: _Reader) -> Tuple[Endpoint, ...]:
    return tuple(_r_endpoint(r) for _ in range(r.u32()))


def _w_node_id(w: _Writer, nid: NodeId) -> None:
    w.u64(nid.high)
    w.u64(nid.low)


def _r_node_id(r: _Reader) -> NodeId:
    return NodeId(r.u64(), r.u64())


def _w_opt_node_id(w: _Writer, nid) -> None:
    w.u8(1 if nid is not None else 0)
    if nid is not None:
        _w_node_id(w, nid)


def _r_opt_node_id(r: _Reader):
    return _r_node_id(r) if r.u8() else None


def _w_metadata(w: _Writer, md) -> None:
    w.u32(len(md))
    for key, value in md:
        w.string(key)
        w.blob(value)


def _r_metadata(r: _Reader) -> Tuple[Tuple[str, bytes], ...]:
    return tuple((r.string(), r.blob()) for _ in range(r.u32()))


def _w_rank(w: _Writer, rank: Rank) -> None:
    w.u32(rank.round)
    w.u32(rank.node_index)


def _r_rank(r: _Reader) -> Rank:
    return Rank(r.u32(), r.u32())


def _w_rings(w: _Writer, rings) -> None:
    w.u32(len(rings))
    for ring in rings:
        w.u32(ring)


def _r_rings(r: _Reader) -> Tuple[int, ...]:
    return tuple(r.u32() for _ in range(r.u32()))


# Optional trailing trace-context field (alert batches + consensus messages):
# written ONLY when present, so a frame without a trace id is byte-identical
# to the pre-trace layout — old recordings and golden fixtures stay valid,
# and a peer that never stamps traces interoperates unchanged. On decode the
# message body consumes an exact prefix, so any remainder IS the extension.


def _w_opt_trace(w: _Writer, trace_id) -> None:
    if trace_id is not None:
        w.u64(trace_id)


def _r_opt_trace(r: _Reader):
    return None if r.done() else r.u64()


def _w_alert(w: _Writer, a: AlertMessage) -> None:
    _w_endpoint(w, a.edge_src)
    _w_endpoint(w, a.edge_dst)
    w.u8(int(a.edge_status))
    w.i64(a.configuration_id)
    _w_rings(w, a.ring_numbers)
    _w_opt_node_id(w, a.node_id)
    _w_metadata(w, a.metadata)


def _r_alert(r: _Reader) -> AlertMessage:
    return AlertMessage(
        edge_src=_r_endpoint(r),
        edge_dst=_r_endpoint(r),
        edge_status=EdgeStatus(r.u8()),
        configuration_id=r.i64(),
        ring_numbers=_r_rings(r),
        node_id=_r_opt_node_id(r),
        metadata=_r_metadata(r),
    )


# -- message codecs --------------------------------------------------------

_REQUEST_TAGS: Dict[Type, int] = {
    PreJoinMessage: 1,
    JoinMessage: 2,
    BatchedAlertMessage: 3,
    ProbeMessage: 4,
    FastRoundPhase2bMessage: 5,
    Phase1aMessage: 6,
    Phase1bMessage: 7,
    Phase2aMessage: 8,
    Phase2bMessage: 9,
    LeaveMessage: 10,
    GossipMessage: 11,
    CohortCutMessage: 12,
    DelegateDecisionMessage: 13,
    GlobalTierMessage: 14,
}

_RESPONSE_TAGS: Dict[Type, int] = {
    JoinResponse: 1,
    Response: 2,
    ConsensusResponse: 3,
    ProbeResponse: 4,
}


def encode_request(request: RapidRequest) -> bytes:
    """Encode a request envelope. Memoized when the request is hashable:
    broadcast fan-out sends the SAME (frozen) request to every member, and a
    cache hit costs ~1/5 of re-packing — the bytes are immutable, so sharing
    them is safe. A request built with unhashable sequence fields (e.g.
    lists) still encodes, just uncached."""
    try:
        # trace_id is compare=False (types.py): two protocol-equal requests
        # with different trace stamps hash alike, so the stamp must join the
        # cache key explicitly or one message's bytes would carry the other's
        # trace id.
        return _encode_request_cached(request, getattr(request, "trace_id", None))
    except TypeError:  # unhashable field values — encode without the cache
        return _encode_request_impl(request)


# Deliberately tiny cache: the reuse window is the handful of broadcasts
# whose fan-out futures are interleaved on the loop at once, and a small LRU
# avoids pinning dead request batches for the process lifetime.
@functools.lru_cache(maxsize=8)
def _encode_request_cached(request: RapidRequest, _trace_id) -> bytes:
    return _encode_request_impl(request)


def _encode_request_impl(request: RapidRequest) -> bytes:
    w = _Writer()
    tag = _REQUEST_TAGS.get(type(request))
    if tag is None:
        raise CodecError(f"unknown request type {type(request)!r}")
    w.u8(tag)
    if isinstance(request, PreJoinMessage):
        _w_endpoint(w, request.sender)
        _w_node_id(w, request.node_id)
    elif isinstance(request, JoinMessage):
        _w_endpoint(w, request.sender)
        _w_node_id(w, request.node_id)
        _w_rings(w, request.ring_numbers)
        w.i64(request.configuration_id)
        _w_metadata(w, request.metadata)
    elif isinstance(request, BatchedAlertMessage):
        _w_endpoint(w, request.sender)
        w.u32(len(request.messages))
        for alert in request.messages:
            _w_alert(w, alert)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, ProbeMessage):
        _w_endpoint(w, request.sender)
    elif isinstance(request, FastRoundPhase2bMessage):
        _w_endpoint(w, request.sender)
        w.i64(request.configuration_id)
        _w_endpoints(w, request.endpoints)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, Phase1aMessage):
        _w_endpoint(w, request.sender)
        w.i64(request.configuration_id)
        _w_rank(w, request.rank)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, Phase1bMessage):
        _w_endpoint(w, request.sender)
        w.i64(request.configuration_id)
        _w_rank(w, request.rnd)
        _w_rank(w, request.vrnd)
        _w_endpoints(w, request.vval)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, Phase2aMessage):
        _w_endpoint(w, request.sender)
        w.i64(request.configuration_id)
        _w_rank(w, request.rnd)
        _w_endpoints(w, request.vval)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, Phase2bMessage):
        _w_endpoint(w, request.sender)
        w.i64(request.configuration_id)
        _w_rank(w, request.rnd)
        _w_endpoints(w, request.endpoints)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, LeaveMessage):
        _w_endpoint(w, request.sender)
    elif isinstance(request, CohortCutMessage):
        _w_endpoint(w, request.sender)
        w.i64(request.configuration_id)
        w.u32(request.cohort)
        _w_endpoints(w, request.endpoints)
        _w_endpoints(w, request.joiner_eps)
        w.u32(len(request.joiner_ids))
        for nid in request.joiner_ids:
            _w_node_id(w, nid)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, DelegateDecisionMessage):
        _w_endpoint(w, request.sender)
        w.i64(request.configuration_id)
        _w_endpoints(w, request.endpoints)
        _w_endpoints(w, request.joiner_eps)
        w.u32(len(request.joiner_ids))
        for nid in request.joiner_ids:
            _w_node_id(w, nid)
        _w_opt_trace(w, request.trace_id)
    elif isinstance(request, GlobalTierMessage):
        if isinstance(request.payload, (GlobalTierMessage, GossipMessage)):
            raise CodecError("nested envelope in GlobalTierMessage payload")
        _w_endpoint(w, request.sender)
        # Nested envelope: the payload is a complete request of its own
        # (the GossipMessage framing precedent).
        w.blob(_encode_request_impl(request.payload))
    elif isinstance(request, GossipMessage):
        if isinstance(request.payload, GossipMessage):
            raise CodecError("nested GossipMessage payload")
        if not 0 <= request.ttl <= 255:
            raise CodecError(f"gossip ttl out of u8 range: {request.ttl}")
        _w_endpoint(w, request.origin)
        w.u64(request.msg_id)
        w.u8(request.ttl)
        # Nested envelope: the payload is a complete request of its own.
        w.blob(_encode_request_impl(request.payload))
    return w.getvalue()


def decode_request(data: bytes) -> RapidRequest:
    r = _Reader(data)
    tag = r.u8()
    if tag == 1:
        out: RapidRequest = PreJoinMessage(_r_endpoint(r), _r_node_id(r))
    elif tag == 2:
        out = JoinMessage(
            sender=_r_endpoint(r),
            node_id=_r_node_id(r),
            ring_numbers=_r_rings(r),
            configuration_id=r.i64(),
            metadata=_r_metadata(r),
        )
    elif tag == 3:
        sender = _r_endpoint(r)
        messages = tuple(_r_alert(r) for _ in range(r.u32()))
        out = BatchedAlertMessage(sender, messages, trace_id=_r_opt_trace(r))
    elif tag == 4:
        out = ProbeMessage(_r_endpoint(r))
    elif tag == 5:
        out = FastRoundPhase2bMessage(
            _r_endpoint(r), r.i64(), _r_endpoints(r), trace_id=_r_opt_trace(r)
        )
    elif tag == 6:
        out = Phase1aMessage(_r_endpoint(r), r.i64(), _r_rank(r), trace_id=_r_opt_trace(r))
    elif tag == 7:
        out = Phase1bMessage(
            _r_endpoint(r), r.i64(), _r_rank(r), _r_rank(r), _r_endpoints(r),
            trace_id=_r_opt_trace(r),
        )
    elif tag == 8:
        out = Phase2aMessage(
            _r_endpoint(r), r.i64(), _r_rank(r), _r_endpoints(r), trace_id=_r_opt_trace(r)
        )
    elif tag == 9:
        out = Phase2bMessage(
            _r_endpoint(r), r.i64(), _r_rank(r), _r_endpoints(r), trace_id=_r_opt_trace(r)
        )
    elif tag == 10:
        out = LeaveMessage(_r_endpoint(r))
    elif tag == 11:
        origin = _r_endpoint(r)
        msg_id = r.u64()
        ttl = r.u8()
        payload = decode_request(r.blob())
        if isinstance(payload, GossipMessage):
            # One level of nesting only: a gossiped gossip envelope is
            # meaningless and unbounded recursion is a parser DoS.
            raise CodecError("nested GossipMessage payload")
        out = GossipMessage(origin, msg_id, ttl, payload)
    elif tag == 12:
        out = CohortCutMessage(
            sender=_r_endpoint(r),
            configuration_id=r.i64(),
            cohort=r.u32(),
            endpoints=_r_endpoints(r),
            joiner_eps=_r_endpoints(r),
            joiner_ids=tuple(_r_node_id(r) for _ in range(r.u32())),
            trace_id=_r_opt_trace(r),
        )
    elif tag == 13:
        out = DelegateDecisionMessage(
            sender=_r_endpoint(r),
            configuration_id=r.i64(),
            endpoints=_r_endpoints(r),
            joiner_eps=_r_endpoints(r),
            joiner_ids=tuple(_r_node_id(r) for _ in range(r.u32())),
            trace_id=_r_opt_trace(r),
        )
    elif tag == 14:
        sender = _r_endpoint(r)
        payload = decode_request(r.blob())
        if isinstance(payload, (GlobalTierMessage, GossipMessage)):
            # One level of nesting only, as for gossip: an envelope inside
            # the envelope is meaningless and unbounded recursion is a
            # parser DoS.
            raise CodecError("nested envelope in GlobalTierMessage payload")
        out = GlobalTierMessage(sender, payload)
    else:
        raise CodecError(f"unknown request tag {tag}")
    if not r.done():
        raise CodecError("trailing bytes in request")
    return out


def encode_response(response: RapidResponse) -> bytes:
    w = _Writer()
    tag = _RESPONSE_TAGS.get(type(response))
    if tag is None:
        raise CodecError(f"unknown response type {type(response)!r}")
    w.u8(tag)
    if isinstance(response, JoinResponse):
        _w_endpoint(w, response.sender)
        w.u8(int(response.status_code))
        w.i64(response.configuration_id)
        _w_endpoints(w, response.endpoints)
        w.u32(len(response.identifiers))
        for nid in response.identifiers:
            _w_node_id(w, nid)
        _w_endpoints(w, response.metadata_keys)
        w.u32(len(response.metadata_values))
        for md in response.metadata_values:
            _w_metadata(w, md)
    elif isinstance(response, ProbeResponse):
        w.u8(int(response.status))
    return w.getvalue()


def decode_response(data: bytes) -> RapidResponse:
    r = _Reader(data)
    tag = r.u8()
    if tag == 1:
        out: RapidResponse = JoinResponse(
            sender=_r_endpoint(r),
            status_code=JoinStatusCode(r.u8()),
            configuration_id=r.i64(),
            endpoints=_r_endpoints(r),
            identifiers=tuple(_r_node_id(r) for _ in range(r.u32())),
            metadata_keys=_r_endpoints(r),
            metadata_values=tuple(_r_metadata(r) for _ in range(r.u32())),
        )
    elif tag == 2:
        out = Response()
    elif tag == 3:
        out = ConsensusResponse()
    elif tag == 4:
        out = ProbeResponse(NodeStatus(r.u8()))
    else:
        raise CodecError(f"unknown response tag {tag}")
    if not r.done():
        raise CodecError("trailing bytes in response")
    return out


Writer = _Writer
Reader = _Reader
write_endpoint = _w_endpoint
read_endpoint = _r_endpoint
write_node_id = _w_node_id
read_node_id = _r_node_id
