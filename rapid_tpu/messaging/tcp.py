"""TCP transport: the real-network implementation of the messaging SPI.

Plays the role of the reference's socket transports (default gRPC,
``GrpcClient.java``/``GrpcServer.java``, and the raw-TCP alternate,
``NettyClientServer.java``): length-framed request/response over persistent
connections, correlation by a per-message counter, per-message-type deadlines
and bounded retries, BOOTSTRAPPING probe answers before the service exists.

Frame layout (little-endian): u32 payload length | u64 correlation id |
u8 kind (0=request, 1=response) | codec payload.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import Dict, Optional, Tuple

from rapid_tpu.errors import ShuttingDownError
from rapid_tpu.messaging.base import MessagingClient, MessagingServer
from rapid_tpu.messaging.codec import (
    decode_request,
    decode_response,
    encode_request,
    encode_response,
)
from rapid_tpu.messaging.retries import call_with_retries
from rapid_tpu.messaging.stats import TransportStats
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    JoinMessage,
    NodeStatus,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    RapidRequest,
    RapidResponse,
)

LOG = logging.getLogger(__name__)

_HEADER = struct.Struct("<IQB")
_MAX_FRAME = 64 * 1024 * 1024


async def _read_frame(reader: asyncio.StreamReader) -> Tuple[int, int, bytes]:
    header = await reader.readexactly(_HEADER.size)
    length, correlation_id, kind = _HEADER.unpack(header)
    if length > _MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length}")
    payload = await reader.readexactly(length)
    return correlation_id, kind, payload


def _write_frame(
    writer: asyncio.StreamWriter, correlation_id: int, kind: int, payload: bytes
) -> None:
    writer.write(_HEADER.pack(len(payload), correlation_id, kind) + payload)


class TcpServer(MessagingServer):
    def __init__(self, listen_address: Endpoint) -> None:
        self.listen_address = listen_address
        self._service = None
        self._server: Optional[asyncio.AbstractServer] = None
        # Event-loop-confined (tools/analysis/concurrency.py): mutated only
        # in cooperative straight-line sections, no lock needed — but no
        # read->await->write may straddle an await.
        self._connections: set = set()  # guarded-by: event-loop
        self.stats = TransportStats()  # paper Table 2 accounting
        # Strong references to in-flight handlers: the event loop only holds
        # tasks weakly, so without this a handler can be garbage-collected
        # mid-flight and the request silently dropped.
        self._handler_tasks: set = set()  # guarded-by: event-loop

    def set_membership_service(self, service) -> None:
        self._service = service

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connection, host=self.listen_address.hostname, port=self.listen_address.port
        )
        if self.listen_address.port == 0:
            # Ephemeral bind: adopt the kernel-assigned port so callers can
            # advertise a real, reachable address.
            port = self._server.sockets[0].getsockname()[1]
            self.listen_address = Endpoint(self.listen_address.hostname, port)

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            # Close live connections first: wait_closed() blocks until every
            # per-connection reader loop returns.
            for writer in list(self._connections):
                writer.close()
            await self._server.wait_closed()
            # Reader loops are done now, so no NEW handler tasks can appear
            # (cancelling before wait_closed would race buffered frames
            # spawning fresh handlers). Reap the stragglers: they must not
            # outlive shutdown (they would write to closed writers and leak
            # "Task was destroyed but it is pending" at loop close).
            if self._handler_tasks:
                tasks = list(self._handler_tasks)
                for task in tasks:
                    task.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
            self._server = None

    async def _on_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._connections.add(writer)
        try:
            while True:
                correlation_id, kind, payload = await _read_frame(reader)
                self.stats.rx(_HEADER.size + len(payload))
                if kind != 0:
                    raise ConnectionError("client sent non-request frame")
                task = asyncio.ensure_future(
                    self._handle_one(correlation_id, payload, writer)
                )
                self._handler_tasks.add(task)
                task.add_done_callback(self._handler_tasks.discard)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._connections.discard(writer)
            writer.close()

    async def _handle_one(
        self, correlation_id: int, payload: bytes, writer: asyncio.StreamWriter
    ) -> None:
        try:
            request = decode_request(payload)
            if self._service is None:
                if isinstance(request, ProbeMessage):
                    response: RapidResponse = ProbeResponse(status=NodeStatus.BOOTSTRAPPING)
                else:
                    return  # no service yet; let the sender time out and retry
            else:
                response = await self._service.handle_message(request)
            payload_out = encode_response(response)
            _write_frame(writer, correlation_id, 1, payload_out)
            self.stats.tx(_HEADER.size + len(payload_out))
            await writer.drain()
        except Exception as exc:  # noqa: BLE001 — connection-level fault isolation
            LOG.debug("server %s failed handling request: %r", self.listen_address, exc)


class _Connection:
    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        stats: TransportStats,
    ) -> None:
        self.reader = reader
        self.writer = writer
        self.stats = stats
        self.pending: Dict[int, asyncio.Future] = {}
        self.reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                correlation_id, kind, payload = await _read_frame(self.reader)
                # Count at the frame-read site: a response that lands after
                # its request timed out still crossed the wire (exactly the
                # slow-RPC regime Table 2 measures).
                self.stats.rx(_HEADER.size + len(payload))
                future = self.pending.pop(correlation_id, None)
                if future is not None and not future.done():
                    future.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as exc:
            for future in self.pending.values():
                if not future.done():
                    future.set_exception(ConnectionError(f"connection lost: {exc!r}"))
            self.pending.clear()

    def close(self) -> None:
        self.reader_task.cancel()
        self.writer.close()


class TcpClient(MessagingClient):
    """Persistent-connection client with correlation ids (the reference's
    channel cache + outstandingRequests future map, NettyClientServer.java:70-137)."""

    def __init__(self, my_addr: Endpoint, settings: Optional[Settings] = None) -> None:
        self.my_addr = my_addr
        self._settings = settings if settings is not None else Settings()
        # The check-then-connect in _connection_for is serialized by the
        # PER-REMOTE locks below (a dict of locks is beyond what the
        # guarded-by analysis can prove held, so the map itself carries the
        # event-loop discipline: no read->await->write outside those locks).
        self._connections: Dict[Endpoint, _Connection] = {}  # guarded-by: event-loop
        self._connect_locks: Dict[Endpoint, asyncio.Lock] = {}  # guarded-by: event-loop
        self._correlation = itertools.count(1)
        self._shut_down = False  # guarded-by: event-loop
        self.stats = TransportStats()  # paper Table 2 accounting

    def _timeout_ms_for(self, request: RapidRequest) -> float:
        if isinstance(request, (JoinMessage, PreJoinMessage)):
            return self._settings.rpc_join_timeout_ms
        if isinstance(request, ProbeMessage):
            return self._settings.rpc_probe_timeout_ms
        return self._settings.rpc_timeout_ms

    async def _connection_for(self, remote: Endpoint) -> _Connection:
        # Per-remote connect lock: concurrent first sends must share one
        # connection, not race to open several and leak the losers.
        lock = self._connect_locks.setdefault(remote, asyncio.Lock())
        async with lock:
            conn = self._connections.get(remote)
            if conn is not None and not conn.writer.is_closing():
                return conn
            reader, writer = await asyncio.open_connection(remote.hostname, remote.port)
            conn = _Connection(reader, writer, self.stats)
            self._connections[remote] = conn
            return conn

    def _invalidate(self, remote: Endpoint, conn: _Connection) -> None:
        if self._connections.get(remote) is conn:
            self._connections.pop(remote, None)
        conn.close()

    async def _attempt(self, remote: Endpoint, request: RapidRequest) -> RapidResponse:
        if self._shut_down:
            raise ShuttingDownError(f"client {self.my_addr} is shut down")
        timeout_s = self._timeout_ms_for(request) / 1000.0
        conn = await asyncio.wait_for(self._connection_for(remote), timeout=timeout_s)
        correlation_id = next(self._correlation)
        future: asyncio.Future = asyncio.get_event_loop().create_future()
        conn.pending[correlation_id] = future
        try:
            payload_out = encode_request(request)
            _write_frame(conn.writer, correlation_id, 0, payload_out)
            self.stats.tx(_HEADER.size + len(payload_out))
            await conn.writer.drain()
            payload = await asyncio.wait_for(future, timeout=timeout_s)
            return decode_response(payload)
        except asyncio.TimeoutError:
            # A slow RPC is not a transport failure: drop only this request's
            # correlation slot and leave the shared connection (and everyone
            # else's in-flight requests) alone.
            conn.pending.pop(correlation_id, None)
            raise
        except Exception:  # noqa: BLE001 — cleanup-and-reraise, not a catch:
            # any transport-level failure invalidates the cached connection
            # (GrpcClient.java:106-115's channel invalidation) and then
            # propagates unchanged to the caller's retry policy.
            conn.pending.pop(correlation_id, None)
            self._invalidate(remote, conn)
            raise

    async def send(self, remote: Endpoint, request: RapidRequest) -> RapidResponse:
        return await call_with_retries(
            lambda: self._attempt(remote, request), self._settings.rpc_default_retries
        )

    async def send_best_effort(
        self, remote: Endpoint, request: RapidRequest
    ) -> Optional[RapidResponse]:
        try:
            return await self._attempt(remote, request)
        except ShuttingDownError:
            raise
        except Exception:  # noqa: BLE001 — the best-effort contract
            # (IMessagingClient.java:25-49): one attempt, None on any
            # transport failure; only shutdown races propagate (above).
            return None

    async def shutdown(self) -> None:
        self._shut_down = True
        for conn in self._connections.values():
            conn.close()
        self._connections.clear()
