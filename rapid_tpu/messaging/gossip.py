"""Epidemic (gossip) broadcaster — the alternate broadcast strategy the
reference's SPI documents but never ships (``IBroadcaster.java:24-29``: "one
can plug in alternate implementations, such as gossip").

Instead of the origin unicasting to all N members
(``UnicastToAllBroadcaster.java:46-53``, origin egress O(N)), the origin
pushes a :class:`~rapid_tpu.types.GossipMessage` envelope to ``fanout``
random members; every member relays a FIRST-SEEN envelope to ``fanout``
random members of its own and drops redeliveries. With fanout ~ ln N + c,
push-once epidemics reach all N members with high probability while each
node's egress stays O(log N) — the load-spreading the paper's §7 points at
for vote/alert traffic at scale.

The relay layer lives entirely in messaging: the protocol core still hands
requests to its ``Broadcaster`` and receives them through ``handle_message``;
the unwrap/dedup/relay happens in a router facade wrapped around the service
(``GossipBroadcaster.router``), so transports and the membership service are
untouched. Wire framing is first-class (codec tag 11).
"""

from __future__ import annotations

import math
import random
from collections import OrderedDict
from typing import List, Optional, Tuple

from rapid_tpu.messaging.base import Broadcaster, MessagingClient
from rapid_tpu.types import Endpoint, GossipMessage, RapidRequest, Response

# Remembered (origin, msg_id) pairs; beyond this the oldest are forgotten.
# A forgotten-then-redelivered envelope re-relays once — wasteful, never
# incorrect (the protocol's handlers are all idempotent / config-id gated).
_SEEN_CAP = 8192


class GossipBroadcaster(Broadcaster):
    """Push gossip with first-seen relay.

    ``fanout``/``ttl``: explicit values, or None to size from the current
    membership at each broadcast (fanout = ceil(ln N) + 4, ttl =
    ceil(log2 N) + 4 — w.h.p. full coverage with O(N log N) total
    transmissions, each node sending O(log N)).
    """

    def __init__(
        self,
        client: MessagingClient,
        self_endpoint: Endpoint,
        fanout: Optional[int] = None,
        ttl: Optional[int] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        if ttl is not None and not 0 <= ttl <= 255:
            # The wire encodes ttl as u8; catching it here beats a
            # struct.error inside a fire-and-forget send task.
            raise ValueError(f"gossip ttl must be in [0, 255], got {ttl}")
        if fanout is not None and fanout < 1:
            raise ValueError(f"gossip fanout must be >= 1, got {fanout}")
        if getattr(client, "supports_gossip", True) is False:
            # e.g. the reference-schema interop transport: GossipMessage has
            # no rapid.proto representation (deliberately — see PARITY.md),
            # and failing at wiring time beats every broadcast vanishing
            # into per-send KeyErrors inside fire-and-forget tasks.
            raise ValueError(
                f"{type(client).__name__} cannot carry gossip envelopes; "
                "use the framework-native transports (in-process/TCP/UDP)"
            )
        self._client = client
        self._self = self_endpoint
        self._fanout = fanout
        self._ttl = ttl
        # Identity-seeded default: relay fan-out picks stay decorrelated
        # across members (different endpoints) but reproducible across runs
        # (determinism audit, tools/analysis/determinism.py).
        self._rng = rng if rng is not None else random.Random(f"gossip:{self_endpoint}")
        # Relay state is event-loop-confined (tools/analysis/concurrency.py):
        # broadcast/accept/_relay are synchronous, so every dedup
        # check-then-remember runs atomically under cooperative scheduling —
        # the annotation keeps it that way (an await slipped between a _seen
        # lookup and its _remember would re-relay duplicate envelopes).
        #: Optional fan-out scope (set by the hierarchical service,
        #: rapid_tpu/hier): maps the full membership to the subset this
        #: node relays to — gossip then spreads within the cohort instead of
        #: cluster-wide, keeping the epidemic's per-node egress O(log c).
        self.scope_fn = None  # guarded-by: event-loop
        self._members: List[Endpoint] = []  # guarded-by: event-loop
        self._seen: "OrderedDict[Tuple[Endpoint, int], None]" = OrderedDict()  # guarded-by: event-loop
        self.relays_sent = 0  # observability: total envelope transmissions

    @classmethod
    def factory(cls, fanout: Optional[int] = None, ttl: Optional[int] = None):
        """A ``broadcaster_factory`` for ``Cluster.start/join``:
        ``factory(client, listen_address, rng) -> GossipBroadcaster``."""

        def make(client: MessagingClient, listen_address: Endpoint, rng):
            return cls(client, listen_address, fanout=fanout, ttl=ttl, rng=rng)

        return make

    # -- Broadcaster SPI ------------------------------------------------

    def broadcast(self, request: RapidRequest) -> None:
        n = len(self._members)
        msg_id = self._rng.getrandbits(64)
        self._remember((self._self, msg_id))
        envelope = GossipMessage(
            origin=self._self, msg_id=msg_id, ttl=self._ttl_for(n), payload=request
        )
        self._relay(envelope)
        if self._self in self._members:
            # Deliver to self directly (UnicastToAllBroadcaster includes the
            # sender in its fan-out; the envelope never loops back to us —
            # its msg_id is already remembered).
            self._client.send_nowait(self._self, request)

    def set_membership(self, members: List[Endpoint]) -> None:
        scoped = self.scope_fn(members) if self.scope_fn is not None else members
        self._members = list(scoped)

    # -- relay side (called by the router facade) -----------------------

    def accept(self, envelope: GossipMessage) -> Optional[RapidRequest]:
        """First delivery: relay onward and return the payload for local
        handling. Redelivery: None."""
        key = (envelope.origin, envelope.msg_id)
        if key in self._seen:
            return None
        self._remember(key)
        if envelope.ttl > 0:
            self._relay(
                GossipMessage(
                    origin=envelope.origin,
                    msg_id=envelope.msg_id,
                    ttl=envelope.ttl - 1,
                    payload=envelope.payload,
                )
            )
        return envelope.payload

    def router(self, service) -> "GossipRouter":
        """Wrap the membership service for ``set_membership_service``."""
        return GossipRouter(self, service)

    # -- internals ------------------------------------------------------

    def _ttl_for(self, n: int) -> int:
        if self._ttl is not None:
            return self._ttl
        return math.ceil(math.log2(max(n, 2))) + 4

    def _fanout_for(self, n: int) -> int:
        if self._fanout is not None:
            return self._fanout
        return math.ceil(math.log(max(n, 2))) + 4

    def _relay(self, envelope: GossipMessage) -> None:
        candidates = [m for m in self._members if m != self._self]
        if not candidates:
            return
        k = min(self._fanout_for(len(self._members)), len(candidates))
        for target in self._rng.sample(candidates, k):
            self.relays_sent += 1
            self._client.send_nowait(target, envelope)

    def _remember(self, key: Tuple[Endpoint, int]) -> None:
        self._seen[key] = None
        if len(self._seen) > _SEEN_CAP:
            self._seen.popitem(last=False)


class GossipRouter:
    """Duck-typed stand-in for the membership service at the server seam:
    unwraps gossip envelopes (dedup + relay via the broadcaster), forwards
    everything else — and first deliveries — to the real service."""

    def __init__(self, broadcaster: GossipBroadcaster, service) -> None:
        self._broadcaster = broadcaster
        self._service = service

    async def handle_message(self, request: RapidRequest):
        if isinstance(request, GossipMessage):
            payload = self._broadcaster.accept(request)
            if payload is not None:
                await self._service.handle_message(payload)
            return Response()
        return await self._service.handle_message(request)
