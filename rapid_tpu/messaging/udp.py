"""Hybrid TCP+UDP transport: datagrams for one-way best-effort traffic.

The reference routes everything over unary gRPC, but the paper notes that
alert gossip and consensus vote counting are one-way and loss-tolerant and
"can run over UDP" (paper §7). This transport implements that: broadcast-fan
messages (batched alerts, fast-round votes, phase2b echoes, leaves) travel
as single datagrams — no connection setup, no response path — while
request/response traffic (joins, probes, coordinator-bound phase1b) stays on
the reliable TCP path. Both listeners share the endpoint's port.

Protocol safety and liveness: the protocol treats everything routed over UDP
as best-effort and replaces the reference transport's delivery guarantee at
the protocol level (see settings.py): alert batches are re-broadcast while
their cut is unresolved, undecided consensus re-arms (vote re-offer plus
escalating classic rounds, ``fast_paxos.py``), and a node that misses a
decision entirely pulls the configuration from a peer over the reliable TCP
path (``service._config_sync_loop``). Datagram loss therefore costs
convergence latency, never liveness or correctness. Even the historically
worst case — a decision naming a joiner whose every UP alert datagram was
lost — now resolves by pulling the decided configuration (identifiers
included) from a peer instead of forcing a rejoin
(``service._recover_from_unknown_joiners``). tests/test_udp_loss.py pins the
envelope; tests/test_delivery_liveness.py pins each mechanism.
"""

from __future__ import annotations

import asyncio
import ipaddress
import logging
import random
from typing import Dict, Optional

from rapid_tpu.messaging.codec import decode_request, encode_request
from rapid_tpu.messaging.tcp import TcpClient, TcpServer
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    BatchedAlertMessage,
    Endpoint,
    GossipMessage,
    FastRoundPhase2bMessage,
    LeaveMessage,
    Phase1aMessage,
    Phase2aMessage,
    Phase2bMessage,
    RapidRequest,
    RapidResponse,
    Response,
)

LOG = logging.getLogger(__name__)

# One-way message types: no caller consumes their response. GossipMessage
# envelopes are fire-and-forget relays (GossipRouter discards the response),
# so --transport udp --broadcast gossip keeps the datagram fast path.
ONEWAY_TYPES = (
    BatchedAlertMessage,
    FastRoundPhase2bMessage,
    GossipMessage,
    Phase1aMessage,
    Phase2aMessage,
    Phase2bMessage,
    LeaveMessage,
)

_MAX_DATAGRAM = 60 * 1024


class _ServerProtocol(asyncio.DatagramProtocol):
    def __init__(self, server: "UdpHybridServer") -> None:
        self._server = server
        # Strong references: the loop only weakly references tasks, and a
        # collected handler task silently drops the datagram.
        self._tasks: set = set()

    def datagram_received(self, data: bytes, addr) -> None:
        task = asyncio.ensure_future(self._server._handle_datagram(data))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)


class UdpHybridServer(TcpServer):
    """TCP server plus a UDP listener on the same port for one-way traffic."""

    def __init__(self, listen_address: Endpoint) -> None:
        super().__init__(listen_address)
        self._udp_transport: Optional[asyncio.DatagramTransport] = None

    async def start(self) -> None:
        await super().start()
        loop = asyncio.get_event_loop()
        try:
            self._udp_transport, _ = await loop.create_datagram_endpoint(
                lambda: _ServerProtocol(self),
                local_addr=(self.listen_address.hostname, self.listen_address.port),
            )
        except BaseException:
            # Don't leak the already-accepting TCP listener.
            await super().shutdown()
            raise

    async def shutdown(self) -> None:
        if self._udp_transport is not None:
            self._udp_transport.close()
            self._udp_transport = None
        await super().shutdown()

    async def _handle_datagram(self, data: bytes) -> None:
        try:
            self.stats.rx(len(data))
            request = decode_request(data)
            if self._service is not None:
                await self._service.handle_message(request)
        except Exception as exc:  # noqa: BLE001 — datagram-level fault isolation
            LOG.debug("server %s dropped bad datagram: %r", self.listen_address, exc)


class _ClientProtocol(asyncio.DatagramProtocol):
    def datagram_received(self, data: bytes, addr) -> None:
        pass  # one-way: responses never arrive

    def error_received(self, exc: Exception) -> None:
        # sendto errors surface here asynchronously, not at the call site.
        LOG.debug("udp send error: %r", exc)


class UdpHybridClient(TcpClient):
    """TCP client whose best-effort sends of one-way message types go as
    single datagrams (everything else rides the TCP correlation path)."""

    def __init__(self, my_addr: Endpoint, settings: Optional[Settings] = None) -> None:
        super().__init__(my_addr, settings)
        self._udp_transports: Dict[int, asyncio.DatagramTransport] = {}  # guarded-by: _udp_lock
        self._udp_lock = asyncio.Lock()

    async def _udp(self, ip_version: int) -> asyncio.DatagramTransport:
        # Guarded like TcpClient._connection_for: concurrent first sends must
        # share one socket, not race to create and leak several.
        transport = self._udp_transports.get(ip_version)
        if transport is not None and not transport.is_closing():
            return transport
        async with self._udp_lock:
            transport = self._udp_transports.get(ip_version)
            if transport is None or transport.is_closing():
                loop = asyncio.get_event_loop()
                local = ("0.0.0.0", 0) if ip_version == 4 else ("::", 0)
                transport, _ = await loop.create_datagram_endpoint(
                    _ClientProtocol, local_addr=local
                )
                self._udp_transports[ip_version] = transport
            return transport

    async def send_best_effort(
        self, remote: Endpoint, request: RapidRequest
    ) -> Optional[RapidResponse]:
        # The datagram fast path applies only to literal-IP endpoints:
        # transport.sendto never raises to the caller (errors land in
        # error_received), so a hostname that resolves differently — or not
        # at all — would be a silent drop with a fake success. Non-IP
        # hostnames take the reliable TCP path.
        if isinstance(request, ONEWAY_TYPES):
            try:
                ip = ipaddress.ip_address(remote.hostname)
            except ValueError:
                ip = None
            if ip is not None:
                payload = encode_request(request)
                if len(payload) <= _MAX_DATAGRAM:
                    if await self._send_datagram(ip.version, remote, payload):
                        return Response()  # fire-and-forget: no ack exists
        return await super().send_best_effort(remote, request)

    async def _send_datagram(self, ip_version: int, remote: Endpoint, payload: bytes) -> bool:
        """Put one datagram on the wire; False routes the caller to the TCP
        fallback. The seam LossyDatagramClient injects network loss at."""
        try:
            transport = await self._udp(ip_version)
            transport.sendto(payload, (remote.hostname, remote.port))
            self.stats.tx(len(payload))
            return True
        except Exception as exc:  # noqa: BLE001 — fall back to TCP
            LOG.debug("udp send to %s failed (%r); falling back to tcp", remote, exc)
            return False

    async def shutdown(self) -> None:
        # Under the same lock _udp() creates through: a shutdown racing a
        # concurrent first send could otherwise clear the map mid-create and
        # leak the freshly-opened datagram transport past shutdown
        # (surfaced by the unguarded-mutation analysis).
        async with self._udp_lock:
            for transport in self._udp_transports.values():
                transport.close()
            self._udp_transports.clear()
        await super().shutdown()


class LossyDatagramClient(UdpHybridClient):
    """Fault-injection client: a seeded fraction of outbound datagrams is
    dropped AFTER the sender commits to the datagram path — exactly where
    network loss strikes (the sender believes it sent; no TCP fallback
    engages). This is the instrument that quantifies the hybrid transport's
    admitted tradeoff (module docstring above): datagram loss costs
    convergence latency — lost votes and alerts ride out the redelivery and
    fallback timers, and in the limit a node catches up by config pull.
    tests/test_udp_loss.py pins the rejoin-free envelope;
    examples/udp_loss_curve.py measures the latency curve."""

    def __init__(
        self,
        my_addr: Endpoint,
        settings: Optional[Settings] = None,
        loss_rate: float = 0.0,
        rng: Optional[random.Random] = None,
    ) -> None:
        if not 0.0 <= loss_rate <= 1.0:
            raise ValueError(f"loss_rate must be in [0, 1], got {loss_rate}")
        super().__init__(my_addr, settings)
        self.loss_rate = loss_rate
        self._rng = rng if rng is not None else random.Random(0)
        self.datagrams_dropped = 0
        self.datagrams_delivered = 0

    async def _send_datagram(self, ip_version: int, remote: Endpoint, payload: bytes) -> bool:
        if self._rng.random() < self.loss_rate:
            self.datagrams_dropped += 1
            self.stats.tx(len(payload))  # the sender transmitted; the network ate it
            return True
        self.datagrams_delivered += 1
        return await super()._send_datagram(ip_version, remote, payload)
