"""In-process transport: many virtual endpoints in one event loop.

The reference's tests run whole clusters over in-process gRPC
(``GrpcServer.java:132-148`` in-process mode, ``settings.setUseInProcessTransport``);
this module is the equivalent first-class transport, plus the fault-injection
interceptor seam its test fixtures provide (``MessageDropInterceptor.java:24-73``:
drop-first-N-of-type at the server, latch-delay-by-type at the client).

This transport is also how co-located virtual nodes talk on a TPU host in the
hybrid host/device deployment: message passing is a Python method call, so the
whole cluster's protocol traffic stays in one process.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Type

from rapid_tpu.errors import ShuttingDownError
from rapid_tpu.messaging.base import MessagingClient, MessagingServer
from rapid_tpu.messaging.codec import encode_request, encode_response
from rapid_tpu.messaging.retries import call_with_retries
from rapid_tpu.messaging.stats import TransportStats
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    JoinMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    NodeStatus,
    RapidRequest,
    RapidResponse,
)


class InProcessNetwork:
    """A registry of in-process servers, shared by the clients of one test or
    one co-located deployment."""

    def __init__(self, count_wire_bytes: bool = False) -> None:
        self.servers: Dict[Endpoint, "InProcessServer"] = {}
        # Endpoints listed here are unreachable (simulated crash/partition).
        self.blackholed: set = set()
        # Directional blackholes: (src, dst) pairs that drop.
        self.blackholed_links: set = set()
        # Statistical link shaping (seeded loss/delay/duplication), consulted
        # per attempt when set — the sim subsystem's LinkShaper
        # (rapid_tpu/sim/faults.py) plugs in here. None = a perfect network,
        # zero overhead on the common path.
        self.shaper = None
        # One-shot message-triggered callbacks, consulted on every server
        # handle — the chaos runner's ``committee_crash`` arming point: a
        # fault that must land at an exact PROTOCOL moment (e.g. between
        # cohort-cut forwarding and the global decision) hooks the first
        # sighting of the message that opens the window. Empty on the
        # common path.
        self.tripwires: List["RequestTripwire"] = []
        # Account wire-EQUIVALENT bytes (what the codec would put on a TCP
        # frame) in every client/server TransportStats. Off by default: no
        # bytes actually move in-process, and encoding every message only
        # to measure it would tax the big cluster tests. Message counts are
        # always kept.
        self.count_wire_bytes = count_wire_bytes

    def server_for(self, endpoint: Endpoint) -> Optional["InProcessServer"]:
        return self.servers.get(endpoint)


class ServerDropFirstN:
    """Drop the first N messages of a type at the server
    (ServerDropInterceptors.FirstN, MessageDropInterceptor.java:24-49)."""

    def __init__(self, message_type: Type, count: int) -> None:
        self._type = message_type
        self._remaining = count

    def should_drop(self, request: RapidRequest) -> bool:
        if isinstance(request, self._type) and self._remaining > 0:
            self._remaining -= 1
            return True
        return False


class RequestTripwire:
    """Fire a callback ONCE when the first message of a type is observed at
    any server — the in-process analog of an interceptor that reacts to a
    protocol moment rather than a wall-clock one. The callback runs
    synchronously BEFORE the triggering message is handled, so a fault it
    injects (e.g. crashing the recipient) affects the triggering delivery
    itself, exactly like a process dying as the datagram arrives."""

    def __init__(self, message_type: Type, callback) -> None:
        self._type = message_type
        self._callback = callback
        self.fired = False

    def observe(self, request: RapidRequest) -> None:
        if not self.fired and isinstance(request, self._type):
            self.fired = True
            self._callback()


class ClientDelayer:
    """Hold messages of a type until a latch opens
    (ClientInterceptors.Delayer, MessageDropInterceptor.java:51-73)."""

    def __init__(self, message_type: Type) -> None:
        self._type = message_type
        self._event = asyncio.Event()
        # Messages currently parked on the latch — tests sequence on this
        # instead of sleeping (a fixed sleep can miss the interleaving and
        # silently skip the path under test).
        self.held = 0

    def open(self) -> None:
        self._event.set()

    async def maybe_delay(self, request: RapidRequest) -> None:
        if isinstance(request, self._type) and not self._event.is_set():
            self.held += 1
            try:
                await self._event.wait()
            finally:
                self.held -= 1


class InProcessServer(MessagingServer):
    def __init__(self, network: InProcessNetwork, listen_address: Endpoint) -> None:
        self._network = network
        self.listen_address = listen_address
        self._service = None
        self._started = False
        self.drop_interceptors: List[ServerDropFirstN] = []
        self.stats = TransportStats()  # paper Table 2 accounting

    def set_membership_service(self, service) -> None:
        self._service = service

    async def start(self) -> None:
        self._network.servers[self.listen_address] = self
        self._started = True

    async def shutdown(self) -> None:
        self._network.servers.pop(self.listen_address, None)
        self._started = False

    async def handle(self, request: RapidRequest) -> RapidResponse:
        if not self._started:
            raise ConnectionError(f"server {self.listen_address} not started")
        self.stats.rx(
            len(encode_request(request)) if self._network.count_wire_bytes else 0
        )
        for tripwire in self._network.tripwires:
            tripwire.observe(request)
        if self.listen_address in self._network.blackholed:
            # A tripwire (or a concurrent fault) crashed THIS server while
            # the message was in flight: the triggering delivery is lost
            # with the process, like a real crash mid-arrival.
            raise ConnectionError(f"server {self.listen_address} crashed")
        for interceptor in self.drop_interceptors:
            if interceptor.should_drop(request):
                raise ConnectionError("dropped by interceptor")
        if self._service is None:
            # Answer probes while bootstrapping; joiners' FDs tolerate this
            # status (GrpcServer.java:77-96).
            if isinstance(request, ProbeMessage):
                response: RapidResponse = ProbeResponse(status=NodeStatus.BOOTSTRAPPING)
            else:
                raise ConnectionError(
                    f"server {self.listen_address} has no service yet"
                )
        else:
            response = await self._service.handle_message(request)
        # Account the response direction too (TCP counts both ways; without
        # this the in-process Table 2 numbers omit all response traffic).
        self.stats.tx(
            len(encode_response(response)) if self._network.count_wire_bytes else 0
        )
        return response


class InProcessClient(MessagingClient):
    def __init__(
        self,
        network: InProcessNetwork,
        my_addr: Endpoint,
        settings: Optional[Settings] = None,
    ) -> None:
        self._network = network
        self.my_addr = my_addr
        self._settings = settings if settings is not None else Settings()
        self._shut_down = False
        self.delayers: List[ClientDelayer] = []
        self.stats = TransportStats()  # paper Table 2 accounting

    def _timeout_ms_for(self, request: RapidRequest) -> float:
        # Per-message-type deadlines (GrpcClient.java:194-203).
        if isinstance(request, (JoinMessage, PreJoinMessage)):
            return self._settings.rpc_join_timeout_ms
        if isinstance(request, ProbeMessage):
            return self._settings.rpc_probe_timeout_ms
        return self._settings.rpc_timeout_ms

    async def _attempt(self, remote: Endpoint, request: RapidRequest) -> RapidResponse:
        if self._shut_down:
            raise ShuttingDownError(f"client {self.my_addr} is shut down")
        for delayer in self.delayers:
            await delayer.maybe_delay(request)
        if remote in self._network.blackholed or self.my_addr in self._network.blackholed:
            raise ConnectionError(f"{remote} unreachable (blackholed)")
        if (self.my_addr, remote) in self._network.blackholed_links:
            raise ConnectionError(f"link {self.my_addr}->{remote} blackholed")
        shaper = self._network.shaper
        duplicated = False
        if shaper is not None:
            plan = shaper.plan(self.my_addr, remote)
            if plan.drop:
                raise ConnectionError(
                    f"link {self.my_addr}->{remote} dropped (shaper)"
                )
            if plan.delay_ms > 0:
                await shaper.hold_ms(plan.delay_ms)
            duplicated = plan.duplicate
        server = self._network.server_for(remote)
        if server is None:
            raise ConnectionError(f"no server at {remote}")
        self.stats.tx(
            len(encode_request(request)) if self._network.count_wire_bytes else 0
        )
        # Yield to the loop so in-process delivery preserves async semantics.
        await asyncio.sleep(0)
        if duplicated:
            # A duplicated datagram: the server handles the request twice
            # (exercising receiver-side dedup — gossip first-seen, alert
            # report idempotency); the caller sees the second response, as a
            # real retransmit's caller would. The first copy's fate is
            # independent of the second's: a server-side drop (interceptor)
            # or timeout on one copy must not fail the other.
            try:
                await asyncio.wait_for(
                    server.handle(request),
                    timeout=self._timeout_ms_for(request) / 1000.0,
                )
            except (ConnectionError, asyncio.TimeoutError):
                pass
        response = await asyncio.wait_for(
            server.handle(request), timeout=self._timeout_ms_for(request) / 1000.0
        )
        self.stats.rx(
            len(encode_response(response)) if self._network.count_wire_bytes else 0
        )
        return response

    async def send(self, remote: Endpoint, request: RapidRequest) -> RapidResponse:
        return await call_with_retries(
            lambda: self._attempt(remote, request), self._settings.rpc_default_retries
        )

    async def send_best_effort(
        self, remote: Endpoint, request: RapidRequest
    ) -> Optional[RapidResponse]:
        try:
            return await self._attempt(remote, request)
        except ShuttingDownError:
            raise
        except Exception:  # noqa: BLE001 — the best-effort contract
            # (IMessagingClient.java:25-49): one attempt, None on any
            # transport failure; only shutdown races propagate (above).
            return None

    async def shutdown(self) -> None:
        self._shut_down = True
