"""Per-node network accounting — the paper's Table 2 instrument.

The reference's evaluation reports per-process network use (mean/p99/max
KB/s received and transmitted during the crash experiment: Rapid mean
0.71/0.71, max 9.56/11.37 — paper Table 2) but ships no counters; the
numbers came from external OS instrumentation. Here every transport
carries a ``TransportStats`` so the same measurement is a library call:
``client.stats.snapshot()`` / ``server.stats.snapshot()``.

What counts: the TCP paths count real wire bytes (header + payload) per
frame; the UDP datagram path counts datagram payloads; the in-process
transport counts messages always and wire-EQUIVALENT bytes (the codec
encoding the message would have on the TCP transport) when constructed
with ``count_wire_bytes=True``. Request encoding is memoized (small LRU,
hashable messages only) so accounting a broadcast fan-out costs one
encode, not N; responses are not fanned out and are encoded per send.
"""

from __future__ import annotations

import time
from typing import Dict


class TransportStats:
    """Monotonic tx/rx message and byte counters with a rate window."""

    __slots__ = ("msgs_tx", "bytes_tx", "msgs_rx", "bytes_rx", "_window_start")

    def __init__(self) -> None:
        self.msgs_tx = 0
        self.bytes_tx = 0
        self.msgs_rx = 0
        self.bytes_rx = 0
        self._window_start = time.monotonic()

    def tx(self, nbytes: int = 0) -> None:
        self.msgs_tx += 1
        self.bytes_tx += nbytes

    def rx(self, nbytes: int = 0) -> None:
        self.msgs_rx += 1
        self.bytes_rx += nbytes

    def reset_window(self) -> None:
        """Zero the counters and restart the rate window (e.g. after
        bootstrap, to measure steady state the way Table 2 does)."""
        self.msgs_tx = self.bytes_tx = self.msgs_rx = self.bytes_rx = 0
        self._window_start = time.monotonic()

    def snapshot(self) -> Dict[str, float]:
        elapsed_s = max(time.monotonic() - self._window_start, 1e-9)
        return {
            "msgs_tx": self.msgs_tx,
            "bytes_tx": self.bytes_tx,
            "msgs_rx": self.msgs_rx,
            "bytes_rx": self.bytes_rx,
            "elapsed_s": round(elapsed_s, 3),
            "kbps_tx": round(self.bytes_tx / 1024.0 / elapsed_s, 3),
            "kbps_rx": round(self.bytes_rx / 1024.0 / elapsed_s, 3),
        }
