from rapid_tpu.messaging.base import (
    Broadcaster,
    MessagingClient,
    MessagingServer,
    UnicastToAllBroadcaster,
)
from rapid_tpu.messaging.inprocess import (
    ClientDelayer,
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
    ServerDropFirstN,
)
from rapid_tpu.messaging.retries import call_with_retries
from rapid_tpu.messaging.tcp import TcpClient, TcpServer
from rapid_tpu.messaging.udp import UdpHybridClient, UdpHybridServer

__all__ = [
    "Broadcaster",
    "MessagingClient",
    "MessagingServer",
    "UnicastToAllBroadcaster",
    "ClientDelayer",
    "InProcessClient",
    "InProcessNetwork",
    "InProcessServer",
    "ServerDropFirstN",
    "call_with_retries",
    "TcpClient",
    "TcpServer",
    "UdpHybridClient",
    "UdpHybridServer",
]
