from rapid_tpu.messaging.base import (
    Broadcaster,
    MessagingClient,
    MessagingServer,
    UnicastToAllBroadcaster,
)
from rapid_tpu.messaging.inprocess import (
    ClientDelayer,
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
    ServerDropFirstN,
)
from rapid_tpu.messaging.retries import call_with_retries

__all__ = [
    "Broadcaster",
    "MessagingClient",
    "MessagingServer",
    "UnicastToAllBroadcaster",
    "ClientDelayer",
    "InProcessClient",
    "InProcessNetwork",
    "InProcessServer",
    "ServerDropFirstN",
    "call_with_retries",
]
