"""Messaging plugin SPI.

The critical design seam of the reference, reproduced exactly: the protocol
core never touches sockets. All sends go through ``MessagingClient``
(``messaging/IMessagingClient.java:25-49``), all receives enter through a
``MessagingServer`` that forwards to ``MembershipService.handle_message``
(``messaging/IMessagingServer.java:24-40``), and broadcast fan-out is a
``Broadcaster`` (``messaging/IBroadcaster.java:26-29``). Transports are
swapped via ``Cluster`` builder arguments.
"""

from __future__ import annotations

import abc
import asyncio
import functools
import logging
import random
from typing import List, Optional, Set, TYPE_CHECKING

from rapid_tpu.errors import ShuttingDownError
from rapid_tpu.types import Endpoint, RapidRequest, RapidResponse

if TYPE_CHECKING:
    from rapid_tpu.protocol.service import MembershipService

LOG = logging.getLogger(__name__)


def _reap_nowait_task(tasks: "Set[asyncio.Task]", task: asyncio.Task) -> None:
    tasks.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is None:
        return
    # Best-effort sends absorb transport failures and return None; the only
    # EXPECTED escapee is ShuttingDownError racing a late broadcast. Anything
    # else is a transport bug that must stay as visible as the loop's old
    # "exception was never retrieved" message, not quieter.
    if isinstance(exc, ShuttingDownError):
        LOG.debug("send_nowait raced transport shutdown: %r", exc)
    else:
        LOG.warning("send_nowait task failed: %r", exc)


class MessagingClient(abc.ABC):
    """Send messages to remote endpoints.

    ``send`` retransmits per the transport's retry policy and raises on final
    failure; ``send_best_effort`` makes one attempt and returns None on
    failure (IMessagingClient.java:25-49).
    """

    @abc.abstractmethod
    async def send(self, remote: Endpoint, request: RapidRequest) -> RapidResponse:
        ...

    @abc.abstractmethod
    async def send_best_effort(
        self, remote: Endpoint, request: RapidRequest
    ) -> Optional[RapidResponse]:
        ...

    def send_nowait(self, remote: Endpoint, request: RapidRequest) -> None:
        """Fire-and-forget best-effort send (broadcasts, consensus traffic).
        The task is tracked in a per-client strong-reference set — the event
        loop holds tasks weakly, so an untracked send could be garbage-
        collected mid-flight — and its outcome is observed by the reaper
        callback (``send_best_effort`` returns None on failure by contract,
        but a transport shutting down underneath the send re-raises). The
        set lives on the client instance (lazily, so abstract subclasses
        need no ``super().__init__``): when the client is dropped after
        shutdown, any entry stranded by a loop that closed mid-flight is
        released with it instead of accumulating for the process lifetime."""
        tasks: Set[asyncio.Task] = self.__dict__.setdefault("_nowait_tasks", set())
        task = asyncio.ensure_future(self.send_best_effort(remote, request))
        tasks.add(task)
        task.add_done_callback(functools.partial(_reap_nowait_task, tasks))

    @abc.abstractmethod
    async def shutdown(self) -> None:
        ...


class MessagingServer(abc.ABC):
    """Receive messages and hand them to the membership service. The server
    starts before the service exists (join protocol); probes received in that
    window answer BOOTSTRAPPING (GrpcServer.java:77-96)."""

    @abc.abstractmethod
    async def start(self) -> None:
        ...

    @abc.abstractmethod
    async def shutdown(self) -> None:
        ...

    @abc.abstractmethod
    def set_membership_service(self, service: "MembershipService") -> None:
        ...


class Broadcaster(abc.ABC):
    """Fan a request out to all members (IBroadcaster.java:26-29)."""

    @abc.abstractmethod
    def broadcast(self, request: RapidRequest) -> None:
        ...

    @abc.abstractmethod
    def set_membership(self, members: List[Endpoint]) -> None:
        ...


class UnicastToAllBroadcaster(Broadcaster):
    """Default broadcaster: best-effort unicast to every member, in an order
    shuffled once per configuration to spread load
    (UnicastToAllBroadcaster.java:46-62)."""

    def __init__(self, client: MessagingClient, rng: Optional[random.Random] = None) -> None:
        self._client = client
        self._members: List[Endpoint] = []
        # The service always threads its identity-seeded rng; this SPI layer
        # has no identity of its own to derive a seed from, so a bare
        # standalone construction keeps the stdlib default.
        self._rng = rng if rng is not None else random.Random()  # unseeded-ok: no identity at this layer; every in-library caller injects the service's seeded rng

    def broadcast(self, request: RapidRequest) -> None:
        for member in self._members:
            self._client.send_nowait(member, request)

    def set_membership(self, members: List[Endpoint]) -> None:
        members = list(members)
        self._rng.shuffle(members)
        self._members = members
