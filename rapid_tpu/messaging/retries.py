"""Async retry combinator (reference: messaging/impl/Retries.java:43-90)."""

from __future__ import annotations

from typing import Awaitable, Callable, TypeVar

from rapid_tpu.errors import ShuttingDownError

T = TypeVar("T")


async def call_with_retries(
    call: Callable[[], Awaitable[T]],
    retries: int,
) -> T:
    """Run ``call`` until it succeeds, for at most ``retries + 1`` attempts;
    re-raises the last failure. Terminal conditions — task cancellation
    (BaseException) and client shutdown — propagate immediately instead of
    burning further attempts."""
    last_exc: Exception | None = None
    for _ in range(retries + 1):
        try:
            return await call()
        except ShuttingDownError:
            raise
        except Exception as exc:  # noqa: BLE001 — transport failures vary by impl
            last_exc = exc
    assert last_exc is not None
    raise last_exc
