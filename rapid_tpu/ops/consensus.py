"""Batched Fast-Paxos vote tallies on device.

The reference counts identical proposals in a hash map per receiving node
(``FastPaxos.java:125-156``, with the comment that votesReceived "should be a
bitset"). Here a whole configuration's fast round is tallied in one kernel
over N vote slots: proposals are 64-bit set-hashes (uint32 hi/lo lanes), and
the decision rule is the reference's: decided iff
``total_votes >= N - F`` and ``max identical votes >= N - F`` with
``F = floor((N-1)/4)``.

Two kernels:
- ``tally_candidates`` — counts votes against a small candidate list; every
  reduction is a plain sum, so it shards over N with psum (the multi-chip
  path).
- ``tally_sorted`` — no candidate knowledge: sort the vote hashes and find
  the longest run (single-chip / debugging path).

Narrow-width discipline (the compact engine state,
models/state.compaction_policy): vote/candidate hash lanes are identity
and stay uint32 under every policy; every count in this module already
accumulates at an EXPLICIT ``dtype=jnp.int32`` (``jnp.sum(matches, ...)``,
``total``) rather than inheriting an input dtype — which is exactly why
the tallies are width-independent of however narrowly the caller stores
its state. Keep any new reduction here explicitly int32-accumulated; the
``dtype-widening`` lint guards the store side in the round body.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from rapid_tpu.ops.hashing import lex_argsort
# Single source of truth for the decision threshold: plain arithmetic, works
# on Python ints and JAX arrays alike.
from rapid_tpu.protocol.fast_paxos import fast_paxos_quorum as fast_paxos_quorum_size


class TallyResult(NamedTuple):
    decided: jnp.ndarray  # scalar bool
    winner_hi: jnp.ndarray  # uint32 (0 when undecided)
    winner_lo: jnp.ndarray
    max_count: jnp.ndarray  # int32 votes for the best proposal
    total_votes: jnp.ndarray  # int32 valid votes seen


@jax.jit
def tally_candidates(
    vote_hi: jnp.ndarray,
    vote_lo: jnp.ndarray,
    vote_valid: jnp.ndarray,
    cand_hi: jnp.ndarray,
    cand_lo: jnp.ndarray,
    cand_valid: jnp.ndarray,
    n_members: jnp.ndarray,
) -> TallyResult:
    """Count identical votes against C candidate proposals.

    vote_*: [N] per-slot vote hash lanes + validity (has this member voted).
    cand_*: [C] candidate proposal hashes (C small; from cohort proposals).
    """
    c = cand_hi.shape[0]
    matches = (
        vote_valid[None, :]
        & cand_valid[:, None]
        & (vote_hi[None, :] == cand_hi[:, None])
        & (vote_lo[None, :] == cand_lo[:, None])
    )
    counts = jnp.sum(matches, axis=1, dtype=jnp.int32)  # [C], per-candidate
    total = jnp.sum(vote_valid, dtype=jnp.int32)
    # The cross-cohort decision test as pure reductions over C: on the
    # cohort-meshed engine the candidate lanes are sharded over the cohort
    # axis, and an argmax+gather (counts[best], cand_hi[best]) would
    # all-gather them — max + first-max one-hot select lowers to psums
    # instead, and is bit-identical to argmax's first-max tie-break.
    max_count = jnp.max(counts)
    cand_ids = jnp.arange(c, dtype=jnp.int32)
    best = jnp.min(jnp.where(counts == max_count, cand_ids, jnp.int32(c)))
    sel = cand_ids == best  # one-hot: the lowest-index max candidate
    quorum = fast_paxos_quorum_size(n_members)
    decided = (total >= quorum) & (max_count >= quorum)
    return TallyResult(
        decided=decided,
        winner_hi=jnp.max(jnp.where(decided & sel, cand_hi, jnp.uint32(0))),
        winner_lo=jnp.max(jnp.where(decided & sel, cand_lo, jnp.uint32(0))),
        max_count=max_count,
        total_votes=total,
    )


def undecided_log2_bucket(rounds_undecided: jnp.ndarray, buckets: int) -> jnp.ndarray:
    """Log2 histogram bucket of a decision's rounds-undecided count: bucket
    ``floor(log2(max(r, 1)))`` clamped into ``[0, buckets)``, so bucket 0 is
    the one-round fast path and the last bucket absorbs every long stall.
    Elementwise int32 bit-twiddling (popcount-free: a 15-bit counter needs
    at most 15 halvings), used by the telemetry plane's
    ``tl_undecided_hist`` scatter — keep it reduction-free so it can never
    add hot-loop collectives."""
    r = jnp.maximum(rounds_undecided.astype(jnp.int32), 1)
    bucket = jnp.zeros((), dtype=jnp.int32)
    for _ in range(buckets - 1):
        r = r >> 1
        bucket = bucket + (r > 0).astype(jnp.int32)
    return jnp.minimum(bucket, buckets - 1)


@jax.jit
def tally_sorted(
    vote_hi: jnp.ndarray,
    vote_lo: jnp.ndarray,
    vote_valid: jnp.ndarray,
    n_members: jnp.ndarray,
) -> TallyResult:
    """Longest-identical-run tally without candidate knowledge: sort votes by
    (invalid, hi, lo) and segment-count runs of equal hashes."""
    n = vote_hi.shape[0]
    invalid = (~vote_valid).astype(jnp.uint32)
    order = lex_argsort((invalid, vote_hi, vote_lo))
    hi_s = vote_hi[order]
    lo_s = vote_lo[order]
    valid_s = vote_valid[order]

    idx = jnp.arange(n, dtype=jnp.int32)
    new_run = (idx == 0) | (hi_s != jnp.roll(hi_s, 1)) | (lo_s != jnp.roll(lo_s, 1))
    new_run = new_run | ~valid_s  # invalid tail never forms runs
    run_id = jnp.cumsum(new_run.astype(jnp.int32)) - 1
    counts = jax.ops.segment_sum(valid_s.astype(jnp.int32), run_id, num_segments=n)
    best_run = jnp.argmax(counts)
    max_count = counts[best_run]
    first_of_best = jnp.argmax(run_id == best_run)  # first True index
    total = jnp.sum(vote_valid, dtype=jnp.int32)
    quorum = fast_paxos_quorum_size(n_members)
    decided = (total >= quorum) & (max_count >= quorum)
    return TallyResult(
        decided=decided,
        winner_hi=jnp.where(decided, hi_s[first_of_best], jnp.uint32(0)),
        winner_lo=jnp.where(decided, lo_s[first_of_best], jnp.uint32(0)),
        max_count=max_count,
        total_votes=total,
    )
