"""Batched multi-node cut detection on device.

The reference tallies alerts one at a time through hash maps
(``MultiNodeCutDetector.java:84-128``); here the whole detector state is a
dense ``reports[N, K]`` bool matrix and one batch of alerts is processed by a
single fused kernel: OR-in the new reports (per-(subject, ring) dedup is the
OR), row-sum the tallies, apply the H/L watermark, run the implicit
edge-invalidation pass (``MultiNodeCutDetector.java:137-164``), and re-check.

Per-batch semantics match the union-of-proposals the membership service
consumes per BatchedAlertMessage (``MembershipService.java:300-354``): a
proposal is released iff at least one subject is past H and none sits in
[L, H) after implicit invalidation.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class CutState(NamedTuple):
    """reports[n, k] — per-(subject, ring) report bits; seen_down — whether any
    DOWN alert was applied since the last clear (gates invalidation, matching
    MultiNodeCutDetector.java:139-142); released[n] — subjects already emitted
    in an earlier batch's proposal (the reference clears its proposal set on
    release, MultiNodeCutDetector.java:120-121, so they must not re-propose)."""

    reports: jnp.ndarray
    seen_down: jnp.ndarray
    released: jnp.ndarray

    @staticmethod
    def create(n: int, k: int) -> "CutState":
        return CutState(
            reports=jnp.zeros((n, k), dtype=bool),
            seen_down=jnp.zeros((), dtype=bool),
            released=jnp.zeros((n,), dtype=bool),
        )


class CutResult(NamedTuple):
    state: CutState
    propose: jnp.ndarray  # scalar bool: a cut is ready
    proposal_mask: jnp.ndarray  # [n] bool: members of the cut (when propose)
    tally: jnp.ndarray  # [n] int32 report counts (diagnostics)


@partial(jax.jit, static_argnames=("h", "l"))
def process_alert_batch(
    state: CutState,
    new_reports: jnp.ndarray,
    batch_has_down: jnp.ndarray,
    inval_obs_idx: jnp.ndarray,
    subject_mask: jnp.ndarray,
    h: int,
    l: int,
) -> CutResult:
    """Apply one batch of alerts.

    new_reports:    [n, k] bool — report bits to OR in (dedup via OR).
    batch_has_down: scalar bool — batch contained any DOWN alert.
    inval_obs_idx:  [k, n] int32 — per (ring, subject): the slot whose own
                    failure implies this edge (observer for present nodes,
                    expected observer for joiners); -1 disables.
    subject_mask:   [n] bool — slots that may legitimately be reported on
                    (present members + pending joiners).
    """
    n, k = state.reports.shape
    reports = (state.reports | new_reports) & subject_mask[:, None]
    seen_down = state.seen_down | batch_has_down

    tally = jnp.sum(reports, axis=1, dtype=jnp.int32)
    stable = tally >= h
    flux = (tally >= l) & (tally < h)
    # Pending-stable only: subjects released in an earlier batch left the
    # reference's proposal set (MultiNodeCutDetector.java:120-121) and no
    # longer legitimize implicit edges.
    in_union = (stable & ~state.released) | flux

    # Implicit edge invalidation: for every subject in flux, edges whose
    # (expected) observer is itself failing/joining are auto-reported. The
    # union (stable | flux) is invariant under the pass, so one masked OR is
    # the fixpoint (see MultiNodeCutDetector.java:146-159).
    obs = inval_obs_idx.T  # [n, k]
    obs_in_union = jnp.where(obs >= 0, in_union[jnp.clip(obs, 0, n - 1)], False)
    implicit = flux[:, None] & obs_in_union
    reports = jnp.where(seen_down, reports | implicit, reports) & subject_mask[:, None]

    tally2 = jnp.sum(reports, axis=1, dtype=jnp.int32)
    stable2 = tally2 >= h
    flux2 = (tally2 >= l) & (tally2 < h)
    fresh_stable = stable2 & ~state.released
    propose = jnp.any(fresh_stable) & ~jnp.any(flux2)
    proposal_mask = fresh_stable & propose

    return CutResult(
        state=CutState(
            reports=reports,
            seen_down=seen_down,
            released=state.released | proposal_mask,
        ),
        propose=propose,
        proposal_mask=proposal_mask,
        tally=tally2,
    )


def alerts_to_report_matrix(n: int, k: int, dst_idx, ring_numbers) -> jnp.ndarray:
    """Scatter a list of (subject slot, ring) alerts into an [n, k] bool
    matrix. Inputs are index arrays of equal length; negative dst entries are
    ignored (padding)."""
    dst_idx = jnp.asarray(dst_idx, dtype=jnp.int32)
    ring_numbers = jnp.asarray(ring_numbers, dtype=jnp.int32)
    valid = (dst_idx >= 0) & (ring_numbers >= 0) & (ring_numbers < k)
    flat = jnp.where(valid, dst_idx * k + ring_numbers, n * k)
    out = jnp.zeros((n * k + 1,), dtype=bool).at[flat].set(True)
    return out[: n * k].reshape(n, k)
