"""Batched multi-node cut detection on device.

The reference tallies alerts one at a time through hash maps
(``MultiNodeCutDetector.java:84-128``); here the whole detector state is a
dense ``reports[N, K]`` bool matrix and one batch of alerts is processed by a
single fused kernel: OR-in the new reports (per-(subject, ring) dedup is the
OR), row-sum the tallies, apply the H/L watermark, run the implicit
edge-invalidation pass (``MultiNodeCutDetector.java:137-164``), and re-check.

Per-batch semantics match the union-of-proposals the membership service
consumes per BatchedAlertMessage (``MembershipService.java:300-354``): a
proposal is released iff at least one subject is past H and none sits in
[L, H) after implicit invalidation.

Two grains live here:

- :func:`process_alert_batch` — ONE detector over ``[n, k]`` report bools
  (the host-twin / single-receiver grain);
- :func:`cohort_watermark_pass` — C independent detectors batched over a
  leading cohort axis of uint32 ring bitmasks (the engine's round-body
  grain, formerly ``virtual_cluster._cohort_cut_detection``). The cohort
  dimension is a REAL mesh axis on the 2-D ``('cohort', 'nodes')`` engine
  mesh: everything in the pass is either elementwise on ``[c, n]``
  (shard-local) or a per-cohort reduction over the node axis (a psum over
  node-axis subgroups) — nothing reduces or gathers over the cohort axis,
  so per-device watermark state is ``[c/dc, n/dn]``, not ``[c, n]``. The
  cross-cohort work (3N/4 quorum count, winner selection, classic
  fallback) lives in the consensus tally, not here.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from rapid_tpu.ops.pallas_kernels import (
    _popcount32,
    watermark_merge_classify_impl,
)


class CutState(NamedTuple):
    """reports[n, k] — per-(subject, ring) report bits; seen_down — whether any
    DOWN alert was applied since the last clear (gates invalidation, matching
    MultiNodeCutDetector.java:139-142); released[n] — subjects already emitted
    in an earlier batch's proposal (the reference clears its proposal set on
    release, MultiNodeCutDetector.java:120-121, so they must not re-propose)."""

    reports: jnp.ndarray
    seen_down: jnp.ndarray
    released: jnp.ndarray

    @staticmethod
    def create(n: int, k: int) -> "CutState":
        return CutState(
            reports=jnp.zeros((n, k), dtype=bool),
            seen_down=jnp.zeros((), dtype=bool),
            released=jnp.zeros((n,), dtype=bool),
        )


class CutResult(NamedTuple):
    state: CutState
    propose: jnp.ndarray  # scalar bool: a cut is ready
    proposal_mask: jnp.ndarray  # [n] bool: members of the cut (when propose)
    tally: jnp.ndarray  # [n] int32 report counts (diagnostics)


@partial(jax.jit, static_argnames=("h", "l"))
def process_alert_batch(
    state: CutState,
    new_reports: jnp.ndarray,
    batch_has_down: jnp.ndarray,
    inval_obs_idx: jnp.ndarray,
    subject_mask: jnp.ndarray,
    h: int,
    l: int,
) -> CutResult:
    """Apply one batch of alerts.

    new_reports:    [n, k] bool — report bits to OR in (dedup via OR).
    batch_has_down: scalar bool — batch contained any DOWN alert.
    inval_obs_idx:  [k, n] int32 — per (ring, subject): the slot whose own
                    failure implies this edge (observer for present nodes,
                    expected observer for joiners); -1 disables.
    subject_mask:   [n] bool — slots that may legitimately be reported on
                    (present members + pending joiners).
    """
    n, k = state.reports.shape
    reports = (state.reports | new_reports) & subject_mask[:, None]
    seen_down = state.seen_down | batch_has_down

    tally = jnp.sum(reports, axis=1, dtype=jnp.int32)
    stable = tally >= h
    flux = (tally >= l) & (tally < h)
    # Pending-stable only: subjects released in an earlier batch left the
    # reference's proposal set (MultiNodeCutDetector.java:120-121) and no
    # longer legitimize implicit edges.
    in_union = (stable & ~state.released) | flux

    # Implicit edge invalidation: for every subject in flux, edges whose
    # (expected) observer is itself failing/joining are auto-reported. The
    # union (stable | flux) is invariant under the pass, so one masked OR is
    # the fixpoint (see MultiNodeCutDetector.java:146-159).
    obs = inval_obs_idx.T  # [n, k]
    obs_in_union = jnp.where(obs >= 0, in_union[jnp.clip(obs, 0, n - 1)], False)
    implicit = flux[:, None] & obs_in_union
    reports = jnp.where(seen_down, reports | implicit, reports) & subject_mask[:, None]

    tally2 = jnp.sum(reports, axis=1, dtype=jnp.int32)
    stable2 = tally2 >= h
    flux2 = (tally2 >= l) & (tally2 < h)
    fresh_stable = stable2 & ~state.released
    propose = jnp.any(fresh_stable) & ~jnp.any(flux2)
    proposal_mask = fresh_stable & propose

    return CutResult(
        state=CutState(
            reports=reports,
            seen_down=seen_down,
            released=state.released | proposal_mask,
        ),
        propose=propose,
        proposal_mask=proposal_mask,
        tally=tally2,
    )


def cohort_watermark_pass(
    report_bits: jnp.ndarray,
    new_bits: jnp.ndarray,
    seen_down: jnp.ndarray,
    released: jnp.ndarray,
    announced: jnp.ndarray,
    subject_mask: jnp.ndarray,
    inval_obs: jnp.ndarray,
    heard_down: jnp.ndarray,
    h,  # Python int or traced int32 (per-tenant fleet watermarks)
    l,
    k: int,
):
    """Batched per-cohort watermark pass over uint32 ring-report bitmasks
    (:func:`process_alert_batch` semantics over a leading cohort axis, gated
    by the per-configuration announced-proposal flag,
    MembershipService.java:318-348).

    report_bits/released: ``[c, n]`` per-cohort detector state;
    seen_down/announced/heard_down: ``[c]`` cohort lanes; subject_mask:
    ``[n]``; inval_obs: ``[k, n]``. Returns ``(report_bits, released,
    announced, seen_down, propose, proposal_mask)``.

    Sharding discipline (the 2-D mesh contract): the merge + popcount + H/L
    classification is plain elementwise jnp on ``[c, n]`` — XLA's own
    fusion measured faster than a hand-written Mosaic version at engine
    shapes (ops/pallas_kernels.py module docstring) and it partitions
    shard-locally on a ``('cohort', 'nodes')`` mesh. The per-cohort
    release/propose decisions are reductions over the NODE axis only
    (per-shard psums); nothing here reduces over the cohort axis. The
    implicit-invalidation gather only runs when some cohort actually has
    subjects in flux after a DOWN event (lax.cond): in pure crash/join
    rounds every subject jumps straight past H, so the expensive gather is
    skipped — and on the mesh the gathered traffic stays cond-gated.
    """
    c, n = report_bits.shape
    # The impl, not the jitted wrapper: the tenant fleet vmaps this pass
    # with TRACED per-tenant h/l, which a static-argnames jit would reject;
    # inside the engine's traces the wrapper was inlined anyway, so the
    # compiled program is unchanged.
    report_bits, cls = watermark_merge_classify_impl(
        report_bits,
        new_bits,
        jnp.broadcast_to(subject_mask[None, :], (c, n)),
        h,
        l,
    )
    seen_down = seen_down | heard_down  # [c]
    stable = cls == 2
    flux = cls == 1

    def with_implicit(report_bits):
        # Implicit edge invalidation (MultiNodeCutDetector.java:137-164): the
        # union (pending-stable | flux) is invariant under the pass, so one
        # masked OR is the fixpoint. Already-released subjects left the
        # pending set (MultiNodeCutDetector.java:120-121) and no longer
        # legitimize implicit edges. Per-ring loop: [c, n] gathers, never a
        # [c, n, k] materialization (C can be in the hundreds).
        in_union = (stable & ~released) | flux  # [c, n]
        # Accumulate at the report lane's own dtype (uint8/uint16 under the
        # compact policy, K <= 8*itemsize by construction): a uint32
        # operand would silently re-widen the whole [c, n] lane.
        bdt = report_bits.dtype
        implicit_bits = jnp.zeros((c, n), dtype=bdt)
        for ring in range(k):
            obs_r = inval_obs[ring]  # [n]
            gathered = in_union[:, jnp.clip(obs_r, 0, n - 1)]  # [c, n]
            implicit_r = flux & gathered & (obs_r >= 0)[None, :] & seen_down[:, None]
            implicit_bits = implicit_bits | (
                implicit_r.astype(bdt) << jnp.asarray(ring, bdt)
            )
        merged = report_bits | implicit_bits
        return jnp.where(subject_mask[None, :], merged, 0)

    need_invalidation = jnp.any(flux & seen_down[:, None])
    report_bits = jax.lax.cond(need_invalidation, with_implicit, lambda r: r, report_bits)

    tally2 = _popcount32(report_bits)
    stable2 = tally2 >= h
    flux2 = (tally2 >= l) & (tally2 < h)
    fresh_stable = stable2 & ~released
    propose = ~announced & jnp.any(fresh_stable, axis=1) & ~jnp.any(flux2, axis=1)
    proposal_mask = fresh_stable & propose[:, None]
    return (
        report_bits,
        released | proposal_mask,
        announced | propose,
        seen_down,
        propose,
        proposal_mask,
    )


def telemetry_cut_masks(
    prev_bits: jnp.ndarray,
    new_bits: jnp.ndarray,
    final_bits: jnp.ndarray,
    subject_mask: jnp.ndarray,
    h,
    l,
):
    """Telemetry-plane observation of one :func:`cohort_watermark_pass`:
    ``(active[c, n], invalidated[c, n])`` bool masks, derived purely from
    the pass's inputs and outputs so the pass itself (including its
    cond-gated implicit-invalidation branch) stays byte-identical whether
    or not telemetry observes it.

    ``active``     — slots with nonzero report bits or a watermark tally in
                     the ``[l, h)`` flux band (the ISSUE's active-subject
                     definition; the quantity sparse O(activity) rounds
                     will skip work by).
    ``invalidated``— slots that gained report bits the merge did NOT
                     deliver: any bit in ``final_bits`` absent from
                     ``prev_bits | new_bits`` can only have come from the
                     implicit edge-invalidation pass
                     (MultiNodeCutDetector.java:137-164).

    Everything here is elementwise on ``[c, n]`` (plus the existing-grain
    popcount), so on a ``('cohort', 'nodes')`` mesh it is shard-local —
    zero collectives by construction."""
    bdt = final_bits.dtype
    delivered = (prev_bits.astype(bdt) | new_bits.astype(bdt)) & jnp.where(
        subject_mask[None, :], ~jnp.zeros((), dtype=bdt), 0
    )
    tally = _popcount32(final_bits)
    active = (final_bits != 0) | ((tally >= l) & (tally < h))
    invalidated = (final_bits & ~delivered) != 0
    return active, invalidated


def alerts_to_report_matrix(n: int, k: int, dst_idx, ring_numbers) -> jnp.ndarray:
    """Scatter a list of (subject slot, ring) alerts into an [n, k] bool
    matrix. Inputs are index arrays of equal length; negative dst entries are
    ignored (padding)."""
    dst_idx = jnp.asarray(dst_idx, dtype=jnp.int32)
    ring_numbers = jnp.asarray(ring_numbers, dtype=jnp.int32)
    valid = (dst_idx >= 0) & (ring_numbers >= 0) & (ring_numbers < k)
    flat = jnp.where(valid, dst_idx * k + ring_numbers, n * k)
    out = jnp.zeros((n * k + 1,), dtype=bool).at[flat].set(True)
    return out[: n * k].reshape(n, k)
