from rapid_tpu.ops.consensus import TallyResult, tally_candidates, tally_sorted
from rapid_tpu.ops.cut_detection import (
    CutResult,
    CutState,
    alerts_to_report_matrix,
    process_alert_batch,
)
from rapid_tpu.ops.hashing import lex_argsort, masked_set_hash, mix32
from rapid_tpu.ops.rings import (
    RingTopology,
    endpoint_ring_keys,
    predecessor_of_keys,
    ring_perms,
    ring_topology,
    ring_topology_from_perm,
)

__all__ = [
    "TallyResult",
    "tally_candidates",
    "tally_sorted",
    "CutResult",
    "CutState",
    "alerts_to_report_matrix",
    "process_alert_batch",
    "lex_argsort",
    "masked_set_hash",
    "mix32",
    "RingTopology",
    "endpoint_ring_keys",
    "predecessor_of_keys",
    "ring_perms",
    "ring_topology",
    "ring_topology_from_perm",
]
