"""Pallas TPU kernel for the protocol hot path, plus the uint32-bitmask
watermark core it rides on.

The hot per-round computation is the ALERT DELIVERY pass: per (cohort, ring)
bitwise work over gathered rx-block words plus a per-edge jitter hash draw.
The Mosaic kernel below (``delivery_new_bits_pallas``) runs the whole
(cohort-word x ring) loop nest in VMEM — measured 2.25x over XLA's fusion at
engine shapes (evidence/round2/microbench_slope.json) and on by default on
TPU via ``EngineConfig.use_pallas``.

The cut detector's watermark pass (merge report bits, popcount, classify
against H/L — ``MultiNodeCutDetector.java:84-128``) lives here too as
``watermark_merge_classify``, but as a plain jnp elementwise core: a
hand-written Mosaic version of it was benchmarked at 0.69x of XLA's own
fusion at engine shapes (2.52 ms vs 3.67 ms at [8, 1M], EVALUATION.md) and
was deleted — XLA already fuses an elementwise OR+popcount+compare sweep
optimally, so the kernel carried maintenance cost for negative return.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from rapid_tpu.ops.hashing import mix32 as _mix32

try:  # pallas is TPU/Mosaic-gated; keep import soft for CPU-only installs
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover  # noqa: BLE001 — any import failure
    # (missing extra, Mosaic ABI mismatch, partial install) means the same
    # thing here: no pallas, fall back to the pure-JAX kernels.
    _HAS_PALLAS = False

_LANES = 128


def _popcount32(v):
    """Branch-free 32-bit popcount (Hacker's Delight 5-1), VPU-friendly."""
    v = v - ((v >> 1) & jnp.uint32(0x55555555))
    v = (v & jnp.uint32(0x33333333)) + ((v >> 2) & jnp.uint32(0x33333333))
    v = (v + (v >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((v * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)


def watermark_merge_classify_impl(
    old_bits: jnp.ndarray,
    new_bits: jnp.ndarray,
    subject_mask: jnp.ndarray,
    h,
    l,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-subject report bitmasks and classify against H/L.

    old_bits/new_bits: uint32 ring-report bitmasks; subject_mask: bool
    (present members + pending joiners — reports for anything else clear to 0,
    the filter invariant of MembershipService.java:644-675). Any shape:
    elementwise, shape-preserving (no resharding of distributed inputs); XLA
    fuses the whole sweep (see module docstring for why there is deliberately
    no Mosaic version).
    ``h``/``l`` may be Python ints (the classic static engine config) or
    traced int32 scalars — the tenant fleet (rapid_tpu/tenancy) vmaps this
    pass with PER-TENANT watermarks, so the comparisons must trace; both
    spellings lower to the identical compare ops.
    Returns (merged_bits at the INPUT bitmask dtype, cls int32: 0 none /
    1 flux / 2 stable), shaped like the inputs. Dtype-preserving on
    purpose: the compact engine stores report bitmasks at uint8/uint16
    (models/state.compaction_policy) and a uint32 operand here would
    silently re-widen the lane — the weak-typed zero keeps the merge at
    the lane's own width while the popcount accumulates at int32.
    """
    merged = jnp.where(subject_mask, old_bits | new_bits, 0)
    tally = _popcount32(merged)
    stable = tally >= h
    flux = (tally >= l) & (tally < h)
    cls = jnp.where(stable, jnp.int32(2), jnp.where(flux, jnp.int32(1), jnp.int32(0)))
    return merged, cls


#: The standalone jitted entry (host twins / tests); the engine's round body
#: calls the impl directly so traced per-tenant h/l stay legal.
watermark_merge_classify = jax.jit(
    watermark_merge_classify_impl, static_argnames=()
)


def _delivery_kernel(k, w, spread, permille, lanes, blocked_ref, age_ref, epoch_ref, out_ref):
    """Fused per-cohort alert delivery for one ``lanes``-slot tile.

    The engine's delivery pass (virtual_cluster._deliver_alerts) is, per
    round, K iterations of [c, n] bitwise work over gathered rx-block words
    plus a per-(cohort, edge) hash draw — bandwidth-bound elementwise
    traffic. This kernel runs the whole (cohort-word x ring) loop nest in
    VMEM: one read of the blocked words and ages, one write of the packed
    result, nothing materialized per ring.

    Layout: 32 cohorts per uint32 word ride the sublane axis as a
    [32, lanes] tile; slots ride lanes (lanes = tile width, a multiple of
    128 — tunable per shape, examples/delivery_autotune.py); cohort words
    and rings are static Python loops. Hash streams are bit-identical to
    the jnp path AND across tile widths (the draw is salted by the GLOBAL
    slot index, tile*lanes + lane).
    """
    lane = jax.lax.broadcasted_iota(jnp.uint32, (32, lanes), 1)
    j = jax.lax.broadcasted_iota(jnp.uint32, (32, lanes), 0)  # cohort-in-word
    tile = pl.program_id(0)
    slot = tile.astype(jnp.uint32) * jnp.uint32(lanes) + lane
    slot_salt = slot * jnp.uint32(0x85EBCA77)
    epoch_salt = epoch_ref[0] * jnp.uint32(0x27D4EB2F)
    for wi in range(w):
        acc = jnp.zeros((32, lanes), jnp.uint32)
        cohort_term = (jnp.uint32(wi * 32) + j) * jnp.uint32(0x9E3779B1)
        for ring in range(k):
            words = blocked_ref[wi * k + ring : wi * k + ring + 1, :]  # [1, lanes]
            blocked_bit = (jnp.broadcast_to(words, (32, lanes)) >> j) & jnp.uint32(1)
            age = jnp.broadcast_to(age_ref[ring : ring + 1, :], (32, lanes))
            if spread > 0:
                rnd = _mix32(
                    cohort_term
                    ^ slot_salt
                    ^ jnp.uint32((ring * 0xC2B2AE3D) & 0xFFFFFFFF)
                    ^ epoch_salt
                )
                if permille >= 1000:
                    delay = (rnd % jnp.uint32(spread + 1)).astype(jnp.int32)
                else:
                    gate = (
                        _mix32(rnd ^ jnp.uint32(0xA511E9B3)) % jnp.uint32(1000)
                    ) < jnp.uint32(permille)
                    delay = jnp.where(
                        gate, 1 + (rnd % jnp.uint32(spread)).astype(jnp.int32), 0
                    )
            else:
                delay = jnp.int32(0)
            delivered = (age >= delay) & (blocked_bit == 0)
            acc = acc | (delivered.astype(jnp.uint32) << jnp.uint32(ring))
        out_ref[wi * 32 : (wi + 1) * 32, :] = acc


@functools.partial(
    jax.jit, static_argnames=("k", "spread", "permille", "interpret", "lanes")
)
def delivery_new_bits_pallas(
    blocked_rows: jnp.ndarray,
    age_kn: jnp.ndarray,
    epoch: jnp.ndarray,
    k: int,
    spread: int,
    permille: int,
    interpret: bool = False,
    lanes: int = _LANES,
) -> jnp.ndarray:
    """Fused delivery pass: ``new_bits[w*32, n]`` from packed rx-block rows.

    blocked_rows: [w*k, n] uint32 — row wi*k+ring = the wi-th cohort word of
    ring's per-slot block bits (virtual_cluster._edge_masks layout).
    age_kn: [k, n] int32 rounds since each edge fired (negative = unfired).
    epoch: [1] uint32 configuration epoch (salts the delay draws).
    Returns all w*32 cohort lanes; callers slice [:c]. Slots are padded to
    the ``lanes``-wide tile internally (padding ages are hugely negative,
    so the pad lanes deliver nothing). ``lanes`` (multiple of 128) sets the
    per-grid-step tile width — wider tiles amortize grid overhead at large
    N; outputs are bit-identical across widths.
    """
    if lanes % _LANES or lanes <= 0:
        raise ValueError(f"lanes must be a positive multiple of {_LANES}: {lanes}")
    wk, n = blocked_rows.shape
    w = wk // k
    n_pad = (-n) % lanes
    if n_pad:
        blocked_rows = jnp.pad(blocked_rows, ((0, 0), (0, n_pad)))
        age_kn = jnp.pad(age_kn, ((0, 0), (0, n_pad)), constant_values=-(1 << 29))
    total = n + n_pad
    grid = (total // lanes,)
    out = pl.pallas_call(
        functools.partial(_delivery_kernel, k, w, spread, permille, lanes),
        out_shape=jax.ShapeDtypeStruct((w * 32, total), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((wk, lanes), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, lanes), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (w * 32, lanes), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(blocked_rows, age_kn, epoch.astype(jnp.uint32))
    return out[:, :n]


@functools.lru_cache(maxsize=1)
def pallas_usable() -> bool:
    """Smoke-test the Mosaic kernel once on tiny shapes: True iff the pallas
    path compiles, runs, and classifies correctly on the current backend.

    Callers that embed ``use_pallas=True`` inside a LARGER jitted program
    (the engine) cannot catch a Mosaic failure at their own compile time, so
    they should consult this before opting in — the kernel is strictly an
    optimization over the bit-identical jnp core. (``python -O`` safe: the
    wrong-result check is a real branch, not an assert.)"""
    if not (_HAS_PALLAS and jax.default_backend() == "tpu"):
        return False
    try:
        # The engine's use_pallas flag gates the DELIVERY kernel, so fitness
        # is the delivery kernel's alone. Smoke:
        # k=3, one cohort word, all edges fired at round 0 and unblocked —
        # every bit must deliver at age >= spread.
        k = 3
        blocked = jnp.zeros((k, 256), jnp.uint32)
        age = jnp.full((k, 256), 9, jnp.int32)
        bits = delivery_new_bits_pallas(
            blocked, age, jnp.zeros((1,), jnp.uint32), k, 2, 1000
        )
        if int(bits[0, 0]) != (1 << k) - 1:
            raise RuntimeError("delivery kernel missed matured alerts")
        return True
    except Exception:  # noqa: BLE001 — any kernel failure means "don't use it"
        return False


def reports_matrix_to_bits(reports: jnp.ndarray) -> jnp.ndarray:
    """[..., n, k] bool report matrix -> [..., n] uint32 bitmasks."""
    k = reports.shape[-1]
    weights = (jnp.uint32(1) << jnp.arange(k, dtype=jnp.uint32))
    return jnp.sum(reports.astype(jnp.uint32) * weights, axis=-1, dtype=jnp.uint32)


def bits_to_reports_matrix(bits: jnp.ndarray, k: int) -> jnp.ndarray:
    """[..., n] uint32 bitmasks -> [..., n, k] bool report matrix."""
    shifts = jnp.arange(k, dtype=jnp.uint32)
    return ((bits[..., None] >> shifts) & 1).astype(bool)
