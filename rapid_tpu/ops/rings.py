"""Device kernels for the K-ring expander topology.

The reference maintains K TreeSets and answers successor/predecessor queries
one node at a time (``MembershipView.java:234-322``). On TPU the whole
topology is one batched computation: N node slots carry K seeded 64-bit hash
keys (as uint32 hi/lo lanes); for each ring we argsort the alive slots and
read every node's observer (ring successor) and subject (ring predecessor) in
one gather. Dynamic membership is a padded ``alive`` mask — adds/deletes flip
mask bits and the next ``ring_topology`` call re-derives the permutations,
keeping all shapes static for XLA.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from rapid_tpu.ops.hashing import lex_argsort
from rapid_tpu.protocol.view import ring_key


class RingTopology(NamedTuple):
    """Batched observer/subject tables for all K rings.

    obs_idx[k, i]  = slot of the observer (ring-k successor) of slot i, or -1
    subj_idx[k, i] = slot of the subject (ring-k predecessor) of slot i, or -1
    order[k, p]    = slot at sorted ring position p (alive slots first)

    Entries are -1 for dead slots and when fewer than 2 nodes are alive
    (matching MembershipView.java:240-242's empty observer list).
    """

    obs_idx: jnp.ndarray
    subj_idx: jnp.ndarray
    order: jnp.ndarray


def endpoint_ring_keys(endpoints, k: int):
    """Host-side: K seeded 64-bit ring keys per endpoint, split into uint32
    lanes of shape [K, N]. Uses the exact key function of the host view so
    device and host topologies agree bit-for-bit. The native C library (when
    built) computes the whole batch at memory bandwidth; the Python fallback
    is bit-identical."""
    from rapid_tpu.utils._native import native_ring_keys_batch

    keys = native_ring_keys_batch(
        [ep.hostname.encode("utf-8") for ep in endpoints],
        [ep.port for ep in endpoints],
        k,
    )
    if keys is None:
        keys = np.asarray(
            [[ring_key(ep, seed) for ep in endpoints] for seed in range(k)],
            dtype=np.uint64,
        )
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return jnp.asarray(hi), jnp.asarray(lo)


def _ring_topology_single(key_hi, key_lo, alive):
    """One ring: returns (obs_idx[N], subj_idx[N], order[N])."""
    n = key_hi.shape[0]
    dead = (~alive).astype(jnp.uint32)
    order = lex_argsort((dead, key_hi, key_lo))  # alive slots first, by 64-bit key
    n_alive = jnp.sum(alive.astype(jnp.int32))

    positions = jnp.arange(n, dtype=jnp.int32)
    in_ring = positions < n_alive
    succ_pos = jnp.where(positions + 1 >= n_alive, 0, positions + 1)
    pred_pos = jnp.where(positions - 1 < 0, n_alive - 1, positions - 1)
    valid = in_ring & (n_alive >= 2)
    succ_slot = jnp.where(valid, order[succ_pos], -1)
    pred_slot = jnp.where(valid, order[pred_pos], -1)

    obs_idx = jnp.full((n,), -1, dtype=jnp.int32).at[order].set(succ_slot)
    subj_idx = jnp.full((n,), -1, dtype=jnp.int32).at[order].set(pred_slot)
    return obs_idx, subj_idx, order.astype(jnp.int32)


@jax.jit
def ring_topology(key_hi: jnp.ndarray, key_lo: jnp.ndarray, alive: jnp.ndarray) -> RingTopology:
    """All K rings at once: key_hi/key_lo are [K, N] uint32, alive is [N] bool."""
    obs, subj, order = jax.vmap(_ring_topology_single, in_axes=(0, 0, None))(
        key_hi, key_lo, alive
    )
    return RingTopology(obs_idx=obs, subj_idx=subj, order=order)


@jax.jit
def predecessor_of_keys(
    key_hi: jnp.ndarray,
    key_lo: jnp.ndarray,
    alive: jnp.ndarray,
    query_hi: jnp.ndarray,
    query_lo: jnp.ndarray,
) -> jnp.ndarray:
    """Expected observers of joiners: for each query key (one per ring per
    joiner), the alive slot that precedes it on that ring — the semantics of
    ``getExpectedObserversOf`` (MembershipView.java:292-322).

    key_hi/key_lo: [K, N]; query_hi/query_lo: [K, J]. Returns [K, J] slot
    indices (-1 when no node is alive). Rank is computed by a masked
    comparison sum — O(N·J) elementwise work that maps cleanly onto sharded N.
    """

    n_alive = jnp.sum(alive.astype(jnp.int32))
    dead = (~alive).astype(jnp.uint32)

    def one_ring(khi, klo, qhi, qlo):
        order = lex_argsort((dead, khi, klo))

        def one_query(h, low):
            less = (khi < h) | ((khi == h) & (klo < low))
            rank = jnp.sum((less & alive).astype(jnp.int32))
            # Predecessor = alive node at sorted position (rank - 1) mod n_alive.
            pred_pos = jnp.where(rank - 1 < 0, n_alive - 1, rank - 1)
            return jnp.where(n_alive >= 1, order[pred_pos], -1).astype(jnp.int32)

        return jax.vmap(one_query)(qhi, qlo)

    return jax.vmap(one_ring)(key_hi, key_lo, query_hi, query_lo)
