"""Device kernels for the K-ring expander topology.

The reference maintains K TreeSets and answers successor/predecessor queries
one node at a time (``MembershipView.java:234-322``). On TPU the whole
topology is one batched computation: N node slots carry K seeded 64-bit hash
keys (as uint32 hi/lo lanes); for each ring we argsort the alive slots and
read every node's observer (ring successor) and subject (ring predecessor) in
one gather. Dynamic membership is a padded ``alive`` mask — adds/deletes flip
mask bits and the next ``ring_topology`` call re-derives the permutations,
keeping all shapes static for XLA.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from rapid_tpu.ops.hashing import lex_argsort
from rapid_tpu.protocol.view import ring_key


class RingTopology(NamedTuple):
    """Batched observer/subject tables for all K rings.

    obs_idx[k, i]  = slot of the observer (ring-k successor) of slot i, or -1
    subj_idx[k, i] = slot of the subject (ring-k predecessor) of slot i, or -1
    order[k, p]    = slot at sorted ring position p (alive slots first)

    Entries are -1 for dead slots and when fewer than 2 nodes are alive
    (matching MembershipView.java:240-242's empty observer list).
    """

    obs_idx: jnp.ndarray
    subj_idx: jnp.ndarray
    order: jnp.ndarray


def endpoint_ring_keys(endpoints, k: int, topology: str = "native"):
    """Host-side: K seeded 64-bit ring keys per endpoint, split into uint32
    lanes of shape [K, N]. Uses the exact key function of the host view so
    device and host topologies agree bit-for-bit. The native C library (when
    built) computes the whole batch at memory bandwidth; the Python fallback
    is bit-identical.

    Native topology only: the u64 keyspace and unsigned ring order are what
    the device kernels assume. ``TOPOLOGY_JAVA`` views order rings by SIGNED
    4-byte-port hashes (``view.ring_key_java``); feeding those through this
    seam would silently compute divergent ring orders, so it is rejected."""
    if topology != "native":
        raise ValueError(
            f"the device/engine path requires the native topology; got {topology!r} "
            "(java-compat ring order is host-path only)"
        )
    from rapid_tpu.utils._native import native_ring_keys_batch

    keys = native_ring_keys_batch(
        [ep.hostname.encode("utf-8") for ep in endpoints],
        [ep.port for ep in endpoints],
        k,
    )
    if keys is None:
        keys = np.asarray(
            [[ring_key(ep, seed) for ep in endpoints] for seed in range(k)],
            dtype=np.uint64,
        )
    hi = (keys >> np.uint64(32)).astype(np.uint32)
    lo = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return jnp.asarray(hi), jnp.asarray(lo)


def _ring_topology_single(key_hi, key_lo, alive):
    """One ring: returns (obs_idx[N], subj_idx[N], order[N])."""
    n = key_hi.shape[0]
    dead = (~alive).astype(jnp.uint32)
    order = lex_argsort((dead, key_hi, key_lo))  # alive slots first, by 64-bit key
    n_alive = jnp.sum(alive.astype(jnp.int32))

    positions = jnp.arange(n, dtype=jnp.int32)
    in_ring = positions < n_alive
    succ_pos = jnp.where(positions + 1 >= n_alive, 0, positions + 1)
    pred_pos = jnp.where(positions - 1 < 0, n_alive - 1, positions - 1)
    valid = in_ring & (n_alive >= 2)
    succ_slot = jnp.where(valid, order[succ_pos], -1)
    pred_slot = jnp.where(valid, order[pred_pos], -1)

    obs_idx = jnp.full((n,), -1, dtype=jnp.int32).at[order].set(succ_slot)
    subj_idx = jnp.full((n,), -1, dtype=jnp.int32).at[order].set(pred_slot)
    return obs_idx, subj_idx, order.astype(jnp.int32)


@jax.jit
def ring_topology(key_hi: jnp.ndarray, key_lo: jnp.ndarray, alive: jnp.ndarray) -> RingTopology:
    """All K rings at once: key_hi/key_lo are [K, N] uint32, alive is [N] bool."""
    obs, subj, order = jax.vmap(_ring_topology_single, in_axes=(0, 0, None))(
        key_hi, key_lo, alive
    )
    return RingTopology(obs_idx=obs, subj_idx=subj, order=order)


def ring_perms(key_hi: jnp.ndarray, key_lo: jnp.ndarray) -> jnp.ndarray:
    """Static per-ring key-order permutations, [K, N] int32: perm[k, p] is
    the slot at position p of ring k's FIXED key order (aliveness ignored).

    Ring keys never change after slot creation, so this is computed ONCE;
    every later topology query is O(N) scans over it
    (``ring_topology_from_perm``) instead of an O(N log N) re-sort per view
    change — at N=1M the per-view-change K-ring argsort is the single
    largest block of the commit path.
    """
    # lex_argsort already batches over leading axes (it sorts dimension=-1).
    return lex_argsort((jnp.asarray(key_hi), jnp.asarray(key_lo))).astype(jnp.int32)


def _from_perm_single(perm, alive):
    """One ring, sort-free: (obs_idx[N], subj_idx[N], order[N]) from the
    static key order. Successor among alive = next alive position in the
    fixed circular order (suffix-min scan); predecessor = previous
    (prefix-max scan); the alive-first ``order`` is a stable partition
    (rank scans + one scatter). Bit-identical to ``_ring_topology_single``:
    restricting a fixed total order to the alive subset IS the alive
    order, and lex_argsort is stable so dead slots tie-break identically.
    """
    n = perm.shape[0]
    ao = alive[perm]  # alive bit per ring position
    pos = jnp.arange(n, dtype=jnp.int32)
    n_alive = jnp.sum(ao.astype(jnp.int32))

    idx_succ = jnp.where(ao, pos, n)  # sentinel past the end
    suffix_min = jax.lax.cummin(idx_succ, reverse=True)
    first_alive = suffix_min[0]
    nxt = jnp.concatenate([suffix_min[1:], jnp.full((1,), n, dtype=jnp.int32)])
    succ_pos = jnp.where(nxt >= n, first_alive, nxt)  # wrap to ring start

    idx_pred = jnp.where(ao, pos, -1)
    prefix_max = jax.lax.cummax(idx_pred)
    last_alive = prefix_max[-1]
    prv = jnp.concatenate([jnp.full((1,), -1, dtype=jnp.int32), prefix_max[:-1]])
    pred_pos = jnp.where(prv < 0, last_alive, prv)  # wrap to ring end

    valid = ao & (n_alive >= 2)
    succ_slot = jnp.where(valid, perm[jnp.clip(succ_pos, 0, n - 1)], -1)
    pred_slot = jnp.where(valid, perm[jnp.clip(pred_pos, 0, n - 1)], -1)
    # full(-1), not zeros: if perm were ever not a permutation (corrupted
    # state), unwritten entries must read as the documented "no observer"
    # sentinel, never as valid slot 0.
    obs_idx = jnp.full((n,), -1, dtype=jnp.int32).at[perm].set(succ_slot)
    subj_idx = jnp.full((n,), -1, dtype=jnp.int32).at[perm].set(pred_slot)
    return obs_idx, subj_idx, _alive_first_order(perm, alive)


def _alive_first_order(perm, alive):
    """``lex_argsort((dead, keys...))`` without the sort: stable partition
    of the static key order into alive-first via rank scans + one scatter."""
    n = perm.shape[0]
    ao = alive[perm]
    n_alive = jnp.sum(ao.astype(jnp.int32))
    alive_rank = jnp.cumsum(ao.astype(jnp.int32)) - 1
    dead_rank = n_alive + jnp.cumsum((~ao).astype(jnp.int32)) - 1
    return (
        jnp.zeros((n,), dtype=jnp.int32)
        .at[jnp.where(ao, alive_rank, dead_rank)]
        .set(perm)
    )


def ring_topology_from_perm(perm: jnp.ndarray, alive: jnp.ndarray) -> RingTopology:
    """``ring_topology`` without the sort: derive all K rings' topology from
    the static key-order permutations (``ring_perms``) and the current alive
    mask with O(N) scans. Output is bit-identical to ``ring_topology``
    (equivalence pinned in tests/test_ops_rings.py).

    Accepts ``perm`` at ANY integer dtype — the compact engine stores its
    ring_perm at the policy's index width (int8/int16,
    models/state.compaction_policy) and gathers/scatters index with it
    directly; the returned tables are int32 (position arithmetic
    accumulates wide here) and the caller narrows on store."""
    obs, subj, order = jax.vmap(_from_perm_single, in_axes=(0, None))(
        jnp.asarray(perm), jnp.asarray(alive, dtype=bool)
    )
    return RingTopology(obs_idx=obs, subj_idx=subj, order=order)


@jax.jit
def predecessor_of_keys(
    key_hi: jnp.ndarray,
    key_lo: jnp.ndarray,
    alive: jnp.ndarray,
    query_hi: jnp.ndarray,
    query_lo: jnp.ndarray,
    perm: "jnp.ndarray | None" = None,
) -> jnp.ndarray:
    """Expected observers of joiners: for each query key (one per ring per
    joiner), the alive slot that precedes it on that ring — the semantics of
    ``getExpectedObserversOf`` (MembershipView.java:292-322).

    key_hi/key_lo: [K, N]; query_hi/query_lo: [K, J]. Returns [K, J] slot
    indices (-1 when no node is alive). Rank is computed by a masked
    comparison sum — O(N·J) elementwise work that maps cleanly onto sharded N.
    With ``perm`` (the static key-order permutations, ``ring_perms``) the
    alive-first order comes from O(N) partition scans instead of a K-ring
    argsort — this sits inside a bootstrap wave's timed path, where the
    engine passes its ``state.ring_perm``. Results are identical either way.
    """

    n_alive = jnp.sum(alive.astype(jnp.int32))

    if perm is None:
        dead = (~alive).astype(jnp.uint32)
        orders = jax.vmap(lambda h, low: lex_argsort((dead, h, low)))(
            key_hi, key_lo
        )
    else:
        orders = jax.vmap(_alive_first_order, in_axes=(0, None))(perm, alive)

    def one_ring(khi, klo, qhi, qlo, order):
        def one_query(h, low):
            less = (khi < h) | ((khi == h) & (klo < low))
            rank = jnp.sum((less & alive).astype(jnp.int32))
            # Predecessor = alive node at sorted position (rank - 1) mod n_alive.
            pred_pos = jnp.where(rank - 1 < 0, n_alive - 1, rank - 1)
            return jnp.where(n_alive >= 1, order[pred_pos], -1).astype(jnp.int32)

        return jax.vmap(one_query)(qhi, qlo)

    return jax.vmap(one_ring)(key_hi, key_lo, query_hi, query_lo, orders)
