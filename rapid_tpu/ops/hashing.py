"""Device-side hashing & sorting primitives.

JAX runs with 32-bit integers by default (x64 disabled), so 64-bit ring keys
and proposal identities are carried as (hi, lo) uint32 lane pairs. Sorting by
a 64-bit key uses LSD radix composition of stable 32-bit argsorts, which XLA
compiles to efficient on-device sorts.
"""

from __future__ import annotations

import jax.numpy as jnp


def lex_argsort(keys: tuple) -> jnp.ndarray:
    """Stable argsort by a tuple of equal-length integer arrays along the last
    axis, most significant key first. One fused multi-key ``lax.sort`` — a
    single on-device sort instead of one stable pass per key (3x fewer sorts
    on the ring-rebuild hot path)."""
    import jax

    iota = jax.lax.broadcasted_iota(jnp.int32, keys[0].shape, keys[0].ndim - 1)
    # The iota is the last *key*: ties on the real keys break by input index,
    # which equals stable order while letting the backend use an unstable
    # (cheaper) sort network.
    out = jax.lax.sort(
        tuple(keys) + (iota,), dimension=-1, num_keys=len(keys) + 1, is_stable=False
    )
    return out[-1]


def mix32(x: jnp.ndarray) -> jnp.ndarray:
    """A murmur3-style 32-bit finalizer: cheap per-lane avalanche on device."""
    x = jnp.asarray(x, dtype=jnp.uint32)
    x ^= x >> 16
    x = x * jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x = x * jnp.uint32(0xC2B2AE35)
    x ^= x >> 16
    return x


def masked_set_hash(hi: jnp.ndarray, lo: jnp.ndarray, mask: jnp.ndarray) -> tuple:
    """Order-independent 64-bit identity for a *set* of members, given
    per-member (hi, lo) identity lanes and a membership mask.

    Commutative (XOR + sum lanes) so it shards over the N axis with psum and
    never depends on device-side ordering. Used for proposal identities and
    engine configuration ids (host configuration ids use the sequential fold
    in rapid_tpu.protocol.view for reference parity).
    """
    mask = mask.astype(jnp.uint32)
    mixed_hi = mix32(hi ^ jnp.uint32(0x9E3779B9)) * mask
    mixed_lo = mix32(lo ^ jnp.uint32(0x85EBCA77)) * mask
    # Wrapping-sum folds (sum-of-hashes multiset hash): commutative, so the
    # sharded path can reduce them with a plain psum over the N axis.
    h1 = jnp.sum(mixed_hi, dtype=jnp.uint32) + jnp.sum(mask, dtype=jnp.uint32)
    h2 = jnp.sum(mixed_lo, dtype=jnp.uint32)
    return mix32(h1), mix32(h2 + h1)
