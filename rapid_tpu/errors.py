"""Exception types (reference: Cluster.java:483-502, MembershipView.java:502-519)."""

from __future__ import annotations


class RapidTpuError(Exception):
    """Base class for all framework errors."""


class NodeAlreadyInRingError(RapidTpuError):
    pass


class NodeNotInRingError(RapidTpuError):
    pass


class UUIDAlreadySeenError(RapidTpuError):
    pass


class JoinError(RapidTpuError):
    """Terminal join failure after all retries (Cluster.java:483-487)."""


class JoinPhaseOneError(RapidTpuError):
    """Seed rejected phase 1; carries the response for retry logic (Cluster.java:489-499)."""

    def __init__(self, join_response) -> None:
        super().__init__(f"phase-1 rejected: {join_response.status_code.name}")
        self.join_response = join_response


class JoinPhaseTwoError(RapidTpuError):
    """No observer returned a valid phase-2 confirmation (Cluster.java:501-502)."""


class ShuttingDownError(RapidTpuError):
    """Messaging client used after shutdown (GrpcClient.java:217-221)."""
