"""Multi-host deployment: the DCN story.

The reference scales across machines with gRPC over the datacenter network
(SURVEY §5.8). The TPU-native equivalent: each TPU host process joins a
``jax.distributed`` job; the global mesh spans every chip in the slice, the
engine's N axis shards across it, and XLA routes the protocol's reductions
over ICI within a host/pod and DCN between them — no NCCL/MPI analog to
manage.

Single-host (and CPU dry-run) paths work without initialization; this module
is the thin entry for real multi-host jobs. It is exercised by real
``jax.distributed`` jobs in ``tests/test_multihost.py`` — a single-process
job and a true two-process multi-controller run (virtual CPU devices, one
global mesh, cross-process collectives); the driver's ``dryrun_multichip``
additionally validates the sharded program on a virtual mesh.
"""

from __future__ import annotations

from typing import Optional

import jax

from rapid_tpu.parallel.mesh import make_mesh, shard_pytree


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this process to a multi-host JAX job. On managed TPU slices all
    arguments auto-detect; pass them explicitly elsewhere
    (coordinator '<host>:<port>')."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """A 1-D 'nodes' mesh over every device in the job (all hosts). Use with
    rapid_tpu.parallel.make_sharded_step; jax.jit handles cross-host
    collectives transparently for globally-sharded arrays."""
    return make_mesh(jax.devices())


def local_device_count() -> int:
    return jax.local_device_count()


def is_coordinator() -> bool:
    return jax.process_index() == 0


# Multi-controller-safe placement lives in mesh.py (one mechanism for both
# single-process and global meshes); re-exported here as the multi-host
# entry point's natural vocabulary.
shard_host_pytree = shard_pytree
