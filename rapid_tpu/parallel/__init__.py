from rapid_tpu.parallel.mesh import (
    NODE_AXIS,
    fault_shardings,
    make_mesh,
    make_sharded_step,
    shard_faults,
    shard_state,
    state_shardings,
)

__all__ = [
    "NODE_AXIS",
    "fault_shardings",
    "make_mesh",
    "make_sharded_step",
    "shard_faults",
    "shard_state",
    "state_shardings",
]
