"""Device-mesh sharding for the virtual-cluster engine.

Two scale axes, one rule table. The engine's state is data-parallel over N
(virtual members) AND over C (receiver cohorts): every per-slot array
partitions on its N dimension over the ``nodes`` mesh axis, and — since the
cohort-meshed refactor — every cohort-dimensioned array partitions on its C
dimension over the ``cohort`` mesh axis. ``make_mesh()`` builds the classic
1-D ``('nodes',)`` mesh; ``make_mesh(shape=(dc, dn))`` builds the 2-D
``('cohort', 'nodes')`` mesh the 1M+ headline benchmark targets. One
regex-driven rule table (:data:`PARTITION_RULES`, the SNIPPETS [1]
``match_partition_rules`` pattern keyed on pytree field names) produces the
sharding tables for EITHER mesh: an axis name absent from the target mesh
drops to replicated on that axis, so the 1-D mesh keeps its exact
historical layout and a new ``EngineState`` leaf that matches no rule is a
hard error — it can never silently replicate.

All of the engine's global reductions (watermark tallies, vote counts, set
hashes) are sums/anys over N or cross-cohort decision reductions over C,
which XLA lowers to psum over ICI; ring topology is re-derived only on view
changes — sort-free O(N) scans over the static key-order perms
(``ring_topology_from_perm``; the one argsort runs at state creation) — and
its cross-shard permutation gathers are the one collective-heavy op (XLA
inserts what it needs). This is not just a docstring claim:
``tools/collective_audit.py`` classifies every collective in the compiled
HLO (EVALUATION.md §3c), ``tests/test_parallel.py`` pins the invariants,
and the ``device_program`` gate freezes both the 1-D and the 2-D compiled
programs' collective/donation budgets into ``tools/analysis/hlo.lock.json``
— the convergence hot loop's unconditional traffic stays reduce-class, with
[c,n]-scale gathers confined to lax.cond branches.

This is the TPU equivalent of the reference's scale story (§ SURVEY 5.7):
the reference keeps per-node load O(K) as N grows; here the whole cluster's
protocol state is data-parallel over the mesh, and per-device cohort state
shrinks by the cohort-axis size instead of replicating.

Compaction: the rule table is keyed on FIELD NAMES, so the config-derived
narrow layout (``EngineConfig.compact=1`` — models/state.compaction_policy)
and the opt-in bit-packed mask representation (``state.pack_masks``: [n] ->
[n/8] uint8 along the slot axis, ranks preserved) shard through the SAME
rules with no second table: per-device bytes shrink by the dtype ratio on
top of the 1/dn axis split. :func:`shard_pytree`'s up-front divisibility
validation covers the packed shapes too — a packed [n/8] lane that does
not divide the node axis raises the same named ``ShardingShapeError``
(pack after padding: ``pad_to_multiple(n, 8 * node_devices)``).
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rapid_tpu.models.state import (
    EngineConfig,
    EngineState,
    FaultInputs,
    TelemetryLanes,
    TraceRing,
)
from rapid_tpu.models.virtual_cluster import (
    engine_step_impl,
    engine_step_telem_impl,
    engine_step_trace_impl,
    run_until_membership_impl,
    run_until_membership_telem_impl,
)

NODE_AXIS = "nodes"
COHORT_AXIS = "cohort"
#: The multi-tenant batch axis (rapid_tpu/tenancy): a LEADING [t] dimension
#: stacked over the whole engine pytree, sharded fully parallel — tenants
#: never communicate, so no collective may ever carry the tenant axis in
#: its replica groups (the device_program gate freezes that budget).
TENANT_AXIS = "tenant"

#: Spec tuples are PartitionSpec entries by position: an axis name, or None
#: (that array dimension is not meshed). Empty tuple = fully replicated.
Spec = Tuple[Optional[str], ...]


class ShardingShapeError(ValueError):
    """A pytree leaf's shape does not divide the mesh axes it shards over
    (or its sharding targets a different mesh). Raised by
    :func:`shard_pytree` with the leaf and axis named — XLA's own error for
    the same condition is an opaque HLO sharding failure deep inside
    ``make_array_from_callback``."""


#: Regex-driven partition rules over the engine pytree field names
#: (``EngineState`` + ``FaultInputs`` share one namespace — no field name
#: collides). First match wins; matching is ``re.fullmatch`` so a rule can
#: never accidentally claim a superstring field. The ``sharding`` analyzer
#: family lint-checks this table: every state/fault array leaf must match a
#: rule, a rule matching no leaf is dead, and a fully-replicating rule must
#: justify itself with ``# replicated-ok: <reason>`` on its line.
PARTITION_RULES: Tuple[Tuple[str, Spec], ...] = (
    # [k, n] ring/key/topology tables: slots on the last axis.
    (r"key_hi|key_lo|ring_perm|obs_idx|subj_idx|inval_obs", (None, NODE_AXIS)),
    # [n, k] per-edge failure-detector state: slots on the first axis.
    (r"fd_count|fd_hist|fd_fired|fire_round|probe_fail", (NODE_AXIS, None)),
    # [c] cohort lanes (watermark flags + proposal-id lanes): sharded over
    # the cohort mesh axis — these replicated on every device before the
    # cohort axis was meshed.
    (r"seen_down|announced|prop_hi|prop_lo", (COHORT_AXIS,)),
    # [c, n] cohort-by-slot watermark/delivery state: both axes meshed.
    (r"report_bits|released|prop_mask|rx_block", (COHORT_AXIS, NODE_AXIS)),
    # [n] per-slot lanes (identity, membership, votes, classic-Paxos
    # acceptor state, fault masks).
    (
        r"id_hi|id_lo|alive|join_pending|cohort_of|vote_hi|vote_lo"
        r"|vote_valid|cp_rnd_r|cp_rnd_i|cp_vrnd_r|cp_vrnd_i|cp_vval_src"
        r"|retired|crashed",
        (NODE_AXIS,),
    ),
    (
        r"config_epoch|config_hi|config_lo|n_members|rounds_undecided"
        r"|classic_epoch|round_idx",
        (),  # replicated-ok: per-configuration scalar lanes
    ),
    # Telemetry plane (models/state.TelemetryLanes): the [c, n] activity and
    # invalidation masks shard exactly like the watermark state they
    # observe; the [c] proposal counter rides the cohort axis.
    (r"tl_active|tl_invalidated", (COHORT_AXIS, NODE_AXIS)),
    (r"tl_proposals", (COHORT_AXIS,)),
    (
        r"tl_rounds|tl_alerts|tl_tally_sum|tl_fast_decisions"
        r"|tl_classic_decisions|tl_conflict_rounds|tl_undecided_hist",
        (),  # replicated-ok: per-engine scalar counters + the 8-bucket histogram
    ),
    # Round-trace ring (models/state.TraceRing): every lane is a per-round
    # scalar record stretched over the [R] ring axis (no n/c dimension to
    # shard) plus the cursor/wrap scalars.
    (
        r"tr_round|tr_epoch|tr_active|tr_alerts|tr_proposals|tr_tally"
        r"|tr_path|tr_conflict|tr_undecided|tr_cursor|tr_wraps",
        (),  # replicated-ok: [R]-ring per-round scalar records + cursor/wrap counters
    ),
)


def make_mesh(
    devices: Optional[Sequence] = None,
    shape: Optional[Tuple[int, ...]] = None,
) -> Mesh:
    """The engine device mesh: 1-D ``('nodes',)`` by default, 2-D
    ``('cohort', 'nodes')`` when ``shape=(cohort_devices, node_devices)`` is
    given, or 3-D ``('tenant', 'cohort', 'nodes')`` when
    ``shape=(tenant_devices, cohort_devices, node_devices)`` is given (the
    multi-tenant fleet mesh — rapid_tpu/tenancy). The shape product must
    equal the device count."""
    devices = list(devices) if devices is not None else jax.devices()
    if shape is None:
        return Mesh(np.array(devices), (NODE_AXIS,))
    if len(shape) == 2:
        axis_names: Tuple[str, ...] = (COHORT_AXIS, NODE_AXIS)
    elif len(shape) == 3:
        axis_names = (TENANT_AXIS, COHORT_AXIS, NODE_AXIS)
    else:
        raise ValueError(
            f"mesh shape must be (cohort, nodes) or (tenant, cohort, "
            f"nodes), got {shape}"
        )
    if any(d < 1 for d in shape):
        raise ValueError(f"mesh shape must be positive, got {shape}")
    total = 1
    for d in shape:
        total *= d
    if total != len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {total} devices, got {len(devices)}"
        )
    return Mesh(np.array(devices).reshape(shape), axis_names)


def match_partition_rules(
    rules: Sequence[Tuple[str, Spec]], fields: Sequence[str]
) -> Dict[str, Spec]:
    """field name -> spec via the first rule whose regex fullmatches — the
    SNIPPETS [1] ``match_partition_rules`` pattern, keyed on NamedTuple
    field names instead of flax parameter paths. Raises on an uncovered
    field: a new engine-state leaf must be placed in the table before it
    can shard (silent replication of [n]- or [c,n]-scale state is exactly
    the failure mode this table exists to prevent)."""
    out: Dict[str, Spec] = {}
    for name in fields:
        for pattern, spec in rules:
            if re.fullmatch(pattern, name):
                out[name] = spec
                break
        else:
            raise ValueError(
                f"no partition rule matches engine leaf {name!r} — add it "
                f"to rapid_tpu.parallel.mesh.PARTITION_RULES"
            )
    return out


def _resolve_spec(spec: Spec, mesh: Mesh) -> P:
    """A rule spec as a PartitionSpec on ``mesh``: axis names the mesh does
    not carry drop to None (the 1-D ``('nodes',)`` mesh replicates the
    cohort dimension, exactly the pre-2-D layout)."""
    return P(*(ax if ax is None or ax in mesh.axis_names else None for ax in spec))


def _shardings_for(cls, mesh: Mesh):
    specs = match_partition_rules(PARTITION_RULES, cls._fields)
    return cls(
        **{
            field: NamedSharding(mesh, _resolve_spec(specs[field], mesh))
            for field in cls._fields
        }
    )


def state_shardings(mesh: Mesh) -> EngineState:
    """A NamedSharding pytree matching EngineState, built from
    :data:`PARTITION_RULES` for the given 1-D or 2-D mesh."""
    return _shardings_for(EngineState, mesh)


def fault_shardings(mesh: Mesh) -> FaultInputs:
    return _shardings_for(FaultInputs, mesh)


def telemetry_shardings(mesh: Mesh) -> TelemetryLanes:
    """NamedShardings for the telemetry lanes — the SAME rule table (the
    ``tl_`` rules), so the plane shards wherever the state it observes
    shards."""
    return _shardings_for(TelemetryLanes, mesh)


def trace_shardings(mesh: Mesh) -> TraceRing:
    """NamedShardings for the round-trace ring — the SAME rule table (the
    ``tr_`` rules): ring lanes replicate (per-round scalars, no meshed
    dimension), so the ring never adds cross-shard traffic to a round."""
    return _shardings_for(TraceRing, mesh)


def _fleet_shardings_for(cls, mesh: Mesh):
    """The tenant-stacked sharding table: the SAME rule table, with the
    leading ``[t]`` axis of every stacked leaf sharded on ``'tenant'`` and
    the existing rules unchanged underneath — a scalar lane becomes a [t]
    array on 'tenant', a [c, n] leaf becomes [t, c, n] on ('tenant',
    'cohort', 'nodes'). There is deliberately NO second rule table: a leaf
    uncovered by :data:`PARTITION_RULES` is exactly as hard an error for
    the fleet as for a single cluster."""
    specs = match_partition_rules(PARTITION_RULES, cls._fields)
    return cls(
        **{
            field: NamedSharding(
                mesh, _resolve_spec((TENANT_AXIS, *specs[field]), mesh)
            )
            for field in cls._fields
        }
    )


def fleet_state_shardings(mesh: Mesh) -> EngineState:
    """NamedShardings for a tenant-STACKED EngineState ([t, ...] leaves)."""
    return _fleet_shardings_for(EngineState, mesh)


def fleet_fault_shardings(mesh: Mesh) -> FaultInputs:
    return _fleet_shardings_for(FaultInputs, mesh)


def fleet_telemetry_shardings(mesh: Mesh) -> TelemetryLanes:
    """NamedShardings for tenant-STACKED telemetry lanes ([t, ...])."""
    return _fleet_shardings_for(TelemetryLanes, mesh)


def fleet_trace_shardings(mesh: Mesh) -> TraceRing:
    """NamedShardings for tenant-STACKED trace rings ([t, ...]): the tenant
    axis shards, the ring lanes replicate within a tenant block."""
    return _fleet_shardings_for(TraceRing, mesh)


def shard_fleet_state(state: EngineState, mesh: Mesh) -> EngineState:
    """Place a tenant-stacked state onto a ``('tenant', 'cohort', 'nodes')``
    mesh. A tenant count that does not divide the tenant axis raises
    :class:`ShardingShapeError` naming the leaf and ``pad_to_multiple``
    (pad the fleet with idle tenants — an all-dead spare cluster steps for
    free)."""
    return shard_pytree(state, fleet_state_shardings(mesh), mesh=mesh)


def shard_fleet_faults(faults: FaultInputs, mesh: Mesh) -> FaultInputs:
    return shard_pytree(faults, fleet_fault_shardings(mesh), mesh=mesh)


def pad_to_multiple(value: int, multiple: int) -> int:
    """Smallest count >= ``value`` divisible by ``multiple`` — size N slots
    (or C cohorts) so they divide a mesh axis: ``n_slots=pad_to_multiple(n,
    mesh.shape[NODE_AXIS])`` (spare slots stay dead until a join wave uses
    them; spare cohorts simply receive no members)."""
    if multiple < 1 or value < 0:
        raise ValueError(f"pad_to_multiple({value}, {multiple})")
    return ((value + multiple - 1) // multiple) * multiple


def _validate_leaf(label: str, shape: Tuple[int, ...], sharding: NamedSharding) -> None:
    spec = sharding.spec
    for dim, axes in enumerate(spec):
        if axes is None:
            continue
        names = (axes,) if isinstance(axes, str) else tuple(axes)
        size = 1
        for name in names:
            size *= dict(zip(sharding.mesh.axis_names, sharding.mesh.devices.shape))[
                name
            ]
        if dim >= len(shape) or shape[dim] % size:
            got = shape[dim] if dim < len(shape) else "<missing>"
            raise ShardingShapeError(
                f"leaf {label} shape {tuple(shape)}: dimension {dim} "
                f"(= {got}) does not divide mesh axis {'*'.join(names)} "
                f"(size {size}) — pad it to "
                f"pad_to_multiple({got}, {size}) slots (see "
                f"rapid_tpu.parallel.mesh.pad_to_multiple)"
            )


def shard_pytree(tree, shardings, mesh: Optional[Mesh] = None):
    """Place host-computed arrays onto a mesh — single-process OR global
    (multi-controller). ``jax.device_put`` only targets addressable devices,
    so every leaf is assembled via ``jax.make_array_from_callback``: each
    process supplies exactly its addressable shards. In a multi-controller
    job this requires every process to have computed identical host values
    (deterministic seeds) — the standard multi-controller contract.

    ``shardings`` leaves are NamedShardings, or bare PartitionSpecs when an
    explicit ``mesh`` is passed. Every leaf is validated up front: its
    shape must divide the mesh axes it shards over, and (when ``mesh`` is
    given) its sharding must live on that mesh — violations raise
    :class:`ShardingShapeError` naming the leaf and the axis instead of
    XLA's opaque per-shard shape mismatch."""

    def place(path, x, sharding):
        x = np.asarray(x)
        if isinstance(sharding, P):
            if mesh is None:
                raise ShardingShapeError(
                    f"leaf {jax.tree_util.keystr(path)}: a bare "
                    f"PartitionSpec needs an explicit mesh= argument"
                )
            sharding = NamedSharding(mesh, sharding)
        if mesh is not None and sharding.mesh != mesh:
            raise ShardingShapeError(
                f"leaf {jax.tree_util.keystr(path)}: sharding targets mesh "
                f"{sharding.mesh.axis_names}{sharding.mesh.devices.shape}, "
                f"not the requested {mesh.axis_names}{mesh.devices.shape}"
            )
        _validate_leaf(jax.tree_util.keystr(path), x.shape, sharding)
        return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])

    return jax.tree_util.tree_map_with_path(place, tree, shardings)


def shard_state(state: EngineState, mesh: Mesh) -> EngineState:
    """Place an existing (host/single-device) state onto the mesh."""
    return shard_pytree(state, state_shardings(mesh), mesh=mesh)


def shard_faults(faults: FaultInputs, mesh: Mesh) -> FaultInputs:
    return shard_pytree(faults, fault_shardings(mesh), mesh=mesh)


def make_sharded_step(cfg: EngineConfig, mesh: Mesh):
    """jit the engine step with explicit in/out shardings over ``mesh``
    (1-D or 2-D).

    Output events replicate (they are scalars plus the [n] winner mask, which
    stays sharded).
    """
    st_sh = state_shardings(mesh)
    ft_sh = fault_shardings(mesh)

    return jax.jit(
        lambda state, faults: engine_step_impl(cfg, state, faults),
        in_shardings=(st_sh, ft_sh),
        out_shardings=None,  # let XLA propagate; state stays mesh-sharded
        donate_argnums=(0,),
    )


def make_sharded_wave(cfg: EngineConfig, mesh: Mesh, max_cuts: int = 8):
    """jit the whole-wave convergence loop (``run_until_membership_impl`` —
    multiple view changes in one dispatch) with the mesh's shardings: the
    multi-chip twin of the single-chip bench hot path, and — on the 2-D
    ``('cohort', 'nodes')`` mesh — the 1M+ headline configuration. Returns
    ``wave(state, faults, target, max_steps, min_cuts) ->
    (state, steps, cuts, resolved, sizes)``; the scalar observations and
    the [max_cuts] sizes vector replicate."""
    st_sh = state_shardings(mesh)
    ft_sh = fault_shardings(mesh)

    return jax.jit(
        lambda state, faults, target, max_steps, min_cuts: (
            run_until_membership_impl(
                cfg, state, faults, target, max_steps, max_cuts, min_cuts
            )
        ),
        in_shardings=(st_sh, ft_sh, None, None, None),
        out_shardings=None,  # XLA propagates; state stays mesh-sharded
        donate_argnums=(0,),
    )


def make_sharded_step_telem(cfg: EngineConfig, mesh: Mesh):
    """:func:`make_sharded_step` with the telemetry lanes riding along —
    the audited ``sharded_step_telem`` entrypoint: the plane's lanes shard
    on the same mesh via :func:`telemetry_shardings`, and the HLO lock
    pins that turning them on adds zero hot-loop collectives and zero
    host transfers to the compiled program."""
    st_sh = state_shardings(mesh)
    ft_sh = fault_shardings(mesh)
    tl_sh = telemetry_shardings(mesh)

    return jax.jit(
        lambda state, telem, faults: engine_step_telem_impl(
            cfg, state, telem, faults
        ),
        in_shardings=(st_sh, tl_sh, ft_sh),
        out_shardings=None,  # XLA propagates; state/lanes stay mesh-sharded
        donate_argnums=(0, 1),
    )


def make_sharded_step_trace(cfg: EngineConfig, mesh: Mesh):
    """:func:`make_sharded_step_telem` with the round-trace ring riding
    along — the audited ``step_trace`` program's mesh twin: the ring's
    lanes replicate via :func:`trace_shardings` (per-round scalars carry no
    meshed axis), so trace=R adds zero hot-loop collectives and zero host
    transfers on any mesh."""
    st_sh = state_shardings(mesh)
    ft_sh = fault_shardings(mesh)
    tl_sh = telemetry_shardings(mesh)
    tr_sh = trace_shardings(mesh)

    return jax.jit(
        lambda state, telem, trace, faults: engine_step_trace_impl(
            cfg, state, telem, trace, faults
        ),
        in_shardings=(st_sh, tl_sh, tr_sh, ft_sh),
        out_shardings=None,  # XLA propagates; state/lanes/ring stay mesh-sharded
        donate_argnums=(0, 1, 2),
    )


def make_sharded_wave_telem(cfg: EngineConfig, mesh: Mesh, max_cuts: int = 8):
    """:func:`make_sharded_wave` with telemetry lanes in the convergence
    carry — the audited ``sharded_wave_telem`` entrypoint. Returns
    ``wave(state, telem, faults, target, max_steps, min_cuts) ->
    (state, telem, steps, cuts, resolved, sizes)``."""
    st_sh = state_shardings(mesh)
    ft_sh = fault_shardings(mesh)
    tl_sh = telemetry_shardings(mesh)

    return jax.jit(
        lambda state, telem, faults, target, max_steps, min_cuts: (
            run_until_membership_telem_impl(
                cfg, state, telem, faults, target, max_steps, max_cuts,
                min_cuts,
            )
        ),
        in_shardings=(st_sh, tl_sh, ft_sh, None, None, None),
        out_shardings=None,  # XLA propagates; state/lanes stay mesh-sharded
        donate_argnums=(0, 1),
    )
