"""Device-mesh sharding for the virtual-cluster engine.

Scale axis = N (virtual members), sharded over a 1-D mesh axis ``nodes``:
every per-slot array partitions on its N dimension; ring/cohort axes and
scalars replicate. All of the engine's global reductions (watermark tallies,
vote counts, set hashes) are sums/anys over N, which XLA lowers to psum over
ICI; ring topology is re-derived only on view changes — sort-free O(N)
scans over the static key-order perms (``ring_topology_from_perm``; the
one argsort runs at state creation) — and its cross-shard permutation
gathers are the one collective-heavy op (XLA inserts what it needs). This is
not just a docstring claim: ``tools/collective_audit.py`` classifies every
collective in the compiled HLO (EVALUATION.md §3c), and
``tests/test_parallel.py::test_round_body_collectives_are_reductions_only``
pins the invariants — the convergence hot loop's unconditional traffic is
~1.2 KB of all-reduces per round, with [c,n]-scale gathers confined to
lax.cond branches.

This is the TPU equivalent of the reference's scale story (§ SURVEY 5.7):
the reference keeps per-node load O(K) as N grows; here the whole cluster's
protocol state is data-parallel over N.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rapid_tpu.models.state import EngineConfig, EngineState, FaultInputs
from rapid_tpu.models.virtual_cluster import (
    engine_step_impl,
    run_until_membership_impl,
)

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (NODE_AXIS,))


def state_shardings(mesh: Mesh) -> EngineState:
    """A NamedSharding pytree matching EngineState: shard every N axis."""

    def sh(*spec) -> NamedSharding:
        return NamedSharding(mesh, P(*spec))

    return EngineState(
        key_hi=sh(None, NODE_AXIS),
        key_lo=sh(None, NODE_AXIS),
        ring_perm=sh(None, NODE_AXIS),
        id_hi=sh(NODE_AXIS),
        id_lo=sh(NODE_AXIS),
        alive=sh(NODE_AXIS),
        obs_idx=sh(None, NODE_AXIS),
        subj_idx=sh(None, NODE_AXIS),
        inval_obs=sh(None, NODE_AXIS),
        config_epoch=sh(),  # replicated-ok: per-configuration scalar
        config_hi=sh(),  # replicated-ok: config-id scalar lane
        config_lo=sh(),  # replicated-ok: config-id scalar lane
        n_members=sh(),  # replicated-ok: membership-size scalar
        fd_count=sh(NODE_AXIS, None),
        fd_hist=sh(NODE_AXIS, None),
        fd_fired=sh(NODE_AXIS, None),
        fire_round=sh(NODE_AXIS, None),
        join_pending=sh(NODE_AXIS),
        cohort_of=sh(NODE_AXIS),
        report_bits=sh(None, NODE_AXIS),
        seen_down=sh(),  # replicated-ok: [c] cohort flags; the cohort axis is not meshed
        released=sh(None, NODE_AXIS),
        announced=sh(),  # replicated-ok: [c] cohort flags; the cohort axis is not meshed
        prop_mask=sh(None, NODE_AXIS),
        prop_hi=sh(),  # replicated-ok: [c] proposal-id lanes; cohort axis not meshed
        prop_lo=sh(),  # replicated-ok: [c] proposal-id lanes; cohort axis not meshed
        vote_hi=sh(NODE_AXIS),
        vote_lo=sh(NODE_AXIS),
        vote_valid=sh(NODE_AXIS),
        rounds_undecided=sh(),  # replicated-ok: fallback-timer scalar
        cp_rnd_r=sh(NODE_AXIS),
        cp_rnd_i=sh(NODE_AXIS),
        cp_vrnd_r=sh(NODE_AXIS),
        cp_vrnd_i=sh(NODE_AXIS),
        cp_vval_src=sh(NODE_AXIS),
        classic_epoch=sh(),  # replicated-ok: classic-attempt scalar
        round_idx=sh(),  # replicated-ok: round-counter scalar
        retired=sh(NODE_AXIS),
    )


def fault_shardings(mesh: Mesh) -> FaultInputs:
    def sh(*spec) -> NamedSharding:
        return NamedSharding(mesh, P(*spec))

    return FaultInputs(
        crashed=sh(NODE_AXIS),
        probe_fail=sh(NODE_AXIS, None),
        rx_block=sh(None, NODE_AXIS),
    )


def make_sharded_step(cfg: EngineConfig, mesh: Mesh):
    """jit the engine step with explicit in/out shardings over ``mesh``.

    Output events replicate (they are scalars plus the [n] winner mask, which
    stays sharded).
    """
    st_sh = state_shardings(mesh)
    ft_sh = fault_shardings(mesh)

    return jax.jit(
        lambda state, faults: engine_step_impl(cfg, state, faults),
        in_shardings=(st_sh, ft_sh),
        out_shardings=None,  # let XLA propagate; state stays node-sharded
        donate_argnums=(0,),
    )


def make_sharded_wave(cfg: EngineConfig, mesh: Mesh, max_cuts: int = 8):
    """jit the whole-wave convergence loop (``run_until_membership_impl`` —
    multiple view changes in one dispatch) with node-axis shardings: the
    multi-chip twin of the single-chip bench hot path. Returns
    ``wave(state, faults, target, max_steps, min_cuts) ->
    (state, steps, cuts, resolved, sizes)``; the scalar observations and
    the [max_cuts] sizes vector replicate."""
    st_sh = state_shardings(mesh)
    ft_sh = fault_shardings(mesh)

    return jax.jit(
        lambda state, faults, target, max_steps, min_cuts: (
            run_until_membership_impl(
                cfg, state, faults, target, max_steps, max_cuts, min_cuts
            )
        ),
        in_shardings=(st_sh, ft_sh, None, None, None),
        out_shardings=None,  # XLA propagates; state stays node-sharded
        donate_argnums=(0,),
    )


def shard_pytree(tree, shardings):
    """Place host-computed arrays onto a mesh — single-process OR global
    (multi-controller). ``jax.device_put`` only targets addressable devices,
    so every leaf is assembled via ``jax.make_array_from_callback``: each
    process supplies exactly its addressable shards. In a multi-controller
    job this requires every process to have computed identical host values
    (deterministic seeds) — the standard multi-controller contract."""

    def place(x, sharding):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])

    return jax.tree.map(place, tree, shardings)


def shard_state(state: EngineState, mesh: Mesh) -> EngineState:
    """Place an existing (host/single-device) state onto the mesh."""
    return shard_pytree(state, state_shardings(mesh))


def shard_faults(faults: FaultInputs, mesh: Mesh) -> FaultInputs:
    return shard_pytree(faults, fault_shardings(mesh))
