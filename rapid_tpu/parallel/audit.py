"""Compiled-HLO collective audit for sharded engine programs.

parallel/mesh.py's communication story ("all of the engine's global
reductions lower to psum; the ring argsort is the one collective-heavy op,
and only at view changes") is a claim about what XLA's SPMD partitioner
emits — so it is checked against the compiled artifact itself: parse every
cross-device collective out of ``compiled.as_text()`` and classify it by the
op_name metadata jax records ("…/while/body/…" = convergence hot loop,
"…/cond/…" = lax.cond branch). ``tools/collective_audit.py`` builds the
evidence table with this; ``tests/test_parallel.py`` pins the invariants.
"""

from __future__ import annotations

import re
from typing import Dict, List

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
                "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8}


def _shape_bytes(shape_str: str) -> int:
    """'(u32[64]{0}, …)' or 'u32[2,1024]{0,1}' -> total payload bytes."""
    total = 0
    for dtype, dims in re.findall(r"(\w+)\[([\d,]*)\]", shape_str):
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        total += elems * _DTYPE_BYTES.get(dtype, 4)
    return total


def classify_location(op_name: str) -> str:
    """hot-loop / hot-loop-cond / cond / prologue, from op_name metadata."""
    if "/while/body" in op_name:
        if "/cond/" in op_name.split("/while/body", 1)[1]:
            return "hot-loop-cond"
        return "hot-loop"
    if "/while/cond" in op_name:
        # The while PREDICATE runs unconditionally every round — it is hot
        # loop, not a gated branch (a generic '/cond/' test would exempt it
        # from the invariants).
        return "hot-loop"
    if "/cond/" in op_name:
        return "cond"
    return "prologue"


def source_of(op_name: str) -> str:
    """Human label for the jax op a collective lowered from."""
    markers = (
        ("ring_topology", "view-change topology rebuild"),
        ("classic_attempt", "classic-fallback attempt"),
        ("tally_candidates", "fast-round vote tally"),
        ("cumsum", "classic-fallback attempt"),
        ("reduce_or", "round-body reduction"),
        ("reduce_sum", "round-body reduction"),
        ("reduce_max", "round-body reduction"),
        ("gather", "cross-slot gather"),
        ("sort", "sort"),
        ("reduce", "reduction"),
    )
    for needle, label in markers:
        if needle in op_name:
            return label
    return "other"


def audit_collectives(compiled_text: str, n: int, c: int) -> List[Dict]:
    """One row per collective op in the HLO text: kind, global shape,
    payload bytes, location, source, and scale flags (n_scale = at least
    [n]-proportional payload, cn_scale = at least [c,n]).

    Matches both synchronous ops and the async ``-start`` halves TPU
    compiles emit (``all-reduce-start``/``all-reduce-done`` pairs — the
    ``-done`` half is skipped so pairs are not double-counted)."""
    rows = []
    for line in compiled_text.splitlines():
        m = re.search(
            r"= (\([^)]*\)|\S+?) ("
            + "|".join(COLLECTIVE_KINDS)
            + r")(-start)?\(",
            line,
        )
        if not m:
            continue
        shape, kind = m.group(1), m.group(2)
        op_name_m = re.search(r'op_name="([^"]*)"', line)
        op_name = op_name_m.group(1) if op_name_m else ""
        payload = _shape_bytes(shape)
        rows.append({
            "kind": kind,
            "shape": shape.split("{")[0],
            "bytes": payload,
            "location": classify_location(op_name),
            "source": source_of(op_name),
            "cn_scale": payload >= c * n,
            "n_scale": payload >= n,
        })
    return rows


def collective_violations(rows: List[Dict]) -> Dict[str, List[Dict]]:
    """The two invariants the sharded design guarantees."""
    return {
        # Every round, unconditionally: reductions only — an unconditional
        # gather here would ship O(n)+ bytes per round for no reason.
        "hot_loop_non_reduce": [
            r for r in rows
            if r["location"] == "hot-loop" and r["kind"] != "all-reduce"
        ],
        # [c,n]-sized traffic must be cond-gated (implicit invalidation,
        # classic attempt, view-change re-sort) — never unconditional. The
        # prologue may hold the hoisted [n]-scale edge gathers, nothing
        # [c,n]-scale.
        "unconditional_cn_anywhere": [
            r for r in rows if r["cn_scale"] and "cond" not in r["location"]
        ],
    }
