"""Compiled-HLO collective audit — thin re-export.

The classifier that lived here (collective-kind matching, payload
accounting, the hot-loop/cond/prologue location attribution) grew into
``rapid_tpu.parallel.hlo_facts`` when the ``device_program`` analyzer
family (tools/analysis/device_program.py) started freezing its facts into
``tools/analysis/hlo.lock.json``. This module stays as the compatible
import surface for the existing consumers (``tests/test_parallel.py``,
``tools/collective_audit.py``): same names, one definition, and a plain
package-relative import — no path games, so an installed distribution of
``rapid_tpu`` keeps working without the repo checkout.
"""

from __future__ import annotations

from rapid_tpu.parallel.hlo_facts import (  # noqa: F401 — re-exported
    COLLECTIVE_KINDS,
    DTYPE_BITS,
    audit_collectives,
    classify_location,
    collective_violations,
    payload_class,
    shape_bytes,
    source_of,
)

__all__ = [
    "COLLECTIVE_KINDS",
    "DTYPE_BITS",
    "audit_collectives",
    "classify_location",
    "collective_violations",
    "payload_class",
    "shape_bytes",
    "source_of",
]
