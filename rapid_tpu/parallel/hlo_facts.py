"""Compiled-HLO fact extraction: collectives, transfers, donation aliases.

The canonical home of the classifier that started life as
``rapid_tpu/parallel/audit.py`` (now a thin re-export): pure text parsing
over ``compiled.as_text()``, no jax import, stdlib only — which is why it
lives IN the packaged library (an installed wheel must be able to import
it) while the ``device_program`` analyzer family
(tools/analysis/device_program.py), the evidence-table CLI
(tools/collective_audit.py), and the sharded-engine invariants test
(tests/test_parallel.py) all consume it from here (tools depends on the
library, never the reverse).

Everything here is derived from two pieces of metadata XLA records in the
compiled artifact: the shape string of each op (payload accounting) and the
``op_name`` jax attaches (location attribution — "…/while/body/…" is the
convergence hot loop, "…/cond/…" a lax.cond branch). The module header's
``input_output_alias`` table is the compiled truth about buffer donation:
a ``donate_argnums`` argument either appears there or was dropped.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

#: Host<->device transfer ops: a compiled engine program must not smuggle
#: host round-trips into the dispatch (the whole point of the fused-engine
#: design); any of these appearing is budget-checked against the lock.
TRANSFER_OPS = (
    "infeed",
    "outfeed",
    "send",
    "send-done",
    "recv",
    "recv-done",
)

#: Bits per element by HLO dtype token. Bits, not bytes: the sub-byte
#: dtypes (s4/u4) pack two elements per byte and a byte table would have to
#: lie about them.
DTYPE_BITS = {
    "pred": 8,
    "s4": 4, "u4": 4,
    "s8": 8, "u8": 8, "f8e4m3": 8, "f8e5m2": 8, "f8e4m3fn": 8,
    "f8e4m3b11fnuz": 8, "f8e5m2fnuz": 8, "f8e4m3fnuz": 8,
    "s16": 16, "u16": 16, "bf16": 16, "f16": 16,
    "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64, "c64": 64,
    "c128": 128,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(shape_str: str, unknown: Optional[List[str]] = None) -> int:
    """'(u32[64]{0}, …)' or 'u32[2,1024]{0,1}' -> total payload bytes.

    Handles tuple shapes with nested layout annotations (the ``{0,1}``
    suffixes are not shape tokens and are ignored). A dtype missing from
    ``DTYPE_BITS`` is never silently guessed: it is appended to ``unknown``
    when a list is passed, else raises ``ValueError`` — the analyzer turns
    collected unknowns into findings (``hlo-unknown-dtype``)."""
    total_bits = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        bits = DTYPE_BITS.get(dtype)
        if bits is None:
            if unknown is None:
                raise ValueError(f"unknown HLO dtype {dtype!r} in {shape_str!r}")
            unknown.append(dtype)
            continue
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        total_bits += elems * bits
    return (total_bits + 7) // 8


def shape_operand_bytes(
    shape_str: str, unknown: Optional[List[str]] = None
) -> List[int]:
    """Per-operand payload bytes of a (possibly tuple) shape string.

    A variadic all-reduce carries a tuple shape — ``(u32[64]{0},
    f32[64]{0})`` — and :func:`shape_bytes` prices the whole tuple as one
    sum. This returns one entry per array leaf instead, so callers can
    account BOTH the total payload (sum) and the largest single operand:
    the scaling-class fit must see totals (multi-operand fusion cannot
    hide payload growth inside a tuple) while per-operand sizes keep the
    largest-single-payload classing honest. Unknown dtypes follow the
    :func:`shape_bytes` contract: appended to ``unknown`` when a list is
    passed (the operand is skipped), else ``ValueError``."""
    out: List[int] = []
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        bits = DTYPE_BITS.get(dtype)
        if bits is None:
            if unknown is None:
                raise ValueError(f"unknown HLO dtype {dtype!r} in {shape_str!r}")
            unknown.append(dtype)
            continue
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        out.append((elems * bits + 7) // 8)
    return out


def compiled_cost_analysis(compiled) -> Optional[Dict[str, float]]:
    """Normalized ``compiled.cost_analysis()``: ``{"flops", "bytes_accessed"}``
    floats, or None when the backend exposes neither (never guessed).

    jax versions disagree on the return shape (a dict, or a one-element
    list of dicts per partition) and backends disagree on which keys they
    populate; this folds both to one optional dict keyed by our fact
    names. Duck-typed on the compiled object — no jax import, keeping this
    module stdlib-only."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backends without a cost model raise backend-specific types; absent pricing is the documented None contract, not a wedge
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    for key, fact in (("flops", "flops"), ("bytes accessed", "bytes_accessed")):
        value = ca.get(key)
        if isinstance(value, (int, float)) and value == value and value >= 0:
            out[fact] = float(value)
    return out or None


def entry_parameter_bytes(
    compiled_text: str, unknown: Optional[List[str]] = None
) -> Dict[str, int]:
    """Per-dtype payload bytes of the ENTRY computation's parameters —
    the compiled-artifact proof that a dtype-narrowing policy actually
    landed (a compact engine program's signature carries s8/s16/u8/u16
    argument lanes where the wide oracle carries only s32/u32/pred).

    Parses the ``ENTRY %name (arg: dtype[dims], ...) -> ...`` header line;
    nested computations' parameters (while bodies etc.) are deliberately
    excluded — only the entry signature is the program's argument surface.
    Sub-byte dtypes price at their true bit width via :data:`DTYPE_BITS`."""
    for line in compiled_text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("ENTRY "):
            continue
        head, sep, _tail = stripped.partition(") -> ")
        if not sep:
            continue
        params = head.partition("(")[2]
        out: Dict[str, int] = {}
        for dtype, dims in _SHAPE_RE.findall(params):
            bits = DTYPE_BITS.get(dtype)
            if bits is None:
                if unknown is None:
                    raise ValueError(
                        f"unknown HLO dtype {dtype!r} in ENTRY parameters"
                    )
                unknown.append(dtype)
                continue
            elems = 1
            for d in dims.split(","):
                if d:
                    elems *= int(d)
            out[dtype] = out.get(dtype, 0) + (elems * bits + 7) // 8
        return out
    return {}


def classify_location(op_name: str) -> str:
    """hot-loop / hot-loop-cond / cond / prologue, from op_name metadata.

    Both loop spellings count: the plain ``…/while/body/…`` scope and the
    batched ``…vmap(while)/body/…`` scope the tenant fleet's vmapped loops
    trace under — a fleet hot-loop collective must never pass as prologue.
    """
    for marker in ("/while/body", "vmap(while)/body"):
        if marker in op_name:
            if "/cond/" in op_name.split(marker, 1)[1]:
                return "hot-loop-cond"
            return "hot-loop"
    if "/while/cond" in op_name or "vmap(while)/cond" in op_name:
        # The while PREDICATE runs unconditionally every round — it is hot
        # loop, not a gated branch (a generic '/cond/' test would exempt it
        # from the invariants).
        return "hot-loop"
    if "/cond/" in op_name:
        return "cond"
    return "prologue"


def source_of(op_name: str) -> str:
    """Human label for the jax op a collective lowered from."""
    markers = (
        ("ring_topology", "view-change topology rebuild"),
        ("classic_attempt", "classic-fallback attempt"),
        ("tally_candidates", "fast-round vote tally"),
        ("cumsum", "classic-fallback attempt"),
        ("reduce_or", "round-body reduction"),
        ("reduce_sum", "round-body reduction"),
        ("reduce_max", "round-body reduction"),
        ("gather", "cross-slot gather"),
        ("sort", "sort"),
        ("reduce", "reduction"),
        # Lowering-artifact spellings: GSPMD re-shards around these ops and
        # the resulting collectives inherit their op_name leaf. Naming them
        # keeps the dataflow gate's cost join total — an unnamed source
        # would land in "other" and the sparse-opportunity map could not
        # attribute its payload bytes (dataflow.py joins on these labels).
        ("scatter", "scatter update"),
        ("concatenate", "concatenate"),
        ("dynamic_slice", "dynamic slice"),
        ("squeeze", "reshape"),
        ("slice", "slice"),
    )
    for needle, label in markers:
        if needle in op_name:
            return label
    return "other"


def payload_class(nbytes: int, n: int, c: int) -> str:
    """Scale class of a collective payload at engine shapes: ``cn`` ([c,n]
    or larger), ``n`` (at least [n]-proportional), ``scalar`` otherwise.
    The lockfile freezes the CLASS, not raw bytes, so a benign constant
    tweak does not drift the gate while a scale-class jump always does."""
    if nbytes >= c * n:
        return "cn"
    if nbytes >= n:
        return "n"
    return "scalar"


PAYLOAD_CLASS_RANK = {"scalar": 0, "n": 1, "cn": 2}


def audit_collectives(compiled_text: str, n: int, c: int) -> List[Dict]:
    """One row per collective op in the HLO text: kind, global shape,
    payload bytes, location, source, scale flags (n_scale = at least
    [n]-proportional payload, cn_scale = at least [c,n]), and any unknown
    dtype tokens the payload accounting could not size.

    Matches both synchronous ops and the async ``-start`` halves TPU
    compiles emit (``all-reduce-start``/``all-reduce-done`` pairs — the
    ``-done`` half is skipped so pairs are not double-counted)."""
    rows = []
    for line in compiled_text.splitlines():
        m = re.search(
            r"= (\([^)]*\)|\S+?) ("
            + "|".join(COLLECTIVE_KINDS)
            + r")(-start)?\(",
            line,
        )
        if not m:
            continue
        shape, kind = m.group(1), m.group(2)
        op_name_m = re.search(r'op_name="([^"]*)"', line)
        op_name = op_name_m.group(1) if op_name_m else ""
        unknown: List[str] = []
        operand_bytes = shape_operand_bytes(shape, unknown=unknown)
        payload = sum(operand_bytes)
        rows.append({
            "kind": kind,
            "shape": shape.split("{")[0],
            # "bytes" is the TOTAL payload (sum over tuple operands) —
            # the fact the ladder fit consumes; "largest_operand_bytes"
            # prices the biggest single array so a variadic fusion can
            # neither hide growth in the sum nor in one operand.
            "bytes": payload,
            "operand_bytes": operand_bytes,
            "largest_operand_bytes": max(operand_bytes, default=0),
            "location": classify_location(op_name),
            "source": source_of(op_name),
            "cn_scale": payload >= c * n,
            "n_scale": payload >= n,
            "groups": collective_groups(line),
            "unknown_dtypes": sorted(set(unknown)),
        })
    return rows


#: replica_groups in the explicit list form: {{0,1},{2,3}}.
_RG_LIST_RE = re.compile(r"replica_groups=\{((?:\{[\d,]*\},?)*)\}")
_RG_GROUP_RE = re.compile(r"\{([\d,]*)\}")
#: replica_groups in the iota (v2) form: [4,2]<=[2,2,2]T(0,2,1) — groups =
#: transpose(iota(prod).reshape(reshape_dims), perm).reshape(G, S) rows.
_RG_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?"
)
#: collective-permute carries (source, target) device pairs instead.
_STP_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)*)\}")


def _iota_groups(g: int, s: int, rdims: List[int], perm: List[int]) -> List[List[int]]:
    """Expand the iota replica-group form without numpy (this module is
    stdlib-only): devices = transpose(arange(prod).reshape(rdims), perm)
    flattened row-major, chunked into G groups of S."""
    strides = [0] * len(rdims)
    acc = 1
    for d in range(len(rdims) - 1, -1, -1):
        strides[d] = acc
        acc *= rdims[d]
    shape_t = [rdims[p] for p in perm]
    devices: List[int] = []
    idx_t = [0] * len(shape_t)
    total = acc
    for _ in range(total):
        devices.append(
            sum(idx_t[j] * strides[perm[j]] for j in range(len(perm)))
        )
        for j in range(len(shape_t) - 1, -1, -1):
            idx_t[j] += 1
            if idx_t[j] < shape_t[j]:
                break
            idx_t[j] = 0
    return [devices[i * s : (i + 1) * s] for i in range(g)]


def collective_groups(line: str) -> Optional[List[List[int]]]:
    """The device groups one collective HLO line communicates within:
    ``replica_groups`` (explicit-list or iota form) as group lists, or
    ``source_target_pairs`` (collective-permute) as one two-device group
    per pair. None when the line names neither — which for a partitioned
    module means ALL devices participate (callers must treat None as one
    all-device group, never as "no communication")."""
    m = _RG_IOTA_RE.search(line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        rdims = [int(x) for x in m.group(3).split(",")]
        perm = (
            [int(x) for x in m.group(4).split(",")]
            if m.group(4) else list(range(len(rdims)))
        )
        return _iota_groups(g, s, rdims, perm)
    m = _RG_LIST_RE.search(line)
    if m:
        groups = [
            [int(x) for x in body.split(",") if x]
            for body in _RG_GROUP_RE.findall(m.group(1))
        ]
        # ``replica_groups={}`` is XLA's spelling for ONE group containing
        # every participant — fold it into the None (all-devices) case so
        # it can never read as "no communication".
        return groups or None
    m = _STP_RE.search(line)
    if m:
        return [
            [int(x) for x in body.split(",")]
            for body in _RG_GROUP_RE.findall(m.group(0))
        ]
    return None


def groups_cross_blocks(
    groups: Optional[List[List[int]]], block: int
) -> bool:
    """True when any group spans two device blocks of size ``block`` —
    with the tenant axis leading the mesh, device ids are contiguous per
    tenant slice, so a group containing ids from two blocks is a
    cross-tenant collective. ``None`` groups (all-participants) cross by
    definition whenever more than one block exists."""
    if groups is None:
        return True
    for group in groups:
        if len({device // block for device in group}) > 1:
            return True
    return False


def collective_violations(rows: List[Dict]) -> Dict[str, List[Dict]]:
    """The two invariants the sharded design guarantees."""
    return {
        # Every round, unconditionally: reductions only — an unconditional
        # gather here would ship O(n)+ bytes per round for no reason.
        "hot_loop_non_reduce": [
            r for r in rows
            if r["location"] == "hot-loop" and r["kind"] != "all-reduce"
        ],
        # [c,n]-sized traffic must be cond-gated (implicit invalidation,
        # classic attempt, view-change re-sort) — never unconditional. The
        # prologue may hold the hoisted [n]-scale edge gathers, nothing
        # [c,n]-scale.
        "unconditional_cn_anywhere": [
            r for r in rows if r["cn_scale"] and "cond" not in r["location"]
        ],
    }


def count_transfer_ops(compiled_text: str) -> Dict[str, int]:
    """Host<->device transfer ops per kind (zero entries omitted)."""
    counts: Dict[str, int] = {}
    pattern = re.compile(
        r"= (?:\([^)]*\)|\S+?) (" + "|".join(TRANSFER_OPS) + r")\("
    )
    for line in compiled_text.splitlines():
        m = pattern.search(line)
        if m:
            counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


#: One alias-table entry: ``{output_index}: (param, {param_index}, kind)``.
#: Parsed straight off the ``HloModule`` header line — the entry shape is
#: specific enough that no other header field matches it, which sidesteps
#: brace-balancing the ``input_output_alias={...}`` table (its entries
#: contain ``}, `` themselves).
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(may-alias|must-alias)\)"
)


def input_output_aliases(compiled_text: str) -> List[Tuple[int, str]]:
    """The module header's donation outcomes: one ``(parameter_number,
    alias_kind)`` per output buffer XLA agreed to alias onto an input.
    Empty when nothing was donated — or when every donation was dropped."""
    header = compiled_text.splitlines()[0] if compiled_text else ""
    if "input_output_alias=" not in header:
        return []
    return [
        (int(param), kind)
        for param, kind in _ALIAS_ENTRY_RE.findall(header)
    ]
