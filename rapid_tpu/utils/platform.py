"""Force the live JAX platform despite eager sitecustomize imports.

This build environment reaches its TPU through the experimental ``axon``
plugin: a ``sitecustomize`` module imports jax at interpreter startup, so by
the time user code runs, ``jax.config`` has already captured whatever
``JAX_PLATFORMS`` said at process start. Setting the environment variable
afterwards does nothing; the live config must be updated explicitly, and it
must happen before the first backend initialization.

One helper, one behavior — used by ``tests/conftest.py``, ``bench.py``, and
``__graft_entry__.py`` so a platform-selection fix lands everywhere at once.
"""

from __future__ import annotations

import logging
import os
import re

LOG = logging.getLogger(__name__)

_COUNT_FLAG = "xla_force_host_platform_device_count"


def force_platform(platform: str, n_host_devices: int | None = None) -> bool:
    """Point the live jax config at ``platform`` before any backend exists.

    ``n_host_devices`` (CPU only) requests that many virtual host devices via
    ``XLA_FLAGS``; the flag is read lazily at first backend initialization, so
    setting it post-import still works. Returns True when the config update
    succeeded; on failure (a backend is already live) a warning is logged and
    the caller should verify ``jax.devices()[0].platform`` before trusting the
    process.
    """
    os.environ["JAX_PLATFORMS"] = platform
    if n_host_devices is not None and platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if _COUNT_FLAG in flags:
            # Replace a conflicting count rather than silently keeping it
            # (e.g. inherited --...count=8 when the caller asked for 16).
            flags = re.sub(rf"--{_COUNT_FLAG}=\d+", f"--{_COUNT_FLAG}={n_host_devices}", flags)
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = f"{flags} --{_COUNT_FLAG}={n_host_devices}".strip()
    import jax

    try:
        jax.config.update("jax_platforms", platform)
        return True
    except Exception as exc:  # pragma: no cover  # noqa: BLE001 — backend
        # init failures vary by runtime (RuntimeError, plugin errors); all
        # mean "platform not forced", reported to the caller as False.
        LOG.warning("could not force jax platform %r: %s", platform, exc)
        return False
