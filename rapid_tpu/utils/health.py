"""Per-node health model and its cluster-wide aggregation.

The protocol's internal suspicion machinery (pending cut reports, undecided
proposals, decision catch-up, the wedged-pull escalation) already encodes
"how is this node doing" — this module names those conditions as a small
ordered vocabulary so operators, ``telemetry_snapshot()``, the Prometheus
exposition, and ``tools/clustertop.py`` all speak the same states:

- ``STABLE``      — no membership change in flight; the steady state.
- ``DETECTING``   — edge reports held below the H watermark (a cut is
                    accumulating, or a straggler report is pending).
- ``PROPOSING``   — a cut proposal is announced and consensus is undecided.
- ``CATCHING_UP`` — a decided configuration could not be applied locally;
                    the node is pulling it from peers.
- ``WEDGED``      — the catch-up loop escalated (futile pulls past the
                    threshold) or the node was evicted (KICKED): operator /
                    application intervention is required.

States are severity-ordered; a node in several conditions reports the worst.
``aggregate_health`` folds many nodes' states into one cluster view — the
header of clustertop and the summary a fleet scraper alerts on.
"""

from __future__ import annotations

import enum
from typing import Dict, Iterable, Union


class NodeHealth(enum.Enum):
    """Severity-ordered node health vocabulary (worst last)."""

    STABLE = "stable"
    DETECTING = "detecting"
    PROPOSING = "proposing"
    CATCHING_UP = "catching_up"
    WEDGED = "wedged"

    @property
    def severity(self) -> int:
        return _SEVERITY[self]


_SEVERITY: Dict[NodeHealth, int] = {
    NodeHealth.STABLE: 0,
    NodeHealth.DETECTING: 1,
    NodeHealth.PROPOSING: 2,
    NodeHealth.CATCHING_UP: 3,
    NodeHealth.WEDGED: 4,
}


def parse_health(value: Union[str, NodeHealth, None]) -> NodeHealth:
    """Lenient parse for snapshot JSON: enum value ('stable') or member name
    ('STABLE'); unknown/absent values read as STABLE (an old snapshot
    predating the health model must not render a node as unhealthy)."""
    if isinstance(value, NodeHealth):
        return value
    if isinstance(value, str):
        try:
            return NodeHealth(value.lower())
        except ValueError:
            pass
    return NodeHealth.STABLE


def aggregate_health(
    states: Iterable[Union[str, NodeHealth, None]],
) -> Dict[str, object]:
    """Cluster-wide fold of per-node health states: the worst state present
    (the cluster is only as healthy as its sickest member) plus per-state
    counts — zero-filled over the full vocabulary so consumers see a stable
    shape. An empty input aggregates to STABLE with all-zero counts."""
    counts = {state.value: 0 for state in NodeHealth}
    worst = NodeHealth.STABLE
    for raw in states:
        state = parse_health(raw)
        counts[state.value] += 1
        if state.severity > worst.severity:
            worst = state
    return {"overall": worst.value, "counts": counts}
