from rapid_tpu.utils.xxhash import xxh64, xxh64_int, to_signed64

__all__ = ["xxh64", "xxh64_int", "to_signed64"]
