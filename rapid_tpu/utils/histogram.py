"""Bounded, mergeable log-bucketed latency histogram.

The ``Metrics`` registry originally kept every timing sample in an unbounded
per-name ``List[float]`` — on a long-lived node that list grows forever,
which disqualifies it for production scrapes. This histogram replaces it with
a FIXED bucket schedule: upper bounds grow geometrically by sqrt(2) per
bucket from 0.01 ms, so any sample lands within a factor of sqrt(2) of its
true value, memory is O(NUM_BUCKETS) regardless of sample count, and two
histograms recorded on different nodes (or epochs) merge by bucket-wise
addition — associative and commutative, which is what lets a dashboard fold
per-node snapshots into one cluster-wide quantile (tools/clustertop.py).

The schedule is a module constant shared by every instance: recorders,
mergers, and the Prometheus renderer (utils/exposition.py emits the
``_bucket``/``_sum``/``_count`` triplet from it) all agree on bucket edges
by construction, so a snapshot serialized as sparse ``{bucket_index: count}``
JSON is portable across processes.
"""

from __future__ import annotations

import math
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional

#: Geometric growth per bucket. sqrt(2) bounds any quantile's relative error
#: at ~41% while covering 0.01 ms .. ~9 hours in 64 buckets.
GROWTH = 2.0 ** 0.5

#: Upper bound of the first bucket, in milliseconds.
FIRST_UPPER_MS = 0.01

#: Finite buckets; one extra overflow bucket (index NUM_BUCKETS) plays the
#: Prometheus ``+Inf`` role.
NUM_BUCKETS = 64

#: The fixed schedule: ``UPPER_BOUNDS_MS[i]`` is the inclusive upper bound of
#: bucket i. Values above the last bound land in the overflow bucket.
UPPER_BOUNDS_MS = tuple(FIRST_UPPER_MS * GROWTH**i for i in range(NUM_BUCKETS))


def bucket_index(value_ms: float) -> int:
    """Index of the bucket holding ``value_ms`` (<= its upper bound);
    non-positive values fall into bucket 0, values past the last finite
    bound into the overflow bucket NUM_BUCKETS."""
    if value_ms <= FIRST_UPPER_MS:
        return 0
    return bisect_left(UPPER_BOUNDS_MS, value_ms)


class LogHistogram:
    """Fixed-schedule log-bucketed histogram of millisecond durations.

    Quantiles come back as the upper bound of the bucket containing the
    requested rank, clamped to the exact recorded max — so for any recorded
    distribution ``true_q <= quantile(q) <= true_q * GROWTH`` (the rank-bound
    property pinned by tests/test_histogram_properties.py). ``merge`` adds
    bucket counts, counts, and sums, and takes the max of maxima: associative
    and commutative over everything except ``last`` (which is a display
    nicety, defined as the most recent operand's last sample).
    """

    __slots__ = ("_counts", "count", "sum", "max", "last")

    def __init__(self) -> None:
        self._counts: List[int] = [0] * (NUM_BUCKETS + 1)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self.last = 0.0

    def observe(self, value_ms: float) -> None:
        self._counts[bucket_index(value_ms)] += 1
        self.count += 1
        self.sum += value_ms
        if value_ms > self.max:
            self.max = value_ms
        self.last = value_ms

    # -- merging -------------------------------------------------------

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Fold ``other`` into self (in place); returns self for chaining."""
        for i, c in enumerate(other._counts):
            self._counts[i] += c
        self.count += other.count
        self.sum += other.sum
        if other.max > self.max:
            self.max = other.max
        if other.count:
            self.last = other.last
        return self

    @classmethod
    def merged(cls, histograms: Iterable["LogHistogram"]) -> "LogHistogram":
        out = cls()
        for hist in histograms:
            out.merge(hist)
        return out

    # -- quantiles -----------------------------------------------------

    def quantile(self, q: float) -> float:
        """The q-quantile (0 < q <= 1) as the containing bucket's upper
        bound, clamped to the exact max; 0.0 on an empty histogram."""
        if self.count == 0:
            return 0.0
        rank = min(self.count, max(1, math.ceil(q * self.count)))
        cumulative = 0
        for i, c in enumerate(self._counts):
            cumulative += c
            if cumulative >= rank:
                bound = UPPER_BOUNDS_MS[i] if i < NUM_BUCKETS else self.max
                return min(bound, self.max)
        return self.max  # unreachable: cumulative reaches count

    # -- snapshots -----------------------------------------------------

    def summary(self) -> Dict[str, object]:
        """JSON-ready bounded summary: headline quantiles plus the sparse
        bucket counts (``{index: count}``, string keys for JSON round-trip)
        the Prometheus renderer and cross-node mergers consume. Size is
        O(NUM_BUCKETS) no matter how many samples were recorded."""
        return {
            "count": self.count,
            "last": round(self.last, 3),
            "p50": round(self.quantile(0.50), 3),
            "p90": round(self.quantile(0.90), 3),
            "p99": round(self.quantile(0.99), 3),
            "max": round(self.max, 3),
            "sum": round(self.sum, 3),
            "buckets": {str(i): c for i, c in enumerate(self._counts) if c},
        }

    @classmethod
    def from_summary(cls, summary: Dict[str, object]) -> "LogHistogram":
        """Rebuild a mergeable histogram from a ``summary()`` dict (e.g. one
        loaded from a telemetry-snapshot JSON file). Tolerates missing keys:
        a legacy timer dict without buckets rebuilds as count-only."""
        out = cls()
        for key, c in (summary.get("buckets") or {}).items():
            idx = int(key)
            if 0 <= idx <= NUM_BUCKETS:
                out._counts[idx] += int(c)
        out.count = int(summary.get("count", 0))
        out.sum = float(summary.get("sum", 0.0))
        out.max = float(summary.get("max", 0.0))
        out.last = float(summary.get("last", 0.0))
        return out

    def cumulative_buckets(self) -> List[tuple]:
        """(upper_bound_ms, cumulative_count) pairs for Prometheus
        ``_bucket`` rendering: every finite bound up to the highest occupied
        bucket, then ``("+Inf", count)``. Cumulative counts make truncating
        the empty tail spec-valid — all omitted bounds equal the total."""
        out: List[tuple] = []
        highest = max((i for i, c in enumerate(self._counts) if c), default=-1)
        cumulative = 0
        for i in range(min(highest, NUM_BUCKETS - 1) + 1):
            cumulative += self._counts[i]
            out.append((UPPER_BOUNDS_MS[i], cumulative))
        out.append(("+Inf", self.count))
        return out


def cumulative_from_summary(summary: Dict[str, object]) -> Optional[List[tuple]]:
    """``cumulative_buckets()`` for a summary dict, or None when the dict
    carries no bucket data (legacy snapshot) — the exposition layer's seam."""
    if "buckets" not in summary:
        return None
    return LogHistogram.from_summary(summary).cumulative_buckets()
