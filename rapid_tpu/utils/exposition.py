"""Metrics exposition: one unified telemetry snapshot per node, rendered as
Prometheus text or JSON.

The reference has no runtime telemetry surface at all (SURVEY §5.1/5.5); the
paper's Table 2 network numbers came from external OS tooling. This module
unifies the three in-tree instruments — the ``Metrics`` registry
(utils/metrics.py), per-transport ``TransportStats`` (messaging/stats.py),
and the flight recorder (utils/flight_recorder.py) — into a single snapshot
dict with a stable shape, and renders it in the Prometheus text exposition
format under stable metric names (pinned by tests/test_observability.py).

Snapshot shape (``MembershipService.telemetry_snapshot`` /
``Cluster.telemetry_snapshot`` produce it; ``tools/traceview.py`` and the
standalone agent's ``--metrics-dump`` consume it)::

    {
      "node": "host:port",
      "configuration_id": int,
      "membership_size": int,
      "metrics": {<counter>: int, ..., "<timer>_ms": {count,last,p50,max}},
      "transport": {"client": TransportStats.snapshot()|None, "server": ...},
      "recorder": FlightRecorder.snapshot(),
    }
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

_PREFIX = "rapid"

#: Counters every membership-service scrape exposes even before the first
#: increment. Prometheus series that appear only once an event has happened
#: break rate()/absent() alerting; zero-filling the known vocabulary keeps
#: the series set stable from the first scrape. (``Metrics`` counters are a
#: defaultdict — there is no registry to enumerate, so the vocabulary lives
#: here and the golden test pins it.)
KNOWN_COUNTERS = (
    "alerts_enqueued",
    "alerts_received",
    "alert_batches_sent",
    "alert_batches_redelivered",
    "proposals_announced",
    "classic_rounds_started",
    "view_changes",
    "kicked",
    "config_beacons_sent",
    "config_catch_ups",
    "config_sync_unchanged",
    "config_pull_unchanged_served",
    "catch_up_wedged",
    "decision_missing_joiner_uuid",
)

_TRANSPORT_COUNTERS = ("msgs_tx", "bytes_tx", "msgs_rx", "bytes_rx")
_TRANSPORT_GAUGES = ("kbps_tx", "kbps_rx")


def _esc(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(**labels: str) -> str:
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items() if v is not None)
    return "{" + inner + "}" if inner else ""


def _num(value: Any) -> str:
    # Prometheus floats; integers render without a trailing .0 for
    # readability (both parse identically).
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


class _Renderer:
    def __init__(self) -> None:
        self._lines: List[str] = []
        self._typed: set = set()

    def sample(
        self, name: str, kind: str, value: Any, **labels: str
    ) -> None:
        if name not in self._typed:
            self._typed.add(name)
            self._lines.append(f"# TYPE {name} {kind}")
        self._lines.append(f"{name}{_labels(**labels)} {_num(value)}")

    def text(self) -> str:
        return "\n".join(self._lines) + "\n"


def prometheus_text(snapshot: Dict[str, Any]) -> str:
    """Render one unified telemetry snapshot as Prometheus text exposition.

    Metric names are a stable API (tests/test_observability.py pins them):
    - ``rapid_membership_size`` / ``rapid_configuration_id`` gauges;
    - every ``Metrics`` counter as ``rapid_<name>_total`` (the
      KNOWN_COUNTERS vocabulary is zero-filled);
    - every ``Metrics`` timer as ``rapid_<name>_ms{stat=...}``;
    - transport counters as ``rapid_transport_<dir>_total{side=...}``;
    - flight-recorder depth/capacity/total/dropped gauges.
    """
    node = snapshot.get("node")
    out = _Renderer()
    if "membership_size" in snapshot:
        out.sample(f"{_PREFIX}_membership_size", "gauge",
                   snapshot["membership_size"], node=node)
    if "configuration_id" in snapshot:
        out.sample(f"{_PREFIX}_configuration_id", "gauge",
                   snapshot["configuration_id"], node=node)

    metrics: Dict[str, Any] = dict(snapshot.get("metrics", {}))
    counters = {name: 0 for name in KNOWN_COUNTERS}
    timers: Dict[str, Dict[str, Any]] = {}
    for name, value in metrics.items():
        if isinstance(value, dict):
            timers[name] = value
        else:
            counters[name] = value
    for name in sorted(counters):
        out.sample(f"{_PREFIX}_{name}_total", "counter", counters[name], node=node)
    for name in sorted(timers):
        for stat, value in sorted(timers[name].items()):
            out.sample(f"{_PREFIX}_{name}", "summary", value, node=node, stat=stat)

    transport = snapshot.get("transport") or {}
    for side in sorted(transport):
        stats = transport[side]
        if not stats:
            continue
        for key in _TRANSPORT_COUNTERS:
            if key in stats:
                out.sample(f"{_PREFIX}_transport_{key}_total", "counter",
                           stats[key], node=node, side=side)
        for key in _TRANSPORT_GAUGES:
            if key in stats:
                out.sample(f"{_PREFIX}_transport_{key}", "gauge",
                           stats[key], node=node, side=side)

    recorder = snapshot.get("recorder")
    if recorder:
        # Derived from the ring counters, not len(events): a snapshot taken
        # with a truncated tail still reports the true ring depth.
        depth = recorder.get("recorded_total", 0) - recorder.get("dropped", 0)
        out.sample(f"{_PREFIX}_flight_recorder_depth", "gauge", depth, node=node)
        out.sample(f"{_PREFIX}_flight_recorder_capacity", "gauge",
                   recorder.get("capacity", 0), node=node)
        out.sample(f"{_PREFIX}_flight_recorder_recorded_total", "counter",
                   recorder.get("recorded_total", 0), node=node)
        out.sample(f"{_PREFIX}_flight_recorder_dropped_total", "counter",
                   recorder.get("dropped", 0), node=node)
    return out.text()


def metric_names(text: str) -> List[str]:
    """The sorted set of metric names in a Prometheus text exposition —
    what the golden-name test pins."""
    names = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        if name:
            names.add(name)
    return sorted(names)


def snapshot_json(snapshot: Dict[str, Any], indent: Optional[int] = None) -> str:
    """The JSON twin of the Prometheus rendering — the artifact
    ``--metrics-dump`` writes and ``tools/traceview.py`` merges."""
    return json.dumps(snapshot, indent=indent, sort_keys=False)
