"""Metrics exposition: one unified telemetry snapshot per node, rendered as
Prometheus text or JSON.

The reference has no runtime telemetry surface at all (SURVEY §5.1/5.5); the
paper's Table 2 network numbers came from external OS tooling. This module
unifies the in-tree instruments — the ``Metrics`` registry
(utils/metrics.py), per-transport ``TransportStats`` (messaging/stats.py),
the flight recorder (utils/flight_recorder.py), and the node health model
(utils/health.py) — into a single snapshot dict with a stable shape, and
renders it in the Prometheus text exposition format under stable metric
names (pinned by tests/test_observability.py).

Snapshot shape (``MembershipService.telemetry_snapshot`` /
``Cluster.telemetry_snapshot`` produce it; ``tools/traceview.py``,
``tools/clustertop.py`` and the standalone agent's ``--metrics-dump``
consume it)::

    {
      "node": "host:port",
      "configuration_id": int,
      "membership_size": int,
      "health": "stable" | "detecting" | "proposing" | "catching_up" | "wedged",
      "metrics": {<counter>: int, ...,
                  "<timer>_ms": {count,last,p50,p90,p99,max,sum,buckets},
                  "<family>_ms": {<phase>: {count,...,buckets}, ...}},
      "transport": {"client": TransportStats.snapshot()|None, "server": ...},
      "recorder": FlightRecorder.snapshot(),
    }

Timers render as real Prometheus histograms (``_bucket``/``_sum``/``_count``
on the fixed schedule of utils/histogram.py); phase families additionally
carry ``phase=`` (and, for "phase/path" keys, ``path=``) labels — the
convergence SLO surface: ``rapid_view_change_phase_ms_bucket{phase="detection",...}``.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional

from rapid_tpu.utils.health import NodeHealth
from rapid_tpu.utils.histogram import LogHistogram, cumulative_from_summary

#: The zero-count summary shape, for series that must exist from the first
#: scrape even though their instrument is minted lazily on first record.
_EMPTY_HISTOGRAM_SUMMARY = LogHistogram().summary()

_PREFIX = "rapid"

#: Counters every membership-service scrape exposes even before the first
#: increment. Prometheus series that appear only once an event has happened
#: break rate()/absent() alerting; zero-filling the known vocabulary keeps
#: the series set stable from the first scrape. (``Metrics`` counters are a
#: defaultdict — there is no registry to enumerate, so the vocabulary lives
#: here and the golden test pins it.)
KNOWN_COUNTERS = (
    "alerts_enqueued",
    "alerts_received",
    "alert_batches_sent",
    "alert_batches_redelivered",
    "proposals_announced",
    "classic_rounds_started",
    "view_changes",
    "kicked",
    "config_beacons_sent",
    "config_catch_ups",
    "config_sync_unchanged",
    "config_pull_unchanged_served",
    "catch_up_wedged",
    "decision_missing_joiner_uuid",
)

_TRANSPORT_COUNTERS = ("msgs_tx", "bytes_tx", "msgs_rx", "bytes_rx")
_TRANSPORT_GAUGES = ("kbps_tx", "kbps_rx")

#: Device-engine counters zero-filled on every snapshot that carries an
#: ``engine`` section (``VirtualCluster.telemetry_snapshot``) — the engine
#: tier's series set must be stable from the first scrape, same rule as
#: KNOWN_COUNTERS for host nodes.
ENGINE_KNOWN_COUNTERS = (
    "engine_dispatches",
    "engine_steps",
    "engine_convergence_steps",
    "engine_cuts_committed",
    "engine_h2d_bytes",
    "engine_d2h_bytes",
)

#: Tenant-fleet counters zero-filled on snapshots whose ``engine`` section
#: carries a ``tenancy`` block (``TenantFleet.telemetry_snapshot``) — the
#: fleet tier's series set is stable from the first scrape, and a
#: single-cluster scrape never grows them.
TENANCY_KNOWN_COUNTERS = (
    "engine_tenant_rounds",
    "engine_tenant_cuts",
    "engine_tenant_quarantines",
)

#: Streaming-tier counters zero-filled on snapshots whose ``engine`` section
#: carries a ``stream`` block (a ``rapid_tpu.serving.StreamDriver`` is
#: attached to the driver) — same stable-series rule; batch-only scrapes
#: never grow them.
STREAM_KNOWN_COUNTERS = (
    "engine_stream_waves",
    "engine_stream_cuts",
)

#: Supervision-tier counters zero-filled on snapshots whose ``engine``
#: section carries a ``recovery`` block (a ``rapid_tpu.serving.supervisor.
#: Supervisor`` is attached) — same stable-series rule; unsupervised
#: scrapes never grow them.
RECOVERY_KNOWN_COUNTERS = (
    "engine_recovery_retries",
    "engine_recovery_wedges",
    "engine_recovery_checkpoints",
    "engine_recovery_resumes",
    "engine_recovery_quarantines",
    "engine_recovery_quarantine_dropped_events",
)

#: ``engine.stream`` gauge keys (``StreamDriver.snapshot()``); rate/ratio
#: gauges are None before the first drain and render NaN so the series set
#: is stable from the first scrape.
_ENGINE_STREAM_GAUGES = (
    "waves_submitted",
    "waves_completed",
    "waves_in_flight",
    "rounds_per_wave",
    "depth",
    "view_changes_per_sec",
    "overlap_efficiency",
    "p99_alert_to_commit_ms",
)

#: ``engine.recovery`` gauge keys (``Supervisor.snapshot()``); None values
#: (no checkpoint yet, no resume yet) render NaN so the series set is
#: stable from attach.
_ENGINE_RECOVERY_GAUGES = (
    "waves_submitted",
    "checkpoint_every",
    "checkpoints_written",
    "last_checkpoint_wave",
    "retries",
    "wedges",
    "resumes",
    "quarantined",
    "mttr_ms",
)

#: ``engine.compile`` counter keys -> metric suffix (all render as
#: ``rapid_engine_<suffix>_total``); the compile_ms histogram is rendered
#: separately.
_ENGINE_COMPILE_COUNTERS = (
    ("compiles", "compiles"),
    ("persistent_cache_hits", "persistent_cache_hits"),
    ("persistent_cache_misses", "persistent_cache_misses"),
    ("cache_requests", "compile_cache_requests"),
)

#: ``engine.memory`` gauge keys (``None`` probes render as NaN so the
#: series set is identical on platforms without allocator stats).
_ENGINE_MEMORY_GAUGES = (
    "live_buffers",
    "live_buffer_bytes",
    "device_bytes_in_use",
    "device_peak_bytes",
)

#: Device-telemetry-plane activity counters (``engine.activity`` — present
#: exactly when the driver was built with ``telemetry=1``; the section is
#: zero-minted at attach, so every series below exists from the first
#: scrape and is never minted mid-run). Rendered as
#: ``rapid_engine_activity_<name>_total``.
_ENGINE_ACTIVITY_COUNTERS = (
    "rounds",
    "alerts",
    "active_sum",
    "invalidations",
    "proposals",
    "tally_sum",
    "conflict_rounds",
)

#: ``engine.activity`` derived gauges (``rapid_engine_activity_<name>``):
#: the rates/peaks clustertop and perfview columns read.
_ENGINE_ACTIVITY_GAUGES = (
    "active_peak",
    "active_fraction",
    "peak_active_fraction",
    "fast_path_share",
    "conflict_rate",
    "winning_tally_mean",
)

#: Round-trace ring counters (``engine.trace`` / per-tenant
#: ``engine.tenant_trace`` — present exactly when the driver was built with
#: ``trace=R``; zero-minted at attach, so every series exists from the
#: first scrape). Rendered as ``rapid_engine_trace_<name>_total``.
_ENGINE_TRACE_COUNTERS = (
    "rounds_recorded",
    "wraps",
)

#: Round-trace ring gauges (``rapid_engine_trace_<name>``): ring geometry,
#: held-window census, and the newest record's stamps — the clustertop
#: ROUNDS pane's inputs.
_ENGINE_TRACE_GAUGES = (
    "capacity",
    "rounds_held",
    "decisions_held",
    "conflicts_held",
    "last_round",
    "last_epoch",
    "last_active",
    "last_path",
    "last_undecided",
)

#: ``engine.stream`` gauge keys that exist only on trace>0 targets
#: (StreamDriver.snapshot adds them exactly then): rendered when present,
#: so a trace=0 stream's scrape vocabulary is unchanged.
_ENGINE_STREAM_TRACE_GAUGES = (
    "rounds_to_decision_p99",
    "queue_wait_rounds_p99",
    "waves_evicted",
)


def _esc(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(**labels: str) -> str:
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items() if v is not None)
    return "{" + inner + "}" if inner else ""


def _num(value: Any) -> str:
    # Prometheus floats; integers render without a trailing .0 for
    # readability (both parse identically). Non-finite floats use the
    # exposition-format tokens — Python's repr ('nan', 'inf') is not
    # parseable by Prometheus scrapers.
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    value = float(value)
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return repr(value)


def _le(bound: Any) -> str:
    """A histogram bucket's ``le`` label value: short float form for finite
    bounds, the literal ``+Inf`` token for the overflow bucket."""
    return bound if isinstance(bound, str) else format(bound, ".6g")


class _Renderer:
    def __init__(self) -> None:
        self._lines: List[str] = []
        self._typed: set = set()

    def declare(self, name: str, kind: str) -> None:
        if name not in self._typed:
            self._typed.add(name)
            self._lines.append(f"# TYPE {name} {kind}")

    def sample(
        self, name: str, kind: str, value: Any, **labels: str
    ) -> None:
        self.declare(name, kind)
        self._lines.append(f"{name}{_labels(**labels)} {_num(value)}")

    def histogram(self, name: str, summary: Dict[str, Any], **labels: str) -> None:
        """One Prometheus histogram series set (``_bucket``/``_sum``/
        ``_count``) from a LogHistogram summary dict. ``labels`` precede the
        ``le`` label on every bucket line; the TYPE is declared once per
        family name across label sets."""
        buckets = cumulative_from_summary(summary)
        if buckets is None:
            # Legacy timer dict without bucket data (an old snapshot file):
            # fall back to the stat-labeled summary rendering.
            for stat, value in sorted(summary.items()):
                self.sample(name, "summary", value, **labels, stat=stat)
            return
        self.declare(name, "histogram")
        for bound, cumulative in buckets:
            self._lines.append(
                f"{name}_bucket{_labels(**labels, le=_le(bound))} {cumulative}"
            )
        self._lines.append(f"{name}_sum{_labels(**labels)} {_num(summary.get('sum', 0.0))}")
        self._lines.append(f"{name}_count{_labels(**labels)} {summary.get('count', 0)}")

    def text(self) -> str:
        return "\n".join(self._lines) + "\n"


def _render_activity(
    out: "_Renderer", activity: Dict[str, Any], node: Optional[str],
    tenant: Optional[str] = None,
) -> None:
    """One ``engine.activity`` section as Prometheus series: the raw
    counters, the fast/classic decision split
    (``rapid_engine_decision_path_total{path=...}``), the derived rate
    gauges, and the rounds-undecided log2 histogram
    (``{bucket="<log2 floor>"}``). ``tenant`` adds the fleet variants'
    per-tenant label."""
    for key in _ENGINE_ACTIVITY_COUNTERS:
        out.sample(f"{_PREFIX}_engine_activity_{key}_total", "counter",
                   activity.get(key, 0), node=node, tenant=tenant)
    for path in ("fast", "classic"):
        out.sample(f"{_PREFIX}_engine_decision_path_total", "counter",
                   activity.get(f"decisions_{path}", 0),
                   node=node, tenant=tenant, path=path)
    for key in _ENGINE_ACTIVITY_GAUGES:
        out.sample(f"{_PREFIX}_engine_activity_{key}", "gauge",
                   activity.get(key, 0), node=node, tenant=tenant)
    for bucket, count in enumerate(activity.get("rounds_undecided_hist", ())):
        out.sample(f"{_PREFIX}_engine_activity_rounds_undecided_total",
                   "counter", count, node=node, tenant=tenant,
                   bucket=str(bucket))


def _render_trace(
    out: "_Renderer", trace: Dict[str, Any], node: Optional[str],
    tenant: Optional[str] = None,
) -> None:
    """One decoded ring digest (``engine.trace`` / a ``tenant_trace``
    entry) as Prometheus series: the monotone cursor/wrap counters plus the
    held-window and last-record gauges. The per-record lanes themselves are
    a timeline, not a gauge surface — traceview renders those."""
    for key in _ENGINE_TRACE_COUNTERS:
        out.sample(f"{_PREFIX}_engine_trace_{key}_total", "counter",
                   trace.get(key, 0), node=node, tenant=tenant)
    for key in _ENGINE_TRACE_GAUGES:
        out.sample(f"{_PREFIX}_engine_trace_{key}", "gauge",
                   trace.get(key, 0), node=node, tenant=tenant)


def _phase_labels(phase_key: str) -> Dict[str, str]:
    """'detection' -> {phase: detection}; 'agreement/fast' ->
    {phase: agreement, path: fast} (the consensus-path split of the
    agreement phase — arXiv:1308.1358's fast/classic boundary)."""
    if "/" in phase_key:
        phase, path = phase_key.split("/", 1)
        return {"phase": phase, "path": path}
    return {"phase": phase_key}


def prometheus_text(snapshot: Dict[str, Any]) -> str:
    """Render one unified telemetry snapshot as Prometheus text exposition.

    Metric names are a stable API (tests/test_observability.py pins them):
    - ``rapid_membership_size`` / ``rapid_configuration_id`` gauges;
    - ``rapid_node_health{state=...}`` one-hot over the health vocabulary;
    - every ``Metrics`` counter as ``rapid_<name>_total`` (the
      KNOWN_COUNTERS vocabulary is zero-filled);
    - every ``Metrics`` timer as a ``rapid_<name>`` histogram
      (``_bucket``/``_sum``/``_count``), phase families labeled
      ``{phase=...}`` (and ``path=`` for the agreement split);
    - transport counters as ``rapid_transport_<dir>_total{side=...}``;
    - flight-recorder depth/capacity/total/dropped gauges.
    """
    node = snapshot.get("node")
    out = _Renderer()
    if "membership_size" in snapshot:
        out.sample(f"{_PREFIX}_membership_size", "gauge",
                   snapshot["membership_size"], node=node)
    if "configuration_id" in snapshot:
        out.sample(f"{_PREFIX}_configuration_id", "gauge",
                   snapshot["configuration_id"], node=node)
    if "health" in snapshot:
        # One-hot over the full vocabulary: the series set is stable from
        # the first scrape, so absent() alerting works per state.
        current = str(snapshot["health"]).lower()
        for state in NodeHealth:
            out.sample(f"{_PREFIX}_node_health", "gauge",
                       1 if state.value == current else 0,
                       node=node, state=state.value)

    metrics: Dict[str, Any] = dict(snapshot.get("metrics", {}))
    counters = {name: 0 for name in KNOWN_COUNTERS}
    engine_section = snapshot.get("engine")
    if "engine" in snapshot:
        counters.update({name: 0 for name in ENGINE_KNOWN_COUNTERS})
    if isinstance(engine_section, dict) and "tenancy" in engine_section:
        counters.update({name: 0 for name in TENANCY_KNOWN_COUNTERS})
    if isinstance(engine_section, dict) and "recovery" in engine_section:
        counters.update({name: 0 for name in RECOVERY_KNOWN_COUNTERS})
    if isinstance(engine_section, dict) and "stream" in engine_section:
        counters.update({name: 0 for name in STREAM_KNOWN_COUNTERS})
        # The alert->commit timer is lazily minted on the first wave
        # COMPLETION, so a scrape between attach and first completion
        # would otherwise lack the histogram triplet — zero-fill it (the
        # stable-series rule the counters above follow).
        metrics.setdefault(
            "engine_stream_alert_to_commit_ms", _EMPTY_HISTOGRAM_SUMMARY
        )
    timers: Dict[str, Dict[str, Any]] = {}
    for name, value in metrics.items():
        if isinstance(value, dict):
            timers[name] = value
        else:
            counters[name] = value
    for name in sorted(counters):
        out.sample(f"{_PREFIX}_{name}_total", "counter", counters[name], node=node)
    for name in sorted(timers):
        value = timers[name]
        if "count" in value:
            out.histogram(f"{_PREFIX}_{name}", value, node=node)
        else:
            # Phase family: {phase_key: histogram summary}.
            for phase_key in sorted(value):
                out.histogram(
                    f"{_PREFIX}_{name}", value[phase_key],
                    **_phase_labels(phase_key), node=node,
                )

    transport = snapshot.get("transport") or {}
    for side in sorted(transport):
        stats = transport[side]
        if not stats:
            continue
        for key in _TRANSPORT_COUNTERS:
            if key in stats:
                out.sample(f"{_PREFIX}_transport_{key}_total", "counter",
                           stats[key], node=node, side=side)
        for key in _TRANSPORT_GAUGES:
            if key in stats:
                out.sample(f"{_PREFIX}_transport_{key}", "gauge",
                           stats[key], node=node, side=side)

    engine = snapshot.get("engine")
    if engine:
        # Device-engine tier: process-wide compile/cache counters, the
        # compile-duration histogram, and the device-memory gauges (NaN for
        # probes the platform does not expose — the series stays).
        compile_stats = engine.get("compile") or {}
        for key, suffix in _ENGINE_COMPILE_COUNTERS:
            out.sample(f"{_PREFIX}_engine_{suffix}_total", "counter",
                       compile_stats.get(key, 0), node=node)
        compile_ms = compile_stats.get("compile_ms")
        if isinstance(compile_ms, dict):
            out.histogram(f"{_PREFIX}_engine_compile_ms", compile_ms, node=node)
        memory = engine.get("memory") or {}
        for key in _ENGINE_MEMORY_GAUGES:
            value = memory.get(key)
            out.sample(f"{_PREFIX}_engine_{key}", "gauge",
                       float("nan") if value is None else value, node=node)
        stream = engine.get("stream")
        if isinstance(stream, dict):
            # The streaming tier (rapid_tpu/serving): pipeline state and
            # the drained sustained rates as gauges (NaN pre-drain — the
            # series set is stable from the first scrape); the cumulative
            # wave/cut counters ride the ordinary metrics section,
            # zero-filled above, and the alert->commit histogram renders
            # from the timer family like every other timer.
            for key in _ENGINE_STREAM_GAUGES:
                value = stream.get(key)
                out.sample(f"{_PREFIX}_engine_stream_{key}", "gauge",
                           float("nan") if value is None else value,
                           node=node)
            # Ring-derived decomposition gauges: present in the snapshot
            # exactly when the stream's target runs trace>0 (NaN pre-drain).
            for key in _ENGINE_STREAM_TRACE_GAUGES:
                if key in stream:
                    value = stream.get(key)
                    out.sample(f"{_PREFIX}_engine_stream_{key}", "gauge",
                               float("nan") if value is None else value,
                               node=node)
        tenancy = engine.get("tenancy")
        if isinstance(tenancy, dict):
            # The fleet tier: tenant count, per-dispatch tenant throughput,
            # and the quarantine census as gauges (the cumulative counters
            # ride the ordinary metrics section, zero-filled above).
            out.sample(f"{_PREFIX}_engine_tenants", "gauge",
                       tenancy.get("tenants", 0), node=node)
            out.sample(f"{_PREFIX}_engine_tenant_rounds_per_dispatch",
                       "gauge",
                       tenancy.get("tenant_rounds_per_dispatch", 0.0),
                       node=node)
            out.sample(f"{_PREFIX}_engine_tenants_quarantined", "gauge",
                       tenancy.get("quarantined", 0), node=node)
        activity = engine.get("activity")
        if isinstance(activity, dict):
            # The device telemetry plane (models/state.TelemetryLanes):
            # present exactly when the driver runs with telemetry=1. The
            # aggregate renders unlabelled; a fleet's per-tenant list adds
            # tenant=<idx> variants of the same names.
            _render_activity(out, activity, node)
            tenant_activity = engine.get("tenant_activity")
            if isinstance(tenant_activity, (list, tuple)):
                for idx, per_tenant in enumerate(tenant_activity):
                    _render_activity(out, per_tenant, node, tenant=str(idx))
        trace = engine.get("trace")
        if isinstance(trace, dict):
            # The round-trace ring (models/state.TraceRing): present
            # exactly when the driver runs with trace=R (zero-minted at
            # attach — the series set is stable from the first scrape).
            _render_trace(out, trace, node)
        tenant_trace = engine.get("tenant_trace")
        if isinstance(tenant_trace, (list, tuple)):
            for idx, per_tenant in enumerate(tenant_trace):
                _render_trace(out, per_tenant, node, tenant=str(idx))
        recovery = engine.get("recovery")
        if isinstance(recovery, dict):
            # The supervision tier (rapid_tpu/serving/supervisor.py):
            # checkpoint cadence/progress, retry/wedge/resume tallies, the
            # quarantine census, and the last resume's MTTR (NaN until a
            # resume happens — the series set is stable from attach).
            for key in _ENGINE_RECOVERY_GAUGES:
                value = recovery.get(key)
                out.sample(f"{_PREFIX}_engine_recovery_{key}", "gauge",
                           float("nan") if value is None else value,
                           node=node)

    recorder = snapshot.get("recorder")
    if recorder:
        # Derived from the ring counters, not len(events): a snapshot taken
        # with a truncated tail still reports the true ring depth.
        depth = recorder.get("recorded_total", 0) - recorder.get("dropped", 0)
        out.sample(f"{_PREFIX}_flight_recorder_depth", "gauge", depth, node=node)
        out.sample(f"{_PREFIX}_flight_recorder_capacity", "gauge",
                   recorder.get("capacity", 0), node=node)
        out.sample(f"{_PREFIX}_flight_recorder_recorded_total", "counter",
                   recorder.get("recorded_total", 0), node=node)
        out.sample(f"{_PREFIX}_flight_recorder_dropped_total", "counter",
                   recorder.get("dropped", 0), node=node)
    return out.text()


def metric_names(text: str) -> List[str]:
    """The sorted set of metric names in a Prometheus text exposition —
    what the golden-name test pins."""
    names = set()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        if name:
            names.add(name)
    return sorted(names)


def snapshot_json(snapshot: Dict[str, Any], indent: Optional[int] = None) -> str:
    """The JSON twin of the Prometheus rendering — the artifact
    ``--metrics-dump`` writes and ``tools/traceview.py`` merges."""
    return json.dumps(snapshot, indent=indent, sort_keys=False)
