"""Time & scheduling abstraction.

The reference drives everything off wall-clock scheduled executors
(``SharedResources.java:100-102``). To keep tests deterministic and to let the
TPU virtual-cluster engine run simulated time at 100K nodes, every timing
consumer in this framework (alert batcher, failure detectors, consensus
fallback) goes through this interface instead of the event loop directly.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import Callable, List, Optional, Tuple


class CancelHandle:
    __slots__ = ("_cancel",)

    def __init__(self, cancel: Callable[[], None]):
        self._cancel = cancel

    def cancel(self) -> None:
        self._cancel()


class Clock:
    """Abstract clock + one-shot scheduler."""

    def now_ms(self) -> float:
        raise NotImplementedError

    async def sleep_ms(self, delay_ms: float) -> None:
        raise NotImplementedError

    def call_later_ms(self, delay_ms: float, fn: Callable[[], None]) -> CancelHandle:
        raise NotImplementedError


class AsyncioClock(Clock):
    """Wall-clock implementation over the running asyncio loop."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        self._loop = loop

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        if self._loop is None:
            self._loop = asyncio.get_event_loop()
        return self._loop

    def now_ms(self) -> float:
        return self.loop.time() * 1000.0

    async def sleep_ms(self, delay_ms: float) -> None:
        await asyncio.sleep(delay_ms / 1000.0)

    def call_later_ms(self, delay_ms: float, fn: Callable[[], None]) -> CancelHandle:
        handle = self.loop.call_later(delay_ms / 1000.0, fn)
        return CancelHandle(handle.cancel)


class NodeClock(Clock):
    """Per-node view of a shared base clock, with injectable skew and pause.

    The chaos-simulation subsystem (rapid_tpu/sim) gives every simulated
    node its own ``NodeClock`` over the test's one ``ManualClock`` so fault
    schedules can express per-node clock faults deterministically:

    - **skew**: ``set_skew(offset_ms)`` shifts this node's ``now_ms``
      readings (timestamps, metrics, batching-window arithmetic) without
      touching anyone else's — the classic mis-set-NTP failure mode;
    - **pause**: ``pause()`` freezes ``now_ms`` AND defers every timer the
      node scheduled (its failure detectors, alert batcher, sync loops all
      stop firing) until ``resume()`` — a GC pause / VM freeze. The node
      still answers inbound RPCs, which is exactly what makes real frozen
      processes so confusing to their peers.

    Timers are scheduled on the base clock; a callback landing while paused
    is parked and re-armed (delay 0) at resume, so no tick is lost, only
    late — matching a thawed process running its overdue timers.
    """

    def __init__(self, base: Clock) -> None:
        self._base = base
        self._offset_ms = 0.0
        self._paused = False
        self._paused_at = 0.0
        self._parked: List[Callable[[], None]] = []

    def now_ms(self) -> float:
        if self._paused:
            return self._paused_at
        return self._base.now_ms() + self._offset_ms

    def set_skew(self, offset_ms: float) -> None:
        if self._paused:
            raise RuntimeError("cannot re-skew a paused clock (resume first)")
        self._offset_ms = offset_ms

    @property
    def paused(self) -> bool:
        return self._paused

    def pause(self) -> None:
        if self._paused:
            return
        self._paused_at = self.now_ms()
        self._paused = True

    def resume(self) -> None:
        if not self._paused:
            return
        self._paused = False
        parked, self._parked = self._parked, []
        for fn in parked:
            # Re-armed rather than run inline: resume() is called from
            # synchronous schedule-application code, and overdue callbacks
            # must fire from the clock/loop context they were written for.
            self._base.call_later_ms(0, fn)

    async def sleep_ms(self, delay_ms: float) -> None:
        event = asyncio.Event()
        self.call_later_ms(delay_ms, event.set)
        await event.wait()

    def call_later_ms(self, delay_ms: float, fn: Callable[[], None]) -> CancelHandle:
        cancelled = [False]

        def fire() -> None:
            if cancelled[0]:
                return
            if self._paused:
                self._parked.append(fire)
            else:
                fn()

        inner = self._base.call_later_ms(delay_ms, fire)

        def cancel() -> None:
            cancelled[0] = True
            inner.cancel()

        return CancelHandle(cancel)


class ManualClock(Clock):
    """Deterministic clock for unit tests: time only moves via ``advance_ms``."""

    def __init__(self) -> None:
        self._now = 0.0
        self._counter = itertools.count()
        self._pending: List[Tuple[float, int, Callable[[], None], List[bool]]] = []

    def now_ms(self) -> float:
        return self._now

    async def sleep_ms(self, delay_ms: float) -> None:
        event = asyncio.Event()
        self.call_later_ms(delay_ms, event.set)
        await event.wait()

    def call_later_ms(self, delay_ms: float, fn: Callable[[], None]) -> CancelHandle:
        cancelled = [False]
        heapq.heappush(self._pending, (self._now + delay_ms, next(self._counter), fn, cancelled))
        return CancelHandle(lambda: cancelled.__setitem__(0, True))

    def advance_ms(self, delta_ms: float) -> None:
        """Move time forward, firing due callbacks in order."""
        target = self._now + delta_ms
        while self._pending and self._pending[0][0] <= target:
            when, _, fn, cancelled = heapq.heappop(self._pending)
            self._now = when
            if not cancelled[0]:
                fn()
        self._now = target
