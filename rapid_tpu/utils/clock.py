"""Time & scheduling abstraction.

The reference drives everything off wall-clock scheduled executors
(``SharedResources.java:100-102``). To keep tests deterministic and to let the
TPU virtual-cluster engine run simulated time at 100K nodes, every timing
consumer in this framework (alert batcher, failure detectors, consensus
fallback) goes through this interface instead of the event loop directly.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
from typing import Callable, List, Optional, Tuple


class CancelHandle:
    __slots__ = ("_cancel",)

    def __init__(self, cancel: Callable[[], None]):
        self._cancel = cancel

    def cancel(self) -> None:
        self._cancel()


class Clock:
    """Abstract clock + one-shot scheduler."""

    def now_ms(self) -> float:
        raise NotImplementedError

    async def sleep_ms(self, delay_ms: float) -> None:
        raise NotImplementedError

    def call_later_ms(self, delay_ms: float, fn: Callable[[], None]) -> CancelHandle:
        raise NotImplementedError


class AsyncioClock(Clock):
    """Wall-clock implementation over the running asyncio loop."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        self._loop = loop

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        if self._loop is None:
            self._loop = asyncio.get_event_loop()
        return self._loop

    def now_ms(self) -> float:
        return self.loop.time() * 1000.0

    async def sleep_ms(self, delay_ms: float) -> None:
        await asyncio.sleep(delay_ms / 1000.0)

    def call_later_ms(self, delay_ms: float, fn: Callable[[], None]) -> CancelHandle:
        handle = self.loop.call_later(delay_ms / 1000.0, fn)
        return CancelHandle(handle.cancel)


class ManualClock(Clock):
    """Deterministic clock for unit tests: time only moves via ``advance_ms``."""

    def __init__(self) -> None:
        self._now = 0.0
        self._counter = itertools.count()
        self._pending: List[Tuple[float, int, Callable[[], None], List[bool]]] = []

    def now_ms(self) -> float:
        return self._now

    async def sleep_ms(self, delay_ms: float) -> None:
        event = asyncio.Event()
        self.call_later_ms(delay_ms, event.set)
        await event.wait()

    def call_later_ms(self, delay_ms: float, fn: Callable[[], None]) -> CancelHandle:
        cancelled = [False]
        heapq.heappush(self._pending, (self._now + delay_ms, next(self._counter), fn, cancelled))
        return CancelHandle(lambda: cancelled.__setitem__(0, True))

    def advance_ms(self, delta_ms: float) -> None:
        """Move time forward, firing due callbacks in order."""
        target = self._now + delta_ms
        while self._pending and self._pending[0][0] <= target:
            when, _, fn, cancelled = heapq.heappop(self._pending)
            self._now = when
            if not cancelled[0]:
                fn()
        self._now = target
