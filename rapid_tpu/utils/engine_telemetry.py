"""Process-wide device-engine compile/memory telemetry.

The host protocol got its observability tier in PRs 1-2 (flight recorder,
exposition, phase SLOs); the jitted device engine had none — every XLA
compile, persistent-cache hit, and device allocation was invisible, which is
how the perf trajectory went blind (ROADMAP item 2). This module is the
engine-side counterpart: a process-global collector fed by ``jax.monitoring``
events, plus best-effort device-memory probes, consumed by
``VirtualCluster.telemetry_snapshot()`` and the bench ledger.

Compile events are inherently process-global (the XLA compilation cache and
the persistent on-disk cache are shared by every engine instance in the
process), so the collector is a module singleton: ``install()`` registers
the listeners once, ``compile_snapshot()`` reads the monotonic totals, and
callers that want per-phase attribution diff two snapshots around the work
(``CompileDelta``).

Everything degrades gracefully: a JAX build without ``jax.monitoring`` (or
without ``memory_stats``/``live_arrays``) yields zero counters / ``None``
gauges, never an exception — telemetry must not be able to take down the
engine it observes.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

from rapid_tpu.utils.histogram import LogHistogram

logger = logging.getLogger(__name__)

#: jax.monitoring point-event names -> our counter names. The persistent
#: compilation cache emits hits/misses; ``compile_requests_use_cache``
#: counts every compile request that consulted it (hit + miss + disabled).
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "persistent_cache_hits",
    "/jax/compilation_cache/cache_misses": "persistent_cache_misses",
    "/jax/compilation_cache/compile_requests_use_cache": "cache_requests",
}

#: The duration event XLA records once per backend compile — its count is
#: the process's compile count, its sum the total compile wall time.
_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"


class _CompileCollector:
    """Monotonic process-wide compile/cache totals (thread-safe: monitoring
    callbacks can fire from compile worker threads)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            name: 0 for name in _EVENT_COUNTERS.values()
        }
        self.compiles = 0
        self.compile_ms_hist = LogHistogram()

    def on_event(self, event: str, **_kwargs: Any) -> None:
        name = _EVENT_COUNTERS.get(event)
        if name is not None:
            with self._lock:
                self.counters[name] += 1

    def on_duration(self, event: str, duration_secs: float, **_kwargs: Any) -> None:
        if event == _COMPILE_DURATION_EVENT:
            with self._lock:
                self.compiles += 1
                self.compile_ms_hist.observe(duration_secs * 1000.0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self.counters)
            out["compiles"] = self.compiles
            out["compile_ms"] = self.compile_ms_hist.summary()
        return out


_COLLECTOR = _CompileCollector()
_INSTALL_LOCK = threading.Lock()
_installed: Optional[bool] = None  # None = never attempted


def install() -> bool:
    """Register the monitoring listeners once per process; True iff compile
    events are being captured (False on a JAX without ``jax.monitoring``).
    Idempotent — every ``VirtualCluster`` constructor calls it."""
    global _installed
    with _INSTALL_LOCK:
        if _installed is not None:
            return _installed
        try:
            from jax import monitoring
        except ImportError:
            logger.warning(
                "jax.monitoring unavailable: engine compile telemetry disabled"
            )
            _installed = False
            return False
        try:
            monitoring.register_event_listener(_COLLECTOR.on_event)
            monitoring.register_event_duration_secs_listener(
                _COLLECTOR.on_duration
            )
        except Exception as exc:  # noqa: BLE001 — a monitoring-API mismatch
            # must degrade to "no compile telemetry", never break engine
            # construction: the collector is strictly an observer.
            logger.warning("engine compile telemetry disabled: %r", exc)
            _installed = False
            return False
        _installed = True
        return True


def compile_snapshot() -> Dict[str, Any]:
    """Monotonic process-wide compile/cache totals:
    ``{compiles, compile_ms: <histogram summary>, persistent_cache_hits,
    persistent_cache_misses, cache_requests}``. All zeros when capture is
    unavailable (callers need not care)."""
    return _COLLECTOR.snapshot()


class CompileDelta:
    """Attribute process-global compile activity to one phase: snapshot on
    enter, diff on exit (``delta`` holds the scalar differences).

    Only correct when nothing else compiles concurrently — true for the
    bench (one workload per process) and the tests that use it.
    """

    def __init__(self) -> None:
        self.delta: Dict[str, int] = {}
        self._before: Dict[str, Any] = {}

    def __enter__(self) -> "CompileDelta":
        self._before = compile_snapshot()
        return self

    def __exit__(self, *_exc: Any) -> None:
        after = compile_snapshot()
        self.delta = {
            key: after[key] - self._before[key]
            for key in after
            if isinstance(after[key], int)
        }
        self.delta["compile_ms"] = round(
            float(after["compile_ms"]["sum"])
            - float(self._before["compile_ms"]["sum"]),
            3,
        )


def device_memory_snapshot() -> Dict[str, Any]:
    """Best-effort device memory view: live-buffer census via
    ``jax.live_arrays()`` plus the backend allocator's
    ``bytes_in_use``/``peak_bytes_in_use`` where the platform reports them
    (TPU does; CPU returns None). Missing probes yield ``None`` values, so
    the snapshot shape is stable across platforms."""
    out: Dict[str, Any] = {
        "live_buffers": None,
        "live_buffer_bytes": None,
        "device_bytes_in_use": None,
        "device_peak_bytes": None,
    }
    try:
        import jax

        arrays = jax.live_arrays()
        out["live_buffers"] = len(arrays)
        out["live_buffer_bytes"] = int(
            sum(getattr(a, "nbytes", 0) or 0 for a in arrays)
        )
    except Exception as exc:  # noqa: BLE001 — a backend that cannot
        # enumerate live arrays (or a deleted-buffer race mid-census) means
        # "no census this scrape", never a failed scrape.
        logger.debug("live-array census unavailable: %r", exc)
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats:
            if "bytes_in_use" in stats:
                out["device_bytes_in_use"] = int(stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                out["device_peak_bytes"] = int(stats["peak_bytes_in_use"])
    except Exception as exc:  # noqa: BLE001 — memory_stats is
        # platform-optional (None/absent on CPU and some plugins); the
        # gauges stay None rather than poisoning the snapshot.
        logger.debug("device memory_stats unavailable: %r", exc)
    return out


# ---------------------------------------------------------------------------
# Device telemetry plane: digest decode (models/virtual_cluster.py's
# telemetry_digest_impl packs the lanes into one int32 vector at host-sync
# boundaries; this is the host-side vocabulary for unpacking it)
# ---------------------------------------------------------------------------

#: Scalar layout of the telemetry digest vector, in order; the
#: TELEMETRY_BUCKETS rounds-undecided histogram buckets follow. Shared by
#: ``telemetry_digest_impl`` (producer) and :func:`activity_summary`
#: (consumer) so the two cannot skew silently.
TELEMETRY_DIGEST_FIELDS = (
    "rounds",
    "alerts",
    "active_sum",
    "active_peak",
    "invalidations",
    "proposals",
    "tally_sum",
    "decisions_fast",
    "decisions_classic",
    "conflict_rounds",
)


def activity_summary(digest: Any, n: int, c: int) -> Dict[str, Any]:
    """The ``engine.activity`` snapshot section from one fetched digest
    vector: the raw counters plus the derived rates clustertop/perfview/
    bench read — mean/peak active-subject fraction (of the [c, n] detector
    slots, per round), the fast-path decision share, and the conflict rate
    (rounds some cohort sat announced-but-undecided, per round). Pure host
    arithmetic on an already-fetched vector — never fetches."""
    from rapid_tpu.models.state import TELEMETRY_BUCKETS

    vec = [int(v) for v in digest]
    expected = len(TELEMETRY_DIGEST_FIELDS) + TELEMETRY_BUCKETS
    if len(vec) != expected:
        raise ValueError(
            f"telemetry digest carries {len(vec)} values, expected {expected}"
        )
    out: Dict[str, Any] = dict(zip(TELEMETRY_DIGEST_FIELDS, vec))
    out["rounds_undecided_hist"] = vec[len(TELEMETRY_DIGEST_FIELDS):]
    rounds = out["rounds"]
    slots = n * c
    decisions = out["decisions_fast"] + out["decisions_classic"]
    out["active_fraction"] = (
        out["active_sum"] / (rounds * slots) if rounds else 0.0
    )
    out["peak_active_fraction"] = (
        out["active_peak"] / rounds if rounds else 0.0
    )
    out["fast_path_share"] = (
        out["decisions_fast"] / decisions if decisions else 0.0
    )
    out["conflict_rate"] = out["conflict_rounds"] / rounds if rounds else 0.0
    out["winning_tally_mean"] = (
        out["tally_sum"] / decisions if decisions else 0.0
    )
    return out


def zero_activity_summary(n: int, c: int) -> Dict[str, Any]:
    """The all-zero activity section minted at driver attach: every series
    the plane will ever export exists from the first scrape (the exposition
    never mints a series mid-run)."""
    from rapid_tpu.models.state import TELEMETRY_BUCKETS

    return activity_summary(
        [0] * (len(TELEMETRY_DIGEST_FIELDS) + TELEMETRY_BUCKETS), n, c
    )


def aggregate_activity(summaries: Any, n: int, c: int) -> Dict[str, Any]:
    """Fleet-level rollup of per-tenant activity summaries: the counters and
    the histogram sum across tenants, the peak lanes take the tenant max
    (a peak summed across independent clusters is not a peak), and the
    derived rates are recomputed over the pooled totals."""
    summaries = list(summaries)
    if not summaries:
        return zero_activity_summary(n, c)
    hist = [
        sum(s["rounds_undecided_hist"][b] for s in summaries)
        for b in range(len(summaries[0]["rounds_undecided_hist"]))
    ]
    vec = [sum(s[f] for s in summaries) for f in TELEMETRY_DIGEST_FIELDS]
    out = activity_summary(vec + hist, n, c)
    out["active_peak"] = max(s["active_peak"] for s in summaries)
    out["peak_active_fraction"] = max(
        s["peak_active_fraction"] for s in summaries
    )
    return out


# ---------------------------------------------------------------------------
# Device round-trace ring: digest decode (models/virtual_cluster.py's
# trace_digest_impl packs the ring into one int32 vector at host-sync
# boundaries; this is the host-side vocabulary for unpacking it)
# ---------------------------------------------------------------------------

#: Per-round record fields, in the lane order ``trace_digest_impl`` packs
#: (after the two leading ``[cursor, wraps]`` scalars, one ``[R]`` lane per
#: field). Shared by producer and consumer so the two cannot skew silently —
#: the same contract :data:`TELEMETRY_DIGEST_FIELDS` carries for the plane.
TRACE_RECORD_FIELDS = (
    "round",
    "epoch",
    "active",
    "alerts",
    "proposals",
    "tally",
    "path",
    "conflict",
    "undecided",
)

#: Decision-path code vocabulary (the ``path`` record field): the engine's
#: analog of the host protocol's decided_path label.
TRACE_PATH_NAMES = {0: "none", 1: "fast", 2: "classic"}


def trace_summary(digest: Any, capacity: int) -> Dict[str, Any]:
    """The ``engine.trace`` snapshot section from one fetched trace digest:
    the decoded ring — ``records`` oldest -> newest, each a dict of
    :data:`TRACE_RECORD_FIELDS` plus the global round ordinal ``seq`` (the
    i-th round ever recorded) — and the derived scalars the exposition /
    clustertop / perfview surfaces read. Pure host arithmetic on an
    already-fetched vector — never fetches.

    Decode contract (tests/test_trace_ring.py pins it): the ring holds
    exactly the last ``min(capacity, cursor)`` rounds; when wrapped, the
    oldest record sits at slot ``cursor % capacity``; the decoded
    ``(epoch, round)`` stamps are strictly lexicographically increasing."""
    vec = [int(v) for v in digest]
    expected = 2 + len(TRACE_RECORD_FIELDS) * capacity
    if len(vec) != expected:
        raise ValueError(
            f"trace digest carries {len(vec)} values, expected {expected}"
        )
    cursor, wraps = vec[0], vec[1]
    lanes = {
        field: vec[2 + i * capacity : 2 + (i + 1) * capacity]
        for i, field in enumerate(TRACE_RECORD_FIELDS)
    }
    held = min(cursor, capacity)
    start = cursor % capacity if cursor >= capacity else 0
    records = []
    for i in range(held):
        slot = (start + i) % capacity
        rec = {field: lanes[field][slot] for field in TRACE_RECORD_FIELDS}
        rec["seq"] = cursor - held + i
        records.append(rec)
    last = records[-1] if records else dict.fromkeys(TRACE_RECORD_FIELDS, 0)
    return {
        "capacity": capacity,
        "rounds_recorded": cursor,
        "wraps": wraps,
        "rounds_held": held,
        "decisions_held": sum(1 for r in records if r["path"]),
        "conflicts_held": sum(r["conflict"] for r in records),
        "last_round": last["round"],
        "last_epoch": last["epoch"],
        "last_active": last["active"],
        "last_path": last["path"],
        "last_undecided": last["undecided"],
        "records": records,
    }


def zero_trace_summary(capacity: int) -> Dict[str, Any]:
    """The all-zero trace section minted at driver attach (empty ring, no
    records) — same never-mint-a-series-mid-run rule as
    :func:`zero_activity_summary`."""
    return trace_summary(
        [0] * (2 + len(TRACE_RECORD_FIELDS) * capacity), capacity
    )


def trace_recorder_snapshot(
    summary: Dict[str, Any],
    node: str = "(engine)",
    t0_ms: float = 0.0,
    ms_per_round: float = 1.0,
    config_id: Optional[int] = None,
) -> Dict[str, Any]:
    """A decoded ring rendered as a flight-recorder snapshot dict — the
    per-node artifact shape ``tools/traceview.py`` merges — so device rounds
    join the host and ``(chaos)`` lanes of one causally-ordered timeline.

    Device rounds carry no wall clock, so timestamps are synthesized on an
    injected :class:`~rapid_tpu.utils.clock.ManualClock`: record ``seq``
    lands at ``t0_ms + seq * ms_per_round`` (callers aligning against a host
    recording pick the scenario's round cadence). Every round emits one
    registered ``ENGINE_ROUND`` event; conflict rounds add
    ``ENGINE_CONFLICT`` and deciding rounds ``ENGINE_DECISION`` — ranked so
    they interleave correctly with host consensus events at equal stamps."""
    from rapid_tpu.utils.clock import ManualClock
    from rapid_tpu.utils.flight_recorder import EventName, FlightRecorder

    clock = ManualClock()
    records = summary["records"]
    recorder = FlightRecorder(
        node, clock, capacity=max(1, summary["capacity"] * 3)
    )
    for rec in records:
        target = t0_ms + rec["seq"] * ms_per_round
        clock.advance_ms(target - clock.now_ms())
        recorder.record(
            EventName.ENGINE_ROUND,
            config_id=config_id,
            seq=rec["seq"],
            round=rec["round"],
            epoch=rec["epoch"],
            active=rec["active"],
            alerts=rec["alerts"],
            proposals=rec["proposals"],
            undecided=rec["undecided"],
        )
        if rec["conflict"]:
            recorder.record(
                EventName.ENGINE_CONFLICT,
                config_id=config_id,
                seq=rec["seq"],
                epoch=rec["epoch"],
                undecided=rec["undecided"],
            )
        if rec["path"]:
            recorder.record(
                EventName.ENGINE_DECISION,
                config_id=config_id,
                seq=rec["seq"],
                epoch=rec["epoch"],
                path=TRACE_PATH_NAMES.get(rec["path"], str(rec["path"])),
                tally=rec["tally"],
            )
    snap = recorder.snapshot()
    # The ring already dropped rounds before the decode window; surface the
    # TRUE totals so "dropped" reads as rounds lost to wraparound, not as
    # recorder-local arithmetic over the survivors.
    snap["recorded_total"] = summary["rounds_recorded"]
    snap["dropped"] = summary["rounds_recorded"] - summary["rounds_held"]
    return snap


def first_divergent_round(
    a: Dict[str, Any], b: Dict[str, Any]
) -> Optional[int]:
    """The global round ordinal (``seq``) of the first record where two
    decoded rings disagree, or None when their overlapping windows agree
    record-for-record. Compares the overlap of the two held windows plus
    the cursor frontier — the chaos repro artifact's divergence instrument
    (a write-time ring vs a replay-time ring of the same schedule)."""
    by_seq_a = {r["seq"]: r for r in a["records"]}
    by_seq_b = {r["seq"]: r for r in b["records"]}
    shared = sorted(set(by_seq_a) & set(by_seq_b))
    for seq in shared:
        ra, rb = by_seq_a[seq], by_seq_b[seq]
        if any(ra[f] != rb[f] for f in TRACE_RECORD_FIELDS):
            return seq
    if a["rounds_recorded"] != b["rounds_recorded"]:
        # One run recorded more rounds than the other: the first round the
        # shorter run never executed is where the histories fork.
        return min(a["rounds_recorded"], b["rounds_recorded"])
    return None


def compiled_memory_analysis(compiled: Any) -> Optional[Dict[str, int]]:
    """The XLA ``memory_analysis()`` of one compiled executable as a plain
    dict (argument/output/temp/generated-code bytes) — the per-config
    memory-delta instrument. None when the backend does not expose it."""
    try:
        analysis = compiled.memory_analysis()
        return {
            "argument_bytes": int(analysis.argument_size_in_bytes),
            "output_bytes": int(analysis.output_size_in_bytes),
            "temp_bytes": int(analysis.temp_size_in_bytes),
            "generated_code_bytes": int(analysis.generated_code_size_in_bytes),
        }
    except Exception as exc:  # noqa: BLE001 — memory analysis is a bonus
        # diagnostic; any backend without it reports None, not a failure.
        logger.debug("memory_analysis unavailable: %r", exc)
        return None
