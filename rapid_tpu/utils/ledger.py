"""Append-only JSONL run ledger for benchmark / long-running engine runs.

Every perf claim in this repo's trajectory should be attributable (what code
produced it), fresh (measured at HEAD, not replayed), and diagnosable (when
a run wedges, the artifact says exactly how far it got). The flight recorder
gives host nodes that property per message; this ledger gives whole BENCH
runs the same property per stage: one JSON object per line, appended and
flushed as it happens, so even a SIGKILLed or wedged process leaves a
complete prefix pointing at the last completed stage.

Event names come from the registered :class:`LedgerEvent` vocabulary and
stage names from :data:`STAGE_NAMES` — the same discipline the flight
recorder's ``EventName`` enum enforces (free-form strings would fork the
vocabulary and break ``tools/perfview.py``'s timeline rendering); the lint
tier pins both (tests/test_lint.py + tools/analysis/ledger.py).

Line shape::

    {"event": "stage_begin", "seq": 3, "pid": 123, "t_s": 12.345,
     "wall": "2026-08-03T12:00:00Z", "run_id": "...", "stage": "state_build",
     ...fields}

``t_s`` is seconds since the *ledger object's* construction (monotonic);
``wall`` is UTC wall clock for cross-run correlation. The bench's parent
watchdog and its child workload append to ONE file (O_APPEND line writes are
atomic for these line sizes), correlated by ``run_id``/``pid``.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import time
from contextlib import contextmanager
from enum import Enum
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple


class LedgerEvent(Enum):
    """The registered run-ledger event vocabulary (renderers key off it)."""

    RUN_BEGIN = "run_begin"
    RUN_END = "run_end"
    RUN_FAIL = "run_fail"
    ATTEMPT_BEGIN = "attempt_begin"
    ATTEMPT_END = "attempt_end"
    STAGE_BEGIN = "stage_begin"
    STAGE_END = "stage_end"
    STAGE_FAIL = "stage_fail"
    HEARTBEAT_GAP = "heartbeat_gap"
    COMPILE_STATS = "compile_stats"
    DEVICE_MEMORY = "device_memory"
    WATCHDOG_KILL = "watchdog_kill"
    SNAPSHOT_REPLAY = "snapshot_replay"
    METRIC = "metric"
    # Self-healing serving runtime (rapid_tpu/serving/supervisor.py +
    # recovery.py): retry/backoff attempts, deadline wedges, checkpoint
    # writes and corruption fallbacks, deterministic resumes, and
    # poisoned-tenant quarantines — the events perfview renders as the
    # recovery timeline.
    RECOVERY_RETRY = "recovery_retry"
    RECOVERY_WEDGED = "recovery_wedged"
    RECOVERY_CHECKPOINT = "recovery_checkpoint"
    RECOVERY_CHECKPOINT_CORRUPT = "recovery_checkpoint_corrupt"
    RECOVERY_RESUME = "recovery_resume"
    RECOVERY_QUARANTINE = "recovery_quarantine"


#: Registered stage names (parameterize via fields — e.g. ``n=`` — never by
#: minting a new name): the vocabulary perfview's timeline and the parent
#: watchdog's per-stage budgets are defined over.
STAGE_NAMES = frozenset({
    "devices_init",
    "native_build",
    "ramp",
    "state_build",
    "warmup_compile",
    "timed_samples",
    "rtt_probe",
    "xl_point",
    "stretch_point",
    "loss_variant",
    "tenant_fleet",
    "stream",
    "chaos",
    "recovery",
    "hlo_audit",
    "profile",
})


def utc_stamp() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def git_head_rev(root: str) -> Optional[str]:
    """Short HEAD rev of the repo at ``root``, or None when unavailable."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=10,
        ).stdout.strip()
        return out or None
    except (OSError, subprocess.TimeoutExpired):
        return None


def code_hash(root: str, paths: Sequence[str]) -> str:
    """Deterministic sha256 over the measurement-relevant source trees (the
    "hash roots"): every file's relative path + content, sorted, caches and
    compiled artifacts excluded. Unlike a bare git rev this survives
    evidence-only commits AND detects uncommitted edits — two ledgers with
    equal code hashes measured byte-identical code."""
    digest = hashlib.sha256()
    skip_dirs = {"__pycache__", ".git", "target", "build"}
    skip_suffixes = (".pyc", ".so", ".o")
    files: List[Path] = []
    for entry in paths:
        path = Path(root) / entry
        if path.is_file():
            files.append(path)
        elif path.is_dir():
            for sub in path.rglob("*"):
                if not sub.is_file():
                    continue
                if any(part in skip_dirs for part in sub.parts):
                    continue
                if sub.name.endswith(skip_suffixes):
                    continue
                files.append(sub)
    for path in sorted(files):
        rel = os.path.relpath(str(path), root)
        digest.update(rel.encode())
        digest.update(b"\0")
        try:
            digest.update(path.read_bytes())
        except OSError:
            digest.update(b"<unreadable>")
        digest.update(b"\0")
    return digest.hexdigest()[:16]


def provenance(root: str, hash_roots: Sequence[str]) -> Dict[str, Any]:
    """The attribution block every ``run_begin`` carries: git rev + code
    hash over the hash roots, so any number in the ledger can be traced to
    the exact source that produced it."""
    return {
        "git_rev": git_head_rev(root),
        "code_hash": code_hash(root, hash_roots),
        "hash_roots": list(hash_roots),
    }


class RunLedger:
    """Append-only JSONL event writer. Every ``emit`` validates its event
    (and stage) against the registered vocabularies and flushes the line —
    a wedged process's ledger is complete up to the wedge."""

    def __init__(self, path: str, run_id: Optional[str] = None,
                 t0: Optional[float] = None) -> None:
        self.path = str(path)
        self.run_id = run_id or f"run-{os.getpid()}-{int(time.time())}"
        #: ``t_s`` epoch on the monotonic clock. A run spanning several
        #: processes (watchdog parent + attempt children + a fallback
        #: continuation) passes the FIRST writer's epoch along with the
        #: run id, so every process's t_s lands on one shared timeline
        #: (CLOCK_MONOTONIC is system-wide per boot on the platforms this
        #: runs on).
        self.t0 = t0 if t0 is not None else time.monotonic()
        self._seq = 0
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        # Line-buffered append: one write syscall per line (atomic at these
        # sizes), so parent and child can share the file.
        self._file = open(self.path, "a", buffering=1)

    def close(self) -> None:
        if not self._file.closed:
            self._file.close()

    def emit(self, event: LedgerEvent, stage: Optional[str] = None,
             **fields: Any) -> None:
        if not isinstance(event, LedgerEvent):
            raise TypeError(
                f"ledger events must be LedgerEvent members, got {event!r}"
            )
        if stage is not None and stage not in STAGE_NAMES:
            raise ValueError(
                f"unregistered ledger stage {stage!r}; add it to "
                f"rapid_tpu.utils.ledger.STAGE_NAMES"
            )
        record: Dict[str, Any] = {
            "event": event.value,
            "seq": self._seq,
            "pid": os.getpid(),
            "t_s": round(time.monotonic() - self.t0, 3),
            "wall": utc_stamp(),
            "run_id": self.run_id,
        }
        if stage is not None:
            record["stage"] = stage
        record.update(fields)
        self._seq += 1
        self._file.write(json.dumps(record) + "\n")
        self._file.flush()

    @contextmanager
    def stage(self, name: str, timeout_s: Optional[float] = None,
              **fields: Any):
        """One ledger-bracketed stage: ``stage_begin`` (carrying the
        caller's per-stage timeout so the watchdog parent can enforce it
        from the ledger alone), then ``stage_end`` with the measured
        duration — or ``stage_fail`` with the error, re-raised."""
        begin_fields = dict(fields)
        if timeout_s is not None:
            begin_fields["timeout_s"] = timeout_s
        self.emit(LedgerEvent.STAGE_BEGIN, stage=name, **begin_fields)
        start = time.monotonic()
        try:
            yield
        except BaseException as exc:
            self.emit(
                LedgerEvent.STAGE_FAIL, stage=name,
                duration_ms=round((time.monotonic() - start) * 1000.0, 3),
                error=repr(exc),
            )
            raise
        self.emit(
            LedgerEvent.STAGE_END, stage=name,
            duration_ms=round((time.monotonic() - start) * 1000.0, 3),
        )


def read_ledger(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """(events, skipped_lines). Tolerant by design: a torn final line (the
    process died mid-write) or foreign garbage is counted and skipped, never
    an exception — the ledger's whole point is being readable after a
    crash."""
    events: List[Dict[str, Any]] = []
    skipped = 0
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return [], 0
    for line in lines:
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            skipped += 1
            continue
        if isinstance(record, dict) and "event" in record:
            events.append(record)
        else:
            skipped += 1
    return events, skipped


def last_completed_stage(events: Sequence[Dict[str, Any]]) -> Optional[str]:
    """The most recent ``stage_end``'s stage name — what a loud failure
    points at ("got through warmup_compile, died in timed_samples")."""
    for record in reversed(list(events)):
        if record.get("event") == LedgerEvent.STAGE_END.value:
            return record.get("stage")
    return None


def open_stage(events: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The latest ``stage_begin`` without a matching ``stage_end``/
    ``stage_fail`` — the stage a wedged run is stuck in (the watchdog
    parent's per-stage-timeout input)."""
    open_begin: Optional[Dict[str, Any]] = None
    for record in events:
        event = record.get("event")
        if event == LedgerEvent.STAGE_BEGIN.value:
            open_begin = record
        elif event in (LedgerEvent.STAGE_END.value, LedgerEvent.STAGE_FAIL.value):
            if open_begin is not None and open_begin.get("stage") == record.get("stage"):
                open_begin = None
    return open_begin
