"""Seeded 64-bit hashing for ring permutations and configuration identifiers.

The reference orders its K monitoring rings by seeded xxHash of each endpoint
(``rapid/src/main/java/com/vrg/rapid/MembershipView.java:562-587``, via
net.openhft zero-allocation-hashing) and folds endpoint/identifier hashes into
a 64-bit configuration id (``MembershipView.java:544-556``). This module is a
self-contained XXH64 implementation (the environment ships no xxhash package)
plus the fold helpers the rest of the framework uses.

Device kernels never hash strings: hosts hash endpoints once with this module
and ship ``uint32`` hi/lo words to the TPU (see ``rapid_tpu.ops.rings``).
"""

from __future__ import annotations

import struct

_MASK64 = (1 << 64) - 1

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK64


def _round(acc: int, lane: int) -> int:
    acc = (acc + lane * _P2) & _MASK64
    acc = _rotl(acc, 31)
    return (acc * _P1) & _MASK64


def _merge_round(acc: int, val: int) -> int:
    acc ^= _round(0, val)
    return ((acc * _P1) + _P4) & _MASK64


def _avalanche(h: int) -> int:
    h ^= h >> 33
    h = (h * _P2) & _MASK64
    h ^= h >> 29
    h = (h * _P3) & _MASK64
    h ^= h >> 32
    return h


def xxh64(data: bytes, seed: int = 0) -> int:
    """XXH64 of ``data`` with ``seed``; returns an unsigned 64-bit int."""
    n = len(data)
    seed &= _MASK64

    if n >= 32:
        v1 = (seed + _P1 + _P2) & _MASK64
        v2 = (seed + _P2) & _MASK64
        v3 = seed
        v4 = (seed - _P1) & _MASK64
        i = 0
        limit = n - 32
        while i <= limit:
            l1, l2, l3, l4 = struct.unpack_from("<QQQQ", data, i)
            v1 = _round(v1, l1)
            v2 = _round(v2, l2)
            v3 = _round(v3, l3)
            v4 = _round(v4, l4)
            i += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _MASK64
        h = _merge_round(h, v1)
        h = _merge_round(h, v2)
        h = _merge_round(h, v3)
        h = _merge_round(h, v4)
    else:
        h = (seed + _P5) & _MASK64
        i = 0

    h = (h + n) & _MASK64

    while i + 8 <= n:
        (lane,) = struct.unpack_from("<Q", data, i)
        h ^= _round(0, lane)
        h = (_rotl(h, 27) * _P1 + _P4) & _MASK64
        i += 8

    if i + 4 <= n:
        (lane32,) = struct.unpack_from("<I", data, i)
        h ^= (lane32 * _P1) & _MASK64
        h = (_rotl(h, 23) * _P2 + _P3) & _MASK64
        i += 4

    while i < n:
        h ^= (data[i] * _P5) & _MASK64
        h = (_rotl(h, 11) * _P1) & _MASK64
        i += 1

    return _avalanche(h)


def xxh64_int(value: int, seed: int = 0) -> int:
    """Hash an integer by its little-endian 8-byte encoding (signed or unsigned)."""
    return xxh64(struct.pack("<q", _to_signed64(value)), seed)


def xxh64_int4(value: int, seed: int = 0) -> int:
    """Hash an integer by its little-endian 4-byte encoding — the reference's
    ``LongHashFunction.hashInt`` (a Java ``int`` is 4 bytes), used by the
    Java-compatible topology mode for port hashing. The tpu-native default
    hashes ports as 8 bytes (xxh64_int)."""
    return xxh64(struct.pack("<i", value - (1 << 32) if value >= (1 << 31) else value), seed)


def _to_signed64(value: int) -> int:
    value &= _MASK64
    return value - (1 << 64) if value >= (1 << 63) else value


def to_signed64(value: int) -> int:
    """Interpret an unsigned 64-bit value as Java-style signed (for display/parity)."""
    return _to_signed64(value)
