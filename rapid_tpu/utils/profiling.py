"""JAX profiler hooks (SURVEY §5.1: the rebuild adds first-class profiling).

Wraps ``jax.profiler`` so engine convergences and kernel passes can be traced
to TensorBoard-compatible traces without touching call sites:

    from rapid_tpu.utils.profiling import trace
    with trace("/tmp/rapid-trace"):
        vc.run_to_decision()
"""

from __future__ import annotations

from contextlib import contextmanager

import jax


@contextmanager
def trace(log_dir: str):
    """Capture a device+host profile of the enclosed block into ``log_dir``
    (view with TensorBoard or Perfetto)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace span for host-side phases (shows up in the profile)."""
    return jax.profiler.TraceAnnotation(name)
