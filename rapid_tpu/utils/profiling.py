"""JAX profiler hooks (SURVEY §5.1: the rebuild adds first-class profiling).

Wraps ``jax.profiler`` so engine convergences and kernel passes can be traced
to TensorBoard-compatible traces without touching call sites:

    from rapid_tpu.utils.profiling import trace
    with trace("/tmp/rapid-trace"):
        vc.run_to_decision()

Hardened for production use (bench.py wires it in as the opt-in
``--profile`` stage):

- **Graceful no-op** on platforms/builds where ``jax.profiler`` is missing
  or ``start_trace`` fails (some plugin backends raise): the enclosed block
  still runs, a WARNING says no trace was captured, and nothing crashes —
  profiling must never be able to take down the run it observes.
- **No nesting**: ``jax.profiler.start_trace`` inside an active trace is a
  runtime error deep in XLA with an unhelpful message; this wrapper rejects
  it eagerly with a clear one. (Module-level flag: the profiler itself is a
  process-wide singleton, so a process-wide guard is the correct scope.)
"""

from __future__ import annotations

import logging
from contextlib import contextmanager, nullcontext

logger = logging.getLogger(__name__)

#: True while a ``trace()`` block is active in this process (the underlying
#: profiler is process-global, so the guard is too).
_active = False


def profiler_available() -> bool:
    """True iff this JAX build exposes a usable ``jax.profiler``."""
    try:
        import jax

        return hasattr(jax, "profiler") and hasattr(jax.profiler, "start_trace")
    except ImportError:
        return False


@contextmanager
def trace(log_dir: str):
    """Capture a device+host profile of the enclosed block into ``log_dir``
    (view with TensorBoard or Perfetto). No-ops with a WARNING when the
    profiler is unavailable or fails to start; raises ``RuntimeError`` when
    called inside an active ``trace()`` block (the profiler cannot nest)."""
    global _active
    if _active:
        raise RuntimeError(
            "profiling.trace() does not nest: a trace is already active in "
            "this process — close it before starting another"
        )
    started = False
    _active = True
    try:
        if profiler_available():
            import jax

            try:
                jax.profiler.start_trace(log_dir)
                started = True
            except Exception as exc:  # noqa: BLE001 — profiling is an
                # opt-in diagnostic: a backend that cannot start a trace
                # (plugin without profiler support, busy session) must not
                # fail the profiled workload.
                logger.warning(
                    "jax.profiler.start_trace(%r) failed (%r); "
                    "running unprofiled", log_dir, exc,
                )
        else:
            logger.warning(
                "jax.profiler unavailable on this platform; running unprofiled"
            )
        yield
    finally:
        _active = False
        if started:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception as exc:  # noqa: BLE001 — a failed stop leaves
                # no trace file but the profiled block already ran; log,
                # don't mask the block's own outcome.
                logger.warning("jax.profiler.stop_trace() failed: %r", exc)


def annotate(name: str):
    """Named trace span for host-side phases (shows up in the profile);
    a no-op context manager when the profiler is unavailable."""
    if profiler_available():
        import jax

        try:
            return jax.profiler.TraceAnnotation(name)
        except Exception as exc:  # noqa: BLE001 — same opt-in-diagnostic
            # contract as trace(): degrade to a no-op span.
            logger.warning("TraceAnnotation(%r) unavailable: %r", name, exc)
    return nullcontext()
