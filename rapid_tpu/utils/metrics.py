"""First-class metrics: counters and bounded timer histograms.

The reference has no runtime metrics at all (SURVEY §5.1/5.5 — logging and
subscriptions only); this registry gives every node and the virtual-cluster
engine cheap counters plus latency histograms, headlined by the north-star
timer, view-change convergence.

Two production constraints shape the design:

- **Bounded memory.** Timings land in fixed-schedule ``LogHistogram``s
  (utils/histogram.py), not unbounded lists: a node that records a million
  samples holds O(buckets), and its snapshot renders as a real Prometheus
  histogram (``_bucket``/``_sum``/``_count``) in utils/exposition.py.
- **Injected time.** The owning component passes its protocol clock's
  ``now_ms`` at construction, so ``timer()``/``mark()`` measure simulated
  time correctly under ``ManualClock`` — wall clock is only the default for
  registries with no protocol clock (e.g. the device engine's dispatch
  counters). The lint tier (tools/staticcheck.py) bans direct wall-clock
  reads inside rapid_tpu/protocol/ to keep it that way.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Callable, Dict, Optional

from rapid_tpu.utils.histogram import LogHistogram


def _wall_now_ms() -> float:
    return time.perf_counter_ns() / 1e6


class Metrics:
    def __init__(self, now_ms: Optional[Callable[[], float]] = None) -> None:
        self.counters: Dict[str, int] = defaultdict(int)
        #: Plain timers: name -> bounded histogram.
        self.timings: Dict[str, LogHistogram] = {}
        #: Labeled timer families: name -> phase -> bounded histogram (a
        #: phase key may carry a secondary label as "phase/path", e.g.
        #: "agreement/fast" — utils/exposition.py splits it).
        self.phase_timings: Dict[str, Dict[str, LogHistogram]] = {}
        self._marks: Dict[str, float] = {}
        self._now_ms = now_ms if now_ms is not None else _wall_now_ms

    def now_ms(self) -> float:
        """This registry's clock reading (the injected source, or wall)."""
        return self._now_ms()

    def inc(self, name: str, value: int = 1) -> None:
        self.counters[name] += value

    def record_ms(self, name: str, value_ms: float, phase: Optional[str] = None) -> None:
        if phase is None:
            hist = self.timings.get(name)
            if hist is None:
                hist = self.timings[name] = LogHistogram()
        else:
            family = self.phase_timings.setdefault(name, {})
            hist = family.get(phase)
            if hist is None:
                hist = family[phase] = LogHistogram()
        hist.observe(value_ms)

    @contextmanager
    def timer(self, name: str):
        start = self._now_ms()
        try:
            yield
        finally:
            self.record_ms(name, self._now_ms() - start)

    def mark(self, name: str, now_ms: float | None = None) -> None:
        """Start (or restart) a named epoch for ``elapsed_since_ms``. The
        injected clock supplies the default reading; pass one explicitly to
        reuse a reading the caller already took this tick."""
        self._marks[name] = now_ms if now_ms is not None else self._now_ms()

    def has_mark(self, name: str) -> bool:
        return name in self._marks

    def clear_mark(self, name: str) -> None:
        self._marks.pop(name, None)

    def elapsed_since_ms(self, name: str, now_ms: float | None = None) -> float:
        start = self._marks.get(name)
        if start is None:
            return 0.0
        now = now_ms if now_ms is not None else self._now_ms()
        return now - start

    def summary(self) -> Dict[str, object]:
        """Counters verbatim; every timer as its bounded histogram summary
        (``<name>_ms`` -> {count,last,p50,p90,p99,max,sum,buckets}); every
        phase family as ``<name>_ms`` -> {phase: histogram summary}."""
        out: Dict[str, object] = dict(self.counters)
        for name, hist in self.timings.items():
            if hist.count:
                out[f"{name}_ms"] = hist.summary()
        for name, family in self.phase_timings.items():
            phases = {
                phase: hist.summary() for phase, hist in family.items() if hist.count
            }
            if phases:
                out[f"{name}_ms"] = phases
        return out
