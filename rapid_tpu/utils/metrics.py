"""First-class metrics: counters and timers.

The reference has no runtime metrics at all (SURVEY §5.1/5.5 — logging and
subscriptions only); this registry gives every node and the virtual-cluster
engine cheap counters plus the north-star timer, view-change convergence.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List


class Metrics:
    def __init__(self) -> None:
        self.counters: Dict[str, int] = defaultdict(int)
        self.timings_ms: Dict[str, List[float]] = defaultdict(list)
        self._marks: Dict[str, float] = {}

    def inc(self, name: str, value: int = 1) -> None:
        self.counters[name] += value

    def record_ms(self, name: str, value_ms: float) -> None:
        self.timings_ms[name].append(value_ms)

    @contextmanager
    def timer(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record_ms(name, (time.perf_counter() - start) * 1000.0)

    def mark(self, name: str, now_ms: float | None = None) -> None:
        """Start (or restart) a named epoch for ``elapsed_since_ms``. Pass the
        owning component's clock reading for simulated-time correctness."""
        self._marks[name] = now_ms if now_ms is not None else time.perf_counter_ns() / 1e6

    def elapsed_since_ms(self, name: str, now_ms: float | None = None) -> float:
        start = self._marks.get(name)
        if start is None:
            return 0.0
        now = now_ms if now_ms is not None else time.perf_counter_ns() / 1e6
        return now - start

    def summary(self) -> Dict[str, object]:
        out: Dict[str, object] = dict(self.counters)
        for name, values in self.timings_ms.items():
            if values:
                ordered = sorted(values)
                out[f"{name}_ms"] = {
                    "count": len(values),
                    "last": round(values[-1], 3),
                    "p50": round(ordered[len(ordered) // 2], 3),
                    "max": round(ordered[-1], 3),
                }
        return out
