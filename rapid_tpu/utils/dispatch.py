"""The host-side engine dispatch seam, shared by every device driver.

``VirtualCluster`` and ``TenantFleet`` (and, through them, the streaming
pipeline in ``rapid_tpu/serving``) observe the device engine at the same
grain: transfer bytes charged at the host<->device boundary, and one bounded
latency histogram per dispatch phase (``engine_dispatch_ms{phase=...}``).
Before this seam was shared, the two drivers carried copy-pasted methods and
the phase labels were free strings — a typo'd phase would silently mint a
new histogram series and vanish from every dashboard keyed on the known
names. :data:`ENGINE_DISPATCH_PHASES` is the registered phase vocabulary,
enforced at WRITE time (the ledger's ``STAGE_NAMES`` discipline applied to
the telemetry tier): an unregistered phase raises instead of forking the
vocabulary.

The ``stream_enqueue`` / ``stream_fetch`` pair is the streaming pipeline's
split of the old dispatch+fetch grain: an enqueued dispatch returns as soon
as JAX has queued the program (host time spent *submitting*), while a fetch
phase brackets the explicit synchronization boundaries (host time spent
*blocked on the device*). Their separation is what makes overlap efficiency
measurable from the histograms alone (``serving/stream.py``).
"""

from __future__ import annotations

import time
from contextlib import contextmanager

#: The registered dispatch-phase vocabulary — every ``_dispatch(...)`` entry
#: across the engine drivers. Parameterize by metric fields, never by
#: minting a phase name: renderers (clustertop's DISP99 merge, perfview,
#: scrape configs) key off these labels, and the golden-name tests pin the
#: series they produce.
ENGINE_DISPATCH_PHASES = frozenset({
    # VirtualCluster entrypoints.
    "step",
    "sync",
    "run_to_decision",
    "run_until_membership",
    # TenantFleet entrypoints.
    "fleet_step",
    "fleet_decision",
    "fleet_wave",
    # The per-tenant health reduction (the serving supervision tier's
    # poisoned-tenant tripwire, rapid_tpu/serving/supervisor.py).
    "health_scan",
    # Streaming pipeline (rapid_tpu/serving): enqueue-only dispatches and
    # the explicit fetch boundaries they synchronize at.
    "stream_enqueue",
    "stream_fetch",
})


class DispatchSeam:
    """Mixin: transfer-byte accounting + the phase-validated dispatch timer.

    Hosts must provide ``self.metrics`` (a :class:`rapid_tpu.utils.metrics.
    Metrics` registry); everything here writes through it.
    """

    def _account_h2d(self, *arrays) -> None:
        """Charge host->device uploads (indices, masks, initial state) to
        the transfer-byte counter. Host-side accounting at the driver seams:
        only arrays that originate on the host are charged, which is exactly
        the traffic a remote-tunnel deployment pays for."""
        self.metrics.inc(
            "engine_h2d_bytes",
            int(sum(int(getattr(a, "nbytes", 0) or 0) for a in arrays)),
        )

    def _account_d2h(self, nbytes: int) -> None:
        self.metrics.inc("engine_d2h_bytes", int(nbytes))

    @contextmanager
    def _dispatch(self, entry: str):
        """Time one device dispatch (and any fetch the caller performs
        inside the block) into the bounded per-phase latency histogram
        (``engine_dispatch_ms{phase=<entry>}``) and bump the dispatch
        counter — the engine's per-dispatch observability grain. ``entry``
        must come from :data:`ENGINE_DISPATCH_PHASES`; a typo fails here,
        at write time, instead of silently forking the series set."""
        if entry not in ENGINE_DISPATCH_PHASES:
            raise ValueError(
                f"unregistered engine dispatch phase {entry!r}; add it to "
                f"rapid_tpu.utils.dispatch.ENGINE_DISPATCH_PHASES"
            )
        self.metrics.inc("engine_dispatches")
        start = time.perf_counter()
        try:
            yield
        finally:
            self.metrics.record_ms(
                "engine_dispatch",
                (time.perf_counter() - start) * 1000.0,
                phase=entry,
            )
