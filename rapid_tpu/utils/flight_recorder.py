"""Per-node flight recorder: a bounded ring of structured protocol events.

The paper's evaluation (Table 2, §5 convergence timelines) was produced with
*external* OS instrumentation because the reference ships no runtime
telemetry. The `Metrics` registry (utils/metrics.py) already closes the
counter gap; this module closes the *narrative* gap — "show me this one view
change, end to end, across all nodes". Every node keeps a fixed-size ring
buffer of structured protocol events (alert tx/rx, cut-detector watermark
crossings, fast-round proposal/tally, classic-fallback engagement, catch-up
pulls, view-change delivery) stamped with the node's protocol clock (so
timestamps are correct under simulated time, utils/clock.py) and a
correlation key — the ``trace_id`` minted at the first alert of a
configuration change and carried on the wire (messaging/codec.py). A
recording is Dapper-style raw material: ``tools/traceview.py`` merges the
per-node rings into one causally-ordered timeline.

The ring is deliberately dumb and allocation-cheap: recording is a list
store at an incrementing index, never a dict resize or a lock (the whole
protocol runs on one event loop). Overwrite is the intended behavior — a
recorder is a *flight* recorder, sized to hold the last few view changes of
context at the moment someone asks "what just happened".
"""

from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional

from rapid_tpu.utils.clock import Clock
from rapid_tpu.utils.xxhash import xxh64


def mint_trace_id(node: str, config_id: int, now_ms: float) -> int:
    """Mint the correlation key for one membership change: a u64 hash of
    (minting node, configuration, protocol-clock time). Deterministic given
    its inputs — crucially it does NOT consume the service's seeded ``rng``
    stream, so enabling tracing can never perturb peer selection or
    consensus jitter in a reproducible test. Never returns 0 (the wire
    treats the field as optional; 0 stays a valid, if unlikely, id — the
    guard just keeps minted ids visibly non-degenerate)."""
    value = xxh64(f"{node}|{config_id}|{now_ms}".encode("utf-8"), seed=0x7A11)
    return value or 1


class EventName(enum.Enum):
    """Registered flight-recorder event vocabulary.

    The lint gate (tests/test_lint.py) enforces that every ``record()`` call
    site in rapid_tpu/ names an attribute of this enum — free-form strings
    would silently fork the vocabulary and break traceview's phase ordering.
    """

    # Alert pipeline
    ALERT_ENQUEUED = "alert_enqueued"
    ALERT_BATCH_TX = "alert_batch_tx"
    ALERT_BATCH_RX = "alert_batch_rx"
    ALERT_REDELIVERY = "alert_redelivery"
    # Cut detector watermarks
    CUT_L_CROSSED = "cut_l_crossed"
    CUT_H_CROSSED = "cut_h_crossed"
    CUT_RELEASED = "cut_released"
    # Consensus
    FAST_ROUND_PROPOSAL = "fast_round_proposal"
    FAST_ROUND_VOTE_RX = "fast_round_vote_rx"
    CLASSIC_ROUND_START = "classic_round_start"
    CLASSIC_PHASE2A_TX = "classic_phase2a_tx"
    CONSENSUS_DECIDED = "consensus_decided"
    # Hierarchical membership (rapid_tpu/hier): cohort fast path + global tier
    COHORT_CUT_DECIDED = "cohort_cut_decided"
    COHORT_CUT_FORWARDED = "cohort_cut_forwarded"
    COHORT_CUT_RX = "cohort_cut_rx"
    GLOBAL_DECISION = "global_decision"
    # View lifecycle
    VIEW_CHANGE = "view_change"
    KICKED = "kicked"
    # Delivery-liveness machinery
    CATCH_UP_PULL = "catch_up_pull"
    CATCH_UP_RESULT = "catch_up_result"
    CONFIG_BEACON_TX = "config_beacon_tx"
    UNKNOWN_JOINER_WEDGE = "unknown_joiner_wedge"
    # Device engine round-trace ring (models/state.TraceRing): one decoded
    # ring record per fused-engine round, synthesized at fetch boundaries by
    # utils/engine_telemetry.trace_recorder_snapshot so traceview merges
    # device rounds into the same timeline as host and chaos lanes.
    ENGINE_ROUND = "engine_round"
    ENGINE_CONFLICT = "engine_conflict"
    ENGINE_DECISION = "engine_decision"

    # Causal phase rank within one membership change: used by traceview to
    # order events that share a timestamp (simulated clocks tick coarsely).
    @property
    def phase_rank(self) -> int:
        return _PHASE_RANK[self]


_PHASE_RANK: Dict[EventName, int] = {
    EventName.ALERT_ENQUEUED: 0,
    EventName.ALERT_BATCH_TX: 1,
    EventName.ALERT_REDELIVERY: 1,
    EventName.ALERT_BATCH_RX: 2,
    EventName.CUT_L_CROSSED: 3,
    EventName.CUT_H_CROSSED: 4,
    EventName.CUT_RELEASED: 5,
    EventName.FAST_ROUND_PROPOSAL: 6,
    EventName.FAST_ROUND_VOTE_RX: 7,
    EventName.CLASSIC_ROUND_START: 8,
    EventName.CLASSIC_PHASE2A_TX: 9,
    EventName.CONSENSUS_DECIDED: 10,
    # The hierarchy's second tier runs after a cohort's consensus decided
    # and before the view change delivers: rank between them.
    EventName.COHORT_CUT_DECIDED: 10,
    EventName.COHORT_CUT_FORWARDED: 11,
    EventName.COHORT_CUT_RX: 11,
    EventName.GLOBAL_DECISION: 12,
    EventName.CATCH_UP_PULL: 11,
    EventName.CATCH_UP_RESULT: 12,
    EventName.CONFIG_BEACON_TX: 11,
    EventName.UNKNOWN_JOINER_WEDGE: 12,
    EventName.VIEW_CHANGE: 13,
    EventName.KICKED: 13,
    # Device rounds: the round record opens its timestamp's pipeline; the
    # conflict flag aligns with the classic-fallback window and the decision
    # with CONSENSUS_DECIDED, so a host recording and a decoded ring of the
    # same scenario interleave in causal order at equal timestamps.
    EventName.ENGINE_ROUND: 0,
    EventName.ENGINE_CONFLICT: 9,
    EventName.ENGINE_DECISION: 10,
}


class FlightEvent:
    """One recorded protocol event. Plain attributes, not a dataclass: the
    recorder allocates one of these per record() on the protocol hot path."""

    __slots__ = ("seq", "t_ms", "node", "name", "config_id", "trace_id", "fields")

    def __init__(
        self,
        seq: int,
        t_ms: float,
        node: str,
        name: EventName,
        config_id: Optional[int],
        trace_id: Optional[int],
        fields: Dict[str, Any],
    ) -> None:
        self.seq = seq
        self.t_ms = t_ms
        self.node = node
        self.name = name
        self.config_id = config_id
        self.trace_id = trace_id
        self.fields = fields

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "t_ms": self.t_ms,
            "node": self.node,
            "name": self.name.value,
            "config_id": self.config_id,
            "trace_id": self.trace_id,
            "fields": self.fields,
        }

    def __repr__(self) -> str:  # debugging aid, not wire format
        return (
            f"FlightEvent(#{self.seq} t={self.t_ms} {self.node} "
            f"{self.name.value} cfg={self.config_id} trace={self.trace_id} "
            f"{self.fields})"
        )


class FlightRecorder:
    """Fixed-capacity ring buffer of :class:`FlightEvent`.

    ``clock`` is the owning component's protocol clock — under
    ``ManualClock`` the recording carries simulated timestamps, which is
    what makes recordings from a simulated-time test mergeable.
    """

    DEFAULT_CAPACITY = 512

    def __init__(self, node: str, clock: Clock, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.node = node
        self.capacity = capacity
        self._clock = clock
        self._buf: List[Optional[FlightEvent]] = [None] * capacity
        self._total = 0  # events ever recorded; ring index = seq % capacity

    # -- recording -----------------------------------------------------

    def record(
        self,
        name: EventName,
        config_id: Optional[int] = None,
        trace_id: Optional[int] = None,
        **fields: Any,
    ) -> FlightEvent:
        event = FlightEvent(
            seq=self._total,
            t_ms=self._clock.now_ms(),
            node=self.node,
            name=name,
            config_id=config_id,
            trace_id=trace_id,
            fields=fields,
        )
        self._buf[self._total % self.capacity] = event
        self._total += 1
        return event

    # -- inspection ----------------------------------------------------

    def __len__(self) -> int:
        """Events currently held (== depth gauge in the exposition)."""
        return min(self._total, self.capacity)

    @property
    def recorded_total(self) -> int:
        return self._total

    @property
    def dropped(self) -> int:
        """Events overwritten by ring wraparound."""
        return max(0, self._total - self.capacity)

    def events(self) -> List[FlightEvent]:
        """Held events, oldest first."""
        if self._total <= self.capacity:
            return [e for e in self._buf[: self._total] if e is not None]
        start = self._total % self.capacity
        out = self._buf[start:] + self._buf[:start]
        return [e for e in out if e is not None]

    def tail(self, n: int) -> List[FlightEvent]:
        return self.events()[-n:] if n > 0 else []

    def snapshot(self, tail: Optional[int] = None) -> Dict[str, Any]:
        """JSON-ready recording: metadata + (tail of) the event ring. This is
        the per-node artifact ``tools/traceview.py`` merges."""
        events = self.events() if tail is None else self.tail(tail)
        return {
            "node": self.node,
            "capacity": self.capacity,
            "recorded_total": self._total,
            "dropped": self.dropped,
            "events": [e.to_dict() for e in events],
        }
