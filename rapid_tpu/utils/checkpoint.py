"""Checkpoint / resume.

The reference persists nothing; its serializable state is exactly the
``Configuration`` — (identifiers-seen, ring-0 member list) — documented as
sufficient to reconstruct an identical view (``MembershipView.java:521-533``)
and streamed to every joiner. This module makes that durable:

- host path: ``Configuration`` <-> bytes (the wire codec's field layout), so a
  node can restart into a known view and rejoin from peers;
- device path: the whole ``EngineState`` <-> one ``.npz`` file, so a 100K-node
  virtual cluster resumes mid-protocol (reports, votes, FD counters intact);
- serving path: :func:`save_serving_state` / :func:`load_serving_state` — one
  crash-consistent checkpoint of a whole serving target (state + faults, and
  for fleet-stacked targets the per-tenant knob lanes) plus a JSON meta block
  (the supervisor's wave cursor, rapid_tpu/serving/recovery.py).

Durability discipline (every writer here): the payload is sealed with an
xxh64 integrity trailer (the in-tree ``utils/xxhash.py``) and published by
atomic tmp-file + ``os.replace`` — a reader never observes a half-written
file, and a torn/bit-flipped/truncated one fails loudly as
:class:`CheckpointCorruptError` (a named error the recovery tier can fall
back on) instead of a numpy/zipfile/struct traceback. Pre-trailer
checkpoints still load (the trailer is detected, never assumed).
"""

from __future__ import annotations

import io
import json
import logging
import os
import struct
from pathlib import Path
from typing import TYPE_CHECKING, Any, Dict, Optional, Tuple

import numpy as np

from rapid_tpu.utils.xxhash import xxh64

LOG = logging.getLogger(__name__)


class CheckpointCorruptError(ValueError):
    """A checkpoint file failed its framing or integrity checks (truncated,
    bit-flipped, bad magic, or an unreadable archive). Subclasses ValueError
    so pre-hardening callers that caught ValueError keep working; the
    recovery tier catches THIS name to fall back to an older checkpoint."""


#: Integrity trailer: payload || 8-byte LE xxh64(payload) || magic.
_TRAILER_MAGIC = b"RTXS"
_TRAILER_LEN = 8 + len(_TRAILER_MAGIC)


def _seal(payload: bytes) -> bytes:
    return payload + struct.pack("<Q", xxh64(payload)) + _TRAILER_MAGIC


def _unseal(data: bytes, path) -> bytes:
    """Verify and strip the integrity trailer. Files from pre-trailer
    writers (no magic) pass through unverified — backward compatible, and a
    truncation that happens to cut the trailer off cleanly still fails
    downstream on the archive framing."""
    if len(data) >= _TRAILER_LEN and data[-len(_TRAILER_MAGIC):] == _TRAILER_MAGIC:
        payload = data[:-_TRAILER_LEN]
        (digest,) = struct.unpack("<Q", data[-_TRAILER_LEN:-len(_TRAILER_MAGIC)])
        if xxh64(payload) != digest:
            raise CheckpointCorruptError(
                f"{path}: checkpoint integrity trailer mismatch (the file "
                f"was corrupted after it was written)"
            )
        return payload
    return data


def _atomic_write(path, data: bytes) -> None:
    """Publish ``data`` at ``path`` via tmp-file + rename: a crash mid-write
    leaves the previous checkpoint intact, never a half-written file under
    the published name."""
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _default_retired(cfg):
    import jax.numpy as jnp

    LOG.warning(
        "checkpoint predates the 'retired' field: retirement history is "
        "unrecoverable — do not re-admit previously-removed slots after "
        "this resume"
    )
    return jnp.zeros((cfg.n,), dtype=bool)

from rapid_tpu.messaging.codec import (
    Reader,
    Writer,
    read_endpoint,
    read_node_id,
    write_endpoint,
    write_node_id,
)
from rapid_tpu.protocol.view import (
    TOPOLOGY_JAVA,
    TOPOLOGY_NATIVE,
    Configuration,
    MembershipView,
)

if TYPE_CHECKING:
    from rapid_tpu.models.state import EngineConfig, EngineState

_MAGIC = b"RTCF"
# v2 appends a topology-mode byte; v1 checkpoints (which predate the
# java-compat mode and were always native) still load. Native configs are
# WRITTEN as v1: the trailing byte buys nothing in the default case, and
# emitting v2 would make every checkpoint unreadable to older readers that
# only accept v1 — forward incompatibility reserved for java-mode configs,
# which older readers could not resume correctly anyway.
_VERSION = 2
_TOPOLOGY_CODES = {TOPOLOGY_NATIVE: 0, TOPOLOGY_JAVA: 1}
_TOPOLOGY_NAMES = {code: name for name, code in _TOPOLOGY_CODES.items()}


def configuration_to_bytes(config: Configuration) -> bytes:
    w = Writer()
    w.raw(_MAGIC)
    version = 1 if config.topology == TOPOLOGY_NATIVE else _VERSION
    w.u8(version)
    w.u32(len(config.node_ids))
    for nid in config.node_ids:
        write_node_id(w, nid)
    w.u32(len(config.endpoints))
    for ep in config.endpoints:
        write_endpoint(w, ep)
    if version >= 2:
        w.u8(_TOPOLOGY_CODES[config.topology])
    return w.getvalue()


def configuration_from_bytes(data: bytes) -> Configuration:
    if data[:4] != _MAGIC:
        raise CheckpointCorruptError("not a rapid_tpu configuration checkpoint")
    r = Reader(data[4:])
    try:
        version = r.u8()
        if version not in (1, _VERSION):
            raise ValueError(f"unsupported checkpoint version {version}")
        node_ids = tuple(read_node_id(r) for _ in range(r.u32()))
        endpoints = tuple(read_endpoint(r) for _ in range(r.u32()))
        if version == 1:
            topology = TOPOLOGY_NATIVE
        else:
            code = r.u8()
            if code not in _TOPOLOGY_NAMES:
                raise ValueError(f"unknown topology code {code} in checkpoint")
            topology = _TOPOLOGY_NAMES[code]
    except CheckpointCorruptError:
        raise
    except (struct.error, IndexError, ValueError, EOFError) as exc:
        # A truncated/bit-flipped blob must surface as the NAMED error, not
        # a struct/codec traceback — the recovery tier dispatches on it.
        raise CheckpointCorruptError(
            f"truncated or corrupt configuration checkpoint: {exc}"
        ) from exc
    return Configuration(node_ids, endpoints, topology=topology)


def save_configuration(path, config: Configuration) -> None:
    """Durable twin of :func:`configuration_to_bytes`: xxh64-sealed payload
    published by atomic tmp+rename."""
    _atomic_write(path, _seal(configuration_to_bytes(config)))


def load_configuration(path) -> Configuration:
    """Load a :func:`save_configuration` file (or a raw pre-trailer blob);
    truncation/corruption raises :class:`CheckpointCorruptError`."""
    return configuration_from_bytes(_unseal(Path(path).read_bytes(), path))


def view_from_configuration(config: Configuration, k: int) -> MembershipView:
    """Resume: rebuild the K rings from a configuration snapshot (the
    snapshot's topology mode rides along, so a java-compat cluster resumes
    java-compat)."""
    return MembershipView(
        k,
        node_ids=config.node_ids,
        endpoints=config.endpoints,
        topology=config.topology,
    )


def _cfg_entries(cfg: "EngineConfig") -> Dict[str, np.ndarray]:
    return {
        "__cfg__": np.asarray(list(cfg), dtype=np.int64),
        # Field names pin value->field pairing across EngineConfig schema
        # changes: positional loading silently misassigns values once any
        # non-trailing field is added/removed.
        "__cfg_fields__": np.asarray(cfg._fields, dtype=np.str_),
    }


def _npz_bytes(entries: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, **entries)
    return buf.getvalue()


class _LoadedNpz(dict):
    """A fully-materialized checkpoint archive, quacking like the NpzFile
    the loaders were written against (mapping + ``.files`` + a no-op
    context manager — every member is already decompressed in memory)."""

    @property
    def files(self):
        return list(self)

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        return False


def _open_npz(path) -> _LoadedNpz:
    """Read, integrity-check, and FULLY load a sealed .npz checkpoint;
    every corruption class surfaces as :class:`CheckpointCorruptError`,
    never a zipfile/zlib/numpy traceback. Members are decompressed eagerly
    here — member corruption under an intact central directory (a
    trailer-less legacy file, or damage confined to the trailer bytes that
    :func:`_unseal` passes through unverified) only manifests at
    decompression, and deferring it would leak a raw ``zlib.error``
    through the recovery tier's named-error fallback."""
    import zipfile
    import zlib

    payload = _unseal(Path(path).read_bytes(), path)
    try:
        with np.load(io.BytesIO(payload)) as data:
            return _LoadedNpz({k: data[k] for k in data.files})
    except (
        zipfile.BadZipFile, zlib.error, ValueError, OSError, EOFError,
        KeyError,
    ) as exc:
        raise CheckpointCorruptError(
            f"{path}: truncated or corrupt checkpoint archive: {exc}"
        ) from exc


def _settle_device_owned(tree):
    """Copy every leaf of a just-loaded pytree into an executable-OWNED
    device buffer (one jitted identity-copy, ~ms per load).

    Hard-won (root-caused via the bench ``recovery`` drill; sibling note in
    tools/analysis/device_program.py's cache scoping): on this jaxlib's CPU
    backend, arrays materialized from host numpy buffers — exactly what a
    checkpoint load produces — can later be DONATED into an engine
    executable that was DESERIALIZED from the persistent compilation
    cache, and the donation then frees memory the backend does not own:
    an intermittent glibc double-free/segfault (~1 in 3 at the recovery
    drill's shape). Buffers that are executable OUTPUTS are device-owned
    and donation-safe, so every loader below routes its pytrees through
    this copy before handing them to a driver."""
    import jax
    import jax.numpy as jnp

    settled = jax.jit(lambda t: jax.tree_util.tree_map(jnp.copy, t))(tree)
    jax.block_until_ready(settled)
    return settled


def save_engine_state(path, cfg: "EngineConfig", state: "EngineState") -> None:
    arrays = {field: np.asarray(value) for field, value in state._asdict().items()}
    # Derived data is never persisted: ring_perm is a pure function of the
    # key lanes, and loading a stale/corrupted copy would silently diverge
    # topology from the keys. Load always recomputes it (one sort).
    arrays.pop("ring_perm", None)
    _atomic_write(path, _seal(_npz_bytes({**_cfg_entries(cfg), **arrays})))


def load_engine_state(path) -> Tuple["EngineConfig", "EngineState"]:
    from rapid_tpu.models.state import (
        EngineConfig,
        EngineState,
        compaction_policy,
        lane_dtypes,
    )

    with _open_npz(path) as data:
        vals = [int(v) for v in data["__cfg__"]]
        if "__cfg_fields__" in data:
            # Name-keyed: removed fields' saved values are dropped, fields
            # added since the checkpoint fill from EngineConfig defaults.
            saved = dict(zip([str(f) for f in data["__cfg_fields__"]], vals))
            cfg = EngineConfig(**{
                f: saved[f] for f in EngineConfig._fields if f in saved
            })
        else:
            # Legacy checkpoints (no name map, written round <= 2): values
            # are positional over the 12 pre-round-3 fields, optionally
            # followed by the since-deleted pallas_watermark — never by any
            # round-3+ field (those writers always emit the name map). So:
            # take the stable 12, drop the stale tail, default the rest.
            legacy_fields = 12  # ... through delivery_prob_permille
            cfg = EngineConfig(*vals[:legacy_fields])
        import jax.numpy as jnp

        from rapid_tpu.ops.rings import ring_perms as _ring_perms

        # Fields added after a checkpoint was written fill with their
        # initial-state defaults (per-configuration state is safe to reset:
        # at worst a fallback restarts from round 2) — at the POLICY dtypes
        # of the saved config, so a compact checkpoint's filled lanes match
        # the lanes the engine would have built (models/state
        # compaction_policy; wide configs keep the historical int32s).
        dts = {f: jnp.dtype(d) for f, d in lane_dtypes(cfg).items()}
        fire_never = compaction_policy(cfg).fire_never
        defaults = {
            "cp_rnd_r": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_rnd_r"]),
            "cp_rnd_i": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_rnd_i"]),
            "cp_vrnd_r": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_vrnd_r"]),
            "cp_vrnd_i": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_vrnd_i"]),
            "cp_vval_src": lambda: jnp.full(
                (cfg.n,), -1, dtype=dts["cp_vval_src"]
            ),
            "classic_epoch": lambda: jnp.zeros((), dtype=dts["classic_epoch"]),
            "fire_round": lambda: jnp.where(
                jnp.asarray(data["fd_fired"]),
                jnp.zeros((), dtype=dts["fire_round"]),
                jnp.asarray(fire_never, dtype=dts["fire_round"]),
            ),
            "round_idx": lambda: jnp.int32(0),
            "fd_hist": lambda: jnp.zeros((cfg.n, cfg.k), dtype=dts["fd_hist"]),
            # NOT per-configuration state: retirement is cross-configuration
            # history and cannot be reconstructed from an old checkpoint.
            # Resuming one forgets which identity lanes were spent — callers
            # must not re-admit previously-removed slots after such a resume
            # (warned below).
            "retired": lambda: _default_retired(cfg),
            # Derived, not stateful: recompute from the (always-saved) key
            # lanes for checkpoints written before the field existed.
            "ring_perm": lambda: _ring_perms(
                jnp.asarray(data["key_hi"]), jnp.asarray(data["key_lo"])
            ).astype(dts["ring_perm"]),
        }
        arrays = {}
        for field in EngineState._fields:
            if field == "ring_perm":
                # Always derived from the key lanes — a persisted copy (from
                # any writer) is ignored rather than trusted for coherence.
                arrays[field] = defaults[field]()
            elif field in data:
                arrays[field] = jnp.asarray(data[field])
            elif field in defaults:
                arrays[field] = defaults[field]()
            else:
                raise KeyError(
                    f"checkpoint missing field {field!r} with no known default"
                )
        state = _settle_device_owned(EngineState(**arrays))
    return cfg, state


# ---------------------------------------------------------------------------
# Serving checkpoints: the whole serving target (state + faults [+ knobs]),
# wide / compact / bit-packed / fleet-stacked alike, plus a meta cursor
# ---------------------------------------------------------------------------

def save_serving_state(
    path,
    cfg: "EngineConfig",
    state: "EngineState",
    faults,
    knobs=None,
    meta: Optional[Dict[str, Any]] = None,
) -> None:
    """One crash-consistent checkpoint of a serving target: the state AND
    fault pytrees (and, for a fleet, the [t] knob lanes) exactly as stored —
    shapes and dtypes round-trip verbatim, so compact (policy-narrowed),
    bit-packed, and fleet-stacked layouts all come back bit-identical
    (unlike :func:`save_engine_state`, ``ring_perm`` is persisted too: the
    stacked/packed shapes cannot be re-derived by the single-cluster
    recompute, and bit-exact resume is the whole point here). ``meta`` is a
    small JSON-serializable dict (the supervisor's wave cursor). Sealed +
    atomic like every writer in this module."""
    entries = dict(_cfg_entries(cfg))
    entries["__meta__"] = np.frombuffer(
        json.dumps(meta or {}, sort_keys=True).encode(), dtype=np.uint8
    )
    for prefix, tree in (("state", state), ("faults", faults), ("knobs", knobs)):
        if tree is None:
            continue
        for field, value in tree._asdict().items():
            entries[f"{prefix}__{field}"] = np.asarray(value)
    _atomic_write(path, _seal(_npz_bytes(entries)))


def load_serving_state(path):
    """Inverse of :func:`save_serving_state`: returns ``(cfg, state, faults,
    knobs_or_None, meta)`` with every leaf at its saved shape and dtype.
    Corruption raises :class:`CheckpointCorruptError`; a missing pytree
    field raises KeyError naming it (a serving checkpoint is always written
    whole by this module — absence means a foreign or damaged file)."""
    import jax.numpy as jnp

    from rapid_tpu.models.state import EngineConfig, EngineState, FaultInputs

    with _open_npz(path) as data:
        vals = [int(v) for v in data["__cfg__"]]
        saved = dict(zip([str(f) for f in data["__cfg_fields__"]], vals))
        cfg = EngineConfig(**{
            f: saved[f] for f in EngineConfig._fields if f in saved
        })
        meta = json.loads(bytes(data["__meta__"]).decode() or "{}")

        def tree(cls, prefix):
            arrays = {}
            for field in cls._fields:
                key = f"{prefix}__{field}"
                if key not in data:
                    raise KeyError(
                        f"serving checkpoint missing {key!r} (not written "
                        f"by save_serving_state, or damaged)"
                    )
                arrays[field] = jnp.asarray(data[key])
            return cls(**arrays)

        state = tree(EngineState, "state")
        faults = tree(FaultInputs, "faults")
        knobs = None
        if any(k.startswith("knobs__") for k in data.files):
            from rapid_tpu.tenancy.fleet import TenantKnobs

            knobs = tree(TenantKnobs, "knobs")
        # None is an empty pytree: knobs settles through unchanged.
        state, faults, knobs = _settle_device_owned((state, faults, knobs))
    return cfg, state, faults, knobs, meta
