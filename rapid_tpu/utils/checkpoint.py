"""Checkpoint / resume.

The reference persists nothing; its serializable state is exactly the
``Configuration`` — (identifiers-seen, ring-0 member list) — documented as
sufficient to reconstruct an identical view (``MembershipView.java:521-533``)
and streamed to every joiner. This module makes that durable:

- host path: ``Configuration`` <-> bytes (the wire codec's field layout), so a
  node can restart into a known view and rejoin from peers;
- device path: the whole ``EngineState`` <-> one ``.npz`` file, so a 100K-node
  virtual cluster resumes mid-protocol (reports, votes, FD counters intact).
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING, Tuple

import numpy as np

LOG = logging.getLogger(__name__)


def _default_retired(cfg):
    import jax.numpy as jnp

    LOG.warning(
        "checkpoint predates the 'retired' field: retirement history is "
        "unrecoverable — do not re-admit previously-removed slots after "
        "this resume"
    )
    return jnp.zeros((cfg.n,), dtype=bool)

from rapid_tpu.messaging.codec import (
    Reader,
    Writer,
    read_endpoint,
    read_node_id,
    write_endpoint,
    write_node_id,
)
from rapid_tpu.protocol.view import (
    TOPOLOGY_JAVA,
    TOPOLOGY_NATIVE,
    Configuration,
    MembershipView,
)

if TYPE_CHECKING:
    from rapid_tpu.models.state import EngineConfig, EngineState

_MAGIC = b"RTCF"
# v2 appends a topology-mode byte; v1 checkpoints (which predate the
# java-compat mode and were always native) still load. Native configs are
# WRITTEN as v1: the trailing byte buys nothing in the default case, and
# emitting v2 would make every checkpoint unreadable to older readers that
# only accept v1 — forward incompatibility reserved for java-mode configs,
# which older readers could not resume correctly anyway.
_VERSION = 2
_TOPOLOGY_CODES = {TOPOLOGY_NATIVE: 0, TOPOLOGY_JAVA: 1}
_TOPOLOGY_NAMES = {code: name for name, code in _TOPOLOGY_CODES.items()}


def configuration_to_bytes(config: Configuration) -> bytes:
    w = Writer()
    w.raw(_MAGIC)
    version = 1 if config.topology == TOPOLOGY_NATIVE else _VERSION
    w.u8(version)
    w.u32(len(config.node_ids))
    for nid in config.node_ids:
        write_node_id(w, nid)
    w.u32(len(config.endpoints))
    for ep in config.endpoints:
        write_endpoint(w, ep)
    if version >= 2:
        w.u8(_TOPOLOGY_CODES[config.topology])
    return w.getvalue()


def configuration_from_bytes(data: bytes) -> Configuration:
    if data[:4] != _MAGIC:
        raise ValueError("not a rapid_tpu configuration checkpoint")
    r = Reader(data[4:])
    version = r.u8()
    if version not in (1, _VERSION):
        raise ValueError(f"unsupported checkpoint version {version}")
    node_ids = tuple(read_node_id(r) for _ in range(r.u32()))
    endpoints = tuple(read_endpoint(r) for _ in range(r.u32()))
    if version == 1:
        topology = TOPOLOGY_NATIVE
    else:
        code = r.u8()
        if code not in _TOPOLOGY_NAMES:
            raise ValueError(f"unknown topology code {code} in checkpoint")
        topology = _TOPOLOGY_NAMES[code]
    return Configuration(node_ids, endpoints, topology=topology)


def view_from_configuration(config: Configuration, k: int) -> MembershipView:
    """Resume: rebuild the K rings from a configuration snapshot (the
    snapshot's topology mode rides along, so a java-compat cluster resumes
    java-compat)."""
    return MembershipView(
        k,
        node_ids=config.node_ids,
        endpoints=config.endpoints,
        topology=config.topology,
    )


def save_engine_state(path, cfg: "EngineConfig", state: "EngineState") -> None:
    arrays = {field: np.asarray(value) for field, value in state._asdict().items()}
    # Derived data is never persisted: ring_perm is a pure function of the
    # key lanes, and loading a stale/corrupted copy would silently diverge
    # topology from the keys. Load always recomputes it (one sort).
    arrays.pop("ring_perm", None)
    np.savez_compressed(
        path,
        __cfg__=np.asarray(list(cfg), dtype=np.int64),
        # Field names pin value->field pairing across EngineConfig schema
        # changes: positional loading silently misassigns values once any
        # non-trailing field is added/removed.
        __cfg_fields__=np.asarray(cfg._fields, dtype=np.str_),
        **arrays,
    )


def load_engine_state(path) -> Tuple["EngineConfig", "EngineState"]:
    from rapid_tpu.models.state import (
        EngineConfig,
        EngineState,
        compaction_policy,
        lane_dtypes,
    )

    with np.load(path) as data:
        vals = [int(v) for v in data["__cfg__"]]
        if "__cfg_fields__" in data:
            # Name-keyed: removed fields' saved values are dropped, fields
            # added since the checkpoint fill from EngineConfig defaults.
            saved = dict(zip([str(f) for f in data["__cfg_fields__"]], vals))
            cfg = EngineConfig(**{
                f: saved[f] for f in EngineConfig._fields if f in saved
            })
        else:
            # Legacy checkpoints (no name map, written round <= 2): values
            # are positional over the 12 pre-round-3 fields, optionally
            # followed by the since-deleted pallas_watermark — never by any
            # round-3+ field (those writers always emit the name map). So:
            # take the stable 12, drop the stale tail, default the rest.
            legacy_fields = 12  # ... through delivery_prob_permille
            cfg = EngineConfig(*vals[:legacy_fields])
        import jax.numpy as jnp

        from rapid_tpu.ops.rings import ring_perms as _ring_perms

        # Fields added after a checkpoint was written fill with their
        # initial-state defaults (per-configuration state is safe to reset:
        # at worst a fallback restarts from round 2) — at the POLICY dtypes
        # of the saved config, so a compact checkpoint's filled lanes match
        # the lanes the engine would have built (models/state
        # compaction_policy; wide configs keep the historical int32s).
        dts = {f: jnp.dtype(d) for f, d in lane_dtypes(cfg).items()}
        fire_never = compaction_policy(cfg).fire_never
        defaults = {
            "cp_rnd_r": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_rnd_r"]),
            "cp_rnd_i": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_rnd_i"]),
            "cp_vrnd_r": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_vrnd_r"]),
            "cp_vrnd_i": lambda: jnp.zeros((cfg.n,), dtype=dts["cp_vrnd_i"]),
            "cp_vval_src": lambda: jnp.full(
                (cfg.n,), -1, dtype=dts["cp_vval_src"]
            ),
            "classic_epoch": lambda: jnp.zeros((), dtype=dts["classic_epoch"]),
            "fire_round": lambda: jnp.where(
                jnp.asarray(data["fd_fired"]),
                jnp.zeros((), dtype=dts["fire_round"]),
                jnp.asarray(fire_never, dtype=dts["fire_round"]),
            ),
            "round_idx": lambda: jnp.int32(0),
            "fd_hist": lambda: jnp.zeros((cfg.n, cfg.k), dtype=dts["fd_hist"]),
            # NOT per-configuration state: retirement is cross-configuration
            # history and cannot be reconstructed from an old checkpoint.
            # Resuming one forgets which identity lanes were spent — callers
            # must not re-admit previously-removed slots after such a resume
            # (warned below).
            "retired": lambda: _default_retired(cfg),
            # Derived, not stateful: recompute from the (always-saved) key
            # lanes for checkpoints written before the field existed.
            "ring_perm": lambda: _ring_perms(
                jnp.asarray(data["key_hi"]), jnp.asarray(data["key_lo"])
            ).astype(dts["ring_perm"]),
        }
        arrays = {}
        for field in EngineState._fields:
            if field == "ring_perm":
                # Always derived from the key lanes — a persisted copy (from
                # any writer) is ignored rather than trusted for coherence.
                arrays[field] = defaults[field]()
            elif field in data:
                arrays[field] = jnp.asarray(data[field])
            elif field in defaults:
                arrays[field] = defaults[field]()
            else:
                raise KeyError(
                    f"checkpoint missing field {field!r} with no known default"
                )
        state = EngineState(**arrays)
    return cfg, state
