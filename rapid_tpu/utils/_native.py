"""ctypes bridge to the native host-runtime library (native/rapid_native.cpp).

Loads ``librapid_native.so`` if present (building it on first use when a
toolchain is available), exposing batch ring-key construction and the
configuration-id fold. Every entry point has a pure-Python fallback producing
bit-identical values; ``RAPID_TPU_NO_NATIVE=1`` disables the native path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

LOG = logging.getLogger(__name__)

_REPO_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _REPO_NATIVE_DIR / "build" / "librapid_native.so"

_lib: Optional[ctypes.CDLL] = None
_attempted = False


def _try_build() -> bool:
    makefile = _REPO_NATIVE_DIR / "Makefile"
    if not makefile.exists():
        return False
    try:
        subprocess.run(
            ["make", "-C", str(_REPO_NATIVE_DIR)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _LIB_PATH.exists()
    except Exception as exc:  # noqa: BLE001 — any build failure means fallback
        LOG.debug("native build failed: %r", exc)
        return False


def ensure_built() -> bool:
    """Build the native library if missing. Call from setup paths (bench,
    test session start, packaging) — never from the event loop: the compile
    can take tens of seconds and would stall the protocol."""
    global _attempted
    if os.environ.get("RAPID_TPU_NO_NATIVE"):
        return False
    if _LIB_PATH.exists():
        return True
    built = _try_build()
    _attempted = False  # allow get_lib to pick up a fresh build
    return built


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (Python fallback). Load-only:
    runtime code paths never compile (see ensure_built)."""
    global _lib, _attempted
    if _attempted:
        return _lib
    _attempted = True
    if os.environ.get("RAPID_TPU_NO_NATIVE"):
        return None
    if not _LIB_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.rapid_xxh64.restype = ctypes.c_uint64
        lib.rapid_xxh64.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.rapid_ring_key.restype = ctypes.c_uint64
        lib.rapid_ring_key.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int32,
            ctypes.c_uint64,
        ]
        lib.rapid_ring_keys_batch.restype = None
        lib.rapid_ring_keys_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rapid_configuration_id.restype = ctypes.c_uint64
        lib.rapid_configuration_id.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_uint64,
        ]
        _lib = lib
    except OSError as exc:  # pragma: no cover
        LOG.debug("native load failed: %r", exc)
        _lib = None
    return _lib


def native_xxh64(data: bytes, seed: int) -> Optional[int]:
    lib = get_lib()
    if lib is None:
        return None
    return int(lib.rapid_xxh64(data, len(data), ctypes.c_uint64(seed)))


def _pack_hostnames(hostnames: Sequence[bytes]):
    offsets = np.zeros(len(hostnames) + 1, dtype=np.uint64)
    for i, h in enumerate(hostnames):
        offsets[i + 1] = offsets[i] + len(h)
    blob = b"".join(hostnames)
    return blob, offsets


def native_ring_keys_batch(
    hostnames: Sequence[bytes], ports: Sequence[int], k: int
) -> Optional[np.ndarray]:
    """[k, n] uint64 ring keys, or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(hostnames)
    blob, offsets = _pack_hostnames(hostnames)
    ports_arr = np.asarray(ports, dtype=np.int32)
    out = np.empty((k, n), dtype=np.uint64)
    lib.rapid_ring_keys_batch(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ports_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_uint64(n),
        ctypes.c_uint32(k),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return out


def native_configuration_id(
    id_highs: Sequence[int],
    id_lows: Sequence[int],
    hostnames: Sequence[bytes],
    ports: Sequence[int],
) -> Optional[int]:
    lib = get_lib()
    if lib is None:
        return None
    highs = np.asarray(id_highs, dtype=np.uint64)
    lows = np.asarray(id_lows, dtype=np.uint64)
    blob, offsets = _pack_hostnames(hostnames)
    ports_arr = np.asarray(ports, dtype=np.int32)
    return int(
        lib.rapid_configuration_id(
            highs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ctypes.c_uint64(len(highs)),
            blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ports_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_uint64(len(hostnames)),
        )
    )
