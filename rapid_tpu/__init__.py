"""rapid_tpu: a TPU-native distributed membership framework.

A ground-up rebuild of the capabilities of Rapid (lalithsuresh/rapid) —
expander-based monitoring overlays, multi-node cut detection, and leaderless
Fast Paxos — designed for TPU execution: the protocol hot paths (ring
topology, watermark tallies, vote counting) are batched JAX kernels over N
virtual nodes sharded across a device mesh, while the host-side asyncio
runtime speaks the same two-interface messaging seam as the reference
(IMessagingClient / IMessagingServer).
"""

from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, NodeId

__version__ = "0.1.0"

__all__ = ["Settings", "Endpoint", "NodeId", "Cluster", "ClusterEvents", "__version__"]


def __getattr__(name):
    # Lazy: the protocol runtime pulls in asyncio machinery that pure-kernel
    # users (and the sharded engine) never need.
    if name == "Cluster":
        from rapid_tpu.protocol.cluster import Cluster

        return Cluster
    if name == "ClusterEvents":
        from rapid_tpu.protocol.events import ClusterEvents

        return ClusterEvents
    raise AttributeError(f"module 'rapid_tpu' has no attribute {name!r}")
