"""Two-level hierarchical membership (ROADMAP item 3).

Flat Rapid fans every alert and consensus vote out O(N) cluster-wide; at
planet scale that is the wall. This package lifts the device engine's cohort
idea into the host protocol, following the two-tier split of "Fast Raft for
Hierarchical Consensus" (arXiv:2506.17793) and the small-reconfiguration-tier
stitching of "Reconfigurable Atomic Transaction Commit" (arXiv:1906.01365):

- :mod:`rapid_tpu.hier.cohorts` — a deterministic, seeded cohort map over
  the membership view (rebalanced only at reconfiguration) plus
  cohort-scoped expander monitoring rings;
- :mod:`rapid_tpu.hier.broadcast` — the cohort-scoped broadcaster (alert
  batches and cohort fast-round votes fan out O(cohort), not O(N));
- :mod:`rapid_tpu.hier.service` — :class:`HierMembershipService`: the
  cohort-local fast path (cut detection + Fast Paxos inside the cohort) and
  the global reconfiguration tier (a small delegate committee running the
  existing Fast-Paxos/classic machinery over cohort cut proposals,
  serializing them into the single cluster-wide configuration chain).

Every node still observes strongly-consistent, totally-ordered view changes
— the chain-consistency oracle of :mod:`rapid_tpu.sim.oracles` holds
unchanged over the hierarchy.
"""

from rapid_tpu.hier.cohorts import CohortMap, CohortTopology
from rapid_tpu.hier.broadcast import CohortBroadcaster
from rapid_tpu.hier.service import HierMembershipService

__all__ = [
    "CohortMap",
    "CohortTopology",
    "CohortBroadcaster",
    "HierMembershipService",
]
