"""Deterministic cohort assignment + cohort-scoped monitoring topology.

The cohort map is a pure function of (membership set, seed, target size):
members are ordered by a seeded 64-bit hash (endpoint tie-break, exactly the
ring-key discipline of :mod:`rapid_tpu.protocol.view`) and split into
``n_cohorts = max(1, (n + target//2) // target)`` contiguous chunks whose
sizes differ by at most one. Every node computes the identical map from the
same configuration — no coordination, no extra wire traffic — and the map is
rebuilt ONLY at reconfiguration (the service's per-configuration reset), so
cohort membership never shifts under a node mid-change.

Delegates and the global committee are positional: a cohort's delegate is
its first member in chunk order; its failover candidates are the members
after it; the global reconfiguration committee is the first
``committee_per_cohort`` members of every cohort. A committee of >1 per
cohort is what keeps the global tier live across a delegate failure — with
one delegate per cohort and two cohorts, a single dead delegate would stall
even classic Paxos (majority of 2 is 2).

A joiner (not yet a member) is assigned to the cohort whose hash-order chunk
its own key falls into, so its gatekeepers — and the cohort that runs its
admission — are computable by every node before it is admitted.
"""

from __future__ import annotations

import bisect
from typing import Dict, Iterable, List, Optional, Tuple

from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import Endpoint
from rapid_tpu.utils.xxhash import xxh64

#: Committee members contributed by each cohort to the global tier (the
#: delegate plus its first failover candidate). See the module docstring on
#: why one per cohort is not fault tolerant at small cohort counts.
COMMITTEE_PER_COHORT = 2


def cohort_key(endpoint: Endpoint, seed: int) -> int:
    """The seeded ordering key that places an endpoint in the cohort space
    (the same keyspace whether or not the endpoint is a member yet)."""
    return xxh64(str(endpoint).encode("utf-8"), seed ^ 0xC0804)


class CohortMap:
    """One configuration's cohort partition. Immutable after construction."""

    __slots__ = (
        "seed",
        "target_size",
        "n_cohorts",
        "_ordered",
        "_keys",
        "_cohort_of",
        "_chunks",
    )

    def __init__(
        self, members: Iterable[Endpoint], seed: int, target_size: int
    ) -> None:
        if target_size < 2:
            raise ValueError(f"target cohort size must be >= 2, got {target_size}")
        self.seed = seed
        self.target_size = target_size
        ordered = sorted(set(members), key=lambda ep: (cohort_key(ep, seed), ep))
        self._ordered: Tuple[Endpoint, ...] = tuple(ordered)
        self._keys: List[int] = [cohort_key(ep, seed) for ep in ordered]
        n = len(ordered)
        self.n_cohorts = max(1, (n + target_size // 2) // target_size) if n else 1
        # Balanced contiguous chunks: sizes differ by at most one, so no
        # cohort degenerates below the detectability floor while others
        # bloat (a 1-member cohort could never detect its own failure).
        base, extra = divmod(n, self.n_cohorts)
        chunks: List[Tuple[Endpoint, ...]] = []
        cohort_of: Dict[Endpoint, int] = {}
        pos = 0
        for idx in range(self.n_cohorts):
            size = base + (1 if idx < extra else 0)
            chunk = self._ordered[pos : pos + size]
            chunks.append(chunk)
            for ep in chunk:
                cohort_of[ep] = idx
            pos += size
        self._chunks = tuple(chunks)
        self._cohort_of = cohort_of

    # -- queries --------------------------------------------------------

    def cohort_of(self, endpoint: Endpoint) -> int:
        """The cohort index of ``endpoint``: its chunk when it is a member,
        else the chunk its hash key falls into (the joiner assignment — the
        cohort that gatekeeps its admission)."""
        idx = self._cohort_of.get(endpoint)
        if idx is not None:
            return idx
        if not self._ordered:
            return 0
        pos = bisect.bisect_left(
            self._keys, cohort_key(endpoint, self.seed)
        )
        return self._cohort_of[self._ordered[min(pos, len(self._ordered) - 1)]]

    def is_member(self, endpoint: Endpoint) -> bool:
        return endpoint in self._cohort_of

    def members_of(self, cohort: int) -> Tuple[Endpoint, ...]:
        return self._chunks[cohort]

    def delegate_of(
        self, cohort: int, exclude: Iterable[Endpoint] = ()
    ) -> Optional[Endpoint]:
        """The cohort's current forwarder: first chunk member not excluded
        (callers exclude the members a decided cut is removing)."""
        excluded = set(exclude)
        for ep in self._chunks[cohort]:
            if ep not in excluded:
                return ep
        return None

    def forward_candidates(
        self, cohort: int, exclude: Iterable[Endpoint] = ()
    ) -> Tuple[Endpoint, ...]:
        """Deterministic failover order for forwarding a decided cohort cut
        to the global tier: chunk order minus the excluded (cut) members."""
        excluded = set(exclude)
        return tuple(ep for ep in self._chunks[cohort] if ep not in excluded)

    def committee(self) -> Tuple[Endpoint, ...]:
        """The global reconfiguration tier's membership: the first
        ``COMMITTEE_PER_COHORT`` members of every cohort, in cohort order.
        Static for the configuration — quorums need a fixed membership — so
        no dynamic exclusion; a dead committee member is tolerated by the
        classic-majority arithmetic, not by re-selection."""
        out: List[Endpoint] = []
        for chunk in self._chunks:
            out.extend(chunk[:COMMITTEE_PER_COHORT])
        return tuple(out)

    def to_dict(self) -> Dict[str, object]:
        """Telemetry shape: cohort index -> member strings."""
        return {
            "seed": self.seed,
            "n_cohorts": self.n_cohorts,
            "cohorts": {
                str(idx): [str(ep) for ep in chunk]
                for idx, chunk in enumerate(self._chunks)
            },
        }


class CohortTopology:
    """Cohort-scoped expander monitoring rings over one configuration.

    Each cohort gets its own K-ring :class:`MembershipView` built over just
    its members (identifier history is irrelevant for ring queries, so the
    mini-views carry none). ``subjects_of``/``observers_of``/``ring_numbers``
    then answer within the node's cohort — a cohort-local failure is
    detected, reported, and aggregated entirely inside the cohort. Built
    lazily per cohort and only at reconfiguration, alongside the map.
    """

    __slots__ = ("k", "topology", "_map", "_views")

    def __init__(self, cohort_map: CohortMap, k: int, topology: str) -> None:
        self.k = k
        self.topology = topology
        self._map = cohort_map
        self._views: Dict[int, MembershipView] = {}

    def view_of(self, cohort: int) -> MembershipView:
        view = self._views.get(cohort)
        if view is None:
            view = MembershipView(
                self.k,
                endpoints=self._map.members_of(cohort),
                topology=self.topology,
            )
            self._views[cohort] = view
        return view

    def _cohort_view(self, endpoint: Endpoint) -> MembershipView:
        return self.view_of(self._map.cohort_of(endpoint))

    # -- the monitoring-topology SPI the service consults ----------------

    def subjects_of(self, node: Endpoint) -> List[Endpoint]:
        return self._cohort_view(node).subjects_of(node)

    def observers_of(self, node: Endpoint) -> List[Endpoint]:
        return self._cohort_view(node).observers_of(node)

    def expected_observers_of(self, joiner: Endpoint) -> List[Endpoint]:
        return self._cohort_view(joiner).expected_observers_of(joiner)

    def ring_numbers(self, observer: Endpoint, subject: Endpoint) -> List[int]:
        return self._cohort_view(subject).ring_numbers(observer, subject)
