"""Two-level membership service: cohort-local fast path + global tier.

:class:`HierMembershipService` subclasses the flat
:class:`~rapid_tpu.protocol.service.MembershipService` and re-scopes the
three O(N) surfaces to the cohort while leaving every safety mechanism —
join bookkeeping, config catch-up, KICKED discipline, the totally-ordered
configuration chain — untouched:

1. **Monitoring + detection** (``_monitor_topology``/``_cut_view``): failure
   detectors watch K ring-predecessors *within the node's cohort*; alert
   batches broadcast cohort-scoped (:class:`CohortBroadcaster`); the
   H/L-watermark cut detector aggregates over cohort ring numbers. A
   cohort-local failure is detected with O(cohort·K) messages.

2. **Cohort agreement** (``_new_fast_paxos``): the released cut enters a
   Fast-Paxos round whose membership is the cohort — quorum arithmetic,
   classic fallback, vote redelivery all unchanged, just over c nodes
   instead of N. The decision is a *cohort cut proposal*, not yet a view
   change.

3. **Global reconfiguration tier**: a small committee (the first
   ``COMMITTEE_PER_COHORT`` members of every cohort) runs the identical
   Fast-Paxos/classic machinery — wrapped in ``GlobalTierMessage`` envelopes
   so the two tiers' consensus traffic cannot cross — over cohort cut
   proposals. Decided cohort cuts are forwarded to the committee as
   ``CohortCutMessage``s by the cohort's delegate, with a deterministic
   staggered failover chain (every surviving cohort member re-forwards on an
   escalating timer until the view change lands, so a dead or gray delegate
   costs latency, never liveness). Committee members adopt the union of the
   cuts they know as their global proposal; the global decision is applied
   locally and disseminated to each committee member's own cohort as a
   ``DelegateDecisionMessage``. Every node therefore delivers the same
   totally-ordered configuration chain; a node that misses the decision
   recovers through the existing config-sync/catch-up machinery
   (``_consensus_pending`` keeps the anti-entropy suspicion alive while a
   cohort cut awaits its global decision).

Degenerate single-cohort configurations (membership below ~1.5× the target
cohort size) bypass the global tier: the cohort IS the cluster, and the
cohort decision applies directly — bit-identical to flat Rapid.

The device vote tally (``vote_tally_factory``) is not used in hierarchical
mode: its batched quorum test is sized for the flat N-member round, and the
cohort rounds are small by construction.
"""

from __future__ import annotations

import inspect
import random
from typing import Dict, List, Optional, Tuple

from rapid_tpu.hier.broadcast import CohortBroadcaster
from rapid_tpu.hier.cohorts import CohortMap, CohortTopology
from rapid_tpu.protocol.fast_paxos import FastPaxos
from rapid_tpu.protocol.service import (
    CONSENSUS_TYPES,
    MembershipService,
    _MARK_AGREEMENT,
)
from rapid_tpu.types import (
    CohortCutMessage,
    DelegateDecisionMessage,
    Endpoint,
    GlobalTierMessage,
    NodeId,
    RapidRequest,
    RapidResponse,
    Response,
)
from rapid_tpu.utils.clock import CancelHandle
from rapid_tpu.utils.flight_recorder import EventName

#: Per-cohort phase SLO family: renders as
#: ``rapid_cohort_phase_ms_bucket{phase=...,path=c<idx>}`` (the "phase/path"
#: split of utils/exposition.py). ``cohort_agree`` = proposal release ->
#: cohort consensus; ``global_agree`` = cohort consensus -> global decision.
_COHORT_PHASE_TIMER = "cohort_phase"
_MARK_GLOBAL = "hier_phase_global"


class HierMembershipService(MembershipService):
    def __init__(self, *args, **kwargs) -> None:
        # Positional-compatible with MembershipService (Cluster passes
        # keywords; tests may not) — normalize the ones the hierarchy needs
        # before the base constructor runs, because the base constructor
        # already calls the overridden hooks (_new_fast_paxos,
        # broadcaster.set_membership -> _cohort_scope). The mapping is
        # derived from the base signature itself (not a hardcoded name
        # list), so a base-signature change mis-binds loudly here instead
        # of silently zipping args to the wrong names.
        signature = inspect.signature(MembershipService.__init__)
        bound = signature.bind(None, *args, **kwargs).arguments
        bound.pop("self", None)
        settings = bound["settings"]
        view = bound["view"]
        my_addr = bound["my_addr"]
        target = settings.hier_target_cohort_size
        if target <= 0:
            raise ValueError(
                "HierMembershipService requires settings.hier_target_cohort_size > 0"
            )
        self._hier_target = target
        self._hier_seed = settings.hier_seed
        self._hier_k = settings.k
        self._hier_topology_mode = settings.topology
        self._hier_addr = my_addr
        self._cohort_map = CohortMap(view.ring(0), self._hier_seed, target)
        self._cohort_topology = CohortTopology(
            self._cohort_map, self._hier_k, self._hier_topology_mode
        )
        # Hier coordination state. All of it is event-loop confined: mutated
        # either under the protocol lock (handlers) or from synchronous
        # clock callbacks, never across an await.
        self._awaiting_global = False  # guarded-by: event-loop
        self._global_proposed = False  # guarded-by: event-loop
        self._known_cohort_cuts: Dict[int, Tuple[Endpoint, ...]] = {}  # guarded-by: event-loop
        self._hier_joiner_ids: Dict[Endpoint, NodeId] = {}  # guarded-by: event-loop
        self._forward_handle: Optional[CancelHandle] = None  # guarded-by: event-loop
        self._forward_rank = 0  # guarded-by: event-loop
        self._global_paxos: Optional[FastPaxos] = None  # guarded-by: event-loop
        self._committee: Tuple[Endpoint, ...] = ()  # guarded-by: event-loop

        broadcaster = bound.get("broadcaster")
        if broadcaster is None:
            rng = bound.get("rng")
            broadcaster = CohortBroadcaster(
                bound["client"], my_addr,
                rng=rng if rng is not None else random.Random(f"cohort:{my_addr}"),
                scope_fn=self._cohort_scope,
            )
            bound["broadcaster"] = broadcaster
        elif hasattr(broadcaster, "scope_fn"):
            # An injected strategy (e.g. GossipBroadcaster) that supports
            # scoping relays inside the cohort instead of cluster-wide.
            broadcaster.scope_fn = self._cohort_scope

        super().__init__(**bound)
        self._reset_global_tier()

    # ------------------------------------------------------------------
    # cohort bookkeeping
    # ------------------------------------------------------------------

    def _cohort_scope(self, _members: List[Endpoint]) -> List[Endpoint]:
        """The broadcast fan-out: this node's cohort (it includes self, so
        self-delivery of alerts and votes keeps flat semantics)."""
        m = self._cohort_map
        if not m.is_member(self._hier_addr):
            return []  # evicted: no cohort left to speak to
        return list(m.members_of(m.cohort_of(self._hier_addr)))

    def _my_cohort(self) -> int:
        return self._cohort_map.cohort_of(self._hier_addr)

    def _rebuild_cohorts(self) -> None:
        """Rebalance point: the ONLY place the cohort map changes, entered
        exclusively from the per-configuration reset — cohort membership is
        immutable within a configuration."""
        self._cohort_map = CohortMap(
            self.view.ring(0), self._hier_seed, self._hier_target
        )
        self._cohort_topology = CohortTopology(
            self._cohort_map, self._hier_k, self._hier_topology_mode
        )

    def _reset_global_tier(self) -> None:
        if self._forward_handle is not None:
            self._forward_handle.cancel()
            self._forward_handle = None
        if self._global_paxos is not None:
            self._global_paxos.cancel_fallback()
        self._awaiting_global = False
        self._global_proposed = False
        self._known_cohort_cuts = {}
        self._hier_joiner_ids = {}
        self.metrics.clear_mark(_MARK_GLOBAL)
        m = self._cohort_map
        self._committee = m.committee()
        if m.n_cohorts > 1 and self.my_addr in self._committee:
            self._global_paxos = FastPaxos(
                my_addr=self.my_addr,
                configuration_id=self.view.configuration_id,
                membership_size=len(self._committee),
                broadcast_fn=self._broadcast_global,
                send_fn=self._send_global,
                on_decide=self._on_global_decided,
                clock=self.clock,
                consensus_fallback_base_delay_ms=(
                    self.settings.consensus_fallback_base_delay_ms
                ),
                rng=self.rng,
                on_classic_round=self._count_global_classic_round,
                recorder=self.recorder,
                trace_supplier=lambda: self._trace_id,
            )
        else:
            self._global_paxos = None

    def _count_global_classic_round(self) -> None:
        self.metrics.inc("cohort_global_classic_rounds")

    # ------------------------------------------------------------------
    # base-service seams
    # ------------------------------------------------------------------

    def _monitor_topology(self):
        return self._cohort_topology

    def _cut_view(self):
        return self._cohort_topology.view_of(self._my_cohort())

    def _consensus_pending(self) -> bool:
        # A cohort cut that is decided but not yet globally serialized keeps
        # the anti-entropy suspicion alive: if the global decision (or our
        # DelegateDecisionMessage) is lost, the config-sync pull recovers it.
        return super()._consensus_pending() or self._awaiting_global

    def _new_fast_paxos(self) -> FastPaxos:
        cohort_members = self._cohort_scope([])
        return FastPaxos(
            my_addr=self.my_addr,
            configuration_id=self.view.configuration_id,
            membership_size=max(len(cohort_members), 1),
            broadcast_fn=self.broadcaster.broadcast,  # cohort-scoped
            send_fn=self.client.send_nowait,
            on_decide=self._on_cohort_cut_decided,
            clock=self.clock,
            consensus_fallback_base_delay_ms=(
                self.settings.consensus_fallback_base_delay_ms
            ),
            rng=self.rng,
            on_classic_round=self._on_fast_round_failed,
            recorder=self.recorder,
            trace_supplier=lambda: self._trace_id,
        )

    def _reset_for_new_configuration(self) -> None:
        self._rebuild_cohorts()  # before super: _new_fast_paxos and the
        # broadcaster scope both read the NEW map
        super()._reset_for_new_configuration()
        self._reset_global_tier()

    async def shutdown(self) -> None:
        if self._forward_handle is not None:
            self._forward_handle.cancel()
            self._forward_handle = None
        if self._global_paxos is not None:
            self._global_paxos.cancel_fallback()
        await super().shutdown()

    # ------------------------------------------------------------------
    # tier 1 -> tier 2: cohort decision, forwarding, failover
    # ------------------------------------------------------------------

    def _on_cohort_cut_decided(self, hosts: Tuple[Endpoint, ...]) -> None:
        hosts = tuple(hosts)
        m = self._cohort_map
        if m.n_cohorts <= 1:
            # Degenerate hierarchy: the cohort is the cluster; the cohort
            # decision IS the view change (flat semantics, zero extra hops).
            self._decide_view_change(hosts)
            return
        my_cohort = self._my_cohort()
        now = self.clock.now_ms()
        self.metrics.inc("cohort_cuts_decided")
        if self.metrics.has_mark(_MARK_AGREEMENT):
            # Cohort-agreement slice of the SLO decomposition; the base
            # service's agreement phase keeps running until the view change
            # (it now spans both tiers).
            self.metrics.record_ms(
                _COHORT_PHASE_TIMER,
                self.metrics.elapsed_since_ms(_MARK_AGREEMENT, now),
                phase=f"cohort_agree/c{my_cohort}",
            )
        self.recorder.record(
            EventName.COHORT_CUT_DECIDED,
            config_id=self.view.configuration_id,
            trace_id=self._trace_id,
            cohort=my_cohort,
            proposal=[str(h) for h in hosts],
        )
        for ep in hosts:
            if not self.view.is_host_present(ep) and ep in self._joiner_uuid:
                self._hier_joiner_ids.setdefault(ep, self._joiner_uuid[ep])
        self._register_cohort_cut(my_cohort, hosts)
        # Forwarding with deterministic failover: every surviving cohort
        # member is a candidate, staggered by its rank — the delegate
        # (rank 0) forwards immediately, the backup after one fallback
        # period, and so on; everyone stops once the view change lands
        # (_awaiting_global clears in the per-config reset).
        candidates = m.forward_candidates(my_cohort, exclude=hosts)
        if self.my_addr not in candidates:
            return  # we are in the cut (being removed): survivors forward
        self._forward_rank = candidates.index(self.my_addr)
        if self._forward_rank == 0:
            self._forward_cohort_cut()
        self._arm_forward_timer()

    def _arm_forward_timer(self) -> None:
        if self._forward_handle is not None:
            self._forward_handle.cancel()
        delay_ms = (
            self.settings.consensus_fallback_base_delay_ms
            * (self._forward_rank + 1)
        )
        self._forward_handle = self.clock.call_later_ms(
            delay_ms, self._forward_tick
        )

    def _forward_tick(self) -> None:
        """Clock callback (no lock, like the consensus liveness tick): while
        the global decision is outstanding, (re)forward our cohort's cut —
        redelivery for a lost CohortCutMessage AND failover past a dead
        delegate in one mechanism. Reads event-loop-confined state only."""
        if self._stopped or not self._awaiting_global:
            return
        self._forward_cohort_cut()
        self._arm_forward_timer()

    def _forward_cohort_cut(self) -> None:
        my_cohort = self._my_cohort()
        cut = self._known_cohort_cuts.get(my_cohort)
        if cut is None:
            return
        joiner_pairs = [
            (ep, self._hier_joiner_ids[ep])
            for ep in cut
            if ep in self._hier_joiner_ids
        ]
        message = CohortCutMessage(
            sender=self.my_addr,
            configuration_id=self.view.configuration_id,
            cohort=my_cohort,
            endpoints=cut,
            joiner_eps=tuple(ep for ep, _ in joiner_pairs),
            joiner_ids=tuple(nid for _, nid in joiner_pairs),
            trace_id=self._trace_id,
        )
        self.metrics.inc("cohort_cuts_forwarded")
        self.recorder.record(
            EventName.COHORT_CUT_FORWARDED,
            config_id=self.view.configuration_id,
            trace_id=self._trace_id,
            cohort=my_cohort,
            committee=len(self._committee),
        )
        for member in self._committee:
            if member != self.my_addr:
                self.client.send_nowait(member, message)

    def _register_cohort_cut(
        self, cohort: int, endpoints: Tuple[Endpoint, ...]
    ) -> None:
        self._known_cohort_cuts.setdefault(cohort, tuple(endpoints))
        self._awaiting_global = True
        if not self.metrics.has_mark(_MARK_GLOBAL):
            self.metrics.mark(_MARK_GLOBAL)
        self._maybe_propose_global()

    def _maybe_propose_global(self) -> None:
        """Committee members adopt the union of every cohort cut they know
        as their global proposal — once. Concurrent cuts that race past the
        adoption point disagree on the union and fall back to the classic
        path, which decides ONE of the proposed values; the losing cohort's
        cut is re-detected and re-proposed in the next configuration (the
        same convergence story as flat Rapid's proposal races)."""
        if self._global_paxos is None or self._global_proposed:
            return
        union: set = set()
        for cut in self._known_cohort_cuts.values():
            union.update(cut)
        if not union:
            return
        self._global_proposed = True
        self._global_paxos.propose(tuple(self.view.ring_zero_sorted(union)))

    # ------------------------------------------------------------------
    # tier 2: the committee's consensus transport + decision fan-out
    # ------------------------------------------------------------------

    def _broadcast_global(self, request: RapidRequest) -> None:
        for member in self._committee:
            self.client.send_nowait(
                member, GlobalTierMessage(sender=self.my_addr, payload=request)
            )

    def _send_global(self, destination: Endpoint, request: RapidRequest) -> None:
        self.client.send_nowait(
            destination, GlobalTierMessage(sender=self.my_addr, payload=request)
        )

    def _record_global_phase(self) -> None:
        if self.metrics.has_mark(_MARK_GLOBAL):
            self.metrics.record_ms(
                _COHORT_PHASE_TIMER,
                self.metrics.elapsed_since_ms(_MARK_GLOBAL, self.clock.now_ms()),
                phase=f"global_agree/c{self._my_cohort()}",
            )
            self.metrics.clear_mark(_MARK_GLOBAL)

    def _on_global_decided(self, hosts: Tuple[Endpoint, ...]) -> None:
        hosts = tuple(hosts)
        self.metrics.inc("cohort_global_decisions")
        self.recorder.record(
            EventName.GLOBAL_DECISION,
            config_id=self.view.configuration_id,
            trace_id=self._trace_id,
            proposal=[str(h) for h in hosts],
        )
        self._record_global_phase()
        joiner_pairs = [
            (ep, self._hier_joiner_ids[ep])
            for ep in hosts
            if ep in self._hier_joiner_ids
        ]
        for ep, nid in joiner_pairs:
            self._joiner_uuid.setdefault(ep, nid)
        decision = DelegateDecisionMessage(
            sender=self.my_addr,
            configuration_id=self.view.configuration_id,
            endpoints=hosts,
            joiner_eps=tuple(ep for ep, _ in joiner_pairs),
            joiner_ids=tuple(nid for _, nid in joiner_pairs),
            trace_id=self._trace_id,
        )
        m = self._cohort_map
        if m.is_member(self.my_addr):
            # Dissemination is cohort-parallel: every committee member tells
            # its own cohort (two tellers per cohort — one lost message
            # costs nothing; two lost messages cost one config-sync pull).
            for member in m.members_of(m.cohort_of(self.my_addr)):
                if member != self.my_addr:
                    self.client.send_nowait(member, decision)
        self._decide_view_change(hosts)

    # ------------------------------------------------------------------
    # inbound hier traffic (runs under the protocol lock)
    # ------------------------------------------------------------------

    def _handle_hier_message(self, request: RapidRequest) -> RapidResponse:
        if isinstance(request, CohortCutMessage):
            if (
                request.configuration_id != self.view.configuration_id
                or self._kicked_signalled
            ):
                return Response()
            self._adopt_trace(request.trace_id)
            self.metrics.inc("cohort_cuts_received")
            self.recorder.record(
                EventName.COHORT_CUT_RX,
                config_id=request.configuration_id,
                trace_id=self._trace_id,
                cohort=request.cohort,
                sender=str(request.sender),
            )
            for ep, nid in zip(request.joiner_eps, request.joiner_ids):
                self._hier_joiner_ids.setdefault(ep, nid)
            self._register_cohort_cut(request.cohort, tuple(request.endpoints))
            return Response()
        if isinstance(request, GlobalTierMessage):
            if self._global_paxos is None or not isinstance(
                request.payload, CONSENSUS_TYPES
            ):
                # Not on the committee this configuration (stale sender map),
                # or a payload the tier never emits: acknowledge and drop.
                return Response()
            self._adopt_trace(getattr(request.payload, "trace_id", None))
            return self._global_paxos.handle_message(request.payload)
        if isinstance(request, DelegateDecisionMessage):
            if (
                request.configuration_id != self.view.configuration_id
                or self._kicked_signalled
            ):
                return Response()
            self._adopt_trace(request.trace_id)
            self.metrics.inc("cohort_decisions_received")
            for ep, nid in zip(request.joiner_eps, request.joiner_ids):
                self._joiner_uuid.setdefault(ep, nid)
            self._record_global_phase()
            self._decide_view_change(tuple(request.endpoints))
            return Response()
        return Response()

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def telemetry_snapshot(self, recorder_tail: Optional[int] = None):
        snapshot = super().telemetry_snapshot(recorder_tail=recorder_tail)
        m = self._cohort_map
        my_cohort = self._my_cohort()
        snapshot["cohort"] = my_cohort
        snapshot["hier"] = {
            "n_cohorts": m.n_cohorts,
            "cohort": my_cohort,
            "cohort_size": len(m.members_of(my_cohort)) if m.is_member(self.my_addr) else 0,
            "committee": self.my_addr in self._committee,
            "delegate": m.delegate_of(my_cohort) == self.my_addr,
        }
        return snapshot
