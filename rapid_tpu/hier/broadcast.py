"""Cohort-scoped broadcast: the O(cohort) fan-out of the fast path.

Flat Rapid's ``UnicastToAllBroadcaster`` sends every alert batch and
fast-round vote to all N members. In hierarchical mode the only nodes that
can act on that traffic are the sender's cohort-mates — they hold the
cohort's cut detector and vote in the cohort's fast round — so the
broadcaster restricts the fan-out to them. The scope is recomputed from the
service's cohort map at each ``set_membership`` (i.e. at reconfiguration,
when the map itself was just rebuilt), never mid-configuration.
"""

from __future__ import annotations

import random
from typing import Callable, List, Optional

from rapid_tpu.messaging.base import Broadcaster, MessagingClient
from rapid_tpu.types import Endpoint, RapidRequest

#: scope_fn(full_membership) -> the subset this node fans out to.
ScopeFn = Callable[[List[Endpoint]], List[Endpoint]]


class CohortBroadcaster(Broadcaster):
    def __init__(
        self,
        client: MessagingClient,
        self_endpoint: Endpoint,
        rng: Optional[random.Random] = None,
        scope_fn: Optional[ScopeFn] = None,
    ) -> None:
        self._client = client
        self._self = self_endpoint
        # Identity-seeded default, as everywhere (determinism audit).
        self._rng = rng if rng is not None else random.Random(f"cohort:{self_endpoint}")
        #: Set by the owning service after construction (the service owns
        #: the cohort map the scope is computed from).
        self.scope_fn: Optional[ScopeFn] = scope_fn
        self._members: List[Endpoint] = []  # guarded-by: event-loop

    def broadcast(self, request: RapidRequest) -> None:
        for member in self._members:
            self._client.send_nowait(member, request)

    def set_membership(self, members: List[Endpoint]) -> None:
        scoped = list(self.scope_fn(members)) if self.scope_fn is not None else list(members)
        self._rng.shuffle(scoped)
        self._members = scoped
