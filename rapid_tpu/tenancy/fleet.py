"""The tenant fleet: B independent virtual clusters stepped as ONE compiled
program.

"Millions of users" means fleets of independent membership clusters, not one
giant one (ROADMAP item 4; the Rapid paper evaluates thousands of *single*
clusters' stability under churn — arXiv:1803.03620 §5). The TPU analog of
serving that fleet is batching whole clusters into one dispatch: every
engine impl (``engine_step_impl`` / ``run_to_decision_impl`` / the
whole-wave convergence loop) vmaps over a leading tenant axis of the
existing ``EngineState``/``FaultInputs`` pytrees, with independent seeds,
fault inputs, and PER-TENANT protocol knobs (H/L watermarks, failure
threshold, classic-fallback delay — :class:`TenantKnobs`, traced int32
lanes, so one executable serves every knob mix). Per-tenant results are
bit-identical to B separate ``VirtualCluster`` runs — the non-negotiable
parity bar, proved by the pinned differential grid in
``tests/test_tenancy.py`` exactly the way ``tests/test_parallel_2d.py``
pinned the 2-D mesh.

Sharding: the leading ``[t]`` axis shards on the ``'tenant'`` axis of the
3-D ``('tenant', 'cohort', 'nodes')`` mesh (``parallel/mesh.py``:
``fleet_state_shardings`` prepends the tenant axis to the SAME rule table —
an uncovered leaf stays a hard error). Tenants never communicate: no
collective in the compiled fleet program may carry the tenant axis in its
replica groups, and the ``device_program`` gate freezes that budget
(``fleet3d_step``/``fleet3d_wave`` in ``hlo.lock.json``,
``cross_tenant_collectives: 0`` — drift fails the build).

Batched-control-flow tradeoffs, stated plainly:

- vmap turns the per-cluster ``lax.cond`` view-change gate into a select —
  the commit math (sort-free ring rebuild, O(N) scans) runs every round and
  is masked away for undecided tenants. For fleet deployments (hundreds of
  SMALL clusters, ~1K members each) that is a constant factor on a round
  body of the same order, not a scale break; the 1M-member single-cluster
  path keeps its gated commit untouched.
- the fleet wave runs LOCKSTEP: a ``fori_loop`` over the step budget with
  per-tenant freeze masking, instead of a batched while. A batched while's
  predicate is an any() across tenants — a cross-tenant collective in the
  hottest location of the program, which the zero-cross-tenant budget
  forbids. The loop predicate here is a replicated counter; finished
  tenants coast. (``fleet_run_to_decision`` keeps the dynamic batched
  while for single-device driver use, where there is no mesh and the any()
  is free.)
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rapid_tpu.models.state import (
    EngineConfig,
    EngineState,
    FaultInputs,
    StepEvents,
    TelemetryLanes,
    TraceRing,
    initial_telemetry,
    initial_trace,
)
from rapid_tpu.models.virtual_cluster import (
    VirtualCluster,
    _compute_round,
    apply_view_change_impl,
    engine_step_impl,
    engine_step_telem_impl,
    engine_step_trace_impl,
    run_to_decision_impl,
    run_to_decision_telem_impl,
    run_to_decision_trace_impl,
    telemetry_digest_impl,
    trace_digest_impl,
)
from rapid_tpu.parallel.mesh import (
    TENANT_AXIS,
    Mesh,
    NamedSharding,
    _resolve_spec,
    fleet_fault_shardings,
    fleet_state_shardings,
    match_partition_rules,
)
from rapid_tpu.utils import engine_telemetry, exposition
from rapid_tpu.utils.dispatch import DispatchSeam
from rapid_tpu.utils.health import NodeHealth
from rapid_tpu.utils.metrics import Metrics

#: The EngineConfig fields that vary per tenant, as traced
#: :class:`TenantKnobs` lanes. EVERY other config field must be IDENTICAL
#: across a fleet's tenants (they pin array shapes or Python-level trace
#: structure — static branches, unrolled loops), so the static set is
#: DERIVED, not enumerated: a field appended to EngineConfig later is
#: fleet-static by default and fails closed in :meth:`TenantFleet.from_clusters`
#: rather than silently running every tenant with tenant 0's value.
KNOB_FIELDS = ("h", "l", "fd_threshold", "fallback_rounds")

FLEET_STATIC_FIELDS = tuple(
    f for f in EngineConfig._fields if f not in KNOB_FIELDS
)

#: Partition rules for the fleet-level knob pytree, in the exact
#: ``parallel/mesh.py`` table style (the ``sharding`` analyzer parses this
#: table too): every knob lane is a [t] array sharded on the tenant axis.
PARTITION_RULES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    (r"h|l|fd_threshold|fallback_rounds", (TENANT_AXIS,)),
)


class TenantKnobs(NamedTuple):
    """Per-tenant protocol knobs as traced int32 lanes — the K/H/L settings
    the reference would spread across B separate JVM configs, batched so one
    executable serves every mix (and the online autotuner can sweep them,
    rapid_tpu/tenancy/autotune.py)."""

    h: jnp.ndarray  # [t] int32 — high watermark
    l: jnp.ndarray  # [t] int32 — low watermark
    fd_threshold: jnp.ndarray  # [t] int32 — failed windows before alerting
    fallback_rounds: jnp.ndarray  # [t] int32 — classic-Paxos recovery delay

    @staticmethod
    def from_configs(cfgs: Sequence[EngineConfig]) -> "TenantKnobs":
        return TenantKnobs(
            h=jnp.asarray([c.h for c in cfgs], dtype=jnp.int32),
            l=jnp.asarray([c.l for c in cfgs], dtype=jnp.int32),
            fd_threshold=jnp.asarray(
                [c.fd_threshold for c in cfgs], dtype=jnp.int32
            ),
            fallback_rounds=jnp.asarray(
                [c.fallback_rounds for c in cfgs], dtype=jnp.int32
            ),
        )


def knob_shardings(mesh: Mesh) -> TenantKnobs:
    """NamedShardings for the knob pytree from :data:`PARTITION_RULES` (the
    [t] lanes shard on 'tenant'; on a mesh without the axis they
    replicate)."""
    specs = match_partition_rules(PARTITION_RULES, TenantKnobs._fields)
    return TenantKnobs(
        **{
            field: NamedSharding(mesh, _resolve_spec(specs[field], mesh))
            for field in TenantKnobs._fields
        }
    )


def _tenant_cfg(cfg: EngineConfig, knobs: TenantKnobs) -> EngineConfig:
    """The per-tenant engine config inside the vmapped trace: the shared
    static geometry with this tenant's traced knob scalars woven in. Every
    knob field is used only in jnp comparisons inside the round body, so a
    tracer is as good as the Python int a single cluster compiles with —
    and lowers to the identical arithmetic."""
    return cfg._replace(
        h=knobs.h,
        l=knobs.l,
        fd_threshold=knobs.fd_threshold,
        fallback_rounds=knobs.fallback_rounds,
    )


def fleet_step_impl(
    cfg: EngineConfig,
    state: EngineState,
    faults: FaultInputs,
    knobs: TenantKnobs,
) -> Tuple[EngineState, StepEvents]:
    """One protocol round for EVERY tenant: ``engine_step_impl`` vmapped
    over the leading tenant axis. Events come back stacked ([t] scalars,
    [t, n] winner masks)."""

    def one(state, faults, kn):
        return engine_step_impl(_tenant_cfg(cfg, kn), state, faults)

    return jax.vmap(one)(state, faults, knobs)


def fleet_run_to_decision_impl(
    cfg: EngineConfig,
    state: EngineState,
    faults: FaultInputs,
    knobs: TenantKnobs,
    max_steps,
):
    """Per-tenant single-dispatch convergence: ``run_to_decision_impl``
    vmapped. The batched while's predicate reduces across tenants (vmap's
    any()), so this entrypoint is for SINGLE-DEVICE driver dispatch — the
    mesh-audited fleet entrypoints are the step and the lockstep wave."""

    def one(state, faults, kn):
        return run_to_decision_impl(_tenant_cfg(cfg, kn), state, faults, max_steps)

    return jax.vmap(one)(state, faults, knobs)


def fleet_wave_impl(
    cfg: EngineConfig,
    state: EngineState,
    faults: FaultInputs,
    knobs: TenantKnobs,
    target,
    max_steps,
    max_cuts: int,
    min_cuts,
):
    """The fleet's whole-wave loop: every tenant runs convergences through
    MULTIPLE view changes until its own ``target`` membership (at least its
    own ``min_cuts`` cuts), all in one dispatch — the batched twin of
    ``run_until_membership_impl``, restructured LOCKSTEP (module docstring):
    one flat ``fori_loop`` over the shared step budget, each iteration one
    engine round per tenant with the view change select-applied and
    finished tenants frozen in place. Per-tenant results are bit-identical
    to the nested per-cluster loop — the same ``_compute_round`` /
    ``apply_view_change_impl`` sequence on the same values, only the loop
    skeleton differs (pinned by tests/test_tenancy.py's differential grid).

    Returns ``(state, steps[t], cuts[t], resolved[t], sizes[t, max_cuts])``.
    """

    def one(state, faults, kn, tgt, mc):
        tcfg = _tenant_cfg(cfg, kn)

        def body(_i, carry):
            state, steps, cuts, sizes, done = carry
            active = ~done & (steps < max_steps)
            round_state, decided, winner, _ = _compute_round(tcfg, state, faults)
            committed = apply_view_change_impl(tcfg, round_state, winner)
            commit = active & decided
            picked = jax.tree_util.tree_map(
                lambda old, rnd, com: jnp.where(
                    active, jnp.where(commit, com, rnd), old
                ),
                state, round_state, committed,
            )
            steps = jnp.where(active, steps + 1, steps)
            sizes = jnp.where(
                commit, sizes.at[cuts].set(committed.n_members), sizes
            )
            cuts = cuts + commit.astype(jnp.int32)
            resolved = (picked.n_members == tgt) & (cuts >= mc)
            done = done | (commit & resolved) | (cuts >= max_cuts)
            return (picked, steps, cuts, sizes, done)

        init = (
            state,
            jnp.int32(0),
            jnp.int32(0),
            jnp.full((max_cuts,), -1, dtype=jnp.int32),
            # The equal-churn trap guard, same as the nested loop's
            # entry condition: already-at-target only resolves vacuously
            # when no cuts are demanded.
            (state.n_members == tgt) & (mc <= jnp.int32(0)),
        )
        state, steps, cuts, sizes, _ = jax.lax.fori_loop(
            0, max_steps, body, init
        )
        resolved = (state.n_members == tgt) & (cuts >= mc)
        return (state, steps, cuts, resolved, sizes)

    return jax.vmap(one)(state, faults, knobs, target, min_cuts)


# ---------------------------------------------------------------------------
# Device telemetry plane, fleet grain: the SAME TelemetryLanes pytree with a
# leading [t] axis, threaded through vmapped twins of the entrypoints above.
# These are separate entrypoints (never default arguments on the existing
# ones) so a telemetry=0 fleet keeps compiling byte-identical programs —
# the hlo.lock.json gate holds the existing fleet3d entries frozen.
# ---------------------------------------------------------------------------


def initial_fleet_telemetry(cfg: EngineConfig, tenants: int) -> TelemetryLanes:
    """All-zero telemetry lanes for ``tenants`` clusters: the single-cluster
    lanes with a leading tenant axis, matching the stacked state layout."""
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros((tenants,) + x.shape, x.dtype),
        initial_telemetry(cfg),
    )


def fleet_step_telem_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    faults: FaultInputs,
    knobs: TenantKnobs,
) -> Tuple[EngineState, TelemetryLanes, StepEvents]:
    """:func:`fleet_step_impl` with per-tenant telemetry lanes riding along
    (``engine_step_telem_impl`` vmapped). Per-tenant counters are
    bit-identical to B separate telemetry-enabled ``VirtualCluster`` steps —
    the lanes vmap exactly like the state they observe."""

    def one(state, telem, faults, kn):
        return engine_step_telem_impl(_tenant_cfg(cfg, kn), state, telem, faults)

    return jax.vmap(one)(state, telem, faults, knobs)


def fleet_run_to_decision_telem_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    faults: FaultInputs,
    knobs: TenantKnobs,
    max_steps,
):
    """:func:`fleet_run_to_decision_impl` with telemetry: the batched while
    carries the lanes per tenant (single-device driver entrypoint, same as
    its untelemetered twin)."""

    def one(state, telem, faults, kn):
        return run_to_decision_telem_impl(
            _tenant_cfg(cfg, kn), state, telem, faults, max_steps
        )

    return jax.vmap(one)(state, telem, faults, knobs)


def fleet_wave_telem_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    faults: FaultInputs,
    knobs: TenantKnobs,
    target,
    max_steps,
    max_cuts: int,
    min_cuts,
):
    """The lockstep fleet wave with telemetry lanes in the carry. The lanes
    are select-gated by the SAME ``active`` mask that freezes a finished
    tenant's state: a tenant that coasts after resolving accumulates no
    phantom rounds, so its counters stay bit-identical to a per-cluster
    ``run_until_membership_telem`` drive (pinned with the state parity in
    tests/test_telemetry_plane.py). No reduction ever touches the lanes
    here — the digest is the only cross-shard telemetry reduction, and it
    runs at fetch boundaries, never inside this loop."""

    def one(state, telem, faults, kn, tgt, mc):
        tcfg = _tenant_cfg(cfg, kn)

        def body(_i, carry):
            state, telem, steps, cuts, sizes, done = carry
            active = ~done & (steps < max_steps)
            round_state, decided, winner, _, round_telem = _compute_round(
                tcfg, state, faults, None, telem
            )
            committed = apply_view_change_impl(tcfg, round_state, winner)
            commit = active & decided
            picked = jax.tree_util.tree_map(
                lambda old, rnd, com: jnp.where(
                    active, jnp.where(commit, com, rnd), old
                ),
                state, round_state, committed,
            )
            telem = jax.tree_util.tree_map(
                lambda old, new: jnp.where(active, new, old),
                telem, round_telem,
            )
            steps = jnp.where(active, steps + 1, steps)
            sizes = jnp.where(
                commit, sizes.at[cuts].set(committed.n_members), sizes
            )
            cuts = cuts + commit.astype(jnp.int32)
            resolved = (picked.n_members == tgt) & (cuts >= mc)
            done = done | (commit & resolved) | (cuts >= max_cuts)
            return (picked, telem, steps, cuts, sizes, done)

        init = (
            state,
            telem,
            jnp.int32(0),
            jnp.int32(0),
            jnp.full((max_cuts,), -1, dtype=jnp.int32),
            (state.n_members == tgt) & (mc <= jnp.int32(0)),
        )
        state, telem, steps, cuts, sizes, _ = jax.lax.fori_loop(
            0, max_steps, body, init
        )
        resolved = (state.n_members == tgt) & (cuts >= mc)
        return (state, telem, steps, cuts, resolved, sizes)

    return jax.vmap(one)(state, telem, faults, knobs, target, min_cuts)


# ---------------------------------------------------------------------------
# Round-trace ring, fleet grain: the SAME TraceRing pytree with a leading
# [t] axis, threaded through vmapped twins of the telemetry entrypoints.
# Separate entrypoints again (never default arguments) so trace=0 fleets —
# telemetry-on or off — keep compiling byte-identical programs.
# ---------------------------------------------------------------------------


def initial_fleet_trace(cfg: EngineConfig, tenants: int) -> TraceRing:
    """All-zero trace rings for ``tenants`` clusters: the single-cluster
    ring with a leading tenant axis, matching the stacked lane layout."""
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros((tenants,) + x.shape, x.dtype),
        initial_trace(cfg),
    )


def fleet_step_trace_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    trace: TraceRing,
    faults: FaultInputs,
    knobs: TenantKnobs,
) -> Tuple[EngineState, TelemetryLanes, TraceRing, StepEvents]:
    """:func:`fleet_step_telem_impl` with per-tenant trace rings riding
    along (``engine_step_trace_impl`` vmapped). Each tenant's ring records
    ITS OWN rounds — cursor, wraps, and records are bit-identical to B
    separate trace-enabled ``VirtualCluster`` steps."""

    def one(state, telem, trace, faults, kn):
        return engine_step_trace_impl(
            _tenant_cfg(cfg, kn), state, telem, trace, faults
        )

    return jax.vmap(one)(state, telem, trace, faults, knobs)


def fleet_run_to_decision_trace_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    trace: TraceRing,
    faults: FaultInputs,
    knobs: TenantKnobs,
    max_steps,
):
    """:func:`fleet_run_to_decision_telem_impl` with the ring in the batched
    while carry (single-device driver entrypoint, same as its twins)."""

    def one(state, telem, trace, faults, kn):
        return run_to_decision_trace_impl(
            _tenant_cfg(cfg, kn), state, telem, trace, faults, max_steps
        )

    return jax.vmap(one)(state, telem, trace, faults, knobs)


def fleet_wave_trace_impl(
    cfg: EngineConfig,
    state: EngineState,
    telem: TelemetryLanes,
    trace: TraceRing,
    faults: FaultInputs,
    knobs: TenantKnobs,
    target,
    max_steps,
    max_cuts: int,
    min_cuts,
):
    """The lockstep fleet wave with trace rings in the carry. The ring is
    select-gated by the SAME ``active`` mask that freezes a finished
    tenant's state and telemetry: a coasting tenant's cursor holds still
    and its slots are never overwritten, so the decoded ring stays
    bit-identical to a per-cluster ``run_until_membership_trace`` drive
    (quarantined tenants — done from iteration 0 — record nothing)."""

    def one(state, telem, trace, faults, kn, tgt, mc):
        tcfg = _tenant_cfg(cfg, kn)

        def body(_i, carry):
            state, telem, trace, steps, cuts, sizes, done = carry
            active = ~done & (steps < max_steps)
            round_state, decided, winner, _, round_telem, round_trace = (
                _compute_round(tcfg, state, faults, None, telem, trace)
            )
            committed = apply_view_change_impl(tcfg, round_state, winner)
            commit = active & decided
            picked = jax.tree_util.tree_map(
                lambda old, rnd, com: jnp.where(
                    active, jnp.where(commit, com, rnd), old
                ),
                state, round_state, committed,
            )
            telem = jax.tree_util.tree_map(
                lambda old, new: jnp.where(active, new, old),
                telem, round_telem,
            )
            trace = jax.tree_util.tree_map(
                lambda old, new: jnp.where(active, new, old),
                trace, round_trace,
            )
            steps = jnp.where(active, steps + 1, steps)
            sizes = jnp.where(
                commit, sizes.at[cuts].set(committed.n_members), sizes
            )
            cuts = cuts + commit.astype(jnp.int32)
            resolved = (picked.n_members == tgt) & (cuts >= mc)
            done = done | (commit & resolved) | (cuts >= max_cuts)
            return (picked, telem, trace, steps, cuts, sizes, done)

        init = (
            state,
            telem,
            trace,
            jnp.int32(0),
            jnp.int32(0),
            jnp.full((max_cuts,), -1, dtype=jnp.int32),
            (state.n_members == tgt) & (mc <= jnp.int32(0)),
        )
        state, telem, trace, steps, cuts, sizes, _ = jax.lax.fori_loop(
            0, max_steps, body, init
        )
        resolved = (state.n_members == tgt) & (cuts >= mc)
        return (state, telem, trace, steps, cuts, resolved, sizes)

    return jax.vmap(one)(state, telem, trace, faults, knobs, target, min_cuts)


def tenant_health_impl(cfg: EngineConfig, state: EngineState) -> jnp.ndarray:
    """The cheap device-side health reduction: one [t] bool lane, True =
    the tenant's state satisfies the protocol invariants. This is the
    serving tier's poisoned-tenant tripwire (rapid_tpu/serving/supervisor):
    every lane is integral, so "finite" materializes as range/consistency
    checks — the device-side twin of ``models/state.validate_envelope``
    plus the cross-lane invariants a corrupted tenant breaks first:

    - ``n_members`` equals the alive population and sits in [0, n];
    - no slot is simultaneously alive and retired (identities are spent
      exactly once);
    - the per-configuration counters (round_idx, rounds_undecided,
      classic_epoch, promised classic ranks) are non-negative, and under a
      compact layout round_idx sits inside ROUND_ENVELOPE (the
      validate_envelope tripwire — past it the narrow fire_round sentinel
      stops being distinguishable).

    Reductions only (no gathers, no cross-tenant ops): the compiled cost is
    one pass over the [t, ...] lanes, and the hlo budgets are untouched —
    this helper is deliberately NOT a registered device_program entrypoint.
    """
    from rapid_tpu.models.state import ROUND_ENVELOPE

    def one(s: EngineState) -> jnp.ndarray:
        ok = s.n_members == jnp.sum(s.alive, dtype=jnp.int32)
        ok &= (s.n_members >= 0) & (s.n_members <= cfg.n)
        ok &= ~jnp.any(s.alive & s.retired)
        ok &= s.round_idx >= 0
        ok &= s.rounds_undecided.astype(jnp.int32) >= 0
        ok &= s.classic_epoch.astype(jnp.int32) >= 0
        ok &= jnp.all(s.cp_rnd_r.astype(jnp.int32) >= 0)
        ok &= s.config_epoch >= 0
        if cfg.compact:
            ok &= s.round_idx <= ROUND_ENVELOPE
        return ok

    return jax.vmap(one)(state)


tenant_health = jax.jit(tenant_health_impl, static_argnums=(0,))  # donate-ok: read-only health reduction — the state must survive the scan

fleet_step = jax.jit(fleet_step_impl, static_argnums=(0,), donate_argnums=(1,))
fleet_run_to_decision = jax.jit(
    fleet_run_to_decision_impl, static_argnums=(0,), donate_argnums=(1,)
)
fleet_wave = jax.jit(
    fleet_wave_impl, static_argnums=(0, 6), donate_argnums=(1,)
)

fleet_step_telem = jax.jit(
    fleet_step_telem_impl, static_argnums=(0,), donate_argnums=(1, 2)
)
fleet_run_to_decision_telem = jax.jit(
    fleet_run_to_decision_telem_impl, static_argnums=(0,), donate_argnums=(1, 2)
)
fleet_wave_telem = jax.jit(
    fleet_wave_telem_impl, static_argnums=(0, 7), donate_argnums=(1, 2)
)
# donate-ok: read-only boundary fetch — the per-tenant lanes stay live.
fleet_telemetry_digest = jax.jit(jax.vmap(telemetry_digest_impl))

fleet_step_trace = jax.jit(
    fleet_step_trace_impl, static_argnums=(0,), donate_argnums=(1, 2, 3)
)
fleet_run_to_decision_trace = jax.jit(
    fleet_run_to_decision_trace_impl,
    static_argnums=(0,),
    donate_argnums=(1, 2, 3),
)
fleet_wave_trace = jax.jit(
    fleet_wave_trace_impl, static_argnums=(0, 8), donate_argnums=(1, 2, 3)
)
# donate-ok: read-only boundary fetch — the per-tenant rings stay live.
fleet_trace_digest = jax.jit(jax.vmap(trace_digest_impl))


def make_fleet_step(cfg: EngineConfig, mesh: Mesh):
    """jit the fleet step with explicit in-shardings over a
    ``('tenant', 'cohort', 'nodes')`` mesh — the audited batched-step
    entrypoint (``fleet3d_step`` in the HLO lock: zero cross-tenant
    collectives, donation fully aliased)."""
    st_sh = fleet_state_shardings(mesh)
    ft_sh = fleet_fault_shardings(mesh)
    kn_sh = knob_shardings(mesh)

    return jax.jit(
        lambda state, faults, knobs: fleet_step_impl(cfg, state, faults, knobs),
        in_shardings=(st_sh, ft_sh, kn_sh),
        # The state output is pinned to the input table so a driver loop can
        # feed it straight back (XLA propagation is free to "improve" a
        # replicated dimension onto an idle axis, which would then mismatch
        # the declared in_shardings on the next dispatch); events propagate.
        out_shardings=(st_sh, None),
        donate_argnums=(0,),
    )


def make_fleet_wave(cfg: EngineConfig, mesh: Mesh, max_cuts: int = 8):
    """jit the lockstep fleet wave with the mesh's shardings — the audited
    batched-wave entrypoint (``fleet3d_wave``). ``target``/``min_cuts`` are
    [t] lanes sharded on 'tenant'; ``max_steps`` is a replicated scalar (it
    is the lockstep loop's only predicate input — the reason the compiled
    hot loop carries no cross-tenant collective)."""
    st_sh = fleet_state_shardings(mesh)
    ft_sh = fleet_fault_shardings(mesh)
    kn_sh = knob_shardings(mesh)
    lane = NamedSharding(mesh, _resolve_spec((TENANT_AXIS,), mesh))

    return jax.jit(
        lambda state, faults, knobs, target, max_steps, min_cuts: (
            fleet_wave_impl(
                cfg, state, faults, knobs, target, max_steps, max_cuts,
                min_cuts,
            )
        ),
        in_shardings=(st_sh, ft_sh, kn_sh, lane, None, lane),
        # State pinned to the input table (round-trippable, donation-exact);
        # the [t] observation lanes propagate.
        out_shardings=(st_sh, None, None, None, None),
        donate_argnums=(0,),
    )


def stack_pytrees(trees: Sequence):
    """Stack B same-shape pytrees along a new leading tenant axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


class TenantFleet(DispatchSeam):
    """Host driver over the batched engine: owns the stacked state, the
    per-tenant knobs, and the dispatch telemetry (the shared
    :class:`DispatchSeam` — one phase vocabulary across every driver).

    Construction is by stacking ordinary per-tenant ``VirtualCluster``
    builds (:meth:`from_clusters`) — every injection seam (crash, join
    wave, rx-block, cohort assignment) stays the single-cluster API, run
    per tenant BEFORE stacking; the fleet then steps all of them per
    dispatch. ``tests/test_tenancy.py`` pins that this round-trip is
    bit-identical to driving the B clusters separately."""

    def __init__(
        self,
        cfg: EngineConfig,
        state: EngineState,
        faults: FaultInputs,
        knobs: TenantKnobs,
    ) -> None:
        b = int(knobs.h.shape[0])
        for leaf in jax.tree_util.tree_leaves((state, faults, knobs)):
            if leaf.shape[:1] != (b,):
                raise ValueError(
                    f"fleet pytrees must share the leading tenant axis "
                    f"({b}); got a leaf of shape {leaf.shape}"
                )
        self.cfg = cfg
        self.state = state
        self.faults = faults
        self.knobs = knobs
        self.b = b
        self.metrics = Metrics()
        # Attached by rapid_tpu.serving.StreamDriver (None = batch-only).
        self.stream = None
        # Attached by rapid_tpu.serving.supervisor.Supervisor (None = no
        # supervision tier — batch scrapes keep their series set).
        self.recovery = None
        # tenant -> raw frozen membership captured at quarantine time (the
        # per-tenant freeze-lane inputs; see quarantine()).
        self._quarantined: dict = {}
        # Device telemetry plane: per-tenant lanes + the host-side activity
        # cache, zero-minted at attach (every series exists from scrape 0)
        # and refreshed ONLY at host-sync boundaries.
        self.telem = (
            initial_fleet_telemetry(cfg, b) if cfg.telemetry else None
        )
        self._activity = (
            [engine_telemetry.zero_activity_summary(cfg.n, cfg.c)
             for _ in range(b)]
            if cfg.telemetry else None
        )
        # Round-trace ring, per tenant (trace=R refines the telemetry plane;
        # VirtualCluster.__init__ already rejects trace without telemetry,
        # and EngineConfig validation runs there for every construction
        # path, so a fleet config reaching here is consistent).
        self.trace_ring = (
            initial_fleet_trace(cfg, b) if cfg.trace else None
        )
        self._trace = (
            [engine_telemetry.zero_trace_summary(cfg.trace)
             for _ in range(b)]
            if cfg.trace else None
        )
        engine_telemetry.install()

    # -- construction ---------------------------------------------------

    @classmethod
    def from_clusters(cls, clusters: Sequence[VirtualCluster]) -> "TenantFleet":
        """Stack B prepared single-tenant clusters into one fleet. The
        static geometry (slot count, rings, cohorts, delivery model) must
        match across tenants — it pins the one compiled program; the
        per-tenant knobs (H/L, fd_threshold, fallback delay) may differ
        freely and ride :class:`TenantKnobs`."""
        if not clusters:
            raise ValueError("a fleet needs at least one tenant")
        cfgs = [vc.cfg for vc in clusters]
        base = cfgs[0]
        for i, cfg in enumerate(cfgs[1:], start=1):
            diffs = [
                f"{f}: {getattr(base, f)!r} != {getattr(cfg, f)!r}"
                for f in FLEET_STATIC_FIELDS
                if getattr(base, f) != getattr(cfg, f)
            ]
            if diffs:
                raise ValueError(
                    f"tenant {i} differs from tenant 0 in fleet-static "
                    f"config fields ({'; '.join(diffs)}) — these pin the "
                    f"one compiled program; only the TenantKnobs fields "
                    f"may vary per tenant"
                )
        for i, cfg in enumerate(cfgs):
            if not 1 <= cfg.l <= cfg.h <= cfg.k:
                raise ValueError(
                    f"tenant {i}: watermarks must satisfy 1 <= L <= H <= K, "
                    f"got L={cfg.l} H={cfg.h} K={cfg.k}"
                )
            if cfg.fd_window and cfg.fd_threshold > cfg.fd_window:
                raise ValueError(
                    f"tenant {i}: fd_threshold ({cfg.fd_threshold}) cannot "
                    f"exceed fd_window ({cfg.fd_window})"
                )
        fleet = cls(
            base,
            stack_pytrees([vc.state for vc in clusters]),
            stack_pytrees([vc.faults for vc in clusters]),
            TenantKnobs.from_configs(cfgs),
        )
        # The stack re-uploads every tenant's state: charge it once here
        # (the per-cluster builders already charged their own uploads to
        # their own metrics registries, which the fleet does not inherit).
        fleet._account_h2d(*jax.tree_util.tree_leaves(fleet.state))
        if base.telemetry:
            # Carry each tenant's accumulated lanes into the stack (a fleet
            # assembled mid-run keeps its tenants' activity stories).
            fleet.telem = stack_pytrees([vc.telem for vc in clusters])
            fleet._account_h2d(*jax.tree_util.tree_leaves(fleet.telem))
        if base.trace:
            # Same carry for the rings: a mid-run stack keeps each tenant's
            # last-R rounds (cursor and wraps included).
            fleet.trace_ring = stack_pytrees(
                [vc.trace_ring for vc in clusters]
            )
            fleet._account_h2d(*jax.tree_util.tree_leaves(fleet.trace_ring))
        return fleet

    @classmethod
    def create(
        cls,
        tenants: int,
        n_members: int,
        n_slots: Optional[int] = None,
        k: int = 10,
        cohorts: int = 2,
        seeds: Optional[Sequence[int]] = None,
        knobs: Optional[Sequence[Tuple[int, int, int]]] = None,
        **engine_kwargs,
    ) -> "TenantFleet":
        """Synthetic fleet: B independent synthetic clusters (independent
        identity seeds), round-robin cohorts, optional per-tenant
        ``(h, l, fd_threshold)`` knob triples."""
        if seeds is None:
            seeds = list(range(tenants))
        if len(seeds) != tenants:
            raise ValueError(f"need {tenants} seeds, got {len(seeds)}")
        if knobs is not None and len(knobs) != tenants:
            raise ValueError(f"need {tenants} knob triples, got {len(knobs)}")
        clusters = []
        for i in range(tenants):
            h, l, fd = knobs[i] if knobs is not None else (9, 4, 3)
            vc = VirtualCluster.create(
                n_members, n_slots=n_slots, k=k, h=h, l=l, cohorts=cohorts,
                fd_threshold=fd, seed=seeds[i], **engine_kwargs,
            )
            vc.assign_cohorts_roundrobin()
            clusters.append(vc)
        return cls.from_clusters(clusters)

    # -- execution ------------------------------------------------------

    def step(self) -> StepEvents:
        """One protocol round for every tenant — one dispatch, B clusters
        (``engine_dispatch_ms{phase="fleet_step"}``).

        Events come back DEVICE-resident, so ``engine_tenant_cuts`` is
        deliberately not bumped here: reading ``events.decided`` would put
        a host sync on the hot path. The fetching entrypoints
        (:meth:`run_to_decision` / :meth:`run_until_membership`) do the cut
        accounting; a step-driven loop that fetches events itself (the
        autotune sweep) observes its cuts in its own results."""
        return self._step("fleet_step")

    def stream_step(self) -> StepEvents:
        """One ENQUEUED batched round for the streaming pipeline
        (rapid_tpu/serving): the same compiled ``fleet_step`` program as
        :meth:`step` — bit-identical per tenant — accounted under the
        ``stream_enqueue`` phase and guaranteed fetch-free; the stacked
        events stay device-resident (the stream driver's ticket)."""
        return self._step("stream_enqueue")

    def _step(self, phase: str) -> StepEvents:
        """ONE body for both step spellings: only the dispatch-phase label
        differs, so a change here cannot diverge the streamed path from the
        batch path the bit-identity tests pin."""
        self.metrics.inc("engine_tenant_rounds", self.b)
        with self._dispatch(phase):
            if self.trace_ring is not None:
                self.state, self.telem, self.trace_ring, events = (
                    fleet_step_trace(
                        self.cfg, self.state, self.telem, self.trace_ring,
                        self.faults, self.knobs,
                    )
                )
            elif self.telem is not None:
                self.state, self.telem, events = fleet_step_telem(
                    self.cfg, self.state, self.telem, self.faults, self.knobs
                )
            else:
                self.state, events = fleet_step(
                    self.cfg, self.state, self.faults, self.knobs
                )
        return events

    def stream_crash(self, pairs) -> None:
        """Crash ``(tenant, slot)`` pairs mid-stream: one device-side
        scatter onto the stacked crash mask — only the [m, 2] int32 index
        array crosses the host->device boundary, and the update enqueues
        behind the in-flight dispatches (no fetch, no sync). Host-side
        bounds check first: jnp scatters CLAMP out-of-range indices, which
        would silently crash tenant b-1 / slot n-1 on a typo."""
        arr = np.asarray(pairs, dtype=np.int32).reshape(-1, 2)
        if arr.size and (
            arr[:, 0].min() < 0 or arr[:, 0].max() >= self.b
            or arr[:, 1].min() < 0 or arr[:, 1].max() >= self.cfg.n
        ):
            raise IndexError(
                f"(tenant, slot) pairs out of range [0, {self.b}) x "
                f"[0, {self.cfg.n}): {arr.tolist()}"
            )
        self._account_h2d(arr)
        idx = jnp.asarray(arr)
        self.faults = self.faults._replace(
            crashed=self.faults.crashed.at[idx[:, 0], idx[:, 1]].set(True)
        )

    def run_to_decision(self, max_steps: int = 64):
        """Every tenant runs to its own first view change in one dispatch;
        returns ``(rounds[t], decided[t], winner[t, n] on device,
        members[t])`` with one packed observation fetch."""
        with self._dispatch("fleet_decision"):
            if self.trace_ring is not None:
                self.state, self.telem, self.trace_ring, steps, decided, winner = (
                    fleet_run_to_decision_trace(
                        self.cfg, self.state, self.telem, self.trace_ring,
                        self.faults, self.knobs, jnp.int32(max_steps),
                    )
                )
            elif self.telem is not None:
                self.state, self.telem, steps, decided, winner = (
                    fleet_run_to_decision_telem(
                        self.cfg, self.state, self.telem, self.faults,
                        self.knobs, jnp.int32(max_steps),
                    )
                )
            else:
                self.state, steps, decided, winner = fleet_run_to_decision(
                    self.cfg, self.state, self.faults, self.knobs,
                    jnp.int32(max_steps),
                )
            obs = np.asarray(
                jnp.stack(
                    [steps, decided.astype(jnp.int32), self.state.n_members]
                )
            )
        self._account_d2h(obs.nbytes)
        rounds = obs[0]
        was_decided = obs[1].astype(bool)
        self.metrics.inc("engine_tenant_rounds", int(rounds.sum()))
        self.metrics.inc("engine_tenant_cuts", int(was_decided.sum()))
        return rounds, was_decided, winner, obs[2]

    def run_until_membership(
        self,
        targets,
        max_steps: int = 192,
        max_cuts: int = 8,
        min_cuts=0,
    ):
        """The fleet wave: every tenant resolves its own churn — through
        its own number of view changes — to its own target membership, in
        ONE lockstep dispatch. ``targets``/``min_cuts`` broadcast from
        scalars or give one value per tenant. Returns ``(rounds[t],
        cuts[t], resolved[t], sizes[t, max_cuts])`` as host arrays."""
        targets = np.broadcast_to(
            np.asarray(targets, dtype=np.int32), (self.b,)
        ).copy()
        min_cuts = np.broadcast_to(
            np.asarray(min_cuts, dtype=np.int32), (self.b,)
        ).copy()
        # Quarantined tenants ride the wave FROZEN: their target lane is
        # pinned to the raw membership captured at quarantine time and
        # min_cuts to 0, so the lockstep loop's done lane is True from
        # iteration 0 — the tenant's state never changes, inside the SAME
        # compiled program (data, not a recompile). The captured value may
        # be garbage (that is WHY the tenant was quarantined), so the range
        # check below applies only to the serving lanes.
        serving = np.ones(self.b, dtype=bool)
        for t, frozen_members in self._quarantined.items():
            targets[t] = frozen_members
            min_cuts[t] = 0
            serving[t] = False
        bad = targets[serving]
        if bad.size and (bad.min() < 0 or bad.max() > self.cfg.n):
            raise ValueError(
                f"targets must be in [0, {self.cfg.n}]: {targets.tolist()}"
            )
        self._account_h2d(targets, min_cuts)
        with self._dispatch("fleet_wave"):
            if self.trace_ring is not None:
                (
                    self.state, self.telem, self.trace_ring,
                    steps, cuts, resolved, sizes,
                ) = fleet_wave_trace(
                    self.cfg, self.state, self.telem, self.trace_ring,
                    self.faults, self.knobs, jnp.asarray(targets),
                    jnp.int32(max_steps), int(max_cuts),
                    jnp.asarray(min_cuts),
                )
            elif self.telem is not None:
                self.state, self.telem, steps, cuts, resolved, sizes = (
                    fleet_wave_telem(
                        self.cfg, self.state, self.telem, self.faults,
                        self.knobs, jnp.asarray(targets),
                        jnp.int32(max_steps), int(max_cuts),
                        jnp.asarray(min_cuts),
                    )
                )
            else:
                self.state, steps, cuts, resolved, sizes = fleet_wave(
                    self.cfg, self.state, self.faults, self.knobs,
                    jnp.asarray(targets), jnp.int32(max_steps), int(max_cuts),
                    jnp.asarray(min_cuts),
                )
            obs = np.asarray(
                jnp.concatenate(
                    [steps, cuts, resolved.astype(jnp.int32), sizes.reshape(-1)]
                )
            )
        self._account_d2h(obs.nbytes)
        b = self.b
        rounds, n_cuts = obs[:b], obs[b : 2 * b]
        resolved_h = obs[2 * b : 3 * b].astype(bool)
        sizes_h = obs[3 * b :].reshape(b, max_cuts)
        self.metrics.inc("engine_tenant_rounds", int(rounds.sum()))
        self.metrics.inc("engine_tenant_cuts", int(n_cuts.sum()))
        return rounds, n_cuts, resolved_h, sizes_h

    def sync(self) -> None:
        """Complete all pending uploads/compute on the fleet state."""
        jax.block_until_ready(self.state)
        self._refresh_activity()

    def _refresh_activity(self) -> None:
        """Refresh the per-tenant activity cache from the device lanes —
        called ONLY at host-sync boundaries (sync / health_scan / the
        stream driver's fetch seam), never on the dispatch hot path."""
        if self.telem is None:
            return
        # telemetry-fetch-ok: host-sync boundary — the caller is already
        # paying a blocking device round trip here.
        digest = np.asarray(fleet_telemetry_digest(self.telem))
        self._account_d2h(digest.nbytes)
        self._activity = [
            engine_telemetry.activity_summary(
                digest[t], self.cfg.n, self.cfg.c
            )
            for t in range(self.b)
        ]
        if self.trace_ring is not None:
            # telemetry-fetch-ok: same host-sync boundary — one stacked
            # [t, 2 + 9R] digest fetch decodes every tenant's ring.
            tdigest = np.asarray(fleet_trace_digest(self.trace_ring))
            self._account_d2h(tdigest.nbytes)
            self._trace = [
                engine_telemetry.trace_summary(tdigest[t], self.cfg.trace)
                for t in range(self.b)
            ]

    @property
    def activity(self) -> Optional[dict]:
        """The fleet-wide activity aggregate from the last host-sync
        boundary (counters summed, peaks maxed across tenants), or None on
        a telemetry=0 fleet — reading it never touches the device."""
        if self._activity is None:
            return None
        return engine_telemetry.aggregate_activity(
            self._activity, self.cfg.n, self.cfg.c
        )

    @property
    def tenant_activity(self) -> Optional[List[dict]]:
        """Per-tenant activity summaries (copies) from the last host-sync
        boundary, or None on a telemetry=0 fleet."""
        if self._activity is None:
            return None
        return [dict(a) for a in self._activity]

    @property
    def tenant_trace(self) -> Optional[List[dict]]:
        """Per-tenant decoded ring digests (deep copies — records included)
        from the last host-sync boundary, or None on a trace=0 fleet.
        Reading it never touches the device."""
        if self._trace is None:
            return None
        out = []
        for tr in self._trace:
            d = dict(tr)
            d["records"] = [dict(r) for r in tr["records"]]
            out.append(d)
        return out

    # -- health & quarantine (the serving supervision tier's seams) ------

    def health_scan(self) -> np.ndarray:
        """Run the device-side health reduction
        (:func:`tenant_health_impl`) over every tenant: one dispatch, one
        [t]-bool fetch; returns the POISONED mask (True = invariants
        violated). Cheap enough to run between waves — the supervisor's
        poisoned-tenant tripwire."""
        with self._dispatch("health_scan"):
            ok = np.asarray(tenant_health(self.cfg, self.state))
        self._account_d2h(ok.nbytes)
        self._refresh_activity()
        return ~ok

    def tenant_health_report(self, t: int) -> List[str]:
        """Host-side diagnosis of ONE tenant: the named violations behind a
        health_scan hit (the repro's violations.txt). Mirrors
        :func:`tenant_health_impl` check for check — the device scan is the
        cheap tripwire, this is the loud explanation, and the two cannot
        disagree on a poisoned tenant because both read the same lanes."""
        from rapid_tpu.models.state import ROUND_ENVELOPE

        if not 0 <= t < self.b:
            raise IndexError(f"tenant index {t} out of range [0, {self.b})")
        s = self.tenant_state(t)
        violations: List[str] = []
        alive = int(np.sum(np.asarray(s.alive)))
        members = int(s.n_members)
        self._account_d2h(np.asarray(s.alive).nbytes + 4)
        if members != alive:
            violations.append(
                f"tenant {t}: n_members={members} != alive population {alive}"
            )
        if not 0 <= members <= self.cfg.n:
            violations.append(
                f"tenant {t}: n_members={members} outside [0, {self.cfg.n}]"
            )
        if bool(np.any(np.asarray(s.alive) & np.asarray(s.retired))):
            violations.append(
                f"tenant {t}: slot(s) simultaneously alive and retired"
            )
        for lane in ("round_idx", "rounds_undecided", "classic_epoch"):
            value = int(getattr(s, lane))
            if value < 0:
                violations.append(f"tenant {t}: {lane}={value} negative")
        if int(np.min(np.asarray(s.cp_rnd_r))) < 0:
            violations.append(f"tenant {t}: negative promised classic rank")
        if int(s.config_epoch) < 0:
            violations.append(
                f"tenant {t}: config_epoch={int(s.config_epoch)} negative"
            )
        if self.cfg.compact and int(s.round_idx) > ROUND_ENVELOPE:
            violations.append(
                f"tenant {t}: round_idx={int(s.round_idx)} past the compact "
                f"envelope {ROUND_ENVELOPE} (validate_envelope tripwire)"
            )
        return violations

    def quarantine(self, tenants: Sequence[int]) -> None:
        """Quarantine tenants inside the RUNNING compiled program: capture
        each tenant's raw membership (one [t] fetch, shared) and pin its
        wave-path freeze lanes to it — the lockstep ``done`` mask the fleet
        wave already carries holds the tenant bit-frozen from iteration 0,
        with no recompile (the lanes are data) and zero effect on the other
        B-1 tenants (vmap independence, the zero-cross-tenant budget frozen
        in hlo.lock.json). The batched STEP path has no freeze lane (a
        per-tenant gate there would be a new program input — a recompile,
        which this mechanism exists to avoid): step dispatches keep
        executing the quarantined tenant's rounds, harmlessly to the
        others; serving callers stop feeding it churn and exclude it from
        their accounting (the supervision tier does both). Idempotent per
        tenant; never reversible within a fleet's lifetime (a poisoned
        state has no un-poison story — export the repro and re-admit a
        fresh tenant instead)."""
        members = np.asarray(self.state.n_members)
        self._account_d2h(members.nbytes)
        for t in tenants:
            t = int(t)
            if not 0 <= t < self.b:
                raise IndexError(
                    f"tenant index {t} out of range [0, {self.b})"
                )
            if t not in self._quarantined:
                self._quarantined[t] = int(members[t])
                self.metrics.inc("engine_tenant_quarantines")

    @property
    def quarantined(self) -> Tuple[int, ...]:
        """The quarantined tenant indices, sorted."""
        return tuple(sorted(self._quarantined))

    # -- observers ------------------------------------------------------

    def tenant_state(self, i: int) -> EngineState:
        """Tenant ``i``'s state slice (device-resident views)."""
        if not 0 <= i < self.b:
            raise IndexError(f"tenant index {i} out of range [0, {self.b})")
        return jax.tree_util.tree_map(lambda x: x[i], self.state)

    def membership_sizes(self) -> np.ndarray:
        out = np.asarray(self.state.n_members)
        self._account_d2h(out.nbytes)
        return out

    def config_ids(self) -> List[int]:
        """Per-tenant 64-bit configuration ids, one packed fetch."""
        obs = np.asarray(jnp.stack([self.state.config_hi, self.state.config_lo]))
        self._account_d2h(obs.nbytes)
        return [
            (int(hi) << 32) | int(lo) for hi, lo in zip(obs[0], obs[1])
        ]

    def config_epochs(self) -> np.ndarray:
        out = np.asarray(self.state.config_epoch)
        self._account_d2h(out.nbytes)
        return out

    def health(self) -> NodeHealth:
        """Fleet-wide health in the host vocabulary: PROPOSING while any
        tenant has churn in flight, STABLE otherwise (one scalar fetch)."""
        pending = int(
            jnp.sum(self.state.alive & self.faults.crashed, dtype=jnp.int32)
            + jnp.sum(self.state.join_pending, dtype=jnp.int32)
        )
        self._account_d2h(4)
        return NodeHealth.PROPOSING if pending else NodeHealth.STABLE

    # -- observability (utils/exposition.py schema) ---------------------

    def telemetry_snapshot(self) -> dict:
        """The fleet's unified telemetry snapshot — the engine schema plus
        a ``tenancy`` section (tenant count, per-dispatch tenant
        throughput), so one scrape pipeline serves host nodes, single
        clusters, and fleets alike (golden names pinned in
        tests/test_engine_telemetry.py)."""
        counters = self.metrics.counters
        dispatches = counters.get("engine_dispatches", 0)
        tenant_rounds = counters.get("engine_tenant_rounds", 0)
        return {
            "node": f"tenant-fleet/{self.b}x{self.cfg.n}",
            "membership_size": int(self.membership_sizes().sum()),
            "health": self.health().value,
            "metrics": self.metrics.summary(),
            "engine": {
                "n": self.cfg.n,
                "cohorts": self.cfg.c,
                "use_pallas": self.cfg.use_pallas,
                "compile": engine_telemetry.compile_snapshot(),
                "memory": engine_telemetry.device_memory_snapshot(),
                "tenancy": {
                    "tenants": self.b,
                    "tenant_rounds_total": int(tenant_rounds),
                    "tenant_cuts_total": int(
                        counters.get("engine_tenant_cuts", 0)
                    ),
                    "tenant_rounds_per_dispatch": round(
                        tenant_rounds / dispatches, 3
                    ) if dispatches else 0.0,
                    "quarantined": len(self._quarantined),
                },
                # Device telemetry plane: present only when the fleet was
                # built with telemetry=1 (the stable-series rule — a
                # telemetry=0 fleet's scrape vocabulary is unchanged). The
                # aggregate pools every tenant; the per-tenant list feeds
                # the exposition's tenant=<idx> labelled variants.
                **(
                    {
                        "activity": engine_telemetry.aggregate_activity(
                            self._activity, self.cfg.n, self.cfg.c
                        ),
                        "tenant_activity": [
                            dict(a) for a in self._activity
                        ],
                    }
                    if self._activity is not None
                    else {}
                ),
                # Round-trace ring: per-tenant decoded digests, present only
                # on trace>0 fleets (the same stable-series rule).
                **(
                    {"tenant_trace": self.tenant_trace}
                    if self._trace is not None
                    else {}
                ),
                # Streaming tier: present only when a StreamDriver is
                # attached (the VirtualCluster rule — batch-only scrapes
                # keep their series set).
                **(
                    {"stream": self.stream.snapshot()}
                    if self.stream is not None
                    else {}
                ),
                # Supervision tier: present only when a Supervisor is
                # attached (same stable-series rule).
                **(
                    {"recovery": self.recovery.snapshot()}
                    if self.recovery is not None
                    else {}
                ),
            },
            "transport": {},
            "recorder": None,
        }

    def prometheus_text(self) -> str:
        return exposition.prometheus_text(self.telemetry_snapshot())
