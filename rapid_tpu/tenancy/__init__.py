"""Multi-tenant batched serving: step hundreds of independent clusters per
dispatch (ROADMAP item 4).

``fleet`` holds the batched engine — :class:`~rapid_tpu.tenancy.fleet.TenantFleet`
vmaps the existing engine impls over a leading tenant axis; ``chaos`` compiles
``sim/fuzz.py`` scenario families per tenant into one stacked fleet and checks
the oracle battery tenant by tenant; ``autotune`` sweeps per-tenant K/H/L
knobs online with the khl_sensitivity conflict metric as the objective.
"""

from rapid_tpu.tenancy.fleet import TenantFleet, TenantKnobs  # noqa: F401

__all__ = ["TenantFleet", "TenantKnobs"]
