"""Online per-tenant K/H/L autotune: the knob sweep AS a tenant fleet.

``examples/khl_sensitivity.py`` reproduces the paper's Fig. 11 study — the
fraction of receivers whose FIRST announced proposal misses a victim (a
conflict) under delivery skew, per (H, L) setting. That conflict metric is
exactly an online autotune objective: run B tenants over the IDENTICAL
scenario (same seed, same victims, same delivery jitter), one knob setting
per tenant, in one batched dispatch per round — the sweep costs one fleet
step where the sequential version paid B single-cluster steps — and pick
the winner the way ``examples/delivery_autotune.py`` picks its tile width
(a per-candidate score table plus one ``best_*`` field consumers read off).

Score per knob: ``(conflict, rounds)`` lexicographic — a setting whose
first decided cut contains exactly the victim set beats any conflicted
setting; among clean settings, faster decisions win (H low → fast but
conflict-prone; H high → safe but slow — the paper's tradeoff, measured
instead of assumed).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.tenancy.fleet import TenantFleet

#: The default candidate grid: the paper's H sweep at sane L, highest-H
#: first (grid order is the tie-break, so equal scores prefer the safest
#: watermark).
DEFAULT_KNOB_GRID: Tuple[Tuple[int, int], ...] = (
    (9, 4), (8, 3), (7, 2), (6, 2), (5, 1),
)


def sweep_khl(
    n: int = 256,
    f: int = 4,
    knob_grid: Sequence[Tuple[int, int]] = DEFAULT_KNOB_GRID,
    k: int = 10,
    cohorts: int = 8,
    seed: int = 0,
    fd_threshold: int = 1,
    delivery_spread: int = 8,
    stagger_rounds: int = 2,
    max_rounds: int = 96,
) -> Dict:
    """One batched knob sweep: ``len(knob_grid)`` tenants, identical
    F-failure scenario, per-tenant (H, L). Returns the autotune artifact::

        {"n", "f", "seed", "objective",
         "per_knob": {"H/L": {"decided", "rounds", "conflict"}},
         "best_knob": "H/L" | None}

    ``conflict`` is the khl_sensitivity metric at tenant grain: the first
    DECIDED cut differs from the full victim set (an early/partial
    almost-everywhere-agreement outcome the H watermark exists to prevent).
    ``best_knob`` is None only when no candidate decided in budget."""
    knob_grid = [tuple(kn) for kn in knob_grid]
    rng = np.random.default_rng(seed + 1000)
    victims = np.sort(rng.choice(n, size=f, replace=False))

    clusters = []
    for h, l in knob_grid:
        vc = VirtualCluster.create(
            n, k=k, h=h, l=l, cohorts=cohorts, fd_threshold=fd_threshold,
            seed=seed, delivery_spread=delivery_spread,
        )
        vc.assign_cohorts_roundrobin()
        if stagger_rounds:
            # Identical per-edge detection jitter across tenants: the same
            # rng seed per tenant means ONLY the knobs differ.
            vc.stagger_fd_counts(
                np.random.default_rng(seed + 2000), stagger_rounds
            )
        vc.crash(victims)
        clusters.append(vc)
    fleet = TenantFleet.from_clusters(clusters)

    b = fleet.b
    victims_mask = np.zeros(fleet.cfg.n, dtype=bool)
    victims_mask[victims] = True
    first_winner = np.zeros((b, fleet.cfg.n), dtype=bool)
    decided_round = np.full(b, -1, dtype=np.int64)
    for round_idx in range(max_rounds):
        events = fleet.step()
        decided = np.asarray(events.decided)
        winners = np.asarray(events.winner_mask)
        fresh = decided & (decided_round < 0)
        if fresh.any():
            decided_round[fresh] = round_idx + 1
            first_winner[fresh] = winners[fresh]
        if (decided_round >= 0).all():
            break

    per_knob: Dict[str, Dict] = {}
    scores = []
    for i, (h, l) in enumerate(knob_grid):
        decided = bool(decided_round[i] >= 0)
        conflict = decided and bool(
            (first_winner[i] != victims_mask).any()
        )
        per_knob[f"{h}/{l}"] = {
            "decided": decided,
            "rounds": int(decided_round[i]) if decided else None,
            "conflict": conflict if decided else None,
        }
        if decided:
            # Tie-break by GRID ORDER (i), not by knob name: equal scores
            # prefer the caller's safest-first ordering.
            scores.append(((int(conflict), int(decided_round[i])), i, f"{h}/{l}"))
    best: Optional[str] = min(scores)[2] if scores else None
    return {
        "n": n,
        "f": f,
        "seed": seed,
        "tenants": b,
        "objective": "first-cut conflict (khl_sensitivity metric), then "
                     "decision rounds",
        "per_knob": per_knob,
        "best_knob": best,
    }
