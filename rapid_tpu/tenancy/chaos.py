"""Fleet-scale batched chaos: hundreds of adversarial scenarios per
dispatch, the oracle battery checked tenant by tenant, and a per-tenant
shrinker that collapses a violating fleet to a single-tenant repro.

The single-cluster differential oracle (``sim/oracles.replay_through_engine``)
compiles ONE fault schedule's membership phases onto ONE engine; this module
is its fleet twin: B ``(family, seed)`` pairs from ``sim/fuzz.py`` — honest
adverse-network shapes, ADVERSARIAL shapes (Byzantine observers lying
against the H/L watermarks), and the hier×tenancy cross-product (the
WAN-shaped hierarchical families' cohort structure and churn compiled per
tenant) — each an independent seeded scenario, compile onto B per-tenant
clusters with independent fault inputs, stack into one
:class:`~rapid_tpu.tenancy.fleet.TenantFleet`, and resolve phase group by
phase group with ONE fleet-wave dispatch per group (B scenarios'
convergences per dispatch, however differently they churn). After the
groups, a STABILITY SOAK steps the whole stacked fleet a fixed number of
plain rounds so tenants carrying sub-H false-report loads demonstrably hold
the stable band (a frozen tenant proves nothing — the soak is what makes
"no eviction" a run, not a vacuous skip). Scenario diversity and throughput
in one workload — ``run_fleet`` reports wall clock and a first-class
``scenarios_per_sec``, the number ``bench.py``'s ``chaos`` stage and
``chaosrun fuzz --fleet`` publish.

The per-tenant verdicts mirror the sim battery's oracle vocabulary at the
engine grain, every violation naming its tenant index (no cross-tenant
bleed — one tenant's broken chain must never taint its neighbors' verdicts,
pinned in tests/test_tenancy_chaos.py):

- ``fleet-convergence`` — every phase group resolved within its budget;
- ``fleet-membership`` — final alive slots are exactly the schedule's
  surviving slots;
- ``fleet-chain-consistency`` — the tenant's configuration chain only
  advances: per-phase config ids all distinct, epochs strictly increasing;
- ``fleet-stability`` — a tenant whose only hostile load is sub-H false
  reports committed a cut during the soak (the stable band leaked);
- ``fleet-injection`` — a scenario's fault injection itself failed
  mid-``run_fleet``; the tenant is named and frozen instead of the whole
  fleet dying on a bare exception.

When a violation fires, :func:`shrink_tenant` greedily minimizes ONLY the
violating tenant's schedule — every other tenant replaced by quiescent
filler so each probe run stays one fleet dispatch at the original fleet
shape — and :func:`write_fleet_repro` collapses the result to a
single-tenant repro directory in the sim schedule format, replayable by
``chaosrun replay`` (which recognizes the ``fleet.json`` marker and replays
through the engine fleet path).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.sim.faults import (
    WATERMARK_H,
    WATERMARK_K,
    WATERMARK_L,
    FaultEvent,
    FaultSchedule,
)
from rapid_tpu.sim.fuzz import hier_geometry, scenario_family
from rapid_tpu.sim.oracles import Violation, inject_engine_event
from rapid_tpu.sim.scenario import endpoints_for
from rapid_tpu.tenancy.fleet import TenantFleet

#: Engine-replayable flat families (restart-bearing schedules are excluded
#: by engine_compatible). The adversarial flat families ride the same
#: geometry: stable-band lies compile to persistent sub-H probe-fail loads,
#: H-crossing lies to membership-bearing phase groups.
ENGINE_FAMILIES = (
    "partition_heal",
    "asymmetric_link",
    "crash_during_join",
    "churn_under_loss",
    "false_alert_stability",
    "watermark_probe",
)

#: The hier×tenancy cross-product: the hierarchical families' cohort
#: structure (the seeded CohortMap of the initial cluster, mapped onto the
#: engine's receiver-cohort axis) and membership churn compiled per tenant.
#: Environment-only faults (WAN loss/delay, link flaps, clock skew) have no
#: round-granular engine analog and are not replayed — the same contract as
#: the differential oracle: they must change WHEN, never WHAT, is decided.
HIER_FAMILIES = (
    "wan_cohort_asym",
    "delegate_gray_failure",
    "cohort_boundary_flap",
    "committee_crash_during_reconfig",
)

#: Everything the fleet fuzzer mixes per dispatch, in DISPATCH order:
#: adversarial shapes lead so any fleet size B >= 1 carries Byzantine
#: coverage — ``fleet_specs`` cycles this tuple, and a small-B bench run
#: (RAPID_TPU_BENCH_CHAOS_B=4) must still be an ADVERSARIAL workload, not
#: four honest churn scenarios wearing the chaos label. Membership vs the
#: fuzz registry is linted (chaosvocab); completeness vs the mix tables is
#: pinned in tests/test_tenancy_chaos.py.
FLEET_FAMILIES = (
    "false_alert_stability",
    "committee_crash_during_reconfig",
    "watermark_probe",
    "partition_heal",
    "wan_cohort_asym",
    "crash_during_join",
    "delegate_gray_failure",
    "churn_under_loss",
    "cohort_boundary_flap",
    "asymmetric_link",
)

#: The default per-tenant knob triple (h, l, fd_threshold): the reference
#: watermarks the schedules' own accounting uses — deriving it (instead of
#: re-typing 9/4) keeps a Settings retune from silently forking the
#: compiler's defaults away from what validate()/adversarial_crossings()
#: judge against (the knob/schedule-mismatch shape stays an EXPLICIT act).
DEFAULT_KNOBS = (WATERMARK_H, WATERMARK_L, 1)

#: Plain rounds stepped after the phase groups so stable-band tenants
#: demonstrably hold: enough rounds for a (wrongly) released cut to decide
#: if the detector leaked, small enough to stay negligible per dispatch.
STABILITY_SOAK_ROUNDS = 12

#: Ring capacity of the repro verify run: large enough to hold a shrunk
#: schedule's full round history (shrunk repros resolve in a handful of
#: short phase groups), so the ``trace.json`` artifact usually carries
#: every round the repro executed, not just a tail window.
REPRO_TRACE_R = 64


@dataclass
class TenantScenario:
    """One tenant's compiled scenario: the schedule, its engine cluster, and
    the host-side expectations the oracles check against."""

    family: str
    seed: int
    schedule: FaultSchedule
    vc: VirtualCluster
    groups: List[List[FaultEvent]]
    expected_slots: frozenset  # surviving slot indices at the end
    knobs: Tuple[int, int, int] = DEFAULT_KNOBS
    delivery_spread: int = 0
    #: Subjects carrying a sub-H false-report load for the whole run — the
    #: stability soak asserts these tenants commit NO cut.
    stable_subjects: frozenset = frozenset()

    @property
    def name(self) -> str:
        return f"{self.family}/{self.seed}"


@dataclass
class PhaseRecord:
    resolved: bool
    cuts: int
    config_id: int
    config_epoch: int
    members: int


@dataclass
class FleetRunResult:
    """What one batched chaos run observed, per tenant — the oracle input."""

    scenarios: List[TenantScenario]
    phases: List[List[PhaseRecord]] = field(default_factory=list)
    final_slots: List[frozenset] = field(default_factory=list)
    dispatches: int = 0
    total_rounds: int = 0
    total_cuts: int = 0
    #: Mid-run per-tenant failures (injection raised) as (tenant index,
    #: already-formed violation) pairs, prepended by check_fleet — a broken
    #: scenario must never surface as a bare exception that kills the
    #: other B-1 tenants' verdicts. The index rides structurally (never
    #: re-parsed out of the formatted message).
    errors: List[Tuple[int, Violation]] = field(default_factory=list)
    #: Cuts each tenant committed during the stability soak (None = no soak).
    soak_cuts: Optional[np.ndarray] = None
    soak_rounds: int = 0
    #: Wall clock of the whole batched run and the first-class throughput
    #: number it buys: scenarios resolved per second of fleet dispatch.
    wall_ms: float = 0.0
    scenarios_per_sec: float = 0.0


def _hier_cohort_of(seed: int, n_slots: int) -> np.ndarray:
    """The engine receiver-cohort assignment for a hier-profile tenant: the
    family's own seeded CohortMap over the initial members (so a fault
    aimed at a real cohort boundary lands on the same structure the host
    protocol would build), joiner slots round-robin."""
    cmap, endpoints, slot_of = hier_geometry(seed)
    cohort_of = np.zeros(n_slots, dtype=np.int32)
    for ep, slot in slot_of.items():
        if slot < len(endpoints) and cmap.is_member(ep):
            cohort_of[slot] = cmap.cohort_of(ep)
    n0 = sum(1 for ep in slot_of if cmap.is_member(ep))
    for slot in range(n0, n_slots):
        cohort_of[slot] = slot % cmap.n_cohorts
    return cohort_of


def compile_schedule(
    schedule: FaultSchedule,
    family: str,
    seed: int,
    knobs: Tuple[int, int, int] = DEFAULT_KNOBS,
    delivery_spread: int = 0,
    telemetry: bool = False,
    trace: int = 0,
) -> TenantScenario:
    """Compile one schedule onto a per-tenant engine cluster — the same
    event mapping the differential oracle uses (``inject_engine_event``),
    with the tenant's ``(h, l, fd_threshold)`` knobs on top. ``trace``
    additionally carries the round-trace ring (implies telemetry) — engine
    results are bit-identical with or without either plane.

    Sub-H false-report loads (the stable band) are applied HERE, as
    persistent per-(subject, ring) probe failures: they are environment-
    shaped (membership never changes), so they ride every subsequent round
    of every group and the stability soak. H-crossing lies arrive as
    membership-bearing phase groups, normalized by ``membership_phases`` to
    carry the cumulative ring set.

    Note the deliberate asymmetry: the schedule's OWN accounting (does this
    lie evict?) always uses the reference watermarks (``WATERMARK_H``),
    while the tenant may run different knobs — a knob/schedule mismatch is
    exactly the violating-fleet shape the shrinker regression pins."""
    if not schedule.engine_compatible:
        raise ValueError(
            f"{family}/{seed}: schedule is not engine-replayable (restarts "
            f"spend engine slots forever)"
        )
    endpoints = endpoints_for(seed, schedule.n_slots)
    h, l, fd_threshold = knobs
    vc = VirtualCluster.from_endpoints(
        endpoints, n_slots=len(endpoints), n_members=schedule.n0,
        k=WATERMARK_K, h=h, l=l, fd_threshold=fd_threshold,
        delivery_spread=delivery_spread,
        telemetry=telemetry or bool(trace), trace=trace,
    )
    if schedule.profile == "hier":
        vc.assign_cohorts(_hier_cohort_of(seed, schedule.n_slots))
    # Persistent sub-H lies: everything claimed about subjects that never
    # cross H. (Crossing subjects' rings arrive with their phase group.)
    crossed = {s for s, _ in schedule.adversarial_crossings().values()}
    stable: Dict[int, set] = {}
    for event in schedule.events:
        if event.kind not in ("false_alert", "alert_storm"):
            continue
        if str(event.args.get("status", "DOWN")) != "DOWN":
            continue
        subject = int(event.args["subject"])  # type: ignore[arg-type]
        if subject in crossed:
            continue
        stable.setdefault(subject, set()).update(
            int(r) for r in event.args.get("rings", ())  # type: ignore[union-attr]
        )
    if stable:
        probe = np.zeros((schedule.n_slots, WATERMARK_K), dtype=bool)
        for subject, rings in stable.items():
            assert len(rings) < WATERMARK_H
            probe[subject, sorted(rings)] = True
        vc.set_flaky_edges(probe)
    joined = set(range(schedule.n0))
    for event in schedule.events:
        if event.kind in ("join", "restart"):
            joined |= set(event.slots)
    expected = frozenset(joined - schedule.expected_removed_slots())
    return TenantScenario(
        family=family,
        seed=seed,
        schedule=schedule,
        vc=vc,
        groups=schedule.membership_phases(),
        expected_slots=expected,
        knobs=tuple(knobs),
        delivery_spread=delivery_spread,
        stable_subjects=frozenset(stable),
    )


def compile_tenant(
    family: str,
    seed: int,
    knobs: Tuple[int, int, int] = DEFAULT_KNOBS,
    delivery_spread: int = 0,
    telemetry: bool = False,
) -> TenantScenario:
    """Compile one named ``(family, seed)`` scenario (sim/fuzz.py) onto a
    per-tenant engine cluster. ``telemetry=True`` carries the device
    telemetry plane — engine results are bit-identical either way."""
    return compile_schedule(
        scenario_family(family, seed), family, seed, knobs, delivery_spread,
        telemetry,
    )


def compile_quiescent(
    seed: int,
    knobs: Tuple[int, int, int] = DEFAULT_KNOBS,
    delivery_spread: int = 0,
    n0: int = 8,
    n_slots: int = 12,
) -> TenantScenario:
    """An event-free filler tenant at the shared geometry: it idles through
    every wave for free (already at target, zero cuts demanded). The
    shrinker swaps these in for every non-violating tenant so a probe run
    keeps the original fleet shape — one dispatch, same compiled program."""
    schedule = FaultSchedule(
        n0=n0, n_slots=n_slots, seed=seed, name=f"quiescent/{seed}"
    )
    return compile_schedule(schedule, "quiescent", seed, knobs, delivery_spread)


def compile_fleet(
    specs: Sequence[Tuple[str, int]],
    knobs: Optional[Sequence[Tuple[int, int, int]]] = None,
    delivery_spread: int = 0,
    telemetry: bool = False,
) -> List[TenantScenario]:
    """One compiled scenario per ``(family, seed)`` spec — honest, hostile,
    and hier families freely mixed. All families share the fuzz geometry
    (``N0``/``N_SLOTS``), so the B clusters stack into one fleet; ``knobs``
    optionally varies (h, l, fd_threshold) per tenant; ``delivery_spread``
    is fleet-static (it pins the compiled program) and applies to every
    tenant."""
    if knobs is not None and len(knobs) != len(specs):
        raise ValueError(f"need {len(specs)} knob triples, got {len(knobs)}")
    return [
        compile_tenant(
            family, seed, knobs[i] if knobs else DEFAULT_KNOBS,
            delivery_spread, telemetry,
        )
        for i, (family, seed) in enumerate(specs)
    ]


def _restore_trace_rings(
    fleet: TenantFleet, scenarios: Sequence[TenantScenario]
) -> None:
    """Hand each tenant's slice of the fleet's trace ring back to its
    cluster, so the ring stays continuous across the per-group
    ``from_clusters`` restacks (the same continuity ``vc.state`` gets
    above). No-op for untraced fleets — device-side slicing, no fetch."""
    if fleet.trace_ring is None:
        return
    import jax

    for i, scenario in enumerate(scenarios):
        scenario.vc.trace_ring = jax.tree_util.tree_map(
            lambda leaf, t=i: leaf[t], fleet.trace_ring
        )


def _inject_group(vc: VirtualCluster, group: List[FaultEvent]) -> int:
    """Apply one membership phase group's events to a tenant's cluster via
    the shared host-event -> engine-seam mapping. Returns the membership
    delta."""
    return sum(inject_engine_event(vc, event) for event in group)


def run_fleet(
    scenarios: Sequence[TenantScenario],
    max_steps: int = 64,
    max_cuts: int = 8,
    soak_rounds: Optional[int] = None,
) -> FleetRunResult:
    """Resolve every tenant's scenario, phase group by phase group: inject
    group ``g`` into each tenant that still has one, stack, and resolve the
    whole fleet in ONE wave dispatch per group (tenants whose schedule ran
    out of groups idle for free — already at target, zero cuts demanded),
    then soak ``soak_rounds`` plain fleet rounds (default: the stability
    soak when any tenant carries a sub-H false-report load, else none).

    A tenant whose injection RAISES is frozen and reported as a
    ``fleet-injection`` violation naming its index — never a bare exception
    (the mid-run plumbing of ISSUE 12 satellite 3). Per-tenant observations
    land in a :class:`FleetRunResult` for :func:`check_fleet`, alongside
    the run's wall clock and ``scenarios_per_sec``."""
    scenarios = list(scenarios)
    started = time.perf_counter()
    result = FleetRunResult(scenarios=scenarios)
    result.phases = [[] for _ in scenarios]
    expected = [s.schedule.n0 for s in scenarios]
    dead = [False] * len(scenarios)
    n_groups = max((len(s.groups) for s in scenarios), default=0)
    alive: Optional[np.ndarray] = None
    for g in range(n_groups):
        min_cuts = []
        for i, scenario in enumerate(scenarios):
            if not dead[i] and g < len(scenario.groups):
                try:
                    expected[i] += _inject_group(scenario.vc, scenario.groups[g])
                    min_cuts.append(1)
                except Exception as exc:  # noqa: BLE001 — named, not propagated
                    dead[i] = True
                    result.errors.append((i, Violation(
                        "fleet-injection",
                        f"tenant {i} ({scenario.name}): phase group {g} "
                        f"injection failed: {exc!r}",
                    )))
                    expected[i] = int(np.asarray(scenario.vc.state.n_members))
                    min_cuts.append(0)
            else:
                min_cuts.append(0)
        fleet = TenantFleet.from_clusters([s.vc for s in scenarios])
        rounds, cuts, resolved, _sizes = fleet.run_until_membership(
            expected, max_steps=max_steps, max_cuts=max_cuts,
            min_cuts=min_cuts,
        )
        config_ids = fleet.config_ids()
        epochs = fleet.config_epochs()
        members = fleet.membership_sizes()
        result.dispatches += 1
        result.total_rounds += int(rounds.sum())
        result.total_cuts += int(cuts.sum())
        for i, scenario in enumerate(scenarios):
            scenario.vc.state = fleet.tenant_state(i)
            result.phases[i].append(PhaseRecord(
                resolved=bool(resolved[i]),
                cuts=int(cuts[i]),
                config_id=config_ids[i],
                config_epoch=int(epochs[i]),
                members=int(members[i]),
            ))
        _restore_trace_rings(fleet, scenarios)
        alive = np.asarray(fleet.state.alive)

    if soak_rounds is None:
        soak_rounds = (
            STABILITY_SOAK_ROUNDS
            if any(s.stable_subjects for s in scenarios)
            else 0
        )
    if soak_rounds > 0:
        # The stability soak: plain lockstep rounds with NO targets — every
        # tenant steps (a wave would freeze already-at-target tenants, and
        # a frozen detector proves nothing about the stable band).
        fleet = TenantFleet.from_clusters([s.vc for s in scenarios])
        decided_rounds = []
        for _ in range(soak_rounds):
            events = fleet.step()
            decided_rounds.append(events.decided)
        import jax.numpy as jnp

        result.soak_cuts = np.asarray(
            jnp.sum(jnp.stack(decided_rounds).astype(jnp.int32), axis=0)
        )
        result.soak_rounds = soak_rounds
        result.dispatches += soak_rounds
        result.total_rounds += soak_rounds * len(scenarios)
        result.total_cuts += int(result.soak_cuts.sum())
        for i, scenario in enumerate(scenarios):
            scenario.vc.state = fleet.tenant_state(i)
        _restore_trace_rings(fleet, scenarios)
        alive = np.asarray(fleet.state.alive)

    if alive is None:
        alive = np.stack([np.asarray(s.vc.state.alive) for s in scenarios])
    result.final_slots = [
        frozenset(np.nonzero(alive[i])[0].tolist())
        for i in range(len(scenarios))
    ]
    result.wall_ms = (time.perf_counter() - started) * 1000.0
    result.scenarios_per_sec = (
        len(scenarios) / (result.wall_ms / 1000.0) if result.wall_ms > 0 else 0.0
    )
    return result


# ---------------------------------------------------------------------------
# The per-tenant oracle battery
# ---------------------------------------------------------------------------


def check_fleet(result: FleetRunResult) -> List[Violation]:
    """Run every fleet oracle over every tenant's record; each violation
    names its tenant index and scenario. One tenant's defect must never
    leak into another's verdict — the checks below consult ONLY tenant
    ``i``'s record when judging tenant ``i``. Mid-run injection failures
    (already tenant-named) come first; an errored tenant is otherwise
    skipped (its state is whatever the failure left behind — judging it
    would manufacture noise)."""
    violations: List[Violation] = [v for _, v in result.errors]
    errored = {t for t, _ in result.errors}
    for i, scenario in enumerate(result.scenarios):
        if i in errored:
            continue
        label = f"tenant {i} ({scenario.name})"
        records = result.phases[i]
        for g, record in enumerate(records):
            if not record.resolved:
                violations.append(Violation(
                    "fleet-convergence",
                    f"{label}: phase group {g} unresolved after "
                    f"{record.cuts} cut(s)",
                ))
        if result.final_slots and result.final_slots[i] != scenario.expected_slots:
            violations.append(Violation(
                "fleet-membership",
                f"{label}: final membership slots "
                f"{sorted(result.final_slots[i])} != schedule's surviving "
                f"slots {sorted(scenario.expected_slots)}",
            ))
        chain = [r.config_id for r in records if r.cuts > 0]
        if len(set(chain)) != len(chain):
            repeated = sorted({f"{c:#x}" for c in chain if chain.count(c) > 1})
            violations.append(Violation(
                "fleet-chain-consistency",
                f"{label}: configuration id(s) {repeated} re-delivered — "
                f"the chain must only advance",
            ))
        epochs = [r.config_epoch for r in records]
        if any(b < a for a, b in zip(epochs, epochs[1:])):
            violations.append(Violation(
                "fleet-chain-consistency",
                f"{label}: config epochs regressed across phases: {epochs}",
            ))
        if (
            scenario.stable_subjects
            and result.soak_cuts is not None
            and int(result.soak_cuts[i]) > 0
        ):
            violations.append(Violation(
                "fleet-stability",
                f"{label}: committed {int(result.soak_cuts[i])} cut(s) "
                f"during the stability soak although its false-report "
                f"count stayed below H — sub-H reports must delay, not "
                f"trigger, a view change",
            ))
    return violations


def violating_tenants(violations: Sequence[Violation]) -> Dict[int, List[str]]:
    """tenant index -> the oracle names that flagged it (the no-bleed
    assertion's grain). Every fleet violation — including mid-run injection
    failures — carries the ``tenant <i> (<name>): ...`` detail prefix, so
    this parse is total over the battery's output."""
    out: Dict[int, List[str]] = {}
    for violation in violations:
        prefix = violation.detail.split(":", 1)[0]  # "tenant <i> (<name>)"
        idx = int(prefix.split()[1])
        out.setdefault(idx, []).append(violation.oracle)
    return out


# ---------------------------------------------------------------------------
# Per-tenant shrinking + the single-tenant fleet repro
# ---------------------------------------------------------------------------


def shrink_tenant(
    scenarios: Sequence[TenantScenario],
    violations: Sequence[Violation],
    max_runs: int = 32,
    max_steps: int = 64,
) -> Tuple[int, FaultSchedule, List[Violation], int]:
    """Greedily minimize ONLY the violating tenant's schedule: every other
    tenant is replaced by quiescent filler so each probe run keeps the
    original fleet shape (one dispatch, same compiled wave program), and a
    reduction is accepted only if the SAME oracle set still flags the SAME
    tenant index. Returns (tenant index, minimal schedule, the minimal
    run's violations, probe runs spent). With multiple violating tenants
    the lowest index is shrunk (one repro per run keeps the artifact
    readable; rerun for the rest)."""
    from rapid_tpu.sim.fuzz import _shrink_candidates

    by_tenant = violating_tenants(violations)
    if not by_tenant:
        raise ValueError("nothing to shrink: the fleet upheld every oracle")
    t = min(by_tenant)
    target = frozenset(by_tenant[t])
    victim = scenarios[t]

    def probe(schedule: FaultSchedule) -> Tuple[frozenset, List[Violation]]:
        row = [
            compile_schedule(
                schedule, victim.family, victim.seed, victim.knobs,
                victim.delivery_spread,
            )
            if i == t
            else compile_quiescent(
                s.seed, s.knobs, s.delivery_spread,
                n0=s.schedule.n0, n_slots=s.schedule.n_slots,
            )
            for i, s in enumerate(scenarios)
        ]
        got = check_fleet(run_fleet(row, max_steps=max_steps))
        return frozenset(violating_tenants(got).get(t, [])), got

    current = victim.schedule
    current_violations = list(violations)
    runs = 0
    improved = True
    while improved and runs < max_runs:
        improved = False
        for candidate in _shrink_candidates(current):
            if runs >= max_runs:
                break
            try:
                candidate.validate()
            except Exception:  # noqa: BLE001 — invalid reduction, skip
                continue
            if not candidate.engine_compatible:
                continue
            runs += 1
            got_oracles, got = probe(candidate)
            if target <= got_oracles:
                current, current_violations = candidate, got
                improved = True
                break
    return t, current, current_violations, runs


def write_fleet_repro(
    directory,
    schedule: FaultSchedule,
    knobs: Tuple[int, int, int],
    family: str,
    seed: int,
    delivery_spread: int = 0,
    tenant_index: int = 0,
    fleet_size: int = 1,
) -> Path:
    """Collapse a shrunk violating tenant to a single-tenant repro dir in
    the sim schedule format: ``schedule.json`` (the repro itself),
    ``fleet.json`` (the engine-side compile recipe — knobs, family, the
    original tenant index and fleet size for provenance), ``violations.txt``
    re-verified by ONE fresh single-tenant fleet run (tenant index 0 — what
    a replay will see), and ``trace.json`` — the verify run's decoded
    round-trace ring (capacity :data:`REPRO_TRACE_R`), the write-time round
    history ``replay_trace_divergence`` diffs a replay against to name the
    first divergent round. The verify run carries the ring on top of the
    engine (bit-identical either way — the trace differential the HLO gate
    pins), so the artifact costs no extra run. ``chaosrun replay``
    recognizes the marker and replays through the engine fleet path."""
    import json

    from rapid_tpu.models.virtual_cluster import trace_digest
    from rapid_tpu.utils import engine_telemetry

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    single = compile_schedule(
        schedule, family, seed, knobs, delivery_spread,
        telemetry=True, trace=REPRO_TRACE_R,
    )
    verified = check_fleet(run_fleet([single]))
    # telemetry-fetch-ok: repro-write boundary — the verify run is over;
    # one digest fetch freezes the decoded ring into the artifact.
    digest = np.asarray(trace_digest(single.vc.trace_ring))
    summary = engine_telemetry.trace_summary(digest, REPRO_TRACE_R)
    (directory / "trace.json").write_text(
        json.dumps(summary, indent=1, sort_keys=True) + "\n"
    )
    (directory / "schedule.json").write_text(schedule.to_json())
    (directory / "fleet.json").write_text(json.dumps({
        "version": 1,
        "family": family,
        "seed": seed,
        "knobs": list(knobs),
        "delivery_spread": delivery_spread,
        "tenant_index": tenant_index,
        "fleet_size": fleet_size,
    }, indent=1) + "\n")
    (directory / "violations.txt").write_text(
        "".join(f"{v}\n" for v in verified) or "(none)\n"
    )
    return directory


def replay_fleet_repro(directory) -> Tuple[FleetRunResult, List[Violation]]:
    """Re-run a single-tenant fleet repro: compile the schedule with the
    recorded knobs onto one engine tenant, run, and return the violations —
    deterministic, so a written repro reproduces exactly (and a repro that
    STOPS failing is itself news worth printing)."""
    import json

    directory = Path(directory)
    recipe = json.loads((directory / "fleet.json").read_text())
    schedule = FaultSchedule.from_json((directory / "schedule.json").read_text())
    scenario = compile_schedule(
        schedule,
        str(recipe.get("family", "repro")),
        int(recipe.get("seed", schedule.seed)),
        tuple(recipe.get("knobs", DEFAULT_KNOBS)),
        int(recipe.get("delivery_spread", 0)),
    )
    result = run_fleet([scenario])
    return result, check_fleet(result)


def replay_trace_divergence(directory) -> Optional[dict]:
    """Diff a repro dir's written ``trace.json`` (the decoded round-trace
    ring frozen at write time) against a fresh trace-enabled replay of the
    same schedule. Returns None for pre-trace repro dirs (no artifact —
    older repros stay replayable); otherwise a dict carrying both runs'
    recorded-round counts and ``first_divergent_round`` — the global round
    ordinal where the two histories fork, or None when the rings agree
    record for record (the deterministic-repro invariant). This is the
    round-granular instrument behind ``chaosrun replay``: when verdicts
    diverge, it names WHERE, not just that they did."""
    import json

    from rapid_tpu.models.virtual_cluster import trace_digest
    from rapid_tpu.utils import engine_telemetry

    directory = Path(directory)
    path = directory / "trace.json"
    if not path.exists():
        return None
    written = json.loads(path.read_text())
    capacity = int(written.get("capacity", REPRO_TRACE_R))
    recipe = json.loads((directory / "fleet.json").read_text())
    schedule = FaultSchedule.from_json((directory / "schedule.json").read_text())
    scenario = compile_schedule(
        schedule,
        str(recipe.get("family", "repro")),
        int(recipe.get("seed", schedule.seed)),
        tuple(recipe.get("knobs", DEFAULT_KNOBS)),
        int(recipe.get("delivery_spread", 0)),
        telemetry=True, trace=capacity,
    )
    run_fleet([scenario])
    # telemetry-fetch-ok: replay boundary — the run is over; one digest
    # fetch decodes the replayed ring for the divergence diff.
    digest = np.asarray(trace_digest(scenario.vc.trace_ring))
    replayed = engine_telemetry.trace_summary(digest, capacity)
    return {
        "capacity": capacity,
        "written_rounds": int(written["rounds_recorded"]),
        "replayed_rounds": replayed["rounds_recorded"],
        "first_divergent_round": engine_telemetry.first_divergent_round(
            written, replayed
        ),
    }


# ---------------------------------------------------------------------------
# Fleet fuzzing (the chaosrun --fleet / bench `chaos` stage workload)
# ---------------------------------------------------------------------------


def fleet_specs(b: int, base_seed: int = 0) -> List[Tuple[str, int]]:
    """B mixed specs cycling every fleet family with independent seeds —
    the default hostile-heavy workload of ``chaosrun fuzz --fleet`` and the
    bench ``chaos`` stage."""
    return [
        (FLEET_FAMILIES[i % len(FLEET_FAMILIES)], base_seed + 1 + i)
        for i in range(b)
    ]


def fuzz_fleet(
    b: int,
    base_seed: int = 0,
    out_dir=None,
    max_steps: int = 64,
    shrink_failures: bool = True,
) -> dict:
    """One fleet-fuzz round: compile B mixed scenarios, resolve them in
    batched wave dispatches, run the per-tenant battery, and (on violation)
    shrink the violating tenant and write a single-tenant repro. Returns a
    summary dict with per-family scenario and violation tallies plus the
    throughput numbers ``chaosrun`` prints."""
    specs = fleet_specs(b, base_seed)
    scenarios = compile_fleet(specs)
    result = run_fleet(scenarios, max_steps=max_steps)
    violations = check_fleet(result)
    by_tenant = violating_tenants(violations)
    families: Dict[str, int] = {}
    family_violations: Dict[str, int] = {}
    for i, (family, _seed) in enumerate(specs):
        families[family] = families.get(family, 0) + 1
        if i in by_tenant:
            family_violations[family] = family_violations.get(family, 0) + 1
    summary = {
        "tenants": b,
        "dispatches": result.dispatches,
        "total_cuts": result.total_cuts,
        "wall_ms": round(result.wall_ms, 3),
        "scenarios_per_sec": round(result.scenarios_per_sec, 2),
        "families": families,
        "family_violations": family_violations,
        "violations": [str(v) for v in violations],
        "violating_tenants": sorted(by_tenant),
    }
    if violations and shrink_failures:
        t, minimal, _min_violations, runs = shrink_tenant(
            scenarios, violations, max_steps=max_steps
        )
        summary["shrunk_tenant"] = t
        summary["shrunk_events"] = len(minimal.events)
        summary["shrink_runs"] = runs
        if out_dir is not None:
            victim = scenarios[t]
            repro = write_fleet_repro(
                Path(out_dir) / f"tenant{t}", minimal, victim.knobs,
                victim.family, victim.seed, victim.delivery_spread,
                tenant_index=t, fleet_size=b,
            )
            summary["repro"] = str(repro)
    return summary
