"""Batched chaos harness: sim scenario families compiled per tenant, the
oracle battery checked tenant by tenant.

The single-cluster differential oracle (``sim/oracles.replay_through_engine``)
compiles ONE fault schedule's membership phases onto ONE engine; this module
is its fleet twin: B ``(family, seed)`` pairs from ``sim/fuzz.py`` — each an
independent seeded scenario — compile onto B per-tenant clusters with
independent fault inputs, stack into one :class:`~rapid_tpu.tenancy.fleet.TenantFleet`,
and resolve phase group by phase group with ONE fleet-wave dispatch per
group (B scenarios' convergences per dispatch, however differently they
churn). Scenario diversity and throughput in one workload — the shape
``bench.py``'s ``tenant_fleet`` stage measures.

The per-tenant verdicts mirror the sim battery's oracle vocabulary at the
engine grain, every violation naming its tenant index (no cross-tenant
bleed — one tenant's broken chain must never taint its neighbors' verdicts,
pinned in tests/test_tenancy_chaos.py):

- ``fleet-convergence`` — every phase group resolved within its budget;
- ``fleet-membership`` — final alive slots are exactly the schedule's
  surviving slots;
- ``fleet-chain-consistency`` — the tenant's configuration chain only
  advances: per-phase config ids all distinct, epochs strictly increasing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.sim.faults import MEMBER_DELTA, FaultSchedule
from rapid_tpu.sim.fuzz import scenario_family
from rapid_tpu.sim.oracles import Violation
from rapid_tpu.sim.scenario import endpoints_for
from rapid_tpu.tenancy.fleet import TenantFleet

#: Engine-replayable flat families (the hier families run the two-level host
#: protocol; restart-bearing schedules are excluded by engine_compatible).
ENGINE_FAMILIES = (
    "partition_heal",
    "asymmetric_link",
    "crash_during_join",
    "churn_under_loss",
)


@dataclass
class TenantScenario:
    """One tenant's compiled scenario: the schedule, its engine cluster, and
    the host-side expectations the oracles check against."""

    family: str
    seed: int
    schedule: FaultSchedule
    vc: VirtualCluster
    groups: List[List[Tuple[str, Tuple[int, ...]]]]
    expected_slots: frozenset  # surviving slot indices at the end

    @property
    def name(self) -> str:
        return f"{self.family}/{self.seed}"


@dataclass
class PhaseRecord:
    resolved: bool
    cuts: int
    config_id: int
    config_epoch: int
    members: int


@dataclass
class FleetRunResult:
    """What one batched chaos run observed, per tenant — the oracle input."""

    scenarios: List[TenantScenario]
    phases: List[List[PhaseRecord]] = field(default_factory=list)
    final_slots: List[frozenset] = field(default_factory=list)
    dispatches: int = 0
    total_rounds: int = 0
    total_cuts: int = 0


def compile_tenant(
    family: str,
    seed: int,
    knobs: Tuple[int, int, int] = (9, 4, 1),
) -> TenantScenario:
    """Compile one ``(family, seed)`` scenario onto a per-tenant engine
    cluster — the same mapping the differential oracle uses (matched FD /
    delivery semantics: fd_threshold=1 for the host's static detector,
    delivery_spread=0 for same-window delivery), with the tenant's
    ``(h, l, fd_threshold)`` knobs on top."""
    schedule = scenario_family(family, seed)
    if not schedule.engine_compatible:
        raise ValueError(
            f"{family}/{seed}: schedule is not engine-replayable (restarts "
            f"spend engine slots forever)"
        )
    endpoints = endpoints_for(seed, schedule.n_slots)
    h, l, fd_threshold = knobs
    vc = VirtualCluster.from_endpoints(
        endpoints, n_slots=len(endpoints), n_members=schedule.n0,
        k=10, h=h, l=l, fd_threshold=fd_threshold, delivery_spread=0,
    )
    joined = set(range(schedule.n0))
    for event in schedule.events:
        if event.kind in ("join", "restart"):
            joined |= set(event.slots)
    expected = frozenset(joined - schedule.expected_removed_slots())
    return TenantScenario(
        family=family,
        seed=seed,
        schedule=schedule,
        vc=vc,
        groups=schedule.membership_phases(),
        expected_slots=expected,
    )


def compile_fleet(
    specs: Sequence[Tuple[str, int]],
    knobs: Optional[Sequence[Tuple[int, int, int]]] = None,
) -> List[TenantScenario]:
    """One compiled scenario per ``(family, seed)`` spec. All flat families
    share the fuzz geometry (``N0``/``N_SLOTS``), so the B clusters stack
    into one fleet; ``knobs`` optionally varies (h, l, fd_threshold) per
    tenant."""
    if knobs is not None and len(knobs) != len(specs):
        raise ValueError(f"need {len(specs)} knob triples, got {len(knobs)}")
    return [
        compile_tenant(family, seed, knobs[i] if knobs else (9, 4, 1))
        for i, (family, seed) in enumerate(specs)
    ]


def _inject_group(
    vc: VirtualCluster, group: List[Tuple[str, Tuple[int, ...]]]
) -> int:
    """Apply one membership phase group's events to a tenant's cluster
    (the differential oracle's event mapping: a one-way ingress partition
    is detector-identical to a crash). Returns the membership delta."""
    delta = 0
    for kind, slots in group:
        if kind == "join":
            vc.inject_join_wave(list(slots))
        elif kind == "leave":
            vc.initiate_leave(list(slots))
        else:  # crash / partition_oneway
            vc.crash(list(slots))
        delta += MEMBER_DELTA[kind] * len(slots)
    return delta


def run_fleet(
    scenarios: Sequence[TenantScenario],
    max_steps: int = 64,
    max_cuts: int = 8,
) -> FleetRunResult:
    """Resolve every tenant's scenario, phase group by phase group: inject
    group ``g`` into each tenant that still has one, stack, and resolve the
    whole fleet in ONE wave dispatch per group (tenants whose schedule ran
    out of groups idle for free — already at target, zero cuts demanded).
    Per-tenant observations land in a :class:`FleetRunResult` for
    :func:`check_fleet`."""
    scenarios = list(scenarios)
    result = FleetRunResult(scenarios=scenarios)
    result.phases = [[] for _ in scenarios]
    expected = [s.schedule.n0 for s in scenarios]
    n_groups = max((len(s.groups) for s in scenarios), default=0)
    for g in range(n_groups):
        min_cuts = []
        for i, scenario in enumerate(scenarios):
            if g < len(scenario.groups):
                expected[i] += _inject_group(scenario.vc, scenario.groups[g])
                min_cuts.append(1)
            else:
                min_cuts.append(0)
        fleet = TenantFleet.from_clusters([s.vc for s in scenarios])
        rounds, cuts, resolved, _sizes = fleet.run_until_membership(
            expected, max_steps=max_steps, max_cuts=max_cuts,
            min_cuts=min_cuts,
        )
        config_ids = fleet.config_ids()
        epochs = fleet.config_epochs()
        members = fleet.membership_sizes()
        result.dispatches += 1
        result.total_rounds += int(rounds.sum())
        result.total_cuts += int(cuts.sum())
        for i, scenario in enumerate(scenarios):
            scenario.vc.state = fleet.tenant_state(i)
            result.phases[i].append(PhaseRecord(
                resolved=bool(resolved[i]),
                cuts=int(cuts[i]),
                config_id=config_ids[i],
                config_epoch=int(epochs[i]),
                members=int(members[i]),
            ))
        alive = np.asarray(fleet.state.alive)
    if n_groups == 0:
        alive = np.stack([np.asarray(s.vc.state.alive) for s in scenarios])
    result.final_slots = [
        frozenset(np.nonzero(alive[i])[0].tolist())
        for i in range(len(scenarios))
    ]
    return result


# ---------------------------------------------------------------------------
# The per-tenant oracle battery
# ---------------------------------------------------------------------------


def check_fleet(result: FleetRunResult) -> List[Violation]:
    """Run every fleet oracle over every tenant's record; each violation
    names its tenant index and scenario. One tenant's defect must never
    leak into another's verdict — the checks below consult ONLY tenant
    ``i``'s record when judging tenant ``i``."""
    violations: List[Violation] = []
    for i, scenario in enumerate(result.scenarios):
        label = f"tenant {i} ({scenario.name})"
        records = result.phases[i]
        for g, record in enumerate(records):
            if not record.resolved:
                violations.append(Violation(
                    "fleet-convergence",
                    f"{label}: phase group {g} unresolved after "
                    f"{record.cuts} cut(s)",
                ))
        if result.final_slots and result.final_slots[i] != scenario.expected_slots:
            violations.append(Violation(
                "fleet-membership",
                f"{label}: final membership slots "
                f"{sorted(result.final_slots[i])} != schedule's surviving "
                f"slots {sorted(scenario.expected_slots)}",
            ))
        chain = [r.config_id for r in records if r.cuts > 0]
        if len(set(chain)) != len(chain):
            repeated = sorted({f"{c:#x}" for c in chain if chain.count(c) > 1})
            violations.append(Violation(
                "fleet-chain-consistency",
                f"{label}: configuration id(s) {repeated} re-delivered — "
                f"the chain must only advance",
            ))
        epochs = [r.config_epoch for r in records]
        if any(b < a for a, b in zip(epochs, epochs[1:])):
            violations.append(Violation(
                "fleet-chain-consistency",
                f"{label}: config epochs regressed across phases: {epochs}",
            ))
    return violations


def violating_tenants(violations: Sequence[Violation]) -> Dict[int, List[str]]:
    """tenant index -> the oracle names that flagged it (the no-bleed
    assertion's grain)."""
    out: Dict[int, List[str]] = {}
    for violation in violations:
        prefix = violation.detail.split(":", 1)[0]  # "tenant <i> (<name>)"
        idx = int(prefix.split()[1])
        out.setdefault(idx, []).append(violation.oracle)
    return out
