"""Native C library vs pure-Python: bit-identical hashing and ring keys."""

import numpy as np
import pytest

from rapid_tpu.protocol.view import _MASK64, configuration_id_of, ring_key
from rapid_tpu.types import Endpoint, NodeId
from rapid_tpu.utils._native import (
    get_lib,
    native_configuration_id,
    native_ring_keys_batch,
    native_xxh64,
)
from rapid_tpu.utils.xxhash import to_signed64, xxh64

native = pytest.mark.skipif(get_lib() is None, reason="native library unavailable")


@native
def test_native_xxh64_matches_python():
    rng = np.random.default_rng(0)
    for length in [0, 1, 3, 4, 7, 8, 15, 16, 31, 32, 33, 64, 100, 1000]:
        data = bytes(rng.integers(0, 256, size=length, dtype=np.uint8))
        for seed in (0, 1, 7, 2**63, 2**64 - 1):
            assert native_xxh64(data, seed) == xxh64(data, seed), (length, seed)


@native
def test_native_ring_keys_match_python():
    rng = np.random.default_rng(1)
    endpoints = [
        Endpoint(f"host-{i}.example.{rng.integers(0, 100)}", int(rng.integers(1, 65536)))
        for i in range(200)
    ]
    k = 10
    keys = native_ring_keys_batch(
        [ep.hostname.encode() for ep in endpoints], [ep.port for ep in endpoints], k
    )
    assert keys is not None
    for seed in range(k):
        for i, ep in enumerate(endpoints):
            assert int(keys[seed, i]) == ring_key(ep, seed)


@native
def test_native_configuration_id_matches_python():
    rng = np.random.default_rng(2)
    node_ids = sorted(
        NodeId(int(rng.integers(0, 2**63)), int(rng.integers(0, 2**63))) for _ in range(50)
    )
    endpoints = [Endpoint(f"10.2.{i}.{i}", 1000 + i) for i in range(50)]
    # Pure-Python fold computed directly (configuration_id_of itself prefers
    # the native path, which would make this comparison tautological).
    from rapid_tpu.utils.xxhash import xxh64_int

    h = 1
    for nid in node_ids:
        h = (h * 37 + xxh64_int(nid.high)) & _MASK64
        h = (h * 37 + xxh64_int(nid.low)) & _MASK64
    for ep in endpoints:
        h = (h * 37 + xxh64(ep.hostname.encode())) & _MASK64
        h = (h * 37 + xxh64_int(ep.port)) & _MASK64
    expected = to_signed64(h)
    assert expected == configuration_id_of(node_ids, endpoints)
    native_value = native_configuration_id(
        [nid.high for nid in node_ids],
        [nid.low for nid in node_ids],
        [ep.hostname.encode() for ep in endpoints],
        [ep.port for ep in endpoints],
    )
    assert to_signed64(native_value) == expected
