"""Engine<->host scenario-parity oracle.

The same scenario — staggered crashes, then a join wave, then a one-way
partition, N=32 — driven through BOTH stacks:

  host:   full asyncio `Cluster` instances over the in-process transport,
          static failure detectors, ManualClock (the reference architecture,
          ClusterTest.java:229-337 scenario family), and
  engine: the fused single-program `VirtualCluster`, built via
          `from_endpoints` so its ring topology is the host view's
          bit-for-bit, with matched detection/batching semantics,

asserting the two produce the IDENTICAL cut sequence (each cut as a set of
(endpoint, UP/DOWN)) and the identical final membership. Kernel-level
equivalence tests pin each device op against a host function; this is the
missing cross-STACK oracle at scenario granularity: grouping of staggered
faults into cuts, join-gatekeeper semantics, re-detection of a fault whose
alerts straddle a configuration change, and eviction of a one-way-partitioned
node must all agree end to end.

Timing map (the "matched FD/batching parameters"): one engine round models
one failure-detector interval (1000 ms sim). The host's StaticFailureDetector
notifies on the first tick after blacklisting == engine `fd_threshold=1`;
`delivery_spread=0` == the in-process transport's same-window delivery.
Faults are injected between convergences in both stacks (sub-interval
injection phase is not representable in the round-granular engine — a
documented semantic choice of the model, DESIGN.md).
"""

import asyncio
import functools
import random

import numpy as np

from rapid_tpu.messaging.inprocess import InProcessNetwork
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.settings import Settings
from rapid_tpu.types import EdgeStatus, Endpoint
from rapid_tpu.utils.clock import ManualClock

N0 = 32  # initial members
JOINERS = 4
ALL = N0 + JOINERS
ENDPOINTS = [Endpoint(f"10.9.{i // 250}.{i % 250}", 7000 + i) for i in range(ALL)]

# Scenario cast (slot indices == ENDPOINTS indices).
CRASH_WAVE_1 = [5, 11]  # staggered crash, first group
CRASH_WAVE_2 = [23]  # second group, one detection interval later
JOIN_SLOTS = list(range(N0, ALL))
PARTITIONED = 17  # one-way (ingress) partition victim


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=120)

        asyncio.run(with_timeout())

    return wrapper


async def _drain(loop_yields=60):
    for _ in range(loop_yields):
        await asyncio.sleep(0)


async def _advance(clock: ManualClock, total_ms: float, step_ms: float = 50):
    advanced = 0.0
    while advanced < total_ms:
        clock.advance_ms(step_ms)
        advanced += step_ms
        await _drain()


async def _run_host_scenario():
    """Returns (cut_sequence, final_membership) from the asyncio stack.

    cut_sequence: list of frozensets of (Endpoint, EdgeStatus).
    """
    settings = Settings()  # reference-default: 1 s FD interval, 100 ms batch
    network = InProcessNetwork()
    clock = ManualClock()
    fd = StaticFailureDetectorFactory()

    clusters = {}
    clusters[0] = await Cluster.start(
        ENDPOINTS[0], settings=settings, network=network, fd_factory=fd,
        clock=clock, rng=random.Random(0),
    )
    for i in range(1, N0):
        task = asyncio.ensure_future(
            Cluster.join(ENDPOINTS[0], ENDPOINTS[i], settings=settings,
                         network=network, fd_factory=fd, clock=clock,
                         rng=random.Random(i))
        )
        while not task.done():
            await _advance(clock, 200)
        clusters[i] = task.result()
    assert all(c.membership_size == N0 for c in clusters.values())

    # Observe the cut sequence from node 0 (never faulted in this scenario).
    cuts = []
    clusters[0].register_subscription(
        ClusterEvents.VIEW_CHANGE,
        lambda change: cuts.append(
            frozenset((sc.endpoint, sc.status) for sc in change.status_changes)
        ),
    )

    async def converge_members(expected: int, budget_ms=8_000):
        for _ in range(int(budget_ms // 400)):
            await _advance(clock, 400)
            live = [c for i, c in clusters.items() if i in live_ids]
            if all(c.membership_size == expected for c in live):
                return
        raise AssertionError(
            f"host did not converge to {expected}: "
            f"{[clusters[i].membership_size for i in sorted(live_ids)]}"
        )

    live_ids = set(range(N0))

    # Phase A: staggered crashes — wave 2 lands one detection interval after
    # wave 1 (its alerts straddle wave 1's configuration change and must be
    # re-detected in the new configuration).
    for s in CRASH_WAVE_1:
        network.blackholed.add(ENDPOINTS[s])
    fd.add_failed_nodes([ENDPOINTS[s] for s in CRASH_WAVE_1])
    live_ids -= set(CRASH_WAVE_1)
    await _advance(clock, 1_050)  # one FD interval: wave 1 detected
    for s in CRASH_WAVE_2:
        network.blackholed.add(ENDPOINTS[s])
    fd.add_failed_nodes([ENDPOINTS[s] for s in CRASH_WAVE_2])
    live_ids -= set(CRASH_WAVE_2)
    await converge_members(N0 - 3)

    # Phase B: a 4-node join wave through one seed.
    join_tasks = [
        asyncio.ensure_future(
            Cluster.join(ENDPOINTS[0], ENDPOINTS[s], settings=settings,
                         network=network, fd_factory=fd, clock=clock,
                         rng=random.Random(s))
        )
        for s in JOIN_SLOTS
    ]
    while not all(t.done() for t in join_tasks):
        await _advance(clock, 200)
    for s, t in zip(JOIN_SLOTS, join_tasks):
        clusters[s] = t.result()
    live_ids |= set(JOIN_SLOTS)
    await converge_members(N0 - 3 + JOINERS)

    # Phase C: one-way partition — everything INTO the victim drops (it can
    # still send), its observers stop getting probe responses (modeled by the
    # static FD blacklist, as in the reference's asymmetric-failure tests).
    for i in range(ALL):
        if i != PARTITIONED:
            network.blackholed_links.add((ENDPOINTS[i], ENDPOINTS[PARTITIONED]))
    fd.add_failed_nodes([ENDPOINTS[PARTITIONED]])
    live_ids -= {PARTITIONED}
    await converge_members(N0 - 3 + JOINERS - 1)

    final = set(clusters[0].membership)
    assert len({tuple(clusters[i].membership) for i in live_ids}) == 1
    await asyncio.gather(
        *(c.shutdown() for c in clusters.values()), return_exceptions=True
    )
    return cuts, final


def _run_engine_scenario():
    """The same scenario through the fused engine; same return shape."""
    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        engine_step_nodonate,
    )

    vc = VirtualCluster.from_endpoints(
        ENDPOINTS, n_slots=ALL, n_members=N0, k=10, h=9, l=4,
        fd_threshold=1,  # static FD notifies on the first tick
        delivery_spread=0,  # in-process transport: same-window delivery
    )
    cuts = []

    def run_to_decision(max_steps=24):
        nonlocal_state = {"state": vc.state}
        for _ in range(max_steps):
            before = nonlocal_state["state"]
            was_alive = np.asarray(before.alive)
            state, events = engine_step_nodonate(vc.cfg, before, vc.faults)
            nonlocal_state["state"] = state
            if bool(events.decided):
                mask = np.asarray(events.winner_mask)
                cut = frozenset(
                    (
                        ENDPOINTS[s],
                        EdgeStatus.DOWN if was_alive[s] else EdgeStatus.UP,
                    )
                    for s in np.nonzero(mask)[0].tolist()
                )
                cuts.append(cut)
                vc.state = state
                return
        raise AssertionError("engine did not decide")

    # Phase A: wave 1, then wave 2 one round (= one FD interval) later —
    # wave 2's detection straddles wave 1's view change, as on the host.
    vc.crash(CRASH_WAVE_1)
    run_to_decision()
    vc.crash(CRASH_WAVE_2)
    run_to_decision()

    # Phase B: the join wave.
    vc.inject_join_wave(JOIN_SLOTS)
    run_to_decision()

    # Phase C: the one-way partition. In the round-granular engine a node
    # whose ingress is fully cut is detector-indistinguishable from a
    # crash-stop: its observers' probes go unanswered and it casts no vote
    # (it hears no proposals). `crash` models exactly that pair.
    vc.crash([PARTITIONED])
    run_to_decision()

    alive = np.asarray(vc.state.alive)
    final = {ENDPOINTS[s] for s in np.nonzero(alive)[0].tolist()}
    return cuts, final


@async_test
async def test_host_and_engine_agree_on_cut_sequence_and_membership():
    host_cuts, host_final = await _run_host_scenario()
    engine_cuts, engine_final = _run_engine_scenario()

    expected_final = {
        ENDPOINTS[i]
        for i in range(ALL)
        if i not in CRASH_WAVE_1 + CRASH_WAVE_2 + [PARTITIONED]
    }
    assert host_final == expected_final
    assert engine_final == expected_final

    # The oracle: identical cut GROUPING and contents, in order.
    assert [sorted(map(repr, c)) for c in host_cuts] == [
        sorted(map(repr, c)) for c in engine_cuts
    ], f"cut sequences diverged:\n host={host_cuts}\n engine={engine_cuts}"
    assert len(host_cuts) == 4  # wave1, wave2, join wave, partition
