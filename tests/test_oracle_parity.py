"""Engine<->host scenario-parity oracle.

The same scenario — staggered crashes, then a join wave, then a one-way
partition, N=32 — driven through BOTH stacks:

  host:   full asyncio `Cluster` instances over the in-process transport,
          static failure detectors, ManualClock (the reference architecture,
          ClusterTest.java:229-337 scenario family), and
  engine: the fused single-program `VirtualCluster`, built via
          `from_endpoints` so its ring topology is the host view's
          bit-for-bit, with matched detection/batching semantics,

asserting the two produce the IDENTICAL cut sequence (each cut as a set of
(endpoint, UP/DOWN)) and the identical final membership. Kernel-level
equivalence tests pin each device op against a host function; this is the
missing cross-STACK oracle at scenario granularity: grouping of staggered
faults into cuts, join-gatekeeper semantics, re-detection of a fault whose
alerts straddle a configuration change, and eviction of a one-way-partitioned
node must all agree end to end.

Timing map (the "matched FD/batching parameters"): one engine round models
one failure-detector interval (1000 ms sim). The host's StaticFailureDetector
notifies on the first tick after blacklisting == engine `fd_threshold=1`;
`delivery_spread=0` == the in-process transport's same-window delivery.
Faults are injected between convergences in both stacks (sub-interval
injection phase is not representable in the round-granular engine — a
documented semantic choice of the model, DESIGN.md).
"""

import asyncio
import functools
import random

import numpy as np
import pytest

from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.sim.faults import FaultEvent, FaultSchedule
from rapid_tpu.sim.oracles import check_all
from rapid_tpu.sim.scenario import ScenarioRunner, SimHarness
from rapid_tpu.types import EdgeStatus, Endpoint
from rapid_tpu.utils.clock import ManualClock

N0 = 32  # initial members
JOINERS = 4
ALL = N0 + JOINERS
ENDPOINTS = [Endpoint(f"10.9.{i // 250}.{i % 250}", 7000 + i) for i in range(ALL)]

# Scenario cast (slot indices == ENDPOINTS indices).
CRASH_WAVE_1 = [5, 11]  # staggered crash, first group
CRASH_WAVE_2 = [23]  # second group, one detection interval later
JOIN_SLOTS = list(range(N0, ALL))
PARTITIONED = 17  # one-way (ingress) partition victim


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=120)

        asyncio.run(with_timeout())

    return wrapper


async def _drain(loop_yields=60):
    for _ in range(loop_yields):
        await asyncio.sleep(0)


async def _advance(clock: ManualClock, total_ms: float, step_ms: float = 50):
    advanced = 0.0
    while advanced < total_ms:
        clock.advance_ms(step_ms)
        advanced += step_ms
        await _drain()


async def _run_host_scenario():
    """Returns (cut_sequence, final_membership) from the asyncio stack.

    cut_sequence: list of frozensets of (Endpoint, EdgeStatus).
    """
    h = SimHarness(ENDPOINTS)
    await h.bootstrap(N0)
    converge_members = h.converge_members

    # Phase A: staggered crashes — wave 2 lands one detection interval after
    # wave 1 (its alerts straddle wave 1's configuration change and must be
    # re-detected in the new configuration). This sub-interval stagger is
    # what the generic phase runner deliberately cannot express.
    h.crash(CRASH_WAVE_1)
    await _advance(h.clock, 1_050)  # one FD interval: wave 1 detected
    h.crash(CRASH_WAVE_2)
    await converge_members(N0 - 3)

    # Phase B: a 4-node join wave through one seed.
    await h.join_wave(JOIN_SLOTS)
    await converge_members(N0 - 3 + JOINERS)

    # Phase C: one-way partition — the victim's observers stop getting probe
    # responses (modeled by the static FD blacklist, as in the reference's
    # asymmetric-failure tests).
    h.partition_one_way(PARTITIONED)
    await converge_members(N0 - 3 + JOINERS - 1)

    final = await h.shutdown()
    return h.cuts, final


def _run_engine_scenario():
    """The same scenario through the fused engine; same return shape."""
    from rapid_tpu.models.virtual_cluster import (
        VirtualCluster,
        engine_step_nodonate,
    )

    vc = VirtualCluster.from_endpoints(
        ENDPOINTS, n_slots=ALL, n_members=N0, k=10, h=9, l=4,
        fd_threshold=1,  # static FD notifies on the first tick
        delivery_spread=0,  # in-process transport: same-window delivery
    )
    cuts = []

    def run_to_decision(max_steps=24):
        nonlocal_state = {"state": vc.state}
        for _ in range(max_steps):
            before = nonlocal_state["state"]
            was_alive = np.asarray(before.alive)
            state, events = engine_step_nodonate(vc.cfg, before, vc.faults)
            nonlocal_state["state"] = state
            if bool(events.decided):
                mask = np.asarray(events.winner_mask)
                cut = frozenset(
                    (
                        ENDPOINTS[s],
                        EdgeStatus.DOWN if was_alive[s] else EdgeStatus.UP,
                    )
                    for s in np.nonzero(mask)[0].tolist()
                )
                cuts.append(cut)
                vc.state = state
                return
        raise AssertionError("engine did not decide")

    # Phase A: wave 1, then wave 2 one round (= one FD interval) later —
    # wave 2's detection straddles wave 1's view change, as on the host.
    vc.crash(CRASH_WAVE_1)
    run_to_decision()
    vc.crash(CRASH_WAVE_2)
    run_to_decision()

    # Phase B: the join wave.
    vc.inject_join_wave(JOIN_SLOTS)
    run_to_decision()

    # Phase C: the one-way partition. In the round-granular engine a node
    # whose ingress is fully cut is detector-indistinguishable from a
    # crash-stop: its observers' probes go unanswered and it casts no vote
    # (it hears no proposals). `crash` models exactly that pair.
    vc.crash([PARTITIONED])
    run_to_decision()

    alive = np.asarray(vc.state.alive)
    final = {ENDPOINTS[s] for s in np.nonzero(alive)[0].tolist()}
    return cuts, final


def _random_phase_schedule(seed: int, n0: int, n_slots: int) -> FaultSchedule:
    """A random convergence-serialized phase schedule over the slot pool —
    crash waves, join waves, one-way partitions, graceful leaves — sized to
    keep the cluster healthy (node 0, the observer, never faulted;
    membership never below 2/3 of peak), expressed as a sim-subsystem
    :class:`FaultSchedule` so the runner and oracles do the rest."""
    rng = random.Random(seed)
    live = set(range(n0))
    peak = n0
    pending_pool = list(range(n0, n_slots))
    events = []
    for _ in range(rng.randint(3, 5)):
        floor = (peak * 2) // 3  # healthy-cluster invariant, vs PEAK size
        removable = len(live) - floor
        kind = rng.choice(["crash", "join", "partition_oneway", "leave"])
        if kind == "join" and pending_pool:
            size = rng.randint(1, min(4, len(pending_pool)))
            slots = [pending_pool.pop(0) for _ in range(size)]
            events.append(FaultEvent("join", tuple(slots)))
            live |= set(slots)
            peak = max(peak, len(live))
        elif kind == "crash" and removable >= 1:
            size = rng.randint(1, min(4, removable))
            slots = rng.sample(sorted(live - {0}), size)
            events.append(FaultEvent("crash", tuple(sorted(slots))))
            live -= set(slots)
        elif kind in ("partition_oneway", "leave") and removable >= 1:
            victim = rng.choice(sorted(live - {0}))
            events.append(FaultEvent(kind, (victim,)))
            live -= {victim}
        # A fault phase drawn at the floor is skipped, not shrunk past it.
    schedule = FaultSchedule(
        n0=n0, n_slots=n_slots, seed=seed, events=events,
        name=f"oracle-parity/{seed}",
    )
    schedule.validate()
    return schedule


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_random_schedules_agree_across_stacks(seed):
    # Differential property: ANY convergence-serialized schedule of crash
    # waves, join waves, one-way partitions, and leaves must uphold every
    # invariant oracle — including the host<->engine differential, whose
    # refinement relation (host cuts compose, in order and without crossing
    # a boundary, into the engine's round-granular cuts — the
    # almost-everywhere-agreement batching artifact this module's timing map
    # documents) now lives in rapid_tpu/sim/oracles.py as a reusable
    # checker. This is the fixed-scenario oracle below, generalized over
    # randomized schedules and migrated onto the chaos subsystem.
    schedule = _random_phase_schedule(seed, n0=24, n_slots=32)
    result = ScenarioRunner(schedule).run()
    violations = check_all(result)
    assert not violations, "\n".join(str(v) for v in violations)
    assert len(result.cuts) >= len(schedule.membership_phases())


async def _run_host_fallback_scenario(endpoints, n0, victim_slot, n_blocked):
    """Fallback-forcing host run: ingress-block ``n_blocked`` of the victim's
    observers so fewer than the fast quorum can vote, forcing the decision
    through classic Paxos. Blocked nodes are chosen among the victim's
    OBSERVERS deliberately: each then holds local evidence (its own ring
    report, stuck below L) that a cut is unresolved — the suspicion signal
    that drives the config-sync pull by which they re-join the new
    configuration THROUGH the partition (requests out, responses back).
    Returns (cuts, final_membership, blocked_slots, classic_rounds_started,
    one_step_failed_events)."""
    h = SimHarness(endpoints)
    await h.bootstrap(n0)
    victim = endpoints[victim_slot]
    view = h.clusters[0].service.view
    blocked = []
    for obs in view.observers_of(victim):
        if obs not in (endpoints[0], victim) and obs not in blocked:
            blocked.append(obs)
        if len(blocked) == n_blocked:
            break
    assert len(blocked) == n_blocked
    one_step_failed = []
    for cluster in h.clusters.values():
        cluster.register_subscription(
            ClusterEvents.VIEW_CHANGE_ONE_STEP_FAILED, one_step_failed.append
        )

    for b in blocked:
        for other in endpoints[:n0]:
            if other != b:
                h.network.blackholed_links.add((other, b))
    h.crash([victim_slot])
    # Generous budget: the classic fallback fires on the jittered timer and
    # blocked nodes then need config-sync pulls to adopt the decision.
    await h.converge_members(n0 - 1, budget_ms=60_000)

    # Heal and confirm the agreement is stable (nothing pending re-fires).
    h.network.blackholed_links.clear()
    await h.converge_members(n0 - 1)

    classic_started = sum(
        h.clusters[i].service.metrics.counters["classic_rounds_started"]
        for i in h.live_ids
    )
    blocked_slots = [endpoints.index(b) for b in blocked]
    final = await h.shutdown()
    return h.cuts, final, blocked_slots, classic_started, one_step_failed


def _run_engine_fallback_scenario(endpoints, n0, victim_slot, blocked_slots):
    """The same fallback-forcing schedule through the engine: each blocked
    node gets a dedicated cohort whose ingress is rx-blocked (own alerts
    still arrive, matching the host's open self-delivery), so its detector
    never crosses H and it never votes — the fast round sits below quorum
    and the decision must come from the classic attempt
    (models/virtual_cluster.py classic_attempt ≙ host paxos.py).
    Returns (cut, final_membership, fast_decided)."""
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    c = len(blocked_slots) + 1
    vc = VirtualCluster.from_endpoints(
        endpoints[:n0], n_slots=n0, n_members=n0, k=10, h=9, l=4,
        cohorts=c, fd_threshold=1, delivery_spread=0,
        fallback_rounds=4, concurrent_coordinators=2,
    )
    cohort_of = np.zeros(n0, dtype=np.int32)
    for idx, s in enumerate(blocked_slots):
        cohort_of[s] = idx + 1
    vc.assign_cohorts(cohort_of)
    rx = np.zeros((c, n0), dtype=bool)
    for idx, s in enumerate(blocked_slots):
        rx[idx + 1, :] = True
        rx[idx + 1, s] = False  # own alerts still arrive (host parity)
    vc.set_rx_block(rx)

    vc.crash([victim_slot])
    was_alive = np.asarray(vc.state.alive)
    for _ in range(64):
        events = vc.step()
        if bool(events.decided):
            fast = bool(events.fast_decided)
            mask = np.asarray(events.winner_mask)
            break
    else:
        raise AssertionError("engine did not decide under the vote partition")
    cut = frozenset(
        (endpoints[s], EdgeStatus.DOWN if was_alive[s] else EdgeStatus.UP)
        for s in np.nonzero(mask)[0].tolist()
    )
    # Heal and step: stale alerts from previously-blocked cohorts re-open
    # (set_rx_block re-stamps fired edges) and must not flip membership.
    vc.set_rx_block(np.zeros((c, n0), dtype=bool))
    for _ in range(8):
        events = vc.step()
        assert not bool(events.decided), "heal must not re-fire a decision"
    alive = np.asarray(vc.state.alive)
    final = {endpoints[s] for s in np.nonzero(alive)[0].tolist()}
    return cut, final, fast


@pytest.mark.parametrize("seed", [11, 12])
@async_test
async def test_forced_classic_fallback_agrees_across_stacks(seed):
    # VERDICT item: the cross-stack differential never forced a classic
    # fallback. Here the fast round is partitioned below quorum in BOTH
    # stacks — n_blocked observers cannot hear alerts, so only
    # n0-1-n_blocked nodes vote, under the N - floor((N-1)/4) fast quorum —
    # and both stacks must (a) decide via the classic path, (b) decide the
    # IDENTICAL value, (c) reach the identical final membership. Rank
    # identity is deliberately not compared: host ranks are (round,
    # endpoint-hash) while engine ranks are (round, slot) — the portable
    # contract is path + value + membership. Reference bar: the
    # drop-the-fast-round recovery tests, PaxosTests.java:72-191,424-446.
    n0 = 16
    rng = random.Random(seed)
    victim_slot = rng.randrange(1, n0)
    n_blocked = 4  # floor((N-1)/4) < 4 voters lost <= N/2 - majority margin
    endpoints = [Endpoint(f"10.7.{seed}.{i}", 7400 + i) for i in range(n0)]

    host_cuts, host_final, blocked_slots, classic_started, one_step_failed = (
        await _run_host_fallback_scenario(endpoints, n0, victim_slot, n_blocked)
    )
    engine_cut, engine_final, engine_fast = _run_engine_fallback_scenario(
        endpoints, n0, victim_slot, blocked_slots
    )

    expected_cut = frozenset({(endpoints[victim_slot], EdgeStatus.DOWN)})
    assert host_cuts == [expected_cut]
    assert engine_cut == expected_cut
    assert host_final == engine_final == set(endpoints) - {endpoints[victim_slot]}
    # Both stacks took the slow path.
    assert not engine_fast, "engine must have decided via the classic attempt"
    assert classic_started >= 1, "host must have engaged the classic fallback"
    assert one_step_failed, "VIEW_CHANGE_ONE_STEP_FAILED must fire somewhere"


@async_test
async def test_host_and_engine_agree_on_cut_sequence_and_membership():
    host_cuts, host_final = await _run_host_scenario()
    engine_cuts, engine_final = _run_engine_scenario()

    expected_final = {
        ENDPOINTS[i]
        for i in range(ALL)
        if i not in CRASH_WAVE_1 + CRASH_WAVE_2 + [PARTITIONED]
    }
    assert host_final == expected_final
    assert engine_final == expected_final

    # The oracle: identical cut GROUPING and contents, in order.
    assert [sorted(map(repr, c)) for c in host_cuts] == [
        sorted(map(repr, c)) for c in engine_cuts
    ], f"cut sequences diverged:\n host={host_cuts}\n engine={engine_cuts}"
    assert len(host_cuts) == 4  # wave1, wave2, join wave, partition
