"""Device cut-detection kernel vs the sequential MultiNodeCutDetector oracle.

The device kernel uses end-of-batch semantics: a cut is released iff after the
whole batch (plus implicit invalidation) at least one subject is past H and
none is in [L, H). The sequential oracle is order-sensitive mid-batch, so the
harness feeds it alerts with flux-enders first — the order under which its
union-of-proposals coincides with end-of-batch semantics (see
rapid_tpu/ops/cut_detection.py docstring).
"""

import numpy as np
import pytest

from rapid_tpu.ops.cut_detection import (
    CutState,
    alerts_to_report_matrix,
    process_alert_batch,
)
from rapid_tpu.ops.rings import endpoint_ring_keys, predecessor_of_keys, ring_topology
from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint, NodeId

K, H, L = 10, 8, 3


def make_world(n_members, n_joiners, seed):
    rng = np.random.default_rng(seed)
    total = n_members + n_joiners
    ports = rng.choice(40000, size=total, replace=False) + 1
    endpoints = [Endpoint(f"10.1.{i % 256}.{i // 256}", int(p)) for i, p in enumerate(ports)]
    members, joiners = endpoints[:n_members], endpoints[n_members:]
    view = MembershipView(K)
    for i, ep in enumerate(members):
        view.ring_add(ep, NodeId(0, i))
    return view, members, joiners, rng


def build_inval_obs(view, members, joiners):
    """[K, n_slots] invalidation-observer table: ring successors for members,
    alive-predecessors (expected observers) for joiner slots."""
    n = len(members)
    key_hi, key_lo = endpoint_ring_keys(members, K)
    alive = np.ones(n, dtype=bool)
    topo = ring_topology(key_hi, key_lo, alive)
    obs = np.asarray(topo.obs_idx)  # [K, n]
    if joiners:
        qhi, qlo = endpoint_ring_keys(joiners, K)
        pred = np.asarray(predecessor_of_keys(key_hi, key_lo, alive, qhi, qlo))  # [K, j]
        obs = np.concatenate([obs, pred], axis=1)
    return obs


def run_device(view, members, joiners, alerts):
    slots = members + joiners
    slot_of = {ep: i for i, ep in enumerate(slots)}
    n = len(slots)
    dst_idx, rings = [], []
    has_down = False
    for a in alerts:
        for r in a.ring_numbers:
            dst_idx.append(slot_of[a.edge_dst])
            rings.append(r)
        has_down = has_down or a.edge_status == EdgeStatus.DOWN
    new_reports = alerts_to_report_matrix(n, K, np.array(dst_idx), np.array(rings))
    inval_obs = build_inval_obs(view, members, joiners)
    subject_mask = np.ones(n, dtype=bool)
    result = process_alert_batch(
        CutState.create(n, K),
        new_reports,
        np.asarray(has_down),
        inval_obs,
        subject_mask,
        H,
        L,
    )
    mask = np.asarray(result.proposal_mask)
    return bool(result.propose), {slots[i] for i in range(n) if mask[i]}


def run_oracle(view, alerts):
    """Union-of-proposals per batch + invalidation, as the membership service
    consumes it (MembershipService.java:300-354)."""
    detector = MultiNodeCutDetector(K, H, L)
    proposal = set()
    for a in alerts:
        proposal.update(detector.aggregate(a))
    proposal.update(detector.invalidate_failing_edges(view))
    return bool(proposal), proposal


def order_flux_enders_first(alerts):
    """Sort so subjects whose final tally lands in [L, H) come first."""
    by_dst = {}
    for a in alerts:
        by_dst.setdefault(a.edge_dst, []).append(a)
    flux, other = [], []
    for dst, msgs in by_dst.items():
        rings = {r for m in msgs for r in m.ring_numbers}
        (flux if L <= len(rings) < H else other).append((dst, msgs))
    return [m for _, msgs in flux + other for m in msgs]


def make_alerts(view, subjects_with_counts, status=EdgeStatus.DOWN):
    alerts = []
    for subject, count in subjects_with_counts:
        observers = (
            view.observers_of(subject)
            if view.is_host_present(subject)
            else view.expected_observers_of(subject)
        )
        for ring_number in range(count):
            alerts.append(
                AlertMessage(
                    edge_src=observers[ring_number],
                    edge_dst=subject,
                    edge_status=status,
                    configuration_id=0,
                    ring_numbers=(ring_number,),
                )
            )
    return alerts


@pytest.mark.parametrize("seed", range(8))
def test_randomized_equivalence_members_only(seed):
    view, members, joiners, rng = make_world(40, 0, seed)
    n_subjects = rng.integers(1, 8)
    picks = rng.choice(len(members), size=n_subjects, replace=False)
    subjects = [(members[i], int(rng.integers(1, K + 1))) for i in picks]
    alerts = order_flux_enders_first(make_alerts(view, subjects))

    dev_propose, dev_set = run_device(view, members, joiners, alerts)
    ora_propose, ora_set = run_oracle(view, alerts)
    assert dev_propose == ora_propose
    if dev_propose:
        assert dev_set == ora_set


@pytest.mark.parametrize("seed", range(8))
def test_randomized_equivalence_with_joiners(seed):
    view, members, joiners, rng = make_world(30, 5, 100 + seed)
    picks = rng.choice(len(members), size=3, replace=False)
    subjects = [(members[i], int(rng.integers(1, K + 1))) for i in picks]
    join_subjects = [(j, int(rng.integers(1, K + 1))) for j in joiners[:2]]
    alerts = make_alerts(view, subjects, EdgeStatus.DOWN) + make_alerts(
        view, join_subjects, EdgeStatus.UP
    )
    alerts = order_flux_enders_first(alerts)

    dev_propose, dev_set = run_device(view, members, joiners, alerts)
    ora_propose, ora_set = run_oracle(view, alerts)
    assert dev_propose == ora_propose
    if dev_propose:
        assert dev_set == ora_set


def test_link_invalidation_equivalence():
    # The reference's cutDetectionTestLinkInvalidation scenario on device:
    # dst stuck at H-1 with its remaining observers themselves past H.
    view, members, joiners, _ = make_world(30, 0, 42)
    dst = members[0]
    observers = view.observers_of(dst)
    alerts = []
    for i in range(H - 1):
        alerts.append(
            AlertMessage(observers[i], dst, EdgeStatus.DOWN, 0, (i,))
        )
    failed = set()
    for i in range(H - 1, K):
        failed.add(observers[i])
        oo = view.observers_of(observers[i])
        for j in range(K):
            alerts.append(AlertMessage(oo[j], observers[i], EdgeStatus.DOWN, 0, (j,)))

    dev_propose, dev_set = run_device(view, members, joiners, alerts)
    ora_propose, ora_set = run_oracle(view, alerts)
    assert dev_propose and ora_propose
    assert dev_set == ora_set == failed | {dst}


def test_up_alerts_never_trigger_invalidation():
    view, members, joiners, _ = make_world(25, 3, 5)
    # Joiner stuck in flux; no DOWN alerts anywhere: invalidation must not run.
    alerts = make_alerts(view, [(joiners[0], H - 1)], EdgeStatus.UP)
    dev_propose, _ = run_device(view, members, joiners, alerts)
    ora_propose, _ = run_oracle(view, alerts)
    assert not dev_propose and not ora_propose


def test_released_subjects_do_not_repropose():
    # Reference clears its proposal set on release
    # (MultiNodeCutDetector.java:120-121): a cut released in batch 1 must not
    # reappear in batch 2's proposal.
    view, members, joiners, _ = make_world(20, 0, 8)
    n = len(members)
    inval_obs = build_inval_obs(view, members, [])
    subject_mask = np.ones(n, dtype=bool)
    slot_of = {ep: i for i, ep in enumerate(members)}
    a, b = members[2], members[9]

    m1 = alerts_to_report_matrix(n, K, np.array([slot_of[a]] * H), np.arange(H))
    r1 = process_alert_batch(
        CutState.create(n, K), m1, np.asarray(True), inval_obs, subject_mask, H, L
    )
    assert bool(r1.propose)
    assert {i for i in range(n) if np.asarray(r1.proposal_mask)[i]} == {slot_of[a]}

    m2 = alerts_to_report_matrix(n, K, np.array([slot_of[b]] * H), np.arange(H))
    r2 = process_alert_batch(r1.state, m2, np.asarray(True), inval_obs, subject_mask, H, L)
    assert bool(r2.propose)
    assert {i for i in range(n) if np.asarray(r2.proposal_mask)[i]} == {slot_of[b]}


def test_state_accumulates_across_batches():
    view, members, joiners, _ = make_world(20, 0, 6)
    slots = members
    n = len(slots)
    subject = members[3]
    observers = view.observers_of(subject)
    inval_obs = build_inval_obs(view, members, [])
    subject_mask = np.ones(n, dtype=bool)
    state = CutState.create(n, K)
    slot_of = {ep: i for i, ep in enumerate(slots)}

    # H-1 reports in batch one: no proposal.
    m1 = alerts_to_report_matrix(
        n, K, np.array([slot_of[subject]] * (H - 1)), np.arange(H - 1)
    )
    r1 = process_alert_batch(state, m1, np.asarray(True), inval_obs, subject_mask, H, L)
    assert not bool(r1.propose)
    # The H-th report arrives in batch two: proposal fires from accumulated state.
    m2 = alerts_to_report_matrix(n, K, np.array([slot_of[subject]]), np.array([H - 1]))
    r2 = process_alert_batch(r1.state, m2, np.asarray(True), inval_obs, subject_mask, H, L)
    assert bool(r2.propose)
    assert np.asarray(r2.proposal_mask)[slot_of[subject]]
