"""The batched chaos harness + per-tenant oracle battery + fleet autotune.

The no-cross-tenant-bleed satellite (ISSUE 10): in a fleet where exactly
one tenant's record violates an oracle, ``check_fleet`` must report THAT
tenant's index and leave every other tenant's verdict clean — one broken
chain can never taint its neighbors.
"""

import dataclasses
import json

import pytest

from rapid_tpu.tenancy import chaos
from rapid_tpu.tenancy.autotune import sweep_khl

SPECS = [
    ("partition_heal", 5),
    ("asymmetric_link", 6),
    ("crash_during_join", 7),
    ("churn_under_loss", 8),
]


@pytest.fixture(scope="module")
def fleet_result():
    return chaos.run_fleet(chaos.compile_fleet(SPECS))


def test_genuine_fleet_run_upholds_every_oracle(fleet_result):
    assert chaos.check_fleet(fleet_result) == []
    # Every phase group of every tenant resolved in ONE wave dispatch per
    # group — B scenarios' convergences per dispatch is the whole point.
    assert fleet_result.dispatches == max(
        len(s.groups) for s in fleet_result.scenarios
    )
    assert fleet_result.total_cuts >= len(SPECS)
    for i, scenario in enumerate(fleet_result.scenarios):
        assert fleet_result.final_slots[i] == scenario.expected_slots


def test_single_tenant_chain_violation_is_isolated(fleet_result):
    """Exactly one tenant's chain is tampered (a re-delivered configuration
    id); the battery must flag THAT tenant index — and nothing else."""
    tampered = dataclasses.replace(fleet_result)
    victim = 2
    tampered.phases = [list(records) for records in fleet_result.phases]
    # Re-deliver tenant 2's first committed configuration id in its last
    # phase record — the chain now repeats an id it already delivered.
    first = next(r for r in tampered.phases[victim] if r.cuts > 0)
    tampered.phases[victim][-1] = dataclasses.replace(
        tampered.phases[victim][-1], cuts=1, config_id=first.config_id
    )
    violations = chaos.check_fleet(tampered)
    by_tenant = chaos.violating_tenants(violations)
    assert set(by_tenant) == {victim}
    assert by_tenant[victim] == ["fleet-chain-consistency"]
    assert f"tenant {victim}" in violations[0].detail
    assert fleet_result.scenarios[victim].name in violations[0].detail


def test_single_tenant_membership_violation_is_isolated(fleet_result):
    tampered = dataclasses.replace(fleet_result)
    victim = 1
    tampered.final_slots = list(fleet_result.final_slots)
    tampered.final_slots[victim] = frozenset(
        set(fleet_result.final_slots[victim]) ^ {0}
    )
    violations = chaos.check_fleet(tampered)
    by_tenant = chaos.violating_tenants(violations)
    assert set(by_tenant) == {victim}
    assert by_tenant[victim] == ["fleet-membership"]


def test_unresolved_phase_is_a_convergence_violation(fleet_result):
    tampered = dataclasses.replace(fleet_result)
    victim = 3
    tampered.phases = [list(records) for records in fleet_result.phases]
    tampered.phases[victim][0] = dataclasses.replace(
        tampered.phases[victim][0], resolved=False
    )
    by_tenant = chaos.violating_tenants(chaos.check_fleet(tampered))
    assert set(by_tenant) == {victim}
    assert "fleet-convergence" in by_tenant[victim]


def test_compile_tenant_rejects_unreplayable_and_unknown_schedules():
    with pytest.raises(Exception, match="unknown scenario family"):
        chaos.compile_tenant("no_such_family", 0)
    # Engine families are all flat + restart-free by construction. Every
    # compiled scenario carries WORK: membership phase groups, or (the
    # stable-band adversarial shape) a persistent sub-H false-report load
    # the stability soak judges.
    for family in chaos.ENGINE_FAMILIES:
        scenario = chaos.compile_tenant(family, 3)
        assert scenario.schedule.engine_compatible
        assert scenario.groups or scenario.stable_subjects


# ---------------------------------------------------------------------------
# Adversarial fleet: hostile + hier families mixed, stability soak
# ---------------------------------------------------------------------------

#: One tenant per fleet family — the mixed hostile workload of
#: ``chaosrun fuzz --fleet`` at its smallest complete shape. Module-scope:
#: every adversarial-fleet test below reads this one run (PR 10 budget
#: convention — one fleet compile, many assertions).
ADVERSARIAL_SPECS = [
    (family, 20 + i) for i, family in enumerate(chaos.FLEET_FAMILIES)
]


@pytest.fixture(scope="module")
def adversarial_result():
    return chaos.run_fleet(chaos.compile_fleet(ADVERSARIAL_SPECS))


def test_fleet_families_cover_every_mix_table_and_lead_adversarial():
    # FLEET_FAMILIES is hand-ordered (adversarial first) — completeness vs
    # the engine/hier mix tables must be pinned or a new family could be
    # silently dropped from the fuzz cycle; and any B >= 3 must carry all
    # three Byzantine shapes (the small-B bench stage stays adversarial).
    assert set(chaos.FLEET_FAMILIES) == (
        set(chaos.ENGINE_FAMILIES) | set(chaos.HIER_FAMILIES)
    )
    assert len(chaos.FLEET_FAMILIES) == len(
        chaos.ENGINE_FAMILIES + chaos.HIER_FAMILIES
    )
    assert set(chaos.FLEET_FAMILIES[:3]) == {
        "false_alert_stability", "watermark_probe",
        "committee_crash_during_reconfig",
    }


def test_mixed_adversarial_fleet_upholds_every_oracle(adversarial_result):
    # Honest, Byzantine, and hier cross-product families in ONE fleet: the
    # whole battery holds, every tenant lands on its schedule's accounting
    # (including healthy subjects falsely accused past H — evicted, agreed).
    assert chaos.check_fleet(adversarial_result) == []
    for i, scenario in enumerate(adversarial_result.scenarios):
        assert adversarial_result.final_slots[i] == scenario.expected_slots


def test_stability_soak_ran_and_stable_tenants_held_the_band(
    adversarial_result,
):
    # The fleet carries sub-H false-report tenants (false_alert_stability),
    # so the soak must have stepped — and those tenants committed ZERO cuts
    # through it ("no eviction" is a run, not a vacuous skip).
    assert adversarial_result.soak_rounds == chaos.STABILITY_SOAK_ROUNDS
    assert adversarial_result.soak_cuts is not None
    stable = [
        i for i, s in enumerate(adversarial_result.scenarios)
        if s.stable_subjects
    ]
    assert stable  # the mix genuinely includes stable-band tenants
    for i in stable:
        assert int(adversarial_result.soak_cuts[i]) == 0


def test_fleet_run_reports_first_class_throughput(adversarial_result):
    # scenarios_per_sec is the headline number chaosrun/bench publish:
    # always present, consistent with the recorded wall clock.
    assert adversarial_result.wall_ms > 0
    assert adversarial_result.scenarios_per_sec == pytest.approx(
        len(ADVERSARIAL_SPECS) / (adversarial_result.wall_ms / 1000.0)
    )


def test_midrun_injection_failure_names_its_tenant():
    """ISSUE 12 satellite: a scenario whose fault injection raises
    mid-``run_fleet`` must surface as a ``fleet-injection`` violation
    naming its tenant index — never a bare exception that kills the other
    tenants' verdicts."""
    scenarios = chaos.compile_fleet([("partition_heal", 5), ("crash_during_join", 7)])
    victim = 1
    # Tamper the compiled groups with an injection the engine rejects: a
    # join wave naming a slot outside the cluster's slot table.
    from rapid_tpu.sim.faults import FaultEvent

    scenarios[victim].groups[0] = [FaultEvent("join", (99,))]
    result = chaos.run_fleet(scenarios)  # must NOT raise
    violations = chaos.check_fleet(result)
    by_tenant = chaos.violating_tenants(violations)
    assert victim in by_tenant
    assert "fleet-injection" in by_tenant[victim]
    # The healthy tenant's verdict is untouched by its neighbor's failure.
    assert 0 not in by_tenant
    assert result.final_slots[0] == scenarios[0].expected_slots
    # And the errored tenant is otherwise skipped, not judged on the state
    # the failure left behind (exactly one violation for it).
    assert by_tenant[victim] == ["fleet-injection"]


# ---------------------------------------------------------------------------
# Per-tenant shrinking: the violating tenant collapses to a 1-tenant repro
# ---------------------------------------------------------------------------


def test_tampered_tenant_shrinks_to_minimal_single_tenant_repro(tmp_path):
    """The PR 5 single-cluster shrinker pin, at the fleet grain: a known
    two-tenant violating fleet — tenant 1 runs a LOWERED H knob under a
    stable-band schedule, so the engine evicts a subject the schedule's
    reference-watermark accounting protects — shrinks to a <=3-event
    single-tenant repro that still fails IDENTICALLY on replay."""
    specs = [("partition_heal", 1), ("false_alert_stability", 3)]
    knobs = [(9, 4, 1), (5, 2, 1)]  # tenant 1: H=5 < the schedule's H=9
    scenarios = chaos.compile_fleet(specs, knobs=knobs)
    violations = chaos.check_fleet(chaos.run_fleet(scenarios))
    by_tenant = chaos.violating_tenants(violations)
    assert set(by_tenant) == {1}  # only the knob-tampered tenant fails
    oracles = set(by_tenant[1])
    assert "fleet-stability" in oracles

    t, minimal, min_violations, runs = chaos.shrink_tenant(
        chaos.compile_fleet(specs, knobs=knobs), violations
    )
    assert t == 1
    assert len(minimal.events) <= 3
    assert runs > 0
    # The reduction preserved the verdict: the same oracle set still flags
    # the same tenant.
    assert oracles <= set(chaos.violating_tenants(min_violations)[1])

    # Collapse to a single-tenant repro dir and replay it: the recorded
    # violations reproduce line for line (the chaosrun replay contract).
    repro = chaos.write_fleet_repro(
        tmp_path / "repro", minimal, knobs[1], "false_alert_stability", 3,
        tenant_index=1, fleet_size=len(specs),
    )
    recorded = [
        line for line in (repro / "violations.txt").read_text().splitlines()
        if line and line != "(none)"
    ]
    assert recorded  # the repro still fails after collapsing to one tenant
    _result, replayed = chaos.replay_fleet_repro(repro)
    assert sorted(map(str, replayed)) == sorted(recorded)

    # The write-time verify run froze its decoded round-trace ring next to
    # the verdicts, and a faithful replay never forks round histories (the
    # chaosrun replay trace instrument, ISSUE 17).
    written = json.loads((repro / "trace.json").read_text())
    assert written["rounds_recorded"] > 0
    diff = chaos.replay_trace_divergence(repro)
    assert diff is not None
    assert diff["first_divergent_round"] is None
    assert diff["written_rounds"] == written["rounds_recorded"]
    assert diff["replayed_rounds"] == written["rounds_recorded"]
    # Pre-trace repro dirs (no artifact) skip the instrument silently and
    # stay replayable on verdicts alone.
    (repro / "trace.json").unlink()
    assert chaos.replay_trace_divergence(repro) is None
    _result2, replayed2 = chaos.replay_fleet_repro(repro)
    assert sorted(map(str, replayed2)) == sorted(recorded)


@pytest.mark.slow
def test_fleet_fuzz_broad_sweep_is_clean():
    # Two tenants per family through fuzz_fleet end to end (summary shape,
    # per-family tallies, no violations). Rides the unfiltered check.sh
    # pass; the module fixture keeps one-per-family coverage in tier-1.
    summary = chaos.fuzz_fleet(2 * len(chaos.FLEET_FAMILIES), base_seed=500)
    assert summary["violations"] == []
    assert summary["tenants"] == 2 * len(chaos.FLEET_FAMILIES)
    assert set(summary["families"]) == set(chaos.FLEET_FAMILIES)
    assert all(n == 2 for n in summary["families"].values())
    assert summary["family_violations"] == {}
    assert summary["scenarios_per_sec"] > 0


# ---------------------------------------------------------------------------
# Per-tenant K/H/L autotune (the khl_sensitivity objective, batched)
# ---------------------------------------------------------------------------


def test_khl_sweep_artifact_shape_and_winner_selection():
    grid = ((4, 2), (3, 1), (2, 1))
    result = sweep_khl(
        n=96, f=3, knob_grid=grid, k=4, cohorts=8, seed=0,
        delivery_spread=6, max_rounds=64,
    )
    assert result["tenants"] == len(grid)
    assert set(result["per_knob"]) == {"4/2", "3/1", "2/1"}
    for cell in result["per_knob"].values():
        assert set(cell) == {"decided", "rounds", "conflict"}
        assert cell["decided"] is True and cell["rounds"] > 0
    # Winner selection (the delivery_autotune shape): best_knob is the
    # lexicographic (conflict, rounds) minimum over decided candidates.
    scores = {
        knob: (int(cell["conflict"]), cell["rounds"])
        for knob, cell in result["per_knob"].items()
    }
    assert result["best_knob"] == min(scores, key=lambda kn: scores[kn])


@pytest.mark.slow
def test_khl_sweep_flags_conflict_prone_low_watermark():
    """With heavy delivery skew and a watermark below the failure count, a
    cohort can announce before hearing every victim — the sweep must see
    the conflict and prefer a safe watermark over a merely fast one.

    Rides the unfiltered check.sh pass (a second fleet compile at its own
    geometry); the sweep-artifact test above keeps the autotune mechanism
    in tier-1."""
    result = sweep_khl(
        n=64, f=4, knob_grid=((4, 3), (1, 1)), k=4, cohorts=16, seed=3,
        delivery_spread=8, max_rounds=96,
    )
    low = result["per_knob"]["1/1"]
    safe = result["per_knob"]["4/3"]
    assert low["decided"] and safe["decided"]
    assert low["conflict"] is True  # H=1: first announcement misses victims
    assert safe["conflict"] is False
    assert low["rounds"] < safe["rounds"]  # ...and low H IS faster
    assert result["best_knob"] == "4/3"  # clean beats fast
