"""The batched chaos harness + per-tenant oracle battery + fleet autotune.

The no-cross-tenant-bleed satellite (ISSUE 10): in a fleet where exactly
one tenant's record violates an oracle, ``check_fleet`` must report THAT
tenant's index and leave every other tenant's verdict clean — one broken
chain can never taint its neighbors.
"""

import dataclasses

import pytest

from rapid_tpu.tenancy import chaos
from rapid_tpu.tenancy.autotune import sweep_khl

SPECS = [
    ("partition_heal", 5),
    ("asymmetric_link", 6),
    ("crash_during_join", 7),
    ("churn_under_loss", 8),
]


@pytest.fixture(scope="module")
def fleet_result():
    return chaos.run_fleet(chaos.compile_fleet(SPECS))


def test_genuine_fleet_run_upholds_every_oracle(fleet_result):
    assert chaos.check_fleet(fleet_result) == []
    # Every phase group of every tenant resolved in ONE wave dispatch per
    # group — B scenarios' convergences per dispatch is the whole point.
    assert fleet_result.dispatches == max(
        len(s.groups) for s in fleet_result.scenarios
    )
    assert fleet_result.total_cuts >= len(SPECS)
    for i, scenario in enumerate(fleet_result.scenarios):
        assert fleet_result.final_slots[i] == scenario.expected_slots


def test_single_tenant_chain_violation_is_isolated(fleet_result):
    """Exactly one tenant's chain is tampered (a re-delivered configuration
    id); the battery must flag THAT tenant index — and nothing else."""
    tampered = dataclasses.replace(fleet_result)
    victim = 2
    tampered.phases = [list(records) for records in fleet_result.phases]
    # Re-deliver tenant 2's first committed configuration id in its last
    # phase record — the chain now repeats an id it already delivered.
    first = next(r for r in tampered.phases[victim] if r.cuts > 0)
    tampered.phases[victim][-1] = dataclasses.replace(
        tampered.phases[victim][-1], cuts=1, config_id=first.config_id
    )
    violations = chaos.check_fleet(tampered)
    by_tenant = chaos.violating_tenants(violations)
    assert set(by_tenant) == {victim}
    assert by_tenant[victim] == ["fleet-chain-consistency"]
    assert f"tenant {victim}" in violations[0].detail
    assert fleet_result.scenarios[victim].name in violations[0].detail


def test_single_tenant_membership_violation_is_isolated(fleet_result):
    tampered = dataclasses.replace(fleet_result)
    victim = 1
    tampered.final_slots = list(fleet_result.final_slots)
    tampered.final_slots[victim] = frozenset(
        set(fleet_result.final_slots[victim]) ^ {0}
    )
    violations = chaos.check_fleet(tampered)
    by_tenant = chaos.violating_tenants(violations)
    assert set(by_tenant) == {victim}
    assert by_tenant[victim] == ["fleet-membership"]


def test_unresolved_phase_is_a_convergence_violation(fleet_result):
    tampered = dataclasses.replace(fleet_result)
    victim = 3
    tampered.phases = [list(records) for records in fleet_result.phases]
    tampered.phases[victim][0] = dataclasses.replace(
        tampered.phases[victim][0], resolved=False
    )
    by_tenant = chaos.violating_tenants(chaos.check_fleet(tampered))
    assert set(by_tenant) == {victim}
    assert "fleet-convergence" in by_tenant[victim]


def test_compile_tenant_rejects_unreplayable_and_unknown_schedules():
    with pytest.raises(Exception, match="unknown scenario family"):
        chaos.compile_tenant("no_such_family", 0)
    # Engine families are all flat + restart-free by construction.
    for family in chaos.ENGINE_FAMILIES:
        scenario = chaos.compile_tenant(family, 3)
        assert scenario.schedule.engine_compatible
        assert scenario.groups


# ---------------------------------------------------------------------------
# Per-tenant K/H/L autotune (the khl_sensitivity objective, batched)
# ---------------------------------------------------------------------------


def test_khl_sweep_artifact_shape_and_winner_selection():
    grid = ((4, 2), (3, 1), (2, 1))
    result = sweep_khl(
        n=96, f=3, knob_grid=grid, k=4, cohorts=8, seed=0,
        delivery_spread=6, max_rounds=64,
    )
    assert result["tenants"] == len(grid)
    assert set(result["per_knob"]) == {"4/2", "3/1", "2/1"}
    for cell in result["per_knob"].values():
        assert set(cell) == {"decided", "rounds", "conflict"}
        assert cell["decided"] is True and cell["rounds"] > 0
    # Winner selection (the delivery_autotune shape): best_knob is the
    # lexicographic (conflict, rounds) minimum over decided candidates.
    scores = {
        knob: (int(cell["conflict"]), cell["rounds"])
        for knob, cell in result["per_knob"].items()
    }
    assert result["best_knob"] == min(scores, key=lambda kn: scores[kn])


@pytest.mark.slow
def test_khl_sweep_flags_conflict_prone_low_watermark():
    """With heavy delivery skew and a watermark below the failure count, a
    cohort can announce before hearing every victim — the sweep must see
    the conflict and prefer a safe watermark over a merely fast one.

    Rides the unfiltered check.sh pass (a second fleet compile at its own
    geometry); the sweep-artifact test above keeps the autotune mechanism
    in tier-1."""
    result = sweep_khl(
        n=64, f=4, knob_grid=((4, 3), (1, 1)), k=4, cohorts=16, seed=3,
        delivery_spread=8, max_rounds=96,
    )
    low = result["per_knob"]["1/1"]
    safe = result["per_knob"]["4/3"]
    assert low["decided"] and safe["decided"]
    assert low["conflict"] is True  # H=1: first announcement misses victims
    assert safe["conflict"] is False
    assert low["rounds"] < safe["rounds"]  # ...and low H IS faster
    assert result["best_knob"] == "4/3"  # clean beats fast
