"""Java-compatible topology mode: reference-exact ring ordering and
configuration-id fold (MembershipView.java:544-587).

The tpu-native topology deliberately diverges from the reference (8-byte
port hashing, unsigned orderings) because one uniform u64 keyspace is what
the device kernels ship. ``topology="java"`` switches the host path to the
reference's exact semantics so a compat cluster computes the same ring
orders, observer/subject sets, and configuration ids a Java cluster would.

No JVM exists in this environment, so compatibility is pinned two ways:
every composition rule is RE-DERIVED here step by step from the XXH64
primitives (themselves pinned against the published xxHash test vectors in
tests/test_xxhash.py) exactly as MembershipView.java composes them; and a
committed golden fixture (tests/fixtures/java_topology.json) freezes the
resulting keys/ids so the semantics cannot drift silently.
"""

import asyncio
import functools
import json
import os
import random
import struct

import pytest

from rapid_tpu.messaging.inprocess import InProcessNetwork
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.protocol.view import (
    TOPOLOGY_JAVA,
    TOPOLOGY_NATIVE,
    Configuration,
    MembershipView,
    configuration_id_of,
    node_id_sort_key,
    ring_key,
    ring_key_java,
)
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, NodeId
from rapid_tpu.utils.xxhash import to_signed64, xxh64

from helpers import wait_until

_MASK64 = (1 << 64) - 1
FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "java_topology.json")
FIXTURE_WIDE = os.path.join(
    os.path.dirname(__file__), "fixtures", "java_topology_wide.json"
)


# ---------------------------------------------------------------------------
# Composition rules, re-derived from the XXH64 primitives.
# ---------------------------------------------------------------------------


def test_java_ring_key_composition():
    # AddressComparator.computeHash (MembershipView.java:579-587):
    #   xx(seed).hashBytes(hostname_utf8) * 31 + xx(seed).hashInt(port)
    # hashInt hashes the FOUR little-endian bytes of the Java int; the result
    # is a signed long compared via Long.compare.
    ep = Endpoint("192.168.1.20", 5002)
    for seed in (0, 1, 7):
        host_h = xxh64(b"192.168.1.20", seed)
        port_h = xxh64(struct.pack("<i", 5002), seed)
        expected = to_signed64((host_h * 31 + port_h) & _MASK64)
        assert ring_key_java(ep, seed) == expected


def test_java_vs_native_key_differs_only_in_port_hash_width():
    # Same hostname hash; the native key hashes the port as 8 bytes and
    # stays unsigned, the java key hashes 4 bytes and goes signed.
    ep = Endpoint("host-a", 80)
    host_h = xxh64(b"host-a", 3)
    port8 = xxh64(struct.pack("<q", 80), 3)
    port4 = xxh64(struct.pack("<i", 80), 3)
    assert port8 != port4  # widths genuinely diverge
    assert ring_key(ep, 3) == (host_h * 31 + port8) & _MASK64
    assert ring_key_java(ep, 3) == to_signed64((host_h * 31 + port4) & _MASK64)


def test_java_configuration_id_fold():
    # Configuration.getConfigurationId (MembershipView.java:544-556):
    #   hash = 1
    #   for id in identifiersSeen (signed NodeIdComparator order):
    #       hash = hash*37 + xx(0).hashLong(high); hash = hash*37 + xx(0).hashLong(low)
    #   for ep in ring-0 order:
    #       hash = hash*37 + xx(0).hashBytes(hostname); hash = hash*37 + xx(0).hashInt(port)
    ids = [NodeId(high=5, low=9), NodeId(high=(1 << 63) + 1, low=2)]
    eps = [Endpoint("n1", 1), Endpoint("n2", 2)]
    h = 1
    for nid in ids:
        for word in (nid.high, nid.low):
            signed = word - (1 << 64) if word >= (1 << 63) else word
            h = (h * 37 + xxh64(struct.pack("<q", signed), 0)) & _MASK64
        # hashLong hashes the 8 LE bytes of the signed long — identical bytes
        # either way; the signed conversion above is belt-and-braces.
    for ep in eps:
        h = (h * 37 + xxh64(ep.hostname.encode(), 0)) & _MASK64
        h = (h * 37 + xxh64(struct.pack("<i", ep.port), 0)) & _MASK64
    assert configuration_id_of(ids, eps, TOPOLOGY_JAVA) == to_signed64(h)


def test_signed_identifier_ordering():
    # NodeIdComparator (MembershipView.java:474-499) compares high then low
    # as SIGNED longs: a NodeId with the high bit set sorts FIRST in java
    # mode (negative) but LAST natively (unsigned).
    neg = NodeId(high=(1 << 63) + 5, low=0)  # signed: negative high
    pos = NodeId(high=3, low=0)
    assert sorted([pos, neg], key=lambda n: node_id_sort_key(n, TOPOLOGY_JAVA)) == [neg, pos]
    assert sorted([pos, neg], key=lambda n: node_id_sort_key(n, TOPOLOGY_NATIVE)) == [pos, neg]


def _endpoints_with_divergent_order(seed: int, count: int = 12):
    """A set of endpoints whose signed and unsigned ring orders differ
    (guaranteed once keys straddle the sign bit, which random hashes do)."""
    eps = [Endpoint(f"node-{i}.example", 4000 + i) for i in range(count)]
    unsigned = sorted(eps, key=lambda e: ring_key_java(e, seed) & _MASK64)
    signed = sorted(eps, key=lambda e: ring_key_java(e, seed))
    assert unsigned != signed  # the sign bit genuinely reorders this set
    return eps, signed


def test_ring_order_is_signed():
    eps, signed = _endpoints_with_divergent_order(seed=0)
    view = MembershipView(3, endpoints=eps, topology=TOPOLOGY_JAVA)
    assert view.ring(0) == signed
    # Every ring is ordered by its own seed's signed key.
    for ring_idx in range(3):
        keys = [ring_key_java(e, ring_idx) for e in view.ring(ring_idx)]
        assert keys == sorted(keys)


def test_observers_subjects_follow_java_order():
    eps, signed = _endpoints_with_divergent_order(seed=0)
    view = MembershipView(3, endpoints=eps, topology=TOPOLOGY_JAVA)
    node = signed[0]
    # Ring-0 observer is the signed-order successor, subject the predecessor.
    assert view.observers_of(node)[0] == signed[1]
    assert view.subjects_of(node)[0] == signed[-1]


def test_view_configuration_id_matches_fold():
    ids = [NodeId.from_uuid() for _ in range(5)]
    eps = [Endpoint(f"m{i}", 9000 + i) for i in range(5)]
    view = MembershipView(4, node_ids=ids, endpoints=eps, topology=TOPOLOGY_JAVA)
    expected = configuration_id_of(
        sorted(ids, key=lambda n: node_id_sort_key(n, TOPOLOGY_JAVA)),
        view.ring(0),
        TOPOLOGY_JAVA,
    )
    assert view.configuration_id == expected
    # And it differs from the native id for the same membership.
    native_view = MembershipView(4, node_ids=ids, endpoints=eps)
    assert native_view.configuration_id != view.configuration_id


def test_invalid_topology_rejected():
    with pytest.raises(ValueError):
        MembershipView(3, topology="jvm")
    s = Settings()
    s.topology = "jvm"
    with pytest.raises(ValueError):
        s.validate()


# ---------------------------------------------------------------------------
# Golden fixture: freeze the compat keyspace against silent drift.
# ---------------------------------------------------------------------------


def _golden_case():
    ids = [NodeId(high=h, low=l) for h, l in
           [(1, 2), ((1 << 63) + 7, 3), (42, (1 << 63) + 1)]]
    eps = [Endpoint("alpha.rapid", 50001), Endpoint("beta.rapid", 50002),
           Endpoint("gamma.rapid", 50003)]
    return ids, eps


def test_golden_fixture():
    ids, eps = _golden_case()
    with open(FIXTURE) as f:
        golden = json.load(f)
    for ep, expect in zip(eps, golden["ring_keys"]):
        assert [ring_key_java(ep, seed) for seed in range(3)] == expect
    view = MembershipView(3, node_ids=ids, endpoints=eps, topology=TOPOLOGY_JAVA)
    assert [f"{e.hostname}:{e.port}" for e in view.ring(0)] == golden["ring0_order"]
    assert view.configuration_id == golden["configuration_id"]


def _golden_case_wide():
    """Boundary/hostile inputs: non-ASCII UTF-8 hostnames (umlaut, Cyrillic,
    CJK), single-byte and very long hostnames, boundary ports (1, 65535,
    32768), and boundary identifiers (zero, all-ones, the signed-long
    sign-flip points) — the inputs where a composition misreading (byte
    order of ``hashInt``, sign handling in the fold or comparators) would
    actually diverge."""
    m = 1 << 64
    ids = [NodeId(0, 0), NodeId(m - 1, m - 1), NodeId(1 << 63, (1 << 63) - 1),
           NodeId(1, 1), NodeId(5, (1 << 63) + 5), NodeId(1 << 32, 1 << 32)]
    eps = [Endpoint("köln-node.example", 1), Endpoint("рапид.бг", 65535),
           Endpoint("节点七", 7), Endpoint("a", 80),
           Endpoint("delta.rapid", 50004),
           Endpoint("z-very-long-hostname-segment-z-very-long-hostname-segment", 32768)]
    return ids, eps


def test_golden_fixture_wide():
    ids, eps = _golden_case_wide()
    with open(FIXTURE_WIDE) as f:
        golden = json.load(f)
    k = golden["k"]
    for ep, expect in zip(eps, golden["ring_keys"]):
        assert [ring_key_java(ep, seed) for seed in range(k)] == expect
    view = MembershipView(k, node_ids=ids, endpoints=eps, topology=TOPOLOGY_JAVA)
    for ring_idx in range(k):
        assert [
            f"{e.hostname}:{e.port}" for e in view.ring(ring_idx)
        ] == golden["ring_orders"][ring_idx]
    assert view.configuration_id == golden["configuration_id"]
    # The native keyspace genuinely diverges on every one of these inputs —
    # the fixture would catch a silent fall-through to native hashing.
    for ep in eps:
        assert ring_key(ep, 0) != (ring_key_java(ep, 0) & _MASK64)


# ---------------------------------------------------------------------------
# Checkpoint + cluster integration.
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip_preserves_topology():
    from rapid_tpu.utils.checkpoint import (
        configuration_from_bytes,
        configuration_to_bytes,
        view_from_configuration,
    )

    ids, eps = _golden_case()
    config = Configuration(ids, eps, topology=TOPOLOGY_JAVA)
    restored = configuration_from_bytes(configuration_to_bytes(config))
    assert restored.topology == TOPOLOGY_JAVA
    assert restored.configuration_id == config.configuration_id
    assert view_from_configuration(restored, 3).topology == TOPOLOGY_JAVA
    # Native configs still round-trip native.
    native = Configuration(ids, eps)
    assert configuration_from_bytes(configuration_to_bytes(native)).topology == TOPOLOGY_NATIVE


def test_v1_checkpoint_loads_as_native():
    # Pre-topology checkpoints (version byte 1, no trailing topology byte)
    # were always native mode; they must keep loading — and native configs
    # still WRITE that v1 layout, so old readers keep working (ADVICE r4).
    from rapid_tpu.utils.checkpoint import configuration_from_bytes, configuration_to_bytes

    ids, eps = _golden_case()
    v1 = configuration_to_bytes(Configuration(ids, eps))
    assert v1[4] == 1  # native emits the v1 layout, not a gratuitous v2
    restored = configuration_from_bytes(v1)
    assert restored.topology == TOPOLOGY_NATIVE
    assert restored.endpoints == tuple(eps)

    # A java-mode blob rewritten to v1 (version byte, trailing topology byte
    # dropped) is exactly the legacy layout; it must load as native.
    v2 = bytearray(configuration_to_bytes(Configuration(ids, eps, topology=TOPOLOGY_JAVA)))
    assert v2[4] == 2
    legacy = bytes(v2[:4]) + bytes([1]) + bytes(v2[5:-1])
    assert configuration_from_bytes(legacy).topology == TOPOLOGY_NATIVE


def _async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=60)

        asyncio.run(with_timeout())

    return wrapper


@_async_test
async def test_java_mode_cluster_converges():
    # A compat-mode cluster runs the full protocol (join handshake streams
    # the config; every member folds the same java-semantics id).
    settings = Settings()
    settings.batching_window_ms = 20
    settings.failure_detector_interval_ms = 50
    settings.rpc_timeout_ms = 500
    settings.rpc_join_timeout_ms = 2000
    settings.topology = TOPOLOGY_JAVA
    network = InProcessNetwork()
    eps = [Endpoint("127.0.0.1", 21000 + i) for i in range(4)]
    clusters = [
        await Cluster.start(eps[0], settings=settings, network=network,
                            fd_factory=StaticFailureDetectorFactory(),
                            rng=random.Random(0))
    ]
    try:
        for i in range(1, 4):
            clusters.append(
                await Cluster.join(eps[0], eps[i], settings=settings, network=network,
                                   fd_factory=StaticFailureDetectorFactory(),
                                   rng=random.Random(i))
            )
        assert await wait_until(
            lambda: all(c.membership_size == 4 for c in clusters)
        )
        ids = {c.service.view.configuration_id for c in clusters}
        assert len(ids) == 1
        # The agreed id is the JAVA fold of the membership, not the native one.
        view = clusters[0].service.view
        assert view.topology == TOPOLOGY_JAVA
        expected = configuration_id_of(
            sorted(view.configuration.node_ids,
                   key=lambda n: node_id_sort_key(n, TOPOLOGY_JAVA)),
            view.ring(0),
            TOPOLOGY_JAVA,
        )
        assert ids == {expected}
    finally:
        await asyncio.gather(*(c.shutdown() for c in clusters), return_exceptions=True)


@_async_test
async def test_java_mode_cluster_over_grpc_transport():
    # Compat mode exists for ONE transport: the interop gRPC path that can
    # face a Java cluster (rapid.proto wire format). Run a java-topology
    # cluster end to end over real grpc.aio sockets — join handshake,
    # convergence, crash, re-convergence — and check every member agrees on
    # the JAVA configuration-id fold throughout.
    from helpers import free_endpoints

    from rapid_tpu.interop.grpc_transport import GrpcClient, GrpcServer

    settings = Settings()
    settings.batching_window_ms = 20
    settings.failure_detector_interval_ms = 50
    settings.rpc_timeout_ms = 500
    settings.rpc_join_timeout_ms = 2000
    settings.rpc_probe_timeout_ms = 200
    settings.topology = TOPOLOGY_JAVA
    fd = StaticFailureDetectorFactory()

    eps = free_endpoints(5)

    clusters = [
        await Cluster.start(eps[0], settings=settings,
                            client=GrpcClient(eps[0], settings),
                            server=GrpcServer(eps[0]), fd_factory=fd,
                            rng=random.Random(0))
    ]
    try:
        for i in range(1, 5):
            clusters.append(
                await Cluster.join(eps[0], eps[i], settings=settings,
                                   client=GrpcClient(eps[i], settings),
                                   server=GrpcServer(eps[i]), fd_factory=fd,
                                   rng=random.Random(i))
            )
        assert await wait_until(
            lambda: all(c.membership_size == 5 for c in clusters)
            and len({c.service.view.configuration_id for c in clusters}) == 1
        )

        def java_fold(view):
            return configuration_id_of(
                sorted(view.configuration.node_ids,
                       key=lambda n: node_id_sort_key(n, TOPOLOGY_JAVA)),
                view.ring(0),
                TOPOLOGY_JAVA,
            )

        view = clusters[0].service.view
        assert view.topology == TOPOLOGY_JAVA
        assert view.configuration_id == java_fold(view)

        # Crash: DOWN alerts + consensus ride the gRPC wire; the new
        # configuration id must again be the java fold.
        victim = clusters[2]
        await victim.shutdown()
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await wait_until(
            lambda: all(c.membership_size == 4 for c in survivors)
            and len({c.service.view.configuration_id for c in survivors}) == 1,
            timeout_s=30,
        )
        view = survivors[0].service.view
        assert view.configuration_id == java_fold(view)
    finally:
        await asyncio.gather(*(c.shutdown() for c in clusters), return_exceptions=True)
