"""Messaging-layer tests: codec round-trips, TCP transport, broadcaster
fan-out, client error paths (reference: MessagingTest.java,
NettyClientServerTest.java)."""

import asyncio
import functools

import pytest

from rapid_tpu.errors import ShuttingDownError
from rapid_tpu.messaging.base import UnicastToAllBroadcaster
from rapid_tpu.messaging.codec import (
    decode_request,
    decode_response,
    encode_request,
    encode_response,
)
from rapid_tpu.messaging.inprocess import InProcessClient, InProcessNetwork, InProcessServer
from rapid_tpu.messaging.tcp import TcpClient, TcpServer
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    ConsensusResponse,
    EdgeStatus,
    Endpoint,
    FastRoundPhase2bMessage,
    GossipMessage,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    LeaveMessage,
    NodeId,
    NodeStatus,
    Phase1aMessage,
    Phase1bMessage,
    Phase2aMessage,
    Phase2bMessage,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    Rank,
    Response,
)


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=30)

        asyncio.run(with_timeout())

    return wrapper


EP1 = Endpoint("127.0.0.1", 5001)
EP2 = Endpoint("127.0.0.1", 5002)
NID = NodeId(0x1234567890ABCDEF, 0xFEDCBA0987654321)


ALL_REQUESTS = [
    PreJoinMessage(EP1, NID),
    JoinMessage(EP1, NID, (0, 3, 9), -12345, (("role", b"w\x00rker"),)),
    BatchedAlertMessage(
        EP1,
        (
            AlertMessage(EP1, EP2, EdgeStatus.DOWN, 7, (1, 2)),
            AlertMessage(EP2, EP1, EdgeStatus.UP, 7, (0,), NID, (("k", b"v"),)),
        ),
    ),
    ProbeMessage(EP1),
    FastRoundPhase2bMessage(EP1, 99, (EP1, EP2)),
    Phase1aMessage(EP1, 1, Rank(2, 77)),
    Phase1bMessage(EP1, 1, Rank(2, 77), Rank(1, 1), (EP2,)),
    Phase2aMessage(EP1, 1, Rank(2, 77), (EP2, EP1)),
    Phase2bMessage(EP1, 1, Rank(2, 77), (EP2,)),
    LeaveMessage(EP1),
    GossipMessage(EP1, 0xDEADBEEFCAFEF00D, 7, FastRoundPhase2bMessage(EP2, 3, (EP1,))),
]

ALL_RESPONSES = [
    JoinResponse(
        EP1,
        JoinStatusCode.SAFE_TO_JOIN,
        -42,
        endpoints=(EP1, EP2),
        identifiers=(NID, NodeId(1, 2)),
        metadata_keys=(EP2,),
        metadata_values=((("role", b"seed"),),),
    ),
    Response(),
    ConsensusResponse(),
    ProbeResponse(NodeStatus.BOOTSTRAPPING),
]


@pytest.mark.parametrize("request_msg", ALL_REQUESTS, ids=lambda r: type(r).__name__)
def test_request_codec_roundtrip(request_msg):
    assert decode_request(encode_request(request_msg)) == request_msg


@pytest.mark.parametrize("response_msg", ALL_RESPONSES, ids=lambda r: type(r).__name__)
def test_response_codec_roundtrip(response_msg):
    assert decode_response(encode_response(response_msg)) == response_msg


class EchoService:
    """Minimal stand-in for MembershipService at the transport boundary."""

    def __init__(self):
        self.received = []

    async def handle_message(self, request):
        self.received.append(request)
        if isinstance(request, ProbeMessage):
            return ProbeResponse()
        return Response()


@async_test
async def test_tcp_round_trip():
    server = TcpServer(Endpoint("127.0.0.1", 0))  # ephemeral port
    service = EchoService()
    server.set_membership_service(service)
    await server.start()
    addr = server.listen_address
    client = TcpClient(Endpoint("127.0.0.1", 0))
    try:
        response = await client.send(addr, ProbeMessage(sender=Endpoint("127.0.0.1", 19002)))
        assert response == ProbeResponse()
        response = await client.send(addr, ALL_REQUESTS[1])
        assert response == Response()
        assert service.received[1] == ALL_REQUESTS[1]
    finally:
        await client.shutdown()
        await server.shutdown()


@async_test
async def test_tcp_probe_answers_bootstrapping_before_service():
    server = TcpServer(Endpoint("127.0.0.1", 0))  # no service set; ephemeral
    await server.start()
    addr = server.listen_address
    client = TcpClient(Endpoint("127.0.0.1", 0))
    try:
        response = await client.send_best_effort(addr, ProbeMessage(sender=addr))
        assert response == ProbeResponse(NodeStatus.BOOTSTRAPPING)
    finally:
        await client.shutdown()
        await server.shutdown()


@async_test
async def test_tcp_ten_servers_fan_out():
    # NettyClientServerTest's 10-server round-trip analog.
    servers, services = [], []
    for i in range(10):
        server = TcpServer(Endpoint("127.0.0.1", 0))  # ephemeral ports
        service = EchoService()
        server.set_membership_service(service)
        await server.start()
        servers.append(server)
        services.append(service)
    client = TcpClient(Endpoint("127.0.0.1", 0))
    broadcaster = UnicastToAllBroadcaster(client)
    broadcaster.set_membership([s.listen_address for s in servers])
    try:
        broadcaster.broadcast(LeaveMessage(sender=Endpoint("127.0.0.1", 18999)))
        for _ in range(100):
            if all(len(s.received) == 1 for s in services):
                break
            await asyncio.sleep(0.02)
        assert all(len(s.received) == 1 for s in services)
    finally:
        await client.shutdown()
        for server in servers:
            await server.shutdown()


@async_test
async def test_tcp_client_fails_fast_to_dead_server():
    settings = Settings()
    settings.rpc_default_retries = 1
    settings.rpc_timeout_ms = 200
    client = TcpClient(Endpoint("127.0.0.1", 19050), settings)
    try:
        with pytest.raises((ConnectionError, OSError, asyncio.TimeoutError)):
            await client.send(Endpoint("127.0.0.1", 19999), LeaveMessage(sender=EP1))
        assert (
            await client.send_best_effort(Endpoint("127.0.0.1", 19999), LeaveMessage(sender=EP1))
            is None
        )
    finally:
        await client.shutdown()


@async_test
async def test_client_after_shutdown_raises():
    # MessagingTest.java:428-466 analog: a shut-down client must raise, not hang.
    network = InProcessNetwork()
    client = InProcessClient(network, EP1)
    await client.shutdown()
    with pytest.raises(ShuttingDownError):
        await client.send(EP2, ProbeMessage(sender=EP1))
    tcp_client = TcpClient(EP1)
    await tcp_client.shutdown()
    with pytest.raises(ShuttingDownError):
        await tcp_client.send(EP2, ProbeMessage(sender=EP1))


@async_test
async def test_inprocess_broadcast_fan_out():
    # MessagingTest.java:397-421 analog: broadcaster reaches 100 servers.
    network = InProcessNetwork()
    services = []
    members = []
    for i in range(100):
        addr = Endpoint("10.0.0.1", 20000 + i)
        server = InProcessServer(network, addr)
        service = EchoService()
        server.set_membership_service(service)
        await server.start()
        services.append(service)
        members.append(addr)
    client = InProcessClient(network, EP1)
    broadcaster = UnicastToAllBroadcaster(client)
    broadcaster.set_membership(members)
    broadcaster.broadcast(LeaveMessage(sender=EP1))
    for _ in range(100):
        if all(len(s.received) == 1 for s in services):
            break
        await asyncio.sleep(0.01)
    assert all(len(s.received) == 1 for s in services)


@async_test
async def test_tcp_server_survives_hostile_bytes():
    # Connection-level fault isolation (the reference's gRPC layer gets this
    # from the framework; our framing must provide it): a peer sending an
    # oversized frame header or a well-framed but undecodable payload must
    # cost only ITS connection — a legitimate client is served throughout.
    server = TcpServer(Endpoint("127.0.0.1", 0))  # ephemeral port
    server.set_membership_service(EchoService())
    await server.start()
    addr = server.listen_address
    client = TcpClient(Endpoint("127.0.0.1", 0))
    try:
        import struct

        # Oversized length in the header: server must drop the connection.
        r1, w1 = await asyncio.open_connection(addr.hostname, addr.port)
        w1.write(struct.pack("<IQB", 1 << 30, 0, 0))
        await w1.drain()
        assert await r1.read(64) == b""  # peer closed on us
        w1.close()

        # Valid header, garbage payload: handler swallows the CodecError.
        from tests.helpers import wait_until

        rx_before = server.stats.msgs_rx
        r2, w2 = await asyncio.open_connection(addr.hostname, addr.port)
        payload = b"\xff" * 16
        w2.write(struct.pack("<IQB", len(payload), 7, 0) + payload)
        await w2.drain()
        # Happens-before: the server has READ the hostile frame (rx counts
        # at frame receipt) before any isolation assertion below — without
        # this, the probes could win the race and the test pass vacuously.
        await wait_until(lambda: server.stats.msgs_rx > rx_before)

        # The hostile CONNECTION itself survives a decode failure: a valid
        # probe on the same socket still gets a framed response.
        me = Endpoint("127.0.0.1", 0)
        good = encode_request(ProbeMessage(sender=me))
        w2.write(struct.pack("<IQB", len(good), 9, 0) + good)
        await w2.drain()
        resp_header = await asyncio.wait_for(r2.readexactly(13), timeout=10)
        resp_len, corr, kind = struct.unpack("<IQB", resp_header)
        assert (corr, kind) == (9, 1)
        resp = decode_response(await asyncio.wait_for(r2.readexactly(resp_len), 10))
        assert resp == ProbeResponse()

        # And the real client is unaffected, before and after the hostile
        # peer disconnects mid-session.
        assert await client.send(addr, ProbeMessage(sender=me)) == ProbeResponse()
        w2.close()
        assert await client.send(addr, ProbeMessage(sender=me)) == ProbeResponse()
    finally:
        await client.shutdown()
        await server.shutdown()
