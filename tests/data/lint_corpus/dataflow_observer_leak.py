"""Lint corpus: dataflow provenance defects, one per proof check.

Three miniature traced programs in the registry spec shape, each
violating one property the ``dataflow`` family proves over the real
engine: a telemetry lane read back into an engine lane (the observer
perturbs its subject), a gather whose indices cross the fleet's tenant
axis (tenant ``t`` reads tenant ``t+1``'s lanes), and a dense
full-``N`` op inside an activity-gated ``cond`` branch (provably
maskable work — a sparse-opportunity candidate the map must name).
``clean_dataflow.py`` is the silent twin.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

N = 256
TENANTS = 4


class EngineState(NamedTuple):
    alive: jnp.ndarray  # [n] activity mask — a gating lane
    cuts: jnp.ndarray  # [n] per-slot counters


class TelemetryLanes(NamedTuple):
    tl_enq: jnp.ndarray  # [n] observer tally — must stay write-only


def _observer_feedback():
    # The telemetry tally flows back into the engine's cut counters: the
    # observer plane influences a subject lane.
    def step(state, telem):
        cuts = state.cuts + telem.tl_enq
        telem = TelemetryLanes(tl_enq=telem.tl_enq + 1)
        return EngineState(alive=state.alive, cuts=cuts), telem

    return {
        "jit": jax.jit(step),
        "args": (
            EngineState(
                alive=jnp.ones((N,), jnp.bool_),
                cuts=jnp.zeros((N,), jnp.int32),
            ),
            TelemetryLanes(tl_enq=jnp.zeros((N,), jnp.int32)),
        ),
    }


def _cross_tenant_gather():
    # Each tenant's output row is gathered from ANOTHER tenant's input
    # row — an influence edge across the tenant axis.
    def fleet(lanes):
        return lanes[jnp.arange(TENANTS)[::-1]]

    return {
        "jit": jax.jit(fleet),
        "args": (jnp.ones((TENANTS, 8), jnp.float32),),
    }


def _gated_dense_round():
    # The cumulative tally runs over all N slots, but the cond predicate
    # derives from the activity mask: the whole branch is provably
    # skippable when nothing is alive, yet it prices dense.
    def round_body(state):
        def busy(s):
            return EngineState(alive=s.alive, cuts=jnp.cumsum(s.cuts))

        return jax.lax.cond(
            jnp.any(state.alive), busy, lambda s: s, state
        )

    return {
        "jit": jax.jit(round_body),
        "args": (
            EngineState(
                alive=jnp.ones((N,), jnp.bool_),
                cuts=jnp.zeros((N,), jnp.int32),
            ),
        ),
    }


DATAFLOW_AUDIT_PROGRAMS = {
    "observer_feedback": {  # expect: dataflow-observer-effect
        "build": _observer_feedback,
        "checks": ("observer-effect",),
    },
    "cross_tenant_gather": {  # expect: dataflow-cross-tenant
        "build": _cross_tenant_gather,
        "checks": ("cross-tenant",),
        "tenants": TENANTS,
    },
    "gated_dense_round": {  # expect: dataflow-dense-op
        "build": _gated_dense_round,
        "checks": ("dense-op",),
        "dense_n": N,
    },
}
