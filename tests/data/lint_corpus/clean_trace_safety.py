"""Clean counterpart for the trace-safety analyzer: zero findings.

Exercises the exemptions: ``is None`` pytree-structure tests, shape
metadata branches, static-argument branches, traced-local container
mutation, and unjitted helpers.
"""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def masked_sum(x, mask=None):
    if mask is None:  # pytree structure: resolved at trace time
        return jnp.sum(x)
    return jnp.sum(x * mask)


@functools.partial(jax.jit, static_argnames=("axis",))
def tail_mean(x, axis):
    if x.shape[0] > 1:  # shape metadata is static under trace
        x = x[1:]
    if axis > 0:  # static argument
        return jnp.mean(x, axis=axis)
    return jnp.mean(x)


@jax.jit
def scratch_built(x):
    rows = []
    rows.append(x)  # traced-local container: fine
    return jnp.stack(rows)


def plain_helper(values):
    values.append(1)  # not jitted: mutation is ordinary Python
    return values
