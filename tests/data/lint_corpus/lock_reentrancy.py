"""Seeded defect: awaiting a lock-acquiring method while holding the lock.

asyncio.Lock is not re-entrant, so both the direct and the one-hop
transitive re-acquisition deadlock the holder forever.
"""

import asyncio


class Service:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._state = 0  # guarded-by: _lock

    async def refresh(self):
        async with self._lock:
            await self._reload()  # expect: lock-reentrancy

    async def poke(self):
        async with self._lock:
            await self._indirect()  # expect: lock-reentrancy

    async def _indirect(self):
        # Entry context is provably lock-held (only called from poke's
        # critical section), so the hop itself is reported too, pointing
        # one step closer to the re-acquisition.
        await self._reload()  # expect: lock-reentrancy

    async def _reload(self):
        async with self._lock:
            self._state += 1

    async def safe(self):
        await self._reload()  # lock not held here: fine
