"""Lint corpus: tenant-axis holes in a fleet rule table.

A miniature tenant-knob pytree + ``PARTITION_RULES`` pair in the
rapid_tpu/tenancy declaration style: one ``[t, n]`` tenant-stacked leaf is
matched by a rule whose spec leaves dimension 0 UNMESHED on the tenant axis
(the whole-fleet replication hazard), and one tenant rule matches no leaf
at all (dead entry). The clean ``[t]`` knob lane shows the correct form.
"""

from typing import NamedTuple

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rapid_tpu.parallel.mesh import match_partition_rules

NODE_AXIS = "nodes"
TENANT_AXIS = "tenant"

PARTITION_RULES = (
    (r"knob_h", (TENANT_AXIS,)),
    (r"fleet_alive",
     (None, NODE_AXIS)),  # expect: missing-partition-spec
    (r"ghost_knob", (TENANT_AXIS,)),  # expect: missing-partition-spec
)


class TenantKnobs(NamedTuple):
    knob_h: jnp.ndarray  # [t] int32 — the clean tenant lane
    fleet_alive: jnp.ndarray  # [t, n] — tenant axis unmeshed by its rule


def knob_shardings(mesh: Mesh) -> TenantKnobs:
    specs = match_partition_rules(PARTITION_RULES, TenantKnobs._fields)
    return TenantKnobs(
        **{
            field: NamedSharding(mesh, P(*specs[field]))
            for field in TenantKnobs._fields
        }
    )
