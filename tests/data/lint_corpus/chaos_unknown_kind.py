"""Lint corpus: chaos vocabulary drift, every defect class.

A miniature of the three registries the chaosvocab family cross-checks:
an unknown ``FaultEvent`` kind (typo'd past the closed vocabulary), a
``FAMILIES`` key whose generator function was renamed out from under it,
a fleet mix-table entry naming an unregistered family, and a CLI family
argument with a hand-typed choices list. The allowlisted construction
shows the deliberate-fixture escape hatch.
"""

import argparse

from rapid_tpu.sim.faults import FaultEvent, FaultSchedule
from rapid_tpu.sim.fuzz import FAMILIES as _REAL  # noqa: F401


def crash_wave(seed: int) -> FaultSchedule:
    return FaultSchedule(
        n0=8, n_slots=12, seed=seed,
        events=[
            FaultEvent("crash", (3,)),  # registered: clean
            FaultEvent("falce_alert", (1,),  # expect: chaos-unknown-kind
                       args={"subject": 2, "rings": [0]}),
            FaultEvent("explode", (1,)),  # chaos-kind-ok: deliberate fixture
        ],
    )


def join_wave(seed: int) -> FaultSchedule:
    return FaultSchedule(
        n0=8, n_slots=12, seed=seed,
        events=[FaultEvent("join", (8, 9))],
    )


FAMILIES = {
    "crash_wave": crash_wave,
    "join_surge": join_wave,  # expect: chaos-family-drift
}

ENGINE_FAMILIES = (
    "partition_heal",
    "partition_heel",  # expect: chaos-family-drift
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser()
    parser.add_argument("family", nargs="?",  # expect: chaos-family-drift
                        choices=["crash_wave", "join_surge"])
    return parser
