"""Corpus: retry-backoff jitter drawn from entropy instead of a seed.

A supervision tier whose backoff jitter comes from the global entropy-seeded
generators cannot replay a fault drill bit-identically — the retry timeline
differs every run, so a wedge repro stops reproducing. Analyzed as if it
lived at rapid_tpu/serving/_corpus.py (the determinism discipline's tree);
expectations are pinned finding-by-finding in tests/test_staticcheck.py.
"""

import random

import numpy as np


def jittered_delays(base_ms, attempts):
    # An unseeded instance constructor: a different schedule every process.
    rng = np.random.default_rng()  # expect: unseeded-random
    return [
        base_ms * (2.0 ** a) * (1.0 + 0.25 * float(rng.random()))
        for a in range(attempts)
    ]


def sleepy_backoff(base_ms):
    # The module-level draw shares the global entropy-seeded generator.
    return base_ms * (1.0 + random.random())  # expect: unseeded-random


def full_jitter(step_ms):
    # Legacy numpy module-level draw: numpy's global generator.
    return float(np.random.uniform(0.0, step_ms))  # expect: unseeded-random
