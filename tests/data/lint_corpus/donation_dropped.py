"""Lint corpus: a ``donate_argnums`` buffer XLA silently refuses to alias.

The program donates its [64] input but returns only a scalar reduction —
no output buffer can reuse the donated storage, so the donation is dropped
(XLA reports the unusable buffer at compile time). The inline lock claims
the donation lands; the gate must fail with ``hlo-donation-dropped``
carrying XLA's reason, never freeze the drop silently.
"""

import jax
import jax.numpy as jnp

AUDIT_N = 64
AUDIT_C = 8


def _sum_with_dropped_donation():
    return {
        "jit": jax.jit(lambda x: jnp.sum(x), donate_argnums=(0,)),
        "args": (jnp.arange(AUDIT_N, dtype=jnp.float32),),
        "donated_leaves": 1,
    }


HLO_AUDIT_PROGRAMS = {
    "sum_donating": _sum_with_dropped_donation,  # expect: hlo-donation-dropped
}

#: What this program CLAIMS: the donated buffer is reused for the output.
HLO_LOCK = {
    "sum_donating": {
        "donation": {"donated_leaves": 1, "aliased": 1, "dropped": 0},
    },
}
