"""Seeded defect: a coroutine built and dropped without running.

Calling an ``async def`` as a bare statement creates the coroutine
object and discards it — the body never executes, silently. The
``# expect:`` markers drive tests/test_staticcheck.py.
"""

import asyncio


async def flush_queue():
    await asyncio.sleep(0)


class Notifier:
    async def emit(self):
        await asyncio.sleep(0)

    async def good(self):
        await self.emit()

    def dropped_method(self):
        self.emit()  # expect: unawaited-coroutine


def dropped_module_level():
    flush_queue()  # expect: unawaited-coroutine
