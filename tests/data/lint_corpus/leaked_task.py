"""Seeded defect: a fire-and-forget task nothing retains or observes.

The event loop holds tasks weakly — an untracked ``create_task`` result
can be garbage-collected mid-flight, and its exception is never
retrieved. The ``# expect:`` marker drives tests/test_staticcheck.py.
"""

import asyncio


class Spawner:
    def __init__(self):
        self._tasks = set()

    async def tracked(self, work):
        # Retained + done-callback: the blessed shape.
        task = asyncio.create_task(work())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        await asyncio.sleep(0)

    async def observed(self, work):
        # Chained done-callback without retention is also visible to the
        # analyzer (the statement's call is add_done_callback, not spawn).
        asyncio.create_task(work()).add_done_callback(print)

    async def leaked(self, work):
        asyncio.create_task(work())  # expect: leaked-task
