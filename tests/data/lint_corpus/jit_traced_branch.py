"""Seeded defect: Python control flow on traced values under jax.jit.

``n`` is pinned by static_argnames, so branching on it is legitimate;
branching on the traced ``x`` raises TracerBoolConversionError — but only
on the first call that reaches the branch.
"""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("n",))
def stepper(x, n):
    if n > 2:  # static argument: resolved at trace time, fine
        x = x + 1
    if x > 0:  # expect: jit-traced-branch
        return x
    while x < n:  # expect: jit-traced-branch
        x = x + 1
    return x
