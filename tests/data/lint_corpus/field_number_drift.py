"""Seeded defect: the proto mirror no longer covers the dataclass.

``Pong`` grew a ``payload`` field, but its proto message was never given
a matching field — the interop path silently drops the data on encode.
The ``# expect:`` marker drives tests/test_staticcheck.py.
"""

from dataclasses import dataclass
from typing import Union


@dataclass(frozen=True)
class Ping:
    sender: str


@dataclass(frozen=True)
class Pong:
    sender: str
    payload: bytes


RapidRequest = Union[Ping, Pong]


def _msg(name, *fields):
    return (name, fields)


def _field(name, number, ftype=0):
    return (name, number, ftype)


PROTO_FILE = (
    _msg(
        "Ping",
        _field("sender", 1),
    ),
    _msg(  # expect: field-number-drift
        "Pong",
        _field("sender", 1),
    ),
)
