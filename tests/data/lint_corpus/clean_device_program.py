"""Lint corpus (clean): compiled programs whose inline locks match.

Three shapes the ``device_program`` family must stay silent on: a sharded
hot loop whose lock records its (reduce-class) collective exactly, an
elementwise program whose donation genuinely aliases, and a reduction whose
dropped donation carries an explicit waiver.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AUDIT_N = 64
AUDIT_C = 8


def _hot_loop_psum():
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))

    def per_shard(xs):
        def cond(carry):
            return carry[1] < 8

        def body(carry):
            xs, i = carry
            total = jax.lax.psum(jnp.sum(xs), "nodes")  # scalar all-reduce
            return xs + total / AUDIT_N, i + 1

        out, _ = jax.lax.while_loop(cond, body, (xs, jnp.int32(0)))
        return out

    fn = shard_map(
        per_shard, mesh=mesh, in_specs=P("nodes"), out_specs=P("nodes"),
        check_rep=False,
    )
    return {"jit": jax.jit(fn), "args": (jnp.arange(AUDIT_N, dtype=jnp.float32),)}


def _elementwise_donating():
    return {
        "jit": jax.jit(lambda x: x + 1.0, donate_argnums=(0,)),
        "args": (jnp.arange(AUDIT_N, dtype=jnp.float32),),
        "donated_leaves": 1,
    }


def _sum_with_waiver():
    return {
        "jit": jax.jit(lambda x: jnp.sum(x), donate_argnums=(0,)),
        "args": (jnp.arange(AUDIT_N, dtype=jnp.float32),),
        "donated_leaves": 1,
        "waiver": "scalar reduction: no output buffer can reuse the input",
    }


HLO_AUDIT_PROGRAMS = {
    "hot_loop_psum": _hot_loop_psum,
    "elementwise_donating": _elementwise_donating,
    "sum_waived": _sum_with_waiver,
}

HLO_LOCK = {
    "hot_loop_psum": {
        "collectives": {
            "hot-loop/all-reduce": {
                "count": 1, "bytes": 4, "max_bytes": 4, "class": "scalar",
            },
        },
        "transfers": {},
    },
    "elementwise_donating": {
        "collectives": {},
        "donation": {"donated_leaves": 1, "aliased": 1, "dropped": 0},
    },
    "sum_waived": {
        "donation": {
            "donated_leaves": 1, "aliased": 0, "dropped": 1,
            "waiver": "scalar reduction: no output buffer can reuse the input",
        },
    },
}
