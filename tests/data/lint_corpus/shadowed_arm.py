"""Seeded defect: a dispatch arm made dead by an earlier superclass arm.

``isinstance(request, Probe)`` matches ``DeepProbe`` too, so the later
``DeepProbe`` arm (and its distinct response) is unreachable. The
``# expect:`` marker drives tests/test_staticcheck.py.
"""

from dataclasses import dataclass
from typing import Union


@dataclass(frozen=True)
class Probe:
    sender: str


@dataclass(frozen=True)
class DeepProbe(Probe):
    depth: int = 1


@dataclass(frozen=True)
class Ack:
    pass


@dataclass(frozen=True)
class DeepAck:
    pass


RapidRequest = Union[Probe, DeepProbe]
RapidResponse = Union[Ack, DeepAck]


class MiniService:
    async def handle_message(self, request):
        if isinstance(request, Probe):
            return Ack()
        if isinstance(request, DeepProbe):  # expect: shadowed-arm
            return DeepAck()
        raise TypeError(f"unidentified request type {type(request)!r}")
