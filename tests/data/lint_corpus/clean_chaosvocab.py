"""Lint corpus: the chaos vocabulary discipline upheld — zero findings.

Registered kinds only, a ``FAMILIES`` table whose keys match their
generators, mix tables naming real registered families, and a CLI family
argument wired to the registry itself.
"""

import argparse

from rapid_tpu.sim import fuzz as simfuzz
from rapid_tpu.sim.faults import FaultEvent, FaultSchedule


def partition_flap(seed: int) -> FaultSchedule:
    return FaultSchedule(
        n0=8, n_slots=12, seed=seed,
        events=[
            FaultEvent("partition", (3, 4), dwell_ms=500),
            FaultEvent("heal_partitions"),
            FaultEvent("false_alert", (1,),
                       args={"subject": 2, "rings": [0, 1]}),
        ],
    )


FAMILIES = {
    "partition_flap": partition_flap,
}

ENGINE_FAMILIES = (
    "partition_heal",
    "churn_under_loss",
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser()
    parser.add_argument("family", nargs="?", default=None,
                        choices=sorted(simfuzz.FAMILIES))
    return parser
