"""Lint corpus: an all-gather smuggled into the convergence hot loop.

The miniature program shards a [64] vector over the 8-device mesh and
gathers the FULL vector inside the while body — exactly the regression the
compiled-program gate exists to catch (an unconditional O(n) gather per
round). The inline ``HLO_LOCK`` freezes the budget this program claims
(reductions only, i.e. no collectives recorded), so the compiled artifact
drifts from it and the gate must fail naming the entrypoint, the hot-loop
location class, and the payload delta.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

AUDIT_N = 64
AUDIT_C = 8


def _hot_loop_gather():
    mesh = Mesh(np.array(jax.devices()[:8]), ("nodes",))

    def per_shard(xs):
        def cond(carry):
            return carry[1] < 8

        def body(carry):
            xs, i = carry
            # THE defect: the full [n] vector crosses the mesh every round.
            full = jax.lax.all_gather(xs, "nodes", tiled=True)
            return xs + jnp.sum(full) / full.size, i + 1

        out, _ = jax.lax.while_loop(cond, body, (xs, jnp.int32(0)))
        return out

    fn = shard_map(
        per_shard, mesh=mesh, in_specs=P("nodes"), out_specs=P("nodes"),
        check_rep=False,
    )
    return {"jit": jax.jit(fn), "args": (jnp.arange(AUDIT_N, dtype=jnp.float32),)}


HLO_AUDIT_PROGRAMS = {
    "hot_loop_gather": _hot_loop_gather,  # expect: hlo-collective-budget
}

#: What this program CLAIMS: a collective-free hot loop.
HLO_LOCK = {
    "hot_loop_gather": {
        "collectives": {},
    },
}
