"""Clean counterpart for the concurrency analyzer: zero findings.

Exercises the shapes the analysis must NOT convict: sync helpers whose
lock-held context is proven through the intra-class call graph, atomic
swap-then-return under one acquisition, and lock-free reads.
"""

import asyncio


class Ledger:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._entries = []  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock

    async def push(self, item):
        async with self._lock:
            self._record(item)

    async def push_many(self, items):
        async with self._lock:
            for item in items:
                self._record(item)

    def _record(self, item):
        # Sync helper called only with the lock held: the call-graph
        # fixpoint proves the context, no annotation needed here.
        self._seq += 1
        self._entries.append((self._seq, item))

    async def drain(self):
        async with self._lock:
            drained, self._entries = self._entries, []
        return drained

    def size(self):
        return len(self._entries)  # reads need no lock
