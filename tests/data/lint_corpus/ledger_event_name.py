"""Corpus: ledger vocabulary violations — free-form event strings, unknown
LedgerEvent members, unregistered / non-literal stage names."""

from rapid_tpu.utils.ledger import LedgerEvent, RunLedger


def bad_writer(path):
    ledger = RunLedger(path)
    ledger.emit("stage_begin", stage="state_build")  # expect: ledger-event-name
    ledger.emit(LedgerEvent.NOT_A_MEMBER)  # expect: ledger-event-name
    with ledger.stage("totally_new_stage"):  # expect: ledger-stage-name
        pass
    name = "state_build"
    with ledger.stage(name):  # expect: ledger-stage-name
        pass


def forwarding_helper(ledger, event):
    # Forwarding an already-validated parameter is the one allowed
    # non-member spelling (the caller's site is checked instead).
    ledger.emit(event, stage="state_build")
