"""Clean counterpart for the wire_schema analyzer: zero findings.

A complete miniature of the four-mirror surface in one module: union +
dataclasses, tag table, encode/decode arms for every member, and a proto
mirror (including the oneof envelope, whose field numbers must equal the
native tags).
"""

from dataclasses import dataclass
from typing import Dict, Type, Union


@dataclass(frozen=True)
class Ping:
    sender: str


@dataclass(frozen=True)
class Pong:
    sender: str
    payload: bytes


RapidRequest = Union[Ping, Pong]

_REQUEST_TAGS: Dict[Type, int] = {Ping: 1, Pong: 2}


def _encode_request_impl(request):
    parts = [_REQUEST_TAGS[type(request)]]
    if isinstance(request, Ping):
        parts.append(request.sender)
    elif isinstance(request, Pong):
        parts.append(request.sender)
        parts.append(request.payload)
    return parts


def decode_request(frame):
    tag = frame[0]
    if tag == 1:
        out = Ping(frame[1])
    elif tag == 2:
        out = Pong(frame[1], frame[2])
    else:
        raise ValueError(f"unknown request tag {tag}")
    return out


def _msg(name, *fields):
    return (name, fields)


def _field(name, number, ftype=0):
    return (name, number, ftype)


PROTO_FILE = (
    _msg(
        "Ping",
        _field("sender", 1),
    ),
    _msg(
        "Pong",
        _field("sender", 1),
        _field("payload", 2),
    ),
    _msg(
        "RapidRequest",
        _field("ping", 1),
        _field("pong", 2),
    ),
)
