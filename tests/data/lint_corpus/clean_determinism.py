"""Seeded corpus, clean counterpart: every randomness source is injectable
or identity-seeded, plus the annotated-exception spelling — none of these
may produce a finding."""

import random

import numpy as np


class SeededJitter:
    def __init__(self, my_addr, rng=None):
        self.rng = rng if rng is not None else random.Random(f"jitter:{my_addr}")

    def pick(self, members):
        return self.rng.choice(members)


def explicit_entropy(rng=None):
    # The documented escape hatch: a deliberate entropy default.
    return rng if rng is not None else random.Random()  # unseeded-ok: corpus example of the annotated exception


def seeded_numpy(seed):
    return np.random.default_rng(seed)


def constructed_generator(seed):
    return np.random.Generator(np.random.PCG64(seed))
