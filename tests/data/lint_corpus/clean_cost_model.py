"""Lint corpus (clean): compiled cost that matches its frozen classes.

The linear twin of ``cost_scaling_regression.py``: every operand is a
per-slot [n] lane, so argument bytes and FLOPs both fit O(N) with zero
residual, exactly what the inline ``COST_LOCK`` claims — the
``cost_model`` family must stay silent. ``scalar_probe`` pins the O(1)
floor: a geometry-independent scalar program whose every audited fact is
constant across the ladder.
"""

import jax
import jax.numpy as jnp

COST_LADDER = (8, 16, 32, 64)
AUDIT_C = 1


def _linear_probe(n):
    return {
        "jit": jax.jit(lambda x, y: x * 2.0 + y),
        "args": (
            jnp.ones((n,), jnp.float32),
            jnp.ones((n,), jnp.float32),
        ),
        "donated_leaves": 0,
    }


def _scalar_probe(n):
    del n  # geometry-independent by construction
    return {
        "jit": jax.jit(lambda x: x * 3.0),
        "args": (jnp.float32(1.0),),
        "donated_leaves": 0,
    }


COST_AUDIT_PROGRAMS = {
    "linear_probe": _linear_probe,
    "scalar_probe": _scalar_probe,
}

COST_LOCK = {
    "linear_probe": {
        "facts": {
            "argument_bytes": {"class": "O(N)"},
            "flops": {"class": "O(N)"},
        },
    },
    "scalar_probe": {
        "facts": {
            "argument_bytes": {"class": "O(1)"},
            "flops": {"class": "O(1)"},
        },
    },
}
