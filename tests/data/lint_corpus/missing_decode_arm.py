"""Seeded defect: a tagged union member with no decode arm.

``Pong`` is in the union, tagged, and encodable — but ``decode_request``
never handles tag 2, so every Pong frame a peer sends raises instead of
decoding. The ``# expect:`` markers drive tests/test_staticcheck.py's
corpus gate (the wire_schema analyzer reads all mirrors from this one
module, the way tree sweeps merge types.py/codec.py/proto_schema.py).
"""

from dataclasses import dataclass
from typing import Dict, Type, Union


@dataclass(frozen=True)
class Ping:
    sender: str


@dataclass(frozen=True)
class Pong:
    sender: str
    payload: bytes


RapidRequest = Union[Ping, Pong]

_REQUEST_TAGS: Dict[Type, int] = {Ping: 1, Pong: 2}


def _encode_request_impl(request):
    parts = [_REQUEST_TAGS[type(request)]]
    if isinstance(request, Ping):
        parts.append(request.sender)
    elif isinstance(request, Pong):
        parts.append(request.sender)
        parts.append(request.payload)
    return parts


def decode_request(frame):  # expect: missing-decode-arm
    tag = frame[0]
    if tag == 1:
        out = Ping(frame[1])
    else:
        raise ValueError(f"unknown request tag {tag}")
    return out
