"""Corpus: disciplined round-trace ring fetches — every host decode of
the ring sits at a declared boundary and carries the marker. Host reads
of the DECODED summaries (plain dicts, named ``trace`` by convention)
prove the checker does not overreach onto the host-side cache."""

import numpy as np

from rapid_tpu.models.virtual_cluster import trace_digest
from rapid_tpu.tenancy.fleet import fleet_trace_digest


class MiniRecorder:
    def __init__(self, trace_ring):
        self.trace_ring = trace_ring
        self.trace = None

    def sync(self):
        # telemetry-fetch-ok: sync barrier — the driver is already paying
        # a blocking device round trip here; one [2 + 9R] digest rides it.
        digest = np.asarray(trace_digest(self.trace_ring))
        self.trace = digest
        return digest

    def health_scan(self):
        # telemetry-fetch-ok: health sweep boundary (already blocking);
        # one stacked fetch decodes every tenant's ring.
        per_tenant = np.asarray(fleet_trace_digest(self.trace_ring))
        return per_tenant[:, 0]

    def snapshot(self):
        # Reads of the decoded HOST-side summary are free — ``trace`` is
        # a plain dict here, not the device ring; no marker needed.
        cached = self.trace
        wraps = np.asarray(cached[1]) if cached is not None else None
        return cached, wraps
