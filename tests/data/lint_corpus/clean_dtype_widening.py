"""Lint corpus, clean counterpart: narrow-lane stores the dtype-widening
check must accept — compute-in-int32-cast-on-store, name-only bindings,
astype-wrapped arithmetic, and untracked (never-narrowed) lanes."""

import jax.numpy as jnp


def tick(state, probed):
    # The round-body convention: arithmetic bound to a name (its dtype was
    # settled where it was computed), the store passes the NAME.
    fd_count = jnp.where(probed, state.fd_count + 1, state.fd_count)
    state = state._replace(fd_count=fd_count)
    # Arithmetic wrapped in astype at any depth is an explicit cast.
    state = state._replace(
        fire_round=jnp.where(
            probed[:, 0],
            (state.round_idx.astype(jnp.int32) + 0).astype(state.fire_round.dtype),
            state.fire_round,
        )
    )
    # Lanes outside NARROWABLE_LANES may do inline arithmetic freely:
    # round_idx/config_epoch stay int32 under every policy.
    state = state._replace(
        round_idx=state.round_idx + 1,
        config_epoch=state.config_epoch + 1,
    )
    return state
