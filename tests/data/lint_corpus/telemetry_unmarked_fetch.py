"""Lint corpus: telemetry-lane fetches outside declared boundaries.

The device telemetry plane is write-only inside the round bodies and is
materialized on host ONLY at declared sync seams. Calling a digest jit —
or spelling the fetch directly via numpy / device_get over the lanes —
without a ``# telemetry-fetch-ok: <why>`` marker is a blocking round trip
smuggled onto a hot path.
"""

import numpy as np

import jax

from rapid_tpu.models.virtual_cluster import telemetry_digest
from rapid_tpu.tenancy.fleet import fleet_telemetry_digest


class MiniFleet:
    def __init__(self, telem):
        self.telem = telem
        self._activity = None

    def dispatch(self, wave):
        # Refreshing activity per dispatched wave defeats the plane's whole
        # design — the digest belongs at the drain/sync seam only.
        digest = np.asarray(telemetry_digest(self.telem))  # expect: telemetry-unmarked-fetch
        return digest.sum() + wave

    def scan(self):
        per_tenant = fleet_telemetry_digest(self.telem)  # expect: telemetry-unmarked-fetch
        return per_tenant

    def peek(self):
        # The direct spellings block just the same as the digest jits.
        raw = np.array(self.telem.tl_active)  # expect: telemetry-unmarked-fetch
        lanes = jax.device_get(self.telem)  # expect: telemetry-unmarked-fetch
        return raw.sum(), lanes

    def sync(self):
        # telemetry-fetch-ok: host-sync boundary — the caller is already
        # paying a blocking device round trip here.
        digest = np.asarray(telemetry_digest(self.telem))
        self._activity = digest
        return digest
