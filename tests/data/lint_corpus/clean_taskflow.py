"""Clean counterpart for the taskflow analyzer: zero findings.

Exercises the shapes the analysis must NOT convict: tracked spawns,
awaited coroutines, justified broad catches (with and without logging
bodies), cleanup-then-reraise cancellation handling, and narrow catches.
"""

import asyncio
import logging

LOG = logging.getLogger(__name__)


class Worker:
    def __init__(self):
        self._tasks = set()
        self._lock = asyncio.Lock()

    async def spawn(self, work):
        task = asyncio.create_task(work())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def run_once(self):
        await self.tick()

    async def loop(self):
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive a tick
                LOG.exception("tick failed; continuing")

    async def narrow(self):
        try:
            await self.tick()
        except (ConnectionError, OSError) as exc:
            LOG.debug("transport fault: %r", exc)

    async def tick(self):
        await asyncio.sleep(0)
