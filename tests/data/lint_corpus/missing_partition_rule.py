"""Lint corpus: rule-table holes for the cohort-meshed engine pytree.

A miniature ``EngineState`` + ``PARTITION_RULES`` pair in the current
(regex rule table) declaration style: one [c, n] leaf is matched by a rule
that leaves it UNMESHED (empty spec) without a ``# replicated-ok:``
justification, one leaf matches no rule at all, one rule matches no leaf
(dead entry), and one replication justification survives from the 1-D era
whose premise — that the cohort axis is not a mesh axis — is now false.
"""

from typing import NamedTuple

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rapid_tpu.parallel.mesh import match_partition_rules

NODE_AXIS = "nodes"
COHORT_AXIS = "cohort"

PARTITION_RULES = (  # expect: missing-partition-spec
    (r"alive", (NODE_AXIS,)),
    (r"report_bits",
     ()),  # expect: missing-partition-spec
    (r"round_idx", ()),  # replicated-ok: round-counter scalar
    (r"seen_down", ()),  # replicated-ok: [c] flags; cohort axis is not meshed  # expect: missing-partition-spec
    (r"ghost_lanes", (COHORT_AXIS,)),  # expect: missing-partition-spec
)


class EngineState(NamedTuple):
    alive: jnp.ndarray  # [n]
    report_bits: jnp.ndarray  # [c, n] — unmeshed by its rule above
    seen_down: jnp.ndarray  # [c]
    round_idx: jnp.ndarray  # scalar
    vote_bits: jnp.ndarray  # [n] — covered by NO rule


def state_shardings(mesh: Mesh) -> EngineState:
    specs = match_partition_rules(PARTITION_RULES, EngineState._fields)
    return EngineState(
        **{
            field: NamedSharding(mesh, P(*specs[field]))
            for field in EngineState._fields
        }
    )
