"""Seeded defect: guarded-field mutation outside the guarding lock.

``_events`` is explicitly annotated; ``_count`` is unannotated and its
guard is majority-inferred (two locked mutation sites vs one lock-free).
The ``# expect:`` markers drive tests/test_staticcheck.py's corpus gate.
"""

import asyncio


class Tally:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._events = []  # guarded-by: _lock
        self._count = 0  # unannotated: guard inferred from majority usage

    async def record(self, event):
        async with self._lock:
            self._events.append(event)
            self._count += 1

    async def bump(self):
        async with self._lock:
            self._count += 1

    async def record_fast(self, event):
        self._events.append(event)  # expect: unguarded-mutation

    async def drop(self):
        self._count -= 1  # expect: unguarded-mutation

    def snapshot(self):
        return list(self._events)  # reads need no lock
