"""Lint corpus: a bare Python literal in a traced jit position.

``run(cfg, state, 96)`` traces with ``weak_type=True``; the wrapped
``jnp.int32(96)`` call next to it traces AGAIN — one silent recompile per
spelling of the same value.
"""

import jax
import jax.numpy as jnp


def run_impl(cfg, values, max_steps):
    del cfg
    return values * max_steps


run = jax.jit(run_impl, static_argnums=(0,))


def drive(cfg, values):
    bare = run(cfg, values, 96)  # expect: retrace-hazard
    wrapped = run(cfg, values, jnp.int32(96))
    return bare, wrapped
