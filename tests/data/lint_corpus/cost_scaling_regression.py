"""Lint corpus: compiled cost that grew past its frozen scaling class.

Three miniature programs the ``cost_model`` family must fail, each a
distinct drift mode. ``quadratic_probe`` feeds an [n, n] operand to a
matvec so its argument bytes fit O(N^2) exactly while the inline
``COST_LOCK`` claims O(N) — a scaling REGRESSION by name (its ceiling is
raised to O(N^2) so only the regression fires). ``runaway_probe`` locks
the honest O(N^2) class but keeps the default O(N*K) ceiling, so the fit
agrees with the lock and the CEILING still refuses it. ``stepped_probe``
widens its dtype halfway up the ladder — a policy step function, not a
scaling law — and the fitter must refuse to classify it rather than
guess.
"""

import jax
import jax.numpy as jnp

COST_LADDER = (8, 16, 32, 64)
AUDIT_C = 1


def _quadratic_probe(n):
    # THE defect: the per-round operand is a full [n, n] matrix, so the
    # compiled signature grows quadratically with cluster size.
    return {
        "jit": jax.jit(lambda m, v: m @ v),
        "args": (
            jnp.ones((n, n), jnp.float32),
            jnp.ones((n,), jnp.float32),
        ),
        "donated_leaves": 0,
    }


def _runaway_probe(n):
    return {
        "jit": jax.jit(lambda m: m.sum(axis=1)),
        "args": (jnp.ones((n, n), jnp.float32),),
        "donated_leaves": 0,
    }


def _stepped_probe(n):
    # Bytes-per-element is a step function of n (the dtype widens at 32),
    # so no scaling class explains the series — the fit must REFUSE.
    dtype = jnp.int8 if n < 32 else jnp.int16
    return {
        "jit": jax.jit(lambda x: x + jnp.ones((), x.dtype)),
        "args": (jnp.zeros((n,), dtype),),
        "donated_leaves": 0,
    }


COST_AUDIT_PROGRAMS = {
    "quadratic_probe": _quadratic_probe,  # expect: cost-scaling-regression
    "runaway_probe": _runaway_probe,  # expect: cost-superlinear
    "stepped_probe": _stepped_probe,  # expect: cost-unexplained
}

#: What these programs CLAIM. ``quadratic_probe`` claims linear argument
#: growth under a quadratic ceiling; ``runaway_probe`` admits the
#: quadratic class but inherits the default O(N*K) ceiling; the stepped
#: probe's claimed class is irrelevant — the refusal fires first.
COST_LOCK = {
    "quadratic_probe": {
        "ceiling": "O(N^2)",
        "facts": {"argument_bytes": {"class": "O(N)"}},
    },
    "runaway_probe": {
        "facts": {"argument_bytes": {"class": "O(N^2)"}},
    },
    "stepped_probe": {
        "ceiling": "O(N^2)",
        "facts": {"argument_bytes": {"class": "O(N)"}},
    },
}
