"""Corpus: disciplined ledger use — registered events and stage names only.
Also proves the import gate: an ``emit`` method on an unrelated object in a
file NOT importing the ledger module is out of family scope (see the
unrelated-emitter corpus note in tests/test_staticcheck.py)."""

from rapid_tpu.utils.ledger import LedgerEvent, RunLedger


def good_writer(path):
    ledger = RunLedger(path)
    ledger.emit(LedgerEvent.RUN_BEGIN, mode="inline")
    with ledger.stage("state_build", timeout_s=900, n=1024):
        pass
    ledger.emit(LedgerEvent.RUN_END, outcome="completed")
