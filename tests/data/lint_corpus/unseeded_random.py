"""Seeded corpus: unseeded randomness inside the library.

Every spelling of entropy-seeded randomness the determinism family bans:
an unseeded ``random.Random()``, module-level draws on the global
generator, from-imports aliasing it, and numpy's unseeded ``default_rng``.
The ``# expect:`` markers drive tests/test_staticcheck.py's corpus gate.
"""

import random
from random import choice  # expect: unseeded-random

import numpy as np


class JitterSource:
    def __init__(self, rng=None):
        self.rng = rng if rng is not None else random.Random()  # expect: unseeded-random


def pick_peer(members):
    return random.choice(members)  # expect: unseeded-random


def delay_ms():
    return random.random() * 100.0  # expect: unseeded-random


def reseed_global():
    random.seed()  # expect: unseeded-random


def seeded_looking_system_random(seed):
    # SystemRandom IGNORES its seed argument: flagged even when "seeded".
    return random.SystemRandom(seed)  # expect: unseeded-random


def numpy_stream():
    return np.random.default_rng()  # expect: unseeded-random


def numpy_legacy(n):
    return np.random.permutation(n)  # expect: unseeded-random


def aliased(members):
    return choice(members)
