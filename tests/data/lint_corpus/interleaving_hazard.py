"""Seeded defect: read -> await -> dependent write on guarded state.

Both accesses hold the lock, but not ACROSS the await between them — the
classic check-then-act lost update. The second case is the one-statement
variant on an event-loop-confined field.
"""

import asyncio


async def _refresh(value):
    await asyncio.sleep(0)
    return (value or 0) + 1


class Counter:
    def __init__(self):
        self._lock = asyncio.Lock()
        self._total = 0  # guarded-by: _lock
        self._cache = None  # guarded-by: event-loop

    async def add(self, delta):
        async with self._lock:
            snapshot = self._total
        await asyncio.sleep(0)
        async with self._lock:
            self._total = snapshot + delta  # expect: interleaving-hazard

    async def add_atomic(self, delta):
        async with self._lock:
            self._total = self._total + delta  # lock held across: fine

    async def refresh(self):
        self._cache = await _refresh(self._cache)  # expect: interleaving-hazard

    async def busy_guard(self):
        # The canonical check-then-act: the read lives in the `if` TEST,
        # straight-line with its siblings — two concurrent calls both pass
        # the guard during the sleep and both proceed.
        if self._cache:
            return
        await asyncio.sleep(0)
        self._cache = 1  # expect: interleaving-hazard

    async def wrong_shield(self, delta, gate):
        # An unrelated context manager does not protect the field: its
        # internal await yields to the event loop just the same.
        async with self._lock:
            snapshot = self._total
        async with gate:
            await asyncio.sleep(0)
        async with self._lock:
            self._total = snapshot + delta  # expect: interleaving-hazard
