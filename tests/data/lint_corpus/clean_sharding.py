"""Lint corpus (clean): every sharding-family hatch used correctly.

A fully-declared fault pytree table (replicated leaves justified), a
deliberately non-donating jit probe with its ``# donate-ok:`` reason, a
debug-path host fetch with ``# host-sync-ok:``, and wrapped/static scalars
at every jit callsite.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


class FaultInputs(NamedTuple):
    crashed: jnp.ndarray  # [n]
    rx_block: jnp.ndarray  # [c, n]
    seed: jnp.ndarray  # scalar


def fault_shardings(mesh: Mesh) -> FaultInputs:
    def sh(*spec) -> NamedSharding:
        return NamedSharding(mesh, P(*spec))

    return FaultInputs(
        crashed=sh(NODE_AXIS),
        rx_block=sh(None, NODE_AXIS),
        seed=sh(),  # replicated-ok: rng-seed scalar
    )


def step_impl(cfg, state, faults):
    del cfg
    return state + faults


step = jax.jit(step_impl, static_argnums=(0,), donate_argnums=(1,))
step_probe = jax.jit(step_impl, static_argnums=(0,))  # donate-ok: compile-probe variant; callers keep their state


def snapshot_impl(state):
    host = jax.device_get(state)  # host-sync-ok: debug snapshot, not the product loop
    del host
    return state


def drive(cfg, state, faults):
    return step(cfg, state, jnp.float32(faults))
