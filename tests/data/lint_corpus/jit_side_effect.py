"""Seeded defect: Python side effects and impure reads under jax.jit.

Each fires at TRACE time, not per call: the print happens once, the
append records one tracer, and the wall-clock value is baked into the
compiled program forever.
"""

import time

import jax

TRACE_LOG = []


@jax.jit
def leaky(x):
    print("tracing", x)  # expect: jit-side-effect
    TRACE_LOG.append(x)  # expect: jit-side-effect
    return x * 2


@jax.jit
def stamped(x):
    return x + time.time()  # expect: jit-side-effect


@jax.jit
def reordered(x):
    TRACE_LOG.sort()  # expect: jit-side-effect
    return x


@jax.jit
def tidy(x):
    scratch = []
    scratch.append(x)  # local container: traced-local, fine
    return scratch[0]
