"""Lint corpus (clean): dataflow provenance with every proof holding.

The silent twin of ``dataflow_observer_leak.py``: telemetry is written
from the engine but never read back (a one-way plane), every fleet op
stays inside its own tenant row (elementwise + per-tenant reduction),
and the dense cumulative tally runs unconditionally — real work, not a
mask-gated sparse opportunity. The ``dataflow`` family must stay
silent on all three.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

N = 256
TENANTS = 4


class EngineState(NamedTuple):
    alive: jnp.ndarray  # [n] activity mask
    cuts: jnp.ndarray  # [n] per-slot counters


class TelemetryLanes(NamedTuple):
    tl_enq: jnp.ndarray  # [n] observer tally, write-only


def _observer_silent():
    # Telemetry observes the engine; nothing flows the other way.
    def step(state, telem):
        cuts = state.cuts + 1
        telem = TelemetryLanes(tl_enq=telem.tl_enq + cuts)
        return EngineState(alive=state.alive, cuts=cuts), telem

    return {
        "jit": jax.jit(step),
        "args": (
            EngineState(
                alive=jnp.ones((N,), jnp.bool_),
                cuts=jnp.zeros((N,), jnp.int32),
            ),
            TelemetryLanes(tl_enq=jnp.zeros((N,), jnp.int32)),
        ),
    }


def _per_tenant_fleet():
    # Elementwise work plus a per-tenant mean: every op keeps the tenant
    # axis intact, so no influence edge can cross it.
    def fleet(lanes):
        centered = lanes - lanes.mean(axis=1, keepdims=True)
        return centered * 2.0 + 1.0

    return {
        "jit": jax.jit(fleet),
        "args": (jnp.ones((TENANTS, 8), jnp.float32),),
    }


def _ungated_dense_round():
    # Dense over all N, but unconditional: no mask gates it, so it is
    # honest work and not an opportunity-map entry.
    def round_body(state):
        return EngineState(alive=state.alive, cuts=jnp.cumsum(state.cuts))

    return {
        "jit": jax.jit(round_body),
        "args": (
            EngineState(
                alive=jnp.ones((N,), jnp.bool_),
                cuts=jnp.zeros((N,), jnp.int32),
            ),
        ),
    }


DATAFLOW_AUDIT_PROGRAMS = {
    "observer_silent": {
        "build": _observer_silent,
        "checks": ("observer-effect", "dense-op"),
        "dense_n": N,
    },
    "per_tenant_fleet": {
        "build": _per_tenant_fleet,
        "checks": ("cross-tenant",),
        "tenants": TENANTS,
    },
    "ungated_dense_round": {
        "build": _ungated_dense_round,
        "checks": ("dense-op",),
        "dense_n": N,
    },
}
