"""Corpus (clean): seeded retry-backoff — the schedule is a pure function
of its seed, so a supervised fault drill replays bit-identically. The
counterpart of unseeded_backoff.py; must produce ZERO findings.
"""

import numpy as np


def jittered_delays(base_ms, attempts, seed):
    # Seeded instance: the whole delay schedule derives from the seed.
    rng = np.random.default_rng(seed)
    return [
        base_ms * (2.0 ** a) * (1.0 + 0.25 * float(rng.random()))
        for a in range(attempts)
    ]


def injected_jitter(step_ms, rng):
    # The rng= injection seam: the caller owns determinism.
    return step_ms * (1.0 + float(rng.random()))
