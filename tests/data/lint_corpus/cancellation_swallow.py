"""Seeded defect: a loop that absorbs its own cancellation.

Catching ``asyncio.CancelledError`` without re-raising keeps the task
alive after ``task.cancel()`` — shutdown then hangs awaiting it. The
``# expect:`` marker drives tests/test_staticcheck.py.
"""

import asyncio


class Looper:
    async def immortal(self):
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:  # expect: cancellation-swallow
                continue

    async def well_behaved(self):
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                await self.flush()
                raise

    async def tick(self):
        pass

    async def flush(self):
        pass
