"""Seeded defect: an unparseable file must become a finding, not a crash."""


def broken(:  # expect: syntax-error
    return 0
