"""Seeded defect: a union member the dispatch ladder never matches.

``Status`` is in the request union but no ``isinstance`` arm handles it:
at runtime it falls through to the trailing ``TypeError`` — on a peer's
schedule, not at build time. The ``# expect:`` marker drives
tests/test_staticcheck.py.
"""

from dataclasses import dataclass
from typing import Union


@dataclass(frozen=True)
class Ping:
    sender: str


@dataclass(frozen=True)
class Pong:
    sender: str


@dataclass(frozen=True)
class Status:
    sender: str


@dataclass(frozen=True)
class Ack:
    pass


RapidRequest = Union[Ping, Pong, Status]
RapidResponse = Union[Ack]


class MiniService:
    async def handle_message(self, request):  # expect: unreachable-dispatch-arm
        if isinstance(request, Ping):
            return Ack()
        if isinstance(request, Pong):
            return Ack()
        raise TypeError(f"unidentified request type {type(request)!r}")
