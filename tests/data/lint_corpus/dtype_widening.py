"""Lint corpus: arithmetic stored into policy-narrowed engine lanes.

Under the compact policy (models/state.compaction_policy) ``fd_count`` is
int16 and ``report_bits`` uint8 — jnp promotion re-widens either the moment
an int32/uint32 operand touches the store expression, silently un-doing the
compaction while every differential keeps passing (wide mode compiles
identically either way). The clean spellings: compute-cast-bind-store a
NAME, or wrap the arithmetic in ``.astype(...)``.
"""

import jax.numpy as jnp


def tick(state, probe_failed, new_bits):
    # Inline add on a narrowed counter lane: int16 + int32 -> int32.
    state = state._replace(
        fd_count=state.fd_count + jnp.int32(1)  # expect: dtype-widening
    )
    # Inline OR on the narrowed bitmask lane: uint8 | uint32 -> uint32.
    state = state._replace(
        report_bits=state.report_bits | new_bits.astype(jnp.uint32)  # expect: dtype-widening
    )
    # Escaped: the justification names why the widening is intended.
    state = state._replace(
        rounds_undecided=state.rounds_undecided + 1  # widen-ok: weak-typed literal stays at the lane dtype
    )
    # Clean: accumulate wide, cast the store explicitly.
    state = state._replace(
        fire_round=(state.fire_round.astype(jnp.int32) + 1).astype(state.fire_round.dtype)
    )
    return state


def rebuild(EngineState, n, k, topo):
    # Constructor keyword with un-cast arithmetic on a narrowed index lane.
    return EngineState(
        obs_idx=topo.obs_idx + 0,  # expect: dtype-widening
        subj_idx=topo.subj_idx.astype(jnp.int16),
        fd_count=jnp.zeros((n, k), dtype=jnp.int16),
    )
