"""Lint corpus: sharding-table holes for the engine state pytree.

A miniature ``EngineState`` + ``state_shardings`` pair in one module (the
real pair is split across models/state.py and parallel/mesh.py; tree sweeps
merge those the way wire sweeps merge the schema mirrors): one array leaf
has no declared spec at all, one is silently fully replicated without a
``# replicated-ok:`` reason, and one table entry names a field that does
not exist.
"""

from typing import NamedTuple

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


class EngineState(NamedTuple):
    alive: jnp.ndarray  # [n]
    votes: jnp.ndarray  # [n] — MISSING from the table below
    round_idx: jnp.ndarray  # scalar
    epoch: jnp.ndarray  # scalar


def state_shardings(mesh: Mesh) -> EngineState:
    def sh(*spec) -> NamedSharding:
        return NamedSharding(mesh, P(*spec))

    return EngineState(  # expect: missing-partition-spec
        alive=sh(NODE_AXIS),
        round_idx=sh(),  # expect: missing-partition-spec
        epoch=sh(),  # replicated-ok: round-counter scalar
        ghost=sh(NODE_AXIS),  # expect: missing-partition-spec
    )
