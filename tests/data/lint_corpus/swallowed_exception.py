"""Seeded defect: a broad catch that silently eats every failure.

``except Exception`` with no re-raise and no justification turns any
crash into a silent no-op — the wedge-over-crash failure mode. The
``# expect:`` marker drives tests/test_staticcheck.py.
"""


class Guard:
    def risky(self):
        raise RuntimeError("boom")

    def swallows(self):
        try:
            self.risky()
        except Exception:  # expect: swallowed-exception
            pass

    def justified(self):
        try:
            self.risky()
        except Exception:  # noqa: BLE001 — demo fault-isolation boundary
            pass

    def cleanup_and_reraise(self):
        try:
            self.risky()
        except Exception:
            self.rollback()
            raise

    def rollback(self):
        pass
