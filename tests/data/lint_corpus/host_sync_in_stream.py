"""Lint corpus: blocking reads inside the streaming pipeline.

In a serving module EVERY device->host read is a pipeline stall — JAX async
dispatch only overlaps host work with device compute while the host never
blocks — so each spelling below is a finding anywhere in the module (not
just inside traced functions), unless it is a declared fetch boundary
(``# host-sync-ok: <reason>``).
"""

import numpy as np

import jax
import jax.numpy as jnp


class MiniDriver:
    def __init__(self, target):
        self.target = target
        self.pending = []
        # Casts of HOST values are not fetches — the checker resolves the
        # call inside the cast, so a numpy rng draw stays clean.
        self.budget = int(np.random.default_rng(0).poisson(2.0))

    def submit(self, wave):
        events = self.target.stream_step(wave)
        # Probing the ticket by VALUE forces the fetch the pipeline exists
        # to avoid — a stall on every submit.
        done = bool(events.decided.item())  # expect: host-sync-in-stream
        self.pending.append((wave, events.decided, done))

    def progress(self):
        # Peeking at device state mid-stream is an undeclared fetch —
        # in EITHER numpy spelling (array copies, asarray may alias; both
        # materialize the device buffer on host).
        host_view = np.asarray(self.target.state.alive)  # expect: host-sync-in-stream
        host_copy = np.array(self.target.state.seen_down)  # expect: host-sync-in-stream
        fetched = jax.device_get(host_view)  # expect: host-sync-in-stream
        fetched = fetched + host_copy.sum()
        # The scalar-fetch CAST spelling — the one the pipeline's own
        # drain fetch uses — blocks just the same.
        epoch = int(jnp.sum(self.target.state.config_epoch))  # expect: host-sync-in-stream
        return fetched.sum() + epoch

    def drain(self):
        for _wave, ticket, _done in self.pending:
            jax.block_until_ready(ticket)  # host-sync-ok: declared drain boundary
        last = self.pending[-1][1] if self.pending else None
        if last is not None:
            last.block_until_ready()  # expect: host-sync-in-stream
        total = int(jnp.sum(self.target.state.config_epoch))  # host-sync-ok: the one drain-time epoch fetch
        self.pending.clear()
        return total
