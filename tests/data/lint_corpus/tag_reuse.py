"""Seeded defect: two union members assigned the same wire tag.

A reused tag makes the decoder route one type's frames into the other's
field layout — a silent wire-format corruption the type system never
sees. The ``# expect:`` marker drives tests/test_staticcheck.py.
"""

from dataclasses import dataclass
from typing import Dict, Type, Union


@dataclass(frozen=True)
class Ping:
    sender: str


@dataclass(frozen=True)
class Pong:
    sender: str


RapidRequest = Union[Ping, Pong]

_REQUEST_TAGS: Dict[Type, int] = {
    Ping: 1,
    Pong: 1,  # expect: tag-reuse
}
