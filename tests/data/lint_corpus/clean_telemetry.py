"""Corpus: disciplined telemetry-lane fetches — every host materialization
of the lanes sits at a declared boundary and carries the marker. Host
reads of NON-lane values prove the checker does not overreach."""

import numpy as np

from rapid_tpu.models.virtual_cluster import telemetry_digest
from rapid_tpu.tenancy.fleet import fleet_telemetry_digest


class MiniFleet:
    def __init__(self, telem, state):
        self.telem = telem
        self.state = state
        self._activity = None

    def sync(self):
        # telemetry-fetch-ok: sync barrier — the driver is already paying a
        # blocking device round trip here.
        digest = np.asarray(telemetry_digest(self.telem))
        self._activity = digest
        return digest

    def health_scan(self):
        # telemetry-fetch-ok: health sweep boundary (already blocking).
        per_tenant = np.asarray(fleet_telemetry_digest(self.telem))
        return per_tenant.sum(axis=0)

    def snapshot(self):
        # Reads of the HOST-side cache are free — no marker needed.
        cached = self._activity
        # Materializing non-lane state is the sharding family's business,
        # not this family's: no lane reference, no finding here.
        alive = np.asarray(self.state.alive)
        return cached, alive.sum()
