"""Lint corpus: round-trace ring fetches outside declared boundaries.

The trace ring is the telemetry plane's flight recorder: write-only
inside the round bodies, decoded on host ONLY at the same sync seams the
lane digests use. Calling a trace digest jit — or spelling the fetch
directly via numpy / device_get over the ring — without a
``# telemetry-fetch-ok: <why>`` marker is a blocking round trip smuggled
onto a hot path, exactly like an unmarked lane fetch.
"""

import numpy as np

import jax

from rapid_tpu.models.virtual_cluster import trace_digest
from rapid_tpu.tenancy.fleet import fleet_trace_digest


class MiniRecorder:
    def __init__(self, trace_ring):
        self.trace_ring = trace_ring
        self._summary = None

    def dispatch(self, wave):
        # Decoding the ring per dispatched wave defeats the recorder's
        # whole design — the digest belongs at the drain/sync seam only.
        digest = np.asarray(trace_digest(self.trace_ring))  # expect: telemetry-unmarked-fetch
        return digest[0] + wave

    def scan(self):
        per_tenant = fleet_trace_digest(self.trace_ring)  # expect: telemetry-unmarked-fetch
        return per_tenant

    def peek(self):
        # The direct spellings block just the same as the digest jits.
        cursor = np.array(self.trace_ring.tr_cursor)  # expect: telemetry-unmarked-fetch
        ring = jax.device_get(self.trace_ring)  # expect: telemetry-unmarked-fetch
        return cursor, ring

    def sync(self):
        # telemetry-fetch-ok: host-sync boundary — the caller is already
        # paying a blocking device round trip here.
        digest = np.asarray(trace_digest(self.trace_ring))
        self._summary = digest
        return digest
