"""Lint corpus: device->host syncs inside the traced convergence seams.

Every spelling of the round-trip the fused-dispatch design exists to avoid,
inside a ``*_impl`` function and the while-loop body it hands to lax: each
one is a full tunnel RTT per round on a remote backend.
"""

import numpy as np

import jax
import jax.numpy as jnp


def convergence_impl(state, max_steps):
    def cond(carry):
        return carry[1] < max_steps

    def body(carry):
        x, i = carry
        val = float(jnp.sum(x))  # expect: host-sync-in-hot-path
        host = np.asarray(x)  # expect: host-sync-in-hot-path
        x.block_until_ready()  # expect: host-sync-in-hot-path
        n = jnp.sum(x).item()  # expect: host-sync-in-hot-path
        fetched = jax.device_get(x)  # expect: host-sync-in-hot-path
        return x + val + host.mean() + n + fetched[0], i + 1

    out, _ = jax.lax.while_loop(cond, body, (state, jnp.int32(0)))
    return out
