"""Clean counterpart for the dispatch analyzer: zero findings.

Exercises the shapes the analysis must NOT convict: dispatch through a
module-level tuple alias (the CONSENSUS_TYPES idiom), a helper resolved
through its return annotation, a deliberate exemption declared with
``# dispatched-elsewhere``, and a sync sub-dispatcher that is partial by
design (exhaustiveness binds only the async transport-facing entry).
"""

from dataclasses import dataclass
from typing import Union


@dataclass(frozen=True)
class Ping:
    sender: str


@dataclass(frozen=True)
class VoteA:
    sender: str


@dataclass(frozen=True)
class VoteB:
    sender: str


@dataclass(frozen=True)
class Relay:
    payload: bytes


@dataclass(frozen=True)
class Ack:
    pass


@dataclass(frozen=True)
class VoteAck:
    pass


RapidRequest = Union[Ping, VoteA, VoteB, Relay]
RapidResponse = Union[Ack, VoteAck]

VOTE_TYPES = (VoteA, VoteB)


class MiniService:
    # dispatched-elsewhere: Relay — unwrapped by the relay facade before
    # this service ever sees the envelope.
    async def handle_message(self, request):
        if isinstance(request, Ping):
            return self._handle_ping(request)
        if isinstance(request, VOTE_TYPES):
            return self._votes.handle_message(request)
        raise TypeError(f"unidentified request type {type(request)!r}")

    def _handle_ping(self, request) -> Ack:
        return Ack()


class VoteBox:
    """Sync sub-dispatcher: routes only the vote subset (partial by
    design, like FastPaxos.handle_message)."""

    def handle_message(self, request):
        if isinstance(request, VoteA):
            self._tally_a(request)
        elif isinstance(request, VoteB):
            self._tally_b(request)
        else:
            raise TypeError(f"unexpected vote message {type(request)!r}")
        return VoteAck()

    def _tally_a(self, request):
        pass

    def _tally_b(self, request):
        pass
