"""Property-based fuzzing (hypothesis): codec round-trips for arbitrary
message contents, watermark-kernel equivalence, and XXH64 native parity."""

import numpy as np
import pytest

pytest.importorskip("hypothesis")  # property tests need it; the rest of the suite doesn't
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from rapid_tpu.messaging.codec import (
    decode_request,
    decode_response,
    encode_request,
    encode_response,
)
from rapid_tpu.ops.pallas_kernels import (
    bits_to_reports_matrix,
    watermark_merge_classify,
)
from rapid_tpu.types import (
    AlertMessage,
    BatchedAlertMessage,
    EdgeStatus,
    Endpoint,
    JoinMessage,
    JoinResponse,
    JoinStatusCode,
    NodeId,
    Phase1bMessage,
    Rank,
)
from rapid_tpu.utils.xxhash import xxh64

endpoints = st.builds(
    Endpoint,
    hostname=st.text(min_size=0, max_size=64),
    port=st.integers(min_value=0, max_value=65535),
)
node_ids = st.builds(
    NodeId,
    high=st.integers(min_value=0, max_value=2**64 - 1),
    low=st.integers(min_value=0, max_value=2**64 - 1),
)
metadata = st.lists(
    st.tuples(st.text(max_size=16), st.binary(max_size=32)), max_size=4
).map(tuple)
config_ids = st.integers(min_value=-(2**63), max_value=2**63 - 1)
ring_lists = st.lists(st.integers(min_value=0, max_value=31), max_size=10).map(tuple)

alerts = st.builds(
    AlertMessage,
    edge_src=endpoints,
    edge_dst=endpoints,
    edge_status=st.sampled_from(list(EdgeStatus)),
    configuration_id=config_ids,
    ring_numbers=ring_lists,
    node_id=st.none() | node_ids,
    metadata=metadata,
)


@settings(max_examples=200, deadline=None)
@given(
    st.one_of(
        st.builds(
            JoinMessage,
            sender=endpoints,
            node_id=node_ids,
            ring_numbers=ring_lists,
            configuration_id=config_ids,
            metadata=metadata,
        ),
        st.builds(
            BatchedAlertMessage,
            sender=endpoints,
            messages=st.lists(alerts, max_size=5).map(tuple),
        ),
        st.builds(
            Phase1bMessage,
            sender=endpoints,
            configuration_id=config_ids,
            rnd=st.builds(Rank, round=st.integers(0, 2**31 - 1), node_index=st.integers(0, 2**31 - 1)),
            vrnd=st.builds(Rank, round=st.integers(0, 2**31 - 1), node_index=st.integers(0, 2**31 - 1)),
            vval=st.lists(endpoints, max_size=4).map(tuple),
        ),
    )
)
def test_request_codec_roundtrip_fuzz(request_msg):
    assert decode_request(encode_request(request_msg)) == request_msg


@settings(max_examples=100, deadline=None)
@given(
    st.builds(
        JoinResponse,
        sender=endpoints,
        status_code=st.sampled_from(list(JoinStatusCode)),
        configuration_id=config_ids,
        endpoints=st.lists(endpoints, max_size=5).map(tuple),
        identifiers=st.lists(node_ids, max_size=5).map(tuple),
        metadata_keys=st.lists(endpoints, max_size=3).map(tuple),
        metadata_values=st.lists(metadata, max_size=3).map(tuple),
    )
)
def test_join_response_codec_roundtrip_fuzz(response_msg):
    assert decode_response(encode_response(response_msg)) == response_msg


@settings(max_examples=50, deadline=None)
@given(
    st.integers(min_value=0, max_value=2**32 - 1),
    st.data(),
)
def test_watermark_classify_fuzz(seed, data):
    k, h, l = 10, data.draw(st.integers(4, 10)), data.draw(st.integers(1, 3))
    rng = np.random.default_rng(seed)
    n = 256
    old = rng.integers(0, 1 << k, size=n, dtype=np.uint32)
    new = rng.integers(0, 1 << k, size=n, dtype=np.uint32)
    mask = rng.random(n) < 0.8
    merged, cls = watermark_merge_classify(
        jnp.asarray(old), jnp.asarray(new), jnp.asarray(mask), h, l
    )
    dense = np.asarray(bits_to_reports_matrix(merged, k))
    tally = dense.sum(axis=1)
    expected = np.where(tally >= h, 2, np.where((tally >= l) & (tally < h), 1, 0))
    np.testing.assert_array_equal(np.asarray(cls), expected)
    # Merged bits are exactly (old | new) & mask.
    np.testing.assert_array_equal(np.asarray(merged), np.where(mask, old | new, 0))


@settings(max_examples=200, deadline=None)
@given(st.binary(max_size=128), st.integers(min_value=0, max_value=2**64 - 1))
def test_native_xxh64_parity_fuzz(data, seed):
    from rapid_tpu.utils._native import native_xxh64

    native = native_xxh64(data, seed)
    if native is not None:
        assert native == xxh64(data, seed)
