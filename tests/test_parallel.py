"""Sharded-engine equivalence: the same protocol run over an 8-device mesh
must produce bit-identical membership outcomes to the single-device engine."""

import numpy as np

import jax

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.parallel.mesh import (
    make_mesh,
    make_sharded_step,
    shard_faults,
    shard_state,
)


def run_single(n, victims, steps):
    vc = VirtualCluster.create(n, fd_threshold=2, seed=0)
    vc.crash(victims)
    decided_at = None
    for i in range(steps):
        events = vc.step()
        if bool(events.decided) and decided_at is None:
            decided_at = i
    return vc, decided_at


def run_sharded(step, state, faults, steps):
    """Drive a sharded step for `steps` rounds; (state, first decided round)."""
    decided_at = None
    for i in range(steps):
        state, events = step(state, faults)
        if bool(events.decided) and decided_at is None:
            decided_at = i
    return state, decided_at


def assert_equivalent(state, single):
    """Sharded outcome must be bit-identical to the single-device run."""
    np.testing.assert_array_equal(np.asarray(state.alive), single.alive_mask)
    assert int(state.n_members) == single.membership_size
    assert int(state.config_hi) == int(single.state.config_hi)
    assert int(state.config_lo) == int(single.state.config_lo)
    np.testing.assert_array_equal(
        np.asarray(state.obs_idx), np.asarray(single.state.obs_idx)
    )


def test_mesh_has_eight_devices():
    assert len(jax.devices()) == 8


def test_sharded_engine_matches_single_device():
    n, steps = 256, 6
    victims = [3, 77, 130]

    single, decided_single = run_single(n, victims, steps)

    vc = VirtualCluster.create(n, fd_threshold=2, seed=0)
    vc.crash(victims)
    mesh = make_mesh()
    step = make_sharded_step(vc.cfg, mesh)
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)
    state, decided_sharded = run_sharded(step, state, faults, steps)

    assert decided_sharded == decided_single
    assert_equivalent(state, single)


def test_sharded_state_is_actually_distributed():
    vc = VirtualCluster.create(64, fd_threshold=2, seed=1)
    mesh = make_mesh()
    state = shard_state(vc.state, mesh)
    sharding = state.vote_hi.sharding
    assert sharding.num_devices == 8
    # The N axis is partitioned, not replicated.
    assert not sharding.is_fully_replicated


def test_round_body_collectives_are_reductions_only():
    """Communication economics, checked against the COMPILED artifact: in
    the sharded convergence program, the hot loop's unconditional
    collectives are psum-class all-reduces only, and nothing [c,n]-sized
    moves outside a lax.cond branch (implicit invalidation / classic attempt
    / view-change topology rebuild). Bit-identical outputs prove correctness; this
    pins the cost model (parallel/mesh.py's docstring claim, VERDICT r2
    missing #4). Full-size table: tools/collective_audit.py ->
    evidence/round3/collective_audit.json."""
    import jax

    from rapid_tpu.models.virtual_cluster import run_to_decision_impl
    from rapid_tpu.parallel.audit import (
        audit_collectives,
        collective_violations,
    )
    from rapid_tpu.parallel.mesh import fault_shardings, state_shardings

    n_slots, cohorts = 1024, 64
    vc = VirtualCluster.create(
        n_slots - 8, n_slots=n_slots, fd_threshold=2, cohorts=cohorts,
        delivery_spread=2, seed=0,
    )
    vc.assign_cohorts_roundrobin()
    mesh = make_mesh()
    cfg = vc.cfg
    conv = jax.jit(
        lambda s, f: run_to_decision_impl(cfg, s, f, 96),
        in_shardings=(state_shardings(mesh), fault_shardings(mesh)),
    )
    txt = conv.lower(
        shard_state(vc.state, mesh), shard_faults(vc.faults, mesh)
    ).compile().as_text()
    rows = audit_collectives(txt, n_slots, cohorts)

    assert rows, "no collectives found — sharding did not partition N"
    hot = [r for r in rows if r["location"] == "hot-loop"]
    assert hot, "no hot-loop collectives — while-loop attribution broke"
    violations = collective_violations(rows)
    assert not violations["hot_loop_non_reduce"], violations
    assert not violations["unconditional_cn_anywhere"], violations
    # The hoisted [n]-scale edge gathers sit in the prologue, by design.
    assert any(
        r["location"] == "prologue" and r["kind"] == "all-gather" for r in rows
    )


def test_sharded_convergence_parity_at_10k():
    """N >= 10K churn through the single-dispatch convergence loop, sharded
    vs single-device: identical ROUND COUNTS and bit-identical outcomes
    (VERDICT r2 next-round #3's parity half)."""
    import jax

    from rapid_tpu.models.virtual_cluster import run_to_decision_impl
    from rapid_tpu.parallel.mesh import fault_shardings, state_shardings

    n_slots = 10_240
    n_members = n_slots - 256
    joiners = np.arange(n_members, n_slots)

    def build():
        vc = VirtualCluster.create(
            n_members, n_slots=n_slots, fd_threshold=2, cohorts=64,
            delivery_spread=2, seed=3,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash(np.random.default_rng(3).choice(n_members, 100, replace=False))
        vc.inject_join_wave(joiners)
        return vc

    single = build()
    rounds_single, decided_single, _, members_single = single.run_to_decision()

    vc = build()
    mesh = make_mesh()
    cfg = vc.cfg
    conv = jax.jit(
        lambda s, f: run_to_decision_impl(cfg, s, f, 64),
        in_shardings=(state_shardings(mesh), fault_shardings(mesh)),
    )
    state, steps, decided, _ = conv(
        shard_state(vc.state, mesh), shard_faults(vc.faults, mesh)
    )

    assert decided_single and bool(decided)
    assert int(steps) == rounds_single, (int(steps), rounds_single)
    assert int(state.n_members) == members_single
    assert_equivalent(state, single)


def test_sharded_whole_wave_loop_matches_single_device():
    """The multi-cut whole-wave loop (run_until_membership) under the mesh:
    a churn that resolves through MULTIPLE sharded view changes in one
    dispatch must match the single-device fused loop exactly — rounds,
    cuts, per-cut sizes, final state."""
    import jax.numpy as jnp

    from rapid_tpu.parallel.mesh import make_sharded_wave

    def build():
        vc = VirtualCluster.create(
            60, n_slots=72, cohorts=16, fd_threshold=2, seed=11,
            delivery_spread=1,
        )
        vc.assign_cohorts_roundrobin()
        vc.crash([7, 31])
        # Staggered detection pushes the crash cut BEHIND the join cut, so
        # the wave must commit >= 2 sharded view changes in one dispatch.
        vc.stagger_fd_counts(np.random.default_rng(5), spread_rounds=8)
        vc.inject_join_wave(list(range(60, 72)))
        return vc

    single = build()
    r1, c1, resolved1, sizes1 = single.run_until_membership(70, min_cuts=1)
    assert resolved1 and c1 >= 2  # the scenario genuinely multi-cuts

    vc = build()
    mesh = make_mesh()
    wave = make_sharded_wave(vc.cfg, mesh, max_cuts=8)
    state, steps, cuts, resolved, sizes = wave(
        shard_state(vc.state, mesh), shard_faults(vc.faults, mesh),
        jnp.int32(70), jnp.int32(192), jnp.int32(1),
    )
    assert bool(resolved)
    assert (int(steps), int(cuts)) == (r1, c1)
    assert tuple(np.asarray(sizes)[: int(cuts)].tolist()) == sizes1
    assert int(state.n_members) == 70
    assert_equivalent(state, single)


def test_sharded_join_wave_matches_single_device():
    """The JOIN path under a mesh: inject_join_wave's device-side
    gather/scatter (ring-predecessor lookup, obs_idx/fd columns) runs on
    already-sharded arrays, and the admitted configuration must be
    bit-identical to the single-device run."""
    n_members, n_slots, steps = 192, 256, 8
    joiners = np.arange(n_members, n_members + 48)

    def build():
        vc = VirtualCluster.create(
            n_members, n_slots=n_slots, fd_threshold=2, seed=0,
            delivery_spread=1,
        )
        return vc

    single = build()
    single.inject_join_wave(joiners)
    decided_single = None
    for i in range(steps):
        events = single.step()
        if bool(events.decided) and decided_single is None:
            decided_single = i

    vc = build()
    mesh = make_mesh()
    # Shard FIRST, inject after: the wave's gathers/scatters must work on
    # sharded device arrays, which is the deployment order (state lives on
    # the mesh; joiners arrive later).
    vc.state = shard_state(vc.state, mesh)
    vc.faults = shard_faults(vc.faults, mesh)
    vc.inject_join_wave(joiners)
    step = make_sharded_step(vc.cfg, mesh)
    state, decided_sharded = run_sharded(step, vc.state, vc.faults, steps)

    assert decided_single is not None
    assert decided_sharded == decided_single
    assert single.membership_size == n_members + 48
    assert_equivalent(state, single)
