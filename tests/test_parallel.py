"""Sharded-engine equivalence: the same protocol run over an 8-device mesh
must produce bit-identical membership outcomes to the single-device engine."""

import numpy as np

import jax

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.parallel.mesh import (
    make_mesh,
    make_sharded_step,
    shard_faults,
    shard_state,
)


def run_single(n, victims, steps):
    vc = VirtualCluster.create(n, fd_threshold=2, seed=0)
    vc.crash(victims)
    decided_at = None
    for i in range(steps):
        events = vc.step()
        if bool(events.decided) and decided_at is None:
            decided_at = i
    return vc, decided_at


def test_mesh_has_eight_devices():
    assert len(jax.devices()) == 8


def test_sharded_engine_matches_single_device():
    n, steps = 256, 6
    victims = [3, 77, 130]

    single, decided_single = run_single(n, victims, steps)

    vc = VirtualCluster.create(n, fd_threshold=2, seed=0)
    vc.crash(victims)
    mesh = make_mesh()
    step = make_sharded_step(vc.cfg, mesh)
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)
    decided_sharded = None
    for i in range(steps):
        state, events = step(state, faults)
        if bool(events.decided) and decided_sharded is None:
            decided_sharded = i

    assert decided_sharded == decided_single
    np.testing.assert_array_equal(np.asarray(state.alive), single.alive_mask)
    assert int(state.n_members) == single.membership_size
    assert int(state.config_hi) == int(single.state.config_hi)
    assert int(state.config_lo) == int(single.state.config_lo)
    # Topology identical across the mesh boundary.
    np.testing.assert_array_equal(np.asarray(state.obs_idx), np.asarray(single.state.obs_idx))


def test_sharded_state_is_actually_distributed():
    vc = VirtualCluster.create(64, fd_threshold=2, seed=1)
    mesh = make_mesh()
    state = shard_state(vc.state, mesh)
    sharding = state.vote_hi.sharding
    assert sharding.num_devices == 8
    # The N axis is partitioned, not replicated.
    assert not sharding.is_fully_replicated
