"""Sharded-engine equivalence: the same protocol run over an 8-device mesh
must produce bit-identical membership outcomes to the single-device engine."""

import numpy as np

import jax

from rapid_tpu.models.virtual_cluster import VirtualCluster
from rapid_tpu.parallel.mesh import (
    make_mesh,
    make_sharded_step,
    shard_faults,
    shard_state,
)


def run_single(n, victims, steps):
    vc = VirtualCluster.create(n, fd_threshold=2, seed=0)
    vc.crash(victims)
    decided_at = None
    for i in range(steps):
        events = vc.step()
        if bool(events.decided) and decided_at is None:
            decided_at = i
    return vc, decided_at


def run_sharded(step, state, faults, steps):
    """Drive a sharded step for `steps` rounds; (state, first decided round)."""
    decided_at = None
    for i in range(steps):
        state, events = step(state, faults)
        if bool(events.decided) and decided_at is None:
            decided_at = i
    return state, decided_at


def assert_equivalent(state, single):
    """Sharded outcome must be bit-identical to the single-device run."""
    np.testing.assert_array_equal(np.asarray(state.alive), single.alive_mask)
    assert int(state.n_members) == single.membership_size
    assert int(state.config_hi) == int(single.state.config_hi)
    assert int(state.config_lo) == int(single.state.config_lo)
    np.testing.assert_array_equal(
        np.asarray(state.obs_idx), np.asarray(single.state.obs_idx)
    )


def test_mesh_has_eight_devices():
    assert len(jax.devices()) == 8


def test_sharded_engine_matches_single_device():
    n, steps = 256, 6
    victims = [3, 77, 130]

    single, decided_single = run_single(n, victims, steps)

    vc = VirtualCluster.create(n, fd_threshold=2, seed=0)
    vc.crash(victims)
    mesh = make_mesh()
    step = make_sharded_step(vc.cfg, mesh)
    state = shard_state(vc.state, mesh)
    faults = shard_faults(vc.faults, mesh)
    state, decided_sharded = run_sharded(step, state, faults, steps)

    assert decided_sharded == decided_single
    assert_equivalent(state, single)


def test_sharded_state_is_actually_distributed():
    vc = VirtualCluster.create(64, fd_threshold=2, seed=1)
    mesh = make_mesh()
    state = shard_state(vc.state, mesh)
    sharding = state.vote_hi.sharding
    assert sharding.num_devices == 8
    # The N axis is partitioned, not replicated.
    assert not sharding.is_fully_replicated


def test_sharded_join_wave_matches_single_device():
    """The JOIN path under a mesh: inject_join_wave's device-side
    gather/scatter (ring-predecessor lookup, obs_idx/fd columns) runs on
    already-sharded arrays, and the admitted configuration must be
    bit-identical to the single-device run."""
    n_members, n_slots, steps = 192, 256, 8
    joiners = np.arange(n_members, n_members + 48)

    def build():
        vc = VirtualCluster.create(
            n_members, n_slots=n_slots, fd_threshold=2, seed=0,
            delivery_spread=1,
        )
        return vc

    single = build()
    single.inject_join_wave(joiners)
    decided_single = None
    for i in range(steps):
        events = single.step()
        if bool(events.decided) and decided_single is None:
            decided_single = i

    vc = build()
    mesh = make_mesh()
    # Shard FIRST, inject after: the wave's gathers/scatters must work on
    # sharded device arrays, which is the deployment order (state lives on
    # the mesh; joiners arrive later).
    vc.state = shard_state(vc.state, mesh)
    vc.faults = shard_faults(vc.faults, mesh)
    vc.inject_join_wave(joiners)
    step = make_sharded_step(vc.cfg, mesh)
    state, decided_sharded = run_sharded(step, vc.state, vc.faults, steps)

    assert decided_single is not None
    assert decided_sharded == decided_single
    assert single.membership_size == n_members + 48
    assert_equivalent(state, single)
