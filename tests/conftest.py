"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths execute
without TPU hardware (the driver separately dry-runs the multichip path).
Environment must be set before jax is first imported.
"""

import os

# Force (override) CPU: the global environment pins JAX_PLATFORMS=axon (the
# real TPU tunnel), which tests must not depend on.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# sitecustomize.py (from /root/.axon_site on PYTHONPATH) imports jax at
# interpreter startup, so jax.config captured JAX_PLATFORMS=axon before this
# file ran; override the live config too.
import jax

jax.config.update("jax_platforms", "cpu")


# Build the native host library once per test session (load-only at runtime).
from rapid_tpu.utils._native import ensure_built

ensure_built()
