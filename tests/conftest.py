"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths execute
without TPU hardware (the driver separately dry-runs the multichip path).
Environment must be set before jax is first imported.
"""

# Force (override) CPU: the global environment pins JAX_PLATFORMS=axon (the
# real TPU tunnel), which tests must not depend on.
from rapid_tpu.utils.platform import force_platform

# Not an assert: python -O would strip it, silently leaving tests on the
# accelerator tunnel.
if not force_platform("cpu", n_host_devices=8):
    raise RuntimeError(
        "could not force the CPU platform: a jax backend was initialized "
        "before tests/conftest.py ran; tests must not touch the axon tunnel"
    )


# Build the native host library once per test session (load-only at runtime).
from rapid_tpu.utils._native import ensure_built

ensure_built()
