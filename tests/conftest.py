"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths execute
without TPU hardware (the driver separately dry-runs the multichip path).
Environment must be set before jax is first imported.
"""

# Force (override) CPU: the global environment pins JAX_PLATFORMS=axon (the
# real TPU tunnel), which tests must not depend on. Accelerator capture
# sessions opt out explicitly (RAPID_TPU_TEST_PLATFORM=tpu) to run the
# TPU-gated tests (e.g. the Mosaic-vs-jnp equivalence check) on real
# hardware.
import os

from rapid_tpu.utils.platform import force_platform

_plat = os.environ.get("RAPID_TPU_TEST_PLATFORM", "cpu")
if _plat not in ("cpu", "tpu"):
    # A typo must not silently route the whole suite onto the live tunnel.
    raise RuntimeError(
        f"RAPID_TPU_TEST_PLATFORM={_plat!r}: expected 'cpu' (default) or "
        "'tpu' (accelerator capture sessions)"
    )
if _plat == "cpu":
    # Not an assert: python -O would strip it, silently leaving tests on the
    # accelerator tunnel.
    if not force_platform("cpu", n_host_devices=8):
        raise RuntimeError(
            "could not force the CPU platform: a jax backend was initialized "
            "before tests/conftest.py ran; tests must not touch the axon tunnel"
        )


# Build the native host library once per test session (load-only at runtime).
from rapid_tpu.utils._native import ensure_built

ensure_built()


# Property-test budget dial: HYPOTHESIS_PROFILE=thorough multiplies every
# property/fuzz test's example budget 5x (nightly / pre-release depth).
# Hypothesis profiles can't do this (per-test @settings decorators take
# precedence over a loaded profile), so the dial scales each collected
# test's decorator settings instead — the attachment point hypothesis
# reads at call time. Default runs keep the committed per-test budgets.
# Gated: a container without hypothesis must still run the non-property
# suite (the property/fuzz modules fail collection individually under
# --continue-on-collection-errors; an unconditional import here would take
# the whole session down with them).
try:
    import hypothesis
except ImportError:  # pragma: no cover - environment-dependent
    hypothesis = None

if hypothesis is not None and os.environ.get("HYPOTHESIS_PROFILE") == "thorough":

    def pytest_collection_modifyitems(items):
        scaled = set()  # parametrized items share one function: scale ONCE
        for item in items:
            fn = getattr(item, "function", None)
            spec = getattr(fn, "_hypothesis_internal_use_settings", None)
            if spec is not None and id(fn) not in scaled:
                scaled.add(id(fn))
                fn._hypothesis_internal_use_settings = hypothesis.settings(
                    spec, max_examples=spec.max_examples * 5
                )
        # The attachment point is a hypothesis-private attribute: if an
        # upgrade renames it, every spec lookup above returns None and the
        # dial silently becomes a 1x no-op. Fail fast instead — unless the
        # selected subset genuinely contains no property tests.
        has_hypothesis_items = any(
            getattr(item, "function", None) is not None
            and getattr(item.function, "hypothesis", None) is not None
            for item in items
        )
        if has_hypothesis_items and not scaled:
            raise RuntimeError(
                "HYPOTHESIS_PROFILE=thorough scaled zero tests although "
                "hypothesis-driven items were collected: the "
                "_hypothesis_internal_use_settings attachment point has "
                "moved; update the dial in tests/conftest.py"
            )
