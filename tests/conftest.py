"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths execute
without TPU hardware (the driver separately dry-runs the multichip path).
Environment must be set before jax is first imported.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
