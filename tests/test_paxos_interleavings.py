"""Adversarial message-interleaving safety fuzzer for classic Paxos.

The engine resolves a classic attempt inside one round
(``models/virtual_cluster.py`` ``classic_attempt``: phase1a→1b→2a→2b with
in-attempt rank ordering), so cross-attempt interleavings — a phase2a from
round r arriving while acceptors are already promising round r+2, a stale
phase1b resurfacing after three escalations, duplicated deliveries — can
occur only on the host stack (``protocol/paxos.py``), and they occur MORE
now that the fallback escalates rounds until decided (``fast_paxos.py``).
The scenario oracle (test_oracle_parity.py) compares outcomes of full
schedules; this fuzzer attacks the message layer directly: a seeded
adversarial scheduler that reorders, delays, duplicates, and drops
individual consensus messages across many escalating rounds, checking the
one invariant no interleaving may break — agreement: two nodes never decide
different values. (Liveness under the adversary is not asserted: an
adversary that drops everything trivially prevents decisions; seeds that do
decide must decide consistently, and the chosen value must be one that was
actually proposed. Validity + agreement ≙ PaxosTests.java:72-191's
drop-the-fast-round recovery family, generalized over delivery schedules.)
"""

import random

import pytest

from rapid_tpu.protocol.fast_paxos import FastPaxos, fast_paxos_quorum
from rapid_tpu.types import Endpoint, RapidRequest
from rapid_tpu.utils.clock import ManualClock


def ep(i: int) -> Endpoint:
    return Endpoint("10.5.0.1", 9000 + i)


class AdversarialNetwork:
    """Central message pool with a seeded adversarial scheduler: every
    broadcast/send enqueues (target, message) pairs; delivery order is a
    random permutation draw, messages may be duplicated (redelivery) or
    dropped, and the pool persists across liveness ticks so stale-round
    traffic interleaves with escalated rounds."""

    def __init__(self, rng: random.Random, n: int, drop_p: float, dup_p: float):
        self.rng = rng
        self.n = n
        self.pool = []  # list of (target_index, message)
        self.nodes = []  # FastPaxos instances, filled by the test
        self.drop_p = drop_p
        self.dup_p = dup_p

    def broadcast_from(self, message: RapidRequest) -> None:
        for target in range(self.n):
            self._enqueue(target, message)

    def send(self, remote: Endpoint, message: RapidRequest) -> None:
        self._enqueue(remote.port - 9000, message)

    def _enqueue(self, target: int, message: RapidRequest) -> None:
        if self.rng.random() < self.drop_p:
            return
        self.pool.append((target, message))
        if self.rng.random() < self.dup_p:
            self.pool.append((target, message))

    def deliver_some(self, max_messages: int) -> int:
        """Deliver up to max_messages pool entries in adversarial order."""
        delivered = 0
        while self.pool and delivered < max_messages:
            idx = self.rng.randrange(len(self.pool))
            target, message = self.pool.pop(idx)
            self.nodes[target].handle_message(message)
            delivered += 1
        return delivered


def run_adversarial_schedule(seed: int, n: int = 5, drop_p: float = 0.15,
                             dup_p: float = 0.2):
    """One fuzzed run; returns (decisions per node, proposals)."""
    rng = random.Random(seed)
    clock = ManualClock()
    net = AdversarialNetwork(rng, n, drop_p, dup_p)
    decisions = {}

    def on_decide_for(i):
        def on_decide(value):
            decisions[i] = tuple(value)
        return on_decide

    nodes = []
    for i in range(n):
        fp = FastPaxos(
            my_addr=ep(i), configuration_id=77, membership_size=n,
            broadcast_fn=net.broadcast_from, send_fn=net.send,
            on_decide=on_decide_for(i), clock=clock,
            consensus_fallback_base_delay_ms=100, rng=random.Random(seed + i),
        )
        nodes.append(fp)
    net.nodes = nodes

    # Contested fast round: nodes vote for one of two proposals, split so
    # that neither reaches the fast quorum — every decision must come from
    # classic rounds racing under the adversary.
    proposals = [(ep(100),), (ep(100), ep(101))]
    quorum = fast_paxos_quorum(n)
    split = min(quorum - 1, n - 1)
    for i, fp in enumerate(nodes):
        fp.propose(proposals[0 if i < split else 1],
                   recovery_delay_ms=50 + rng.random() * 200)

    # Interleave clock ticks (escalating rounds at every undecided node)
    # with adversarial deliveries; the pool carries stale-round messages
    # forward into later rounds.
    for _ in range(400):
        clock.advance_ms(rng.choice([0, 10, 40, 150]))
        net.deliver_some(rng.randrange(1, 12))
        if len(decisions) == n:
            break
    # Final drain: deliver everything still pooled (dup/reorder included).
    while net.pool:
        net.deliver_some(len(net.pool))
    return decisions, proposals


@pytest.mark.parametrize("seed", range(40))
def test_agreement_under_adversarial_interleavings(seed):
    decisions, proposals = run_adversarial_schedule(seed)
    decided_values = set(decisions.values())
    # Agreement: no two nodes decide differently — regardless of how many
    # rounds raced, how stale the resurfacing messages were, or what got
    # duplicated or dropped.
    assert len(decided_values) <= 1, (
        f"seed {seed}: divergent decisions {decisions}"
    )
    # Validity: a decided value must be one of the actually-proposed cuts.
    if decided_values:
        assert decided_values <= set(map(tuple, proposals))


@pytest.mark.parametrize("seed", range(10))
def test_lossless_adversary_decides_and_agrees(seed):
    # With no drops the adversary can only reorder/duplicate/delay: every
    # node must eventually decide (the escalating fallback guarantees a
    # round completes once its messages all deliver), and identically.
    decisions, proposals = run_adversarial_schedule(seed, drop_p=0.0)
    assert len(decisions) == 5, f"seed {seed}: only {sorted(decisions)} decided"
    assert len(set(decisions.values())) == 1
    assert set(decisions.values()) <= set(map(tuple, proposals))
