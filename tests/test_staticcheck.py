"""Build gate for the resolution-tier static analysis (tools/staticcheck,
backed by the tools/analysis/ package).

Two halves, matching how the reference treats error-prone: the whole tree
must be finding-free (the gate), and the analyzer itself must demonstrably
catch the defect classes it claims — a gate that never bites is
indistinguishable from no gate. The seeded corpus under
tests/data/lint_corpus/ (one file per defect class, expectations embedded
as ``# expect: <check>`` markers) is the second half for the concurrency
and trace-safety families.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import pytest  # noqa: E402

import staticcheck  # noqa: E402

CORPUS = Path(__file__).resolve().parent / "data" / "lint_corpus"


def _undefined(src: str):
    return staticcheck.check_undefined_names(
        Path("fixture.py"), textwrap.dedent(src)
    )


def test_undefined_name_in_error_branch_is_caught():
    findings = _undefined(
        """
        import os

        def f(a):
            if a:
                return os.sep
            raise RuntimeError(mesage)  # typo: never executed by tests
        """
    )
    assert [f.check for f in findings] == ["undefined-name"]
    assert "mesage" in findings[0].message


def test_global_decl_assignment_binds_at_module_scope():
    findings = _undefined(
        """
        def setup(value):
            global _CACHE
            _CACHE = value

        def read():
            return _CACHE  # bound only via setup()'s global decl
        """
    )
    assert findings == []


def test_class_and_comprehension_scopes_resolve():
    findings = _undefined(
        """
        BASE = 2

        class C:
            x = BASE
            def m(self):
                return [BASE + i for i in range(self.x)]

        lam = lambda z: z + BASE
        """
    )
    assert findings == []


def test_star_import_is_flagged_not_skipped():
    findings = _undefined("from os.path import *\n")
    assert [f.check for f in findings] == ["star-import"]


def _caller_findings(tmp_path, monkeypatch, name: str, callee_src: str, caller_src: str):
    """Materialize a callee+caller module pair under a private root and run
    the call-conformance check on the caller."""
    (tmp_path / f"{name}_callee.py").write_text(textwrap.dedent(callee_src))
    caller = tmp_path / f"{name}_caller.py"
    caller.write_text(textwrap.dedent(caller_src))
    monkeypatch.setattr(staticcheck.core, "REPO", tmp_path)
    monkeypatch.syspath_prepend(str(tmp_path))
    return staticcheck.check_call_signatures(caller)


def test_wrong_kwarg_and_arity_are_caught(tmp_path, monkeypatch):
    findings = _caller_findings(
        tmp_path, monkeypatch, "sigs",
        """
        def encode(message, *, deadline_ms=100):
            return message, deadline_ms
        """,
        """
        import sigs_callee

        def ok():
            return sigs_callee.encode("m", deadline_ms=5)

        def typo():
            return sigs_callee.encode("m", deadlne_ms=5)

        def arity():
            return sigs_callee.encode("m", "extra")
        """,
    )
    assert [f.check for f in findings] == ["call-signature", "call-signature"]
    assert "deadlne_ms" in findings[0].message
    assert "too many positional" in findings[1].message


def test_stale_module_attribute_is_caught(tmp_path, monkeypatch):
    findings = _caller_findings(
        tmp_path, monkeypatch, "attr",
        "def current(): return 1\n",
        """
        import attr_callee

        def f():
            return attr_callee.renamed_away()
        """,
    )
    assert [f.check for f in findings] == ["missing-attribute"]
    assert "renamed_away" in findings[0].message


def test_shadowed_and_dynamic_call_sites_are_skipped(tmp_path, monkeypatch):
    findings = _caller_findings(
        tmp_path, monkeypatch, "shadow",
        "def g(a, b): return a + b\n",
        """
        import shadow_callee
        from shadow_callee import g

        def shadowed(g):
            return g(1, 2, 3, 4)  # parameter, not the module-level g

        def splat(args):
            return g(*args)  # dynamic shape: must not be judged

        def lam():
            return (lambda g: g(9, 9, 9))(len)

        def comp(items):
            return [g for g in items if g]
        """,
    )
    assert findings == []


def test_str_target_bindings_and_class_bodies_shadow(tmp_path, monkeypatch):
    # Bindings whose AST target is a plain string (except-as, match capture)
    # and class-body-level bindings must shadow module-level callables; each
    # of these produced a spurious build-failing finding before being
    # handled.
    findings = _caller_findings(
        tmp_path, monkeypatch, "strbind",
        "def handle(a, b): return a, b\n",
        """
        from strbind_callee import handle

        def except_as():
            try:
                return handle(1, 2)
            except ValueError as handle:
                return handle(0)  # the exception object, not the import

        def match_capture(x):
            match x:
                case [handle]:
                    return handle(9)
                case {**handle}:
                    return handle()
            return None

        class Uses:
            def handle(self):
                return None
            value = handle(None)  # class-local binding wins in the body
        """,
    )
    assert findings == []


def test_missing_root_fails_loudly():
    # A typo'd or renamed root must error, not shrink coverage to zero.
    with pytest.raises(FileNotFoundError, match="no_such_root"):
        list(staticcheck.iter_files(["no_such_root"]))


def test_finding_points_at_the_offending_read():
    findings = _undefined(
        """
        def f(a):


            return mesage
        """
    )
    assert [f.lineno for f in findings] == [5]  # the read, not `def f` (2)


def _dead_defs(tmp_path):
    import ast

    contributions = [
        (ast.parse(p.read_text()), p.name) for p in sorted(tmp_path.glob("*.py"))
    ]
    return staticcheck.check_dead_definitions(contributions)


def test_dead_definition_is_caught(tmp_path):
    (tmp_path / "mod_a.py").write_text(textwrap.dedent(
        """
        def used(): return 1
        def never_called(): return 2
        class Orphan: pass
        def lonely_recursive():
            return lonely_recursive()  # self-reference must not keep it alive
        STALE_TABLE = {"a": 1}
        RETRY = lambda n: RETRY(n - 1)  # self-mention must not keep it alive
        """
    ))
    (tmp_path / "mod_b.py").write_text("from mod_a import used\nprint(used())\n")
    assert sorted(f.message for f in _dead_defs(tmp_path)) == [
        "module-level 'Orphan' is referenced nowhere in the tree",
        "module-level 'RETRY' is referenced nowhere in the tree",
        "module-level 'STALE_TABLE' is referenced nowhere in the tree",
        "module-level 'lonely_recursive' is referenced nowhere in the tree",
        "module-level 'never_called' is referenced nowhere in the tree",
    ]
    # The bare re-export import did NOT count as the use — mod_b calling
    # used() did. Export padding cannot hide dead code:
    (tmp_path / "mod_b.py").write_text(
        "from mod_a import never_called\n__all__ = ['never_called']\n"
    )
    assert any("never_called" in f.message for f in _dead_defs(tmp_path))


def test_dead_definition_liveness_channels(tmp_path):
    # The ways a def stays alive without a plain call: pytest collection
    # (test_/Test*), fixture-by-parameter-name, identifiers inside
    # code-looking strings (subprocess job payloads), and entry points.
    (tmp_path / "mod.py").write_text(textwrap.dedent(
        '''
        def my_fixture(): return 3
        def job_callee(): return 4
        def main(): return 5
        class TestThings:
            def helper(self): pass
        def test_stuff(my_fixture):
            return my_fixture
        JOB = """
        from mod import job_callee
        job_callee()
        """
        print(JOB)
        '''
    ))
    assert _dead_defs(tmp_path) == []


def test_dead_definition_sees_getattr_and_fstring_references(tmp_path):
    # ISSUE 19 regression: a definition consumed only via
    # getattr(obj, "name") or named inside an f-string fragment is live —
    # the dataflow family's dead-lane check proves such lanes reachable,
    # and the two families must never disagree on liveness.
    (tmp_path / "mod.py").write_text(textwrap.dedent(
        '''
        def fd_hist_decode(): return 1
        def config_digest(): return 2
        def truly_dead(): return 3
        def probe(state, name):
            handler = getattr(state, "fd_hist_decode")
            return f"lane config_digest={handler(name)}"
        print(probe)
        '''
    ))
    assert sorted(f.message for f in _dead_defs(tmp_path)) == [
        "module-level 'truly_dead' is referenced nowhere in the tree",
    ]


def test_narrowed_roots_skip_liveness(tmp_path, monkeypatch):
    # A per-file/per-dir CLI run must not report cross-root consumers'
    # definitions as dead: liveness only runs on full-tree invocations.
    (tmp_path / "only.py").write_text("def consumed_elsewhere(): return 1\n")
    monkeypatch.setattr(staticcheck.core, "REPO", tmp_path)
    monkeypatch.syspath_prepend(str(tmp_path))
    findings = staticcheck.run([str(tmp_path / "only.py")])
    assert findings == []


def test_whole_tree_is_finding_free():
    # The gate itself: resolution-tier findings fail the build exactly the
    # way error-prone fails the reference's. All seventeen check families
    # run — including the compiled-program gate (device_program), the
    # ISSUE-18 cost-model ladder (cost_model), and the ISSUE-19 jaxpr
    # provenance gate (dataflow), whose entrypoint compiles/traces are
    # collected ONCE per process; pre-warm the session caches here so
    # this budget pins the ANALYSIS cost, not the compile cost
    # (tests/test_lint.py budgets the compile-inclusive sweep
    # separately). Process CPU time, not wall-clock: a loaded CI machine
    # must not fail the gate — only an analyzer going superlinear.
    import time

    staticcheck.collect_facts()  # session-shared; test_hlo_gate.py pins it
    staticcheck.collect_ladder()  # session-shared; test_lint.py pins it
    staticcheck.collect_dataflow()  # session-shared; test_dataflow.py pins it
    started = time.process_time()
    findings = staticcheck.run()
    elapsed = time.process_time() - started
    assert not findings, "\n".join(str(f) for f in findings)
    assert elapsed < 15.0, (
        f"seventeen-family tree sweep used {elapsed:.1f}s CPU (budget 15s)"
    )


# ---------------------------------------------------------------------------
# Driver robustness: syntax errors are findings, not crashes
# ---------------------------------------------------------------------------


def test_syntax_error_is_finding_not_crash(tmp_path, monkeypatch):
    # One unparseable file must report itself and leave the rest of the
    # tree analyzed (the old driver crashed the whole gate with a
    # traceback on the first broken file).
    (tmp_path / "broken.py").write_text("def f(:\n    return 0\n")
    (tmp_path / "good.py").write_text("def g():\n    return mesage\n")
    monkeypatch.setattr(staticcheck.core, "REPO", tmp_path)
    monkeypatch.syspath_prepend(str(tmp_path))
    findings = staticcheck.run([str(tmp_path)])
    assert sorted(f.check for f in findings) == ["syntax-error", "undefined-name"]
    syntax = next(f for f in findings if f.check == "syntax-error")
    assert syntax.path.endswith("broken.py") and syntax.lineno == 1


# ---------------------------------------------------------------------------
# Seeded lint corpus: one file per defect class, expectations embedded as
# `# expect: <check>` markers — exactly those findings and nothing else
# ---------------------------------------------------------------------------

_EXPECT_RE = re.compile(r"#\s*expect:\s*([a-z][a-z-]*)")

#: corpus file -> (pretend repo path, check function name). The pretend
#: path places the source inside the prefix each analyzer guards, the way
#: the clock-injection tests in test_lint.py do. The wire_schema corpus
#: files keep all schema mirrors as miniatures in one module (tree sweeps
#: merge the three real mirror files the same way).
_CORPUS_CHECKERS = {
    "unguarded_mutation.py": ("rapid_tpu/protocol/_corpus.py", "check_concurrency"),
    "interleaving_hazard.py": ("rapid_tpu/protocol/_corpus.py", "check_concurrency"),
    "lock_reentrancy.py": ("rapid_tpu/protocol/_corpus.py", "check_concurrency"),
    "clean_concurrency.py": ("rapid_tpu/protocol/_corpus.py", "check_concurrency"),
    "jit_side_effect.py": ("rapid_tpu/ops/_corpus.py", "check_trace_safety"),
    "jit_traced_branch.py": ("rapid_tpu/ops/_corpus.py", "check_trace_safety"),
    "clean_trace_safety.py": ("rapid_tpu/ops/_corpus.py", "check_trace_safety"),
    "missing_decode_arm.py": ("rapid_tpu/messaging/_corpus.py", "check_wire_schema"),
    "tag_reuse.py": ("rapid_tpu/messaging/_corpus.py", "check_wire_schema"),
    "field_number_drift.py": ("rapid_tpu/interop/_corpus.py", "check_wire_schema"),
    "clean_wire_schema.py": ("rapid_tpu/messaging/_corpus.py", "check_wire_schema"),
    "unreachable_dispatch_arm.py": ("rapid_tpu/protocol/_corpus.py", "check_dispatch"),
    "shadowed_arm.py": ("rapid_tpu/protocol/_corpus.py", "check_dispatch"),
    "clean_dispatch.py": ("rapid_tpu/protocol/_corpus.py", "check_dispatch"),
    "leaked_task.py": ("rapid_tpu/messaging/_corpus.py", "check_taskflow"),
    "swallowed_exception.py": ("rapid_tpu/messaging/_corpus.py", "check_taskflow"),
    "cancellation_swallow.py": ("rapid_tpu/messaging/_corpus.py", "check_taskflow"),
    "unawaited_coroutine.py": ("rapid_tpu/messaging/_corpus.py", "check_taskflow"),
    "clean_taskflow.py": ("rapid_tpu/messaging/_corpus.py", "check_taskflow"),
    "unseeded_random.py": ("rapid_tpu/messaging/_corpus.py", "check_determinism"),
    "clean_determinism.py": ("rapid_tpu/messaging/_corpus.py", "check_determinism"),
    # ISSUE 15: retry-backoff jitter in the serving supervision tier must
    # stay seeded (a fault drill replays bit-identically) — the defect +
    # clean pair live at the serving prefix the discipline now covers.
    "unseeded_backoff.py": ("rapid_tpu/serving/_corpus.py", "check_determinism"),
    "clean_backoff.py": ("rapid_tpu/serving/_corpus.py", "check_determinism"),
    "ledger_event_name.py": ("rapid_tpu/models/_corpus.py", "check_ledger"),
    "clean_ledger.py": ("rapid_tpu/models/_corpus.py", "check_ledger"),
    # device_program corpus files COMPILE their miniature programs (on the
    # session's 8-device CPU mesh) and compare against the inline HLO_LOCK
    # each carries — the compiled-artifact twin of the AST corpus.
    "hot_loop_collective.py": ("rapid_tpu/models/_corpus.py", "check_device_program"),
    "donation_dropped.py": ("rapid_tpu/models/_corpus.py", "check_device_program"),
    "clean_device_program.py": ("rapid_tpu/models/_corpus.py", "check_device_program"),
    "host_sync_in_hot_path.py": ("rapid_tpu/ops/_corpus.py", "check_sharding"),
    "host_sync_in_stream.py": ("rapid_tpu/serving/_corpus.py", "check_sharding"),
    "missing_partition_spec.py": ("rapid_tpu/parallel/_corpus.py", "check_sharding"),
    "missing_partition_rule.py": ("rapid_tpu/parallel/_corpus.py", "check_sharding"),
    "tenant_partition_rule.py": ("rapid_tpu/tenancy/_corpus.py", "check_sharding"),
    "retrace_hazard.py": ("rapid_tpu/models/_corpus.py", "check_sharding"),
    "dtype_widening.py": ("rapid_tpu/models/_corpus.py", "check_sharding"),
    "clean_dtype_widening.py": ("rapid_tpu/models/_corpus.py", "check_sharding"),
    "clean_sharding.py": ("rapid_tpu/parallel/_corpus.py", "check_sharding"),
    "chaos_unknown_kind.py": ("rapid_tpu/sim/_corpus.py", "check_chaosvocab"),
    "clean_chaosvocab.py": ("rapid_tpu/sim/_corpus.py", "check_chaosvocab"),
    "telemetry_unmarked_fetch.py": ("rapid_tpu/tenancy/_corpus.py", "check_telemetry"),
    "clean_telemetry.py": ("rapid_tpu/tenancy/_corpus.py", "check_telemetry"),
    # ISSUE 17: the round-trace ring rides the telemetry fetch discipline —
    # unmarked ring decodes (digest jits or direct spellings over
    # ``trace_ring`` / ``tr_*``) block like unmarked lane fetches, while
    # the decoded host-side summaries stay free.
    "trace_unmarked_fetch.py": ("rapid_tpu/serving/_corpus.py", "check_telemetry"),
    "clean_trace_fetch.py": ("rapid_tpu/serving/_corpus.py", "check_telemetry"),
    # ISSUE 18: the cost-model corpus COMPILES its miniature programs
    # across the module's inline COST_LADDER and fits each audited fact to
    # a scaling class — the O(N^2) defect trio (regression past the lock,
    # ceiling breach, dtype-step refusal) against the linear clean twin.
    "cost_scaling_regression.py": ("rapid_tpu/models/_corpus.py", "check_cost_model"),
    "clean_cost_model.py": ("rapid_tpu/models/_corpus.py", "check_cost_model"),
    # ISSUE 19: the dataflow corpus TRACES its miniature programs (no
    # compile) and runs the jaxpr provenance proofs over each — observer
    # feedback, a cross-tenant gather, and a mask-gated dense round body
    # against the silent clean twin.
    "dataflow_observer_leak.py": ("rapid_tpu/models/_corpus.py", "check_dataflow"),
    "clean_dataflow.py": ("rapid_tpu/models/_corpus.py", "check_dataflow"),
}


def _expected_markers(path: Path):
    return sorted(
        (m.group(1), lineno)
        for lineno, line in enumerate(path.read_text().splitlines(), 1)
        if (m := _EXPECT_RE.search(line))
    )


def test_corpus_is_complete():
    # Every corpus file is consumed by exactly one parametrized case below
    # (a stray file would silently be a no-op fixture).
    on_disk = {p.name for p in CORPUS.glob("*.py")}
    assert on_disk == set(_CORPUS_CHECKERS) | {"syntax_error.py"}


@pytest.mark.parametrize("name", sorted(_CORPUS_CHECKERS))
def test_lint_corpus(name):
    pretend_rel, checker_name = _CORPUS_CHECKERS[name]
    checker = getattr(staticcheck, checker_name)
    source = (CORPUS / name).read_text()
    findings = checker(staticcheck.core.REPO / pretend_rel, source=source)
    got = sorted((f.check, f.lineno) for f in findings)
    assert got == _expected_markers(CORPUS / name), "\n".join(
        str(f) for f in findings
    )


def test_lint_corpus_syntax_error():
    # Fed through the real driver (an explicit file root bypasses the
    # corpus exclusion): the parse failure becomes the file's one finding.
    findings = staticcheck.run([str(CORPUS / "syntax_error.py")])
    got = sorted((f.check, f.lineno) for f in findings)
    assert got == _expected_markers(CORPUS / "syntax_error.py")


def test_corpus_is_excluded_from_tree_sweeps():
    # The corpus exists to be defective; directory walks must skip it or
    # the whole-tree gate fails on purpose-built defects.
    swept = {str(p) for p in staticcheck.iter_files(("tests",))}
    assert not any("lint_corpus" in p for p in swept)


# ---------------------------------------------------------------------------
# Concurrency analyzer unit behaviors not covered by the corpus
# ---------------------------------------------------------------------------


def _concurrency(source: str, rel: str = "rapid_tpu/protocol/_probe.py"):
    return staticcheck.check_concurrency(
        staticcheck.core.REPO / rel, source=textwrap.dedent(source)
    )


def test_concurrency_checks_gate_on_package_prefix():
    src = """
    import asyncio

    class C:
        def __init__(self):
            self._lock = asyncio.Lock()
            self._x = 0  # guarded-by: _lock

        async def poke(self):
            self._x += 1
    """
    assert [f.check for f in _concurrency(src)] == ["unguarded-mutation"]
    assert _concurrency(src, rel="rapid_tpu/utils/_probe.py") == []


def test_guarded_by_annotation_typo_is_flagged():
    # A typo'd lock name must fail the gate, not silently guard nothing.
    src = """
    import asyncio

    class C:
        def __init__(self):
            self._lock = asyncio.Lock()
            self._x = 0  # guarded-by: _lokc
    """
    findings = _concurrency(src)
    assert [f.check for f in findings] == ["guarded-by-annotation"]
    assert "_lokc" in findings[0].message


def test_unguarded_ok_comment_allowlists_a_mutation():
    src = """
    import asyncio

    class C:
        def __init__(self):
            self._lock = asyncio.Lock()
            self._x = 0  # guarded-by: _lock

        async def poke(self):
            self._x += 1  # unguarded-ok: single-writer during bootstrap
    """
    assert _concurrency(src) == []


def test_escaped_and_unknown_contexts_are_skipped():
    # Methods registered as callbacks (or never called intra-class) have
    # unknowable lock contexts: mutations there must not convict.
    src = """
    import asyncio

    class C:
        def __init__(self, bus):
            self._lock = asyncio.Lock()
            self._x = 0  # guarded-by: _lock
            bus.subscribe(self._on_event)

        def _on_event(self, _evt):
            self._x += 1  # callback: context unknown, skip

        def _never_called_here(self):
            self._x += 1  # no intra-class call site: skip
    """
    assert _concurrency(src) == []


# ---------------------------------------------------------------------------
# Clock-injection extensions (time_ns, datetime spellings, monitoring/)
# ---------------------------------------------------------------------------


def test_clock_check_covers_new_spellings_and_monitoring():
    src = textwrap.dedent(
        """
        import time
        import datetime

        def stamp():
            return (
                time.time_ns(),
                datetime.datetime.now(),
            )
        """
    )
    for rel in ("rapid_tpu/protocol/_probe.py", "rapid_tpu/monitoring/_probe.py"):
        findings = staticcheck.check_clock_injection(
            staticcheck.core.REPO / rel, source=src
        )
        assert [f.check for f in findings] == ["clock-injection"] * 2, findings
    outside = staticcheck.check_clock_injection(
        staticcheck.core.REPO / "rapid_tpu" / "utils" / "_probe.py", source=src
    )
    assert outside == []


def test_wall_clock_ok_comment_allowlists_a_read():
    src = textwrap.dedent(
        """
        import time

        def stamp():
            return time.time()  # wall-clock-ok: operator-facing log line
        """
    )
    findings = staticcheck.check_clock_injection(
        staticcheck.core.REPO / "rapid_tpu" / "monitoring" / "_probe.py", source=src
    )
    assert findings == []


# ---------------------------------------------------------------------------
# Wire-schema lockfile: round-trip, drift naming, end-to-end gate
# ---------------------------------------------------------------------------


def _wire_surface():
    import ast

    from analysis import wire_schema

    trees = [
        (ast.parse((staticcheck.core.REPO / rel).read_text()), rel)
        for rel in staticcheck.WIRE_FILES
    ]
    return wire_schema, wire_schema.extract_surface(trees)


def test_wire_lock_round_trips_clean():
    # The committed lock IS the live surface: regenerating changes nothing,
    # and both the cross-check and the lock comparison are silent.
    wire_schema, surface = _wire_surface()
    committed = json.loads((staticcheck.core.REPO / staticcheck.LOCK_REL).read_text())
    committed.pop("_comment", None)
    assert wire_schema.surface_to_lock(surface) == committed
    assert wire_schema.cross_check(surface) == []
    assert wire_schema.compare_lock(surface, committed) == []


def test_wire_lock_drift_names_the_drifted_message():
    # Buf-style breaking-change reports: each class of mutation (tag
    # renumber, proto field renumber, dataclass field reorder) produces a
    # wire-lock-drift finding naming the message type and the regen command.
    wire_schema, surface = _wire_surface()
    lock = wire_schema.surface_to_lock(surface)
    lock["request_tags"]["JoinMessage"] = 12
    lock["proto"]["Phase1bMessage"]["vval"] = 9
    lock["fields"]["JoinResponse"] = list(reversed(lock["fields"]["JoinResponse"]))
    findings = wire_schema.compare_lock(surface, lock)
    assert {f.check for f in findings} == {"wire-lock-drift"}
    messages = [f.message for f in findings]
    assert any("JoinMessage" in m and "12" in m for m in messages)
    assert any("Phase1bMessage" in m and "vval" in m for m in messages)
    assert any("JoinResponse" in m and "field order" in m for m in messages)
    assert all("--update-wire-lock" in m for m in messages)


def test_tampered_lock_fails_the_tree_gate(tmp_path, monkeypatch):
    # End-to-end through the tree-mode entry the driver calls: a lock that
    # disagrees with the live mirrors produces findings (exit 1 at the CLI).
    import ast

    from analysis import wire_schema

    lock = json.loads((staticcheck.core.REPO / staticcheck.LOCK_REL).read_text())
    lock["response_tags"]["ProbeResponse"] = 9
    del lock["request_tags"]["LeaveMessage"]
    tampered = tmp_path / "wire.lock.json"
    tampered.write_text(json.dumps(lock))
    monkeypatch.setattr(wire_schema, "LOCK_REL", str(tampered))
    trees = [
        (ast.parse((staticcheck.core.REPO / rel).read_text()), rel)
        for rel in staticcheck.WIRE_FILES
    ]
    findings = wire_schema.check_wire_lock(trees)
    assert findings and {f.check for f in findings} == {"wire-lock-drift"}
    assert any("ProbeResponse" in f.message for f in findings)
    assert any("LeaveMessage" in f.message for f in findings)


def test_narrowed_roots_still_run_intra_file_wire_checks():
    # A per-file CLI invocation gets the intra-file wire checks (tree
    # sweeps run the merged three-file check instead, so defects are never
    # double-reported). The corpus's seeded tag reuse, fed through the real
    # driver as an explicit root:
    findings = staticcheck.run([str(CORPUS / "tag_reuse.py")])
    assert [f.check for f in findings] == ["tag-reuse"]


def test_wire_check_is_presence_gated_per_file():
    # A real mirror file analyzed ALONE must not produce cross-file noise:
    # codec.py has tags+arms but no union, types.py has the union but no
    # tags — each is internally consistent, so each is silent. The merged
    # tree-mode check owns the cross-file obligations.
    for rel in staticcheck.WIRE_FILES:
        findings = staticcheck.check_wire_schema(staticcheck.core.REPO / rel)
        assert findings == [], (rel, findings)


# ---------------------------------------------------------------------------
# Dispatch analyzer unit behaviors not covered by the corpus
# ---------------------------------------------------------------------------


_MINI_DISPATCH_PRELUDE = """
from dataclasses import dataclass
from typing import Union


@dataclass(frozen=True)
class Ping:
    sender: str


@dataclass(frozen=True)
class Ack:
    pass


RapidRequest = Union[Ping]
RapidResponse = Union[Ack]
"""


def _dispatch(source: str, rel: str = "rapid_tpu/protocol/_probe.py"):
    return staticcheck.check_dispatch(
        staticcheck.core.REPO / rel, source=textwrap.dedent(source)
    )


def test_dispatch_return_type_resolved_through_helper_annotation():
    src = _MINI_DISPATCH_PRELUDE + """
class S:
    async def handle_message(self, request):
        if isinstance(request, Ping):
            return self._handle(request)
        raise TypeError(request)

    def _handle(self, request) -> Ping:
        return Ping("me")
"""
    findings = _dispatch(src)
    assert [f.check for f in findings] == ["dispatch-return"]
    assert "not a RapidResponse member" in findings[0].message


def test_dispatched_elsewhere_typo_is_flagged():
    # A stale or typo'd exemption must fail the gate, not silently excuse
    # a genuinely unreachable member.
    src = _MINI_DISPATCH_PRELUDE + """
class S:
    # dispatched-elsewhere: Gone
    async def handle_message(self, request):
        if isinstance(request, Ping):
            return Ack()
        raise TypeError(request)
"""
    findings = _dispatch(src)
    assert [f.check for f in findings] == ["unreachable-dispatch-arm"]
    assert "Gone" in findings[0].message and "stale or typo'd" in findings[0].message


def test_dispatch_gates_on_protocol_prefix():
    src = _MINI_DISPATCH_PRELUDE + """
class S:
    async def handle_message(self, request):
        raise TypeError(request)
"""
    assert _dispatch(src, rel="rapid_tpu/utils/_probe.py") == []
    assert [f.check for f in _dispatch(src)] == ["unreachable-dispatch-arm"]


# ---------------------------------------------------------------------------
# Taskflow analyzer unit behaviors not covered by the corpus
# ---------------------------------------------------------------------------


def _taskflow(source: str, rel: str = "rapid_tpu/utils/_probe.py"):
    return staticcheck.check_taskflow(
        staticcheck.core.REPO / rel, source=textwrap.dedent(source)
    )


def test_taskflow_gates_on_library_prefix():
    src = """
    import asyncio

    def fire(work):
        asyncio.ensure_future(work())
    """
    assert [f.check for f in _taskflow(src)] == ["leaked-task"]
    assert _taskflow(src, rel="tools/_probe.py") == []


def test_taskflow_ok_comment_allowlists_a_finding():
    src = """
    import asyncio

    def fire(work):
        asyncio.ensure_future(work())  # taskflow-ok: test shim, loop torn down next line
    """
    assert _taskflow(src) == []


def test_plain_except_exception_in_async_def_is_not_a_cancellation_swallow():
    # CancelledError derives from BaseException since 3.8: a broad-but-
    # justified Exception catch lets cancellation through and must not be
    # convicted; an unjustified BaseException catch is convicted twice
    # (it both swallows errors and absorbs cancellation).
    src = """
    import logging

    LOG = logging.getLogger(__name__)

    async def loop(tick):
        while True:
            try:
                await tick()
            except Exception:  # noqa: BLE001 — the loop must survive
                LOG.exception("tick failed")
    """
    assert _taskflow(src) == []
    src_base = """
    async def loop(tick):
        while True:
            try:
                await tick()
            except BaseException:
                pass
    """
    assert sorted(f.check for f in _taskflow(src_base)) == [
        "cancellation-swallow", "swallowed-exception",
    ]


# ---------------------------------------------------------------------------
# CLI contract: --json / --select / --ignore, human output + exit codes
# ---------------------------------------------------------------------------


def _run_cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    script = Path(staticcheck.__file__).resolve()
    return subprocess.run(
        [sys.executable, str(script), *args],
        capture_output=True, text=True, env=env, timeout=120,
    )


@pytest.mark.slow
def test_cli_json_select_ignore_and_exit_codes(tmp_path):
    # Rides the unfiltered check.sh pass (~15 s wall: each CLI invocation
    # is a fresh interpreter paying full import + analysis); the in-process
    # driver tests above pin the same select/ignore/exit semantics.
    bad = tmp_path / "bad.py"
    bad.write_text("def f():\n    return mesage\n")

    as_json = _run_cli("--json", str(bad))
    assert as_json.returncode == 1
    objs = [json.loads(line) for line in as_json.stdout.splitlines()]
    assert [(o["check"], o["lineno"]) for o in objs] == [("undefined-name", 2)]
    assert objs[0]["path"] == str(bad) and "mesage" in objs[0]["message"]

    human = _run_cli(str(bad))
    assert human.returncode == 1
    assert "[undefined-name]" in human.stdout
    assert human.stdout.strip().endswith("staticcheck: 1 finding(s)")

    ignored = _run_cli("--ignore", "undefined-name", str(bad))
    assert ignored.returncode == 0
    assert ignored.stdout.strip().endswith("staticcheck: 0 finding(s)")

    selected = _run_cli("--select", "clock-injection", "--json", str(bad))
    assert selected.returncode == 0 and selected.stdout.strip() == ""

    typo = _run_cli("--select", "no-such-check", str(bad))
    assert typo.returncode == 2 and "no-such-check" in typo.stderr


def test_cli_families_lists_all_families():
    assert len(staticcheck.FAMILIES) == 17
    result = _run_cli("--families")
    assert result.returncode == 0
    for name, _description in staticcheck.FAMILIES:
        assert name in result.stdout, name


def test_cli_update_wire_lock_is_a_deterministic_round_trip(
    tmp_path, monkeypatch, capsys
):
    # Regenerating over an unchanged tree produces the byte-identical lock —
    # the committed file is exactly what the generator emits, so the gate
    # and the regen command can never fight each other. Regenerate into a
    # REDIRECTED path: writing the repo's lock in place would silently
    # overwrite the committed file with the live surface — masking the very
    # divergence this test exists to catch.
    from analysis import wire_schema

    committed = (staticcheck.core.REPO / staticcheck.LOCK_REL).read_text()
    target = tmp_path / "wire.lock.json"
    monkeypatch.setattr(wire_schema, "LOCK_REL", str(target))
    rc = staticcheck.main(["--update-wire-lock"])
    assert rc == 0
    assert "wrote" in capsys.readouterr().out
    assert target.read_text() == committed


# ---------------------------------------------------------------------------
# Sharding analyzer: *_argnames spellings must resolve, not false-positive
# ---------------------------------------------------------------------------


def _sharding(source: str, rel: str = "rapid_tpu/models/_probe.py"):
    return staticcheck.check_sharding(
        staticcheck.core.REPO / rel, source=textwrap.dedent(source)
    )


def test_donate_argnames_spelling_is_recognized_not_flagged():
    # donate_argnames=("state",) donates the pytree just as argnums would —
    # flagging it (and demanding a bogus # donate-ok:) violates
    # skip-don't-guess.
    findings = _sharding(
        """
        import jax

        def step_impl(cfg, state, faults):
            del cfg
            return state + faults

        step = jax.jit(step_impl, static_argnums=(0,),
                       donate_argnames=("state",))
        """
    )
    assert findings == [], findings


def test_static_argnames_pins_the_position_for_retrace_check():
    # jax maps static_argnames onto positions for positional calls, so a
    # bare literal there never retraces; an unpinned traced position next
    # to it must still flag.
    findings = _sharding(
        """
        import jax

        def run_impl(cfg, values, max_steps, rounds):
            del cfg
            return values * max_steps * rounds

        run = jax.jit(run_impl, static_argnums=(0,),
                      static_argnames=("max_steps",))

        def drive(cfg, values):
            ok = run(cfg, values, 96, jax.numpy.int32(4))
            bad = run(cfg, values, 96, 4)
            return ok, bad
        """
    )
    assert [f.check for f in findings] == ["retrace-hazard"], findings
    assert "position 3" in findings[0].message, findings[0].message
