"""Build gate for the resolution-tier static analysis (tools/staticcheck).

Two halves, matching how the reference treats error-prone: the whole tree
must be finding-free (the gate), and the analyzer itself must demonstrably
catch the defect classes it claims — a gate that never bites is
indistinguishable from no gate.
"""

from __future__ import annotations

import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

import staticcheck  # noqa: E402


def _undefined(src: str):
    return staticcheck.check_undefined_names(
        Path("fixture.py"), textwrap.dedent(src)
    )


def test_undefined_name_in_error_branch_is_caught():
    findings = _undefined(
        """
        import os

        def f(a):
            if a:
                return os.sep
            raise RuntimeError(mesage)  # typo: never executed by tests
        """
    )
    assert [f.check for f in findings] == ["undefined-name"]
    assert "mesage" in findings[0].message


def test_global_decl_assignment_binds_at_module_scope():
    findings = _undefined(
        """
        def setup(value):
            global _CACHE
            _CACHE = value

        def read():
            return _CACHE  # bound only via setup()'s global decl
        """
    )
    assert findings == []


def test_class_and_comprehension_scopes_resolve():
    findings = _undefined(
        """
        BASE = 2

        class C:
            x = BASE
            def m(self):
                return [BASE + i for i in range(self.x)]

        lam = lambda z: z + BASE
        """
    )
    assert findings == []


def test_star_import_is_flagged_not_skipped():
    findings = _undefined("from os.path import *\n")
    assert [f.check for f in findings] == ["star-import"]


def _caller_findings(tmp_path, monkeypatch, name: str, callee_src: str, caller_src: str):
    """Materialize a callee+caller module pair under a private root and run
    the call-conformance check on the caller."""
    (tmp_path / f"{name}_callee.py").write_text(textwrap.dedent(callee_src))
    caller = tmp_path / f"{name}_caller.py"
    caller.write_text(textwrap.dedent(caller_src))
    monkeypatch.setattr(staticcheck, "REPO", tmp_path)
    monkeypatch.syspath_prepend(str(tmp_path))
    return staticcheck.check_call_signatures(caller)


def test_wrong_kwarg_and_arity_are_caught(tmp_path, monkeypatch):
    findings = _caller_findings(
        tmp_path, monkeypatch, "sigs",
        """
        def encode(message, *, deadline_ms=100):
            return message, deadline_ms
        """,
        """
        import sigs_callee

        def ok():
            return sigs_callee.encode("m", deadline_ms=5)

        def typo():
            return sigs_callee.encode("m", deadlne_ms=5)

        def arity():
            return sigs_callee.encode("m", "extra")
        """,
    )
    assert [f.check for f in findings] == ["call-signature", "call-signature"]
    assert "deadlne_ms" in findings[0].message
    assert "too many positional" in findings[1].message


def test_stale_module_attribute_is_caught(tmp_path, monkeypatch):
    findings = _caller_findings(
        tmp_path, monkeypatch, "attr",
        "def current(): return 1\n",
        """
        import attr_callee

        def f():
            return attr_callee.renamed_away()
        """,
    )
    assert [f.check for f in findings] == ["missing-attribute"]
    assert "renamed_away" in findings[0].message


def test_shadowed_and_dynamic_call_sites_are_skipped(tmp_path, monkeypatch):
    findings = _caller_findings(
        tmp_path, monkeypatch, "shadow",
        "def g(a, b): return a + b\n",
        """
        import shadow_callee
        from shadow_callee import g

        def shadowed(g):
            return g(1, 2, 3, 4)  # parameter, not the module-level g

        def splat(args):
            return g(*args)  # dynamic shape: must not be judged

        def lam():
            return (lambda g: g(9, 9, 9))(len)

        def comp(items):
            return [g for g in items if g]
        """,
    )
    assert findings == []


def test_str_target_bindings_and_class_bodies_shadow(tmp_path, monkeypatch):
    # Bindings whose AST target is a plain string (except-as, match capture)
    # and class-body-level bindings must shadow module-level callables; each
    # of these produced a spurious build-failing finding before being
    # handled.
    findings = _caller_findings(
        tmp_path, monkeypatch, "strbind",
        "def handle(a, b): return a, b\n",
        """
        from strbind_callee import handle

        def except_as():
            try:
                return handle(1, 2)
            except ValueError as handle:
                return handle(0)  # the exception object, not the import

        def match_capture(x):
            match x:
                case [handle]:
                    return handle(9)
                case {**handle}:
                    return handle()
            return None

        class Uses:
            def handle(self):
                return None
            value = handle(None)  # class-local binding wins in the body
        """,
    )
    assert findings == []


def test_missing_root_fails_loudly():
    # A typo'd or renamed root must error, not shrink coverage to zero.
    import pytest

    with pytest.raises(FileNotFoundError, match="no_such_root"):
        list(staticcheck.iter_files(["no_such_root"]))


def test_finding_points_at_the_offending_read():
    findings = _undefined(
        """
        def f(a):


            return mesage
        """
    )
    assert [f.lineno for f in findings] == [5]  # the read, not `def f` (2)


def _dead_defs(tmp_path):
    import ast

    contributions = [
        (ast.parse(p.read_text()), p.name) for p in sorted(tmp_path.glob("*.py"))
    ]
    return staticcheck.check_dead_definitions(contributions)


def test_dead_definition_is_caught(tmp_path):
    (tmp_path / "mod_a.py").write_text(textwrap.dedent(
        """
        def used(): return 1
        def never_called(): return 2
        class Orphan: pass
        def lonely_recursive():
            return lonely_recursive()  # self-reference must not keep it alive
        STALE_TABLE = {"a": 1}
        RETRY = lambda n: RETRY(n - 1)  # self-mention must not keep it alive
        """
    ))
    (tmp_path / "mod_b.py").write_text("from mod_a import used\nprint(used())\n")
    assert sorted(f.message for f in _dead_defs(tmp_path)) == [
        "module-level 'Orphan' is referenced nowhere in the tree",
        "module-level 'RETRY' is referenced nowhere in the tree",
        "module-level 'STALE_TABLE' is referenced nowhere in the tree",
        "module-level 'lonely_recursive' is referenced nowhere in the tree",
        "module-level 'never_called' is referenced nowhere in the tree",
    ]
    # The bare re-export import did NOT count as the use — mod_b calling
    # used() did. Export padding cannot hide dead code:
    (tmp_path / "mod_b.py").write_text(
        "from mod_a import never_called\n__all__ = ['never_called']\n"
    )
    assert any("never_called" in f.message for f in _dead_defs(tmp_path))


def test_dead_definition_liveness_channels(tmp_path):
    # The ways a def stays alive without a plain call: pytest collection
    # (test_/Test*), fixture-by-parameter-name, identifiers inside
    # code-looking strings (subprocess job payloads), and entry points.
    (tmp_path / "mod.py").write_text(textwrap.dedent(
        '''
        def my_fixture(): return 3
        def job_callee(): return 4
        def main(): return 5
        class TestThings:
            def helper(self): pass
        def test_stuff(my_fixture):
            return my_fixture
        JOB = """
        from mod import job_callee
        job_callee()
        """
        print(JOB)
        '''
    ))
    assert _dead_defs(tmp_path) == []


def test_narrowed_roots_skip_liveness(tmp_path, monkeypatch):
    # A per-file/per-dir CLI run must not report cross-root consumers'
    # definitions as dead: liveness only runs on full-tree invocations.
    (tmp_path / "only.py").write_text("def consumed_elsewhere(): return 1\n")
    monkeypatch.setattr(staticcheck, "REPO", tmp_path)
    monkeypatch.syspath_prepend(str(tmp_path))
    findings = staticcheck.run([str(tmp_path / "only.py")])
    assert findings == []


def test_whole_tree_is_finding_free():
    # The gate itself: resolution-tier findings fail the build exactly the
    # way error-prone fails the reference's.
    findings = staticcheck.run()
    assert not findings, "\n".join(str(f) for f in findings)
