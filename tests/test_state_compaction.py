"""Config-derived state compaction (ISSUE 13) pins.

The compact engine (``EngineConfig.compact=1``) stores every
:data:`NARROWABLE_LANES` lane at :func:`compaction_policy`'s minimal legal
dtype; the wide int32/uint32 layout stays the differential ORACLE. The bar
here: wide and compact runs of the same scenario are bit-identical —
identical cuts, configuration ids, decision rounds, and (after
:func:`widen_state`) identical state pytrees leaf-for-leaf — across the
mixed scenario grid: crash/join/churn on a single cluster, a tenancy
representative, and a streaming representative (larger grids ride ``slow``
per the PR-10 budget convention).

Also pinned: the FIRE_NEVER sentinel invariant under the narrowest round
dtype the policy can pick (the models/state.py:30 comment as a test), the
bit-pack/unpack bijection, the sizing formula against real pytrees, the
policy <-> lint lane-set mirror, and mesh placement of compact/packed
states through the unchanged rule table.
"""

from __future__ import annotations

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from rapid_tpu.models import state as S  # noqa: E402
from rapid_tpu.models.state import (  # noqa: E402
    FIRE_NEVER,
    FIRE_NEVER_NARROW,
    ROUND_ENVELOPE,
    EngineConfig,
    compaction_policy,
    narrow_state,
    widen_state,
)
from rapid_tpu.models.virtual_cluster import VirtualCluster  # noqa: E402

GEOM = dict(k=3, h=3, l=1, cohorts=2, fd_threshold=2)


def _cluster(compact, n=24, n_slots=40, seed=0, **kw):
    params = {**GEOM, **kw}
    vc = VirtualCluster.create(
        n, n_slots=n_slots, seed=seed, compact=compact, **params
    )
    vc.assign_cohorts_roundrobin()
    return vc


def _assert_states_identical(wide_vc, compact_vc, label=""):
    widened = widen_state(compact_vc.cfg, compact_vc.state)
    for field in wide_vc.state._fields:
        a = np.asarray(getattr(wide_vc.state, field))
        b = np.asarray(getattr(widened, field))
        assert a.dtype == b.dtype, (label, field, a.dtype, b.dtype)
        assert (a == b).all(), (label, field)
    for field in wide_vc.faults._fields:
        a = np.asarray(getattr(wide_vc.faults, field))
        b = np.asarray(getattr(compact_vc.faults, field))
        assert (a == b).all(), (label, field)
    assert wide_vc.config_id == compact_vc.config_id, label


# ---------------------------------------------------------------------------
# Policy derivation (pure)
# ---------------------------------------------------------------------------


def test_policy_is_wide_by_default():
    cfg = EngineConfig(n=1024, k=10, h=9, l=4)
    assert compaction_policy(cfg) == S.WIDE_POLICY
    assert cfg.compact == 0


def test_policy_picks_minimal_legal_dtypes():
    base = dict(k=10, h=9, l=4, compact=1)
    # Index width follows N — and must hold N itself, not just n-1: jax
    # index normalization materializes the axis size in the index dtype,
    # so n=128 under int8 overflows at trace time (the cost-model ladder
    # found exactly that boundary).
    assert compaction_policy(EngineConfig(n=127, **base)).idx == "int8"
    assert compaction_policy(EngineConfig(n=128, **base)).idx == "int16"
    assert compaction_policy(EngineConfig(n=(1 << 15) - 1, **base)).idx == "int16"
    assert compaction_policy(EngineConfig(n=1 << 15, **base)).idx == "int32"
    # Cohort width follows C.
    assert compaction_policy(EngineConfig(n=256, c=8, **base)).cohort == "int8"
    assert compaction_policy(EngineConfig(n=256, c=512, **base)).cohort == "int16"
    # Report bitmask width follows K; the Pallas delivery kernel emits
    # uint32 words, so use_pallas holds the lane wide.
    assert compaction_policy(EngineConfig(n=256, k=8, h=3, l=1, compact=1)).report == "uint8"
    assert compaction_policy(EngineConfig(n=256, k=9, h=3, l=1, compact=1)).report == "uint16"
    assert compaction_policy(EngineConfig(n=256, k=17, h=3, l=1, compact=1)).report == "uint32"
    assert (
        compaction_policy(
            EngineConfig(n=256, k=8, h=3, l=1, use_pallas=True, compact=1)
        ).report
        == "uint32"
    )
    # History width follows fd_window (0 = the unused counter-mode lane).
    assert compaction_policy(EngineConfig(n=256, fd_window=0, **base)).hist == "uint8"
    assert compaction_policy(EngineConfig(n=256, fd_window=8, **base)).hist == "uint8"
    assert compaction_policy(EngineConfig(n=256, fd_window=9, **base)).hist == "uint16"
    assert compaction_policy(EngineConfig(n=256, fd_window=32, **base)).hist == "uint32"
    pol = compaction_policy(EngineConfig(n=256, **base))
    assert pol.counter == "int16" and pol.round == "int16"
    assert pol.fire_never == FIRE_NEVER_NARROW


def test_lane_specs_cover_every_pytree_field():
    from rapid_tpu.models.state import EngineState, FaultInputs

    assert set(S.LANE_SPECS) == set(EngineState._fields) | set(FaultInputs._fields)


def test_narrowable_lanes_mirror_the_lint_set():
    # The sharding analyzer keeps a LITERAL mirror (the analysis package
    # imports no jax-bearing library module); this pin is what keeps the
    # two sets from drifting.
    from analysis import sharding as sharding_checks

    assert sharding_checks.NARROWED_LANES == S.NARROWABLE_LANES
    # And every narrowed lane is actually narrow under a compact policy.
    dts = S.lane_dtypes(EngineConfig(n=128, k=4, h=3, l=1, c=2, compact=1))
    for lane in S.NARROWABLE_LANES:
        assert np.dtype(dts[lane]).itemsize < 4, lane


# ---------------------------------------------------------------------------
# Sizing formula & bit-packing
# ---------------------------------------------------------------------------


def test_state_bytes_formula_matches_real_pytree():
    # The compact variant; the wide formula is additionally pinned against
    # the compiled artifact's own argument accounting (both layouts) in
    # tests/test_hlo_gate.py::test_compact_formula_matches_compiled_argument_bytes.
    vc = _cluster(True)
    measured = S.pytree_nbytes(vc.state) + S.pytree_nbytes(vc.faults)
    assert measured == S.state_bytes_total(vc.cfg)
    packed = S.pytree_nbytes(S.pack_masks(vc.state)) + S.pytree_nbytes(
        S.pack_masks(vc.faults)
    )
    assert packed == S.state_bytes_total(vc.cfg, packed=True)


def test_compact_policy_shrinks_bytes_per_member():
    wide = EngineConfig(n=1024, k=10, h=9, l=4, c=8)
    comp = wide._replace(compact=1)
    assert S.state_bytes_per_member(comp) <= 0.7 * S.state_bytes_per_member(wide)
    assert S.state_bytes_per_member(comp, packed=True) < S.state_bytes_per_member(comp)
    # 10M/100M re-derive the policy at scale: index lanes re-widen, the
    # sizing stays honest (bigger than a naive small-N extrapolation).
    big = EngineConfig(n=100_000_000, k=10, h=9, l=4, c=64, compact=1)
    assert compaction_policy(big).idx == "int32"


def test_pack_unpack_is_a_bijection():
    rng = np.random.default_rng(3)
    for shape, axis in [((40,), 0), ((40, 3), 0), ((2, 40), 1), ((16,), 0)]:
        mask = rng.random(shape) < 0.3
        packed = S.pack_bool(mask, axis=axis)
        assert packed.dtype == jnp.uint8
        assert packed.shape[axis] == shape[axis] // 8
        assert (np.asarray(S.unpack_bool(packed, axis=axis)) == mask).all()
    with pytest.raises(ValueError, match="multiple of 8"):
        S.pack_bool(np.zeros(13, bool), axis=0)


def test_pack_masks_roundtrips_whole_state():
    vc = _cluster(True)
    vc.crash([1, 2])
    packed = S.pack_masks(vc.state)
    assert packed.alive.shape == (5,) and packed.alive.dtype == jnp.uint8
    assert packed.released.shape == (2, 5)
    assert packed.fd_fired.shape == (5, 3)
    assert packed.report_bits.dtype == vc.state.report_bits.dtype  # untouched
    un = S.unpack_masks(packed)
    for field in vc.state._fields:
        assert (
            np.asarray(getattr(un, field)) == np.asarray(getattr(vc.state, field))
        ).all(), field
    pf = S.pack_masks(vc.faults)
    assert pf.crashed.shape == (5,)
    assert (np.asarray(S.unpack_masks(pf).crashed) == np.asarray(vc.faults.crashed)).all()


# ---------------------------------------------------------------------------
# FIRE_NEVER sentinel under the narrowest round dtype (the state.py:30
# comment, as a test)
# ---------------------------------------------------------------------------


def test_fire_never_sentinel_invariant():
    # k/n match the module GEOM so initial_state's ring jits are shared.
    cfg = EngineConfig(n=40, k=3, h=3, l=1, c=2, delivery_spread=2, compact=1)
    pol = compaction_policy(cfg)
    assert jnp.dtype(pol.round) == jnp.int16  # the narrowest pick
    assert pol.fire_never == FIRE_NEVER_NARROW
    # Storable without wrap, and distinct from every in-envelope round.
    assert np.int16(pol.fire_never) == pol.fire_never
    assert pol.fire_never > ROUND_ENVELOPE
    # The invariant itself: an unfired edge's age (round_idx - sentinel,
    # accumulated at int32 as the round body does) stays NEGATIVE for
    # every in-envelope round index, so `age >= delay` can never deliver
    # (delays are >= 0).
    rounds = np.arange(0, ROUND_ENVELOPE + 1, dtype=np.int32)
    ages = rounds - np.int32(pol.fire_never)
    assert (ages < 0).all()
    # One past the envelope the distinction is lost — the envelope is the
    # boundary, not slack.
    assert (ROUND_ENVELOPE + 1) - pol.fire_never == 0
    # Round-trip through the converters: sentinel maps narrow<->wide.
    from rapid_tpu.models.state import initial_state

    rng = np.random.default_rng(0)
    st = initial_state(
        cfg,
        rng.integers(0, 2**32, (3, 40), dtype=np.uint32),
        rng.integers(0, 2**32, (3, 40), dtype=np.uint32),
        rng.integers(0, 2**32, 40, dtype=np.uint32),
        rng.integers(0, 2**32, 40, dtype=np.uint32),
        np.ones(40, bool),
    )
    assert st.fire_round.dtype == jnp.int16
    assert int(np.asarray(st.fire_round).max()) == FIRE_NEVER_NARROW
    wide = widen_state(cfg, st)
    assert wide.fire_round.dtype == jnp.int32
    assert int(np.asarray(wide.fire_round).max()) == FIRE_NEVER
    back = narrow_state(cfg, wide)
    assert (np.asarray(back.fire_round) == np.asarray(st.fire_round)).all()


def test_unfired_edges_never_deliver_near_the_envelope_edge():
    # Engine-level: a compact cluster pushed near the last in-envelope
    # round index still runs the whole detection->delivery->cut pipeline
    # correctly — the crashed slot's edges fire and deliver at the high
    # round stamps while every UNFIRED edge's sentinel age stays negative
    # (no phantom reports; this is exactly what int16 overflow would break
    # a few rounds later). Same GEOM config as the differential tests, so
    # the compiled compact step is shared across the module.
    vc = _cluster(True)
    high = ROUND_ENVELOPE - 8
    vc.state = vc.state._replace(round_idx=jnp.int32(high))
    vc.crash([3])
    decided = False
    for _ in range(8):
        events = vc.step()
        bits = np.asarray(vc.state.report_bits)
        assert (bits[:, :3] == 0).all() and (bits[:, 4:] == 0).all()
        if bool(events.decided):
            decided = True
            assert set(np.nonzero(np.asarray(events.winner_mask))[0]) == {3}
            break
    assert decided  # the pipeline completed at envelope-edge round stamps


def test_envelope_validation_and_stagger_guard():
    wide_vc = _cluster(False)
    cfg = wide_vc.cfg._replace(compact=1)
    S.validate_envelope(cfg, wide_vc.state)  # clean state passes
    bad = wide_vc.state._replace(round_idx=jnp.int32(ROUND_ENVELOPE + 5))
    with pytest.raises(ValueError, match="round_idx"):
        S.validate_envelope(cfg, bad)
    comp = _cluster(True)
    with pytest.raises(ValueError, match="envelope"):
        comp.stagger_fd_counts(np.random.default_rng(0), spread_rounds=1 << 15)


# ---------------------------------------------------------------------------
# Wide <-> compact bit-identity: the mixed scenario grid
# ---------------------------------------------------------------------------


def _drive_churn(vc):
    """Crash + join + leave waves through per-round ``step`` dispatches
    (the compiled ``engine_step`` is shared with the stream differential
    and the envelope test — one compact compile per session): returns
    (per-cut labels, config_ids, rounds_per_phase)."""
    cuts, ids, rounds = [], [], []

    def run(target):
        for round_idx in range(96):
            was_alive = np.asarray(vc.state.alive)
            events = vc.step()
            if bool(events.decided):
                mask = np.asarray(events.winner_mask)
                cuts.append(frozenset(
                    (s, "down" if was_alive[s] else "up")
                    for s in np.nonzero(mask)[0].tolist()
                ))
                ids.append(vc.config_id)
                if vc.membership_size == target:
                    rounds.append(round_idx + 1)
                    return
        raise AssertionError(f"did not reach membership {target}")

    vc.crash([1, 5, 9])
    run(21)
    vc.inject_join_wave([30, 31])
    run(23)
    vc.initiate_leave([2])
    run(22)
    return cuts, ids, rounds


def test_mixed_churn_differential_wide_vs_compact():
    """Tier-1 representative: crash wave + join wave + graceful leave,
    identical decision rounds, cut counts, config-id chains, and final
    state+faults pytrees (widened) between the wide oracle and the compact
    engine."""
    wide, comp = _cluster(False), _cluster(True)
    _assert_states_identical(wide, comp, "initial")
    wide_cuts, wide_ids, wide_rounds = _drive_churn(wide)
    comp_cuts, comp_ids, comp_rounds = _drive_churn(comp)
    assert wide_cuts and wide_cuts == comp_cuts
    assert wide_rounds == comp_rounds
    assert wide_ids == comp_ids
    _assert_states_identical(wide, comp, "after churn")


def test_tenancy_differential_wide_vs_compact():
    """The tenancy representative: a 2-tenant fleet of compact clusters is
    bit-identical (widened) to the wide fleet on per-tenant crash waves."""
    from rapid_tpu.tenancy import TenantFleet

    def fleet(compact):
        clusters = []
        for i in range(2):
            vc = _cluster(compact, n=16, n_slots=16, seed=20 + i)
            clusters.append(vc)
        return TenantFleet.from_clusters(clusters)

    fw, fc = fleet(False), fleet(True)
    for f in (fw, fc):
        f.stream_crash([(0, 2), (1, 5)])
    # Per-round batched steps (the compiled fleet_step — the wide one is
    # shared with the stream-fleet tests' identical config): identical
    # per-tenant decision rounds and winner masks.
    decided_rounds_w, decided_rounds_c = [], []
    for rounds, f in ((decided_rounds_w, fw), (decided_rounds_c, fc)):
        for round_idx in range(24):
            events = f.step()
            for t in np.nonzero(np.asarray(events.decided))[0]:
                rounds.append((round_idx, int(t),
                               tuple(np.nonzero(np.asarray(events.winner_mask[t]))[0])))
    assert decided_rounds_w and decided_rounds_w == decided_rounds_c
    widened = widen_state(fc.cfg, fc.state)
    for field in fw.state._fields:
        a = np.asarray(getattr(fw.state, field))
        b = np.asarray(getattr(widened, field))
        assert a.dtype == b.dtype and (a == b).all(), field


def test_stream_differential_wide_vs_compact():
    """The streaming representative: one seeded Poisson schedule through
    StreamDriver over a wide and a compact cluster — identical cut counts,
    config chains, and final (widened) state pytrees."""
    from rapid_tpu.serving import PoissonChurn, StreamDriver

    waves = PoissonChurn(24, 40, rate=1.0, seed=7).waves(5)
    results = {}
    for compact in (False, True):
        vc = _cluster(compact)
        driver = StreamDriver(vc, rounds_per_wave=4, depth=2)
        for wave in waves:
            driver.submit(wave)
        results[compact] = (vc, driver.drain())
    (wide, wide_res), (comp, comp_res) = results[False], results[True]
    assert wide_res.cuts == comp_res.cuts and wide_res.cuts > 0
    assert wide.config_epoch == comp.config_epoch
    _assert_states_identical(wide, comp, "stream")


@pytest.mark.slow
def test_adverse_grid_differential_wide_vs_compact():
    """Broader grid (check.sh's unfiltered pass): partition + classic
    fallback + concurrent coordinators, windowed FD, and sub-round
    delivery jitter — every variant bit-identical."""
    variants = [
        dict(delivery_spread=2, fallback_rounds=4, concurrent_coordinators=2,
             cohorts=4, delivery_prob_permille=500),
        dict(fd_window=5),
        dict(delivery_spread=3, delivery_prob_permille=250),
    ]
    for kw in variants:
        wide = _cluster(False, n=20, n_slots=32, seed=3, **kw)
        comp = _cluster(True, n=20, n_slots=32, seed=3, **kw)
        for vc in (wide, comp):
            vc.stagger_fd_counts(np.random.default_rng(5), spread_rounds=3)
            if kw.get("cohorts"):
                rx = np.zeros((kw["cohorts"], 32), bool)
                rx[1, :] = True
                vc.set_rx_block(rx)
            vc.crash([0, 7])
        rw = wide.run_until_membership(18, min_cuts=1, max_steps=160)
        rc = comp.run_until_membership(18, min_cuts=1, max_steps=160)
        assert rw == rc, kw
        _assert_states_identical(wide, comp, str(kw))


# ---------------------------------------------------------------------------
# Mesh placement: the unchanged rule table covers compact + packed shapes
# ---------------------------------------------------------------------------


def test_compact_and_packed_states_place_through_the_same_rules():
    from rapid_tpu.parallel.mesh import (
        ShardingShapeError,
        make_mesh,
        shard_faults,
        shard_state,
    )

    if jax.device_count() < 8:
        pytest.skip("needs the forced 8-device CPU mesh")
    vc = _cluster(True, n=60, n_slots=64)
    mesh = make_mesh(jax.devices()[:8])
    sharded = shard_state(vc.state, mesh)
    assert sharded.fd_count.dtype == jnp.int16
    assert sharded.report_bits.dtype == jnp.uint8
    assert (np.asarray(sharded.alive) == np.asarray(vc.state.alive)).all()
    shard_faults(vc.faults, mesh)
    # Packed masks through the SAME table: [64] -> [8] divides 8 devices.
    placed = shard_state(S.pack_masks(vc.state), mesh)
    assert placed.alive.shape == (8,) and placed.alive.dtype == jnp.uint8
    # n=40 packs to [5], which does NOT divide 8 devices: the named
    # validation error, not XLA's opaque per-shard failure.
    bad = S.pack_masks(_cluster(True).state)
    with pytest.raises(ShardingShapeError, match="pad_to_multiple"):
        shard_state(bad, mesh)


def test_checkpoint_roundtrips_compact_state(tmp_path):
    from rapid_tpu.utils.checkpoint import load_engine_state, save_engine_state

    vc = _cluster(True)
    vc.crash([1, 4])
    vc.run_until_converged(64)
    path = tmp_path / "compact.npz"
    save_engine_state(path, vc.cfg, vc.state)
    cfg2, state2 = load_engine_state(path)
    assert cfg2 == vc.cfg and cfg2.compact == 1
    for field in vc.state._fields:
        a, b = np.asarray(getattr(vc.state, field)), np.asarray(getattr(state2, field))
        assert a.dtype == b.dtype and (a == b).all(), field
