"""Device vote-tally kernels vs the FastPaxos host oracle."""

import numpy as np
import pytest

from rapid_tpu.ops.consensus import tally_candidates, tally_sorted
from rapid_tpu.ops.hashing import masked_set_hash
from rapid_tpu.protocol.fast_paxos import FastPaxos, fast_paxos_quorum
from rapid_tpu.types import Endpoint, FastRoundPhase2bMessage
from rapid_tpu.utils.clock import ManualClock

import jax.numpy as jnp


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", i)


def oracle_decision(n, votes):
    """Feed votes (list of proposal tuples or None) to a host FastPaxos."""
    decided = []
    instance = FastPaxos(
        my_addr=ep(0),
        configuration_id=1,
        membership_size=n,
        broadcast_fn=lambda r: None,
        send_fn=lambda d, r: None,
        on_decide=lambda hosts: decided.append(tuple(hosts)),
        clock=ManualClock(),
    )
    for i, proposal in enumerate(votes):
        if proposal is None:
            continue
        instance.handle_message(
            FastRoundPhase2bMessage(sender=ep(100 + i), configuration_id=1, endpoints=proposal)
        )
    return decided[0] if decided else None


def device_votes(n, votes, proposals):
    """Encode per-slot votes as hash lanes. Returns (hi, lo, valid, cand)."""
    prop_hash = {}
    for p_idx, proposal in enumerate(proposals):
        # Stand-in identity lanes: any injective 64-bit encoding works.
        prop_hash[proposal] = (np.uint32(0xA0 + p_idx), np.uint32(0xB0 + p_idx))
    hi = np.zeros(n, dtype=np.uint32)
    lo = np.zeros(n, dtype=np.uint32)
    valid = np.zeros(n, dtype=bool)
    for i, proposal in enumerate(votes):
        if proposal is None:
            continue
        hi[i], lo[i] = prop_hash[proposal]
        valid[i] = True
    cand_hi = np.array([prop_hash[p][0] for p in proposals], dtype=np.uint32)
    cand_lo = np.array([prop_hash[p][1] for p in proposals], dtype=np.uint32)
    cand_valid = np.ones(len(proposals), dtype=bool)
    return hi, lo, valid, (cand_hi, cand_lo, cand_valid), prop_hash


@pytest.mark.parametrize("seed", range(12))
def test_randomized_tally_equivalence(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(4, 200))
    proposals = [tuple(ep(9000 + i) for i in range(j + 1)) for j in range(rng.integers(1, 4))]
    votes = []
    for _ in range(n):
        if rng.random() < 0.15:
            votes.append(None)  # did not vote
        else:
            votes.append(proposals[rng.integers(0, len(proposals))])

    expected = oracle_decision(n, votes)
    hi, lo, valid, (chi, clo, cvalid), prop_hash = device_votes(n, votes, proposals)

    for result in (
        tally_candidates(hi, lo, valid, chi, clo, cvalid, jnp.int32(n)),
        tally_sorted(hi, lo, valid, jnp.int32(n)),
    ):
        if expected is None:
            assert not bool(result.decided)
        else:
            assert bool(result.decided)
            assert (np.uint32(result.winner_hi), np.uint32(result.winner_lo)) == prop_hash[
                expected
            ]


@pytest.mark.parametrize("n", [4, 5, 6, 10, 11, 20, 21, 102, 1000])
def test_exact_quorum_boundary(n):
    quorum = fast_paxos_quorum(n)
    proposal = (ep(1),)
    votes = [proposal] * (quorum - 1) + [None] * (n - quorum + 1)
    hi, lo, valid, cand, _ = device_votes(n, votes, [proposal])
    r = tally_candidates(hi, lo, valid, *cand, jnp.int32(n))
    assert not bool(r.decided)
    votes[quorum - 1] = proposal
    hi, lo, valid, cand, _ = device_votes(n, votes, [proposal])
    r = tally_candidates(hi, lo, valid, *cand, jnp.int32(n))
    assert bool(r.decided)
    r2 = tally_sorted(hi, lo, valid, jnp.int32(n))
    assert bool(r2.decided)


def test_masked_set_hash_properties():
    rng = np.random.default_rng(0)
    n = 64
    hi = rng.integers(0, 2**32, size=n, dtype=np.uint32)
    lo = rng.integers(0, 2**32, size=n, dtype=np.uint32)

    m1 = np.zeros(n, dtype=bool)
    m1[[3, 10, 40]] = True
    h_a = masked_set_hash(jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(m1))

    # Permuting slot order leaves the set hash unchanged.
    perm = rng.permutation(n)
    h_b = masked_set_hash(jnp.asarray(hi[perm]), jnp.asarray(lo[perm]), jnp.asarray(m1[perm]))
    assert (int(h_a[0]), int(h_a[1])) == (int(h_b[0]), int(h_b[1]))

    # Different sets hash differently (w.h.p.).
    m2 = m1.copy()
    m2[41] = True
    h_c = masked_set_hash(jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(m2))
    assert (int(h_a[0]), int(h_a[1])) != (int(h_c[0]), int(h_c[1]))
