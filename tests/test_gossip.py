"""Gossip broadcaster tests: epidemic spread, dedup, relay bounds, and a
full cluster whose broadcast traffic (alerts + consensus votes) rides the
gossip relay instead of unicast-to-all.

The reference documents gossip as the alternate ``IBroadcaster`` strategy
(``IBroadcaster.java:24-29``) without shipping one; these tests pin the
framework's implementation: coverage w.h.p. at the default ln-N fanout,
first-seen relay (no storms), and protocol correctness end-to-end.
"""

import asyncio
import functools
import random

import pytest

from rapid_tpu.messaging.codec import CodecError, decode_request, encode_request
from rapid_tpu.messaging.gossip import GossipBroadcaster
from rapid_tpu.messaging.inprocess import InProcessClient, InProcessNetwork, InProcessServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, GossipMessage, ProbeMessage, Response

from helpers import wait_until

BASE_PORT = 7200


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", BASE_PORT + i)


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=60)

        asyncio.run(with_timeout())

    return wrapper


class RecordingService:
    """Stands in for MembershipService behind the gossip router."""

    def __init__(self) -> None:
        self.received = []

    async def handle_message(self, request):
        self.received.append(request)
        return Response()


async def build_mesh(n: int, fanout=None, ttl=None):
    """N in-process endpoints, each with a gossip broadcaster + router."""
    network = InProcessNetwork()
    nodes = []
    members = [ep(i) for i in range(n)]
    for i in range(n):
        client = InProcessClient(network, ep(i), Settings())
        server = InProcessServer(network, ep(i))
        service = RecordingService()
        broadcaster = GossipBroadcaster(
            client, ep(i), fanout=fanout, ttl=ttl, rng=random.Random(1000 + i)
        )
        broadcaster.set_membership(members)
        server.set_membership_service(broadcaster.router(service))
        await server.start()
        nodes.append((client, server, service, broadcaster))
    return network, nodes


async def teardown_mesh(nodes):
    await asyncio.gather(
        *(s.shutdown() for _, s, _, _ in nodes),
        *(c.shutdown() for c, _, _, _ in nodes),
        return_exceptions=True,
    )


def test_gossip_codec_roundtrip_and_nesting_guard():
    env = GossipMessage(ep(0), 0x0123456789ABCDEF, 5, ProbeMessage(ep(1)))
    assert decode_request(encode_request(env)) == env
    with pytest.raises(CodecError):
        encode_request(GossipMessage(ep(0), 1, 5, env))
    with pytest.raises(CodecError):
        encode_request(GossipMessage(ep(0), 1, 300, ProbeMessage(ep(1))))


def test_gossip_constructor_validation():
    class FakeClient:
        pass

    class NoGossipClient:
        supports_gossip = False

    with pytest.raises(ValueError):
        GossipBroadcaster(FakeClient(), ep(0), ttl=256)
    with pytest.raises(ValueError):
        GossipBroadcaster(FakeClient(), ep(0), fanout=0)
    # The reference-schema interop transport cannot carry gossip envelopes:
    # refuse at wiring time, not as silent per-send failures.
    with pytest.raises(ValueError, match="gossip"):
        GossipBroadcaster(NoGossipClient(), ep(0))


@async_test
async def test_gossip_reaches_every_member():
    """Default ln-N fanout: one broadcast infects all 40 members."""
    n = 40
    _, nodes = await build_mesh(n)
    try:
        payload = ProbeMessage(ep(0))
        nodes[0][3].broadcast(payload)
        assert await wait_until(
            lambda: all(payload in svc.received for _, _, svc, _ in nodes),
            timeout_s=10,
        )
        # First-seen relay: every node delivered the payload exactly once.
        for _, _, svc, _ in nodes:
            assert svc.received.count(payload) == 1
    finally:
        await teardown_mesh(nodes)


@async_test
async def test_gossip_total_transmissions_bounded():
    """Relay-once: total envelope sends <= (N+1) * fanout, not O(N^2)."""
    n = 30
    fanout = 6
    _, nodes = await build_mesh(n, fanout=fanout)
    try:
        nodes[0][3].broadcast(ProbeMessage(ep(0)))
        await wait_until(
            lambda: sum(len(svc.received) for _, _, svc, _ in nodes) >= n - 5,
            timeout_s=10,
        )
        await asyncio.sleep(0.1)  # let in-flight relays settle
        total = sum(b.relays_sent for _, _, _, b in nodes)
        assert total <= (n + 1) * fanout
    finally:
        await teardown_mesh(nodes)


@async_test
async def test_gossip_ttl_zero_never_relays():
    n = 10
    _, nodes = await build_mesh(n, fanout=3, ttl=0)
    try:
        nodes[0][3].broadcast(ProbeMessage(ep(0)))
        await asyncio.sleep(0.2)
        # Only the origin's own fanout transmissions happened; receivers
        # (ttl now 0) did not relay.
        assert sum(b.relays_sent for _, _, _, b in nodes) == 3
    finally:
        await teardown_mesh(nodes)


def fast_settings() -> Settings:
    s = Settings()
    s.batching_window_ms = 20
    s.failure_detector_interval_ms = 50
    s.rpc_timeout_ms = 500
    s.rpc_join_timeout_ms = 2000
    s.rpc_probe_timeout_ms = 200
    s.consensus_fallback_base_delay_ms = 2000
    return s


@async_test
async def test_cluster_over_gossip_broadcast():
    """A 10-node cluster whose alert batches and consensus votes spread by
    gossip: joins converge, and a crash is detected, agreed on, and removed
    everywhere — the full protocol over the alternate broadcast strategy."""
    network = InProcessNetwork()
    settings = fast_settings()
    factory = GossipBroadcaster.factory()
    fd = StaticFailureDetectorFactory()
    clusters = [
        await Cluster.start(
            ep(0), settings=settings, network=network, fd_factory=fd,
            rng=random.Random(0), broadcaster_factory=factory,
        )
    ]
    try:
        for i in range(1, 10):
            clusters.append(
                await Cluster.join(
                    ep(0), ep(i), settings=settings, network=network,
                    fd_factory=fd, rng=random.Random(i),
                    broadcaster_factory=factory,
                )
            )
        assert await wait_until(
            lambda: all(c.membership_size == 10 for c in clusters), timeout_s=30
        )

        # Sanity: broadcast really went through gossip routers.
        assert isinstance(clusters[0].service.broadcaster, GossipBroadcaster)
        assert clusters[0].service.broadcaster.relays_sent > 0

        # Crash one node; the others must converge on 9.
        victim = clusters[5]
        network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await wait_until(
            lambda: all(c.membership_size == 9 for c in survivors), timeout_s=30
        )
        assert all(
            victim.listen_address not in c.membership for c in survivors
        )
    finally:
        await asyncio.gather(
            *(c.shutdown() for c in clusters), return_exceptions=True
        )
