"""DeviceCutDetector vs MultiNodeCutDetector: batch-level equivalence through
the detector SPI, plus a full in-process cluster running with the
device-backed detector on every node."""

import asyncio
import random

import numpy as np
import pytest

from rapid_tpu.protocol.cut_detector import MultiNodeCutDetector
from rapid_tpu.protocol.device_cut_detector import DeviceCutDetector
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import AlertMessage, EdgeStatus, Endpoint, NodeId

K, H, L = 10, 8, 3


def make_view(n, seed=0):
    rng = np.random.default_rng(seed)
    ports = rng.choice(40000, size=n, replace=False) + 1
    endpoints = [Endpoint(f"10.5.{i % 256}.{i // 256}", int(p)) for i, p in enumerate(ports)]
    view = MembershipView(K)
    for i, ep in enumerate(endpoints):
        view.ring_add(ep, NodeId(0, i))
    return view, endpoints


def alerts_for(view, subject, count, status=EdgeStatus.DOWN):
    observers = (
        view.observers_of(subject)
        if view.is_host_present(subject)
        else view.expected_observers_of(subject)
    )
    return [
        AlertMessage(observers[r], subject, status, 0, (r,)) for r in range(count)
    ]


@pytest.mark.parametrize("seed", range(6))
def test_batch_equivalence_randomized(seed):
    view, endpoints = make_view(35, seed)
    rng = np.random.default_rng(seed)
    host = MultiNodeCutDetector(K, H, L)
    device = DeviceCutDetector(K, H, L, max_slots=256)

    host_all, device_all = set(), set()
    # Several random batches accumulating state, then a final batch that
    # pushes a fresh subject to K reports — guaranteeing at least one real
    # release so the equivalence cannot be vacuously satisfied by an
    # always-empty device output.
    batches = []
    for _ in range(3):
        batch = []
        for _ in range(rng.integers(1, 4)):
            subject = endpoints[rng.integers(0, len(endpoints) - 1)]
            batch.extend(alerts_for(view, subject, int(rng.integers(1, K + 1))))
        batches.append(batch)
    batches.append(alerts_for(view, endpoints[-1], K))

    for batch in batches:
        # Order-insensitive comparison: flux-enders first for the host oracle
        # (see tests/test_ops_cut.py docstring).
        by_dst = {}
        for a in batch:
            by_dst.setdefault(a.edge_dst, []).append(a)
        flux, other = [], []
        for dst, msgs in by_dst.items():
            rings = {r for m in msgs for r in m.ring_numbers}
            (flux if L <= len(rings) < H else other).append(msgs)
        ordered = [m for msgs in flux + other for m in msgs]

        host_out = host.aggregate_batch(ordered, view)
        device_out = device.aggregate_batch(ordered, view)
        # Per-batch: device releases are a subset of the host's (mid-batch
        # host releases can be split across device batches)...
        assert device_out <= host_out | host_all
        host_all |= host_out
        device_all |= device_out

    # ...but cumulatively both paths must have released exactly the same
    # members. (A random blocker stuck in [L, H) can legitimately suppress
    # the final batch's release on BOTH paths; non-vacuity — that the device
    # path really does release cuts — is guaranteed by the deterministic
    # tests below, e.g. test_link_invalidation_through_device_detector.)
    assert device_all == host_all


def test_link_invalidation_through_device_detector():
    view, endpoints = make_view(30, 42)
    device = DeviceCutDetector(K, H, L, max_slots=128)
    dst = endpoints[0]
    observers = view.observers_of(dst)
    batch = [AlertMessage(observers[i], dst, EdgeStatus.DOWN, 0, (i,)) for i in range(H - 1)]
    failed = set()
    for i in range(H - 1, K):
        failed.add(observers[i])
        oo = view.observers_of(observers[i])
        batch += [AlertMessage(oo[j], observers[i], EdgeStatus.DOWN, 0, (j,)) for j in range(K)]
    out = device.aggregate_batch(batch, view)
    assert out == failed | {dst}
    assert device.num_proposals == 1


def test_clear_resets():
    view, endpoints = make_view(20, 7)
    device = DeviceCutDetector(K, H, L, max_slots=64)
    subject = endpoints[3]
    out = device.aggregate_batch(alerts_for(view, subject, K), view)
    assert out == {subject}
    device.clear()
    assert device.num_proposals == 0
    out = device.aggregate_batch(alerts_for(view, subject, K), view)
    assert out == {subject}


def test_slot_capacity_overflow_degrades_gracefully():
    # Capacity exhaustion drops alerts for new endpoints (best-effort
    # delivery) instead of wedging the alert handler; existing subjects keep
    # working.
    view, endpoints = make_view(20, 9)
    device = DeviceCutDetector(K, H, L, max_slots=16)
    first = endpoints[0]
    device.aggregate_batch(alerts_for(view, first, 2), view)
    for ep in endpoints[1:]:
        device.aggregate_batch(alerts_for(view, ep, 2), view)  # must not raise
    # The already-slotted subject still reaches a release.
    out = device.aggregate_batch(alerts_for(view, first, K), view)
    assert first in out


def test_cluster_with_device_detector():
    # Full in-process cluster where every node tallies cuts on device.
    from rapid_tpu.messaging.inprocess import InProcessNetwork
    from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
    from rapid_tpu.protocol.cluster import Cluster
    from rapid_tpu.settings import Settings

    def detector_factory(k, h, l):
        return DeviceCutDetector(k, h, l, max_slots=64)

    async def scenario():
        settings = Settings()
        settings.batching_window_ms = 20
        settings.failure_detector_interval_ms = 50
        network = InProcessNetwork()
        fd = StaticFailureDetectorFactory()
        ep0 = Endpoint("127.0.0.1", 35000)
        clusters = [
            await Cluster.start(ep0, settings=settings, network=network, fd_factory=fd,
                                rng=random.Random(0), cut_detector_factory=detector_factory)
        ]
        for i in range(1, 6):
            clusters.append(
                await Cluster.join(ep0, Endpoint("127.0.0.1", 35000 + i), settings=settings,
                                   network=network, fd_factory=fd, rng=random.Random(i),
                                   cut_detector_factory=detector_factory)
            )

        async def converged(size):
            for _ in range(400):
                if all(c.membership_size == size for c in clusters) and (
                    len({tuple(c.membership) for c in clusters}) == 1
                ):
                    return True
                await asyncio.sleep(0.02)
            return False

        assert await converged(6)
        victim = clusters[3]
        network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([victim.listen_address])
        clusters.remove(victim)
        assert await converged(5)
        clusters.append(victim)
        for c in clusters:
            await c.shutdown()

    asyncio.run(asyncio.wait_for(scenario(), timeout=60))
