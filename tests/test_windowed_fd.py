"""Windowed-fraction failure detector (the paper's §7 policy): fail when
>= 40% of the last 10 probes failed; transient blips age out of the window
(unlike the shipped counter policy, which latches them)."""

import asyncio
import functools

from rapid_tpu.monitoring.windowed import WindowedFailureDetector
from rapid_tpu.types import Endpoint, NodeStatus, ProbeResponse


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=30))

    return wrapper


class ScriptedClient:
    """Probe responses played from a script: True = OK, False = drop."""

    def __init__(self, script):
        self.script = list(script)

    async def send_best_effort(self, remote, request):
        ok = self.script.pop(0) if self.script else True
        return ProbeResponse(status=NodeStatus.OK) if ok else None


def make_fd(script, fired):
    return WindowedFailureDetector(
        my_addr=Endpoint("127.0.0.1", 1),
        subject=Endpoint("127.0.0.1", 2),
        client=ScriptedClient(script),
        notifier=lambda: fired.append(True),
        window=10,
        fail_fraction=0.4,
    )


@async_test
async def test_four_of_ten_failures_fire():
    fired = []
    fd = make_fd([True] * 6 + [False] * 4, fired)
    for _ in range(10):
        await fd.tick()
    assert fired == [True]


@async_test
async def test_three_of_ten_failures_do_not_fire():
    fired = []
    fd = make_fd([True, False] * 3 + [True] * 10, fired)  # never 4 in-window
    for _ in range(16):
        await fd.tick()
    assert fired == []


@async_test
async def test_transient_blips_age_out_of_window():
    # 3 early failures, then healthy: the failures scroll out and later
    # isolated blips never accumulate to the threshold — unlike the shipped
    # counter policy, which would latch all of them forever.
    fired = []
    script = [False] * 3 + [True] * 10 + [False] + [True] * 10 + [False] + [True] * 10
    fd = make_fd(script, fired)
    for _ in range(len(script)):
        await fd.tick()
    assert fired == []


@async_test
async def test_window_must_fill_before_firing():
    fired = []
    fd = make_fd([False] * 9, fired)  # 9 failures but window of 10 not full
    for _ in range(9):
        await fd.tick()
    assert fired == []
    await fd.tick()  # 10th probe (script empty -> OK): window full, 9/10 fail
    assert fired == [True]


@async_test
async def test_fires_only_once():
    fired = []
    fd = make_fd([False] * 20, fired)
    for _ in range(20):
        await fd.tick()
    assert fired == [True]


@async_test
async def test_windowed_fd_drives_cluster_eviction():
    # End-to-end: an in-process cluster monitored by the WINDOWED policy
    # detects a blackholed member and evicts it through consensus.
    import random

    from rapid_tpu.messaging.inprocess import (
        InProcessClient,
        InProcessNetwork,
        InProcessServer,
    )
    from rapid_tpu.monitoring.windowed import WindowedFailureDetectorFactory
    from rapid_tpu.protocol.cluster import Cluster
    from rapid_tpu.settings import Settings

    network = InProcessNetwork()
    s = Settings()
    s.batching_window_ms = 20
    s.failure_detector_interval_ms = 25
    eps = [Endpoint("127.0.0.1", 46200 + i) for i in range(4)]
    clusters = []
    try:
        for i, e in enumerate(eps):
            client = InProcessClient(network, e, s)
            server = InProcessServer(network, e)
            fd = WindowedFailureDetectorFactory(e, client, window=4, fail_fraction=0.5)
            if i == 0:
                c = await Cluster.start(e, settings=s, client=client, server=server,
                                        fd_factory=fd, rng=random.Random(0))
            else:
                c = await Cluster.join(eps[0], e, settings=s, client=client,
                                       server=server, fd_factory=fd,
                                       rng=random.Random(i))
            clusters.append(c)

        async def converged(cs, size):
            for _ in range(600):
                if all(c.membership_size == size for c in cs):
                    return True
                await asyncio.sleep(0.02)
            return all(c.membership_size == size for c in cs)

        assert await converged(clusters, 4)
        victim = clusters[3]
        network.blackholed.add(victim.listen_address)
        assert await converged(clusters[:3], 3)
    finally:
        await asyncio.gather(*(c.shutdown() for c in clusters), return_exceptions=True)


@async_test
async def test_host_and_device_windowed_rules_agree():
    # The ACTUAL engine rule (_fd_tick with cfg.fd_window) must fire on
    # exactly the same probe index as the host detector for any outcome
    # script — driven through the real device code, not a replica.
    import jax.numpy as jnp
    import numpy as np

    from rapid_tpu.models.state import EngineConfig, FaultInputs, initial_state
    from rapid_tpu.models.virtual_cluster import _fd_tick

    window, threshold = 6, 3
    n, k = 4, 3
    cfg = EngineConfig(n=n, k=k, h=3, l=1, c=1, fd_threshold=threshold,
                       fd_window=window)
    rng = np.random.default_rng(5)
    key = rng.integers(0, 2**32, size=(k, n), dtype=np.uint32)
    ids = rng.integers(0, 2**32, size=(n,), dtype=np.uint32)
    base_state = initial_state(cfg, key, key, ids, ids, np.ones(n, dtype=bool))
    observer_active = jnp.ones((n, k), dtype=bool)
    edge = (1, 0)  # subject 1, ring 0

    for trial in range(50):
        script = (rng.random(40) < 0.35).tolist()  # True = probe FAILED

        # Host twin (client script: True = OK, so invert).
        fired = []
        fd = WindowedFailureDetector(
            my_addr=Endpoint("127.0.0.1", 1),
            subject=Endpoint("127.0.0.1", 2),
            client=ScriptedClient([not failed for failed in script]),
            notifier=lambda: fired.append(True),
            window=window,
            fail_fraction=threshold / window,
        )
        host_fire = None
        for i in range(len(script)):
            await fd.tick()
            if fired and host_fire is None:
                host_fire = i

        # Device side: step the REAL _fd_tick with the same outcome per
        # round on one edge.
        state = base_state
        dev_fire = None
        for i, failed in enumerate(script):
            probe_fail = np.zeros((n, k), dtype=bool)
            probe_fail[edge] = failed
            faults = FaultInputs.none(cfg)._replace(
                probe_fail=jnp.asarray(probe_fail)
            )
            fd_count, fd_hist, fd_fired, fire = _fd_tick(
                cfg, state, faults, observer_active
            )
            state = state._replace(
                fd_count=fd_count, fd_hist=fd_hist, fd_fired=fd_fired
            )
            if dev_fire is None and bool(np.asarray(fire)[edge]):
                dev_fire = i

        assert host_fire == dev_fire, (
            f"trial {trial}: host fired at {host_fire}, device at {dev_fire}"
        )
