"""Static-analysis build gate.

The reference fails its build on error-prone (-Werror), findbugs, and
checkstyle violations (root pom.xml + build-common/). This environment ships
no ruff/mypy, so the equivalent gate is enforced here with stdlib ``ast``
checks over the whole source tree, run as part of the ordinary test session:
a violation fails the build the same way checkstyle fails the reference's.

Checks: unused module imports, bare ``except:`` clauses, and mutable default
arguments. The resolution tier — undefined names, call-signature
conformance — lives in tools/staticcheck.py, gated by
tests/test_staticcheck.py (the error-prone analog; this file is the
checkstyle analog).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from staticcheck import iter_files as _py_files  # noqa: E402  — one root list for both tiers


def _parse(path: Path):
    return ast.parse(path.read_text(), filename=str(path))


def test_no_unused_imports():
    offenders = []
    for path in _py_files():
        tree = _parse(path)
        imports = []  # (lineno, bound_name)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    imports.append((node.lineno, bound))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    imports.append((node.lineno, bound))
        used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
        # Re-exports: an __all__ entry (or any other string constant EXACTLY
        # equal to the name) counts as a use. Substring matching would let a
        # docstring containing "host" excuse an unused `import os`.
        exact_strings = {
            n.value
            for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
        }
        for lineno, name in imports:
            if name in used or name in exact_strings:
                continue
            offenders.append(f"{path.relative_to(REPO)}:{lineno}: unused import {name!r}")
    assert not offenders, "\n".join(offenders)


def test_no_bare_except():
    offenders = []
    for path in _py_files():
        for node in ast.walk(_parse(path)):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                offenders.append(f"{path.relative_to(REPO)}:{node.lineno}: bare except")
    assert not offenders, "\n".join(offenders)


def test_no_mutable_default_arguments():
    offenders = []
    for path in _py_files():
        for node in ast.walk(_parse(path)):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for default in [*node.args.defaults, *node.args.kw_defaults]:
                    if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(default, ast.Call)
                        and isinstance(default.func, ast.Name)
                        and default.func.id in ("list", "dict", "set")
                    ):
                        offenders.append(
                            f"{path.relative_to(REPO)}:{node.lineno}: "
                            f"mutable default in {node.name}()"
                        )
    assert not offenders, "\n".join(offenders)
