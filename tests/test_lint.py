"""Static-analysis build gate.

The reference fails its build on error-prone (-Werror), findbugs, and
checkstyle violations (root pom.xml + build-common/). This environment ships
no ruff/mypy, so the equivalent gate is enforced here with stdlib ``ast``
checks over the whole source tree, run as part of the ordinary test session:
a violation fails the build the same way checkstyle fails the reference's.

Checks: unused module imports, bare ``except:`` clauses, mutable default
arguments, and two observability-discipline rules over ``rapid_tpu/`` only:
no bare ``print()`` for runtime diagnostics (the library speaks through
``logging``, ``Metrics``, and the flight recorder — exposition that a
production deployment can route; stdout it cannot), and every
flight-recorder ``record()`` call site names its event via the registered
``EventName`` enum (free-form strings would silently fork the event
vocabulary and break traceview's causal phase ordering). The resolution
tier — undefined names, call-signature conformance — lives in
tools/staticcheck.py, gated by tests/test_staticcheck.py (the error-prone
analog; this file is the checkstyle analog).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from staticcheck import iter_files as _py_files  # noqa: E402  — one root list for both tiers


def _parse(path: Path):
    return ast.parse(path.read_text(), filename=str(path))


def test_no_unused_imports():
    offenders = []
    for path in _py_files():
        tree = _parse(path)
        imports = []  # (lineno, bound_name)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    imports.append((node.lineno, bound))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    imports.append((node.lineno, bound))
        used = {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
        # Re-exports: an __all__ entry (or any other string constant EXACTLY
        # equal to the name) counts as a use. Substring matching would let a
        # docstring containing "host" excuse an unused `import os`.
        exact_strings = {
            n.value
            for n in ast.walk(tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
        }
        for lineno, name in imports:
            if name in used or name in exact_strings:
                continue
            offenders.append(f"{path.relative_to(REPO)}:{lineno}: unused import {name!r}")
    assert not offenders, "\n".join(offenders)


def test_no_bare_except():
    offenders = []
    for path in _py_files():
        for node in ast.walk(_parse(path)):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                offenders.append(f"{path.relative_to(REPO)}:{node.lineno}: bare except")
    assert not offenders, "\n".join(offenders)


def test_library_has_no_bare_print():
    """rapid_tpu/ must not print() runtime diagnostics: the structured
    channels (logging, Metrics, FlightRecorder, the exposition snapshot) are
    scrapeable and mergeable; stdout is neither. Examples/tools/tests are
    exempt — a CLI's job is to print."""
    offenders = []
    for path in _py_files(("rapid_tpu",)):
        for node in ast.walk(_parse(path)):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                offenders.append(
                    f"{path.relative_to(REPO)}:{node.lineno}: bare print() — "
                    "use logging / Metrics / FlightRecorder"
                )
    assert not offenders, "\n".join(offenders)


def test_recorder_events_come_from_registered_enum():
    """Every flight-recorder record() call site in rapid_tpu/ must name its
    event as ``EventName.<member>`` — the registered vocabulary traceview's
    causal phase ranking is defined over. (Matched: any ``*.record(...)`` or
    ``self._record(...)`` call; ``Metrics.record_ms`` has a different
    attribute name and is not caught.)"""
    from rapid_tpu.utils.flight_recorder import EventName

    offenders = []
    for path in _py_files(("rapid_tpu",)):
        for node in ast.walk(_parse(path)):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("record", "_record")
            ):
                continue
            args = list(node.args)
            name_arg = args[0] if args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"), None
            )
            ok = (
                isinstance(name_arg, ast.Attribute)
                and isinstance(name_arg.value, ast.Name)
                and name_arg.value.id == "EventName"
                and name_arg.attr in EventName.__members__
            )
            # A record() call forwarding an already-checked EventName
            # parameter (the cut detector's _record helper body) is fine.
            forwards = isinstance(name_arg, ast.Name) and name_arg.id == "name"
            if not (ok or forwards):
                offenders.append(
                    f"{path.relative_to(REPO)}:{node.lineno}: record() event "
                    "must be an EventName member"
                )
    assert not offenders, "\n".join(offenders)


def test_ledger_events_come_from_registered_vocabulary():
    """Every run-ledger ``emit()`` call site in the library, bench.py, and
    tools/ must name its event as ``LedgerEvent.<member>`` — the registered
    vocabulary tools/perfview.py's timeline rendering (and the watchdog's
    per-stage budgets) are defined over. Mirror of the flight-recorder
    EventName rule above; the resolution-tier twin lives in
    tools/analysis/ledger.py (check_ledger) so the CLI gate catches it too.
    Only files importing rapid_tpu.utils.ledger are in scope — unrelated
    ``emit`` methods are not."""
    from staticcheck import check_ledger

    offenders = []
    for path in _py_files(("rapid_tpu", "bench.py", "tools")):
        offenders.extend(str(f) for f in check_ledger(path))
    assert not offenders, "\n".join(offenders)


def test_protocol_reads_no_wall_clock():
    """The clock-disciplined packages (rapid_tpu/protocol/,
    rapid_tpu/monitoring/ — failure detectors are timing consumers too —
    and, since ISSUE 15, rapid_tpu/serving/ — the supervision tier's
    deadline/backoff decisions must replay under an injected clock) must
    not read wall clocks directly (time.time, time.time_ns, datetime.now,
    ...): the clock is injected (utils/clock.py, the Metrics registry's
    now_ms source, the serving drivers' clock= parameter), which is what
    keeps phase timings correct under simulated time and fault drills
    deterministic. The resolution-tier check lives in
    tools/analysis/clocks.py (check_clock_injection) so the CLI gate
    catches it too; this test runs it as part of the ordinary session.
    The tree is currently clean — keep it that way."""
    from staticcheck import check_clock_injection

    offenders = []
    for path in _py_files(
        ("rapid_tpu/protocol", "rapid_tpu/monitoring", "rapid_tpu/serving")
    ):
        offenders.extend(str(f) for f in check_clock_injection(path))
    assert not offenders, "\n".join(offenders)


def test_clock_injection_covers_the_serving_tier():
    """ISSUE 15: the serving supervision tier's timing reads are
    clock-disciplined too — a wall-clock read in a serving module is a
    finding (the wedge-deadline decision path must be injectable), while
    the same source outside the disciplined prefixes stays silent."""
    import textwrap

    from staticcheck import REPO as SC_REPO, check_clock_injection

    offending = textwrap.dedent(
        """
        import time

        def deadline_exceeded(t0, budget_ms):
            return (time.monotonic() - t0) * 1000.0 >= budget_ms
        """
    )
    inside = SC_REPO / "rapid_tpu" / "serving" / "_lint_probe.py"
    findings = check_clock_injection(inside, source=offending)
    assert [f.check for f in findings] == ["clock-injection"]
    outside = SC_REPO / "rapid_tpu" / "sim" / "_lint_probe.py"
    assert check_clock_injection(outside, source=offending) == []


def test_clock_injection_check_catches_both_spellings():
    """The rule itself must fire on both the attribute and the from-import
    spelling, and stay silent outside rapid_tpu/protocol/."""
    import textwrap

    from staticcheck import REPO as SC_REPO, check_clock_injection

    offending = textwrap.dedent(
        """
        import time
        from time import perf_counter

        def now():
            return time.time() + perf_counter()
        """
    )
    inside = SC_REPO / "rapid_tpu" / "protocol" / "_lint_probe.py"
    findings = check_clock_injection(inside, source=offending)
    assert len(findings) == 2, findings
    assert all(f.check == "clock-injection" for f in findings)
    outside = SC_REPO / "rapid_tpu" / "utils" / "_lint_probe.py"
    assert check_clock_injection(outside, source=offending) == []


def test_full_sweep_with_compiled_gate_stays_under_budget():
    """The whole-tree sweep INCLUDING the compiled-artifact families — the
    sharding AST lint, the device_program gate, the ISSUE-18 cost-model
    geometry ladder, and the ISSUE-19 jaxpr provenance trace — must fit
    the ordinary test session: <160 s of process CPU for the collections
    (the base registry compiles plus the N/K/tenant ladder points plus the
    compile-free registry trace; these cost real time and this budget may
    grow with the registry, the analysis-only budget must not) and <30 s
    for the family sweep itself, budgeted separately so neither can hide
    the other going superlinear. Collection results — base facts, ladder,
    AND dataflow payload — are cached per session, so only the FIRST
    sweep in a process pays them (the persistent XLA cache is deliberately
    NOT used for the audit — see
    device_program._scoped_disable_persistent_cache); the identity
    assertions pin that the session caches are real."""
    import time

    import staticcheck

    started = time.process_time()
    first = staticcheck.collect_facts()
    ladder = staticcheck.collect_ladder()
    dataflow_payload, _ = staticcheck.collect_dataflow()
    compile_s = time.process_time() - started
    # Fresh compiles when this file runs standalone; a session-cache hit
    # when test_hlo_gate.py (base), test_cost_model.py, and
    # test_dataflow.py ran first — the check.sh ordering. The cost is
    # pinned in BOTH orderings.
    assert compile_s < 160.0, (
        f"collections (registry + cost ladder + dataflow trace) used "
        f"{compile_s:.1f}s CPU (budget 160s)"
    )
    started = time.process_time()
    findings = staticcheck.run()
    sweep_s = time.process_time() - started
    assert not findings, "\n".join(str(f) for f in findings)
    assert sweep_s < 30.0, (
        f"tree sweep over cached facts used {sweep_s:.1f}s CPU (budget 30s)"
    )
    assert staticcheck.collect_facts() is first  # session cache holds
    assert staticcheck.collect_ladder() is ladder  # ladder cache holds
    assert staticcheck.collect_dataflow()[0] is dataflow_payload  # trace cache


def test_library_sweep_is_clean_under_all_families():
    """The per-file resolution families (incl. the dispatch and taskflow
    analyzers added with the wire-conformance tier) are clean over
    rapid_tpu/ — the library keeps its failure paths justified or narrow,
    its background tasks tracked, and its dispatch chain exhaustive. The
    whole-tree gate (with the deadcode + wire-lock tree checks) lives in
    test_staticcheck.py; this pin localizes a regression to the library."""
    import staticcheck

    findings = staticcheck.run(("rapid_tpu",))
    assert not findings, "\n".join(str(f) for f in findings)


def test_no_mutable_default_arguments():
    offenders = []
    for path in _py_files():
        for node in ast.walk(_parse(path)):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for default in [*node.args.defaults, *node.args.kw_defaults]:
                    if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(default, ast.Call)
                        and isinstance(default.func, ast.Name)
                        and default.func.id in ("list", "dict", "set")
                    ):
                        offenders.append(
                            f"{path.relative_to(REPO)}:{node.lineno}: "
                            f"mutable default in {node.name}()"
                        )
    assert not offenders, "\n".join(offenders)
