"""Unit tests for the chaos-simulation fault model (rapid_tpu/sim/faults.py)
and the per-node clock (utils/clock.NodeClock): schedule serialization round
trips, lifecycle validation, shaper determinism, and clock skew/pause
semantics — the pieces everything else in the subsystem builds on."""

import asyncio
import functools

import pytest

from rapid_tpu.sim.faults import (
    FaultEvent,
    FaultSchedule,
    LinkShaper,
    ScheduleError,
    loss_as_engine_delivery,
    schedule_rng,
)
from rapid_tpu.sim.fuzz import FAMILIES, random_schedule
from rapid_tpu.utils.clock import ManualClock, NodeClock


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=60)

        asyncio.run(with_timeout())

    return wrapper


# ---------------------------------------------------------------------------
# schedule model
# ---------------------------------------------------------------------------


def test_schedule_json_round_trip_is_identity():
    schedule = FaultSchedule(
        n0=8, n_slots=12, seed=77, name="rt",
        converge_budget_ms=44_000.0, phase_budget_ms=33_000.0,
        events=[
            FaultEvent("loss", args={"permille": 50}),
            FaultEvent("join", (8, 9), settle=False),
            FaultEvent("crash", (3,), dwell_ms=250.5),
            FaultEvent("clock_skew", (2,), args={"offset_ms": 100.0}),
            FaultEvent("partition", (4,), dwell_ms=1000),
            FaultEvent("heal_partitions"),
        ],
    )
    schedule.validate()
    restored = FaultSchedule.from_json(schedule.to_json())
    assert restored == schedule
    # And the round trip is stable at the byte level (repro files diff clean).
    assert restored.to_json() == schedule.to_json()


def test_unknown_kind_raises_at_construction():
    # The vocabulary is closed at the point a kind is MINTED: a typo'd kind
    # must never ride silently into a schedule file the runner then crashes
    # on mid-scenario (the chaosvocab lint pins the static half of this).
    with pytest.raises(ScheduleError, match="unknown kind"):
        FaultEvent("explode", (1,))  # chaos-kind-ok: the pin IS the defect
    with pytest.raises(ScheduleError, match="unknown kind"):
        FaultSchedule.from_dict({
            "version": 1, "n0": 8, "n_slots": 12,
            "events": [{"kind": "explode", "slots": [1]}],
        })


@pytest.mark.parametrize("events,message", [
    ([FaultEvent("crash", (0,))], "slot 0"),
    ([FaultEvent("crash", (9,))], "non-live"),
    ([FaultEvent("join", (1,))], "non-fresh"),
    ([FaultEvent("restart", (3,))], "never-removed"),
    ([FaultEvent("leave", (1, 2))], "exactly one"),
    ([FaultEvent("loss", args={"permille": 2000})], "permille"),
    ([FaultEvent("delay", args={"min_ms": 5, "max_ms": 1})], "min_ms"),
    ([FaultEvent("clock_resume", (1,))], "paused"),
    ([FaultEvent("drop_first_n", (1,), args={"message": "fast_round", "count": 2})],
     "message must be one of"),
    ([FaultEvent("drop_first_n", (1,), args={"message": "probe"})], "count"),
    ([FaultEvent("clock_pause", (1,)),
      FaultEvent("clock_skew", (1,), args={"offset_ms": 5.0})], "is paused"),
    ([FaultEvent("crash", (1,), settle=False)], "last event must settle"),
])
def test_validate_rejects_ill_formed_schedules(events, message):
    schedule = FaultSchedule(n0=8, n_slots=12, events=events)
    with pytest.raises(ScheduleError, match=message):
        schedule.validate()


def test_membership_phases_group_overlapped_events():
    schedule = FaultSchedule(
        n0=8, n_slots=12,
        events=[
            FaultEvent("loss", args={"permille": 10}),
            FaultEvent("join", (8, 9), settle=False),
            FaultEvent("crash", (3,)),
            FaultEvent("leave", (4,)),
        ],
    )
    schedule.validate()
    assert [
        [(e.kind, e.slots) for e in group]
        for group in schedule.membership_phases()
    ] == [
        [("join", (8, 9)), ("crash", (3,))],
        [("leave", (4,))],
    ]
    assert schedule.expected_members() == 8 + 2 - 1 - 1
    assert schedule.expected_removed_slots() == {3, 4}


def test_restart_undoes_removal_in_expected_sets():
    schedule = FaultSchedule(
        n0=8, n_slots=12,
        events=[FaultEvent("crash", (5,)), FaultEvent("restart", (5,))],
    )
    schedule.validate()
    assert schedule.expected_removed_slots() == set()
    assert schedule.expected_members() == 8
    assert not schedule.engine_compatible  # restarts cannot replay on device


def test_generated_schedules_validate_across_many_seeds():
    # The generator's own sizing rules must keep every draw well-formed
    # (validate() raising inside random_schedule would fail loudly here).
    for seed in range(200):
        schedule = random_schedule(seed)
        assert schedule.events
    for name, family in FAMILIES.items():
        for seed in range(25):
            family(seed).validate()


def test_loss_as_engine_delivery_maps_the_shared_definition():
    assert loss_as_engine_delivery(50) == {
        "delivery_prob_permille": 50,
        "delivery_spread": 2,
    }
    assert loss_as_engine_delivery(0)["delivery_spread"] == 0
    with pytest.raises(ScheduleError):
        loss_as_engine_delivery(1001)


# ---------------------------------------------------------------------------
# shaper determinism
# ---------------------------------------------------------------------------


def test_shaper_draws_are_a_pure_function_of_the_seed():
    def draws(seed):
        schedule = FaultSchedule(n0=4, n_slots=4, seed=seed)
        shaper = LinkShaper(schedule_rng(schedule), ManualClock())
        shaper.loss_permille = 300
        shaper.delay_max_ms = 20.0
        shaper.dup_permille = 100
        return [shaper.plan("a", "b") for _ in range(64)]

    assert draws(7) == draws(7)
    assert draws(7) != draws(8)


# ---------------------------------------------------------------------------
# NodeClock: skew and pause
# ---------------------------------------------------------------------------


def test_node_clock_skew_shifts_readings_only_per_node():
    base = ManualClock()
    a, b = NodeClock(base), NodeClock(base)
    base.advance_ms(1000)
    a.set_skew(250.0)
    assert a.now_ms() == 1250.0
    assert b.now_ms() == 1000.0
    assert base.now_ms() == 1000.0


@async_test
async def test_node_clock_pause_freezes_time_and_parks_timers():
    base = ManualClock()
    clock = NodeClock(base)
    fired = []
    clock.call_later_ms(100, lambda: fired.append("t1"))
    clock.pause()
    frozen = clock.now_ms()
    base.advance_ms(500)  # t1 comes due during the pause: parked, not run
    assert fired == []
    assert clock.now_ms() == frozen  # readings are frozen too
    clock.call_later_ms(50, lambda: fired.append("t2"))
    base.advance_ms(500)
    assert fired == []
    clock.resume()
    # Every timer that came due during the freeze is overdue: all fire on
    # the next tick after the thaw (re-armed at delay 0), in park order.
    base.advance_ms(1)
    assert fired == ["t1", "t2"]
    assert clock.now_ms() == base.now_ms()  # skew-free clock tracks base again


@async_test
async def test_node_clock_cancel_works_across_a_pause():
    base = ManualClock()
    clock = NodeClock(base)
    fired = []
    handle = clock.call_later_ms(100, lambda: fired.append("x"))
    clock.pause()
    base.advance_ms(200)
    handle.cancel()  # cancelled while parked
    clock.resume()
    base.advance_ms(10)
    assert fired == []


@async_test
async def test_node_clock_sleep_suspends_through_a_pause():
    base = ManualClock()
    clock = NodeClock(base)
    done = []

    async def sleeper():
        await clock.sleep_ms(100)
        done.append(True)

    task = asyncio.ensure_future(sleeper())
    await asyncio.sleep(0)
    clock.pause()
    base.advance_ms(1000)
    for _ in range(5):
        await asyncio.sleep(0)
    assert not done  # the node is frozen; its sleeper must not wake
    clock.resume()
    base.advance_ms(1)
    for _ in range(5):
        await asyncio.sleep(0)
    assert done
    await task


def test_pause_is_idempotent_and_skew_rejected_while_paused():
    clock = NodeClock(ManualClock())
    clock.pause()
    clock.pause()  # no-op, not an error
    with pytest.raises(RuntimeError):
        clock.set_skew(10.0)
    clock.resume()
    clock.resume()  # no-op
    clock.set_skew(10.0)


def test_schedule_rng_is_stable_across_processes():
    # random.Random(str) seeds via a hash of the bytes, not PYTHONHASHSEED,
    # so a repro file replayed in a fresh process draws identically. Pin the
    # first draws; a change here means every committed repro is invalidated.
    rng = schedule_rng(FaultSchedule(n0=2, n_slots=2, seed=123))
    assert [rng.randrange(1000) for _ in range(3)] == [240, 72, 796]
