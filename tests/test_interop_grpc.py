"""Reference-wire interop: protobuf conversion round-trips against the real
protobuf runtime, and a full cluster over the gRPC transport speaking
remoting.MembershipService/sendRequest."""

import asyncio
import functools
import random

import pytest

from rapid_tpu.interop.convert import (
    request_from_proto,
    request_to_proto,
    response_from_proto,
    response_to_proto,
)
from rapid_tpu.interop.proto_schema import proto_class
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.settings import Settings
from rapid_tpu import types as t
from rapid_tpu.types import Endpoint

from tests.test_messaging import ALL_REQUESTS, ALL_RESPONSES


def async_test(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        async def with_timeout():
            await asyncio.wait_for(fn(*args, **kwargs), timeout=60)

        asyncio.run(with_timeout())

    return wrapper


# GossipMessage is framework-native: the reference's rapid.proto has no
# gossip envelope (IBroadcaster.java names gossip but never ships it), so it
# is deliberately NOT representable on the interop transport.
INTEROP_REQUESTS = [r for r in ALL_REQUESTS if not isinstance(r, t.GossipMessage)]


@pytest.mark.parametrize("request_msg", INTEROP_REQUESTS, ids=lambda r: type(r).__name__)
def test_request_proto_roundtrip(request_msg):
    # Serialize through the real protobuf runtime: proves wire-format
    # well-formedness, not just in-memory symmetry.
    wire = request_to_proto(request_msg).SerializeToString()
    parsed = proto_class("RapidRequest")()
    parsed.ParseFromString(wire)
    assert request_from_proto(parsed) == request_msg


def test_gossip_envelope_not_representable_in_reference_schema():
    """The design line the interop layer draws: gossip envelopes cannot
    cross into a reference-schema cluster."""
    env = t.GossipMessage(
        t.Endpoint("127.0.0.1", 1), 1, 1, t.ProbeMessage(t.Endpoint("127.0.0.1", 2))
    )
    with pytest.raises(KeyError):
        request_to_proto(env)


@pytest.mark.parametrize("response_msg", ALL_RESPONSES, ids=lambda r: type(r).__name__)
def test_response_proto_roundtrip(response_msg):
    wire = response_to_proto(response_msg).SerializeToString()
    parsed = proto_class("RapidResponse")()
    parsed.ParseFromString(wire)
    assert response_from_proto(parsed) == response_msg


def test_field_numbers_match_reference_layout():
    # Spot-check the wire-critical field numbers against the documented
    # schema (SURVEY §2.4 / rapid.proto): RapidRequest oneof 1..10 for the
    # reference types (11 is the native-only gossip envelope, 12-14 the
    # hierarchical-membership extension — both outside rapid.proto),
    # JoinResponse fields 1..7, AlertMessage nodeId=6/metadata=7.
    req = proto_class("RapidRequest").DESCRIPTOR
    assert [f.number for f in req.oneofs[0].fields] == (
        list(range(1, 11)) + [12, 13, 14]
    )
    join_response = proto_class("JoinResponse").DESCRIPTOR
    assert [f.name for f in join_response.fields] == [
        "sender", "statusCode", "configurationId", "endpoints",
        "identifiers", "metadataKeys", "metadataValues",
    ]
    alert = proto_class("AlertMessage").DESCRIPTOR
    assert alert.fields_by_name["nodeId"].number == 6
    assert alert.fields_by_name["metadata"].number == 7
    batched = proto_class("BatchedAlertMessage").DESCRIPTOR
    assert batched.fields_by_name["messages"].number == 3  # rapid.proto skips 2
    probe = proto_class("ProbeMessage").DESCRIPTOR
    assert probe.fields_by_name["payload"].number == 3


@async_test
async def test_cluster_over_grpc_with_failure():
    from rapid_tpu.interop.grpc_transport import GrpcClient, GrpcServer

    settings = Settings()
    settings.batching_window_ms = 20
    settings.failure_detector_interval_ms = 50
    settings.rpc_timeout_ms = 500
    settings.rpc_join_timeout_ms = 2000
    settings.rpc_probe_timeout_ms = 200
    fd = StaticFailureDetectorFactory()

    def ep(i):
        return Endpoint("127.0.0.1", 38300 + i)

    clusters = [
        await Cluster.start(ep(0), settings=settings, client=GrpcClient(ep(0), settings),
                            server=GrpcServer(ep(0)), fd_factory=fd, rng=random.Random(0))
    ]
    for i in range(1, 5):
        clusters.append(
            await Cluster.join(ep(0), ep(i), settings=settings,
                               client=GrpcClient(ep(i), settings),
                               server=GrpcServer(ep(i)), fd_factory=fd, rng=random.Random(i))
        )
    try:
        async def converged(cs, size):
            for _ in range(600):
                if all(c.membership_size == size for c in cs) and (
                    len({tuple(c.membership) for c in cs}) == 1
                ):
                    return True
                await asyncio.sleep(0.02)
            return False

        assert await converged(clusters, 5)
        victim = clusters[2]
        await victim.shutdown()
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await converged(survivors, 4)
        assert all(victim.listen_address not in c.membership for c in survivors)
    finally:
        await asyncio.gather(*(c.shutdown() for c in clusters), return_exceptions=True)
