"""The stability-band separation pin (ISSUE 12 acceptance): the SAME
Byzantine false-alert scenario, judged on both sides of the H watermark.

The paper's stability claim says flaky reports between the L and H
watermarks DELAY — never trigger — a view change. These tests push that
claim to observers that LIE (reports about a node that never failed) and
pin the exact separation the ``stability`` oracle enforces:

- **held in [L, H)** — no eviction of the healthy subject, no cut at all,
  and the run converges once the alerts cease (the cluster simply never
  moved);
- **pushed past H** — the healthy subject IS evicted (the adversary buys a
  wrong cut), but the eviction is one agreed, chain-consistent decision:
  every node delivers the same view sequence and the full oracle battery
  (agreement, chain prefix, membership outcome vs the schedule's own
  accounting) holds.

Both runs are deterministic across reruns — a repro file of either IS the
scenario. Geometry mirrors the fuzz families (n0=8 of 12 slots) so these
schedules are fleet-compilable too (tests/test_tenancy_chaos.py covers the
engine grain)."""

from rapid_tpu.sim.faults import (
    WATERMARK_H,
    WATERMARK_L,
    FaultEvent,
    FaultSchedule,
)
from rapid_tpu.sim.fuzz import run_schedule
from rapid_tpu.sim.oracles import check_all

SUBJECT = 3
LIAR = 5


def _band_schedule(storm_rings: int, name: str) -> FaultSchedule:
    """One liar holds the subject's cumulative count at H-1 distinct rings
    (one short of eviction — the adversarially hardest stable point), then
    a two-colluder storm claims ``storm_rings`` rings. With
    ``storm_rings == H-1`` the storm only RE-claims (per-ring dedup keeps
    the tally in the band); with ``storm_rings == H`` it adds exactly one
    fresh ring and tops the count up to H. The two schedules differ by ONE
    claimed ring — that ring is the whole separation."""
    return FaultSchedule(
        n0=8, n_slots=12, seed=0, name=name,
        events=[
            FaultEvent("false_alert", (LIAR,),
                       args={"subject": SUBJECT,
                             "rings": list(range(WATERMARK_H - 1))},
                       dwell_ms=2_000),
            FaultEvent("alert_storm", (4, 6),
                       args={"subject": SUBJECT,
                             "rings": list(range(storm_rings))},
                       dwell_ms=2_000),
        ],
    )


def test_sub_h_false_alerts_never_evict_and_the_run_converges():
    # Held at H-1: inside the stable band, one report short of eviction —
    # the adversarially hardest stable point.
    schedule = _band_schedule(WATERMARK_H - 1, "band/stable")
    assert WATERMARK_L <= WATERMARK_H - 1 < WATERMARK_H
    result = run_schedule(schedule)
    assert check_all(result) == []
    # No view change fired anywhere after bring-up: zero cuts, nobody
    # kicked — the configuration chain never moved.
    assert result.cuts == []
    assert result.kicked == []
    # And the subject is still a member everywhere once the alerts cease.
    assert result.endpoints[SUBJECT] in result.final_membership
    assert result.final_converged
    assert len(result.final_membership) == 8


def test_past_h_false_alerts_evict_with_one_agreed_chain():
    # The SAME shape pushed one ring past the band: the lie crosses H and
    # the healthy subject is evicted — wrongly, but CONSISTENTLY.
    schedule = _band_schedule(WATERMARK_H, "band/crossed")
    assert schedule.adversarial_crossings()  # the schedule accounts the lie
    assert schedule.expected_members() == 7
    result = run_schedule(schedule)
    # The full battery holds: agreement, chain consistency, membership
    # outcome (vs the schedule's own ≥H accounting), stability (the oracle
    # only protects sub-H subjects), bounded convergence.
    assert check_all(result) == []
    # Exactly one cut, agreed by every live node: the wrong-but-consistent
    # eviction of the subject.
    assert len(result.cuts) == 1
    assert result.endpoints[SUBJECT] not in result.final_membership
    assert len(result.final_membership) == 7
    # The evicted subject learned of its own eviction (KICKED) — it was
    # alive to hear the verdict (never actually crashed).
    assert SUBJECT in result.kicked


def test_band_separation_is_deterministic_across_reruns():
    # Both sides of the band replay bit-identically: same cuts, same
    # chains, same outcome — a written repro IS the scenario.
    for rings in (WATERMARK_H - 1, WATERMARK_H):
        a = run_schedule(_band_schedule(rings, "band/det"))
        b = run_schedule(_band_schedule(rings, "band/det"))
        assert a.cuts == b.cuts
        assert a.configs == b.configs
        assert a.final_membership == b.final_membership
        assert sorted(a.kicked) == sorted(b.kicked)


def test_up_lies_about_a_present_host_are_filtered():
    # The no-op lie: UP claims about a host that is already in the view are
    # dropped by every receiver — kept for coverage of the filter branch.
    schedule = FaultSchedule(
        n0=8, n_slots=12, seed=0, name="band/up-noop",
        events=[
            FaultEvent("false_alert", (LIAR,),
                       args={"subject": SUBJECT, "rings": [0, 1],
                             "status": "UP"},
                       dwell_ms=1_000),
        ],
    )
    result = run_schedule(schedule)
    assert check_all(result) == []
    assert result.cuts == []
    assert len(result.final_membership) == 8
