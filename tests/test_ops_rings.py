"""Device ring-topology kernels vs the host MembershipView oracle."""

import numpy as np
import pytest

from rapid_tpu.ops.rings import (
    endpoint_ring_keys,
    predecessor_of_keys,
    ring_perms,
    ring_topology,
    ring_topology_from_perm,
)
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import Endpoint, NodeId


def make_endpoints(n, seed=0):
    rng = np.random.default_rng(seed)
    ports = rng.choice(50000, size=n, replace=False) + 1
    return [Endpoint(f"10.0.{i % 256}.{i // 256}", int(p)) for i, p in enumerate(ports)]


@pytest.mark.parametrize("n,k", [(4, 3), (10, 10), (100, 10), (257, 7)])
def test_topology_matches_view(n, k):
    endpoints = make_endpoints(n, seed=n)
    view = MembershipView(k)
    for i, ep in enumerate(endpoints):
        view.ring_add(ep, NodeId(0, i))

    key_hi, key_lo = endpoint_ring_keys(endpoints, k)
    alive = np.ones(n, dtype=bool)
    topo = ring_topology(key_hi, key_lo, alive)
    obs = np.asarray(topo.obs_idx)
    subj = np.asarray(topo.subj_idx)

    slot_of = {ep: i for i, ep in enumerate(endpoints)}
    for i, ep in enumerate(endpoints):
        expected_obs = [slot_of[o] for o in view.observers_of(ep)]
        expected_subj = [slot_of[s] for s in view.subjects_of(ep)]
        assert obs[:, i].tolist() == expected_obs
        assert subj[:, i].tolist() == expected_subj


def test_topology_with_dead_slots():
    n, k = 60, 10
    endpoints = make_endpoints(n, seed=3)
    rng = np.random.default_rng(7)
    alive = rng.random(n) > 0.3

    view = MembershipView(k)
    for i, ep in enumerate(endpoints):
        if alive[i]:
            view.ring_add(ep, NodeId(0, i))

    key_hi, key_lo = endpoint_ring_keys(endpoints, k)
    topo = ring_topology(key_hi, key_lo, alive)
    obs = np.asarray(topo.obs_idx)
    subj = np.asarray(topo.subj_idx)

    slot_of = {ep: i for i, ep in enumerate(endpoints)}
    for i, ep in enumerate(endpoints):
        if not alive[i]:
            assert (obs[:, i] == -1).all()
            assert (subj[:, i] == -1).all()
            continue
        assert obs[:, i].tolist() == [slot_of[o] for o in view.observers_of(ep)]
        assert subj[:, i].tolist() == [slot_of[s] for s in view.subjects_of(ep)]


def test_topology_single_and_two_nodes():
    endpoints = make_endpoints(5, seed=9)
    k = 10
    key_hi, key_lo = endpoint_ring_keys(endpoints, k)

    alive = np.zeros(5, dtype=bool)
    alive[2] = True
    topo = ring_topology(key_hi, key_lo, alive)
    # A lone node has no observers (MembershipView.java:240-242).
    assert (np.asarray(topo.obs_idx)[:, 2] == -1).all()

    alive[4] = True
    topo = ring_topology(key_hi, key_lo, alive)
    assert (np.asarray(topo.obs_idx)[:, 2] == 4).all()
    assert (np.asarray(topo.obs_idx)[:, 4] == 2).all()


@pytest.mark.parametrize("n,k,alive_frac", [
    (4, 3, 1.0),      # minimum viable ring
    (64, 10, 0.9),    # sparse deaths
    (257, 7, 0.5),    # half dead, odd N
    (100, 10, 0.02),  # near-empty: 2 alive
    (50, 5, 0.0),     # nobody alive
    (33, 4, None),    # exactly ONE alive (below the 2-node floor)
])
def test_from_perm_matches_sorting_topology(n, k, alive_frac):
    # The sort-free scan path (used by every view change) must be
    # bit-identical to the argsort definition across the aliveness range,
    # including the <2-alive floor where every entry is -1.
    rng = np.random.default_rng(n * 31 + k)
    key_hi = rng.integers(0, 2**32, size=(k, n), dtype=np.uint32)
    key_lo = rng.integers(0, 2**32, size=(k, n), dtype=np.uint32)
    if alive_frac is None:
        alive = np.zeros(n, dtype=bool)
        alive[n // 2] = True
    else:
        alive = rng.random(n) < alive_frac
    perm = ring_perms(key_hi, key_lo)
    want = ring_topology(key_hi, key_lo, alive)
    got = ring_topology_from_perm(perm, alive)
    np.testing.assert_array_equal(np.asarray(got.obs_idx), np.asarray(want.obs_idx))
    np.testing.assert_array_equal(np.asarray(got.subj_idx), np.asarray(want.subj_idx))
    np.testing.assert_array_equal(np.asarray(got.order), np.asarray(want.order))

    # The joiner-gatekeeper query must agree between its sorting and
    # perm-scan paths too (inject_join_wave passes the engine's perm).
    j = min(5, n)
    qhi = rng.integers(0, 2**32, size=(k, j), dtype=np.uint32)
    qlo = rng.integers(0, 2**32, size=(k, j), dtype=np.uint32)
    np.testing.assert_array_equal(
        np.asarray(predecessor_of_keys(key_hi, key_lo, alive, qhi, qlo)),
        np.asarray(
            predecessor_of_keys(key_hi, key_lo, alive, qhi, qlo, perm=perm)
        ),
    )


def test_expected_observers_of_joiners():
    n, k, j = 50, 10, 7
    endpoints = make_endpoints(n + j, seed=11)
    members, joiners = endpoints[:n], endpoints[n:]
    view = MembershipView(k)
    for i, ep in enumerate(members):
        view.ring_add(ep, NodeId(0, i))

    key_hi, key_lo = endpoint_ring_keys(members, k)
    qhi, qlo = endpoint_ring_keys(joiners, k)
    alive = np.ones(n, dtype=bool)
    pred = np.asarray(predecessor_of_keys(key_hi, key_lo, alive, qhi, qlo))

    slot_of = {ep: i for i, ep in enumerate(members)}
    for jx, joiner in enumerate(joiners):
        expected = [slot_of[o] for o in view.expected_observers_of(joiner)]
        assert pred[:, jx].tolist() == expected
