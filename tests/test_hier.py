"""Two-level hierarchical membership (rapid_tpu/hier).

Four layers of coverage:

- the deterministic cohort map (pure unit: stability under a seed,
  rebalance-only-at-reconfiguration semantics, joiner assignment, balanced
  chunk sizes, delegate/committee selection and failover order);
- wire framing for the three hier messages (native codec round trips,
  envelope nesting guards);
- protocol end-to-end on an in-process 2-cohort cluster (cohort-local crash
  and join resolve through the global tier; every node delivers the same
  totally-ordered chain; delegate failover when the delegate itself is the
  failure);
- the headline scaling claim: a cohort-local failure resolves with message
  fan-out bounded by the cohort, asserted on the transports' network-stats
  counters against the flat protocol on the identical topology.
"""

import asyncio

import pytest

from rapid_tpu.hier.cohorts import COMMITTEE_PER_COHORT, CohortMap
from rapid_tpu.messaging.codec import CodecError, decode_request, encode_request
from rapid_tpu.messaging.gossip import GossipBroadcaster
from rapid_tpu.sim.scenario import SimHarness, hier_sim_settings, sim_settings
from rapid_tpu.types import (
    CohortCutMessage,
    DelegateDecisionMessage,
    Endpoint,
    GlobalTierMessage,
    GossipMessage,
    NodeId,
    ProbeMessage,
)


def _eps(n, base=7900, net="10.77.0"):
    return [Endpoint(f"{net}.{i}", base + i) for i in range(n)]


def async_test(fn):
    def wrapper(*args, **kwargs):
        asyncio.run(fn(*args, **kwargs))

    wrapper.__name__ = fn.__name__
    return wrapper


# ---------------------------------------------------------------------------
# cohort map
# ---------------------------------------------------------------------------


def test_cohort_map_is_a_pure_function_of_members_and_seed():
    members = _eps(10)
    a = CohortMap(members, seed=7, target_size=4)
    b = CohortMap(list(reversed(members)), seed=7, target_size=4)  # order-free
    assert a.n_cohorts == b.n_cohorts
    for ep in members:
        assert a.cohort_of(ep) == b.cohort_of(ep)
    for c in range(a.n_cohorts):
        assert a.members_of(c) == b.members_of(c)
    # A different seed draws a different partition (overwhelmingly likely
    # for 10 members; pinned seeds keep it deterministic).
    c_map = CohortMap(members, seed=8, target_size=4)
    assert any(
        a.cohort_of(ep) != c_map.cohort_of(ep) for ep in members
    ) or a.members_of(0) != c_map.members_of(0)


def test_cohort_map_rebalances_only_with_membership_change():
    members = _eps(8)
    before = CohortMap(members, seed=1, target_size=4)
    unchanged = CohortMap(members, seed=1, target_size=4)
    # Same membership, same seed -> identical partition (the map is only
    # ever rebuilt at reconfiguration; an unchanged configuration must not
    # shuffle anyone between cohorts).
    for c in range(before.n_cohorts):
        assert before.members_of(c) == unchanged.members_of(c)


def test_cohort_sizes_stay_balanced():
    for n in range(2, 40):
        cmap = CohortMap(_eps(n), seed=3, target_size=4)
        sizes = [len(cmap.members_of(c)) for c in range(cmap.n_cohorts)]
        assert sum(sizes) == n
        assert max(sizes) - min(sizes) <= 1
        if n >= 4:
            # No cohort below the self-detectability floor of 2 members.
            assert min(sizes) >= 2


def test_joiner_assignment_is_deterministic_and_member_free():
    members = _eps(8)
    cmap = CohortMap(members, seed=5, target_size=4)
    joiner = Endpoint("10.99.9.9", 4242)
    target = cmap.cohort_of(joiner)
    assert 0 <= target < cmap.n_cohorts
    assert not cmap.is_member(joiner)
    # Every node computes the identical target cohort.
    assert CohortMap(members, seed=5, target_size=4).cohort_of(joiner) == target


def test_delegate_failover_order_is_deterministic():
    cmap = CohortMap(_eps(8), seed=2, target_size=4)
    for c in range(cmap.n_cohorts):
        chunk = cmap.members_of(c)
        assert cmap.delegate_of(c) == chunk[0]
        # Excluding the delegate promotes the next chunk member, in order.
        assert cmap.delegate_of(c, exclude=[chunk[0]]) == chunk[1]
        assert cmap.forward_candidates(c, exclude=[chunk[0]]) == chunk[1:]
    committee = cmap.committee()
    assert len(committee) == cmap.n_cohorts * COMMITTEE_PER_COHORT
    for c in range(cmap.n_cohorts):
        assert set(cmap.members_of(c)[:COMMITTEE_PER_COHORT]) <= set(committee)


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------


def test_hier_messages_round_trip_through_the_codec():
    ep1, ep2 = Endpoint("a", 1), Endpoint("b", 2)
    nid = NodeId(10, 20)
    for msg in (
        CohortCutMessage(
            sender=ep1, configuration_id=-9, cohort=1, endpoints=(ep2,),
            joiner_eps=(ep2,), joiner_ids=(nid,), trace_id=77,
        ),
        DelegateDecisionMessage(
            sender=ep2, configuration_id=4, endpoints=(ep1, ep2),
        ),
        GlobalTierMessage(sender=ep1, payload=ProbeMessage(sender=ep2)),
    ):
        assert decode_request(encode_request(msg)) == msg


def test_global_tier_envelope_rejects_nested_envelopes():
    ep = Endpoint("a", 1)
    nested = GlobalTierMessage(
        sender=ep, payload=GlobalTierMessage(sender=ep, payload=ProbeMessage(ep))
    )
    with pytest.raises(CodecError):
        encode_request(nested)
    gossiped = GlobalTierMessage(
        sender=ep,
        payload=GossipMessage(origin=ep, msg_id=1, ttl=2, payload=ProbeMessage(ep)),
    )
    with pytest.raises(CodecError):
        encode_request(gossiped)


def test_global_tier_nesting_rule_holds_on_the_interop_path_too():
    # The proto converters must enforce the same one-level rule as the
    # native codec, or the two transports disagree on the wire contract.
    from rapid_tpu.interop.convert import request_from_proto, request_to_proto
    from rapid_tpu.interop.proto_schema import proto_class

    ep = Endpoint("a", 1)
    nested = GlobalTierMessage(
        sender=ep, payload=GlobalTierMessage(sender=ep, payload=ProbeMessage(ep))
    )
    with pytest.raises(ValueError):
        request_to_proto(nested)
    # Decode direction: hand-assemble the nested envelope a non-conforming
    # peer could send and assert it is refused, not recursed into.
    envelope = proto_class("RapidRequest")()
    inner = proto_class("RapidRequest")()
    inner.globalTierMessage.sender.hostname = b"a"
    inner.globalTierMessage.sender.port = 1
    inner.globalTierMessage.payload.probeMessage.sender.hostname = b"a"
    inner.globalTierMessage.payload.probeMessage.sender.port = 1
    envelope.globalTierMessage.sender.hostname = b"a"
    envelope.globalTierMessage.sender.port = 1
    envelope.globalTierMessage.payload.CopyFrom(inner)
    with pytest.raises(ValueError):
        request_from_proto(envelope)


def test_gossip_broadcaster_honors_cohort_scope():
    class _NullClient:
        def send_nowait(self, remote, request):
            pass

    members = _eps(8)
    g = GossipBroadcaster(_NullClient(), members[0], fanout=3, ttl=2)
    g.scope_fn = lambda all_members: all_members[:4]
    g.set_membership(members)
    assert set(g._members) == set(members[:4])


# ---------------------------------------------------------------------------
# end-to-end: 2-cohort in-process cluster
# ---------------------------------------------------------------------------


def _chains_consistent(harness):
    """Every node's delivered chain is an ordered subsequence of node 0's,
    and equal ids carry equal memberships (the chain-consistency oracle,
    inline)."""
    reference = [cid for cid, _ in harness.configs[0]]
    ref_index = {cid: i for i, cid in enumerate(reference)}
    membership_of = {}
    for slot, history in harness.configs.items():
        positions = []
        for cid, members in history:
            assert cid in ref_index, f"slot {slot} forked: {cid:#x} not on node 0's chain"
            positions.append(ref_index[cid])
            seen = membership_of.setdefault(cid, frozenset(members))
            assert seen == frozenset(members), f"config {cid:#x} has two memberships"
        assert positions == sorted(positions)
    return True


@async_test
async def test_two_cohort_cluster_resolves_cohort_local_crash():
    settings = hier_sim_settings()
    harness = SimHarness(_eps(12, net="10.77.1"), settings=settings, id_seed=11)
    await harness.bootstrap(8)
    service = harness.clusters[0].service
    cmap = service._cohort_map
    assert cmap.n_cohorts == 2
    committee = set(cmap.committee())
    victim = next(
        i for i in range(1, 8) if harness.endpoints[i] not in committee
    )
    harness.crash([victim])
    await harness.converge_members(7, budget_ms=60_000)
    assert _chains_consistent(harness)
    # The two-tier machinery genuinely ran: a cohort cut was decided and
    # serialized by the global tier somewhere in the cluster.
    totals = {"cohort_cuts_decided": 0, "cohort_global_decisions": 0}
    for cluster in harness.clusters.values():
        counters = cluster.service.metrics.counters
        for key in totals:
            totals[key] += counters.get(key, 0)
    assert totals["cohort_cuts_decided"] > 0
    assert totals["cohort_global_decisions"] > 0
    await harness.shutdown()


@async_test
async def test_delegate_failure_fails_over_and_still_converges():
    settings = hier_sim_settings()
    harness = SimHarness(_eps(12, net="10.77.2"), settings=settings, id_seed=13)
    await harness.bootstrap(8)
    cmap = harness.clusters[0].service._cohort_map
    seed_ep = harness.endpoints[0]
    # Crash a cohort DELEGATE (never the seed — slot 0 anchors the oracle).
    victim_ep = next(
        cmap.delegate_of(c)
        for c in range(cmap.n_cohorts)
        if cmap.delegate_of(c) != seed_ep
    )
    victim = harness.endpoints.index(victim_ep)
    harness.crash([victim])
    await harness.converge_members(7, budget_ms=60_000)
    assert _chains_consistent(harness)
    # The cut containing the delegate was forwarded by a surviving failover
    # candidate, not the (dead) delegate itself.
    forwarders = [
        slot
        for slot, cluster in harness.clusters.items()
        if cluster.service.metrics.counters.get("cohort_cuts_forwarded", 0) > 0
    ]
    assert forwarders and victim not in forwarders
    await harness.shutdown()


@async_test
async def test_join_lands_through_cohort_gatekeepers():
    settings = hier_sim_settings()
    harness = SimHarness(_eps(12, net="10.77.3"), settings=settings, id_seed=17)
    await harness.bootstrap(8)
    await harness.join_one(8)
    await harness.converge_members(9, budget_ms=60_000)
    assert _chains_consistent(harness)
    # The joiner is a member of exactly the cohort the (rebuilt) map says.
    service = harness.clusters[0].service
    cmap = service._cohort_map
    joiner_ep = harness.endpoints[8]
    assert cmap.is_member(joiner_ep)
    await harness.shutdown()


# ---------------------------------------------------------------------------
# the scaling claim: O(cohort) fan-out, counted on the wire
# ---------------------------------------------------------------------------


@async_test
async def test_cohort_local_failure_fans_out_o_cohort_not_o_n():
    """Same 16-node topology, same crash, flat vs hierarchical: the
    hierarchy must spend well under the flat protocol's messages in total,
    and a plain member OUTSIDE the affected cohort (and off the committee)
    must see near-zero protocol traffic — the whole point of the tier
    split. Counted on TransportStats (the paper's Table 2 instrument)."""
    n = 16
    victim = 5

    async def resolve(settings):
        harness = SimHarness(
            _eps(n + 1, net="10.77.4"), settings=settings, id_seed=3
        )
        await harness.bootstrap(n)
        await harness.advance(3_000)  # settle the bootstrap tail
        for cluster in harness.clusters.values():
            cluster.service.client.stats.reset_window()
        harness.crash([victim])
        await harness.converge_members(n - 1, budget_ms=60_000)
        tx = {
            slot: cluster.service.client.stats.msgs_tx
            for slot, cluster in harness.clusters.items()
        }
        cmap = getattr(harness.clusters[0].service, "_cohort_map", None)
        await harness.shutdown()
        return tx, cmap

    flat_tx, _ = await resolve(sim_settings())
    hier_tx, cmap = await resolve(hier_sim_settings())
    flat_total = sum(flat_tx.values())
    hier_total = sum(hier_tx.values())
    # Totals: the hierarchy resolves the same failure in well under the
    # flat protocol's message budget (measured ~0.45x; the bound leaves
    # headroom for scheduling jitter, not for regressions to O(N)).
    assert hier_total < flat_total * 0.65, (hier_total, flat_total)
    # Per-node: members outside the victim's cohort that hold no committee
    # seat exchange only anti-entropy heartbeats — their egress must not
    # scale with the cluster-wide change at all.
    committee = set(cmap.committee())
    victim_cohort = cmap.cohort_of(Endpoint("10.77.4.5", 7905))
    bystanders = [
        slot
        for slot, ep in enumerate(
            Endpoint(f"10.77.4.{i}", 7900 + i) for i in range(n)
        )
        if slot != victim
        and cmap.cohort_of(ep) != victim_cohort
        and ep not in committee
    ]
    assert bystanders, "topology produced no plain bystanders"
    for slot in bystanders:
        assert hier_tx[slot] <= 6, (slot, hier_tx)
    # The same bystanders under flat Rapid each paid O(N) broadcasts.
    assert min(flat_tx[slot] for slot in bystanders) >= n, (flat_tx, bystanders)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
