"""Multi-node-in-one-process cluster tests over the in-process transport,
mirroring the reference's ClusterTest scenarios
(rapid/src/test/java/com/vrg/rapid/ClusterTest.java)."""

import asyncio
import functools
import random

import pytest

from rapid_tpu.errors import JoinError
from rapid_tpu.messaging.inprocess import (
    ClientDelayer,
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
    ServerDropFirstN,
)
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.protocol.cluster import Cluster
from rapid_tpu.protocol.events import ClusterEvents
from rapid_tpu.settings import Settings
from rapid_tpu.types import Endpoint, JoinMessage, PreJoinMessage

from helpers import wait_until

BASE_PORT = 1234


def async_test_timeout(seconds):
    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            async def with_timeout():
                await asyncio.wait_for(fn(*args, **kwargs), timeout=seconds)

            asyncio.run(with_timeout())

        return wrapper

    return decorate


async_test = async_test_timeout(60)


def fast_settings() -> Settings:
    # Aggressive timeouts, like the reference's useShortJoinTimeouts /
    # useFastFailureDetectionTimeouts helpers (ClusterTest.java:795-804).
    s = Settings()
    s.batching_window_ms = 20
    s.failure_detector_interval_ms = 50
    s.rpc_timeout_ms = 500
    s.rpc_join_timeout_ms = 2000
    s.rpc_probe_timeout_ms = 200
    s.consensus_fallback_base_delay_ms = 2000
    return s


def ep(i: int) -> Endpoint:
    return Endpoint("127.0.0.1", BASE_PORT + i)



async def start_cluster(n, network, fd_factory=None, settings=None, seed_subs=None):
    settings = settings or fast_settings()
    clusters = [
        await Cluster.start(
            ep(0), settings=settings, network=network,
            fd_factory=fd_factory or StaticFailureDetectorFactory(),
            subscriptions=seed_subs, rng=random.Random(0),
        )
    ]
    for i in range(1, n):
        clusters.append(
            await Cluster.join(
                ep(0), ep(i), settings=settings, network=network,
                fd_factory=fd_factory or StaticFailureDetectorFactory(),
                rng=random.Random(i),
            )
        )
    return clusters


async def shutdown_all(clusters):
    await asyncio.gather(*(c.shutdown() for c in clusters), return_exceptions=True)


def all_converged(clusters, expected_size):
    return all(c.membership_size == expected_size for c in clusters) and (
        len({tuple(c.membership) for c in clusters}) == 1
    )


@async_test
async def test_single_node_starts():
    network = InProcessNetwork()
    cluster = await Cluster.start(ep(0), settings=fast_settings(), network=network,
                                  fd_factory=StaticFailureDetectorFactory())
    assert cluster.membership == [ep(0)]
    assert cluster.membership_size == 1
    await cluster.shutdown()


@async_test
async def test_ten_nodes_join_sequentially():
    network = InProcessNetwork()
    clusters = await start_cluster(10, network)
    try:
        assert await wait_until(lambda: all_converged(clusters, 10))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_twenty_nodes_join_in_parallel_through_one_seed():
    network = InProcessNetwork()
    settings = fast_settings()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=StaticFailureDetectorFactory())
    joiners = await asyncio.gather(
        *(
            Cluster.join(ep(0), ep(i), settings=settings, network=network,
                         fd_factory=StaticFailureDetectorFactory(), rng=random.Random(i))
            for i in range(1, 20)
        )
    )
    clusters = [seed] + list(joiners)
    try:
        assert await wait_until(lambda: all_converged(clusters, 20))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_hundred_parallel_joins_through_one_seed():
    # The reference's headline bootstrap test: 100 concurrent joins through a
    # single seed (ClusterTest.java:183-191).
    network = InProcessNetwork()
    settings = fast_settings()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=StaticFailureDetectorFactory())
    joiners = await asyncio.gather(
        *(
            Cluster.join(ep(0), ep(1000 + i), settings=settings, network=network,
                         fd_factory=StaticFailureDetectorFactory(), rng=random.Random(i))
            for i in range(100)
        )
    )
    clusters = [seed] + list(joiners)
    try:
        assert await wait_until(lambda: all_converged(clusters, 101), timeout_s=45)
    finally:
        await shutdown_all(clusters)


@async_test
async def test_fifty_node_cluster_with_multi_failure():
    # The reference's workhorse scale (ClusterTest runs up to 50 nodes).
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    settings = fast_settings()
    clusters = await _bring_up_fifty(network, fd, settings)
    try:
        victims = [clusters[7], clusters[21], clusters[33], clusters[44]]
        for victim in victims:
            network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([v.listen_address for v in victims])
        survivors = [c for c in clusters if c not in victims]
        assert await wait_until(lambda: all_converged(survivors, 46), timeout_s=40)
    finally:
        await shutdown_all(clusters)


async def _bring_up_fifty(network, fd, settings):
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=fd, rng=random.Random(0))
    joiners = await asyncio.gather(
        *(
            Cluster.join(ep(0), ep(i), settings=settings, network=network,
                         fd_factory=fd, rng=random.Random(i))
            for i in range(1, 50)
        )
    )
    clusters = [seed] + list(joiners)
    try:
        assert await wait_until(lambda: all_converged(clusters, 50), timeout_s=40)
    except BaseException:
        # A failed bring-up must not leak 50 live clusters into the loop
        # teardown (the cascade of secondary errors buries the real one).
        await shutdown_all(clusters)
        raise
    return clusters


@async_test_timeout(120)
async def test_twelve_failures_out_of_fifty():
    """The reference's heavier crash fraction (ClusterTest.java crashes 12 of
    50): the largest simultaneous cut the fast round can still clear — the 38
    survivors are EXACTLY the fast-paxos quorum N - floor((N-1)/4) = 38."""
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    settings = fast_settings()
    # Generous batching so staggered detections coalesce (the point is the
    # near-quorum cut, not timing luck), and a short fallback base so that if
    # votes DO split across two cuts, classic recovery is quick.
    settings.batching_window_ms = 300
    settings.consensus_fallback_base_delay_ms = 500
    clusters = await _bring_up_fifty(network, fd, settings)
    try:
        victims = clusters[3:48:4]
        assert len(victims) == 12
        for victim in victims:
            network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([v.listen_address for v in victims])
        survivors = [c for c in clusters if c not in victims]
        assert await wait_until(lambda: all_converged(survivors, 38), timeout_s=60)
        victim_eps = {v.listen_address for v in victims}
        for c in survivors:
            assert victim_eps.isdisjoint(set(c.membership))
    finally:
        await shutdown_all(clusters)


@async_test_timeout(180)  # > 40s bring-up bound + 90s convergence bound
async def test_sixteen_failures_out_of_fifty_requires_classic_fallback():
    """The reference's heaviest crash fraction (ClusterTest.java crashes 16
    of 50). The 34 survivors sit BELOW the fast-round quorum (38 of the
    configuration's 50), so no cut can one-step: convergence must go through
    the jittered classic-Paxos fallback — observable here because the
    declared VIEW_CHANGE_ONE_STEP_FAILED event fires when it engages (classic
    needs only a majority of the survivors: 34 > 25)."""
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    settings = fast_settings()
    settings.batching_window_ms = 300
    settings.consensus_fallback_base_delay_ms = 500
    clusters = await _bring_up_fifty(network, fd, settings)
    try:
        victims = clusters[1:49:3]
        assert len(victims) == 16
        fallback_engaged = []
        for c in clusters:
            if c not in victims:
                c.register_subscription(
                    ClusterEvents.VIEW_CHANGE_ONE_STEP_FAILED,
                    lambda change: fallback_engaged.append(change),
                )
        for victim in victims:
            network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([v.listen_address for v in victims])
        survivors = [c for c in clusters if c not in victims]
        assert await wait_until(lambda: all_converged(survivors, 34), timeout_s=90)
        victim_eps = {v.listen_address for v in victims}
        for c in survivors:
            assert victim_eps.isdisjoint(set(c.membership))
        # The fast round could never have cleared the first cut (34 voters <
        # 38 quorum), so at least one survivor must have engaged classic.
        assert fallback_engaged, "no survivor reported one-step failure"
    finally:
        await shutdown_all(clusters)


@async_test
async def test_join_wave_onto_existing_cluster():
    network = InProcessNetwork()
    settings = fast_settings()
    clusters = await start_cluster(10, network, settings=settings)
    assert await wait_until(lambda: all_converged(clusters, 10))
    wave = await asyncio.gather(
        *(
            Cluster.join(ep(0), ep(100 + i), settings=settings, network=network,
                         fd_factory=StaticFailureDetectorFactory(), rng=random.Random(100 + i))
            for i in range(10)
        )
    )
    clusters += list(wave)
    try:
        assert await wait_until(lambda: all_converged(clusters, 20))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_one_failure_out_of_ten():
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    clusters = await start_cluster(10, network, fd_factory=fd)
    try:
        assert await wait_until(lambda: all_converged(clusters, 10))
        victim = clusters[4]
        network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await wait_until(lambda: all_converged(survivors, 9))
        assert all(victim.listen_address not in c.membership for c in survivors)
    finally:
        await shutdown_all(clusters)


@async_test
async def test_three_failures_out_of_fifteen_single_cut():
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    # A generous batching window: the single-cut assertion below is about
    # the BATCHING invariant, not about timing luck — under host CPU
    # contention the three detections can straddle a 20 ms quiescence window
    # and legitimately split into two cuts, which is not what this test is
    # probing.
    settings = fast_settings()
    settings.batching_window_ms = 200
    clusters = await start_cluster(15, network, fd_factory=fd, settings=settings)
    try:
        assert await wait_until(lambda: all_converged(clusters, 15))
        victims = [clusters[3], clusters[8], clusters[12]]
        view_changes = []
        clusters[0].register_subscription(
            ClusterEvents.VIEW_CHANGE, lambda change: view_changes.append(change)
        )
        for victim in victims:
            network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([v.listen_address for v in victims])
        survivors = [c for c in clusters if c not in victims]
        assert await wait_until(lambda: all_converged(survivors, 12))
        victim_eps = {v.listen_address for v in victims}
        assert all(not victim_eps & set(c.membership) for c in survivors)
        # All three failures resolve in a single consensus decision (the
        # multi-node cut; reference asserts likewise for concurrent crashes).
        assert len(view_changes) == 1
        assert {sc.endpoint for sc in view_changes[0].status_changes} == victim_eps
    finally:
        await shutdown_all(clusters)


@async_test
async def test_graceful_leave():
    network = InProcessNetwork()
    clusters = await start_cluster(8, network)
    try:
        assert await wait_until(lambda: all_converged(clusters, 8))
        leaver = clusters[5]
        await leaver.leave_gracefully()
        survivors = [c for c in clusters if c is not leaver]
        assert await wait_until(lambda: all_converged(survivors, 7))
        assert all(leaver.listen_address not in c.membership for c in survivors)
    finally:
        await shutdown_all(clusters)


@async_test
async def test_kicked_node_gets_event():
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    clusters = await start_cluster(10, network, fd_factory=fd)
    try:
        assert await wait_until(lambda: all_converged(clusters, 10))
        # The victim stays reachable (one-way suspicion): it hears the
        # consensus that evicts it and must fire KICKED
        # (MembershipService.java:433-440).
        victim = clusters[6]
        kicked = []
        victim.register_subscription(ClusterEvents.KICKED, lambda change: kicked.append(change))
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await wait_until(lambda: all_converged(survivors, 9))
        assert await wait_until(lambda: len(kicked) == 1)
        assert victim.listen_address not in kicked[0].membership
    finally:
        await shutdown_all(clusters)


@async_test
async def test_join_with_metadata_propagates():
    network = InProcessNetwork()
    settings = fast_settings()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=StaticFailureDetectorFactory())
    joiner = await Cluster.join(
        ep(0), ep(1), settings=settings, network=network,
        fd_factory=StaticFailureDetectorFactory(),
        metadata=(("role", b"worker"),),
    )
    clusters = [seed, joiner]
    try:
        assert await wait_until(lambda: all_converged(clusters, 2))
        assert await wait_until(lambda: seed.metadata.get(ep(1)) == (("role", b"worker"),))
        late = await Cluster.join(ep(0), ep(2), settings=settings, network=network,
                                  fd_factory=StaticFailureDetectorFactory())
        clusters.append(late)
        # Metadata reaches nodes that join later, via the streamed config.
        assert await wait_until(lambda: late.metadata.get(ep(1)) == (("role", b"worker"),))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_view_change_subscription_sees_joiner_delta():
    network = InProcessNetwork()
    settings = fast_settings()
    changes = []
    seed = await Cluster.start(
        ep(0), settings=settings, network=network,
        fd_factory=StaticFailureDetectorFactory(),
    )
    seed.register_subscription(ClusterEvents.VIEW_CHANGE, lambda c: changes.append(c))
    joiner = await Cluster.join(ep(0), ep(1), settings=settings, network=network,
                                fd_factory=StaticFailureDetectorFactory())
    clusters = [seed, joiner]
    try:
        assert await wait_until(lambda: len(changes) >= 1)
        delta = changes[-1].status_changes
        assert len(delta) == 1
        assert delta[0].endpoint == ep(1)
        assert delta[0].status.name == "UP"
    finally:
        await shutdown_all(clusters)


@async_test
async def test_proposal_event_precedes_view_change():
    # SubscriptionsTest parity: VIEW_CHANGE_PROPOSAL fires when the cut is
    # announced (pre-consensus, MembershipService.java:337-345), before the
    # VIEW_CHANGE for the same delta, and carries the same endpoints.
    network = InProcessNetwork()
    settings = fast_settings()
    fd = StaticFailureDetectorFactory()
    seed = await Cluster.start(ep(0), settings=settings, network=network, fd_factory=fd)
    events = []
    seed.register_subscription(
        ClusterEvents.VIEW_CHANGE_PROPOSAL, lambda c: events.append(("proposal", c))
    )
    seed.register_subscription(
        ClusterEvents.VIEW_CHANGE, lambda c: events.append(("view_change", c))
    )
    joiner = await Cluster.join(ep(0), ep(1), settings=settings, network=network,
                                fd_factory=fd)
    clusters = [seed, joiner]
    try:
        assert await wait_until(lambda: len(events) >= 2)
        kinds = [kind for kind, _ in events]
        assert kinds.index("proposal") < kinds.index("view_change")
        proposal_change = next(c for kind, c in events if kind == "proposal")
        view_change = next(c for kind, c in events if kind == "view_change")
        assert {sc.endpoint for sc in proposal_change.status_changes} == {ep(1)}
        assert {sc.endpoint for sc in view_change.status_changes} == {ep(1)}
        # The proposal event reports the OLD configuration (pre-change), the
        # view change the NEW one.
        assert proposal_change.configuration_id != view_change.configuration_id
        assert ep(1) not in proposal_change.membership
        assert ep(1) in view_change.membership
    finally:
        await shutdown_all(clusters)


@async_test
async def test_down_notification_carries_metadata():
    # SubscriptionsTest.java:170-243: DOWN deltas must carry the failed
    # node's metadata so applications can act on its role.
    network = InProcessNetwork()
    settings = fast_settings()
    fd = StaticFailureDetectorFactory()
    seed = await Cluster.start(ep(0), settings=settings, network=network, fd_factory=fd)
    worker = await Cluster.join(
        ep(0), ep(1), settings=settings, network=network, fd_factory=fd,
        metadata=(("role", b"worker"),),
    )
    filler = await Cluster.join(ep(0), ep(2), settings=settings, network=network, fd_factory=fd)
    clusters = [seed, worker, filler]
    try:
        assert await wait_until(lambda: all_converged(clusters, 3))
        changes = []
        seed.register_subscription(ClusterEvents.VIEW_CHANGE, lambda c: changes.append(c))
        network.blackholed.add(worker.listen_address)
        fd.add_failed_nodes([worker.listen_address])
        assert await wait_until(lambda: seed.membership_size == 2)
        down = [sc for c in changes for sc in c.status_changes if sc.status.name == "DOWN"]
        assert len(down) == 1
        assert down[0].endpoint == ep(1)
        assert down[0].metadata == (("role", b"worker"),)
    finally:
        await shutdown_all(clusters)


@async_test
async def test_join_succeeds_despite_dropped_join_messages():
    # Asymmetric-failure simulation via server-side drop interceptors
    # (ClusterTest.injectAsymmetricDrops / MessageDropInterceptor.java).
    network = InProcessNetwork()
    settings = fast_settings()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=StaticFailureDetectorFactory())
    seed_server = network.servers[ep(0)]
    seed_server.drop_interceptors.append(ServerDropFirstN(PreJoinMessage, 2))
    joiner = await Cluster.join(ep(0), ep(1), settings=settings, network=network,
                                fd_factory=StaticFailureDetectorFactory())
    clusters = [seed, joiner]
    try:
        assert await wait_until(lambda: all_converged(clusters, 2))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_join_fails_when_no_seed():
    network = InProcessNetwork()
    settings = fast_settings()
    settings.join_attempts = 2
    settings.rpc_default_retries = 1
    settings.rpc_timeout_ms = 100
    settings.rpc_join_timeout_ms = 100
    with pytest.raises(JoinError):
        await Cluster.join(ep(0), ep(1), settings=settings, network=network,
                           fd_factory=StaticFailureDetectorFactory())


@async_test
async def test_rejoin_after_crash_with_new_identity():
    # A kicked/crashed node can rejoin with the same address
    # (ClusterTest.java:416-463 rejoin loops).
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    clusters = await start_cluster(6, network, fd_factory=fd)
    try:
        assert await wait_until(lambda: all_converged(clusters, 6))
        victim = clusters[2]
        network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([victim.listen_address])
        survivors = [c for c in clusters if c is not victim]
        assert await wait_until(lambda: all_converged(survivors, 5))
        await victim.shutdown()

        network.blackholed.discard(victim.listen_address)
        fd.blacklist.discard(victim.listen_address)
        rejoined = await Cluster.join(
            ep(0), victim.listen_address, settings=fast_settings(), network=network,
            fd_factory=fd,
        )
        clusters = survivors + [rejoined]
        assert await wait_until(lambda: all_converged(clusters, 6))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_concurrent_joins_and_failures():
    # ClusterTest.java:229-243 (concurrentNodeJoinsAndFails): a 30-node
    # cluster fails 5 members WHILE 10 new nodes join through the seed; the
    # cluster must converge on exactly the surviving 35.
    network = InProcessNetwork()
    fd = StaticFailureDetectorFactory()
    settings = fast_settings()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=fd, rng=random.Random(0))
    joiners = await asyncio.gather(
        *(
            Cluster.join(ep(0), ep(i), settings=settings, network=network,
                         fd_factory=fd, rng=random.Random(i))
            for i in range(1, 30)
        )
    )
    clusters = [seed] + list(joiners)
    try:
        assert await wait_until(lambda: all_converged(clusters, 30), timeout_s=40)

        # Fail 5 and start 10 joins in the same breath — no barrier between.
        victims = clusters[2:7]
        for victim in victims:
            network.blackholed.add(victim.listen_address)
        fd.add_failed_nodes([v.listen_address for v in victims])
        join_tasks = [
            asyncio.ensure_future(
                Cluster.join(ep(0), ep(200 + i), settings=settings, network=network,
                             fd_factory=fd, rng=random.Random(200 + i))
            )
            for i in range(10)
        ]
        wave = await asyncio.gather(*join_tasks)
        clusters += list(wave)  # before any assert: finally must reap the wave
        survivors = [c for c in clusters if c not in victims]
        assert await wait_until(lambda: all_converged(survivors, 35), timeout_s=40)
    finally:
        await shutdown_all(clusters)


@async_test
async def test_phase2_drops_within_rpc_retries():
    # ClusterTest.phase2MessageDropsRpcRetries: the seed drops phase-2
    # JoinMessages retries-1 times — RPC-level retries alone must get the
    # joiner through, without re-initiating the join.
    network = InProcessNetwork()
    settings = fast_settings()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=StaticFailureDetectorFactory())
    network.servers[ep(0)].drop_interceptors.append(
        ServerDropFirstN(JoinMessage, settings.rpc_default_retries - 1)
    )
    joiner = await Cluster.join(ep(0), ep(1), settings=settings, network=network,
                                fd_factory=StaticFailureDetectorFactory())
    clusters = [seed, joiner]
    try:
        assert await wait_until(lambda: all_converged(clusters, 2))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_phase2_drops_force_join_reattempt():
    # ClusterTest.phase2JoinAttemptRetry: the seed drops MORE phase-2
    # messages than the RPC retry budget — the first join attempt fails and
    # the client must re-initiate the whole join, which then succeeds.
    network = InProcessNetwork()
    settings = fast_settings()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=StaticFailureDetectorFactory())
    network.servers[ep(0)].drop_interceptors.append(
        ServerDropFirstN(JoinMessage, settings.rpc_default_retries + 1)
    )
    joiner = await Cluster.join(ep(0), ep(1), settings=settings, network=network,
                                fd_factory=StaticFailureDetectorFactory())
    clusters = [seed, joiner]
    try:
        assert await wait_until(lambda: all_converged(clusters, 2))
    finally:
        await shutdown_all(clusters)


@async_test
async def test_phase2_join_retry_with_config_change():
    # ClusterTest.phase2JoinAttemptRetryWithConfigChange: joiner A's phase-2
    # message is latched at ITS client while another node joins, making A's
    # phase-1 configuration stale; once released, A must take the
    # CONFIG_CHANGED retry path and still end up in the cluster.
    network = InProcessNetwork()
    settings = fast_settings()
    fd = StaticFailureDetectorFactory()
    seed = await Cluster.start(ep(0), settings=settings, network=network,
                               fd_factory=fd)
    client_a = InProcessClient(network, ep(1), settings)
    server_a = InProcessServer(network, ep(1))
    delayer = ClientDelayer(JoinMessage)
    client_a.delayers.append(delayer)
    join_a = asyncio.ensure_future(
        Cluster.join(ep(0), ep(1), settings=settings, client=client_a,
                     server=server_a, fd_factory=fd)
    )
    # Deterministic sequencing: wait until A's phase-2 message is actually
    # parked on the latch (A finished phase 1 under the 2-node config), so
    # B's join below genuinely stales A's configuration.
    assert await wait_until(lambda: delayer.held > 0, timeout_s=10)
    assert not join_a.done()
    b = await Cluster.join(ep(0), ep(2), settings=settings, network=network,
                           fd_factory=fd)  # renders A's configuration stale
    delayer.open()
    a = await join_a
    clusters = [seed, a, b]
    try:
        assert await wait_until(lambda: all_converged(clusters, 3))
    finally:
        await shutdown_all(clusters)
