"""Shared test helpers (imported by the async test suites)."""

import asyncio
import socket


def free_endpoints(count: int, hostname: str = "127.0.0.1"):
    """Kernel-assigned free ports (reserved briefly, then released), returned
    as Endpoints. One definition — per-file copies of the bind-then-close
    idiom would drift (e.g. on SO_REUSEADDR handling)."""
    from rapid_tpu.types import Endpoint

    socks = []
    for _ in range(count):
        sk = socket.socket()
        sk.bind((hostname, 0))
        socks.append(sk)
    endpoints = [Endpoint(hostname, sk.getsockname()[1]) for sk in socks]
    for sk in socks:
        sk.close()
    return endpoints


async def wait_until(predicate, timeout_s=20.0, interval_s=0.02):
    """Poll ``predicate`` until true or the deadline passes; returns its
    final value. One definition — per-file copies drifted on defaults."""
    deadline = asyncio.get_event_loop().time() + timeout_s
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval_s)
    return predicate()
