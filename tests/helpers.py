"""Shared test helpers (imported by the async test suites)."""

import asyncio


async def wait_until(predicate, timeout_s=20.0, interval_s=0.02):
    """Poll ``predicate`` until true or the deadline passes; returns its
    final value. One definition — per-file copies drifted on defaults."""
    deadline = asyncio.get_event_loop().time() + timeout_s
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval_s)
    return predicate()
