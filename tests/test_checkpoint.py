"""Checkpoint/resume: host configuration snapshots and engine state."""

import numpy as np

from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import Endpoint, NodeId
from rapid_tpu.utils.checkpoint import (
    configuration_from_bytes,
    configuration_to_bytes,
    load_engine_state,
    save_engine_state,
    view_from_configuration,
)

K = 10


def test_configuration_roundtrip(tmp_path):
    view = MembershipView(K)
    for i in range(40):
        view.ring_add(Endpoint(f"10.3.0.{i}", 4000 + i), NodeId(i, i * 7))
    blob = configuration_to_bytes(view.configuration)
    restored = configuration_from_bytes(blob)
    assert restored.node_ids == view.configuration.node_ids
    assert restored.endpoints == view.configuration.endpoints
    assert restored.configuration_id == view.configuration_id

    # Resume: identical rings and config id.
    resumed = view_from_configuration(restored, K)
    assert resumed.configuration_id == view.configuration_id
    for ring_idx in range(K):
        assert resumed.ring(ring_idx) == view.ring(ring_idx)


def test_configuration_rejects_garbage():
    import pytest

    with pytest.raises(ValueError):
        configuration_from_bytes(b"not a checkpoint")


def test_native_configs_write_v1_java_configs_write_v2():
    # Backward compatibility: the default (native) topology emits the v1
    # layout older readers accept; only java-mode configs — which old readers
    # could not resume correctly anyway — pay the v2 trailing topology byte.
    from rapid_tpu.protocol.view import TOPOLOGY_JAVA

    native = MembershipView(K)
    native.ring_add(Endpoint("10.3.0.1", 4000), NodeId(1, 7))
    native_blob = configuration_to_bytes(native.configuration)
    assert native_blob[4] == 1  # version byte after the 4-byte magic

    java = MembershipView(K, topology=TOPOLOGY_JAVA)
    java.ring_add(Endpoint("10.3.0.1", 4000), NodeId(1, 7))
    java_blob = configuration_to_bytes(java.configuration)
    assert java_blob[4] == 2
    assert len(java_blob) == len(native_blob) + 1  # the trailing topology byte

    for blob, topology in ((native_blob, "native"), (java_blob, TOPOLOGY_JAVA)):
        restored = configuration_from_bytes(blob)
        assert restored.topology == topology


def test_engine_state_roundtrip(tmp_path):
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(120, fd_threshold=3, seed=0)
    vc.crash([5, 9])
    # Advance mid-protocol so non-trivial state is saved.
    for _ in range(2):
        vc.step()

    path = tmp_path / "engine.npz"
    save_engine_state(path, vc.cfg, vc.state)
    cfg, state = load_engine_state(path)
    assert cfg == vc.cfg
    for field in state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(state, field)), np.asarray(getattr(vc.state, field)), err_msg=field
        )

    # The resumed cluster continues to the same decision.
    resumed = VirtualCluster(cfg, state)
    resumed.crash([5, 9])
    rounds_resumed, events = resumed.run_until_converged()
    assert events is not None
    rounds_orig, events_orig = vc.run_until_converged()
    assert events_orig is not None
    assert rounds_resumed == rounds_orig
    np.testing.assert_array_equal(resumed.alive_mask, vc.alive_mask)


def test_cluster_metrics_surface():
    import asyncio
    import random

    from rapid_tpu.messaging.inprocess import InProcessNetwork
    from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
    from rapid_tpu.protocol.cluster import Cluster
    from rapid_tpu.settings import Settings
    from rapid_tpu.types import Endpoint

    async def scenario():
        settings = Settings()
        settings.batching_window_ms = 20
        settings.failure_detector_interval_ms = 50
        network = InProcessNetwork()
        fd = StaticFailureDetectorFactory()
        seed = await Cluster.start(Endpoint("127.0.0.1", 31000), settings=settings,
                                   network=network, fd_factory=fd, rng=random.Random(0))
        node = await Cluster.join(Endpoint("127.0.0.1", 31000), Endpoint("127.0.0.1", 31001),
                                  settings=settings, network=network, fd_factory=fd,
                                  rng=random.Random(1))
        for _ in range(200):
            if seed.membership_size == 2 and node.membership_size == 2:
                break
            await asyncio.sleep(0.02)
        metrics = seed.metrics
        await seed.shutdown()
        await node.shutdown()
        return metrics

    metrics = asyncio.run(asyncio.wait_for(scenario(), timeout=30))
    assert metrics["view_changes"] >= 1
    assert metrics["proposals_announced"] >= 1
    assert metrics["alerts_enqueued"] >= 1
    assert "view_change_convergence_ms" in metrics
    assert metrics["view_change_convergence_ms"]["last"] > 0


def test_engine_state_loads_checkpoint_missing_new_fields(tmp_path):
    # Forward compatibility: a checkpoint written before fire_round/round_idx
    # (and the classic-paxos fields) existed must load with safe defaults and
    # still converge. Simulate by deleting those keys from a fresh save.
    import numpy as np

    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.utils.checkpoint import load_engine_state, save_engine_state

    vc = VirtualCluster.create(64, fd_threshold=2, seed=3)
    path = tmp_path / "state.npz"
    save_engine_state(path, vc.cfg, vc.state)

    with np.load(path) as data:
        kept = {k: data[k] for k in data.files}
    for legacy_missing in (
        "fire_round", "round_idx", "cp_rnd_r", "cp_rnd_i",
        "cp_vrnd_r", "cp_vrnd_i", "cp_vval_src", "classic_epoch",
        "ring_perm",  # derived: must backfill from the saved key lanes
    ):
        kept.pop(legacy_missing, None)
    stripped = tmp_path / "legacy.npz"
    np.savez_compressed(stripped, **kept)

    cfg, state = load_engine_state(stripped)
    assert cfg == vc.cfg
    np.testing.assert_array_equal(
        np.asarray(state.ring_perm), np.asarray(vc.state.ring_perm)
    )
    restored = VirtualCluster(cfg, state)
    restored.crash([7])
    rounds, events = restored.run_until_converged(max_steps=32)
    assert events is not None
    assert restored.membership_size == 63


def test_legacy_positional_config_drops_stale_watermark_value(tmp_path):
    # Round-<=2 checkpoints carry no __cfg_fields__ name map: 12 positional
    # values plus (sometimes) the since-deleted pallas_watermark. The legacy
    # branch must truncate to the stable 12 and default the rest — NOT let
    # the stale 13th value load as pallas_lanes (lanes=1 would then blow up
    # the delivery kernel's multiple-of-128 check at call time).
    from rapid_tpu.models.state import EngineConfig
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(32, fd_threshold=2, seed=4, delivery_spread=1)
    path = tmp_path / "state.npz"
    save_engine_state(path, vc.cfg, vc.state)

    with np.load(path) as data:
        kept = {k: data[k] for k in data.files}
    del kept["__cfg_fields__"]  # legacy writer had no name map...
    legacy_vals = [int(v) for v in kept["__cfg__"]][:12]
    legacy_vals.append(1)  # ...and a trailing pallas_watermark=1
    kept["__cfg__"] = np.asarray(legacy_vals, dtype=np.int64)
    legacy = tmp_path / "legacy_cfg.npz"
    np.savez_compressed(legacy, **kept)

    cfg, state = load_engine_state(legacy)
    assert cfg.pallas_lanes == EngineConfig._field_defaults["pallas_lanes"] == 128
    assert cfg._replace(pallas_lanes=vc.cfg.pallas_lanes) == vc.cfg
    restored = VirtualCluster(cfg, state)
    restored.crash([3])
    rounds, events = restored.run_until_converged(max_steps=32)
    assert events is not None
    assert restored.membership_size == 31
