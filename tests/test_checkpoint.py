"""Checkpoint/resume: host configuration snapshots and engine state —
including the ISSUE-15 durability bar: atomic publishes, xxh64 integrity
trailers, every corruption class a NAMED CheckpointCorruptError (never a
numpy/zipfile/struct traceback), and bit-exact round trips for the
compact, bit-packed, and fleet-stacked layouts the serving supervisor
checkpoints."""

import numpy as np
import pytest

from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import Endpoint, NodeId
from rapid_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    configuration_from_bytes,
    configuration_to_bytes,
    load_configuration,
    load_engine_state,
    load_serving_state,
    save_configuration,
    save_engine_state,
    save_serving_state,
    view_from_configuration,
)

K = 10


def test_configuration_roundtrip(tmp_path):
    view = MembershipView(K)
    for i in range(40):
        view.ring_add(Endpoint(f"10.3.0.{i}", 4000 + i), NodeId(i, i * 7))
    blob = configuration_to_bytes(view.configuration)
    restored = configuration_from_bytes(blob)
    assert restored.node_ids == view.configuration.node_ids
    assert restored.endpoints == view.configuration.endpoints
    assert restored.configuration_id == view.configuration_id

    # Resume: identical rings and config id.
    resumed = view_from_configuration(restored, K)
    assert resumed.configuration_id == view.configuration_id
    for ring_idx in range(K):
        assert resumed.ring(ring_idx) == view.ring(ring_idx)


def test_configuration_rejects_garbage():
    import pytest

    with pytest.raises(ValueError):
        configuration_from_bytes(b"not a checkpoint")


def test_native_configs_write_v1_java_configs_write_v2():
    # Backward compatibility: the default (native) topology emits the v1
    # layout older readers accept; only java-mode configs — which old readers
    # could not resume correctly anyway — pay the v2 trailing topology byte.
    from rapid_tpu.protocol.view import TOPOLOGY_JAVA

    native = MembershipView(K)
    native.ring_add(Endpoint("10.3.0.1", 4000), NodeId(1, 7))
    native_blob = configuration_to_bytes(native.configuration)
    assert native_blob[4] == 1  # version byte after the 4-byte magic

    java = MembershipView(K, topology=TOPOLOGY_JAVA)
    java.ring_add(Endpoint("10.3.0.1", 4000), NodeId(1, 7))
    java_blob = configuration_to_bytes(java.configuration)
    assert java_blob[4] == 2
    assert len(java_blob) == len(native_blob) + 1  # the trailing topology byte

    for blob, topology in ((native_blob, "native"), (java_blob, TOPOLOGY_JAVA)):
        restored = configuration_from_bytes(blob)
        assert restored.topology == topology


def test_engine_state_roundtrip(tmp_path):
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(120, fd_threshold=3, seed=0)
    vc.crash([5, 9])
    # Advance mid-protocol so non-trivial state is saved.
    for _ in range(2):
        vc.step()

    path = tmp_path / "engine.npz"
    save_engine_state(path, vc.cfg, vc.state)
    cfg, state = load_engine_state(path)
    assert cfg == vc.cfg
    for field in state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(state, field)), np.asarray(getattr(vc.state, field)), err_msg=field
        )

    # The resumed cluster continues to the same decision.
    resumed = VirtualCluster(cfg, state)
    resumed.crash([5, 9])
    rounds_resumed, events = resumed.run_until_converged()
    assert events is not None
    rounds_orig, events_orig = vc.run_until_converged()
    assert events_orig is not None
    assert rounds_resumed == rounds_orig
    np.testing.assert_array_equal(resumed.alive_mask, vc.alive_mask)


def test_cluster_metrics_surface():
    import asyncio
    import random

    from rapid_tpu.messaging.inprocess import InProcessNetwork
    from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
    from rapid_tpu.protocol.cluster import Cluster
    from rapid_tpu.settings import Settings
    from rapid_tpu.types import Endpoint

    async def scenario():
        settings = Settings()
        settings.batching_window_ms = 20
        settings.failure_detector_interval_ms = 50
        network = InProcessNetwork()
        fd = StaticFailureDetectorFactory()
        seed = await Cluster.start(Endpoint("127.0.0.1", 31000), settings=settings,
                                   network=network, fd_factory=fd, rng=random.Random(0))
        node = await Cluster.join(Endpoint("127.0.0.1", 31000), Endpoint("127.0.0.1", 31001),
                                  settings=settings, network=network, fd_factory=fd,
                                  rng=random.Random(1))
        for _ in range(200):
            if seed.membership_size == 2 and node.membership_size == 2:
                break
            await asyncio.sleep(0.02)
        metrics = seed.metrics
        await seed.shutdown()
        await node.shutdown()
        return metrics

    metrics = asyncio.run(asyncio.wait_for(scenario(), timeout=30))
    assert metrics["view_changes"] >= 1
    assert metrics["proposals_announced"] >= 1
    assert metrics["alerts_enqueued"] >= 1
    assert "view_change_convergence_ms" in metrics
    assert metrics["view_change_convergence_ms"]["last"] > 0


def test_engine_state_loads_checkpoint_missing_new_fields(tmp_path):
    # Forward compatibility: a checkpoint written before fire_round/round_idx
    # (and the classic-paxos fields) existed must load with safe defaults and
    # still converge. Simulate by deleting those keys from a fresh save.
    import numpy as np

    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.utils.checkpoint import load_engine_state, save_engine_state

    vc = VirtualCluster.create(64, fd_threshold=2, seed=3)
    path = tmp_path / "state.npz"
    save_engine_state(path, vc.cfg, vc.state)

    with np.load(path) as data:
        kept = {k: data[k] for k in data.files}
    for legacy_missing in (
        "fire_round", "round_idx", "cp_rnd_r", "cp_rnd_i",
        "cp_vrnd_r", "cp_vrnd_i", "cp_vval_src", "classic_epoch",
        "ring_perm",  # derived: must backfill from the saved key lanes
    ):
        kept.pop(legacy_missing, None)
    stripped = tmp_path / "legacy.npz"
    np.savez_compressed(stripped, **kept)

    cfg, state = load_engine_state(stripped)
    assert cfg == vc.cfg
    np.testing.assert_array_equal(
        np.asarray(state.ring_perm), np.asarray(vc.state.ring_perm)
    )
    restored = VirtualCluster(cfg, state)
    restored.crash([7])
    rounds, events = restored.run_until_converged(max_steps=32)
    assert events is not None
    assert restored.membership_size == 63


def _small_cluster(compact=False, seed=0):
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(
        24, n_slots=40, k=3, h=3, l=1, cohorts=2, fd_threshold=2,
        seed=seed, compact=compact,
    )
    vc.assign_cohorts_roundrobin()
    return vc


def _trees_bit_identical(a, b):
    for field in a._fields:
        x = np.asarray(getattr(a, field))
        y = np.asarray(getattr(b, field))
        assert x.dtype == y.dtype and x.shape == y.shape, field
        np.testing.assert_array_equal(x, y, err_msg=field)


# ---------------------------------------------------------------------------
# ISSUE 15 satellite: corruption is a NAMED error, each class pinned
# ---------------------------------------------------------------------------


def test_configuration_file_roundtrip_and_corruption_classes(tmp_path):
    view = MembershipView(K)
    for i in range(8):
        view.ring_add(Endpoint(f"10.3.0.{i}", 4000 + i), NodeId(i, i * 7))
    path = tmp_path / "config.rtcf"
    save_configuration(path, view.configuration)
    assert not list(tmp_path.glob("*.tmp.*"))  # atomic publish, no debris
    restored = load_configuration(path)
    assert restored.configuration_id == view.configuration_id

    data = path.read_bytes()
    # Bit flip inside the payload: the xxh64 trailer catches it by name.
    flipped = bytearray(data)
    flipped[len(flipped) // 3] ^= 0xFF
    (tmp_path / "flip.rtcf").write_bytes(bytes(flipped))
    with pytest.raises(CheckpointCorruptError):
        load_configuration(tmp_path / "flip.rtcf")
    # Truncation (trailer gone, payload cut): named, not a struct error.
    (tmp_path / "trunc.rtcf").write_bytes(data[: len(data) // 2])
    with pytest.raises(CheckpointCorruptError):
        load_configuration(tmp_path / "trunc.rtcf")
    # Bad magic: named.
    (tmp_path / "magic.rtcf").write_bytes(b"XXXX" + data[4:])
    with pytest.raises(CheckpointCorruptError):
        load_configuration(tmp_path / "magic.rtcf")
    # Truncated raw BYTES (pre-file callers) are named too, and the named
    # error still satisfies legacy except-ValueError callers.
    blob = configuration_to_bytes(view.configuration)
    with pytest.raises(CheckpointCorruptError):
        configuration_from_bytes(blob[: len(blob) // 2])
    assert issubclass(CheckpointCorruptError, ValueError)


def test_engine_checkpoint_corruption_classes_are_named(tmp_path):
    vc = _small_cluster()
    vc.crash([3])
    vc.step()
    path = tmp_path / "engine.npz"
    save_engine_state(path, vc.cfg, vc.state)
    assert not list(tmp_path.glob("*.tmp.*"))
    data = path.read_bytes()
    # Truncated archive.
    (tmp_path / "trunc.npz").write_bytes(data[: len(data) // 2])
    with pytest.raises(CheckpointCorruptError):
        load_engine_state(tmp_path / "trunc.npz")
    # Flipped payload byte under an intact length: trailer mismatch.
    flipped = bytearray(data)
    flipped[len(flipped) // 2] ^= 0xFF
    (tmp_path / "flip.npz").write_bytes(bytes(flipped))
    with pytest.raises(CheckpointCorruptError):
        load_engine_state(tmp_path / "flip.npz")
    # Not an archive at all.
    (tmp_path / "garbage.npz").write_bytes(b"not a checkpoint")
    with pytest.raises(CheckpointCorruptError):
        load_engine_state(tmp_path / "garbage.npz")
    # Member corruption under an INTACT central directory (a trailer-less
    # legacy file with a flipped byte mid-archive): the damage only
    # surfaces at member decompression — still the NAMED error, never a
    # raw zlib traceback leaking through the recovery fallback chain.
    legacy_bad = bytearray(data[:-12])
    legacy_bad[len(legacy_bad) // 2] ^= 0xFF
    (tmp_path / "legacy_bad.npz").write_bytes(bytes(legacy_bad))
    with pytest.raises(CheckpointCorruptError):
        load_engine_state(tmp_path / "legacy_bad.npz")
    # Legacy pre-trailer writers (a bare .npz) still load.
    (tmp_path / "legacy.npz").write_bytes(data[:-12])  # strip the trailer
    cfg2, _state2 = load_engine_state(tmp_path / "legacy.npz")
    assert cfg2 == vc.cfg


# ---------------------------------------------------------------------------
# ISSUE 15 satellite: the layouts the supervisor checkpoints round-trip
# bit-exactly (compact, packed, fleet-stacked), and wide checkpoints
# migrate onto a compact config
# ---------------------------------------------------------------------------


def test_packed_mask_layout_roundtrips_bit_identically(tmp_path):
    from rapid_tpu.models.state import pack_masks, unpack_masks

    vc = _small_cluster()
    vc.crash([2, 7])
    vc.step()
    packed_state = pack_masks(vc.state)
    packed_faults = pack_masks(vc.faults)
    path = tmp_path / "packed.npz"
    save_serving_state(
        path, vc.cfg, packed_state, packed_faults, meta={"layout": "packed"}
    )
    cfg2, state2, faults2, knobs2, meta = load_serving_state(path)
    assert cfg2 == vc.cfg and knobs2 is None and meta == {"layout": "packed"}
    _trees_bit_identical(state2, packed_state)  # packed shapes verbatim
    _trees_bit_identical(faults2, packed_faults)
    _trees_bit_identical(unpack_masks(state2), vc.state)  # and exact unpack


def test_compact_serving_checkpoint_widens_bit_identically(tmp_path):
    from rapid_tpu.models.state import widen_state

    vc = _small_cluster(compact=True)
    vc.crash([1, 4])
    vc.run_until_converged(64)
    path = tmp_path / "compact.npz"
    save_serving_state(path, vc.cfg, vc.state, vc.faults)
    cfg2, state2, _faults2, _knobs, _meta = load_serving_state(path)
    assert cfg2.compact == 1
    _trees_bit_identical(state2, vc.state)  # narrow dtypes verbatim
    # ...and the widened view equals the widened original bit-for-bit (the
    # differential seam every compact comparison goes through).
    _trees_bit_identical(widen_state(cfg2, state2), widen_state(vc.cfg, vc.state))


def test_fleet_stacked_checkpoint_roundtrips_and_resumes(tmp_path):
    from rapid_tpu.models.virtual_cluster import VirtualCluster
    from rapid_tpu.tenancy import TenantFleet

    clusters = []
    for i in range(3):
        vc = VirtualCluster.create(
            16, k=3, h=3, l=1, cohorts=2, fd_threshold=2, seed=30 + i
        )
        vc.assign_cohorts_roundrobin()
        clusters.append(vc)
    fleet = TenantFleet.from_clusters(clusters)
    fleet.stream_crash([(0, 2), (2, 5)])
    fleet.step()
    path = tmp_path / "fleet.npz"
    save_serving_state(
        path, fleet.cfg, fleet.state, fleet.faults, knobs=fleet.knobs,
        meta={"wave_index": 1},
    )
    cfg2, state2, faults2, knobs2, meta = load_serving_state(path)
    assert meta["wave_index"] == 1 and knobs2 is not None
    _trees_bit_identical(state2, fleet.state)
    _trees_bit_identical(faults2, fleet.faults)
    _trees_bit_identical(knobs2, fleet.knobs)
    # The resumed fleet steps on to the same place as the original.
    resumed = TenantFleet(cfg2, state2, faults2, knobs2)
    resumed.step()
    fleet.step()
    _trees_bit_identical(resumed.state, fleet.state)
    assert resumed.config_ids() == fleet.config_ids()
    # A missing pytree field is a loud KeyError naming the key.
    import io

    with np.load(io.BytesIO(path.read_bytes()[:-12])) as data:
        kept = {k: data[k] for k in data.files if k != "faults__crashed"}
    buf = io.BytesIO()
    np.savez_compressed(buf, **kept)
    (tmp_path / "missing.npz").write_bytes(buf.getvalue())
    with pytest.raises(KeyError, match="faults__crashed"):
        load_serving_state(tmp_path / "missing.npz")


def test_wide_checkpoint_loads_under_a_compact_config(tmp_path):
    """Migration path: a checkpoint written by a WIDE deployment is brought
    up compact — validate the envelope, narrow, and the widened view is
    bit-identical to the original (so the compact resume replays the wide
    run's protocol exactly); the migrated cluster keeps converging."""
    from rapid_tpu.models.state import narrow_state, validate_envelope, widen_state
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = _small_cluster(compact=False)
    vc.crash([2, 9])
    vc.step()
    path = tmp_path / "wide.npz"
    save_engine_state(path, vc.cfg, vc.state)
    cfg_w, state_w = load_engine_state(path)
    assert cfg_w.compact == 0
    cfg_c = cfg_w._replace(compact=1)
    validate_envelope(cfg_c, state_w)  # the loud alternative to a wrapping cast
    narrowed = narrow_state(cfg_c, state_w)
    _trees_bit_identical(widen_state(cfg_c, narrowed), state_w)
    migrated = VirtualCluster(cfg_c, narrowed)
    migrated.crash([2, 9])
    rounds, events = migrated.run_until_converged(64)
    assert events is not None
    assert migrated.membership_size == 22


def test_legacy_positional_config_drops_stale_watermark_value(tmp_path):
    # Round-<=2 checkpoints carry no __cfg_fields__ name map: 12 positional
    # values plus (sometimes) the since-deleted pallas_watermark. The legacy
    # branch must truncate to the stable 12 and default the rest — NOT let
    # the stale 13th value load as pallas_lanes (lanes=1 would then blow up
    # the delivery kernel's multiple-of-128 check at call time).
    from rapid_tpu.models.state import EngineConfig
    from rapid_tpu.models.virtual_cluster import VirtualCluster

    vc = VirtualCluster.create(32, fd_threshold=2, seed=4, delivery_spread=1)
    path = tmp_path / "state.npz"
    save_engine_state(path, vc.cfg, vc.state)

    with np.load(path) as data:
        kept = {k: data[k] for k in data.files}
    del kept["__cfg_fields__"]  # legacy writer had no name map...
    legacy_vals = [int(v) for v in kept["__cfg__"]][:12]
    legacy_vals.append(1)  # ...and a trailing pallas_watermark=1
    kept["__cfg__"] = np.asarray(legacy_vals, dtype=np.int64)
    legacy = tmp_path / "legacy_cfg.npz"
    np.savez_compressed(legacy, **kept)

    cfg, state = load_engine_state(legacy)
    assert cfg.pallas_lanes == EngineConfig._field_defaults["pallas_lanes"] == 128
    assert cfg._replace(pallas_lanes=vc.cfg.pallas_lanes) == vc.cfg
    restored = VirtualCluster(cfg, state)
    restored.crash([3])
    rounds, events = restored.run_until_converged(max_steps=32)
    assert events is not None
    assert restored.membership_size == 31
