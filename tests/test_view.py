"""MembershipView tests, mirroring the reference's MembershipViewTest scenarios
(rapid/src/test/java/com/vrg/rapid/MembershipViewTest.java)."""

import pytest

from rapid_tpu.errors import (
    NodeAlreadyInRingError,
    NodeNotInRingError,
    UUIDAlreadySeenError,
)
from rapid_tpu.protocol.view import MembershipView
from rapid_tpu.types import Endpoint, JoinStatusCode, NodeId

K = 10


def ep(i: int, host: str = "127.0.0.1") -> Endpoint:
    return Endpoint(host, i)


def nid(i: int) -> NodeId:
    return NodeId(high=0, low=i)


def test_one_ring_addition():
    view = MembershipView(K)
    view.ring_add(ep(123), nid(1))
    for ring_idx in range(K):
        ring = view.ring(ring_idx)
        assert ring == [ep(123)]


def test_multiple_ring_additions():
    view = MembershipView(K)
    num = 10
    for i in range(num):
        view.ring_add(ep(i), nid(i))
    for ring_idx in range(K):
        assert len(view.ring(ring_idx)) == num


def test_ring_readditions_throw():
    view = MembershipView(K)
    num = 10
    for i in range(num):
        view.ring_add(ep(i), nid(i))
    throws = 0
    for i in range(num):
        try:
            view.ring_add(ep(i), nid(i + 100))
        except NodeAlreadyInRingError:
            throws += 1
    assert throws == num


def test_delete_absent_node_throws():
    view = MembershipView(K)
    throws = 0
    for i in range(10):
        try:
            view.ring_delete(ep(i))
        except NodeNotInRingError:
            throws += 1
    assert throws == 10


def test_additions_and_deletions():
    view = MembershipView(K)
    num = 10
    for i in range(num):
        view.ring_add(ep(i), nid(i))
    for i in range(num):
        view.ring_delete(ep(i))
    for ring_idx in range(K):
        assert view.ring(ring_idx) == []


def test_monitoring_single_node_is_empty():
    view = MembershipView(K)
    view.ring_add(ep(1), nid(1))
    assert view.subjects_of(ep(1)) == []
    assert view.observers_of(ep(1)) == []


def test_monitoring_empty_view_throws():
    view = MembershipView(K)
    with pytest.raises(NodeNotInRingError):
        view.observers_of(ep(1))
    with pytest.raises(NodeNotInRingError):
        view.subjects_of(ep(1))


def test_monitoring_two_nodes():
    view = MembershipView(K)
    view.ring_add(ep(1), nid(1))
    view.ring_add(ep(2), nid(2))
    assert len(view.subjects_of(ep(1))) == K
    assert len(view.observers_of(ep(1))) == K
    # With two nodes, every ring's successor/predecessor is the other node.
    assert set(view.subjects_of(ep(1))) == {ep(2)}
    assert set(view.observers_of(ep(1))) == {ep(2)}


def test_monitoring_three_nodes_with_delete():
    view = MembershipView(K)
    view.ring_add(ep(1), nid(1))
    view.ring_add(ep(2), nid(2))
    view.ring_add(ep(3), nid(3))
    assert len(view.subjects_of(ep(1))) == K
    assert len(view.observers_of(ep(1))) == K
    assert set(view.subjects_of(ep(1))) == {ep(2), ep(3)}
    assert set(view.observers_of(ep(1))) == {ep(2), ep(3)}
    view.ring_delete(ep(2))
    assert set(view.subjects_of(ep(1))) == {ep(3)}
    assert set(view.observers_of(ep(1))) == {ep(3)}


def test_monitoring_multiple_nodes():
    view = MembershipView(K)
    num = 1000
    for i in range(num):
        view.ring_add(ep(i), nid(i))
    for i in range(num):
        assert len(view.observers_of(ep(i))) == K
        assert len(view.subjects_of(ep(i))) == K
    # Observer/subject relationships are symmetric: o observes s on ring k
    # iff s is the k-predecessor of o.
    for i in range(0, num, 100):
        node = ep(i)
        for ring_number, subject in enumerate(view.subjects_of(node)):
            assert view.observers_of(subject)[ring_number] == node


def test_expected_observers_single_node_bootstrap():
    view = MembershipView(K)
    view.ring_add(ep(1), nid(1))
    joiner = ep(2)
    expected = view.expected_observers_of(joiner)
    assert len(expected) == K
    assert set(expected) == {ep(1)}


def test_expected_observers_match_post_join_subject_relationship():
    view = MembershipView(K)
    num = 20
    for i in range(num):
        view.ring_add(ep(i), nid(i))
    joiner = ep(5000)
    expected = view.expected_observers_of(joiner)
    assert len(expected) == K
    # The gatekeepers are the joiner's ring predecessors; after the join they
    # are exactly the joiner's subjects-relationship (reference semantics:
    # getExpectedObserversOf and getSubjectsOf share getPredecessorsOf,
    # MembershipView.java:292-322).
    view.ring_add(joiner, nid(5000))
    assert view.subjects_of(joiner) == expected


def test_expected_observers_grow_towards_k():
    # Mirrors monitoringRelationshipBootstrapMultiple
    # (MembershipViewTest.java:319-344).
    view = MembershipView(K)
    joiner = ep(1233)
    num_observers = 0
    for i in range(20):
        view.ring_add(ep(1234 + i), nid(i))
        actual = len(view.expected_observers_of(joiner))
        assert num_observers <= actual
        num_observers = actual
    assert K - 3 <= num_observers <= K


def test_unique_id_rejections():
    view = MembershipView(K)
    view.ring_add(ep(1), nid(1))
    with pytest.raises(UUIDAlreadySeenError):
        view.ring_add(ep(2), nid(1))
    # Identifiers stay poisoned even after the node leaves.
    view.ring_add(ep(2), nid(2))
    view.ring_delete(ep(2))
    with pytest.raises(UUIDAlreadySeenError):
        view.ring_add(ep(2), nid(2))
    assert view.membership_size == 1


def test_is_safe_to_join():
    view = MembershipView(K)
    view.ring_add(ep(1), nid(1))
    assert view.is_safe_to_join(ep(1), nid(99)) == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
    assert view.is_safe_to_join(ep(2), nid(1)) == JoinStatusCode.UUID_ALREADY_IN_RING
    assert view.is_safe_to_join(ep(2), nid(2)) == JoinStatusCode.SAFE_TO_JOIN


def test_configuration_id_changes_every_membership_change():
    view = MembershipView(K)
    num = 1000
    seen = set()
    for i in range(num):
        view.ring_add(ep(i), nid(i))
        seen.add(view.configuration_id)
    assert len(seen) == num
    for i in range(num):
        view.ring_delete(ep(i))
        seen.add(view.configuration_id)
    assert len(seen) == 2 * num


def test_configurations_across_views_agree():
    v1 = MembershipView(K)
    v2 = MembershipView(K)
    num = 100
    # Insert in different orders; converged views must agree on rings and id.
    for i in range(num):
        v1.ring_add(ep(i), nid(i))
    for i in reversed(range(num)):
        v2.ring_add(ep(i), nid(i))
    for ring_idx in range(K):
        assert v1.ring(ring_idx) == v2.ring(ring_idx)
    assert v1.configuration_id == v2.configuration_id


def test_bootstrap_from_configuration():
    v1 = MembershipView(K)
    ids = [nid(i) for i in range(50)]
    for i in range(50):
        v1.ring_add(ep(i), ids[i])
    config = v1.configuration
    v2 = MembershipView(K, node_ids=config.node_ids, endpoints=config.endpoints)
    assert v2.configuration_id == v1.configuration_id
    for ring_idx in range(K):
        assert v1.ring(ring_idx) == v2.ring(ring_idx)


def test_bulk_construction_matches_incremental_with_and_without_native():
    # The constructor's one-pass bulk build (batch hashing + one sort per
    # ring) must be bit-identical to incremental ring_add — under BOTH key
    # sources: the native C batch hasher and the pure-Python fallback.
    import rapid_tpu.utils._native as native_mod

    n = 300
    endpoints = [ep(i) for i in range(n)]
    ids = [nid(i) for i in range(n)]
    incremental = MembershipView(K)
    for e, i in zip(endpoints, ids):
        incremental.ring_add(e, i)

    # The native leg must genuinely run the native hasher: silently testing
    # the Python fallback twice would let a native regression ship green.
    native_available = native_mod.get_lib() is not None
    bulk_native = MembershipView(K, node_ids=ids, endpoints=endpoints)

    real = native_mod.native_ring_keys_batch
    native_mod.native_ring_keys_batch = lambda *a, **k: None
    try:
        import rapid_tpu.protocol.view as view_mod

        # The view imports the symbol lazily inside _bulk_insert, so the
        # module-level patch takes effect for this construction.
        bulk_python = view_mod.MembershipView(K, node_ids=ids, endpoints=endpoints)
    finally:
        native_mod.native_ring_keys_batch = real

    import pytest

    for candidate in (bulk_native, bulk_python):
        for ring_idx in range(K):
            assert candidate.ring(ring_idx) == incremental.ring(ring_idx)
            assert candidate.ring_keys(ring_idx) == incremental.ring_keys(ring_idx)
        assert candidate.configuration_id == incremental.configuration_id
    if not native_available:
        pytest.skip("native hasher not built: only the Python fallback was verified")


def test_ring_numbers():
    view = MembershipView(K)
    for i in range(10):
        view.ring_add(ep(i), nid(i))
    node = ep(0)
    for ring_number, subject in enumerate(view.subjects_of(node)):
        assert ring_number in view.ring_numbers(node, subject)
    total = sum(len(view.ring_numbers(node, s)) for s in set(view.subjects_of(node)))
    assert total == K
