"""Golden wire-byte fixtures for the reference-interop surface.

``interop/proto_schema.py`` rebuilds ``rapid.proto``'s descriptors at
runtime and asserts field numbers, and whole clusters run over the
transport — but neither catches *descriptor drift* that preserves field
numbers while changing types/labels/nesting. A JVM cross-run is impossible
in this environment (no maven/java), so committed golden frames are the
strongest interop proof available: one canonical serialized ``RapidRequest``
per request type (``rapid.proto:21-35``) and one ``RapidResponse`` per
response type (``rapid.proto:37-45``), checked byte-for-byte in both
directions. Any change to the runtime-built schema or the converters that
alters the wire image now breaks the build.

One frame (the probe request) is additionally checked against bytes
assembled FROM FIRST PRINCIPLES (varint/tag arithmetic per the protobuf
wire spec) so the fixtures are anchored outside the protobuf runtime that
generated them.

Regenerate (after an INTENTIONAL schema change, with the diff reviewed):

    python tests/test_wire_fixtures.py --regen
"""

import json
import sys
from pathlib import Path

import rapid_tpu.types as t
from rapid_tpu.interop.convert import (
    request_from_proto,
    request_to_proto,
    response_from_proto,
    response_to_proto,
)
from rapid_tpu.interop.proto_schema import proto_class

FIXTURE_PATH = Path(__file__).parent / "fixtures" / "wire_frames.json"

_EP1 = t.Endpoint("10.0.0.1", 5000)
_EP2 = t.Endpoint("10.0.0.2", 5001)
_EP3 = t.Endpoint("host-3.example", 65535)
# Native NodeId halves are UNSIGNED 64-bit (convert._u64 normalizes on
# decode; the proto carries them as signed int64, rapid.proto:50-54) — the
# high half here exercises the sign-wrapping path on the wire.
_NID = t.NodeId(high=0xF122334455667788, low=0x0123456789ABCDEF)
_RANK = t.Rank(round=2, node_index=41)
_MD = (("role", b"backend"), ("zone", b"\x00\x01\xff"))


def canonical_requests():
    """One representative instance per rapid.proto request type, covering
    every field the converters map (repeated fields, optional nodeId,
    metadata maps, negative 64-bit configuration ids)."""
    alert_down = t.AlertMessage(
        edge_src=_EP1, edge_dst=_EP2, edge_status=t.EdgeStatus.DOWN,
        configuration_id=-6148914691236517206, ring_numbers=(0, 3, 9),
    )
    alert_up = t.AlertMessage(
        edge_src=_EP2, edge_dst=_EP3, edge_status=t.EdgeStatus.UP,
        configuration_id=-6148914691236517206, ring_numbers=(7,),
        node_id=_NID, metadata=_MD,
    )
    return {
        "PreJoinMessage": t.PreJoinMessage(sender=_EP1, node_id=_NID),
        "JoinMessage": t.JoinMessage(
            sender=_EP1, node_id=_NID, ring_numbers=(1, 2, 8),
            configuration_id=1234567890123456789, metadata=_MD,
        ),
        "BatchedAlertMessage": t.BatchedAlertMessage(
            sender=_EP3, messages=(alert_down, alert_up),
        ),
        "ProbeMessage": t.ProbeMessage(sender=_EP1),
        "FastRoundPhase2bMessage": t.FastRoundPhase2bMessage(
            sender=_EP2, configuration_id=-98765432109876543,
            endpoints=(_EP1, _EP2, _EP3),
        ),
        "Phase1aMessage": t.Phase1aMessage(
            sender=_EP1, configuration_id=42, rank=_RANK,
        ),
        "Phase1bMessage": t.Phase1bMessage(
            sender=_EP2, configuration_id=42, rnd=_RANK,
            vrnd=t.Rank(round=1, node_index=7), vval=(_EP1, _EP3),
        ),
        "Phase2aMessage": t.Phase2aMessage(
            sender=_EP3, configuration_id=42, rnd=_RANK, vval=(_EP2,),
        ),
        "Phase2bMessage": t.Phase2bMessage(
            sender=_EP1, configuration_id=42, rnd=_RANK, endpoints=(_EP1, _EP2),
        ),
        "LeaveMessage": t.LeaveMessage(sender=_EP2),
        # Hierarchical-membership extension (rapid_tpu/hier): envelope
        # numbers 12-14, mirroring the native codec tags. Not part of the
        # reference IDL, but frozen the same way so descriptor drift on the
        # extension breaks the build exactly like drift on the core.
        "CohortCutMessage": t.CohortCutMessage(
            sender=_EP1, configuration_id=-6148914691236517206, cohort=3,
            endpoints=(_EP2, _EP3), joiner_eps=(_EP3,), joiner_ids=(_NID,),
        ),
        "DelegateDecisionMessage": t.DelegateDecisionMessage(
            sender=_EP2, configuration_id=1234567890123456789,
            endpoints=(_EP1, _EP3), joiner_eps=(_EP3,),
            joiner_ids=(t.NodeId(1, 2),),
        ),
        "GlobalTierMessage": t.GlobalTierMessage(
            sender=_EP3,
            payload=t.Phase2aMessage(
                sender=_EP3, configuration_id=42, rnd=_RANK, vval=(_EP2,),
            ),
        ),
    }


def canonical_responses():
    return {
        "JoinResponse": t.JoinResponse(
            sender=_EP1, status_code=t.JoinStatusCode.SAFE_TO_JOIN,
            configuration_id=1234567890123456789,
            endpoints=(_EP1, _EP2, _EP3), identifiers=(_NID, t.NodeId(1, 2)),
            metadata_keys=(_EP1,), metadata_values=(_MD,),
        ),
        "Response": t.Response(),
        "ConsensusResponse": t.ConsensusResponse(),
        "ProbeResponse": t.ProbeResponse(status=t.NodeStatus.BOOTSTRAPPING),
    }


def _encode_request(msg) -> bytes:
    # deterministic=True pins map-field ordering; scalar/message fields are
    # already serialized in field-number order by the python runtime.
    return request_to_proto(msg).SerializeToString(deterministic=True)


def _encode_response(msg) -> bytes:
    return response_to_proto(msg).SerializeToString(deterministic=True)


def _load_fixtures():
    with open(FIXTURE_PATH) as f:
        return json.load(f)


def test_request_frames_match_golden_bytes():
    fixtures = _load_fixtures()["requests"]
    messages = canonical_requests()
    assert set(fixtures) == set(messages), "fixture set drifted from type set"
    for name, msg in messages.items():
        assert _encode_request(msg).hex() == fixtures[name], (
            f"{name}: serialized frame differs from the committed golden "
            "bytes — the wire schema or converter changed. If intentional, "
            "regenerate via `python tests/test_wire_fixtures.py --regen` and "
            "review the diff against rapid.proto."
        )


def test_response_frames_match_golden_bytes():
    fixtures = _load_fixtures()["responses"]
    messages = canonical_responses()
    assert set(fixtures) == set(messages), "fixture set drifted from type set"
    for name, msg in messages.items():
        assert _encode_response(msg).hex() == fixtures[name], (
            f"{name}: serialized frame differs from the committed golden bytes"
        )


def test_request_frames_decode_back_to_native():
    # The decode direction, from the COMMITTED bytes (not a fresh encode):
    # a decoder regression cannot hide behind a matching encoder bug.
    fixtures = _load_fixtures()["requests"]
    messages = canonical_requests()
    envelope_cls = proto_class("RapidRequest")
    for name, msg in messages.items():
        envelope = envelope_cls.FromString(bytes.fromhex(fixtures[name]))
        assert request_from_proto(envelope) == msg, name


def test_response_frames_decode_back_to_native():
    fixtures = _load_fixtures()["responses"]
    messages = canonical_responses()
    envelope_cls = proto_class("RapidResponse")
    for name, msg in messages.items():
        envelope = envelope_cls.FromString(bytes.fromhex(fixtures[name]))
        assert response_from_proto(envelope) == msg, name


def test_golden_fixtures_and_wire_lock_cover_the_same_types():
    """Cross-validate the two freezes of the wire surface: every message
    type pinned by the golden frames must be in the staticcheck wire lock
    (tools/analysis/wire.lock.json) and vice versa, so neither can drift
    from the bytes the other pins. The lock's native-only extras (the
    gossip envelope, which the reference never ships) are the exact,
    enumerated exception."""
    lock = json.loads(
        (Path(__file__).parent.parent / "tools" / "analysis" / "wire.lock.json")
        .read_text()
    )
    fixtures = _load_fixtures()
    native_only_requests = {"GossipMessage"}
    assert set(fixtures["requests"]) == set(lock["request_tags"]) - native_only_requests
    assert set(fixtures["responses"]) == set(lock["response_tags"])
    # The lock's proto section mirrors the envelope numbering the frames
    # were serialized under: envelope field number == native tag.
    for name, tag in lock["request_tags"].items():
        if name in native_only_requests:
            continue
        field = name[0].lower() + name[1:]
        assert lock["proto"]["RapidRequest"][field] == tag, name
    for name, tag in lock["response_tags"].items():
        field = name[0].lower() + name[1:]
        assert lock["proto"]["RapidResponse"][field] == tag, name


def _varint(n: int) -> bytes:
    assert n >= 0
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(field: int, payload: bytes) -> bytes:
    """Length-delimited field: tag=(field<<3)|2, then length, then bytes."""
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def test_probe_frame_matches_first_principles_bytes():
    """Assemble the probe RapidRequest by hand from the protobuf wire spec
    and rapid.proto's field numbers — Endpoint{bytes hostname=1, int32
    port=2} (rapid.proto:13-17), ProbeMessage{sender=1} and
    RapidRequest.probeMessage=4 (rapid.proto:21-35) — anchoring the golden
    fixtures outside the runtime that generated them."""
    endpoint = _ld(1, b"10.0.0.1") + bytes([(2 << 3) | 0]) + _varint(5000)
    probe = _ld(1, endpoint)
    envelope = _ld(4, probe)
    assert _encode_request(canonical_requests()["ProbeMessage"]) == envelope
    assert _load_fixtures()["requests"]["ProbeMessage"] == envelope.hex()


def _regen() -> None:
    FIXTURE_PATH.parent.mkdir(parents=True, exist_ok=True)
    data = {
        "_comment": (
            "Golden serialized frames for the rapid.proto interop surface "
            "(hex). Generated by `python tests/test_wire_fixtures.py "
            "--regen`; do not edit by hand."
        ),
        "requests": {
            name: _encode_request(msg).hex()
            for name, msg in sorted(canonical_requests().items())
        },
        "responses": {
            name: _encode_response(msg).hex()
            for name, msg in sorted(canonical_responses().items())
        },
    }
    with open(FIXTURE_PATH, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    print(f"wrote {FIXTURE_PATH}")


if __name__ == "__main__":
    if "--regen" in sys.argv:
        _regen()
    else:
        print(__doc__)
