"""The bench's TPU-snapshot fallback (bench._emit_tpu_snapshot): the driver's
perf artifact depends on this path whenever the accelerator tunnel is wedged,
so its gating rules are pinned here — a snapshot only stands in for the SAME
workload, only ever replays a real TPU capture, prefers the newest stamp, and
always discloses its provenance.
"""

import json

import pytest

import bench


def _capture(n=100_000, platform="tpu", value=100.9, stamp="2026-07-29T14:06:21Z"):
    return {
        "metric": f"churn_resolution_ms_n{n}_churn5pct",
        "value": value,
        "unit": "ms",
        "platform": platform,
        "n_members": n,
        "captured_at": stamp,
    }


def _emit(monkeypatch, capsys, files, env=None):
    """Run _emit_tpu_snapshot against a synthetic evidence set; returns the
    (bool result, parsed stdout JSON or None)."""
    # Scrub ambient bench env (a capture/sweep session exports these): the
    # synthetic evidence set must be the only input.
    for name in ("RAPID_TPU_BENCH_SNAPSHOT", "RAPID_TPU_BENCH_N"):
        monkeypatch.delenv(name, raising=False)
    for name, value in (env or {}).items():
        monkeypatch.setenv(name, value)
    monkeypatch.setattr(
        bench.glob, "glob", lambda pattern: [str(p) for p in files]
    )
    ok = bench._emit_tpu_snapshot()
    out = capsys.readouterr().out.strip()
    return ok, (json.loads(out) if out else None)


def test_replays_newest_tpu_capture_with_provenance(tmp_path, monkeypatch, capsys):
    old = tmp_path / "old.json"
    old.write_text(json.dumps(_capture(value=140.0, stamp="2026-07-28T10:00:00Z")))
    new = tmp_path / "new.json"
    new.write_text(json.dumps(_capture(value=100.9, stamp="2026-07-29T14:06:21Z")))

    ok, data = _emit(monkeypatch, capsys, [old, new])
    assert ok
    assert data["value"] == 100.9  # newest stamp wins, not best value
    assert data["platform"] == "tpu"
    # A replay must be distinguishable from a live run.
    assert data["capture"] == "session_snapshot"
    assert data["live_attempt"] == "wedged"
    assert data["snapshot_path"]
    assert data["captured_at"] == "2026-07-29T14:06:21Z"


def test_never_replays_a_different_workload(tmp_path, monkeypatch, capsys):
    # A smoke run at N=2000 must not replay the 100K capture, and vice versa.
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(_capture(n=100_000)))
    ok, data = _emit(
        monkeypatch, capsys, [f], env={"RAPID_TPU_BENCH_N": "2000"}
    )
    assert not ok and data is None


def test_never_replays_a_cpu_measurement(tmp_path, monkeypatch, capsys):
    f = tmp_path / "bench.json"
    f.write_text(json.dumps(_capture(platform="cpu")))
    ok, data = _emit(monkeypatch, capsys, [f])
    assert not ok and data is None


@pytest.mark.parametrize("content", ["", "not json{", json.dumps(["list"]),
                                     json.dumps({"platform": "tpu"})])
def test_tolerates_malformed_or_incomplete_candidates(
    content, tmp_path, monkeypatch, capsys
):
    # Corrupt/incomplete files are skipped, never crash the fallback.
    bad = tmp_path / "bad.json"
    bad.write_text(content)
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_capture()))
    ok, data = _emit(monkeypatch, capsys, [bad, good])
    assert ok and data["value"] == 100.9


def test_explicit_snapshot_env_overrides_discovery(tmp_path, monkeypatch, capsys):
    chosen = tmp_path / "chosen.json"
    chosen.write_text(json.dumps(_capture(value=88.8)))
    ignored = tmp_path / "ignored.json"
    ignored.write_text(json.dumps(_capture(value=55.5, stamp="2026-07-30T00:00:00Z")))

    # Discovery must not even run (glob would only find the 'ignored' file).
    ok, data = _emit(
        monkeypatch, capsys, [ignored],
        env={"RAPID_TPU_BENCH_SNAPSHOT": str(chosen)},
    )
    assert ok and data["value"] == 88.8


def test_autotuned_lanes_resolution(tmp_path, monkeypatch):
    # Width resolution order: env override first; else newest committed
    # autotune evidence, nearest measured shape; else the default. Garbage
    # lines and non-TPU or insane widths never poison the choice.
    for name in ("RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"):
        monkeypatch.delenv(name, raising=False)
    evdir = tmp_path / "evidence" / "round9"
    evdir.mkdir(parents=True)
    (evdir / "autotune.jsonl").write_text(
        json.dumps({"platform": "tpu", "best_width": 999}) + "\n"  # no shape: skipped
        + json.dumps({"platform": "tpu", "shape": [64, 100_000], "best_width": 256}) + "\n"
        + json.dumps({"platform": "tpu", "shape": [8, 1_000_000], "best_width": 512}) + "\n"
        + json.dumps({"platform": "cpu", "shape": [64, 100_000], "best_width": 1024}) + "\n"
        + json.dumps({"platform": "tpu", "shape": [8, 500_000], "best_width": 7}) + "\n"
        + "not json{\n"
    )
    monkeypatch.setattr(
        bench.glob, "glob", lambda pattern: [str(evdir / "autotune.jsonl")]
    )
    MAIN, XL = "RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"
    assert bench._autotuned_lanes(100_000, MAIN) == 256   # exact shape
    assert bench._autotuned_lanes(90_000, MAIN) == 256    # nearest shape
    assert bench._autotuned_lanes(1_000_000, XL) == 512
    # The sweep plumbs per-point widths through the MAIN env at any N.
    monkeypatch.setenv(MAIN, "1024")
    assert bench._autotuned_lanes(100_000, MAIN) == 1024  # env wins
    assert bench._autotuned_lanes(1_000_000, MAIN) == 1024
    monkeypatch.setenv(XL, "128")
    assert bench._autotuned_lanes(1_000_000, XL) == 128


def test_autotuned_lanes_defaults_without_evidence(monkeypatch):
    for name in ("RAPID_TPU_BENCH_LANES", "RAPID_TPU_BENCH_LANES_1M"):
        monkeypatch.delenv(name, raising=False)
    monkeypatch.setattr(bench.glob, "glob", lambda pattern: [])
    assert bench._autotuned_lanes(100_000, "RAPID_TPU_BENCH_LANES") == 128
